"""Sequence scoring + dp-sharded on-device metric reduction."""

import jax.numpy as jnp
import numpy as np
import pytest

from fairness_llm_tpu.metrics import demographic_parity
from fairness_llm_tpu.metrics.sharded import sharded_demographic_parity
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.runtime.engine import DecodeEngine
from fairness_llm_tpu.runtime.scoring import perplexity_by_model, score_texts


@pytest.fixture(scope="module")
def engine():
    return DecodeEngine(get_model_config("tiny-test"), seed=0)


def test_score_texts_shapes_and_finiteness(engine):
    out = score_texts(engine, ["hello world", "a longer piece of text here", "x"])
    assert out.log_likelihoods.shape == (3,)
    assert np.all(np.isfinite(out.log_likelihoods))
    assert np.all(out.log_likelihoods <= 0)  # log-probs
    assert out.token_counts[1] > out.token_counts[2]
    np.testing.assert_allclose(
        out.mean_logprobs, out.log_likelihoods / out.token_counts, rtol=1e-6
    )


def test_score_batch_invariance(engine):
    """Left-padded scoring must give the same LL whether solo or batched."""
    solo = score_texts(engine, ["the quick brown fox"])
    mixed = score_texts(engine, ["padding text", "the quick brown fox", "more padding here"])
    np.testing.assert_allclose(
        solo.log_likelihoods[0], mixed.log_likelihoods[1], rtol=1e-5
    )


def test_perplexity_by_model(engine):
    ppl = perplexity_by_model({"tiny": engine}, ["some text to score", "another"])
    assert ppl["tiny"] > 1.0 and np.isfinite(ppl["tiny"])


def test_score_texts_on_mesh(engine, eight_device_mesh):
    """Sharded scoring path: dp/tp mesh, results match the single-device LLs."""
    sharded = DecodeEngine(
        get_model_config("tiny-test"), params=engine.params, mesh=eight_device_mesh
    )
    texts = ["score me please", "and also this longer one here", "x y z"]
    a = score_texts(engine, texts)
    b = score_texts(sharded, texts)
    np.testing.assert_allclose(a.log_likelihoods, b.log_likelihoods, rtol=1e-4)


def test_sharded_dp_matches_host_metric(eight_device_mesh):
    """psum-reduced demographic parity == the host-side reference wrapper."""
    rng = np.random.default_rng(0)
    n_profiles, vocab, groups = 16, 40, 3
    counts = np.zeros((n_profiles, vocab), np.float32)
    items_per = 10
    recs_by_group = {f"g{g}": [] for g in range(groups)}
    gids = np.zeros(n_profiles, np.int32)
    item_names = [f"item{i}" for i in range(vocab)]
    for i in range(n_profiles):
        g = i % groups
        gids[i] = g
        # group-dependent item window -> non-trivial parity
        chosen = rng.choice(np.arange(g * 5, g * 5 + 25), size=items_per, replace=False)
        np.add.at(counts[i], chosen, 1.0)
        recs_by_group[f"g{g}"].append([item_names[c] for c in chosen])

    score, js = sharded_demographic_parity(
        eight_device_mesh, jnp.asarray(counts), jnp.asarray(gids), groups
    )
    host_score, _ = demographic_parity(recs_by_group)
    np.testing.assert_allclose(float(score), host_score, atol=1e-5)


def test_mesh_group_counts_fn_randomized(eight_device_mesh):
    """The group_counts_fn hook (what phase 1 actually wires in): DP and EO
    through the psum reduction == host wrappers on randomized rec lists of
    UNEVEN lengths and group sizes (incl. an empty group)."""
    from fairness_llm_tpu.metrics import equal_opportunity
    from fairness_llm_tpu.metrics.sharded import mesh_group_counts_fn

    rng = np.random.default_rng(7)
    items = [f"title {i}" for i in range(30)]
    recs_by_group = {"a": [], "b": [], "c": [], "empty": []}
    for gi, g in enumerate(("a", "b", "c")):
        for _ in range(int(rng.integers(1, 7))):
            k = int(rng.integers(1, 12))
            recs_by_group[g].append(
                [items[int(j)] for j in rng.integers(gi * 3, 30, size=k)]
            )
    relevant = {items[i] for i in range(0, 30, 4)}

    fn = mesh_group_counts_fn(eight_device_mesh)
    dp_s, det_s = demographic_parity(recs_by_group, group_counts_fn=fn)
    dp_h, det_h = demographic_parity(recs_by_group)
    np.testing.assert_allclose(dp_s, dp_h, atol=1e-5)
    assert det_s["divergences"] == pytest.approx(det_h["divergences"], abs=1e-5)

    eo_s = equal_opportunity(recs_by_group, relevant, group_counts_fn=fn)
    eo_h = equal_opportunity(recs_by_group, relevant)
    np.testing.assert_allclose(eo_s[0], eo_h[0], atol=1e-5)
    assert eo_s[1] == pytest.approx(eo_h[1], abs=1e-5)

"""Property-based ShedController invariants (hypothesis over random
burn/load/clock sequences) — sibling of tests/test_breaker_property.py.

The brownout ladder gates live admission at every serving front door, so
its invariants are load-bearing for the overload drill's guarantees:

1. **Monotone per evaluation**: one ``evaluate()`` moves the level by at
   most ONE rung, stays in [0, 3], escalates only while a signal is hot,
   and de-escalates only while everything is healthy — never a jump, never
   a move against the signal.
2. **Hysteresis**: every de-escalation is preceded by at least
   ``healthy_window_s`` of hot-signal-free clock time since the later of
   (the last hot evaluation, the previous de-escalation) — a flapping
   signal can ratchet the ladder up but can never oscillate it, and two
   rungs can never be descended within one healthy window.
"""

import pytest

pytest.importorskip("hypothesis")  # property tests skip where hypothesis isn't baked in
from hypothesis import given, settings
from hypothesis import strategies as st

from fairness_llm_tpu.config import OverloadConfig
from fairness_llm_tpu.serving.overload import ShedController
from fairness_llm_tpu.telemetry import use_registry
from fairness_llm_tpu.telemetry.registry import get_registry

CFG = OverloadConfig(
    enabled=True, burn_threshold=2.0, queue_frac_threshold=0.5,
    queue_window_s=1.0, healthy_window_s=3.0, eval_interval_s=0.0,
)

# One operation: set the fast-window burn gauge, sample a queue depth,
# advance the fake clock, or run one controller evaluation.
OPS = st.lists(
    st.one_of(
        st.tuples(st.just("burn"),
                  st.floats(min_value=0.0, max_value=10.0,
                            allow_nan=False)),
        st.tuples(st.just("depth"),
                  st.integers(min_value=0, max_value=100)),
        st.tuples(st.just("tick"),
                  st.floats(min_value=0.05, max_value=2.0,
                            allow_nan=False)),
        st.tuples(st.just("eval"), st.just(0)),
    ),
    max_size=200,
)


@settings(max_examples=100, deadline=None)
@given(ops=OPS)
def test_level_moves_one_rung_with_the_signal(ops):
    clock = {"t": 0.0}
    with use_registry():
        ctl = ShedController(CFG, clock=lambda: clock["t"])
        # Arm the burn signal (it is presence-gated); long tick sequences
        # age the presence out mid-run, which the oracle handles.
        ctl.note_interactive()
        burn_gauge = get_registry().gauge(
            "slo_burn_rate", component="serving", slo="error_rate",
            window="fast",
        )
        for op, val in ops:
            if op == "burn":
                burn_gauge.set(val)
            elif op == "depth":
                ctl.observe_queue_depth(val, capacity=100)
            elif op == "tick":
                clock["t"] += val
            else:
                hot = ctl.overloaded() is not None  # pure read, no state
                before = ctl.level
                after = ctl.evaluate()
                assert 0 <= after <= 3
                assert abs(after - before) <= 1, (
                    f"level jumped {before} -> {after}"
                )
                if after > before:
                    assert hot, "escalated without a hot signal"
                if after < before:
                    assert not hot, "de-escalated while a signal was hot"
                if hot:
                    assert after >= before, "moved down against the signal"


@settings(max_examples=100, deadline=None)
@given(ops=OPS)
def test_hysteresis_gates_every_descent(ops):
    clock = {"t": 0.0}
    with use_registry():
        ctl = ShedController(CFG, clock=lambda: clock["t"])
        # Arm the burn signal (it is presence-gated); long tick sequences
        # age the presence out mid-run, which the oracle handles.
        ctl.note_interactive()
        burn_gauge = get_registry().gauge(
            "slo_burn_rate", component="serving", slo="error_rate",
            window="fast",
        )
        last_hot_eval = None  # newest evaluation that saw a hot signal
        last_descent = None
        for op, val in ops:
            if op == "burn":
                burn_gauge.set(val)
            elif op == "depth":
                ctl.observe_queue_depth(val, capacity=100)
            elif op == "tick":
                clock["t"] += val
            else:
                hot = ctl.overloaded() is not None
                before = ctl.level
                after = ctl.evaluate()
                now = clock["t"]
                if hot:
                    last_hot_eval = now
                if after < before:
                    # The healthy window must have elapsed since BOTH the
                    # last hot evaluation and the previous descent — the
                    # per-rung restart that stops a sawtooth.
                    for bound in (last_hot_eval, last_descent):
                        if bound is not None:
                            assert now - bound >= CFG.healthy_window_s, (
                                f"descended {now - bound:.2f}s after "
                                "activity, inside the healthy window"
                            )
                    last_descent = now

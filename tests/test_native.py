"""Native C parser: correctness vs the pure-Python path, and fallback safety."""

import numpy as np
import pytest

from fairness_llm_tpu import native
from fairness_llm_tpu.data.movielens import _parse_ratings, load_movielens


@pytest.fixture()
def ratings_file(tmp_path):
    rng = np.random.default_rng(0)
    path = tmp_path / "ratings.dat"
    rows = []
    for _ in range(5000):
        u = int(rng.integers(1, 6041))
        m = int(rng.integers(1, 3953))
        r = int(rng.integers(1, 6))
        ts = int(rng.integers(9e8, 1e9))
        rows.append(f"{u}::{m}::{r}::{ts}")
    path.write_text("\n".join(rows) + "\n")
    return str(path)


def test_native_builds_and_parses(ratings_file):
    if not native.available():
        pytest.skip("no C compiler in environment")
    users, movies, values = native.parse_ratings(ratings_file)
    # oracle: pure python
    import numpy as np

    lines = open(ratings_file).read().splitlines()
    exp_u = np.array([int(l.split("::")[0]) for l in lines], np.int32)
    exp_m = np.array([int(l.split("::")[1]) for l in lines], np.int32)
    exp_v = np.array([float(l.split("::")[2]) for l in lines], np.float32)
    np.testing.assert_array_equal(users, exp_u)
    np.testing.assert_array_equal(movies, exp_m)
    np.testing.assert_allclose(values, exp_v)


def test_parse_ratings_wrapper_matches(ratings_file):
    users, movies, values = _parse_ratings(ratings_file)
    assert len(users) == len(movies) == len(values) == 5000
    assert users.dtype == np.int32 and values.dtype == np.float32


def test_load_movielens_end_to_end(tmp_path, ratings_file):
    # ratings_file already lives at tmp_path/ratings.dat; add movies.dat beside it
    (tmp_path / "movies.dat").write_text(
        "1::Toy Story (1995)::Animation|Children's|Comedy\n"
        "2::Jumanji (1995)::Adventure|Children's|Fantasy\n",
        encoding="latin-1",
    )
    data = load_movielens(str(tmp_path), allow_synthetic=False)
    assert data.num_movies == 2
    assert data.num_ratings == 5000
    assert data.titles[0] == "Toy Story (1995)"

"""Decode cost ledger + perf sentinel tests (ISSUE 12; collectives
component added by ISSUE 16).

Five contracts:

- the SHARED component taxonomy: ``tools/account_decode_step.py`` imports
  the first-match-wins ``COMPONENTS`` table from ``telemetry/costmodel.py``
  (no private copy), and every historical op-name fixture classifies the
  way the round-3..11 private table classified it;
- the jaxpr cost walk is hand-verifiable: tiny toy programs (one dot, one
  attention-shaped dot, one cache DUS, one while loop) produce exactly the
  bytes/FLOPs first principles predict, split per-call vs per-step;
- EVERY compiled decode variant (plain/spec engine decode, serving
  prefill/step, paged prefill/step) publishes a nonzero ledger after a
  continuous + paged + speculative smoke, and the gap decomposition's
  components sum to the measured wall exactly;
- the perf sentinel accepts a clean same-fingerprint re-run, rejects an
  injected 3x slowdown and token-parity drift, and REFUSES a baseline
  recorded under a different harness fingerprint;
- the ``collectives`` component is pinned at all three layers — xplane
  regex (first-match, ahead of gather/attention), jaxpr primitives
  (shard_map psum oracle), and the analytic ``tp_collective_costs``
  injection for GSPMD-auto tp programs, with its double-count guard.
"""

import copy
import dataclasses
import json
import sys

import jax
import jax.numpy as jnp
import pytest
from jax import lax

from fairness_llm_tpu.config import ModelSettings, ServingConfig, SpeculationConfig
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.runtime.engine import DecodeEngine
from fairness_llm_tpu.telemetry import (
    gap_decomposition,
    has_cost_data,
    jaxpr_ledger,
    render_cost_report,
    snapshot,
    use_registry,
    use_timeline,
)
from fairness_llm_tpu.telemetry.costmodel import COMPONENTS, classify
from fairness_llm_tpu.telemetry.roofline import decode_step_bytes


def _tool(name):
    sys.path.insert(0, "/root/repo/tools")
    try:
        import importlib

        return importlib.import_module(name)
    finally:
        sys.path.pop(0)


# -- shared taxonomy -----------------------------------------------------------


def test_account_decode_step_imports_shared_components():
    tool = _tool("account_decode_step")
    assert tool.COMPONENTS is COMPONENTS
    assert tool.classify is classify


# Historical xplane op names from the round-3/4 device captures, with the
# classification the private table in tools/account_decode_step.py produced
# through round 11 (labels renamed to the shared taxonomy, grouping
# IDENTICAL). First-match-wins ordering is part of the contract: e.g.
# "multiply_reduce_fusion" must stay attention (not elementwise), and
# "dynamic-update-slice_fusion" must stay KV (not a fusion catch-all).
HISTORICAL_OP_FIXTURES = [
    ("multiply_reduce_fusion.3", "attention"),
    ("reduce_fusion", "attention"),
    ("softmax_exp", "attention"),
    ("exponential.12", "attention"),
    ("divide_fusion.2", "attention"),
    ("dynamic-update-slice.7", "kv_rw"),
    ("dynamic-update-slice_fusion", "kv_rw"),
    ("fused_dynamic_update_slice", "kv_rw"),
    ("slice.42", "weights_dma"),
    ("bitcast-convert.1", "weights_dma"),
    ("copy.3", "weights_dma"),
    ("dynamic-slice-start", "weights_dma"),
    ("copy-start.2", "weights_dma"),
    ("copy-done.2", "weights_dma"),
    ("slice_fusion", "weights_dma"),
    ("dot.17", "matmuls"),
    ("dot_general_fusion", "matmuls"),
    ("convolution.1", "matmuls"),
    ("rsqrt.4", "norms_elementwise"),
    ("layer_norm_fusion", "norms_elementwise"),
    ("add_fusion.9", "norms_elementwise"),
    ("multiply_fusion", "norms_elementwise"),
    ("subtract.2", "norms_elementwise"),
    ("tanh.1", "norms_elementwise"),
    ("gelu_fusion", "norms_elementwise"),
    ("sort.1", "sampling"),
    ("argmax.3", "sampling"),
    ("rng-bit-generator", "sampling"),
    ("random_fold_in", "sampling"),
    ("iota.2", "sampling"),
    ("cumsum_fusion", "sampling"),
    ("select_n.5", "sampling"),
    ("compare.8", "sampling"),
    ("gather.11", "gather_scatter"),
    ("scatter.4", "gather_scatter"),
    ("while.1", "control"),
    ("condition.2", "control"),
    ("tuple.1", "control"),
    ("parameter.0", "control"),
    ("constant.5", "control"),
    ("some-unknown-op", "other"),
]


@pytest.mark.parametrize("name,expected", HISTORICAL_OP_FIXTURES)
def test_historical_op_names_classify_identically(name, expected):
    assert classify(name) == expected


# -- jaxpr walk vs hand-computed oracles ---------------------------------------


def _ledger_of(fn, *args):
    return jaxpr_ledger(jax.make_jaxpr(fn)(*args), "toy")


def test_jaxpr_ledger_2d_dot_is_matmul():
    w = jnp.ones((8, 32), jnp.float32)
    x = jnp.ones((16, 8), jnp.float32)
    led = _ledger_of(
        lambda w, x: lax.dot_general(x, w, (((1,), (0,)), ((), ()))), w, x
    )
    assert set(led.per_call) == {"matmuls"} and not led.per_step
    c = led.per_call["matmuls"]
    # bytes: x[16,8] + w[8,32] + out[16,32], f32
    assert c.bytes == (16 * 8 + 8 * 32 + 16 * 32) * 4
    # flops: 2 * M * N * K
    assert c.flops == 2 * 16 * 32 * 8


def test_jaxpr_ledger_rank4_dot_is_attention():
    q = jnp.ones((2, 2, 4, 8), jnp.float32)
    led = _ledger_of(
        lambda q: lax.dot_general(q, q, (((3,), (3,)), ((0, 1), (0, 1)))), q
    )
    assert set(led.per_call) == {"attention"}
    c = led.per_call["attention"]
    # bytes: two [2,2,4,8] operands + the [2,2,4,4] scores, f32
    assert c.bytes == (2 * (2 * 2 * 4 * 8) + 2 * 2 * 4 * 4) * 4
    # flops: 2 * out-elements * contracted dim
    assert c.flops == 2 * (2 * 2 * 4 * 4) * 8


def test_jaxpr_ledger_dus_is_kv_rw():
    cache = jnp.zeros((4, 8), jnp.float32)
    row = jnp.ones((1, 8), jnp.float32)
    led = _ledger_of(
        lambda c, r: lax.dynamic_update_slice(c, r, (0, 0)), cache, row
    )
    assert set(led.per_call) == {"kv_rw"}
    c = led.per_call["kv_rw"]
    # bytes: cache in + row + two scalar int32 start indices + cache out
    assert c.bytes == 4 * 8 * 4 + 1 * 8 * 4 + 2 * 4 + 4 * 8 * 4
    assert c.flops == 4 * 8  # one per output element


def test_jaxpr_ledger_while_body_lands_per_step():
    def loop(x):
        def body(c):
            i, acc = c
            return i + jnp.int32(1), acc + acc

        return lax.while_loop(lambda c: c[0] < jnp.int32(4), body,
                              (jnp.int32(0), x))

    led = _ledger_of(loop, jnp.ones((8,), jnp.float32))
    assert not led.per_call and led.has_loop
    # cond: lt over two int32 scalars -> bool scalar = 9 bytes, 1 flop
    assert (led.per_step["control"].bytes,
            led.per_step["control"].flops) == (9, 1)
    # body: scalar add (12 B, 1 flop) + [8] f32 add (96 B, 8 flops), both
    # elementwise.
    assert (led.per_step["norms_elementwise"].bytes,
            led.per_step["norms_elementwise"].flops) == (108, 9)
    # min-times: per-step cost x steps against the given rooflines.
    mt = led.min_times_s(4, 1e9, 1e9)
    assert mt["norms_elementwise"] == pytest.approx(4 * 108 / 1e9)


def test_jaxpr_ledger_scan_multiplies_by_length():
    def scanned(x):
        def step(carry, _):
            return carry + x, None

        out, _ = lax.scan(step, x, None, length=5)
        return out

    led = _ledger_of(scanned, jnp.ones((8,), jnp.float32))
    # 5 iterations of one [8]+[8] add, all per_call (scan has a static trip
    # count — only while bodies are per_step).
    assert not led.per_step
    assert led.per_call["norms_elementwise"].bytes == 5 * (3 * 8 * 4)
    assert led.per_call["norms_elementwise"].flops == 5 * 8


# -- paged roofline satellite --------------------------------------------------


def test_decode_step_bytes_paged_oracle():
    cfg = get_model_config("tiny-test")
    model_item = 2 if cfg.dtype == "bfloat16" else 4
    per_slot = cfg.num_kv_heads * cfg.head_dim * model_item * 2 * cfg.num_layers
    contiguous = {"batch": 4, "cache_slots": 96, "prefix_len": 0}
    base = decode_step_bytes(cfg, contiguous)
    kv = 4 * 96 * per_slot
    assert base == cfg.approx_param_count * model_item + kv
    # Paged: the chunk's one gather (arena read + view write) and one
    # scatter (view read + block write) move 4x the pool KV, amortized over
    # the steps the chunk ran.
    paged8 = decode_step_bytes(cfg, {**contiguous, "paged_kv": True,
                                     "chunk_steps": 8})
    assert paged8 == base + 4 * kv // 8
    # Fewer steps per chunk -> worse amortization -> MORE bytes per step.
    paged1 = decode_step_bytes(cfg, {**contiguous, "paged_kv": True,
                                     "chunk_steps": 1})
    assert paged1 == base + 4 * kv
    assert paged1 > paged8 > base


# -- collectives component (ISSUE 16 satellite) --------------------------------
#
# Three layers, each pinned: the xplane regex (measured captures), the jaxpr
# primitive set (shard_map-manual programs), and the analytic injection path
# (GSPMD-auto tp programs whose jaxpr cannot show the collectives XLA adds
# after partitioning).


# Collective op names as they appear in real xplane captures; all must land
# in "collectives". Ordering is load-bearing: "all-gather"/"reduce-scatter"
# must NOT fall through to gather_scatter or attention's reduce pattern
# (the HISTORICAL_OP_FIXTURES above re-running unchanged pins the converse —
# "reduce_fusion" stays attention, "gather.11" stays gather_scatter).
COLLECTIVE_OP_FIXTURES = [
    "all-reduce.1",
    "all-reduce-start",
    "all-gather.3",
    "reduce-scatter_fusion",
    "collective-permute.2",
    "all-to-all",
    "psum",
]


@pytest.mark.parametrize("name", COLLECTIVE_OP_FIXTURES)
def test_collective_op_names_classify_as_collectives(name):
    assert classify(name) == "collectives"


def test_collectives_is_first_match_in_components():
    # First-match-wins: collectives must outrank gather_scatter/attention so
    # "all-gather"/"reduce-scatter" never misfile as memory ops.
    assert COMPONENTS[0][0] == "collectives"


def test_jaxpr_ledger_shard_map_psum_is_collectives():
    # The jaxpr-visible path: shard_map-manual code traces its psum
    # explicitly (unlike GSPMD-auto programs), and the walk descends into
    # the shard_map sub-jaxpr and books it under "collectives".
    import numpy as np

    from fairness_llm_tpu.parallel.sharding import compat_shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    fn = compat_shard_map(lambda x: lax.psum(x, "tp"), mesh,
                          in_specs=P("tp"), out_specs=P())
    led = _ledger_of(fn, jnp.ones((8,), jnp.float32))
    assert "collectives" in led.per_call
    c = led.per_call["collectives"]
    # psum over the per-device [4] f32 shard: in + out avals.
    assert c.bytes == 2 * 4 * 4
    assert c.flops == 4


def test_tp_collective_costs_oracle():
    from fairness_llm_tpu.telemetry.costmodel import tp_collective_costs

    cfg = get_model_config("tiny-test")  # f32, 2 layers, 4 heads, d_ff 128,
    #                                      d_model 64, vocab 512
    # tp=2, 2 rows x 1 token: both the head and ff axes shard -> one ring
    # all-reduce of the [2, 1, 64] f32 activation per projection per layer
    # at 2(tp-1)/tp, plus the (tp-1)/tp logits all-gather.
    act = 2 * 1 * 64 * 4
    expect = int(2 * 2 * act * 2 * (1 / 2)) + int(2 * 1 * 512 * 4 * (1 / 2))
    assert tp_collective_costs(cfg, 2, rows=2, tokens=1) == \
        [("step", expect, 0)]
    assert expect == 4096  # the exact serve_step@tp2 row serve_tp asserts
    # tp=8: heads (4) fall back to replicated -> only the ff all-reduce and
    # the vocab all-gather charge.
    expect8 = (int(2 * 1 * act * 2 * (7 / 8))
               + int(2 * 1 * 512 * 4 * (7 / 8)))
    assert tp_collective_costs(cfg, 8, rows=2, tokens=1) == \
        [("step", expect8, 0)]
    # Identity / nothing-shards cases charge nothing.
    assert tp_collective_costs(cfg, 1, rows=2) == []
    assert tp_collective_costs(cfg, 3, rows=2) == []  # no axis divides by 3
    # scope passes through (prefill books per-call, not per-step).
    assert tp_collective_costs(cfg, 2, rows=2, tokens=1,
                               scope="call")[0][0] == "call"


def test_instrument_jit_injects_analytic_collectives():
    from fairness_llm_tpu.telemetry.costmodel import instrument_jit

    with use_registry() as reg, use_timeline():
        run = instrument_jit(lambda x: x * 2.0, "toy_tp@tp2",
                             collectives=[("step", 4096, 0)])
        run(jnp.ones((8,), jnp.float32))
        snap = snapshot(reg)
    assert run.ledger is not None
    assert run.ledger.per_step["collectives"].bytes == 4096
    rows = [g for g in snap["gauges"]
            if g["name"] == "cost_ledger_bytes"
            and g["labels"].get("component") == "collectives"]
    assert rows and all(g["labels"]["program"] == "toy_tp@tp2"
                        for g in rows)
    assert sum(g["value"] for g in rows) == 4096


def test_instrument_jit_never_double_counts_explicit_collectives():
    # A shard_map-manual program already traces its psum; the analytic rows
    # must be DROPPED for it, or collectives would be charged twice.
    import numpy as np

    from fairness_llm_tpu.parallel.sharding import compat_shard_map
    from fairness_llm_tpu.telemetry.costmodel import instrument_jit
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    fn = compat_shard_map(lambda x: lax.psum(x, "tp"), mesh,
                          in_specs=P("tp"), out_specs=P())
    with use_registry(), use_timeline():
        run = instrument_jit(fn, "toy_manual",
                             collectives=[("call", 999_999, 0)])
        run(jnp.ones((8,), jnp.float32))
    assert run.ledger is not None
    # Only the walked psum traffic — the analytic 999_999 row was skipped.
    assert run.ledger.per_call["collectives"].bytes == 2 * 4 * 4


# -- six decode variants publish ledgers + decomposition sums ------------------


@pytest.fixture(scope="module")
def engine():
    return DecodeEngine(get_model_config("tiny-test"), seed=0)


def _greedy(m):
    return ModelSettings(temperature=0.0, max_tokens=m)


def _smoke_all_variants(engine):
    from fairness_llm_tpu.serving import ContinuousScheduler, Request

    scfg = ServingConfig(enabled=True, num_slots=2, max_prompt_len=192,
                         max_new_tokens=16, decode_chunk=4)
    pcfg = dataclasses.replace(scfg, paged_kv=True, kv_block_size=16)
    engine.generate(["one two three", "four five six"], _greedy(6))
    engine.generate(["a b c d e f g h i j k l"], _greedy(6),
                    speculation=SpeculationConfig(enabled=True))
    s1 = ContinuousScheduler(engine, scfg, settings=_greedy(8))
    r = s1.serve([Request(prompt="a b c", id="c1", settings=_greedy(8)),
                  Request(prompt="d e f", id="c2", settings=_greedy(8))])
    assert all(x.ok for x in r)
    s2 = ContinuousScheduler(engine, pcfg, settings=_greedy(8))
    r = s2.serve([Request(prompt="a b c", id="p1", settings=_greedy(8)),
                  Request(prompt="a b c d", id="p2", settings=_greedy(8))])
    assert all(x.ok for x in r)


SIX_VARIANTS = ("decode", "spec_decode", "serve_prefill", "serve_step",
                "paged_prefill", "paged_step")


def test_all_six_decode_variants_publish_ledgers(engine):
    with use_registry() as reg, use_timeline():
        _smoke_all_variants(engine)
        snap = snapshot(reg)
    assert has_cost_data(snap)
    by_prog = {}
    for g in snap["gauges"]:
        if g["name"] == "cost_ledger_bytes":
            p = g["labels"]["program"]
            by_prog[p] = by_prog.get(p, 0.0) + g["value"]
    for prog in SIX_VARIANTS:
        assert by_prog.get(prog, 0.0) > 0, f"no ledger for {prog}"
    # The loop programs split per-step work out of the per-call remainder.
    step_scopes = {g["labels"]["program"] for g in snap["gauges"]
                   if g["name"] == "cost_ledger_bytes"
                   and g["labels"].get("scope") == "step"}
    assert {"decode", "spec_decode", "serve_step", "paged_step"} <= step_scopes
    # Gap decomposition: every program's components sum EXACTLY to the
    # measured wall (+ measured host gap) — the acceptance tolerance check.
    decomp = gap_decomposition(snap)
    for prog in SIX_VARIANTS:
        d = decomp[prog]
        assert d["wall_s"] > 0
        assert d["sum_check_s"] == pytest.approx(d["total_s"], rel=1e-9)
        assert d["top_gap_contributor"] in (
            "host_gap", "dispatch", "compile", "unattributed_in_step")
        # Every program compiled at least once in this fresh-registry
        # smoke, so its first-call wall is tagged as compile time.
        assert d["compile_s"] > 0
    # Serving step programs ran >= 2 chunks, so their host gap is a
    # MEASURED nonzero quantity, not an estimate.
    assert decomp["serve_step"]["host_gap_s"] > 0
    assert decomp["paged_step"]["host_gap_s"] > 0
    # The report renders and names a contributor per program.
    report = render_cost_report(snap)
    for prog in SIX_VARIANTS:
        assert f"[{prog}]" in report
    assert "top gap contributor:" in report
    assert "sum check: OK" in report


def test_attribution_off_records_no_cost_data(engine):
    from fairness_llm_tpu.telemetry import set_attribution

    prev = set_attribution(True)
    try:
        with use_registry() as reg, use_timeline():
            set_attribution(False)
            engine.generate(["cost off one", "cost off two"], _greedy(4))
            snap = snapshot(reg)
    finally:
        set_attribution(prev)
    assert not has_cost_data(snap)
    assert not any(g["name"].startswith("cost_ledger")
                   for g in snap["gauges"])


def test_validate_telemetry_require_costmodel(engine, tmp_path):
    from fairness_llm_tpu.telemetry import write_snapshot

    check = _tool("validate_telemetry").check
    with use_registry() as reg, use_timeline():
        _smoke_all_variants(engine)
        write_snapshot(reg, str(tmp_path))
        assert check(str(tmp_path), require_costmodel=True) == 0
    # A snapshot whose compiled programs have no ledgers must fail: keep
    # compiles_total, drop the cost gauges.
    snap = json.load(open(tmp_path / "telemetry_snapshot.json"))
    snap["gauges"] = [g for g in snap["gauges"]
                     if not g["name"].startswith("cost_")]
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(snap))
    assert check(str(bad), require_costmodel=True) == 1


def test_cli_perf_report(engine, tmp_path, capsys):
    from fairness_llm_tpu.cli.main import main as cli_main
    from fairness_llm_tpu.telemetry import write_snapshot

    with use_registry() as reg, use_timeline():
        _smoke_all_variants(engine)
        write_snapshot(reg, str(tmp_path))
    assert cli_main(["perf-report", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "DECODE COST LEDGER / GAP ATTRIBUTION" in out
    assert "[serve_step]" in out and "top gap contributor:" in out
    # telemetry-report appends the same section when cost data exists.
    assert cli_main(["telemetry-report", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "DECODE COST LEDGER / GAP ATTRIBUTION" in out
    # --require-ledger on an empty snapshot fails.
    empty = tmp_path / "empty"
    with use_registry() as reg2:
        write_snapshot(reg2, str(empty))
    assert cli_main(["perf-report", str(empty), "--require-ledger"]) == 1


# -- perf sentinel -------------------------------------------------------------


def _fake_baseline():
    return {
        "schema_version": 1,
        "created_at_unix": 0.0,
        "fingerprint": {"jax": "0.4.37", "platform": "cpu",
                        "device_kind": "cpu", "cpu_count": 8,
                        "model": "tiny-test"},
        "entries": {
            "headline.profiles_per_sec": {"kind": "wall", "value": 10.0},
            "headline.token_checksum": {"kind": "exact", "value": "abc123"},
            "continuous.speedup": {"kind": "wall", "value": 1.4},
            "continuous.useful_tokens": {"kind": "exact", "value": 1234},
            "prefix_cache.hit_ratio": {"kind": "exact", "value": 0.965},
        },
    }


def test_sentinel_accepts_clean_rerun():
    ps = _tool("perf_sentinel")
    base = _fake_baseline()
    fresh = copy.deepcopy(base)
    # Same-fingerprint re-run with in-band wall jitter (±40%) and
    # identical counters must pass.
    fresh["entries"]["headline.profiles_per_sec"]["value"] = 14.0
    fresh["entries"]["continuous.speedup"]["value"] = 1.0
    problems, walls, report = ps.compare(base, fresh)
    assert problems == [] and walls == []
    assert all(r["status"] == "ok" for r in report["entries"].values())


def test_sentinel_rejects_injected_3x_slowdown():
    ps = _tool("perf_sentinel")
    base = _fake_baseline()
    slow = copy.deepcopy(base)
    for spec in slow["entries"].values():
        if spec["kind"] == "wall":
            spec["value"] = spec["value"] / 3.0
    problems, walls, _ = ps.compare(base, slow)
    assert problems == []
    assert len(walls) == 2  # both wall entries out of the 2.0x band


def test_sentinel_rejects_token_parity_drift():
    ps = _tool("perf_sentinel")
    base = _fake_baseline()
    drift = copy.deepcopy(base)
    drift["entries"]["headline.token_checksum"]["value"] = "deadbeef"
    drift["entries"]["prefix_cache.hit_ratio"]["value"] = 0.5
    problems, walls, _ = ps.compare(base, drift)
    assert len(problems) == 2 and walls == []
    assert any("token_checksum" in p for p in problems)


def test_sentinel_missing_entry_is_hard():
    ps = _tool("perf_sentinel")
    base = _fake_baseline()
    fresh = copy.deepcopy(base)
    del fresh["entries"]["continuous.useful_tokens"]
    problems, _, _ = ps.compare(base, fresh)
    assert len(problems) == 1 and "missing" in problems[0]


def test_sentinel_refuses_cross_fingerprint(tmp_path):
    ps = _tool("perf_sentinel")
    base = _fake_baseline()
    other = copy.deepcopy(base)
    other["fingerprint"]["device_kind"] = "TPU v5e"
    other["fingerprint"]["cpu_count"] = 4
    mism = ps.fingerprint_mismatches(base["fingerprint"],
                                     other["fingerprint"])
    assert len(mism) == 2
    # End to end through the CLI: refusal exits 2 (never compares), and
    # --allow-refusal downgrades it to a reported skip (exit 0).
    bpath, fpath = tmp_path / "base.json", tmp_path / "fresh.json"
    bpath.write_text(json.dumps(base))
    fpath.write_text(json.dumps(other))
    argv = sys.argv
    try:
        sys.argv = ["perf_sentinel.py", "--baseline", str(bpath),
                    "--fresh", str(fpath)]
        assert ps.main() == ps.EXIT_REFUSED
        sys.argv = sys.argv + ["--allow-refusal"]
        assert ps.main() == ps.EXIT_OK
    finally:
        sys.argv = argv


def test_sentinel_best_of_n_merge_and_rep_parity():
    ps = _tool("perf_sentinel")
    a = _fake_baseline()
    b = copy.deepcopy(a)
    b["entries"]["headline.profiles_per_sec"]["value"] = 12.0  # better rep
    merged, problems = ps.merge_best([a, b])
    assert problems == []
    assert merged["entries"]["headline.profiles_per_sec"]["value"] == 12.0
    # Lower-is-better wall entries (on/off overhead ratios) keep the
    # SMALLEST rep — max-merging them would keep the noisiest run.
    a["entries"]["overload.overhead_ratio"] = {
        "kind": "wall", "value": 1.5, "better": "lower"}
    b["entries"]["overload.overhead_ratio"] = {
        "kind": "wall", "value": 1.02, "better": "lower"}
    merged, problems = ps.merge_best([a, b])
    assert problems == []
    assert merged["entries"]["overload.overhead_ratio"]["value"] == 1.02
    # Exact entries disagreeing BETWEEN reps is itself parity drift.
    b["entries"]["headline.token_checksum"]["value"] = "zzz"
    _, problems = ps.merge_best([a, b])
    assert len(problems) == 1 and "BETWEEN reps" in problems[0]


def test_sentinel_malformed_wall_value_is_reported_not_crash():
    ps = _tool("perf_sentinel")
    base = _fake_baseline()
    bad = copy.deepcopy(base)
    bad["entries"]["headline.profiles_per_sec"]["value"] = "12.5x"
    problems, walls, _ = ps.compare(base, bad)
    assert problems == [] and len(walls) == 1  # reported, no traceback


def test_host_gap_excludes_prefill_busy_time():
    """The cost ledger's measured host gap counts device-IDLE time between
    chunks; a prefill in the gap is attributed to its own program, so the
    busy cursor must exclude it (step_gap_s keeps the PR-7 all-host-time
    semantics)."""
    from fairness_llm_tpu.telemetry import get_registry, use_registry
    from fairness_llm_tpu.telemetry.timeline import use_timeline

    with use_registry() as reg, use_timeline() as tl:
        tl.decode_chunk("serving", 1.0, 0.3, steps=8, program="serve_step")
        tl.note_busy("serving", 1.5, 0.3)  # a prefill at [1.5, 1.8)
        tl.decode_chunk("serving", 2.0, 0.3, steps=8, program="serve_step")
        # step_gap_s: full between-chunk host time 2.0 - 1.3 = 0.7.
        gap_hist = reg.histogram("step_gap_s", component="serving")
        assert gap_hist.sum == pytest.approx(0.7)
        # cost host gap: only the idle 2.0 - 1.8 = 0.2.
        assert reg.read_value("cost_host_gap_s_total",
                              component="costmodel",
                              program="serve_step") == pytest.approx(0.2)


def test_sentinel_self_check_passes_on_real_format():
    ps = _tool("perf_sentinel")
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as f:
        json.dump(_fake_baseline(), f)
        path = f.name
    assert ps.self_check(path) == ps.EXIT_OK


def test_bench_baseline_shape():
    """bench.write_bench_baseline flattens a result into sentinel-comparable
    entries with the right kinds and a 4-field-plus-model fingerprint."""
    sys.path.insert(0, "/root/repo")
    try:
        import bench
    finally:
        sys.path.pop(0)
    result = {
        "value": 12.5,
        "detail": {
            "decode_tokens_per_sec": 1600.0,
            "token_checksum": "cafe0123",
            "continuous": {
                "continuous": {"tokens_per_sec": 50.0, "useful_tokens": 999},
                "speedup_tokens_per_sec": 1.37,
            },
            "prefix_cache": {
                "on": {"hit_ratio": 0.965, "prefill_tokens": 45},
                "prefill_token_reduction": 0.998,
                "speedup_ratio": 1.14,
            },
        },
    }
    entries = bench.baseline_entries(result)
    assert entries["headline.profiles_per_sec"] == {
        "kind": "wall", "value": 12.5, "better": "higher"}
    assert entries["headline.token_checksum"]["kind"] == "exact"
    assert entries["continuous.useful_tokens"] == {"kind": "exact",
                                                   "value": 999}
    assert entries["prefix_cache.hit_ratio"]["kind"] == "exact"
    assert entries["prefix_cache.speedup_ratio"]["kind"] == "wall"
    fp = bench.harness_fingerprint("tiny-test")
    assert set(fp) == {"jax", "platform", "device_kind", "cpu",
                       "cpu_count", "model"}
    assert fp["jax"] == jax.__version__ and fp["model"] == "tiny-test"
    assert fp["cpu"]  # host CPU identity present (ISA family at minimum)
    # Overhead ratios are lower-is-better: the sentinel's best-of-N merge
    # must keep the SMALLEST rep for them.
    result["detail"]["overload_overhead"] = {"overhead_ratio": 1.02}
    entries = bench.baseline_entries(result)
    assert entries["overload.overhead_ratio"]["better"] == "lower"

"""Ring attention vs dense oracle on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fairness_llm_tpu.parallel.ring import (
    full_attention_reference,
    ring_attention_sharded,
)


def _case(rng, b=2, s=16, h=4, d=8):
    q = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
    positions = jnp.tile(jnp.arange(s)[None, :], (b, 1))
    valid = np.ones((b, s), dtype=bool)
    valid[0, :3] = False  # left padding on row 0
    return q, k, v, positions, jnp.asarray(valid)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense(eight_device_mesh, causal):
    rng = np.random.default_rng(0)
    q, k, v, positions, valid = _case(rng)
    dense = full_attention_reference(q, k, v, positions, valid, causal=causal)
    ring = ring_attention_sharded(eight_device_mesh, q, k, v, positions, valid, causal=causal)
    ring = np.asarray(ring)
    dense = np.asarray(dense)
    # padded-out query rows are undefined; compare only valid queries
    vmask = np.asarray(valid)[:, :, None, None]
    np.testing.assert_allclose(ring * vmask, dense * vmask, atol=1e-5, rtol=1e-5)


def test_ring_long_sequence(eight_device_mesh):
    """Longer sequence split 2 ways over sp (mesh sp=1 in fixture has dp=2,tp=4);
    build a dedicated sp-heavy mesh instead."""
    from fairness_llm_tpu.config import MeshConfig
    from fairness_llm_tpu.parallel import make_mesh

    mesh = make_mesh(MeshConfig(dp=1, tp=1, sp=8))
    rng = np.random.default_rng(1)
    q, k, v, positions, valid = _case(rng, b=1, s=64, h=2, d=16)
    dense = full_attention_reference(q, k, v, positions, valid)
    ring = ring_attention_sharded(mesh, q, k, v, positions, valid)
    vmask = np.asarray(valid)[:, :, None, None]
    np.testing.assert_allclose(
        np.asarray(ring) * vmask, np.asarray(dense) * vmask, atol=1e-5, rtol=1e-5
    )

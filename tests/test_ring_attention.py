"""Ring attention vs dense oracle on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fairness_llm_tpu.parallel.ring import (
    full_attention_reference,
    ring_attention_sharded,
)


def _case(rng, b=2, s=16, h=4, d=8):
    q = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
    positions = jnp.tile(jnp.arange(s)[None, :], (b, 1))
    valid = np.ones((b, s), dtype=bool)
    valid[0, :3] = False  # left padding on row 0
    return q, k, v, positions, jnp.asarray(valid)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense(eight_device_mesh, causal):
    rng = np.random.default_rng(0)
    q, k, v, positions, valid = _case(rng)
    dense = full_attention_reference(q, k, v, positions, valid, causal=causal)
    ring = ring_attention_sharded(eight_device_mesh, q, k, v, positions, valid, causal=causal)
    ring = np.asarray(ring)
    dense = np.asarray(dense)
    # padded-out query rows are undefined; compare only valid queries
    vmask = np.asarray(valid)[:, :, None, None]
    np.testing.assert_allclose(ring * vmask, dense * vmask, atol=1e-5, rtol=1e-5)


def test_ring_gqa_and_window(eight_device_mesh):
    """GQA kv (fewer heads than q) ride the ring unexpanded; sliding window
    masks by global position — both must match the expanded dense oracle."""
    from fairness_llm_tpu.config import MeshConfig
    from fairness_llm_tpu.parallel import make_mesh

    mesh = make_mesh(MeshConfig(dp=1, tp=1, sp=8))
    rng = np.random.default_rng(2)
    b, s, h, hkv, d = 1, 32, 4, 2, 8
    q = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, s, hkv, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, s, hkv, d)).astype(np.float32))
    positions = jnp.tile(jnp.arange(s)[None, :], (b, 1))
    valid = jnp.ones((b, s), bool)
    window = 7

    from jax.sharding import PartitionSpec as P
    import functools

    from fairness_llm_tpu.parallel.ring import ring_attention
    from fairness_llm_tpu.parallel.sharding import compat_shard_map

    fn = compat_shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=True, window=window),
        mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp"), P(None, "sp"),
                  P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    ring = np.asarray(fn(q, k, v, positions, positions, valid))

    kx = jnp.repeat(k, h // hkv, axis=2)
    vx = jnp.repeat(v, h // hkv, axis=2)
    scale = d ** -0.5
    sc = jnp.einsum("bqhd,bkhd->bhqk", q, kx) * scale
    ii = positions[:, :, None]
    jj = positions[:, None, :]
    mask = (jj <= ii) & ((ii - jj) < window)
    sc = jnp.where(mask[:, None, :, :], sc, -jnp.inf)
    p = jax.nn.softmax(sc, axis=-1)
    dense = np.asarray(jnp.einsum("bhqk,bkhd->bqhd", p, vx))
    np.testing.assert_allclose(ring, dense, atol=1e-5, rtol=1e-5)


def test_ring_long_sequence(eight_device_mesh):
    """Longer sequence split 2 ways over sp (mesh sp=1 in fixture has dp=2,tp=4);
    build a dedicated sp-heavy mesh instead."""
    from fairness_llm_tpu.config import MeshConfig
    from fairness_llm_tpu.parallel import make_mesh

    mesh = make_mesh(MeshConfig(dp=1, tp=1, sp=8))
    rng = np.random.default_rng(1)
    q, k, v, positions, valid = _case(rng, b=1, s=64, h=2, d=16)
    dense = full_attention_reference(q, k, v, positions, valid)
    ring = ring_attention_sharded(mesh, q, k, v, positions, valid)
    vmask = np.asarray(valid)[:, :, None, None]
    np.testing.assert_allclose(
        np.asarray(ring) * vmask, np.asarray(dense) * vmask, atol=1e-5, rtol=1e-5
    )

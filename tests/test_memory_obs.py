"""HBM memory ledger tests (ISSUE 18, telemetry/memory.py).

The accounting contract: every pool gauge equals the hand-computed nbytes
of the live device tree it claims to describe (contiguous cache, paged
arena, carried logits, params, prefix-KV LRU entries — and the per-shard
split on a tp mesh), registration/release/rebuild conserve the total, the
headroom forecaster's arithmetic is exact against an injected analytic
limit, arena exhaustion produces exactly one deduplicated
``memory_pressure`` bundle naming the deferring requests, attribution-off
records nothing, and the ``--require-memory`` validator gate accepts a
real run and rejects a stripped one.
"""

import json
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fairness_llm_tpu.config import MeshConfig, ModelSettings, ServingConfig
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.parallel import make_mesh
from fairness_llm_tpu.runtime.engine import DecodeEngine
from fairness_llm_tpu.serving import ContinuousScheduler, Request
from fairness_llm_tpu.telemetry import (
    set_aot_memory_capture,
    set_attribution,
    snapshot,
    use_flight_recorder,
    use_incident_manager,
    use_registry,
    use_timeline,
)
import fairness_llm_tpu.telemetry as T
from fairness_llm_tpu.telemetry.memory import (
    MemoryLedger,
    use_memory_ledger,
)


def _tool(name):
    sys.path.insert(0, "/root/repo/tools")
    try:
        import importlib

        return importlib.import_module(name)
    finally:
        sys.path.pop(0)


def greedy(m: int) -> ModelSettings:
    return ModelSettings(temperature=0.0, max_tokens=m)


@pytest.fixture(scope="module")
def engine():
    return DecodeEngine(get_model_config("tiny-test"), seed=0)


# -- pool accounting oracles ---------------------------------------------------


def test_register_release_conservation():
    """Alloc/release/rebuild conservation: the ledger total is exactly the
    sum of live entries, re-registering a handle REPLACES (rebuild
    semantics), and a drained pool's gauge reads 0 rather than going
    stale."""
    with use_registry() as reg, use_memory_ledger() as mem:
        a = jnp.zeros((8, 16), jnp.float32)   # 512 B
        b = jnp.zeros((4,), jnp.int32)        # 16 B
        assert mem.register("kv_contiguous", "t:a", a) == 8 * 16 * 4
        assert mem.register("logits_carry", "t:b", b) == 16
        assert mem.total_bytes() == 512 + 16
        assert reg.read_value("hbm_bytes", component="memory",
                              pool="kv_contiguous") == 512
        # Rebuild: same handle, twice the array — replaced, not added.
        assert mem.register("kv_contiguous", "t:a",
                            jnp.zeros((16, 16), jnp.float32)) == 1024
        assert mem.pool_bytes("kv_contiguous") == 1024
        assert mem.total_bytes() == 1024 + 16
        # Release drains to zero and the published gauge follows.
        assert mem.release("kv_contiguous", "t:a") == 1024
        assert mem.release("logits_carry", "t:b") == 16
        assert mem.total_bytes() == 0
        assert reg.read_value("hbm_bytes", default=-1.0, component="memory",
                              pool="kv_contiguous") == 0.0
        # Double release is a no-op, not an error.
        assert mem.release("kv_contiguous", "t:a") == 0
        # Unknown pools fail loudly — closed set, like incident classes.
        with pytest.raises(ValueError):
            mem.register("vram", "t:x", a)


def test_contiguous_cache_oracle(engine):
    """hbm_bytes{pool=kv_contiguous} equals the hand-computed bytes of the
    slot cache: layers x (k, v) x [num_slots, cache_len, n_kv, head_dim]
    f32 — and logits_carry equals num_slots x vocab x 4."""
    cfg = engine.config
    with use_registry() as reg, use_memory_ledger() as mem:
        sched = ContinuousScheduler(engine, ServingConfig(
            enabled=True, num_slots=2, max_prompt_len=64, max_new_tokens=16,
        ), settings=greedy(8))
        L = sched.cache_len
        expect_kv = (cfg.num_layers * 2 * 2 * L
                     * cfg.num_kv_heads * cfg.head_dim * 4  # k/v planes, f32
                     + 2 * L * 1    # key_valid, bool
                     + 2 * L * 4    # key_positions, int32
                     + 4            # index, scalar int32
                     + 2 * 4)       # lengths, int32 per slot
        assert mem.pool_bytes("kv_contiguous") == expect_kv
        assert mem.pool_bytes("logits_carry") == 2 * cfg.vocab_size * 4
        assert reg.read_value("hbm_bytes", component="memory",
                              pool="kv_contiguous") == expect_kv


def test_paged_arena_oracle(engine):
    """hbm_bytes{pool=kv_paged} equals the hand-computed arena bytes:
    per layer k/v [N, bs, n_kv, head_dim] f32 plus the validity (bool) and
    position (int32) planes plus per-slot lengths."""
    cfg = engine.config
    with use_registry(), use_memory_ledger() as mem:
        sched = ContinuousScheduler(engine, ServingConfig(
            enabled=True, num_slots=2, max_prompt_len=64, max_new_tokens=16,
            paged_kv=True, kv_block_size=16,
        ), settings=greedy(8))
        N = sched.pool.paged.num_blocks
        bs = 16
        expect = (cfg.num_layers * 2 * N * bs * cfg.num_kv_heads
                  * cfg.head_dim * 4     # k/v planes, f32
                  + N * bs * 1           # key_valid, bool
                  + N * bs * 4           # key_positions, int32
                  + 2 * 4)               # lengths, int32 per slot
        assert mem.pool_bytes("kv_paged") == expect
        # The forecaster's per-block price derives from the same tree.
        assert sched._block_bytes == expect // N


def test_params_pool_and_rebuild():
    """Engine construction registers the param tree; the handle is stable,
    so re-running the preflight (the rebuild path) replaces rather than
    double-counts."""
    with use_registry() as reg, use_memory_ledger() as mem:
        eng = DecodeEngine(get_model_config("tiny-test"), seed=0)
        expect = sum(int(x.nbytes) for x in
                     jax.tree_util.tree_leaves(eng.params))
        assert mem.pool_bytes("params") == expect
        eng._account_params_memory()  # what the VMEM-fallback rebuild runs
        assert mem.pool_bytes("params") == expect
        assert reg.read_value("hbm_bytes_total", component="memory",
                              reconciliation="indicative") == \
            mem.total_bytes()


def test_tp2_shard_split():
    """On a tp=2 mesh a sharded tree publishes per-device hbm_bytes rows
    under shard=tp<id> labels, and the split sums to the per-shard
    bytes."""
    mesh = make_mesh(MeshConfig(tp=2))
    spec = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("tp", None))
    x = jax.device_put(jnp.zeros((8, 16), jnp.float32), spec)
    with use_registry() as reg, use_memory_ledger() as mem:
        assert mem.register("kv_contiguous", "t:x", x) == 512
        shards = {d.id: 0 for sh in [x.addressable_shards] for s in sh
                  for d in [s.device]}
        assert len(shards) == 2
        total = 0
        for did in shards:
            v = reg.read_value("hbm_bytes", default=-1.0,
                               component="memory", pool="kv_contiguous",
                               shard=f"tp{did}")
            assert v == 256  # half of the 512 B array per device
            total += v
        assert total == 512


def test_prefix_kv_lru_instrumented(engine):
    """The engine's prefix-KV LRU registers each cached entry under
    pool=prefix_cache, counts evictions, and keeps the entry gauge at the
    working-set cap."""
    g = greedy(4)
    with use_registry() as reg, use_memory_ledger() as mem:
        for i in range(6):  # 6 distinct prefixes > the LRU's 4-entry cap
            common = f"shared instruction block {i} " * 8
            engine.generate([common + "user a", common + "user b"], g,
                            share_prefix=True)
        assert mem.pool_bytes("prefix_cache") > 0
        assert reg.read_value("prefix_kv_entries",
                              component="engine") <= 4
        assert reg.read_value("prefix_kv_evictions_total",
                              component="engine") >= 1


# -- reconciliation / forecast -------------------------------------------------


def test_headroom_forecast_math():
    with use_registry() as reg, use_memory_ledger() as mem:
        # No limit known: forecast abstains, it never guesses.
        fc = mem.forecast(1000)
        assert fc["basis"] is None and fc["fits"] is None
        assert mem.headroom_frac() is None
        mem.register("other", "t:a", jnp.zeros((256,), jnp.float32))  # 1 KiB
        mem.set_analytic_limit(10_240)
        fc = mem.forecast(2_048)
        assert fc["basis"] == "indicative"
        assert fc["headroom_bytes"] == 10_240 - 1_024
        assert fc["fits"] is True
        assert fc["headroom_after_frac"] == pytest.approx(
            (10_240 - 1_024 - 2_048) / 10_240)
        assert mem.headroom_frac() == pytest.approx(9_216 / 10_240)
        assert mem.forecast(9_217)["fits"] is False
        assert reg.read_value("hbm_bytes_limit", component="memory",
                              reconciliation="indicative") == 10_240
        assert reg.read_value("hbm_headroom_bytes", component="memory",
                              reconciliation="indicative") == 9_216
        # CPU reports no memory_stats, so no measured delta and no alerts.
        assert reg.read_value("hbm_reconciliation_alerts_total",
                              component="memory") == 0


def test_attribution_off_records_nothing():
    prev = set_attribution(True)
    try:
        with use_registry() as reg, use_memory_ledger() as mem:
            set_attribution(False)
            assert mem.register("other", "t:a",
                                jnp.zeros((64,), jnp.float32)) == 0
            assert mem.total_bytes() == 0
            mem.note_pressure("serving", True)
            assert not any(
                getattr(m, "name", "").startswith(("hbm_", "memory_"))
                for m in reg.instruments()
            )
    finally:
        set_attribution(prev)


# -- memory pressure -----------------------------------------------------------


def test_arena_exhaustion_fires_one_bundle(engine, tmp_path):
    """A scarce paged arena defers admissions (the pre-existing hard
    gate), and the ledger turns that into exactly ONE deduplicated
    memory_pressure bundle naming the deferring requests — with the
    recoverable memory_pressure_active gauge back at 0 once the drain
    completes."""
    probe = ContinuousScheduler(engine, ServingConfig(
        enabled=True, num_slots=2, max_prompt_len=192, max_new_tokens=32,
        decode_chunk=4, paged_kv=True, kv_block_size=16,
    ), settings=greedy(8))
    scarce = probe.pool.paged.blocks_per_slot + 2
    del probe
    cfg = ServingConfig(
        enabled=True, num_slots=2, max_prompt_len=192, max_new_tokens=32,
        decode_chunk=4, paged_kv=True, kv_block_size=16, kv_blocks=scarce,
    )
    stem = ("recommend five movies for a user who enjoyed Alien, Heat, "
            "Fargo, Tron and likes thrillers; profile ")
    fam = [stem + t for t in ("male 18-24", "female 18-24", "male 25-34",
                              "female 25-34")]
    with use_registry() as reg, use_timeline(), use_memory_ledger() as mem, \
            use_flight_recorder() as rec, use_incident_manager() as mgr:
        mgr.arm(str(tmp_path / "incidents"))
        sched = ContinuousScheduler(engine, cfg, settings=greedy(8))
        mem.set_analytic_limit(mem.total_bytes() + (16 << 20))
        res = sched.serve([Request(prompt=p, id=f"mem{i}",
                                   settings=greedy(8))
                           for i, p in enumerate(fam)])
        assert all(r.ok for r in res)
        bundles = T.list_bundles(str(tmp_path / "incidents"))
        mem_bundles = [b for b in bundles if b["class"] == "memory_pressure"]
        assert len(mem_bundles) == 1
        named = (mem_bundles[0].get("context") or {}).get("request_ids")
        assert named and all(str(r).startswith("mem") for r in named)
        assert mem_bundles[0]["context"]["headroom_bytes"] is not None
        # Recoverable: pressure cleared once admission succeeded again.
        assert reg.read_value("memory_pressure_active", default=-1.0,
                              component="memory", replica="serving") == 0.0
        # The flight recorder's memory ring saw the pressure transition.
        assert any(e.get("event") == "pressure"
                   for e in rec.rings["memory"])


# -- validator gate / CLI ------------------------------------------------------


def _serve_with_memory_obs(engine, mem):
    """A small serving run with the AOT capture armed — what a
    --telemetry-dir run records (telemetry.configure arms the flag)."""
    engine._account_params_memory()  # fixture engine predates this ledger
    prev = set_aot_memory_capture(True)
    try:
        sched = ContinuousScheduler(engine, ServingConfig(
            enabled=True, num_slots=2, max_prompt_len=64, max_new_tokens=8,
        ), settings=greedy(8))
        res = sched.serve([Request(prompt=p, settings=greedy(8))
                           for p in ("hello there", "quick brown fox",
                                     "one two three")])
        assert all(r.ok for r in res)
    finally:
        set_aot_memory_capture(prev)


def test_validate_require_memory_accept_reject(engine, tmp_path):
    from fairness_llm_tpu.telemetry import write_snapshot

    check = _tool("validate_telemetry").check
    with use_registry() as reg, use_timeline(), use_memory_ledger() as mem:
        _serve_with_memory_obs(engine, mem)
        write_snapshot(reg, str(tmp_path))
        assert check(str(tmp_path), require_memory=True) == 0
    # Same snapshot with the AOT program gauges stripped must fail: every
    # program in compiles_total owes a program_memory_bytes row.
    snap = json.load(open(tmp_path / "telemetry_snapshot.json"))
    bad1 = dict(snap)
    bad1["gauges"] = [g for g in snap["gauges"]
                      if g["name"] != "program_memory_bytes"]
    p1 = tmp_path / "bad_programs.json"
    p1.write_text(json.dumps(bad1))
    assert check(str(p1), require_memory=True) == 1
    # And with the pool gauges stripped too (no ledger at all).
    bad2 = dict(snap)
    bad2["gauges"] = [g for g in snap["gauges"]
                      if not g["name"].startswith("hbm_")]
    p2 = tmp_path / "bad_pools.json"
    p2.write_text(json.dumps(bad2))
    assert check(str(p2), require_memory=True) == 1


def test_cli_memory_report(engine, tmp_path, capsys):
    from fairness_llm_tpu.cli.main import main as cli_main
    from fairness_llm_tpu.telemetry import write_snapshot

    with use_registry() as reg, use_timeline(), use_memory_ledger() as mem:
        _serve_with_memory_obs(engine, mem)
        mem.set_analytic_limit(mem.total_bytes() + (32 << 20))
        write_snapshot(reg, str(tmp_path))
    assert cli_main(["memory-report", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "HBM memory ledger" in out
    assert "indicative" in out            # CPU: analytic-only labeling
    assert "kv_contiguous" in out
    assert "per-program AOT memory" in out
    # telemetry-report appends the same section when memory data exists.
    assert cli_main(["telemetry-report", str(tmp_path)]) == 0
    assert "HBM memory ledger" in capsys.readouterr().out
    # --require-ledger on an empty snapshot fails.
    empty = tmp_path / "empty"
    with use_registry() as reg2:
        from fairness_llm_tpu.telemetry import write_snapshot as ws

        ws(reg2, str(empty))
    assert cli_main(["memory-report", str(empty),
                     "--require-ledger"]) == 1

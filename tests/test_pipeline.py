"""End-to-end pipeline tests on the simulated backend (SURVEY.md §4's fake
decode backend): all three phases run, metrics land in-range, mitigation
actually reduces bias, and checkpoint resume skips completed work."""

import numpy as np
import pytest

from fairness_llm_tpu.config import Config
from fairness_llm_tpu.data import load_movielens
from fairness_llm_tpu.pipeline import (
    SimulatedRecommender,
    run_phase1,
    run_phase2,
    run_phase3,
)
from fairness_llm_tpu.pipeline.facter import (
    balanced_rerank_kernel,
    blended_group_fairness,
    conformal_keep_counts,
    conformal_thresholds_kernel,
    smart_balance,
)
from fairness_llm_tpu.pipeline.parsing import (
    canonical_title,
    parse_numbered_list,
    parse_pairwise_answer,
    parse_ranking_indices,
)

import jax.numpy as jnp


@pytest.fixture()
def config(tmp_path):
    return Config(results_dir=str(tmp_path / "results"), data_dir="/nonexistent")


@pytest.fixture()
def backend(config):
    data = load_movielens(config.data_dir, seed=config.random_seed)
    return SimulatedRecommender(data.titles, seed=config.random_seed, bias=0.8)


def test_phase1_end_to_end(config, backend):
    res = run_phase1(config, model_name="simulated", backend=backend, save=True)
    m = res["metrics"]
    assert res["metadata"]["num_profiles"] == 45
    assert len(res["recommendations"]) == 45
    assert 0.0 < m["demographic_parity_gender"]["score"] < 1.0
    assert 0.0 <= m["individual_fairness"]["score"] <= 1.0
    assert m["individual_fairness"]["num_pairs"] > 0
    assert 0.0 <= m["snsr_snsv"]["snsr"] <= 1.0
    # biased simulator: different groups get different recs -> parity < 0.95
    assert m["demographic_parity_gender"]["score"] < 0.95


def test_phase1_resume_skips_done(config, backend, monkeypatch):
    run_phase1(config, model_name="simulated", backend=backend, save=True)
    calls = []
    orig = backend.generate

    def counting(prompts, settings=None, seed=0, keys=None, prefix_ids=None):
        calls.append(len(prompts))
        return orig(prompts, settings, seed, keys, prefix_ids)

    monkeypatch.setattr(backend, "generate", counting)
    run_phase1(config, model_name="simulated", backend=backend, save=False, resume=True)
    assert sum(calls) == 0  # everything came from the checkpoint


def test_resume_reproduces_uninterrupted_run(config, backend, tmp_path):
    """A sweep resumed from a partial checkpoint must produce byte-identical
    recommendations to the uninterrupted run (absolute-position chunk seeds +
    occurrence-based simulator entropy)."""
    full = run_phase1(config, model_name="simulated", backend=backend, save=False)

    import dataclasses

    from fairness_llm_tpu.pipeline import results as R

    cfg2 = dataclasses.replace(config, results_dir=str(tmp_path / "r2"))
    # fabricate an interruption: checkpoint holding only the first 7 profiles
    partial = {
        pid: rec
        for pid, rec in list(full["recommendations"].items())[:7]
    }
    R.save_checkpoint(
        {pid: {"recommendations": r["recommendations"], "raw_response": r["raw_response"]}
         for pid, r in partial.items()},
        cfg2.results_dir, "phase1", 7,
    )
    resumed = run_phase1(cfg2, model_name="simulated", backend=backend, save=False, resume=True)
    for pid, rec in full["recommendations"].items():
        assert resumed["recommendations"][pid]["recommendations"] == rec["recommendations"], pid


def test_resume_falls_back_past_torn_checkpoint(tmp_path):
    """Torn-write regression: a preemption mid-write must never cost the
    --resume path more than the newest checkpoint. Writes are atomic
    (tmp + os.replace in results.save_results), so the only way a torn
    file appears is an OLDER non-atomic writer or filesystem damage —
    either way, resume must fall back to the newest READABLE checkpoint,
    not crash and not return nothing."""
    from fairness_llm_tpu.pipeline import results as R

    good = {"p1": {"recommendations": ["A"], "raw_response": "1. A"}}
    R.save_checkpoint(good, str(tmp_path), "phase1", 7)
    # A newer checkpoint torn mid-write: truncated JSON, mid-record.
    with open(R.checkpoint_path(str(tmp_path), "phase1", 14), "w") as f:
        f.write('{"completed": 14, "recommendations": {"p1": {"recommen')
    # And one torn inside a multi-byte character (UnicodeDecodeError path).
    with open(R.checkpoint_path(str(tmp_path), "phase1", 21), "wb") as f:
        f.write('{"completed": 21, "recommendations": {"é'.encode()[:-1])
    assert R.load_latest_checkpoint(str(tmp_path), "phase1") == good


def test_save_results_interrupted_write_keeps_previous(tmp_path, monkeypatch):
    """Kill the process mid-save_results: the destination file must still
    hold the PREVIOUS complete content (the atomicity --resume depends on),
    and no tmp litter may accumulate."""
    import os

    from fairness_llm_tpu.pipeline import results as R

    path = str(tmp_path / "phase1" / "phase1_results.json")
    R.save_results({"version": 1}, path)

    real_fsync = os.fsync

    def dying_fsync(fd):
        real_fsync(fd)
        raise KeyboardInterrupt  # preemption lands mid-write, pre-rename

    monkeypatch.setattr(os, "fsync", dying_fsync)
    with pytest.raises(KeyboardInterrupt):
        R.save_results({"version": 2, "huge": "x" * 10000}, path)
    monkeypatch.setattr(os, "fsync", real_fsync)
    assert R.load_results(path) == {"version": 1}
    assert not [p for p in (tmp_path / "phase1").iterdir()
                if p.name.endswith(".tmp")]


def test_phase2_end_to_end(config, backend):
    res = run_phase2(config, models=["simulated"], backends={"simulated": backend},
                     num_items=12, num_comparisons=10)
    mr = res["model_results"]["simulated"]
    assert 0.0 < mr["listwise"]["exposure_ratio"] <= 1.0
    assert 0.0 < mr["pairwise"]["exposure_ratio"] <= 1.0
    assert mr["pairwise"]["num_comparisons"] == 10
    assert set(mr["listwise"]["ranking"]) == set(range(12))
    avg = res["comparison"]["model_fairness"]["simulated"]["average_fairness"]
    assert 0.0 < avg <= 1.0


@pytest.mark.parametrize("variant", ["conformal", "smart", "aggressive"])
def test_phase3_variants(config, backend, variant):
    p1 = run_phase1(config, model_name="simulated", backend=backend, save=True)
    res = run_phase3(config, phase1_results=p1, model_name="simulated",
                     backend=backend, variant=variant)
    b = res["bias_reduction"]
    assert 0.0 <= b["mitigated_fairness"] <= 1.0
    assert res["quality_preservation"]["num_comparisons"] == 45
    # the simulator responds to fairness prompting -> bias must go down
    assert b["bias_reduction_rate"] > 0, f"{variant}: {b}"


def test_phase3_num_profiles_is_stratified(config, backend):
    """--profiles must take N per (gender, age) combo, not a gender-major
    prefix (which would collapse demographic parity to one group)."""
    p1 = run_phase1(config, model_name="simulated", backend=backend, save=False)
    res = run_phase3(config, phase1_results=p1, model_name="simulated",
                     backend=backend, num_profiles=1, save=False)
    assert res["metadata"]["num_profiles"] == 15  # 3 genders x 5 ages x 1
    genders = {pid.split("_")[0] for pid in res["mitigated_recommendations"]}
    # all three genders represented among mitigated profiles
    mit = res["mitigated_recommendations"]
    from fairness_llm_tpu.pipeline.phase3 import _profiles_from_dicts

    profs = {p.id: p for p in _profiles_from_dicts(p1["profiles"])}
    assert {profs[pid].gender for pid in mit} == {"male", "female", "non-binary"}


# ---------------------------------------------------------------------------
# FACTER kernel unit tests
# ---------------------------------------------------------------------------


def test_conformal_thresholds_match_numpy():
    rng = np.random.default_rng(0)
    scores = rng.uniform(0, 1, 200).astype(np.float32)
    groups = rng.integers(0, 3, 200).astype(np.int32)
    out = np.asarray(conformal_thresholds_kernel(jnp.asarray(scores), jnp.asarray(groups), 3, alpha=0.1))
    for g in range(3):
        s = np.sort(scores[groups == g])
        n = len(s)
        idx = int(np.ceil((n + 1) * 0.9)) - 1
        idx = max(0, min(idx, n - 1))
        np.testing.assert_allclose(out[g], s[idx], atol=1e-6)


def test_conformal_filter_mask_general():
    from fairness_llm_tpu.pipeline.facter import conformal_filter_mask

    conf = np.array(
        [[0.9, 0.2, 0.8, np.nan],     # threshold .5 -> keep {0, 2}, floor kicks in (2 < 3)? n_keep=2 -> top-3 by conf = {0,2,1}
         [0.9, 0.8, 0.7, 0.6]],       # threshold .5 -> keep all 4
        np.float32,
    )
    thresholds = np.array([0.5, 0.5], np.float32)
    mask = np.asarray(conformal_filter_mask(jnp.asarray(conf), jnp.asarray(thresholds)))
    assert mask[0].tolist() == [True, True, True, False]  # floor-3 by confidence
    assert mask[1].tolist() == [True, True, True, True]


def test_phase3_model_calibration(config):
    """calibration='model' uses the engine's title likelihoods end to end."""
    from fairness_llm_tpu.models.configs import get_model_config
    from fairness_llm_tpu.pipeline.backends import EngineBackend
    from fairness_llm_tpu.runtime.engine import DecodeEngine

    eng_backend = EngineBackend(DecodeEngine(get_model_config("tiny-test"), seed=0),
                                name="tiny-test")
    sim = SimulatedRecommender(
        [f"Movie {i}" for i in range(40)], seed=config.random_seed, bias=0.8
    )
    p1 = run_phase1(config, model_name="simulated", backend=sim, save=False)
    # hybrid: phase-1 recs from the simulator (parseable), calibration scored
    # by the real engine

    class Hybrid:
        name = "hybrid"
        engine = eng_backend.engine

        def generate(self, prompts, settings=None, seed=0, keys=None, prefix_ids=None):
            return sim.generate(prompts, settings, seed, keys)

    res = run_phase3(config, phase1_results=p1, model_name="simulated",
                     backend=Hybrid(), variant="conformal", save=False,
                     calibration="model")
    assert res["metadata"]["calibration"] == "model"
    assert res["quality_preservation"]["num_comparisons"] == 45
    mit = res["mitigated_recommendations"]
    # floor respected
    assert all(len(v) >= 3 for v in mit.values())
    # and the filter actually DISCRIMINATES on model likelihoods — it must not
    # degenerate to floor-3 truncation everywhere (the scale-mismatch failure
    # mode): most lists keep more than the floor, and some items are dropped
    lens = [len(v) for v in mit.values()]
    assert max(lens) > 3
    assert sum(lens) < 45 * 10  # at least one item filtered out


def test_conformal_keep_is_prefix_with_floor():
    lengths = np.array([10, 10, 2, 10])
    thresholds = np.array([0.0, 0.8, 0.0, 1.0])
    keep = conformal_keep_counts(lengths, thresholds)
    assert keep[0] == 10  # threshold 0 keeps ranks with conf >= 0 -> all
    assert keep[1] == 5  # 1-0.05r >= 0.8 -> r <= 4 -> 5 items
    assert keep[2] == 2  # short list: floor is min(len, 3)
    assert keep[3] == 3  # threshold 1.0 -> keep 1 < 3 -> floor 3


def test_smart_balance_matches_reference_semantics():
    """Tiny case checked by hand against the reference algorithm
    (phase3_final.py:43-110): shared movies with balanced counts come first."""
    recs = {
        "male": [["a", "b", "x"], ["a", "c", "y"]],
        "female": [["a", "b", "z"], ["a", "c", "w"]],
    }
    out = smart_balance(recs, top_k=3)
    # counts: a:2/2 ratio 1, b:1/1, c:1/1 -> balanced {a,b,c} (relaxed <20 rule)
    # male row 0 [a,b,x]: balanced a,b first, then x -> [a,b,x]
    assert out["male"][0] == ["a", "b", "x"]
    # male row 1 [a,c,y]: [a,c,y]
    assert out["male"][1] == ["a", "c", "y"]
    assert out["female"][0] == ["a", "b", "z"]


def test_balanced_rerank_backfill():
    rows = jnp.asarray(np.array([[3, 4, -1, -1]], dtype=np.int32))
    c1 = jnp.asarray(np.array([5, 0, 2, 1, 0], np.float32))
    c2 = jnp.asarray(np.array([5, 0, 2, 0, 1], np.float32))
    out, balanced = balanced_rerank_kernel(rows, c1, c2, top_k=4)
    out = np.asarray(out[0])
    # balanced = {0, 2} (ratio 1.0); row has 3,4 (unbalanced) -> order:
    # no balanced in row; originals 3,4; backfill 0,2
    assert list(out) == [3, 4, 0, 2]
    # aggressive order: cross-group backfill ahead of own unbalanced items
    out_a, _ = balanced_rerank_kernel(
        rows, c1, c2, top_k=4,
        threshold=0.3, relaxed_threshold=0.3, relax_below=0, backfill_first=True,
    )
    assert list(np.asarray(out_a[0])) == [0, 2, 3, 4]


def test_blended_fairness_identical_groups_is_one():
    recs = {"m": [["a", "b"]], "f": [["a", "b"]]}
    assert blended_group_fairness(recs) == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# Parsing unit tests
# ---------------------------------------------------------------------------


def test_parse_numbered_list():
    text = "Here you go:\n1. The Matrix (1999)\n2) Alien\n3: Up\nnot a line"
    assert parse_numbered_list(text) == ["The Matrix (1999)", "Alien", "Up"]


def test_parse_ranking_indices_appends_missing():
    assert parse_ranking_indices("3, 1, 99", 4) == [2, 0, 1, 3]


def test_parse_pairwise():
    assert parse_pairwise_answer(" a") == "A"
    assert parse_pairwise_answer("B.") == "B"
    assert parse_pairwise_answer("both are good: A and B") == "tie"


def test_canonical_title():
    assert canonical_title("Matrix, The (1999)") == "the matrix"
    assert canonical_title("  Amélie   (2001) ") == "amélie"

"""Flash-attention kernel vs dense oracle (interpret mode — runs on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fairness_llm_tpu.ops import flash_attention, flash_supported


def _dense(q, k, v, lengths, causal=True, window=None):
    B, H, S, D = q.shape
    rep = H // k.shape[1]
    kk = jnp.repeat(k, rep, 1)
    vv = jnp.repeat(v, rep, 1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, kk) / np.sqrt(D)
    ii = jnp.arange(S)[:, None]
    jj = jnp.arange(S)[None, :]
    mask = jj >= (S - lengths)[:, None, None, None]
    if causal:
        mask = mask & (jj <= ii)
    if window is not None:
        mask = mask & ((ii - jj) < window)
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, -1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, vv)


@pytest.mark.parametrize(
    "kwargs,D",
    [
        (dict(causal=True), 128),
        (dict(causal=False), 128),
        (dict(causal=True, window=64), 128),
        (dict(causal=True), 64),  # gpt2/llama32-1b head_dim (padded lanes)
        (dict(causal=False), 64),
    ],
)
def test_flash_matches_dense_interpret(kwargs, D):
    B, H, Hkv, S = 2, 4, 2, 256
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, H, S, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, Hkv, S, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, Hkv, S, D)).astype(np.float32))
    lengths = jnp.asarray(np.array([S, S - 37], np.int32))
    out = flash_attention(q, k, v, lengths, interpret=True, **kwargs)
    ref = _dense(q, k, v, lengths, **kwargs)
    valid_q = (jnp.arange(S)[None, :] >= (S - lengths)[:, None])[:, None, :, None]
    np.testing.assert_allclose(
        np.asarray(out * valid_q), np.asarray(ref * valid_q), atol=3e-5
    )


def test_flash_supported_gates():
    assert flash_supported(256, 128)
    assert flash_supported(256, 64)  # gpt2/llama32-1b head_dim: padded lanes
    assert not flash_supported(256, 32)  # sub-64 wastes > half the tile
    assert not flash_supported(200, 128)  # non-multiple seq

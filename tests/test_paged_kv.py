"""Paged KV cache + radix-tree prefix reuse tests (ISSUE 10).

The correctness contract is the same one every serving PR pins — token-for-
token greedy parity with ``DecodeEngine.generate`` alone — now under the
paged layout: shared prefix blocks, copy-on-write at the divergence point,
LRU eviction of unreferenced radix leaves, and block recycling under slot
churn (eviction + backfill + requeue-once + fleet migration). On top of
that: host-side allocator/refcount invariants, the block-granularity
invalidation discipline, and the prefix-cache metrics.
"""

import dataclasses

import numpy as np
import pytest

from fairness_llm_tpu.config import (
    FleetConfig,
    IntegrityConfig,
    ModelSettings,
    ResilienceConfig,
    ServingConfig,
)
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.runtime.engine import DecodeEngine
from fairness_llm_tpu.serving import (
    ContinuousScheduler,
    PagedKV,
    RadixIndex,
    ReplicaSet,
    Request,
    SlotPool,
    SlotState,
)
from fairness_llm_tpu.telemetry import use_registry
from fairness_llm_tpu.utils.failures import ScriptedFaultInjector


def greedy(m: int) -> ModelSettings:
    return ModelSettings(temperature=0.0, max_tokens=m)


PCFG = ServingConfig(
    enabled=True, num_slots=2, queue_capacity=64,
    max_prompt_len=192, max_new_tokens=32, decode_chunk=4,
    paged_kv=True, kv_block_size=16,
)

# A counterfactual-shaped family: one long shared stem, tiny divergent
# tails — the phase-1 regime the paged cache exists for. Byte-tokenized
# lengths stay inside the 192-token serving budget (parity needs that).
STEM = ("Recommend 5 movies. The user enjoyed Alien, Heat, Fargo, Clue, "
        "Tron, Big, Jaws, Up. Genres: drama, thriller. Profile: ")
FAMILY = [STEM + tail for tail in (
    "male 18-24", "female 18-24", "nonbinary 18-24", "male 25-34",
    "female 25-34", "nonbinary 25-34", "male 35-44", "female 35-44",
)]

MIXED = [
    "the quick brown fox",
    "hi",
    "abc abc abc abc abc abc",
    "a long prompt that shifts padding " * 5 + "and lands in a big bucket",
    "zz",
    "recommend ten films please",
    "one two three one two three",
]


@pytest.fixture(scope="module")
def engine():
    return DecodeEngine(get_model_config("tiny-test"), seed=0)


def _req(prompt, m=8, **kw):
    return Request(prompt=prompt, settings=greedy(m), **kw)


def _assert_engine_parity(engine, req, res):
    assert res.ok, (res.id, res.finish_reason, res.error)
    ref = engine.generate([req.prompt], req.settings)
    n = len(res.tokens)
    assert n > 0
    np.testing.assert_array_equal(res.tokens, ref.tokens[0][:n])
    assert np.all(ref.tokens[0][n:] == engine.tokenizer.pad_id)


def _paged_invariant(paged: PagedKV):
    """free + live-private + tree-owned == num_blocks, no id appears twice."""
    tree_blocks = []
    stack = [paged.index.root]
    while stack:
        node = stack.pop()
        for child in node.children.values():
            tree_blocks.append(child.block)
            stack.append(child)
    private = [b for blocks in paged._private.values() for b in blocks]
    everything = list(paged._free) + private + tree_blocks
    assert len(everything) == len(set(everything)), "block id aliased"
    assert len(everything) == paged.num_blocks, (
        len(paged._free), len(private), len(tree_blocks), paged.num_blocks
    )


# -- radix index units --------------------------------------------------------


def test_radix_match_insert_refcount():
    idx = RadixIndex(4)
    ids = list(range(10))  # blocks [0..3], [4..7]; tail 8,9
    m = idx.match(ids)
    assert m.nodes == [] and m.cow_len == 0
    held, promoted = idx.insert(ids, [100, 101], m.nodes)
    assert promoted == [100, 101] and len(held) == 2
    assert all(n.refs == 1 for n in held)
    # Second identical prompt: both full blocks match (9 tokens matchable).
    m2 = idx.match(ids)
    assert [n.block for n in m2.nodes] == [100, 101]
    assert held[0].refs == 2 and held[1].refs == 2
    idx.release(m2.nodes)
    idx.release(held)
    assert held[0].refs == 0 and held[1].refs == 0
    assert len(idx) == 2  # unreferenced nodes stay CACHED


def test_radix_match_caps_at_len_minus_one():
    """A fully-cached prompt must still prefill >= 1 token (the sampler
    needs last-token logits), so an exact-multiple prompt matches one
    block short of everything."""
    idx = RadixIndex(4)
    ids = list(range(8))  # exactly two blocks
    m0 = idx.match(ids)
    held, _ = idx.insert(ids, [7, 8], m0.nodes)
    m = idx.match(ids)
    # only block 0 fully matches (7 matchable tokens); block 1 partial CoW
    assert [n.block for n in m.nodes] == [7]
    assert m.cow_src_block == 8 and m.cow_len == 3
    assert m.matched(4) == 7 == len(ids) - 1
    # match() pinned the CoW source too — it must be unevictable until the
    # device copy lands (commit), so releasing a match means nodes + pin.
    # refs == 2: the original inserter's held ref + this match's pin.
    assert m.cow_node.refs == 2
    idx.release(m.nodes + [m.cow_node])
    idx.release(held)


def test_radix_cow_partial_match():
    idx = RadixIndex(4)
    a = [1, 2, 3, 4, 5, 6, 7, 8, 9]
    held, _ = idx.insert(a, [0, 1], idx.match(a).nodes)
    b = [1, 2, 3, 4, 5, 6, 99, 98, 97]  # diverges inside block 1
    m = idx.match(b)
    assert [n.block for n in m.nodes] == [0]
    assert m.cow_src_block == 1 and m.cow_len == 2  # tokens 5,6 shared
    assert m.matched(4) == 6
    # The pinned source must survive eviction pressure until released.
    assert idx.evict_lru() is None
    idx.release(m.nodes + [m.cow_node])
    idx.release(held)


def test_radix_evict_lru_leaf_first():
    idx = RadixIndex(2)
    a = [1, 2, 3, 4, 5]  # blocks [1,2], [3,4]
    b = [1, 2, 9, 9, 9]  # shares block [1,2], own [9,9]
    ha, _ = idx.insert(a, [10, 11], idx.match(a).nodes)
    mb = idx.match(b)
    hb, _ = idx.insert(b, [mb.nodes[0].block, 12], mb.nodes)
    idx.release(ha)
    idx.release(hb)
    # Leaves are 11 ([3,4], older) and 12 ([9,9], newer); the shared root
    # block 10 is interior and must outlive both.
    assert idx.evict_lru() == 11
    assert idx.evict_lru() == 12
    assert idx.evict_lru() == 10
    assert idx.evict_lru() is None and len(idx) == 0


def test_radix_evict_skips_referenced():
    idx = RadixIndex(2)
    a = [1, 2, 3, 4, 5]
    held, _ = idx.insert(a, [0, 1], idx.match(a).nodes)
    assert idx.evict_lru() is None  # both nodes referenced
    idx.release(held)
    assert idx.evict_lru() == 1


# -- PagedKV allocator --------------------------------------------------------


def test_paged_kv_admit_commit_release_accounting():
    paged = PagedKV(num_slots=2, blocks_per_slot=4, block_size=4)
    ids = list(range(14))  # 3 full blocks + tail
    plan = paged.admit(0, ids)
    assert plan is not None and plan.matched == 0
    assert len(plan.table) == 4 and plan.cow_src == paged.num_blocks
    paged.commit(0, ids)
    _paged_invariant(paged)
    # Twin admission shares the 3 full blocks... but only 13 tokens are
    # matchable, so blocks 0-2 (12 tokens) share + 1 CoW-free token.
    plan2 = paged.admit(1, ids)
    assert plan2 is not None
    assert plan2.table[:3] == plan.table[:3]  # shared prefix blocks
    assert plan2.matched >= 12
    # Shared entries in the write table must DROP (out of range).
    assert all(w == paged.num_blocks for w in plan2.write_table[:3])
    paged.commit(1, ids)
    _paged_invariant(paged)
    # Releasing one twin must not free the other's shared blocks.
    paged.release(0)
    _paged_invariant(paged)
    assert all(b not in paged._free for b in plan2.table[:3])
    m = paged.index.match(ids)
    assert [n.block for n in m.nodes] == plan2.table[:3]
    paged.index.release(m.nodes)
    paged.release(1)
    _paged_invariant(paged)
    # Everything released: the full blocks stay cached in the tree.
    assert paged.index.cached_blocks() == 3


def test_paged_kv_exhaustion_and_eviction():
    paged = PagedKV(num_slots=2, blocks_per_slot=4, block_size=4,
                    num_blocks=5)
    a = list(range(10))
    assert paged.admit(0, a) is not None
    paged.commit(0, a)
    # 4 blocks live-private/tree, 1 free: a disjoint second prompt cannot
    # fit 4 private blocks while slot 0 holds refs.
    b = list(range(100, 110))
    assert paged.admit(1, b) is None
    _paged_invariant(paged)
    paged.release(0)
    # Now the cached (unreferenced) blocks of A evict LRU to make room.
    with use_registry() as reg:
        plan_b = paged.admit(1, b)
        assert plan_b is not None
        ev = reg.peek("kv_blocks_evicted_total", component="paged_kv")
        assert ev is not None and ev.value >= 1
    paged.commit(1, b)
    _paged_invariant(paged)
    paged.release(1)


def test_cow_source_pinned_until_commit():
    """The eviction race regression: between planning an admission and its
    device prefill, ANOTHER admission's eviction must not free the first's
    copy-on-write source block (it would be reallocated and rewritten
    before the copy reads it). match() pins the source; the pin drops at
    commit."""
    paged = PagedKV(num_slots=2, blocks_per_slot=3, block_size=4,
                    num_blocks=6)
    p1 = [1, 2, 3, 4, 5, 6, 7, 8, 9]
    assert paged.admit(0, p1) is not None
    paged.commit(0, p1)
    paged.release(0)  # two cached nodes, three free blocks
    p2 = [1, 2, 3, 4, 5, 6, 99, 98, 97]  # shares blk0, CoW inside blk1
    plan2 = paged.admit(0, p2)
    assert plan2 is not None and plan2.cow_src < paged.num_blocks
    # A disjoint admission needing eviction must BACKPRESSURE, not evict
    # the pinned CoW source out from under the planned copy.
    p3 = [50, 51, 52, 53, 54, 55, 56, 57, 58]
    assert paged.admit(1, p3) is None
    assert paged._cow[0].refs == 1  # the pin is what protected the source
    paged.commit(0, p2)  # copy landed -> pin drops -> source evictable
    assert 0 not in paged._cow
    assert paged.admit(1, p3) is not None
    _paged_invariant(paged)
    paged.release(0)
    paged.release(1)
    _paged_invariant(paged)


def test_slot_pool_routes_release_through_paged():
    paged = PagedKV(num_slots=2, blocks_per_slot=4, block_size=4)
    pool = SlotPool(2, paged=paged)
    s = pool.alloc(SlotState(request=Request(prompt="x"), base=5, real_len=5))
    ids = list(range(5))
    assert paged.admit(s, ids) is not None
    paged.commit(s, ids)
    pool.release(s)
    assert paged.table_for(s) is None
    # Paged mode: no row-reset rides the next step.
    assert pool.pending_invalidation == []
    _paged_invariant(paged)


def test_pending_invalidation_is_o1_and_ordered():
    """The satellite: dict-backed pending set keeps deterministic (release-
    order) flush while alloc's cancellation is O(1)."""
    pool = SlotPool(4)
    for i in range(4):
        pool.alloc(SlotState(request=Request(prompt=f"p{i}"), base=1,
                             real_len=1))
    pool.release(2)
    pool.release(0)
    pool.release(3)
    assert pool.pending_invalidation == [2, 0, 3]  # release order, not id
    assert pool.alloc(SlotState(request=Request(prompt="r"), base=1,
                                real_len=1)) == 0
    assert pool.pending_invalidation == [2, 3]
    assert pool.take_invalidations() == [2, 3]
    assert pool.pending_invalidation == []


# -- serving parity -----------------------------------------------------------


def test_paged_server_matches_engine_greedy_mixed_lengths(engine):
    sched = ContinuousScheduler(engine, PCFG, settings=greedy(16))
    reqs = [_req(p, m=8 + 2 * (i % 5)) for i, p in enumerate(MIXED)]
    results = sched.serve(reqs)
    for req, res in zip(reqs, results):
        _assert_engine_parity(engine, req, res)


def test_paged_parity_shared_prefix_churn_and_requeue(engine):
    """The defining workload through a scarce arena: 8 near-duplicate
    prompts through 2 slots with only ~1.5 slots' worth of blocks, plus a
    mid-sweep decode fault — eviction, backfill, block recycling, and a
    requeue-once all compose, and every token still matches the engine."""
    bps = ContinuousScheduler(engine, PCFG,
                              settings=greedy(8)).pool.paged.blocks_per_slot
    # One slot's worth + 2: admissions serialize behind block backpressure
    # and freed blocks recycle constantly (eviction itself is unit-covered
    # in test_paged_kv_exhaustion_and_eviction — the mid-run fault below
    # resets the index, so demanding an eviction here would race it).
    scarce = dataclasses.replace(PCFG, kv_blocks=bps + 2)
    inj = ScriptedFaultInjector({("fam3", "decode"): 1})
    with use_registry():
        sched = ContinuousScheduler(engine, scarce, settings=greedy(8),
                                    fault_injector=inj)
        reqs = [_req(p, m=8, id=f"fam{i}") for i, p in enumerate(FAMILY)]
        results = sched.serve(reqs)
        for req, res in zip(reqs, results):
            _assert_engine_parity(engine, req, res)
        assert results[3].retries == 1  # the fault requeued once
        _paged_invariant(sched.pool.paged)


def test_paged_parity_independent_of_pool_composition(engine):
    target = FAMILY[2]
    alone = ContinuousScheduler(engine, PCFG, settings=greedy(12)).serve(
        [_req(target, m=12)]
    )[0]
    crowd = [_req(p, m=6) for p in MIXED[:2]] + [_req(target, m=12)] + \
        [_req(p, m=10) for p in FAMILY[:3]]
    crowded = ContinuousScheduler(engine, PCFG, settings=greedy(12)).serve(
        crowd
    )[2]
    np.testing.assert_array_equal(alone.tokens, crowded.tokens)


def test_paged_cow_at_divergence_never_mutates_source(engine):
    """Two prompts diverging mid-block force a copy-on-write; serving the
    first prompt AGAIN afterwards must reproduce the engine exactly — if
    the CoW had mutated the shared source block in place, the re-serve
    would decode the second prompt's tokens through the first's prefix."""
    a, b = FAMILY[0], FAMILY[1]
    with use_registry() as reg:
        sched = ContinuousScheduler(engine, PCFG, settings=greedy(8))
        res_a = sched.serve([_req(a)])[0]
        _assert_engine_parity(engine, _req(a), res_a)
        res_b = sched.serve([_req(b)])[0]
        _assert_engine_parity(engine, _req(b), res_b)
        cow = reg.peek("prefix_cache_cow_total", component="paged_kv")
        assert cow is not None and cow.value >= 1, \
            "divergence inside a block must copy-on-write"
        res_a2 = sched.serve([_req(a)])[0]
        np.testing.assert_array_equal(res_a2.tokens, res_a.tokens)
        res_b2 = sched.serve([_req(b)])[0]
        np.testing.assert_array_equal(res_b2.tokens, res_b.tokens)


def test_paged_twin_release_keeps_shared_blocks_readable(engine):
    """Refcount safety end-to-end: pair members with staggered budgets —
    the short one finishes and releases while its twin still decodes
    through the shared prefix blocks. The twin's tokens must not change."""
    sched = ContinuousScheduler(engine, PCFG, settings=greedy(24))
    reqs = [_req(FAMILY[0], m=2), _req(FAMILY[1], m=24)]
    results = sched.serve(reqs)
    for req, res in zip(reqs, results):
        _assert_engine_parity(engine, req, res)


def test_paged_hit_rate_counterfactual_shape(engine):
    """The acceptance shape: a phase-1-like family must push the hit ratio
    past 0.5 (the CI gate; the bench pushes past 0.8 with more variants),
    with hit tokens visible in the process counters."""
    with use_registry() as reg:
        sched = ContinuousScheduler(engine, PCFG, settings=greedy(8))
        results = sched.serve([_req(p) for p in FAMILY])
        assert all(r.ok for r in results)
        paged = sched.pool.paged
        assert paged.hit_ratio > 0.5, paged.hit_ratio
        hit = reg.peek("prefix_cache_hit_tokens_total", component="paged_kv")
        assert hit is not None and hit.value > 0
        gauge = reg.peek("prefix_cache_hit_ratio", component="paged_kv")
        assert gauge is not None and gauge.value == pytest.approx(
            paged.hit_ratio
        )


def test_paged_numerics_guard_and_corruption_containment(engine):
    """The integrity layer composes: guarded paged programs compile and a
    scripted NaN corruption is contained as a requeue, parity held."""
    engine.numerics_guards = True
    try:
        inj = ScriptedFaultInjector({}, corruptions={("fam1", "decode"): 1})
        sched = ContinuousScheduler(
            engine, PCFG, settings=greedy(8), fault_injector=inj,
            resilience=ResilienceConfig(enabled=True),
        )
        reqs = [_req(p, m=8, id=f"fam{i}") for i, p in enumerate(FAMILY[:4])]
        results = sched.serve(reqs)
        for req, res in zip(reqs, results):
            _assert_engine_parity(engine, req, res)
        # The corrupted chunk rebuilt the arena; the index forgot the
        # zeroed prefixes and the allocator is whole again.
        _paged_invariant(sched.pool.paged)
    finally:
        engine.numerics_guards = False


def test_paged_fleet_migration_parity(engine):
    """Fleet failover over paged replicas: kill r1 mid-sweep — zero lost,
    migrated survivors token-identical through r0's own paged pool."""
    inj = ScriptedFaultInjector(replica_crashes={"r1": 3})
    fleet = ReplicaSet(
        engine, PCFG, settings=greedy(8),
        fleet=FleetConfig(replicas=2, fence_cooldown_s=0.02),
        resilience=ResilienceConfig(enabled=True, breaker_threshold=1,
                                    breaker_cooldown_s=0.01),
        fault_injector=inj, integrity=IntegrityConfig(canary_max_tokens=8),
    )
    reqs = [_req(p, m=8, id=f"fam{i}") for i, p in enumerate(FAMILY)]
    results = fleet.serve(reqs)
    for req, res in zip(reqs, results):
        _assert_engine_parity(engine, req, res)
    assert inj.replica_faults_fired == [("r1", "replica_crash")]


def test_paged_scheduler_reusable_across_serves(engine):
    sched = ContinuousScheduler(engine, PCFG, settings=greedy(8))
    first = sched.serve([_req(FAMILY[0])])[0]
    ratio0 = sched.pool.paged.hit_ratio
    second = sched.serve([_req(FAMILY[0])])[0]
    np.testing.assert_array_equal(first.tokens, second.tokens)
    assert sched.pool.paged.hit_ratio > ratio0  # the repeat hit the cache


# -- prompt layout satellites -------------------------------------------------


def test_recommendation_prompt_pairs_diverge_late():
    """The layout contract the hit rate rides on: counterfactual pairs
    share most of their bytes as a prefix (demographics last)."""
    from fairness_llm_tpu.data.profiles import Profile
    from fairness_llm_tpu.pipeline.prompts import (
        divergence_stats,
        recommendation_prompt,
    )

    movies = [f"Movie {i}" for i in range(10)]
    pairs = []
    for g1, g2 in (("male", "female"), ("female", "non-binary")):
        a = Profile(id="a", gender=g1, age="25-34", occupation="pro",
                    watched_movies=movies, favorite_genres=["drama"])
        b = Profile(id="b", gender=g2, age="25-34", occupation="pro",
                    watched_movies=movies, favorite_genres=["drama"])
        pairs.append((recommendation_prompt(a), recommendation_prompt(b)))
    stats = divergence_stats(pairs)
    assert stats["pairs"] == 2
    assert stats["min_frac"] > 0.7, stats


def test_divergence_stats_math():
    from fairness_llm_tpu.pipeline.prompts import divergence_stats, lcp_len

    assert lcp_len("abcd", "abXd") == 2
    assert lcp_len("abc", "abc") == 3
    s = divergence_stats([("aaaa", "aaXX"), ("bb", "bb")])
    assert s["min_frac"] == pytest.approx(0.5)
    assert s["max_frac"] == pytest.approx(1.0)
    assert divergence_stats([])["pairs"] == 0

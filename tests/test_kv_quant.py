"""int8 KV cache: decode quality vs the full-precision cache."""

import dataclasses

import numpy as np

from fairness_llm_tpu.config import ModelSettings
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.models.transformer import init_params
from fairness_llm_tpu.runtime.engine import DecodeEngine

import jax


def test_quantized_cache_decode_close_to_fp():
    cfg = get_model_config("tiny-test")
    cfg_q = dataclasses.replace(cfg, kv_cache_quant=True)
    params = init_params(cfg, jax.random.key(0))
    fp = DecodeEngine(cfg, params=params)
    q8 = DecodeEngine(cfg_q, params=params)
    g = ModelSettings(temperature=0.0, max_tokens=24)
    prompts = ["the quick brown fox jumps", "over the lazy dog"]
    a = fp.generate(prompts, g)
    b = q8.generate(prompts, g)
    # greedy tokens should agree for the vast majority of steps; int8 KV
    # rounding can flip a late argmax on a random-weight model
    agreement = (a.tokens == b.tokens).mean()
    assert agreement > 0.7, f"quantized decode diverged too much ({agreement:.2f})"


def test_quantized_cache_dtype():
    cfg = dataclasses.replace(get_model_config("tiny-test"), kv_cache_quant=True)
    from fairness_llm_tpu.models.transformer import init_cache

    cache = init_cache(cfg, 2, 32)
    assert cache.layers[0].k.dtype == np.int8
    assert cache.layers[0].k_scale.dtype == np.float32

"""Qwen2 TP readiness: the new family's sharding rules and bias params must
survive compile at tensor parallelism, same compile-time proof style as
tests/test_70b_readiness.py (the biases are the family's novel tensors — a
rule or layout that mishandles them fails here, not on hardware)."""

import types

import flax.linen as nn
import jax
import jax.numpy as jnp
import pytest

from fairness_llm_tpu.config import MeshConfig
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.models.transformer import Transformer, init_cache
from fairness_llm_tpu.parallel import sharding as shd


def _rules_for_shape(cfg, shape):
    return shd.make_axis_rules(cfg, types.SimpleNamespace(shape=shape))


def test_qwen2_7b_rules_tp4():
    cfg = get_model_config("qwen2-7b")
    rules = dict(_rules_for_shape(cfg, {"dp": 1, "tp": 4, "sp": 1}))
    # 28 q heads -> 7/chip; 4 kv heads -> exactly 1/chip; ff + vocab divide.
    assert rules["q_heads"] == "tp"
    assert rules["kv_heads"] == "tp"
    assert rules["ff"] == "tp"
    assert rules["vocab"] == "tp"


def test_qwen2_7b_rules_tp8_gqa_fallback():
    """kv_heads=4 cannot split across tp=8: KV replicates while q heads
    (28, not divisible by 8) also fall back — ff/vocab still shard."""
    cfg = get_model_config("qwen2-7b")
    rules = dict(_rules_for_shape(cfg, {"dp": 1, "tp": 8, "sp": 1}))
    assert rules["kv_heads"] is None
    assert rules["ff"] == "tp"
    assert rules["vocab"] == "tp"


def test_qwen2_aot_compiles_tp4():
    """AOT-compile the real qwen2 prefill+decode at tp=4 (tiny shapes; the
    bias tensors ride the same rules as their kernels)."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    import dataclasses

    # Architecture-faithful but tiny (layers/vocab shrunk): the point is the
    # qkv_bias param tree + rules compiling under GSPMD, not the full size.
    cfg = dataclasses.replace(
        get_model_config("qwen2-7b"), num_layers=2, vocab_size=1024,
        max_seq_len=256,
    )
    mesh = shd.make_mesh(MeshConfig(dp=1, tp=4, sp=1))
    rules = shd.make_axis_rules(cfg, mesh)
    shardings = shd.param_shardings(cfg, mesh, rules)

    model = Transformer(cfg)
    abstract = jax.eval_shape(
        model.init, jax.random.key(0),
        jnp.zeros((1, 8), jnp.int32), jnp.zeros((1, 8), jnp.int32),
    )
    abstract = nn.meta.unbox(abstract["params"])
    aparams = jax.tree.map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, jnp.bfloat16, sharding=s),
        abstract, shardings,
    )
    # bias params exist for q/k/v only
    l0 = abstract["layer_0"]["attn"]
    assert "bias" in l0["q_proj"] and "bias" in l0["k_proj"] and "bias" in l0["v_proj"]
    assert "bias" not in l0["o_proj"]

    B, S = 4, 64

    def prefill(params, tokens, positions, valid):
        cache = init_cache(cfg, B, S + 4)
        logits, cache = model.apply(
            {"params": params}, tokens, positions, valid, cache,
            left_padded=True, last_only=True,
        )
        return logits

    bs = shd.batch_sharding(mesh)
    atoks = jax.ShapeDtypeStruct((B, S), jnp.int32, sharding=bs)
    apos = jax.ShapeDtypeStruct((B, S), jnp.int32, sharding=bs)
    avalid = jax.ShapeDtypeStruct((B, S), jnp.bool_, sharding=bs)
    with mesh, nn.logical_axis_rules(rules):
        compiled = jax.jit(prefill).lower(aparams, atoks, apos, avalid).compile()
    assert compiled.memory_analysis() is not None

"""Integrity subsystem tests: numerics guards, verified artifacts, canary.

The acceptance contract (ISSUE 5): injected NaN logits are contained as
``NumericsFault`` (retried, never delivered), a bit-flipped weight shard is
refused at load with a manifest-digest error naming the file, a canary
mismatch trips the breaker degradation ladder — and, fault-free, the guards
and canary change NOTHING: token-for-token identical output with them on or
off.
"""

import json
import math
import os

import jax.numpy as jnp
import numpy as np
import pytest

from fairness_llm_tpu.config import (
    IntegrityConfig,
    ModelSettings,
    ResilienceConfig,
    ServingConfig,
    SpeculationConfig,
)
from fairness_llm_tpu.integrity import (
    CanaryProbe,
    IntegrityError,
    build_manifest,
    check_finite,
    masked_finite,
    verify_manifest,
    verify_manifest_entry,
    write_manifest,
)
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.resilience import BreakerBoard
from fairness_llm_tpu.runtime.engine import DecodeEngine
from fairness_llm_tpu.serving import ContinuousScheduler, Request, ServingBackend
from fairness_llm_tpu.telemetry import use_registry
from fairness_llm_tpu.utils.failures import (
    NumericsFault,
    ScriptedFaultInjector,
    with_failure_containment,
)

GREEDY = ModelSettings(temperature=0.0, max_tokens=8)
SCFG = ServingConfig(
    enabled=True, num_slots=2, queue_capacity=64,
    max_prompt_len=192, max_new_tokens=32, decode_chunk=4,
)


@pytest.fixture(scope="module")
def engine():
    return DecodeEngine(get_model_config("tiny-test"), seed=0,
                        numerics_guards=True)


@pytest.fixture(scope="module")
def plain_engine():
    return DecodeEngine(get_model_config("tiny-test"), seed=0)


def _poisoned_engine():
    """An engine whose every forward emits NaN logits (poisoned final
    norm), guards armed — the deterministic stand-in for device-side
    numeric corruption."""
    eng = DecodeEngine(get_model_config("tiny-test"), seed=0,
                       numerics_guards=True)
    eng.params["final_norm"]["scale"] = jnp.full_like(
        eng.params["final_norm"]["scale"], jnp.nan
    )
    return eng


# -- numerics guard -----------------------------------------------------------


def test_masked_finite_counts_live_rows_only():
    x = jnp.array([[1.0, 2.0], [jnp.nan, 3.0]])
    assert bool(masked_finite(x))is False
    assert bool(masked_finite(x, live=jnp.array([True, False])))
    assert not bool(masked_finite(x, live=jnp.array([False, True])))


def test_check_finite_counts_and_raises():
    with use_registry() as reg:
        check_finite(True, "engine", "decode")  # healthy: silent
        with pytest.raises(NumericsFault, match="engine decode"):
            check_finite(False, "engine", "decode")
        c = reg.peek("numerics_faults_total", component="engine",
                     stage="decode")
        assert c is not None and c.value == 1


def test_engine_guard_greedy_parity(engine, plain_engine):
    """The guard only ADDS a reduction: tokens identical with it on or off,
    on both the plain and the speculative path."""
    prompts = ["hello there", "the quick brown fox jumps"]
    a = plain_engine.generate(prompts, GREEDY)
    b = engine.generate(prompts, GREEDY)
    np.testing.assert_array_equal(a.tokens, b.tokens)
    spec = SpeculationConfig(enabled=True)
    a2 = plain_engine.generate(prompts, GREEDY, speculation=spec)
    b2 = engine.generate(prompts, GREEDY, speculation=spec)
    np.testing.assert_array_equal(a2.tokens, b2.tokens)


def test_engine_guard_compile_keys_disjoint(engine, plain_engine):
    """Guarded programs must never reuse an unguarded compiled step (their
    return arity differs); the flag lives in the compile key."""
    engine.generate(["hi"], GREEDY)
    plain_engine.generate(["hi"], GREEDY)
    assert any(k[0] == "decode" and k[-1] is True
               for k in engine._compiled if isinstance(k, tuple))
    assert any(k[0] == "decode" and k[-1] is False
               for k in plain_engine._compiled if isinstance(k, tuple))


def test_engine_nan_logits_raise_numerics_fault():
    eng = _poisoned_engine()
    with use_registry() as reg:
        with pytest.raises(NumericsFault):
            eng.generate(["hello"], GREEDY)
        c = reg.peek("numerics_faults_total", component="engine",
                     stage="decode")
        assert c is not None and c.value == 1


def test_engine_nan_contained_to_sentinels():
    """NumericsFault flows through the standard chunk containment: retry
    once, then None sentinels — a poisoned sweep degrades to visible gaps,
    never to corrupt records."""
    eng = _poisoned_engine()

    def gen(prompts, settings=None, seed=0, keys=None, prefix_ids=None):
        return eng.generate(prompts, GREEDY, seed=seed).texts

    with use_registry() as reg:
        out = with_failure_containment(gen)(["a", "b"])
        assert out == [None, None]
        c = reg.peek("contained_chunk_failures_total", component="pipeline",
                     error_type="NumericsFault")
        assert c is not None and c.value == 2  # initial + one retry


def test_spec_numerics_fault_feeds_speculate_breaker():
    """A numerically-sick speculative path must accumulate breaker failures
    (and eventually shed) — success may only be recorded once the chunk's
    finite flag passed, or a persistent NaN source would reset the count
    every call and the breaker would never open."""
    eng = _poisoned_engine()
    eng.breakers = BreakerBoard(failure_threshold=2, cooldown_s=60.0,
                                component="engine")
    spec = SpeculationConfig(enabled=True)
    with use_registry():
        for _ in range(2):
            with pytest.raises(NumericsFault):
                eng.generate(["one two three one two"], GREEDY,
                             speculation=spec)
        assert eng.breakers.state("speculate") == "open"


def test_scheduler_nan_injection_contained_with_parity(engine):
    """An injected NaN faults the whole chunk as NumericsFault; every rider
    requeues once (fresh prefill re-derives the activations) and decodes
    clean tokens — greedy parity with the uninterrupted engine."""
    prompts = {"r0": "hello there", "r1": "the quick brown fox",
               "r2": "abc abc abc"}
    baseline = {rid: engine.generate([p], GREEDY).tokens[0]
                for rid, p in prompts.items()}
    with use_registry() as reg:
        inj = ScriptedFaultInjector(corruptions={("r1", "decode"): 1})
        sched = ContinuousScheduler(
            engine, SCFG, settings=GREEDY, fault_injector=inj,
            resilience=ResilienceConfig(enabled=True),
        )
        results = {r.id: r for r in sched.serve(
            [Request(prompt=p, id=rid, settings=GREEDY)
             for rid, p in prompts.items()]
        )}
        assert inj.corruptions_fired == [("r1", "decode")]
        for rid, ref in baseline.items():
            res = results[rid]
            n = len(res.tokens)
            assert res.ok, (rid, res.finish_reason, res.error)
            assert np.array_equal(np.asarray(res.tokens), ref[:n])
            assert np.all(ref[n:] == engine.tokenizer.pad_id)
        assert sched.last_stats.requeued >= 1
        c = reg.peek("numerics_faults_total", component="serving",
                     stage="decode")
        assert c is not None and c.value == 1
        rq = reg.peek("serving_requeues_by_cause_total", component="serving",
                      cause="numerics")
        assert rq is not None and rq.value >= 1


def test_scheduler_poisoned_prefill_fails_loudly():
    """Permanently-poisoned params: the PREFILL guard refuses every attempt
    and the requests terminate failed (requeue-once, then a Result naming
    the fault) — contained, never silently garbage."""
    eng = _poisoned_engine()
    with use_registry() as reg:
        sched = ContinuousScheduler(eng, SCFG, settings=GREEDY)
        results = sched.serve([
            Request(prompt="hello there", id="p0", settings=GREEDY)
        ])
        assert not results[0].ok
        assert results[0].finish_reason == "failed"
        assert "non-finite" in results[0].error
        c = reg.peek("numerics_faults_total", component="serving",
                     stage="prefill")
        assert c is not None and c.value == 2  # first attempt + requeue


def test_injector_corruption_budget():
    inj = ScriptedFaultInjector(corruptions={"r": 2}, corruption_mode="inf")
    with use_registry():
        assert inj.maybe_corrupt("r", "decode") == "inf"
        assert inj.maybe_corrupt("r", "decode") == "inf"
        assert inj.maybe_corrupt("r", "decode") is None
        assert inj.corruptions_fired == [("r", "decode")] * 2
    with pytest.raises(ValueError):
        ScriptedFaultInjector(corruption_mode="garbage")


# -- manifests ----------------------------------------------------------------


def test_manifest_roundtrip_and_bitflip(tmp_path):
    d = tmp_path / "artifact"
    d.mkdir()
    (d / "a.bin").write_bytes(b"\x00" * 1024)
    (d / "sub").mkdir()
    (d / "sub" / "b.txt").write_text("hello")
    write_manifest(str(d))
    verify_manifest(str(d), kind="test")  # clean round-trip
    with use_registry() as reg:
        ScriptedFaultInjector.flip_bit(str(d / "a.bin"), 500 * 8 + 3)
        with pytest.raises(IntegrityError, match="a.bin"):
            verify_manifest(str(d), kind="test")
        assert reg.peek("manifest_failures_total", kind="test").value == 1
        ScriptedFaultInjector.flip_bit(str(d / "a.bin"), 500 * 8 + 3)  # undo
    verify_manifest(str(d), kind="test")  # healthy again
    # a listed-but-missing file is also a failure naming the file
    os.unlink(d / "sub" / "b.txt")
    with pytest.raises(IntegrityError, match="b.txt"):
        verify_manifest(str(d), kind="test")


def test_manifest_entry_fallback_semantics(tmp_path):
    """verify_manifest_entry is the FALL BACK discipline: True for
    unlisted/unmanifested files (pre-manifest artifacts keep loading),
    False — not raise — on a real mismatch."""
    d = str(tmp_path)
    (tmp_path / "x.json").write_text("{}")
    assert verify_manifest_entry(d, "x.json")  # no manifest at all
    from fairness_llm_tpu.integrity.manifest import update_manifest_entry

    update_manifest_entry(d, "x.json")
    assert verify_manifest_entry(d, "x.json")
    (tmp_path / "y.json").write_text("{}")
    assert verify_manifest_entry(d, "y.json")  # unlisted file
    (tmp_path / "x.json").write_text('{"tampered": 1}')
    with use_registry():
        assert not verify_manifest_entry(d, "x.json")


def test_weights_manifest_refuses_bitflip(tmp_path):
    """The acceptance criterion verbatim: a bit-flipped weight shard is
    refused at load with a manifest-digest error naming the file."""
    from fairness_llm_tpu.runtime.weights import (
        load_checkpoint,
        save_checkpoint_hf,
    )

    cfg = get_model_config("tiny-test")
    eng = DecodeEngine(cfg, seed=0)
    d = str(tmp_path / "ckpt")
    save_checkpoint_hf(cfg, eng.params, d)
    manifest = build_manifest(d)
    entry = manifest["files"]["model.safetensors"]
    assert entry.get("num_tensors", 0) > 0  # shape/dtype summary present
    load_checkpoint(cfg, d)  # clean load passes verification
    shard = os.path.join(d, "model.safetensors")
    with use_registry():
        # flip deep in the tensor-data region: safetensors itself would
        # accept these bytes — only the digest can catch it
        ScriptedFaultInjector.flip_bit(shard, (os.path.getsize(shard) - 64) * 8)
        with pytest.raises(IntegrityError, match="model.safetensors"):
            load_checkpoint(cfg, d)
    # explicit opt-out still loads (the bytes parse; values are just wrong)
    load_checkpoint(cfg, d, verify=False)


def test_train_checkpoint_falls_back_past_corrupt_step(tmp_path):
    """Digest mismatch on the newest train-state step resumes from the
    next-older valid one — same ladder as the results resume."""
    import jax

    from fairness_llm_tpu.train import make_train_step
    from fairness_llm_tpu.train.checkpoint import (
        restore_train_state,
        save_train_state,
    )

    cfg = get_model_config("tiny-test")
    init_state, step = make_train_step(cfg)
    state = init_state(jax.random.key(0))
    tokens = np.random.default_rng(0).integers(3, 512, (4, 8)).astype(np.int32)
    valid = np.ones((4, 8), bool)
    state, _ = step(state, tokens, valid)  # step 1
    save_train_state(str(tmp_path), state)
    state2, _ = step(state, tokens, valid)  # step 2
    save_train_state(str(tmp_path), state2)
    # corrupt a payload file of the NEWEST step (2)
    step_dir = tmp_path / "2"
    victims = [p for p in step_dir.rglob("*") if p.is_file() and p.stat().st_size > 0]
    assert victims
    ScriptedFaultInjector.flip_bit(str(victims[0]), 8)
    with use_registry():
        template = init_state(jax.random.key(1))
        restored = restore_train_state(str(tmp_path), template)
    assert restored is not None
    assert int(restored.step) == 1  # fell back past the corrupt step 2


# -- results: strict JSON + sanitization --------------------------------------


def test_save_results_sanitizes_nan_to_null(tmp_path):
    """Fairness metrics can be NaN (empty group); the written JSON must be
    STRICT (no bare NaN tokens) with the sanitized key paths recorded in
    metadata — and the caller's in-memory dict untouched."""
    from fairness_llm_tpu.pipeline import results as R

    payload = {
        "metadata": {"phase": 1},
        "metrics": {
            "dp": {"score": float("nan"), "groups": [1.0, float("inf"), 2.0]},
            "ok": 0.5,
        },
    }
    path = str(tmp_path / "phase1_results.json")
    R.save_results(payload, path)
    # caller's dict untouched
    assert math.isnan(payload["metrics"]["dp"]["score"])
    assert "sanitized_non_finite" not in payload["metadata"]
    raw = open(path).read()

    def reject_constants(name):  # strict parser: bare NaN/Infinity refused
        raise ValueError(f"non-JSON constant {name}")

    data = json.loads(raw, parse_constant=reject_constants)
    assert data["metrics"]["dp"]["score"] is None
    assert data["metrics"]["dp"]["groups"][1] is None
    assert data["metrics"]["ok"] == 0.5
    assert sorted(data["metadata"]["sanitized_non_finite"]) == [
        "metrics.dp.groups[1]", "metrics.dp.score",
    ]


def test_save_results_updates_manifest(tmp_path):
    from fairness_llm_tpu.pipeline import results as R

    path = str(tmp_path / "phase1" / "phase1_results.json")
    R.save_results({"metrics": {"x": 1.0}}, path)
    manifest = json.load(open(tmp_path / "phase1" / "manifest.json"))
    assert "phase1_results.json" in manifest["files"]
    assert verify_manifest_entry(str(tmp_path / "phase1"),
                                 "phase1_results.json")


# -- parsing satellite --------------------------------------------------------


def test_parse_comma_list_strips_markdown_emphasis():
    """The comma parser must clean items exactly like the numbered parser
    (shared _clean_item): markdown bold/emphasis and quotes stripped."""
    from fairness_llm_tpu.pipeline.parsing import (
        parse_comma_list,
        parse_numbered_list,
    )

    text = '**The Matrix**, "Alien", *Heat*, Up'
    assert parse_comma_list(text) == ["The Matrix", "Alien", "Heat", "Up"]
    numbered = "1. **The Matrix**\n2. \"Alien\"\n3. *Heat*\n4. Up"
    assert parse_numbered_list(numbered) == parse_comma_list(text)


# -- canary -------------------------------------------------------------------


def test_canary_match_then_mismatch_trips_ladder(engine):
    board = BreakerBoard(failure_threshold=3, cooldown_s=60.0)
    sched = ContinuousScheduler(engine, SCFG, settings=GREEDY, breakers=board)
    with use_registry() as reg:
        probe = CanaryProbe.record(engine, max_tokens=8, every_n=2,
                                   board=board)
        assert not probe.tick() and probe.tick()  # every_n cadence
        assert probe.probe(sched)
        assert board.ladder.level == 0
        # tampered reference == silently-wrong serving output, as seen from
        # the comparator's side
        probe.reference = probe.reference.copy()
        probe.reference[0] += 1
        assert not probe.probe(sched)
        assert board.state("decode") == "open"
        assert board.ladder.level >= 1
        assert reg.peek("canary_runs_total", component="serving").value == 2
        assert reg.peek("canary_mismatch_total",
                        component="serving").value == 1


def test_backend_canary_parity(engine):
    """Canary on vs off: byte-identical backend output (the probe rides
    between batches, never inside them)."""
    prompts = ["hello there", "the quick brown fox"]
    base = ServingBackend(engine, SCFG)
    expected = base.generate(prompts, GREEDY, keys=["a", "b"])
    with use_registry():
        be = ServingBackend(
            engine, SCFG,
            resilience=ResilienceConfig(enabled=True),
            integrity=IntegrityConfig(numerics_guards=True, canary_every_n=1,
                                      canary_max_tokens=8),
        )
        got = be.generate(prompts, GREEDY, keys=["a", "b"])
        assert be._canary is not None  # armed and probed
    assert got == expected

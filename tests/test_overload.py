"""Overload-control tests (serving/overload.py + the QoS admission queue).

ISSUE-8 contracts under unit test:

- per-class queue isolation: a batch flood never blocks an interactive
  admission (strict priority), and aging bounds batch starvation;
- deadline-feasibility math: the TTFT lower bound is exact arithmetic over
  the live p50s, cold start never rejects, and a provably-doomed request
  sheds with a retry-after instead of burning a prefill;
- shed semantics: every shed is an explicit terminal Result
  (``finish_reason="shed"`` + ``retry_after_s``), counted in
  ``shed_total{class,reason}``, excluded from SLO burn;
- the brownout ladder's rung effects (class admission, batch token cap);
- greedy token parity for every ADMITTED request across classes;
- the fleet intake gate and the router's qos-aware placement.

The controller's transition monotonicity/hysteresis has its own
property-test module (tests/test_overload_property.py).
"""

import time

import numpy as np
import pytest

from fairness_llm_tpu.config import ModelSettings, OverloadConfig, ServingConfig
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.runtime.engine import DecodeEngine
from fairness_llm_tpu.serving import (
    ClassedAdmissionQueue,
    ContinuousScheduler,
    DeadlineEstimator,
    HealthRouter,
    Request,
    ShedController,
)
from fairness_llm_tpu.telemetry import use_registry
from fairness_llm_tpu.telemetry.registry import get_registry
from fairness_llm_tpu.telemetry.slo import SLOTargets, set_slo_targets

GREEDY_TTFT_SAFE = SLOTargets(ttft_p95_s=300.0, e2e_p99_s=600.0)


def greedy(m: int) -> ModelSettings:
    return ModelSettings(temperature=0.0, max_tokens=m)


SCFG = ServingConfig(
    enabled=True, num_slots=2, queue_capacity=64,
    max_prompt_len=192, max_new_tokens=32, decode_chunk=4,
)


@pytest.fixture(scope="module")
def engine():
    return DecodeEngine(get_model_config("tiny-test"), seed=0)


@pytest.fixture()
def safe_slo():
    """Harness-appropriate SLO targets: compile-time TTFT outliers must not
    drive escalation in tests that exercise other signals."""
    prev = set_slo_targets(GREEDY_TTFT_SAFE)
    yield
    set_slo_targets(prev)


def _req(prompt, m=8, **kw):
    return Request(prompt=prompt, settings=greedy(m), **kw)


# -- Request.qos --------------------------------------------------------------


def test_unknown_qos_rejected_loudly():
    with pytest.raises(ValueError, match="qos"):
        Request(prompt="x", qos="bulk")


# -- ClassedAdmissionQueue ----------------------------------------------------


def test_strict_priority_dequeue():
    q = ClassedAdmissionQueue(capacity=16, overload=OverloadConfig(
        enabled=True, aging_s=0.0))
    b = Request(prompt="b", qos="batch")
    p = Request(prompt="p", qos="probe")
    i = Request(prompt="i", qos="interactive")
    for r in (b, p, i):  # arrival order: batch, probe, interactive
        assert q.submit(r)
    assert [r.qos for r in q.pop(3)] == ["interactive", "batch", "probe"]


def test_batch_flood_never_blocks_interactive_admission():
    """Class isolation: with the batch sub-queue at its bound, interactive
    submits still succeed, and the next pop serves interactive first — a
    flood delays an interactive admission by at most the chunk in flight,
    never by the flood's length."""
    ov = OverloadConfig(enabled=True, batch_capacity=4, aging_s=0.0)
    q = ClassedAdmissionQueue(capacity=64, overload=ov)
    for k in range(8):
        ok = q.submit(Request(prompt=f"b{k}", qos="batch"))
        assert ok == (k < 4)  # the class bound backpressures the flood
    late = Request(prompt="i", qos="interactive")
    assert q.submit(late)  # interactive unaffected by the full batch class
    assert q.pop(1)[0] is late


def test_aging_promotes_starved_batch():
    clock = {"t": 100.0}
    ov = OverloadConfig(enabled=True, aging_s=5.0)
    q = ClassedAdmissionQueue(capacity=16, overload=ov,
                              clock=lambda: clock["t"])
    old_batch = Request(prompt="b", qos="batch", submitted_at=90.0)
    fresh_int = Request(prompt="i", qos="interactive", submitted_at=99.9)
    assert q.submit(old_batch) and q.submit(fresh_int)
    # The batch head has waited 10s >= aging_s: promoted, oldest-first.
    assert q.pop(1)[0] is old_batch
    assert q.pop(1)[0] is fresh_int


def test_requeue_stays_in_own_class():
    q = ClassedAdmissionQueue(capacity=16, overload=OverloadConfig(
        enabled=True, aging_s=0.0))
    now = time.monotonic()
    assert q.submit(Request(prompt="i", qos="interactive", submitted_at=now))
    faulted = Request(prompt="b", qos="batch", submitted_at=now)
    q.requeue(faulted)  # front of BATCH, not of the whole line
    assert q.pop(1)[0].qos == "interactive"
    assert q.pop(1)[0] is faulted


def test_shared_rejection_does_not_burn_class_quota():
    """Quota peek-then-consume: a submission the SHARED limiter rejects
    must not have consumed a per-class token (and vice versa) — burning
    quota on never-admitted work under-admits the class for the rest of
    its window."""
    ov = OverloadConfig(enabled=True, interactive_per_minute=10)
    from fairness_llm_tpu.utils.ratelimit import RateLimiter

    q = ClassedAdmissionQueue(capacity=16, overload=ov,
                              rate_limiter=RateLimiter(calls_per_minute=1))
    assert q.submit(Request(prompt="i0", qos="interactive"))
    assert not q.submit(Request(prompt="i1", qos="interactive"))  # shared
    # Only the ADMITTED submission spent a class token.
    assert len(q._class_limiters["interactive"]._times) == 1
    assert len(q.rate_limiter._times) == 1


def test_journal_preserves_qos(tmp_path):
    """A drained batch request must resume as BATCH: the journal carries
    the class, so a successor process's brownout/priority machinery sees
    the same traffic shape (and old journals without the field default to
    interactive)."""
    from fairness_llm_tpu.resilience.drain import ServingJournal

    j = ServingJournal(str(tmp_path))
    j.record_submitted(Request(prompt="x", id="b", qos="batch"))
    rebuilt = j.to_requests()
    assert [r.qos for r in rebuilt] == ["batch"]
    legacy = j.to_requests([{"prompt": "y", "id": "old"}])  # pre-QoS spec
    assert legacy[0].qos == "interactive"


def test_per_class_rate_limit_and_expiry_sweep():
    ov = OverloadConfig(enabled=True, batch_per_minute=1)
    q = ClassedAdmissionQueue(capacity=16, overload=ov)
    assert q.submit(Request(prompt="b0", qos="batch"))
    assert not q.submit(Request(prompt="b1", qos="batch"))  # quota spent
    assert q.rejected == 1
    assert q.submit(Request(prompt="i", qos="interactive"))  # own quota
    expired = Request(prompt="x", qos="interactive", deadline_s=0.0)
    q.requeue(expired)
    out = q.drain_expired()
    assert out == [expired] and len(q) == 2


# -- DeadlineEstimator --------------------------------------------------------


def _feed_histograms(prefill_s, per_tok_s, n=10):
    reg = get_registry()
    for _ in range(n):
        reg.histogram("prefill_wall_s", component="serving").observe(prefill_s)
        reg.histogram("per_output_token_s",
                      component="serving").observe(per_tok_s)


def test_estimator_cold_start_never_rejects():
    with use_registry():
        est = DeadlineEstimator(safety=1.0)
        assert est.estimate_ttft_s(100, 2, 4) is None
        req = Request(prompt="x", deadline_s=0.001, submitted_at=0.0)
        assert est.infeasible(req, 100, 2, 4, now=0.0005) is None


def test_estimator_ttft_lower_bound_math():
    with use_registry():
        _feed_histograms(prefill_s=0.1, per_tok_s=0.01)
        est = DeadlineEstimator(safety=0.5)
        # 10 ahead on 2 slots = 5 waves x (4 steps x 10ms) + prefill + 1 tok
        bound = est.estimate_ttft_s(10, 2, 4)
        assert bound == pytest.approx(5 * 0.04 + 0.1 + 0.01)
        # 1 ahead on 2 slots floors to 0 waves: prefill + one step only.
        assert est.estimate_ttft_s(1, 2, 4) == pytest.approx(0.11)


def test_estimator_infeasible_vs_feasible():
    with use_registry():
        _feed_histograms(prefill_s=0.1, per_tok_s=0.01)
        est = DeadlineEstimator(safety=0.5)
        # Bound = 0.31s; safety-discounted threshold = 0.155s.
        doomed = Request(prompt="x", deadline_s=0.1, submitted_at=0.0)
        assert est.infeasible(doomed, 10, 2, 4, now=0.0) == \
            pytest.approx(0.31)
        fine = Request(prompt="x", deadline_s=1.0, submitted_at=0.0)
        assert est.infeasible(fine, 10, 2, 4, now=0.0) is None
        # Already past its deadline: infeasible by definition.
        late = Request(prompt="x", deadline_s=0.1, submitted_at=0.0)
        assert est.infeasible(late, 0, 2, 4, now=0.2) is not None
        # safety=0 disables the check entirely.
        off = DeadlineEstimator(safety=0.0)
        assert off.infeasible(doomed, 10, 2, 4, now=0.0) is None


# -- ShedController rung semantics -------------------------------------------


def test_ladder_rung_admission_and_caps():
    with use_registry():
        ctl = ShedController(OverloadConfig(enabled=True, batch_token_cap=4))
        assert all(ctl.admits(q) for q in ("interactive", "batch", "probe"))
        assert ctl.batch_cap(32, "batch") == 32
        ctl._transition(1, "test", 0.0)
        assert ctl.admits("interactive") and ctl.admits("probe")
        assert not ctl.admits("batch")
        assert ctl.batch_cap(32, "batch") == 32  # rung 1: no cap yet
        ctl._transition(2, "test", 0.0)
        assert ctl.batch_cap(32, "batch") == 4
        assert ctl.batch_cap(32, "interactive") == 32  # never touched
        ctl._transition(3, "test", 0.0)
        assert ctl.admits("interactive")
        assert not ctl.admits("batch") and not ctl.admits("probe")
        # Retry-after scales with the rung depth.
        assert ctl.retry_after() == pytest.approx(3.0)
        assert ctl.retry_after(est_ttft=10.0) == pytest.approx(10.0)


def test_controller_signals_depth_and_burn():
    clock = {"t": 0.0}
    with use_registry():
        ctl = ShedController(
            OverloadConfig(enabled=True, queue_frac_threshold=0.5,
                           queue_window_s=1.0, burn_threshold=2.0,
                           eval_interval_s=0.0),
            clock=lambda: clock["t"],
        )
        assert ctl.overloaded() is None
        ctl.observe_queue_depth(depth=60, capacity=100)
        assert "queue_depth" in ctl.overloaded()
        clock["t"] += 2.0  # the depth sample ages out of the window
        assert ctl.overloaded() is None
        get_registry().gauge("slo_burn_rate", component="serving",
                             slo="error_rate", window="fast").set(3.0)
        # Burn alone is gated on interactive presence: a single-tenant
        # batch run burning its OWN ttft budget must not brown itself out.
        assert ctl.overloaded() is None
        ctl.note_interactive()
        assert "slo_burn" in ctl.overloaded()
        clock["t"] += 100.0  # presence expires (interactive_presence_s=60)
        assert ctl.overloaded() is None


# -- scheduler integration ----------------------------------------------------


def test_submit_shed_is_terminal_with_retry_after(engine, safe_slo):
    with use_registry():
        sched = ContinuousScheduler(engine, SCFG, settings=greedy(8),
                                    overload=OverloadConfig(enabled=True))
        sched.shed_controller._transition(1, "test", 0.0)
        req = _req("the quick brown fox", qos="batch", id="shed_me")
        assert not sched.submit(req)
        res = sched.take_result("shed_me")
        assert res is not None and res.finish_reason == "shed"
        assert res.retry_after_s and res.retry_after_s > 0
        assert not res.ok
        reg = get_registry()
        assert reg.read_value("shed_total", component="serving",
                              **{"class": "batch",
                                 "reason": "overload"}) == 1
        # Shed is excluded from the SLO burn windows (flow control, not
        # service failure) but counted as a finished outcome.
        assert reg.read_value("requests_finished_total",
                              component="serving", outcome="shed") == 1
        assert sched.tracer.slo._run[0] == 0  # no SLO observation


def test_served_parity_across_classes_and_shed_cycles(engine, safe_slo):
    """Greedy token-for-token parity for every ADMITTED request, whatever
    class it rode and despite a shed/restore cycle mid-workload."""
    prompts = ["the quick brown fox", "hello there friend",
               "one two three four", "a b c d e"]
    with use_registry():
        refs = {p: engine.generate([p], greedy(8)).tokens[0]
                for p in prompts}
        sched = ContinuousScheduler(engine, SCFG, settings=greedy(8),
                                    overload=OverloadConfig(enabled=True))
        reqs = [_req(p, id=f"par{i}",
                     qos="interactive" if i % 2 == 0 else "batch")
                for i, p in enumerate(prompts)]
        results = sched.serve(reqs)
        assert all(r.ok for r in results)
        for r, p in zip(results, prompts):
            n = len(r.tokens)
            assert n > 0
            assert np.array_equal(np.asarray(r.tokens), refs[p][:n])
        # Shed cycle: escalate, shed one, restore, serve again — identical.
        sched.shed_controller._transition(3, "test", 0.0)
        assert not sched.submit(_req(prompts[0], qos="batch", id="mid"))
        assert sched.take_result("mid").finish_reason == "shed"
        sched.shed_controller._transition(0, "test", 0.0)
        again = sched.serve([_req(p, id=f"re{i}", qos="batch")
                             for i, p in enumerate(prompts)])
        for r, p in zip(again, prompts):
            assert r.ok
            assert np.array_equal(np.asarray(r.tokens),
                                  refs[p][:len(r.tokens)])


def test_doomed_deadline_sheds_without_prefill(engine, safe_slo):
    with use_registry():
        _feed_histograms(prefill_s=0.05, per_tok_s=0.01)
        sched = ContinuousScheduler(engine, SCFG, settings=greedy(8),
                                    overload=OverloadConfig(enabled=True))
        # Stack the queue so the wave estimate is meaningful.
        for i in range(6):
            assert sched.submit(_req("hello there friend", id=f"ahead{i}"))
        doomed = _req("the quick brown fox", id="doomed", deadline_s=0.001)
        assert not sched.submit(doomed)
        res = sched.take_result("doomed")
        assert res.finish_reason == "shed"
        assert "unmeetable" in res.error
        assert res.retry_after_s > 0
        reg = get_registry()
        assert reg.read_value("shed_total", component="serving",
                              **{"class": "interactive",
                                 "reason": "deadline_infeasible"}) == 1
        # No prefill was spent on it: the queue still only holds the six.
        assert len(sched.queue) == 6
        stats = sched.drain()
        assert stats.completed == 6
        assert stats.shed == 1  # folded in at finish_stats


def test_brownout_flood_serves_interactive_sheds_batch(engine, safe_slo):
    """A miniature of the chaos drill's section 7: 3x-capacity mixed flood
    -> batch sheds with retry-after, interactive all served, level returns
    to 0, zero accepted-then-lost."""
    scfg = ServingConfig(enabled=True, num_slots=2, queue_capacity=8,
                         max_prompt_len=192, max_new_tokens=32,
                         decode_chunk=4)
    ov = OverloadConfig(enabled=True, queue_frac_threshold=0.75,
                        queue_window_s=0.3, healthy_window_s=0.01,
                        eval_interval_s=0.0, batch_token_cap=4)
    with use_registry():
        sched = ContinuousScheduler(engine, scfg, settings=greedy(8),
                                    overload=ov)
        flood = [_req("hello there friend", id=f"b{i:02d}", qos="batch")
                 for i in range(20)]
        flood += [_req("the quick brown fox", id=f"i{i}", qos="interactive")
                  for i in range(4)]
        results = {r.id: r for r in sched.serve(flood)}
        assert len(results) == len(flood)  # zero lost
        assert all(results[f"i{i}"].ok for i in range(4))
        shed = [r for r in results.values() if r.finish_reason == "shed"]
        assert shed and all(r.retry_after_s for r in shed)
        served = [r for r in results.values() if r.finish_reason != "shed"]
        assert all(r.ok for r in served)
        # De-escalation: evaluate until the depth window ages out.
        ctl = sched.shed_controller
        deadline = time.monotonic() + 5.0
        while ctl.level > 0 and time.monotonic() < deadline:
            ctl.evaluate()
            time.sleep(0.01)
        assert ctl.level == 0


def test_batch_token_cap_applies_at_rung_two(engine, safe_slo):
    with use_registry():
        sched = ContinuousScheduler(
            engine, SCFG, settings=greedy(8),
            overload=OverloadConfig(enabled=True, batch_token_cap=2),
        )
        ref = engine.generate(["hello there friend"], greedy(8)).tokens[0]
        sched.shed_controller._transition(2, "test", 0.0)
        # Batch sheds at rung 2 — but an already-queued batch request (or
        # one submitted below rung 1... here we exercise the cap directly).
        req = _req("hello there friend", id="capped", qos="batch")
        assert sched._cap_for(req) == 2
        assert sched._cap_for(_req("x", id="i", qos="interactive")) == 8
        sched.shed_controller._transition(0, "test", 0.0)
        assert sched._cap_for(req) == 8
        del ref


def test_canary_probe_shed_is_inconclusive(engine, safe_slo):
    """A shed canary probe must NOT count as a mismatch or trip the
    breaker — flow control is not a fault (rung 3 sheds probes)."""
    from fairness_llm_tpu.integrity.canary import CanaryProbe
    from fairness_llm_tpu.resilience import BreakerBoard

    with use_registry():
        board = BreakerBoard(failure_threshold=3, cooldown_s=60.0)
        sched = ContinuousScheduler(engine, SCFG, settings=greedy(8),
                                    overload=OverloadConfig(enabled=True),
                                    breakers=board)
        probe = CanaryProbe.record(engine, max_tokens=8, every_n=1,
                                   board=board)
        assert probe.probe(sched)  # healthy: matches
        sched.shed_controller._transition(3, "test", 0.0)
        assert probe.probe(sched)  # shed: inconclusive, not a mismatch
        reg = get_registry()
        assert reg.read_value("canary_mismatch_total",
                              component="serving") == 0
        assert board.state("decode") == "closed"


# -- fleet + router -----------------------------------------------------------


class _StubQueue:
    def __init__(self):
        self.full, self.closed = False, False

    def __len__(self):
        return 0


class _StubPool:
    occupancy = 0


class _StubSched:
    def __init__(self):
        self.pool = _StubPool()
        self.queue = _StubQueue()
        self._pending = []
        self.breakers = None
        self.watchdog = None
        self.num_slots = 4


class _StubReplica:
    def __init__(self, name):
        self.name = name
        self.fenced = False
        self.sched = _StubSched()


def test_router_steers_batch_away_from_burning_replica():
    with use_registry():
        router = HealthRouter()
        calm, hot = _StubReplica("r0"), _StubReplica("r1")
        get_registry().gauge("slo_burn_rate", component="serving",
                             replica="r1", slo="error_rate",
                             window="fast").set(5.0)
        # Interactive: plain weighting (burn already discounts health via
        # health_score, but both stay routable).
        assert router.pick([calm, hot], qos="interactive") is calm
        # Batch prefers the calm replica outright...
        assert router.pick([calm, hot], qos="batch") is calm
        # ...and falls back to plain weighting when EVERYONE is burning.
        get_registry().gauge("slo_burn_rate", component="serving",
                             replica="r0", slo="error_rate",
                             window="fast").set(5.0)
        assert router.pick([calm, hot], qos="batch") is not None


def test_fleet_intake_gate_sheds_and_recovers(engine, safe_slo):
    from fairness_llm_tpu.config import FleetConfig
    from fairness_llm_tpu.serving import ReplicaSet

    with use_registry():
        fleet = ReplicaSet(engine, SCFG, settings=greedy(8),
                           fleet=FleetConfig(replicas=2),
                           overload=OverloadConfig(enabled=True))
        refs = {p: engine.generate([p], greedy(8)).tokens[0]
                for p in ("the quick brown fox", "hello there friend")}
        fleet.shed_controller._transition(3, "test", 0.0)
        out = {r.id: r for r in fleet.serve([
            _req("the quick brown fox", id="fi", qos="interactive"),
            _req("hello there friend", id="fb", qos="batch"),
        ])}
        assert out["fi"].ok
        assert np.array_equal(np.asarray(out["fi"].tokens),
                              refs["the quick brown fox"][:len(out["fi"].tokens)])
        assert out["fb"].finish_reason == "shed" and out["fb"].retry_after_s
        assert fleet.last_stats.shed == 1
        fleet.shed_controller._transition(0, "test", 0.0)
        out2 = fleet.serve([_req("hello there friend", id="fb2",
                                 qos="batch")])[0]
        assert out2.ok
        assert np.array_equal(np.asarray(out2.tokens),
                              refs["hello there friend"][:len(out2.tokens)])

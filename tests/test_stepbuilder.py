"""Step-program builder parity harness (ISSUE 14 + the mesh axis).

The correctness contract for ``runtime/stepbuilder.py`` is that every
composition the builder emits — across the five axes it exposes — decodes
token-for-token what ``DecodeEngine.generate`` decodes for the same prompt
alone:

    {contiguous, paged} x {greedy, spec-verify} x {guards on, off}
                        x {fuse 1, 2, 4} x {tp 1, 2, 8}    (where legal)

The tp axis runs on REAL devices (conftest forces 8 virtual CPU devices):
a tp mesh shards params, the slot KV cache (kv-head axis), and the carried
logits (vocab), and every program lowers as one SPMD computation — parity
through slot recycling, a sharded NaN-guard containment, and a sharded
fused-window requeue is pinned below, plus the ``("tp", k)`` compile-key
element's tp=1 byte-identity and the ``@tp<k>`` telemetry label scheme.

Illegal cells are structural, not skipped-for-time: spec-verify is an
engine-path selection (the serving scheduler is greedy/sampled per-row),
paged KV is a serving-path KV source, and fuse composes only with the
serving dispatch (the engine's whole generation is already one dispatch).

On top of the grid: recycled-slot, requeue-after-fault, and fleet-migration
parity for FUSED serving (the chunk boundary moved — the containment and
migration machinery must not care), the one compile-key scheme's pinned
layout, the fused-vs-unfused roofline byte oracle, fused telemetry
attribution (a fused program publishes under its own label), the
degradation ladder's fuse reset, and the CLI flag gates.
"""

import numpy as np
import pytest

from fairness_llm_tpu.config import (
    FleetConfig,
    IntegrityConfig,
    MeshConfig,
    ModelSettings,
    ResilienceConfig,
    ServingConfig,
    SpeculationConfig,
)
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.runtime.engine import DecodeEngine
from fairness_llm_tpu.runtime.sampling import SamplerSettings
from fairness_llm_tpu.parallel import make_mesh
from fairness_llm_tpu.runtime.stepbuilder import (
    STEP_PROGRAMS,
    base_program,
    compile_key,
    program_label,
)
from fairness_llm_tpu.serving.fleet import ReplicaSet
from fairness_llm_tpu.serving.request import Request
from fairness_llm_tpu.serving.scheduler import ContinuousScheduler
from fairness_llm_tpu.telemetry import use_registry
from fairness_llm_tpu.telemetry.roofline import decode_step_bytes
from fairness_llm_tpu.telemetry.timeline import set_attribution, use_timeline
from fairness_llm_tpu.utils.failures import ScriptedFaultInjector


def greedy(m: int) -> ModelSettings:
    return ModelSettings(temperature=0.0, max_tokens=m)


# A near-duplicate family (shared prefix for the paged radix index) plus
# genuinely mixed-length odd prompts, enough of them that a 2-slot pool
# recycles every slot several times per serve.
PROMPTS = [
    "recommend movies for a user who likes drama and history",
    "recommend movies for a user who likes drama and comedy",
    "recommend movies for a user who likes drama and action",
    "the quick brown fox",
    "one two three one two three one",
    "zz zz zz",
]

M = 8  # tokens per request — enough to cross several chunk boundaries


def _scfg(fuse=1, paged=False, slots=2, chunk=2):
    return ServingConfig(
        enabled=True, num_slots=slots, queue_capacity=64,
        max_prompt_len=192, max_new_tokens=32, decode_chunk=chunk,
        fuse_steps=fuse, paged_kv=paged, kv_block_size=16,
    )


@pytest.fixture(scope="module")
def engine():
    return DecodeEngine(get_model_config("tiny-test"), seed=0)


@pytest.fixture(scope="module")
def baseline(engine):
    """Per-prompt single-request engine reference — what every builder
    composition must reproduce token-for-token."""
    return {p: np.asarray(engine.generate([p], greedy(M)).tokens[0])
            for p in PROMPTS}


def _assert_parity(engine, baseline, requests, results):
    by_id = {r.id: r for r in results} if isinstance(results, dict) else None
    for req, res in zip(requests, results if by_id is None else
                        [by_id[q.id] for q in requests]):
        assert res.ok, (req.id, res.finish_reason, res.error)
        got = np.asarray(res.tokens)
        ref = baseline[req.prompt]
        n = len(got)
        assert n > 0 and np.array_equal(got, ref[:n]) \
            and np.all(ref[n:] == engine.tokenizer.pad_id), \
            (req.id, list(got), list(ref))


# -- the compile-key scheme ----------------------------------------------------


def test_compile_key_scheme_layout():
    """The pinned layout invariants: key[0] is the program (the speculation
    slot), the guard flag closes ``decode`` keys and sits mid-key on
    ``spec_decode`` (trailing pair = the speculation knobs), step keys
    carry (chunk, guard, fuse)."""
    s = SamplerSettings(temperature=0.0)
    k = compile_key("decode", batch=8, prompt_len=64, max_new=32, sampler=s,
                    prefix_len=0, guard=True)
    assert k[0] == "decode" and k[-1] is True
    k = compile_key("spec_decode", batch=8, prompt_len=64, max_new=32,
                    prefix_len=0, guard=False, ngram_max=3, draft_len=8)
    assert k[0] == "spec_decode" and k[-2:] == (3, 8) and k[5] is False
    assert compile_key("serve_step", chunk=8, guard=False) == \
        ("serve_step", 8, False, 1)
    assert compile_key("paged_step", chunk=4, guard=True, fuse=4) == \
        ("paged_step", 4, True, 4)
    assert compile_key("serve_prefill", nb=4, P=64, guard=False) == \
        ("serve_prefill", 4, 64, False)
    assert compile_key("prefix", prefix_len=128) == ("prefix", 128)
    with pytest.raises(ValueError):
        compile_key("warp_drive")


def test_program_label_fused_naming():
    assert program_label("serve_step", 1) == "serve_step"
    assert program_label("serve_step", 4) == "serve_step_fused"
    assert program_label("paged_step", 2) == "paged_step_fused"
    assert set(STEP_PROGRAMS) == {
        "serve_step", "paged_step", "serve_step_fused", "paged_step_fused"}


def test_compile_key_mesh_element():
    """The mesh axis: tp=1 keys are BYTE-IDENTICAL to the pre-mesh scheme
    (no trailing element, nothing re-ordered — caches and committed
    compile-stats keys survive the upgrade unchanged), tp>1 appends one
    tagged ``("tp", k)`` element that can never collide with the
    positional int axes."""
    assert compile_key("serve_step", chunk=8, guard=False, tp=1) == \
        compile_key("serve_step", chunk=8, guard=False) == \
        ("serve_step", 8, False, 1)
    assert compile_key("serve_prefill", nb=4, P=64, guard=False, tp=1) == \
        ("serve_prefill", 4, 64, False)
    assert compile_key("serve_step", chunk=8, guard=False, tp=2) == \
        ("serve_step", 8, False, 1, ("tp", 2))
    assert compile_key("paged_prefill", nb=4, P=64, guard=True, tp=8) == \
        ("paged_prefill", 4, 64, True, ("tp", 8))
    # Disjoint across the whole (chunk, guard, fuse, tp) product.
    keys = {compile_key("serve_step", chunk=c, guard=g, fuse=f, tp=t)
            for c in (4, 8) for g in (False, True)
            for f in (1, 4) for t in (1, 2, 8)}
    assert len(keys) == 24
    # A tp=2 fuse=1 key can't alias a tp=1 fuse=2 key (or any other
    # positional coincidence): the tag makes the element self-describing.
    assert compile_key("serve_step", chunk=2, guard=False, tp=2) != \
        compile_key("serve_step", chunk=2, guard=False, fuse=2)


def test_program_label_mesh_suffix():
    """tp>1 programs publish under ``<base>[_fused]@tp<k>`` so sharded and
    single-device measurements never mix in one telemetry series; tp=1
    labels are byte-identical to the pre-mesh names. ``base_program``
    strips the suffix for structural (``*_fused``) checks."""
    assert program_label("serve_step", 1, tp=1) == "serve_step"
    assert program_label("serve_step", 1, tp=2) == "serve_step@tp2"
    assert program_label("paged_step", 4, tp=8) == "paged_step_fused@tp8"
    assert program_label("serve_prefill", tp=2) == "serve_prefill@tp2"
    assert base_program("paged_step_fused@tp8") == "paged_step_fused"
    assert base_program("serve_step") == "serve_step"
    assert base_program("serve_step@tp2").endswith("_fused") is False
    assert base_program("serve_step_fused@tp2").endswith("_fused")


def test_step_keys_disjoint_across_fuse_and_chunk(engine):
    """A fused program can never reuse (or be reused by) the per-chunk
    program: the fuse factor is a compile-key axis, like the mutable
    decode_chunk the degradation ladder halves."""
    keys = {compile_key("serve_step", chunk=c, guard=g, fuse=f)
            for c in (4, 8) for g in (False, True) for f in (1, 2, 4)}
    assert len(keys) == 12


# -- the parity grid -----------------------------------------------------------


@pytest.mark.parametrize("paged", [False, True], ids=["contiguous", "paged"])
@pytest.mark.parametrize("guard", [False, True], ids=["plain", "guarded"])
@pytest.mark.parametrize("fuse", [1, 2, 4])
def test_serving_grid_parity(engine, baseline, paged, guard, fuse):
    """{contiguous, paged} x {guards on, off} x {fuse 1, 2, 4}, greedy
    selection: 6 mixed requests over 2 slots (every slot recycles), each
    token-identical to the engine alone. The fused cells are the tentpole's
    acceptance surface: per-row caps/EOS stops advance in-program, so
    folding k chunks into one dispatch must not move a single token."""
    engine.numerics_guards = guard
    try:
        sched = ContinuousScheduler(
            engine, _scfg(fuse=fuse, paged=paged), settings=greedy(M),
        )
        reqs = [Request(id=f"g{i}", prompt=p, settings=greedy(M))
                for i, p in enumerate(PROMPTS)]
        results = sched.serve(reqs)
        _assert_parity(engine, baseline, reqs, results)
        # The dispatched program compiled under the unified key.
        base = "paged_step" if paged else "serve_step"
        assert compile_key(base, chunk=2, guard=guard, fuse=fuse) \
            in sched._compiled
    finally:
        engine.numerics_guards = False


@pytest.mark.parametrize("guard", [False, True], ids=["plain", "guarded"])
def test_spec_verify_composition_parity(engine, guard):
    """The spec-verify selection (engine path): the builder's draft-and-
    verify composition emits exactly the plain greedy composition's
    tokens, guards on or off."""
    spec = SpeculationConfig(enabled=True, draft_len=4, ngram_max=3)
    engine.numerics_guards = guard
    try:
        prompts = PROMPTS[:3]
        plain = engine.generate(prompts, greedy(16))
        spec_out = engine.generate(prompts, greedy(16), speculation=spec)
        np.testing.assert_array_equal(plain.tokens, spec_out.tokens)
        assert "speculation" in spec_out.stats
    finally:
        engine.numerics_guards = False


def test_fused_requeue_parity(engine, baseline):
    """A decode fault inside a FUSED window discards the whole dispatch
    and requeues every rider once — survivors re-decode token-identical
    (the containment contract is per dispatch, whatever its width)."""
    inj = ScriptedFaultInjector({("g1", "decode"): 1})
    sched = ContinuousScheduler(
        engine, _scfg(fuse=4), settings=greedy(M), fault_injector=inj,
    )
    reqs = [Request(id=f"g{i}", prompt=p, settings=greedy(M))
            for i, p in enumerate(PROMPTS[:4])]
    results = sched.serve(reqs)
    _assert_parity(engine, baseline, reqs, results)
    assert results[1].retries == 1
    assert sched.last_stats.requeued == 1


def test_fused_numerics_guard_containment(engine, baseline):
    """Injected NaN inside a fused window: the guard flag rides the fused
    carry, the whole dispatch is discarded as a NumericsFault at the
    dispatch boundary, and the requeued rider still decodes to parity —
    the chaos drill's fused fault case in miniature."""
    engine.numerics_guards = True
    try:
        inj = ScriptedFaultInjector({}, corruptions={("g0", "decode"): 1})
        sched = ContinuousScheduler(
            engine, _scfg(fuse=4), settings=greedy(M), fault_injector=inj,
            resilience=ResilienceConfig(enabled=True),
        )
        reqs = [Request(id=f"g{i}", prompt=p, settings=greedy(M))
                for i, p in enumerate(PROMPTS[:4])]
        with use_registry() as reg:
            results = sched.serve(reqs)
            m = reg.peek("faults_total", component="serving",
                         kind="numerics", stage="decode")
            assert m is not None and m.value >= 1
        _assert_parity(engine, baseline, reqs, results)
    finally:
        engine.numerics_guards = False


def test_fused_fleet_migration_parity(engine, baseline):
    """Fleet failover with FUSED replicas: kill r1 mid-sweep — zero lost,
    migrated survivors token-identical through r0's own fused dispatch."""
    # Crash on the FIRST health poll: a fused fleet finishes the sweep in
    # so few loop iterations that a later-scheduled crash would miss it.
    inj = ScriptedFaultInjector(replica_crashes={"r1": 1})
    fleet = ReplicaSet(
        engine, _scfg(fuse=4), settings=greedy(M),
        fleet=FleetConfig(replicas=2, fence_cooldown_s=0.02),
        resilience=ResilienceConfig(enabled=True, breaker_threshold=1,
                                    breaker_cooldown_s=0.01),
        integrity=IntegrityConfig(canary_max_tokens=8),
        fault_injector=inj,
    )
    reqs = [Request(id=f"g{i}", prompt=p, settings=greedy(M))
            for i, p in enumerate(PROMPTS)]
    results = fleet.serve(reqs)
    _assert_parity(engine, baseline, reqs, results)
    r0, r1 = fleet.replicas
    assert r1.fences == 1 and r0.fences == 0


def test_watchdog_budget_scales_with_fuse():
    """A fused dispatch legitimately runs k chunks of wall: a hang budget
    tuned for one chunk must not classify every healthy fused dispatch as
    a hang (the scheduler passes budget_scale=fuse_steps), while a stall
    past the SCALED budget still raises."""
    from fairness_llm_tpu.resilience.watchdog import StepWatchdog
    from fairness_llm_tpu.utils.failures import HangFault

    with use_registry():
        wd = StepWatchdog(0.1)
        # 5 chunks of wall under fuse=8: healthy, within the scaled budget.
        assert wd.observe("decode", elapsed=0.5, budget_scale=8) == 0.5
        # The same wall with no scaling (fuse=1) is a hang.
        with pytest.raises(HangFault):
            wd.observe("decode", elapsed=0.5)
        # A stall past even the scaled budget still classifies.
        with pytest.raises(HangFault):
            wd.observe("decode", elapsed=1.0, budget_scale=8)


def test_degradation_rung2_resets_fuse(engine):
    """Rung 2's smaller-compiled-steps posture: the fused dispatch drops
    to 1 alongside the halved chunk, and both restore on retreat."""

    class _Ladder:
        level = 2
        rung = "reduced_footprint"

    class _Board:
        ladder = _Ladder()

    sched = ContinuousScheduler(engine, _scfg(fuse=4, chunk=8),
                                settings=greedy(M))
    sched.breakers = _Board()
    sched._apply_degradation()
    assert sched.fuse_steps == 1 and sched.decode_chunk == 4
    _Ladder.level = 0
    sched._apply_degradation()
    assert sched.fuse_steps == 4 and sched.decode_chunk == 8
    engine.restore_speculation()


# -- roofline: the fused byte oracle ------------------------------------------


def test_fused_vs_unfused_paged_byte_oracle(engine):
    """Hand-computed sibling of PR 12's paged oracle: the paged gather/
    scatter tax amortizes over the steps the dispatch ACTUALLY ran, so a
    fused dispatch (k x the steps) pays 1/k the per-step paged overhead
    while the contiguous terms (params + pool KV) are unchanged."""
    cfg = engine.config
    item = 2 if cfg.dtype == "bfloat16" else 4
    params = cfg.approx_param_count * item
    per_slot = cfg.num_kv_heads * cfg.head_dim * item * 2 * cfg.num_layers
    kv = 2 * 64 * per_slot  # batch=2 slots, 64 cache slots each
    base = {"batch": 2, "cache_slots": 64, "prefix_len": 0}
    plain = decode_step_bytes(cfg, base)
    assert plain == params + kv

    unfused = decode_step_bytes(
        cfg, {**base, "paged_kv": True, "chunk_steps": 8})
    fused = decode_step_bytes(
        cfg, {**base, "paged_kv": True, "chunk_steps": 32})
    assert unfused == params + kv + 4 * kv // 8
    assert fused == params + kv + 4 * kv // 32
    assert fused < unfused
    # Contiguous fused steps stream the same bytes per step as unfused:
    # the fusion win is host-gap amortization, not a byte-model change.
    assert decode_step_bytes(cfg, base) == plain


# -- fused telemetry attribution ----------------------------------------------


def test_fused_program_publishes_own_telemetry(engine):
    """A fused program appearing in compiles_total publishes its OWN cost
    ledger, roofline gauges, and host-gap accumulator under the
    ``serve_step_fused`` label — what ``validate_telemetry``'s extended
    --require-costmodel/--require-profile gates hold it to."""
    prev = set_attribution(True)
    try:
        with use_registry() as reg, use_timeline():
            sched = ContinuousScheduler(engine, _scfg(fuse=2),
                                        settings=greedy(M))
            reqs = [Request(id=f"t{i}", prompt=p, settings=greedy(M))
                    for i, p in enumerate(PROMPTS)]
            results = sched.serve(reqs)
            assert all(r.ok for r in results)

            def rows(name):
                return [m for m in reg.instruments()
                        if m.name == name
                        and m.labels.get("program") == "serve_step_fused"]

            assert any(m.value >= 1 for m in rows("compiles_total"))
            assert rows("cost_ledger_bytes"), \
                "fused program must publish its own ledger"
            assert rows("achieved_over_achievable"), \
                "fused program must publish its own roofline gauges"
            gaps = rows("cost_host_gap_s_total")
            assert gaps and gaps[0].value > 0, \
                "fused dispatches must accumulate a measured host gap"
    finally:
        set_attribution(prev)


# -- CLI flag gates ------------------------------------------------------------


def test_cli_fuse_steps_validation():
    from fairness_llm_tpu.cli.main import main

    base = ["--phase", "1", "--quick", "--model", "simulated", "--no-save"]
    with pytest.raises(SystemExit, match="require --continuous"):
        main(base + ["--fuse-steps", "4"])
    with pytest.raises(SystemExit, match="must be >= 1"):
        main(base + ["--continuous", "--fuse-steps", "0"])
    with pytest.raises(SystemExit, match="cannot combine with --speculate"):
        main(base + ["--continuous", "--speculate", "--fuse-steps", "4"])


def test_serving_config_fuse_default_is_identity():
    """fuse_steps=1 is the byte-identical default: same compile key shape,
    same program label, no fused telemetry names anywhere."""
    assert ServingConfig().fuse_steps == 1
    assert program_label("serve_step", ServingConfig().fuse_steps) == \
        "serve_step"


# -- the mesh axis: real-mesh tensor-parallel serving --------------------------


@pytest.fixture(scope="module")
def tp2_engine():
    """tiny-test over a REAL 2-device tp mesh (conftest forces 8 virtual
    CPU devices): params sharded by the parallel/ rules, programs lowered
    SPMD with XLA-inserted collectives."""
    return DecodeEngine(get_model_config("tiny-test"), seed=0,
                        mesh=make_mesh(MeshConfig(tp=2)))


def _tp_scfg(tp, fuse=1, paged=False):
    return ServingConfig(
        enabled=True, num_slots=2, queue_capacity=64,
        max_prompt_len=192, max_new_tokens=32, decode_chunk=2,
        fuse_steps=fuse, paged_kv=paged, kv_block_size=16, tp=tp,
    )


@pytest.mark.parametrize("paged", [False, True], ids=["contiguous", "paged"])
@pytest.mark.parametrize("fuse", [1, 4])
def test_tp2_serving_grid_parity(engine, baseline, tp2_engine, paged, fuse):
    """{contiguous, paged} x {fuse 1, 4} at tp=2: 6 mixed requests over 2
    slots (every slot recycles) decode token-for-token what the
    SINGLE-DEVICE engine decodes — sharding the cache on kv heads and the
    matmuls on the model axis must move zero tokens. The compiled key
    carries the ("tp", 2) element and the program the @tp2 label."""
    sched = ContinuousScheduler(
        tp2_engine, _tp_scfg(2, fuse=fuse, paged=paged), settings=greedy(M),
    )
    reqs = [Request(id=f"g{i}", prompt=p, settings=greedy(M))
            for i, p in enumerate(PROMPTS)]
    results = sched.serve(reqs)
    _assert_parity(engine, baseline, reqs, results)
    base = "paged_step" if paged else "serve_step"
    assert compile_key(base, chunk=2, guard=False, fuse=fuse, tp=2) \
        in sched._compiled
    assert sched._step_program() == program_label(base, fuse, tp=2)


def test_tp8_heads_replicate_parity(engine, baseline):
    """tp=8 over tiny-test (4 q heads / 2 kv heads): attention can't shard
    by heads, so it replicates while the ff (128) and vocab (512) axes DO
    shard — the mixed layout must still be token-exact. This is the
    degenerate-divisibility cell the sharding rules gate per-axis."""
    eng = DecodeEngine(get_model_config("tiny-test"), seed=0,
                       mesh=make_mesh(MeshConfig(tp=8)))
    sched = ContinuousScheduler(eng, _tp_scfg(8), settings=greedy(M))
    reqs = [Request(id=f"g{i}", prompt=p, settings=greedy(M))
            for i, p in enumerate(PROMPTS[:3])]
    results = sched.serve(reqs)
    _assert_parity(engine, baseline, reqs, results)


def test_tp2_numerics_guard_containment(engine, baseline, tp2_engine):
    """Injected NaN in a SHARDED fused window: the finite flag AND-reduces
    across shards inside the SPMD program, the dispatch is discarded as a
    NumericsFault, and the requeued rider decodes to parity — containment
    must not depend on where the poison lands in the mesh."""
    tp2_engine.numerics_guards = True
    try:
        inj = ScriptedFaultInjector({}, corruptions={("g0", "decode"): 1})
        sched = ContinuousScheduler(
            tp2_engine, _tp_scfg(2, fuse=4), settings=greedy(M),
            fault_injector=inj,
            resilience=ResilienceConfig(enabled=True),
        )
        reqs = [Request(id=f"g{i}", prompt=p, settings=greedy(M))
                for i, p in enumerate(PROMPTS[:4])]
        with use_registry() as reg:
            results = sched.serve(reqs)
            m = reg.peek("faults_total", component="serving",
                         kind="numerics", stage="decode")
            assert m is not None and m.value >= 1
        _assert_parity(engine, baseline, reqs, results)
    finally:
        tp2_engine.numerics_guards = False


def test_tp2_fused_requeue_parity(engine, baseline, tp2_engine):
    """A decode fault inside a SHARDED fused window: the whole dispatch
    discards, device state rebuilds RE-PLACED on the mesh (the donated
    sharded buffers were consumed), and every rider re-decodes
    token-identical."""
    inj = ScriptedFaultInjector({("g1", "decode"): 1})
    sched = ContinuousScheduler(
        tp2_engine, _tp_scfg(2, fuse=4), settings=greedy(M),
        fault_injector=inj,
    )
    reqs = [Request(id=f"g{i}", prompt=p, settings=greedy(M))
            for i, p in enumerate(PROMPTS[:4])]
    results = sched.serve(reqs)
    _assert_parity(engine, baseline, reqs, results)
    assert results[1].retries == 1
    assert sched.last_stats.requeued == 1


def test_tp2_fleet_migration_parity(engine, baseline, tp2_engine):
    """Fleet failover with SHARDED replicas: kill r1 mid-sweep — zero
    lost, migrated survivors token-identical through r0's own sharded
    dispatch (migration moves requests, never sharded device state)."""
    inj = ScriptedFaultInjector(replica_crashes={"r1": 1})
    fleet = ReplicaSet(
        tp2_engine, _tp_scfg(2), settings=greedy(M),
        fleet=FleetConfig(replicas=2, fence_cooldown_s=0.02),
        resilience=ResilienceConfig(enabled=True, breaker_threshold=1,
                                    breaker_cooldown_s=0.01),
        integrity=IntegrityConfig(canary_max_tokens=8),
        fault_injector=inj,
    )
    reqs = [Request(id=f"g{i}", prompt=p, settings=greedy(M))
            for i, p in enumerate(PROMPTS)]
    results = fleet.serve(reqs)
    _assert_parity(engine, baseline, reqs, results)
    r0, r1 = fleet.replicas
    assert r1.fences == 1 and r0.fences == 0


def test_tp2_sharded_telemetry_owns_mesh_labels(tp2_engine):
    """A sharded program publishes compile stats, cost ledger (including
    the nonzero ``collectives`` component — the tp all-reduce traffic)
    and roofline gauges under its OWN ``@tp2`` label, never polluting the
    single-device series — what validate_telemetry's extended
    --require-costmodel gate holds sharded runs to."""
    prev = set_attribution(True)
    try:
        with use_registry() as reg, use_timeline():
            sched = ContinuousScheduler(tp2_engine, _tp_scfg(2, fuse=2),
                                        settings=greedy(M))
            reqs = [Request(id=f"t{i}", prompt=p, settings=greedy(M))
                    for i, p in enumerate(PROMPTS[:4])]
            results = sched.serve(reqs)
            assert all(r.ok for r in results)
            label = "serve_step_fused@tp2"

            def rows(name, **extra):
                return [m for m in reg.instruments()
                        if m.name == name
                        and m.labels.get("program") == label
                        and all(m.labels.get(k) == v
                                for k, v in extra.items())]

            assert any(m.value >= 1 for m in rows("compiles_total"))
            coll = rows("cost_ledger_bytes", component="collectives")
            assert coll and sum(m.value for m in coll) > 0, \
                "sharded program must ledger its collectives traffic"
            assert rows("achieved_over_achievable"), \
                "sharded program must publish its own roofline gauges"
            # Nothing leaked into the unsharded label.
            assert not [m for m in reg.instruments()
                        if m.name == "cost_ledger_bytes"
                        and m.labels.get("program") == "serve_step_fused"]
    finally:
        set_attribution(prev)


def test_scheduler_rejects_dp_mesh_and_tp_mismatch(engine):
    """dp/sp meshes stay rejected at construction; a ServingConfig.tp that
    contradicts the engine's actual mesh fails loudly instead of silently
    serving single-device numbers under a mesh label."""
    dp_engine = DecodeEngine(get_model_config("tiny-test"), seed=0,
                             mesh=make_mesh(MeshConfig(dp=2)))
    with pytest.raises(ValueError, match="tp-only"):
        ContinuousScheduler(dp_engine, _scfg())
    with pytest.raises(ValueError, match="matching tp mesh"):
        ContinuousScheduler(engine, _tp_scfg(2))


def test_cli_tp_validation():
    """--tp follows the --fuse-steps parse-time discipline: every invalid
    combination dies in argparse/config_from_args with the flag named."""
    from fairness_llm_tpu.cli.main import main

    base = ["--phase", "1", "--quick", "--model", "simulated", "--no-save"]
    with pytest.raises(SystemExit, match="require --continuous"):
        main(base + ["--tp", "2"])
    with pytest.raises(SystemExit, match="must be >= 1"):
        main(base + ["--continuous", "--tp", "0"])
    with pytest.raises(SystemExit, match="cannot combine with --mesh"):
        main(base + ["--continuous", "--tp", "2", "--mesh", "dp=2"])
    # Head-count divisibility, checked against the model's config (the
    # conftest harness has 8 virtual devices, so the device gate passes
    # and the head gate must fire on its own).
    with pytest.raises(SystemExit, match="attention heads"):
        main(["--phase", "1", "--quick", "--model", "tiny-test", "--no-save",
              "--continuous", "--tp", "3"])
    # Device-count divisibility: 12 divides gpt2-small's heads but not the
    # harness's 8 devices.
    with pytest.raises(SystemExit, match="device count"):
        main(["--phase", "1", "--quick", "--model", "gpt2-small", "--no-save",
              "--continuous", "--tp", "12"])


def test_serving_config_tp_default_is_identity():
    """tp=1 is the byte-identical default: same compile keys, same
    labels, no mesh suffix anywhere, scheduler mesh-free."""
    assert ServingConfig().tp == 1
    assert program_label("serve_step", 1, tp=ServingConfig().tp) == \
        "serve_step"
    assert compile_key("serve_step", chunk=2, guard=False,
                       tp=ServingConfig().tp) == ("serve_step", 2, False, 1)

"""Telemetry subsystem tests: registry math, exporters, lifecycle tracing.

The load-bearing guarantees pinned here:

- histogram percentiles derive from bucket counts alone, with exact edge
  cases (empty, single-sample, boundary values) and the self-consistency
  ordering p50 <= p95 <= p99 <= observed max;
- registry label isolation (same name, different labels = independent
  instruments) and kind-collision rejection;
- ``SpeculationStats``/``ServingStats`` merge/as_dict/from_dict roundtrips
  stay byte-compatible (they are the phase-metadata wire format) while
  ``publish`` mirrors them into the registry;
- a real scheduler drain produces ordered lifecycle spans with
  TTFT <= e2e per request and nonzero TTFT/queue-wait/per-token histograms
  — the ISSUE-3 acceptance shape.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

import fairness_llm_tpu.telemetry as T
from fairness_llm_tpu.telemetry import (
    Heartbeat,
    Histogram,
    MetricsRegistry,
    RequestTracer,
    assert_span_order,
    use_registry,
)
from fairness_llm_tpu.telemetry.tracing import TERMINAL_EVENTS
from fairness_llm_tpu.utils.profiling import ServingStats, SpeculationStats


# -- histogram math -----------------------------------------------------------


def test_histogram_empty():
    h = Histogram("x", {}, bounds=(1.0, 2.0, 4.0))
    assert h.count == 0 and h.percentile(50) is None and h.mean is None
    d = h.as_dict()
    assert d["count"] == 0 and d["p50"] is None and d["max"] is None
    assert sum(d["bucket_counts"]) == 0


def test_histogram_single_sample_exact():
    h = Histogram("x", {}, bounds=(1.0, 2.0, 4.0))
    h.observe(1.3)
    # The min/max clamp makes every percentile of a single sample exact,
    # whatever bucket resolution says.
    for q in (0, 50, 95, 99, 100):
        assert h.percentile(q) == pytest.approx(1.3)
    assert h.mean == pytest.approx(1.3)


def test_histogram_boundary_values_le_semantics():
    h = Histogram("x", {}, bounds=(1.0, 2.0, 4.0))
    h.observe(2.0)  # exactly on a bound -> that bound's bucket (le)
    assert h.bucket_counts == [0, 1, 0, 0]
    h.observe(2.0000001)  # just past -> next bucket
    assert h.bucket_counts == [0, 1, 1, 0]


def test_histogram_underflow_overflow():
    h = Histogram("x", {}, bounds=(1.0, 2.0, 4.0))
    h.observe(0.25)   # below the first bound -> bucket 0
    h.observe(100.0)  # above the last bound -> overflow bucket
    assert h.bucket_counts == [1, 0, 0, 1]
    assert h.percentile(0) == pytest.approx(0.25)   # clamped to observed min
    assert h.percentile(100) == pytest.approx(100.0)  # overflow uses max


def test_histogram_percentile_ordering_and_range():
    rng = np.random.default_rng(0)
    h = Histogram("x", {})
    vals = rng.lognormal(mean=-3.0, sigma=2.0, size=500)
    for v in vals:
        h.observe(v)
    ps = [h.percentile(q) for q in (1, 25, 50, 90, 95, 99, 100)]
    assert ps == sorted(ps)
    assert h.min <= ps[0] and ps[-1] <= h.max
    # nearest-rank with upper-edge estimate is conservative: never below the
    # true percentile's bucket lower edge, never above observed max
    assert h.percentile(50) <= h.max


def test_histogram_rejects_bad_args():
    with pytest.raises(ValueError):
        Histogram("x", {}, bounds=())
    with pytest.raises(ValueError):
        Histogram("x", {}, bounds=(2.0, 1.0))
    h = Histogram("x", {}, bounds=(1.0,))
    h.observe(1)
    with pytest.raises(ValueError):
        h.percentile(101)


# -- registry -----------------------------------------------------------------


def test_registry_label_isolation_and_identity():
    r = MetricsRegistry()
    a = r.counter("requests_total", component="engine")
    b = r.counter("requests_total", component="serving")
    a.inc(3)
    assert b.value == 0  # labels isolate
    assert r.counter("requests_total", component="engine") is a  # get-or-create


def test_registry_kind_collision_rejected():
    r = MetricsRegistry()
    r.counter("x", component="a")
    with pytest.raises(ValueError):
        r.histogram("x", component="a")
    with pytest.raises(ValueError):
        r.gauge("x", component="b")  # kind is per-name, not per-labelset


def test_counter_monotonic_gauge_not():
    r = MetricsRegistry()
    c = r.counter("c")
    with pytest.raises(ValueError):
        c.inc(-1)
    g = r.gauge("g")
    g.set(5)
    g.set_max(3)
    assert g.value == 5
    g.set_max(9)
    assert g.value == 9


def test_use_registry_swaps_process_registry():
    before = T.get_registry()
    with use_registry() as reg:
        assert T.get_registry() is reg
        T.get_registry().counter("inside").inc()
        assert reg.counter("inside").value == 1
    assert T.get_registry() is before


# -- exporters ----------------------------------------------------------------


def _populated_registry():
    r = MetricsRegistry()
    r.counter("requests_total", component="serving").inc(7)
    r.gauge("queue_depth", component="serving").set(2)
    h = r.histogram("ttft_s", component="serving")
    for v in (0.01, 0.02, 0.04, 0.9):
        h.observe(v)
    return r


def test_snapshot_validates_and_renders():
    snap = T.snapshot(_populated_registry())
    assert T.validate_snapshot(snap) == []
    text = T.render_report(snap)
    assert "ttft_s" in text and "requests_total" in text and "[serving]" in text
    # JSON-serializable end to end (the file format)
    assert T.validate_snapshot(json.loads(json.dumps(snap))) == []


def test_validate_snapshot_catches_corruption():
    snap = T.snapshot(_populated_registry())
    bad = json.loads(json.dumps(snap))
    bad["histograms"][0]["p50"] = 99.0  # > p95: ordering violated
    assert any("ordering" in p for p in T.validate_snapshot(bad))
    bad2 = json.loads(json.dumps(snap))
    bad2["histograms"][0]["bucket_counts"][0] += 1
    assert any("sum" in p for p in T.validate_snapshot(bad2))
    assert T.validate_snapshot({"nope": 1})  # missing sections


def test_prometheus_exposition_cumulative_buckets():
    r = _populated_registry()
    text = T.to_prometheus(r)
    assert 'fairness_llm_requests_total{component="serving"} 7' in text
    # +Inf bucket equals total count; bucket lines are cumulative
    assert 'le="+Inf"} 4' in text
    assert "fairness_llm_ttft_s_count" in text
    assert "# TYPE fairness_llm_ttft_s histogram" in text


def test_write_and_load_snapshot_roundtrip(tmp_path):
    r = _populated_registry()
    path = T.write_snapshot(r, str(tmp_path))
    assert os.path.basename(path) == "telemetry_snapshot.json"
    assert os.path.exists(tmp_path / "metrics.prom")
    snap = T.load_snapshot(str(tmp_path))  # dir form
    assert T.validate_snapshot(snap) == []
    assert snap["counters"][0]["value"] == 7


def test_jsonl_sink_and_read_events(tmp_path):
    p = str(tmp_path / "events.jsonl")
    with T.JsonlSink(p) as sink:
        sink.emit("span", request_id="r1", event="submitted", t=1.0)
        sink.emit("heartbeat", uptime_s=5)
    with open(p, "a") as f:
        f.write('{"torn')  # a killed process can leave a torn last line
    evs = T.read_events(p)
    assert [e["kind"] for e in evs] == ["span", "heartbeat"]
    assert evs[0]["request_id"] == "r1"


def test_global_event_sink_install_and_emit(tmp_path):
    p = str(tmp_path / "e.jsonl")
    sink = T.JsonlSink(p)
    prev = T.install_event_sink(sink)
    try:
        T.emit_event("test", a=1)
    finally:
        T.install_event_sink(prev)
        sink.close()
    assert T.read_events(p)[0]["a"] == 1
    T.emit_event("dropped")  # no sink installed: silent no-op


# -- tracer -------------------------------------------------------------------


def test_tracer_derives_latency_decomposition():
    with use_registry() as reg:
        tr = RequestTracer(component="serving")
        tr.record("r1", "submitted", t=10.0)
        tr.record("r1", "admitted", t=10.5)
        tr.record("r1", "prefill_start", t=10.6)
        tr.record("r1", "first_token", t=11.0)
        row = tr.finalize("r1", "completed", tokens=5)
        assert row.queue_wait_s == pytest.approx(0.5)
        assert row.ttft_s == pytest.approx(1.0)
        assert row.e2e_s is not None and row.ttft_s <= row.e2e_s
        assert row.per_output_token_s is not None
        assert reg.histogram("ttft_s", component="serving").count == 1
        assert reg.histogram("queue_wait_s", component="serving").count == 1
        assert reg.counter("requests_finished_total", component="serving",
                           outcome="completed").value == 1
        assert reg.counter("output_tokens_total", component="serving").value == 5


def test_tracer_partial_lifecycle_and_bad_outcome():
    with use_registry() as reg:
        tr = RequestTracer(component="serving")
        tr.record("q", "submitted", t=1.0)
        row = tr.finalize("q", "expired", tokens=0)  # expired in queue
        assert row.queue_wait_s is None and row.ttft_s is None
        assert row.e2e_s is not None
        # single-token/zero-token requests have no steady-state cadence
        assert row.per_output_token_s is None
        assert reg.histogram("ttft_s", component="serving").count == 0
        with pytest.raises(ValueError):
            tr.finalize("other", "eaten_by_bear", tokens=0)


def test_tracer_requeued_request_uses_delivered_first_token():
    """A fault-requeued request's first attempt's tokens were discarded;
    TTFT/cadence must describe the retry's stream (LAST first_token), while
    queue-wait keeps the FIRST admission (initial backpressure)."""
    with use_registry():
        tr = RequestTracer(component="serving")
        tr.record("r", "submitted", t=0.0)
        tr.record("r", "admitted", t=1.0)
        tr.record("r", "first_token", t=2.0)   # attempt 1, later discarded
        tr.record("r", "requeued", t=3.0)
        tr.record("r", "admitted", t=4.0)
        tr.record("r", "first_token", t=5.0)   # the delivered stream
        row = tr.finalize("r", "completed", tokens=3)
        assert row.queue_wait_s == pytest.approx(1.0)
        assert row.ttft_s == pytest.approx(5.0)


def test_assert_span_order():
    tr = RequestTracer(component="t")
    with use_registry():
        tr.record("a", "submitted", t=1.0)
        tr.record("a", "admitted", t=2.0)
        tr.finalize("a", "completed", tokens=1)
        (row, events), = [tr.finished[-1]]
        assert_span_order(events)
    from fairness_llm_tpu.telemetry import SpanEvent

    with pytest.raises(AssertionError):
        assert_span_order([SpanEvent("a", "admitted", 1.0)])
    with pytest.raises(AssertionError):
        assert_span_order([SpanEvent("a", "submitted", 2.0),
                           SpanEvent("a", "admitted", 1.0)])
    with pytest.raises(AssertionError):
        assert_span_order([SpanEvent("a", "submitted", 1.0),
                           SpanEvent("a", "completed", 2.0),
                           SpanEvent("a", "admitted", 3.0)])


def test_heartbeat_rate_limited():
    with use_registry() as reg:
        hb = Heartbeat(interval_s=1000.0, name="t")
        assert hb.poke(completed=1)      # first poke always fires
        assert not hb.poke(completed=2)  # inside the interval: suppressed
        assert reg.counter("heartbeats_total", component="t").value == 1
        hb0 = Heartbeat(interval_s=0.0, name="t")
        assert hb0.poke() and hb0.poke()  # zero interval: every poke fires


# -- stats dataclass roundtrips + publish ------------------------------------


def test_speculation_stats_roundtrip_and_publish():
    a = SpeculationStats(drafted=10, accepted=4, verify_steps=3, emitted=7,
                         draft_len=8, ngram_max=3)
    d = a.as_dict()
    # byte-compat contract: exactly the PR-1 key set, derived keys included
    assert set(d) == {"drafted", "accepted", "verify_steps", "emitted",
                      "acceptance_rate", "tokens_per_step", "draft_len",
                      "ngram_max"}
    rt = SpeculationStats.from_dict(d)
    assert rt == a
    m = a.merge(SpeculationStats(drafted=2, accepted=1, verify_steps=1,
                                 emitted=2, draft_len=8, ngram_max=3))
    assert m.drafted == 12 and m.accepted == 5
    with use_registry() as reg:
        a.publish()
        assert reg.counter("spec_drafted_total", component="engine").value == 10
        assert reg.counter("spec_accepted_total", component="engine").value == 4


def test_serving_stats_roundtrip_and_publish():
    a = ServingStats(num_slots=4, admitted=6, completed=5, failed=1,
                     requeued=2, decode_steps=30, decoded_tokens=100,
                     occupancy_sum=90, queue_depth_sum=12, queue_depth_max=5,
                     loop_iterations=10)
    d = a.as_dict()
    rt = ServingStats.from_dict(d)
    assert rt == a  # derived keys dropped on the way in
    with use_registry() as reg:
        a.publish()
        assert reg.counter("serving_admitted_total",
                           component="serving").value == 6
        assert reg.counter("serving_decoded_tokens_total",
                           component="serving").value == 100
        assert reg.gauge("serving_num_slots", component="serving").value == 4
        assert reg.gauge("serving_queue_depth_max",
                         component="serving").value == 5
        a.publish()  # second drain accumulates counters, gauges re-set
        assert reg.counter("serving_admitted_total",
                           component="serving").value == 12
        assert reg.gauge("serving_num_slots", component="serving").value == 4


# -- scheduler integration (the acceptance-criteria shape) -------------------


@pytest.fixture(scope="module")
def engine():
    from fairness_llm_tpu.models.configs import get_model_config
    from fairness_llm_tpu.runtime.engine import DecodeEngine

    return DecodeEngine(get_model_config("tiny-test"), seed=0)


def _greedy(m):
    from fairness_llm_tpu.config import ModelSettings

    return ModelSettings(temperature=0.0, max_tokens=m)


def test_scheduler_drain_spans_and_histograms(engine, tmp_path):
    from fairness_llm_tpu.config import ServingConfig
    from fairness_llm_tpu.serving import ContinuousScheduler, Request

    sink = T.JsonlSink(str(tmp_path / "events.jsonl"))
    prev = T.install_event_sink(sink)
    try:
        with use_registry() as reg:
            sched = ContinuousScheduler(
                engine,
                ServingConfig(enabled=True, num_slots=2, max_prompt_len=64,
                              max_new_tokens=16, decode_chunk=4),
                settings=_greedy(16),
            )
            reqs = [Request(prompt=f"the number {i} is", id=f"t{i:02d}",
                            settings=_greedy(4 + 2 * i)) for i in range(5)]
            results = sched.serve(reqs)
            assert all(r.ok for r in results)

            # Per-request span ordering + TTFT <= e2e (from Result fields AND
            # the tracer's retained traces).
            rows = {row.request_id: (row, evs)
                    for row, evs in sched.tracer.finished}
            for r in results:
                row, evs = rows[r.id]
                assert_span_order(evs)
                names = [e.event for e in evs]
                assert names[0] == "submitted"
                assert "admitted" in names and "first_token" in names
                assert names.index("admitted") < names.index("first_token")
                assert evs[-1].event == "completed"
                assert r.ttft_s is not None and r.queue_wait_s is not None
                assert 0 <= r.queue_wait_s <= r.ttft_s <= r.latency_s
                assert row.ttft_s <= row.e2e_s

            # The acceptance-criteria histograms: nonzero counts,
            # self-consistent percentiles.
            for name in ("ttft_s", "queue_wait_s", "per_output_token_s",
                         "e2e_latency_s"):
                h = reg.histogram(name, component="serving")
                assert h.count > 0, name
                p50, p95, p99 = (h.percentile(q) for q in (50, 95, 99))
                assert p50 <= p95 <= p99 <= h.max, name
            assert reg.histogram("ttft_s", component="serving").min > 0

            # Pool-pressure samples: one weighted observation per decode step.
            occ = reg.histogram("slot_occupancy_dist", component="serving")
            stats = sched.last_stats
            assert occ.count == stats.decode_steps > 0
            # drain-level publish mirrored the dataclass into the registry
            assert reg.counter("serving_completed_total",
                               component="serving").value == stats.completed

        # every span event also reached the JSONL sink
        evs = T.read_events(str(tmp_path / "events.jsonl"))
        spans = [e for e in evs if e["kind"] == "span"]
        assert {e["event"] for e in spans} >= {"submitted", "admitted",
                                               "prefill_start", "first_token",
                                               "completed"}
    finally:
        T.install_event_sink(prev)
        sink.close()


def test_scheduler_fault_cause_breakdown(engine):
    from fairness_llm_tpu.config import ServingConfig
    from fairness_llm_tpu.serving import ContinuousScheduler, Request
    from fairness_llm_tpu.utils.failures import ScriptedFaultInjector

    with use_registry() as reg:
        sched = ContinuousScheduler(
            engine,
            ServingConfig(enabled=True, num_slots=2, max_prompt_len=64,
                          max_new_tokens=8, decode_chunk=2),
            settings=_greedy(8),
            fault_injector=ScriptedFaultInjector({("flaky", "decode"): 1}),
        )
        res = sched.serve([Request(prompt="hello there", id="flaky",
                                   settings=_greedy(4))])
        assert res[0].ok and res[0].retries == 1
        assert reg.counter("faults_total", component="serving",
                           kind="injected", stage="decode").value == 1
        assert reg.counter("serving_requeues_by_cause_total",
                           component="serving", cause="injected").value == 1
        # no device-raised faults in this run
        assert reg.counter("faults_total", component="serving",
                           kind="device", stage="decode").value == 0
        # the requeued request's lifecycle records the requeue span
        row, evs = next(t for t in sched.tracer.finished
                        if t[0].request_id == "flaky")
        assert "requeued" in [e.event for e in evs]
        assert row.outcome == "completed"


def test_queue_depth_high_water_mark_gauge(engine):
    """The live admission-queue high-water mark (ISSUE-6 satellite): the
    scheduler set_max's ``queue_depth_hwm`` every loop iteration — the
    fleet router's online backpressure signal while the drain is in
    flight — then resets it at drain close-out (a per-window worst case,
    not a lifetime one; the lifetime max stays in
    ``serving_queue_depth_max``). ``read_value`` peeks without
    materializing instruments for replicas that never served."""
    from fairness_llm_tpu.config import ServingConfig
    from fairness_llm_tpu.serving import ContinuousScheduler, Request
    from fairness_llm_tpu.utils.profiling import ServingStats

    with use_registry() as reg:
        # 1 slot + 6 requests: the queue must back up to >= 5 deep.
        sched = ContinuousScheduler(
            engine,
            ServingConfig(enabled=True, num_slots=1, max_prompt_len=64,
                          max_new_tokens=8, decode_chunk=2,
                          queue_capacity=16),
            settings=_greedy(4),
        )
        reqs = [Request(prompt=f"count to {i}", id=f"hwm{i}",
                        settings=_greedy(4)) for i in range(6)]
        for r in reqs:
            assert sched.submit(r)
        stats = ServingStats(num_slots=1)
        sched.step(stats)  # one loop iteration: the online-reader moment
        assert reg.read_value("queue_depth_hwm", component="serving") >= 5
        stats = sched.drain()
        assert stats.completed == 6
        for r in reqs:
            assert sched.take_result(r.id).ok
        # Drain close-out resets the live window; the per-drain record
        # keeps the max.
        assert reg.read_value("queue_depth_hwm", component="serving") == 0
        assert reg.gauge("serving_queue_depth_max",
                         component="serving").value >= 5
        # read_value never creates: an unserved replica label stays absent.
        assert reg.read_value("queue_depth_hwm", default=-1.0,
                              component="serving", replica="ghost") == -1.0
        assert reg.peek("queue_depth_hwm", component="serving",
                        replica="ghost") is None


def test_engine_generate_instrumented(engine):
    with use_registry() as reg:
        out = engine.generate(["one two three"], _greedy(4), seed=0)
        assert reg.counter("generate_calls_total", component="engine").value == 1
        assert reg.counter("prompt_tokens_total", component="engine").value > 0
        assert reg.counter("decoded_tokens_total", component="engine").value > 0
        h = reg.histogram("generate_wall_s", component="engine")
        assert h.count == 1 and h.max > 0
        assert reg.counter("decode_paths_total", component="engine",
                           path="plain").value == 1


# -- CLI surface --------------------------------------------------------------


def test_cli_telemetry_dir_and_report(tmp_path, capsys):
    from fairness_llm_tpu.cli.main import main

    tel = str(tmp_path / "tel")
    with use_registry():
        rc = main(["--phase", "1", "--quick", "--model", "simulated",
                   "--results-dir", str(tmp_path / "res"), "--no-save",
                   "--telemetry-dir", tel])
    assert rc == 0
    out = capsys.readouterr().out
    assert "TELEMETRY REPORT" in out and "telemetry snapshot:" in out
    snap = T.load_snapshot(tel)
    assert T.validate_snapshot(snap) == []
    # phase-1 instrumentation landed in the snapshot
    names = {(c["name"], c["labels"].get("component"))
             for c in snap["counters"]}
    assert ("phase_runs_total", "phase1") in names
    assert os.path.exists(os.path.join(tel, "metrics.prom"))
    # the heartbeat's first poke streams to events.jsonl
    evs = T.read_events(os.path.join(tel, "events.jsonl"))
    assert any(e["kind"] == "heartbeat" for e in evs)
    # sink was uninstalled at end of run
    assert T.event_sink() is None

    rc = main(["telemetry-report", tel, "--validate"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "TELEMETRY REPORT" in out and "snapshot schema: OK" in out


def test_cli_telemetry_report_rejects_invalid(tmp_path, capsys):
    from fairness_llm_tpu.cli.main import main
    from fairness_llm_tpu.telemetry.export import SNAPSHOT_FILENAME

    snap = T.snapshot(_populated_registry())
    snap["histograms"][0]["p50"] = 1e9  # break the ordering invariant
    path = tmp_path / SNAPSHOT_FILENAME
    path.write_text(json.dumps(snap))
    rc = main(["telemetry-report", str(tmp_path), "--validate"])
    assert rc == 1
    assert "SNAPSHOT INVALID" in capsys.readouterr().out

"""Golden-value tests: the jit metric kernels must reproduce the reference's
committed phase-1 numbers (BASELINE.md) when replaying its saved raw
recommendations.

Targets (reference results/phase1/phase1_summary_report.txt):
  demographic parity (gender) = 0.6772, (age) = 0.6472
  individual fairness         = 0.4669
  equal opportunity           = 1.0000 (vacuous — title-matching bug, SURVEY §8.2)

Tolerance: the kernels run in float32 (TPU-native); the reference computes in
float64 numpy. Observed deltas are ~5e-6, so 1e-4 absolute keeps us four
decimal places of agreement — far inside BASELINE.md's ±1% fidelity bar.
"""

import pytest

from fairness_llm_tpu.data.profiles import Profile, profile_pairs
from fairness_llm_tpu.metrics import (
    demographic_parity,
    equal_opportunity,
    individual_fairness,
)

# The reference's hard-coded "qualified movies" set (phase1_bias_detection.py:248-252)
QUALIFIED_MOVIES = {
    "The Shawshank Redemption", "The Godfather", "The Dark Knight",
    "Pulp Fiction", "Forrest Gump", "Inception", "The Matrix",
    "Goodfellas", "The Silence of the Lambs", "Saving Private Ryan",
}


def _group_recs(results, attribute):
    """Reference ``organize_by_attribute`` semantics (utils.py:308-325)."""
    grouped = {}
    recs = results["recommendations"]
    for prof in results["profiles"]:
        r = recs.get(prof["id"], {}).get("recommendations", [])
        if r:
            grouped.setdefault(prof[attribute], []).append(r)
    return grouped


def test_demographic_parity_gender_golden(reference_phase1_results):
    grouped = _group_recs(reference_phase1_results, "gender")
    score, details = demographic_parity(grouped)
    assert score == pytest.approx(0.6771792137547745, abs=1e-4)
    saved = reference_phase1_results["metrics"]["demographic_parity"]["gender"]
    assert sorted(details["divergences"]) == pytest.approx(sorted(saved["details"]["divergences"]), abs=1e-4)


def test_demographic_parity_age_golden(reference_phase1_results):
    grouped = _group_recs(reference_phase1_results, "age")
    score, _ = demographic_parity(grouped)
    assert score == pytest.approx(0.6471573268458267, abs=1e-4)


def test_individual_fairness_golden(reference_phase1_results):
    profiles = [
        Profile(
            id=p["id"], gender=p["gender"], age=p["age"], occupation=p["occupation"],
            watched_movies=p["preferences"]["watched_movies"],
            favorite_genres=p["preferences"]["favorite_genres"],
        )
        for p in reference_phase1_results["profiles"]
    ]
    pairs = profile_pairs(profiles)
    recs = {
        pid: r["recommendations"]
        for pid, r in reference_phase1_results["recommendations"].items()
        if "recommendations" in r
    }
    score, sims = individual_fairness(pairs, recs)
    # 45 profiles -> 405 single-attribute-differing pairs (SURVEY §3.2)
    assert len(sims) == 405
    assert score == pytest.approx(0.4668974533898281, abs=1e-4)


def test_equal_opportunity_golden_vacuous(reference_phase1_results):
    grouped = _group_recs(reference_phase1_results, "gender")
    score, by_group = equal_opportunity(grouped, QUALIFIED_MOVIES)
    # Titles carry year suffixes, the qualified set doesn't -> all-zero hit rates
    # -> var 0 -> EO = 1.0 (reference bug preserved as documented behavior).
    assert score == pytest.approx(1.0)
    assert all(v == 0.0 for v in by_group.values())

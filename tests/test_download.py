"""data/download.py: extract+verify logic against a fabricated local archive
(no network — the fetch path is exercised via a file:// URL)."""

import zipfile

from fairness_llm_tpu.data.download import EXPECTED_ROWS, fetch_ml1m


def _make_zip(path, rows_per_table):
    with zipfile.ZipFile(path, "w") as z:
        for table, rows in rows_per_table.items():
            z.writestr(f"ml-1m/{table}", "x::y::z\n" * rows)


def test_fetch_extracts_and_verifies(tmp_path):
    archive = tmp_path / "ml-1m.zip"
    _make_zip(archive, EXPECTED_ROWS)
    data_dir = tmp_path / "data"
    assert fetch_ml1m(str(data_dir), url=f"file://{archive}")
    for table in EXPECTED_ROWS:
        assert (data_dir / table).exists()


def test_fetch_skips_when_present(tmp_path):
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    for table in EXPECTED_ROWS:
        (data_dir / table).write_text("1::2::3\n")
    # unreachable URL never touched: tables already present
    assert fetch_ml1m(str(data_dir), url="file:///nonexistent.zip")


def test_fetch_fails_gracefully_offline(tmp_path, capsys):
    data_dir = tmp_path / "data"
    assert not fetch_ml1m(str(data_dir), url=f"file://{tmp_path}/missing.zip")
    assert "manually" in capsys.readouterr().err


def test_fetch_rejects_wrong_row_counts(tmp_path):
    archive = tmp_path / "bad.zip"
    _make_zip(archive, {t: 5 for t in EXPECTED_ROWS})
    data_dir = tmp_path / "data"
    assert not fetch_ml1m(str(data_dir), url=f"file://{archive}")
    # Rejected tables must not survive: otherwise a rerun would hit the
    # already-present early-exit and bless the data verification refused.
    for table in EXPECTED_ROWS:
        assert not (data_dir / table).exists()
    assert not fetch_ml1m(str(data_dir), url=f"file://{archive}")  # still fails


def test_fetch_rejects_non_zip_payload(tmp_path, capsys):
    payload = tmp_path / "portal.zip"
    payload.write_text("<html>sign in to continue</html>")
    assert not fetch_ml1m(str(tmp_path / "data"), url=f"file://{payload}")
    assert "manually" in capsys.readouterr().err

"""CLI + reports smoke tests: the full --all --quick surface end to end."""

import os

from fairness_llm_tpu.cli.main import main, parse_mesh
from fairness_llm_tpu.config import MeshConfig


def test_parse_mesh():
    assert parse_mesh("dp=2,tp=4") == MeshConfig(dp=2, tp=4)
    assert parse_mesh(None) == MeshConfig()


def test_cli_all_quick(tmp_path, capsys):
    rc = main(["--all", "--quick", "--results-dir", str(tmp_path)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "PHASE 1 SUMMARY" in out and "PHASE 3 SUMMARY" in out
    assert os.path.exists(tmp_path / "phase1" / "phase1_results.json")
    assert os.path.exists(tmp_path / "phase2" / "phase2_results.json")
    assert os.path.exists(tmp_path / "phase3" / "phase3_results.json")
    assert os.path.exists(tmp_path / "phase1" / "phase1_summary_report.txt")
    assert os.path.exists(tmp_path / "visualizations" / "fairness_overview.png")
    assert os.path.exists(tmp_path / "visualizations" / "snsr_similarity.png")
    assert os.path.exists(tmp_path / "visualizations" / "phase2_ranking_fairness.png")


def test_cli_single_phase(tmp_path):
    rc = main(["--phase", "2", "--quick", "--results-dir", str(tmp_path), "--no-save"])
    assert rc == 0


def test_phase2_figure_multi_model(tmp_path):
    """The ranking-fairness figure with several models (grouped bars +
    per-group exposure panel for the first model)."""
    from fairness_llm_tpu.config import Config
    from fairness_llm_tpu.pipeline.phase2 import run_phase2
    from fairness_llm_tpu.reports import generate_phase2_figure

    config = Config(results_dir=str(tmp_path), data_dir="/nonexistent")
    res = run_phase2(
        config, models=["simulated-fair", "simulated-biased"], corpus="movielens",
        num_items=30, num_queries=2, num_comparisons=6, save=False,
    )
    path = generate_phase2_figure(res, str(tmp_path / "viz"))
    assert os.path.exists(path) and os.path.getsize(path) > 10_000

"""Fuzz-ish robustness: parsers and tokenizer must never raise on arbitrary
model output (real decodes produce arbitrary bytes/unicode; a crash in the
parse layer would kill a whole sweep chunk)."""

import numpy as np
import pytest

from fairness_llm_tpu.models.tokenizer import ByteTokenizer
from fairness_llm_tpu.pipeline.parsing import (
    canonical_title,
    parse_comma_list,
    parse_numbered_list,
    parse_pairwise_answer,
    parse_ranking_indices,
)


def _random_texts(n=200, seed=0):
    rng = np.random.default_rng(seed)
    pool = (
        "1. ", "2)", "99: ", "A", "B", "tie", ",", "::", "\n", "\t", "  ",
        "The Matrix (1999)", "Amélie", "movie", "-", "🎬", "\\", '"', "*",
        "9" * 50, "(", ")", "answer:", "１２３",  # full-width digits
        "²", "①", "٣",  # isdigit()-true, int()-rejected code points
    )
    for _ in range(n):
        k = rng.integers(0, 12)
        yield "".join(rng.choice(pool) for _ in range(k))


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_parsers_never_raise(seed):
    for text in _random_texts(seed=seed):
        items = parse_numbered_list(text)
        assert all(isinstance(t, str) and t for t in items)
        parse_comma_list(text)
        ranking = parse_ranking_indices(text, 7)
        assert sorted(ranking) == list(range(7))  # always a permutation
        assert parse_pairwise_answer(text) in ("A", "B", "tie")
        canonical_title(text)


def test_tokenizer_roundtrip_arbitrary_unicode():
    tok = ByteTokenizer(512)
    for text in ["", "🎬🎥", "ß∂ƒ©", "a\x00b", "The Matrix (1999)\n\n", "é" * 300]:
        assert tok.decode(tok.encode(text, add_bos=False)) == text


def test_tokenizer_decode_garbage_ids():
    tok = ByteTokenizer(512)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 512, size=500).tolist()  # includes specials + out-of-byte ids
    out = tok.decode(ids)  # must not raise; invalid bytes replaced
    assert isinstance(out, str)

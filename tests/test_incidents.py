"""Incident engine tests: flight recorder, decision trail, triggers,
bundles, and the rotated JSONL sink.

The load-bearing guarantees pinned here (ISSUE 13):

- flight-recorder rings are BOUNDED: a flood evicts oldest, counts drops,
  and never grows; gauge transitions dedup on value;
- every decision point emits a schema-complete ``DecisionRecord`` (known
  decision kind, string action, dict signals, timestamp) — exercised
  through the real components (breaker, ladder, shed controller, router,
  autoscaler, heartbeat, SLO alerts) and through a real scheduler's
  fault/shed paths;
- trigger dedup/cooldown: inside the cooldown the same (class, scope)
  suppresses (counted), a different scope or an elapsed cooldown dumps —
  with an injectable clock, no sleeps;
- bundle dumps are ATOMIC: a dump that dies mid-write leaves no final
  bundle dir and no ``.partial`` leftover, and is counted, never raised
  into the serving loop;
- attribution off (and recording off) records NOTHING — rings, decisions,
  counters all silent;
- ``validate_incidents`` accepts a complete bundle set (``require``),
  rejects empties/torn bundles, and ``forbid`` rejects any bundle;
- ``render_incident_report`` derives the causal chain from the recorded
  trail ("fence(r1) <- 3x breaker trips <- numerics faults <- requests");
- the JSONL sink rotates on size with torn-tail-tolerant readers, and the
  ``fairness-report``/``slo-report`` CLI paths still read rotated dirs.
"""

import json
import os
import sys
import time

import pytest

import fairness_llm_tpu.telemetry as T
from fairness_llm_tpu.telemetry import (
    use_flight_recorder,
    use_incident_manager,
    use_registry,
    use_timeline,
)
from fairness_llm_tpu.telemetry.flightrecorder import (
    RING_CATEGORIES,
    FlightRecorder,
    set_recording,
)
from fairness_llm_tpu.telemetry.incidents import (
    DECISIONS,
    INCIDENT_CLASSES,
    IncidentManager,
    record_decision,
    validate_incidents,
)


def _tool(name):
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import importlib

        return importlib.import_module(name)
    finally:
        sys.path.pop(0)


def _decisions(rec):
    return list(rec.rings["decisions"])


def _assert_schema(entry):
    assert entry["decision"] in DECISIONS
    assert isinstance(entry["action"], str) and entry["action"]
    assert isinstance(entry["signals"], dict)
    assert isinstance(entry["t"], float)


# -- flight recorder -----------------------------------------------------------


def test_ring_bounded_eviction_under_flood():
    with use_registry(), use_timeline():
        rec = FlightRecorder(capacity=8)
        with use_flight_recorder(rec):
            for i in range(100):
                assert rec.record("chunks", i=i)
            assert len(rec.rings["chunks"]) == 8
            assert rec.dropped["chunks"] == 92
            # Oldest evicted: the survivors are the newest 8.
            assert [e["i"] for e in rec.rings["chunks"]] == list(range(92, 100))


def test_ring_unknown_category_is_noop():
    rec = FlightRecorder(capacity=4)
    assert not rec.record("no_such_ring", x=1)


def test_transition_dedup_on_value():
    with use_registry(), use_timeline():
        rec = FlightRecorder(capacity=8)
        with use_flight_recorder(rec):
            assert rec.transition("breaker_state", "serving/decode", "open")
            assert not rec.transition("breaker_state", "serving/decode",
                                      "open")
            assert rec.transition("breaker_state", "serving/decode", "closed")
            assert rec.transition("breaker_state", "serving/prefill", "open")
            edges = list(rec.rings["transitions"])
            assert len(edges) == 3
            assert edges[0]["prev"] is None
            assert edges[1]["prev"] == "open"


def test_snapshot_shape():
    with use_registry(), use_timeline():
        rec = FlightRecorder(capacity=4)
        with use_flight_recorder(rec):
            rec.record("lifecycle", request_id="a", event="submitted")
            snap = rec.snapshot()
    assert set(snap["rings"]) == set(RING_CATEGORIES)
    assert snap["rings"]["lifecycle"][0]["request_id"] == "a"
    assert snap["capacity"] == 4


# -- attribution / recording gating -------------------------------------------


def test_attribution_off_records_nothing():
    from fairness_llm_tpu.telemetry import set_attribution

    with use_registry() as reg, use_timeline():
        rec = FlightRecorder(capacity=8)
        with use_flight_recorder(rec):
            prev = set_attribution(False)
            try:
                assert not rec.record("chunks", x=1)
                assert not rec.transition("g", "k", 1)
                assert record_decision("route", "r0") is None
            finally:
                set_attribution(prev)
            assert all(not v for v in rec.rings.values())
            assert reg.peek("decisions_total", component="incidents") is None


def test_recording_switch_off_records_nothing():
    with use_registry() as reg, use_timeline():
        rec = FlightRecorder(capacity=8)
        with use_flight_recorder(rec):
            prev = set_recording(False)
            try:
                assert not rec.record("chunks", x=1)
                assert record_decision("route", "r0") is None
            finally:
                set_recording(prev)
            assert all(not v for v in rec.rings.values())
            assert reg.peek("decisions_total", component="incidents") is None


def test_record_decision_rejects_unknown_kind():
    with pytest.raises(ValueError):
        record_decision("not_a_decision", "x")


# -- decision points (unit: the real components) -------------------------------


def test_breaker_and_ladder_decisions():
    from fairness_llm_tpu.resilience.breaker import BreakerBoard

    with use_registry(), use_timeline():
        with use_flight_recorder() as rec, use_incident_manager():
            board = BreakerBoard(failure_threshold=1, cooldown_s=60.0)
            board.record_failure("decode")
            kinds = {(d["decision"], d["action"]) for d in _decisions(rec)}
            assert ("breaker", "decode:closed->open") in kinds
            assert ("ladder", "0->1") in kinds
            for d in _decisions(rec):
                _assert_schema(d)
            # The gauge edges landed in the transitions ring too.
            names = {e["name"] for e in rec.rings["transitions"]}
            assert {"breaker_state", "degradation_level"} <= names


def test_shed_controller_transition_decision():
    from fairness_llm_tpu.config import OverloadConfig
    from fairness_llm_tpu.serving.overload import ShedController

    clock = [0.0]
    with use_registry(), use_timeline():
        with use_flight_recorder() as rec, use_incident_manager():
            ctl = ShedController(
                OverloadConfig(enabled=True, queue_frac_threshold=0.5,
                               eval_interval_s=0.0),
                clock=lambda: clock[0],
            )
            ctl.observe_queue_depth(10, 10)
            ctl.evaluate()
            ds = [d for d in _decisions(rec) if d["decision"] == "overload"]
            assert len(ds) == 1 and ds[0]["action"] == "0->1"
            assert ds[0]["signals"]["rung"] == "shed_batch"
            assert "queue_frac" in ds[0]["signals"]
            _assert_schema(ds[0])


class _FakeQueue:
    closed = False
    full = False

    def __len__(self):
        return 0


class _FakeSched:
    def __init__(self):
        self.queue = _FakeQueue()
        self.breakers = None
        self.watchdog = None
        self.has_work = False
        self._pending = []
        self.num_slots = 4

        class _Pool:
            occupancy = 0

        self.pool = _Pool()


class _FakeReplica:
    def __init__(self, name):
        self.name = name
        self.fenced = False
        self.sched = _FakeSched()


def test_router_pick_decision_and_health_edges():
    from fairness_llm_tpu.serving.router import HealthRouter

    with use_registry(), use_timeline():
        with use_flight_recorder() as rec, use_incident_manager():
            router = HealthRouter()
            reps = [_FakeReplica("r0"), _FakeReplica("r1")]
            chosen = router.pick(reps)
            assert chosen is not None
            # Placement decisions land in their OWN ring — a routing flood
            # must never evict the rare criticals from the decisions ring.
            assert not _decisions(rec)
            ds = [d for d in rec.rings["routes"]
                  if d["decision"] == "route"]
            assert len(ds) == 1 and ds[0]["action"] == chosen.name
            assert ds[0]["replica"] == chosen.name
            assert "weight" in ds[0]["signals"]
            _assert_schema(ds[0])
            edges = [e for e in rec.rings["transitions"]
                     if e["name"] == "replica_health_score"]
            assert {e["key"] for e in edges} == {"r0", "r1"}


class _FakeFleet:
    """The Autoscaler's duck-typed surface (see its __init__ docstring)."""

    def __init__(self):
        self.replicas = [_FakeReplica("r0")]
        self.queue = []
        self._pending = []
        self._fleet_labels = {}
        self.shed_controller = None

        class _Serving:
            queue_capacity = 16

        self.serving = _Serving()

        class _Router:
            @staticmethod
            def load(rep):
                return 0.0

        self.router = _Router()

    def _max_replica_burn(self):
        return 9.0  # permanently hot: every tick wants a scale-up

    def add_replica(self):
        rep = _FakeReplica(f"r{len(self.replicas)}")
        self.replicas.append(rep)
        return rep

    def retire_replica(self, rep):
        self.replicas.remove(rep)
        return 0


def test_autoscaler_decision():
    from fairness_llm_tpu.config import AutoscaleConfig
    from fairness_llm_tpu.serving.autoscaler import Autoscaler

    clock = [0.0]
    with use_registry(), use_timeline():
        with use_flight_recorder() as rec, use_incident_manager():
            fleet = _FakeFleet()
            ctl = Autoscaler(fleet, AutoscaleConfig(
                enabled=True, min_replicas=1, max_replicas=3,
                up_window_s=1.0, cooldown_s=0.0, eval_interval_s=0.0,
            ), clock=lambda: clock[0])
            ctl.tick()        # hot window starts
            clock[0] = 2.0
            assert ctl.tick() == "up"
            ds = [d for d in _decisions(rec) if d["decision"] == "autoscale"]
            assert len(ds) == 1 and ds[0]["action"] == "up"
            assert ds[0]["signals"]["burn"] == 9.0
            _assert_schema(ds[0])
            edges = [e for e in rec.rings["transitions"]
                     if e["name"] == "fleet_replicas"]
            assert edges and edges[-1]["value"] == 2


def test_heartbeat_gap_decision_and_trigger(tmp_path):
    from fairness_llm_tpu.telemetry.heartbeat import Heartbeat

    clock = [0.0]
    with use_registry(), use_timeline():
        with use_flight_recorder() as rec, \
                use_incident_manager() as mgr:
            mgr.arm(str(tmp_path / "incidents"))
            hb = Heartbeat(interval_s=10.0, name="t", clock=lambda: clock[0])
            hb.poke()
            clock[0] = 12.0
            hb.poke()  # ordinary cadence: no gap
            clock[0] = 100.0
            hb.poke()  # 88 s dark: gap AND sustained (> 4x interval)
            ds = [d for d in _decisions(rec) if d["decision"] == "heartbeat"]
            assert len(ds) == 1 and ds[0]["signals"]["gap_s"] == 88.0
            bundles = T.list_bundles(str(tmp_path / "incidents"))
            assert len(bundles) == 1
            assert bundles[0]["class"] == "heartbeat_gap"
            assert "went dark" in bundles[0]["cause"]


def test_slo_error_alert_triggers_bundle(tmp_path):
    from fairness_llm_tpu.telemetry.slo import SLOEvaluator, SLOTargets

    with use_registry(), use_timeline():
        with use_flight_recorder() as rec, \
                use_incident_manager() as mgr:
            mgr.arm(str(tmp_path / "incidents"))
            ev = SLOEvaluator(targets=SLOTargets(error_rate=0.01))
            ev.observe("failed", ttft_s=None, e2e_s=None)
            ds = [d for d in _decisions(rec) if d["decision"] == "slo_alert"]
            assert ds and all(d["action"].startswith("error_rate")
                              for d in ds)
            bundles = T.list_bundles(str(tmp_path / "incidents"))
            # One slo_burn bundle (scope-deduped across the three windows).
            assert [b["class"] for b in bundles] == ["slo_burn"]


def test_slo_latency_alert_does_not_trigger(tmp_path):
    from fairness_llm_tpu.telemetry.slo import SLOEvaluator, SLOTargets

    with use_registry(), use_timeline():
        with use_flight_recorder(), use_incident_manager() as mgr:
            mgr.arm(str(tmp_path / "incidents"))
            ev = SLOEvaluator(targets=SLOTargets(ttft_p95_s=0.001))
            ev.observe("completed", ttft_s=5.0, e2e_s=5.0)
            # TTFT burns alert (gauges/events) but must NOT bundle — a
            # fault-free batch sweep blows TTFT on compile alone.
            assert T.list_bundles(str(tmp_path / "incidents")) == []


# -- trigger dedup / cooldown --------------------------------------------------


def test_trigger_dedup_cooldown_injectable_clock(tmp_path):
    clock = [0.0]
    with use_registry() as reg, use_timeline(), use_flight_recorder():
        mgr = IncidentManager(str(tmp_path), cooldown_s=60.0,
                              clock=lambda: clock[0])
        p1 = mgr.trigger("breaker_open", "first", scope="serving")
        assert p1 is not None and os.path.isdir(p1)
        # Same (class, scope) inside the cooldown: suppressed, counted.
        assert mgr.trigger("breaker_open", "again", scope="serving") is None
        assert reg.read_value("incident_suppressed_total",
                              component="incidents",
                              **{"class": "breaker_open"}) == 1
        # Different scope: its own dedup key, dumps immediately.
        p2 = mgr.trigger("breaker_open", "other replica", scope="r1")
        assert p2 is not None and p2 != p1
        # Cooldown elapsed: dumps again.
        clock[0] = 61.0
        p3 = mgr.trigger("breaker_open", "third", scope="serving")
        assert p3 is not None and p3 not in (p1, p2)
        assert reg.read_value("incident_triggers_total",
                              component="incidents",
                              **{"class": "breaker_open"}) == 4
        assert reg.read_value("incident_bundles_total",
                              component="incidents",
                              **{"class": "breaker_open"}) == 3


def test_route_flood_cannot_evict_critical_decisions():
    with use_registry(), use_timeline():
        rec = FlightRecorder(capacity=8)
        with use_flight_recorder(rec):
            record_decision("breaker", "decode:closed->open")
            for i in range(100):
                record_decision("route", f"r{i % 2}")
            # The breaker decision survived the flood; routes have their
            # own (bounded) ring.
            assert [d["decision"] for d in _decisions(rec)] == ["breaker"]
            assert len(rec.rings["routes"]) == 8


def test_rearm_into_existing_dir_never_collides(tmp_path):
    with use_registry(), use_timeline(), use_flight_recorder():
        m1 = IncidentManager(str(tmp_path))
        p1 = m1.trigger("fence", "first run", scope="r0")
        # A fresh manager (new process) over the SAME dir: its seq restarts
        # but names must skip past the prior run's bundles.
        m2 = IncidentManager(str(tmp_path))
        p2 = m2.trigger("fence", "second run", scope="r0")
        assert p2 is not None and p2 != p1
        assert len(T.list_bundles(str(tmp_path))) == 2


def test_failed_dump_does_not_stamp_cooldown(tmp_path, monkeypatch):
    with use_registry(), use_timeline(), use_flight_recorder():
        mgr = IncidentManager(str(tmp_path), cooldown_s=3600.0)
        orig = IncidentManager._write_json

        def dying(dir_, name, obj):
            raise OSError("disk full")

        monkeypatch.setattr(IncidentManager, "_write_json",
                            staticmethod(dying))
        assert mgr.trigger("fence", "x", scope="r0") is None
        monkeypatch.setattr(IncidentManager, "_write_json",
                            staticmethod(orig))
        # The failure must NOT have started the cooldown: the next trigger
        # of the same (class, scope) dumps instead of suppressing for an
        # hour with nothing on disk.
        assert mgr.trigger("fence", "y", scope="r0") is not None


def test_forbid_flags_partial_leftover(tmp_path):
    tel = tmp_path / "tel"
    inc = tel / "incidents"
    os.makedirs(str(inc / "fence-r0-001.partial"))
    assert any("fired" in p
               for p in validate_incidents(str(tel), forbid=True))


def test_trigger_disarmed_is_noop(tmp_path):
    with use_registry() as reg, use_timeline(), use_flight_recorder():
        mgr = IncidentManager()  # no dir = disarmed
        assert mgr.trigger("fence", "x", scope="r0") is None
        assert reg.peek("incident_triggers_total",
                        component="incidents") is None


def test_trigger_unknown_class_rejected(tmp_path):
    mgr = IncidentManager(str(tmp_path))
    with pytest.raises(ValueError):
        mgr.trigger("not_a_class", "x")
    assert set(INCIDENT_CLASSES) >= {"breaker_open", "fence",
                                     "watchdog_hang", "numerics_fault",
                                     "canary_mismatch", "heartbeat_gap"}


# -- bundle contents / atomicity -----------------------------------------------


def test_bundle_contents_and_implicated_filter(tmp_path):
    with use_registry(), use_timeline(), use_flight_recorder() as rec:
        record_decision("fault", "decode:numerics",
                        signals={"request_ids": ["a", "b"]},
                        request_id="a", replica="r1")
        record_decision("route", "r0", replica="r0")
        mgr = IncidentManager(str(tmp_path))
        path = mgr.trigger("numerics_fault", "nan chunk", scope="r1",
                           replica="r1", request_id="a")
        assert path is not None
        for fn in ("incident.json", "flightrecorder.json", "decisions.jsonl",
                   "decisions_implicated.jsonl", "snapshot.json",
                   "trace_slice.json"):
            assert os.path.isfile(os.path.join(path, fn)), fn
        with open(os.path.join(path, "incident.json")) as f:
            manifest = json.load(f)
        assert manifest["class"] == "numerics_fault"
        assert manifest["replica"] == "r1"
        assert manifest["ring_depths"]["decisions"] >= 2
        # The implicated trail filters to r1/a: the r0 route stays out.
        with open(os.path.join(path, "decisions_implicated.jsonl")) as f:
            imp = [json.loads(line) for line in f if line.strip()]
        assert imp and all(d.get("replica") == "r1"
                           or d.get("request_id") == "a" for d in imp)
        # The ring snapshot inside the bundle holds the decision trail too.
        with open(os.path.join(path, "flightrecorder.json")) as f:
            fr = json.load(f)
        assert len(fr["rings"]["decisions"]) == len(rec.rings["decisions"])


def test_bundle_atomicity_mid_dump_kill(tmp_path, monkeypatch):
    with use_registry() as reg, use_timeline(), use_flight_recorder():
        mgr = IncidentManager(str(tmp_path))
        orig = IncidentManager._write_json

        def dying(dir_, name, obj):
            if name == "snapshot.json":
                raise OSError("disk died mid-dump")
            orig(dir_, name, obj)

        monkeypatch.setattr(IncidentManager, "_write_json",
                            staticmethod(dying))
        # Contained: returns None, never raises into the caller.
        assert mgr.trigger("fence", "x", scope="r0") is None
        # No final bundle, no .partial leftover — nothing torn.
        assert os.listdir(str(tmp_path)) == []
        assert reg.read_value("incident_dump_failures_total",
                              component="incidents") == 1
        # The manager recovers: the next (post-cooldown) dump succeeds.
        monkeypatch.setattr(IncidentManager, "_write_json",
                            staticmethod(orig))
        mgr._last_dump.clear()
        assert mgr.trigger("fence", "y", scope="r0") is not None


# -- validate_incidents (--require / --forbid) ---------------------------------


def test_validate_incidents_accept_reject(tmp_path):
    tel = tmp_path / "tel"
    inc = tel / "incidents"
    with use_registry(), use_timeline(), use_flight_recorder():
        mgr = IncidentManager(str(inc))
        # Empty: require rejects, forbid accepts.
        os.makedirs(str(inc))
        assert validate_incidents(str(tel), require=True)
        assert validate_incidents(str(tel), forbid=True) == []
        # One good bundle: require accepts, forbid rejects.
        record_decision("fence", "replica_crash", replica="r1")
        mgr.trigger("fence", "replica r1 fenced", scope="r1", replica="r1")
        assert validate_incidents(str(tel), require=True) == []
        assert validate_incidents(str(tel), forbid=True)
        # A torn .partial leftover: require rejects.
        os.makedirs(str(inc / "fence-zz-099.partial"))
        assert any("torn" in p
                   for p in validate_incidents(str(tel), require=True))
        os.rmdir(str(inc / "fence-zz-099.partial"))
        # A bundle missing a required file: require rejects.
        bundle = T.list_bundles(str(inc))[0]["path"]
        os.remove(os.path.join(bundle, "snapshot.json"))
        assert any("snapshot.json" in p
                   for p in validate_incidents(str(tel), require=True))


def test_validate_telemetry_tool_gates(tmp_path):
    vt = _tool("validate_telemetry")
    tel = str(tmp_path / "tel")
    with use_registry() as reg, use_timeline(), use_flight_recorder():
        mgr = IncidentManager(os.path.join(tel, "incidents"))
        record_decision("fence", "replica_crash", replica="r1")
        mgr.trigger("fence", "replica r1 fenced", scope="r1", replica="r1")
        T.write_snapshot(reg, tel)
        assert vt.check(tel, require_incidents=True) == 0
        assert vt.check(tel, forbid_incidents=True) == 1
    # A fresh registry (zero decisions/bundle counters) must fail require:
    # the snapshot cross-checks bite, not just the files.
    with use_registry() as reg2, use_timeline(), use_flight_recorder():
        T.write_snapshot(reg2, tel)
        assert vt.check(tel, require_incidents=True) == 1


# -- report rendering ----------------------------------------------------------


def test_report_renders_synthetic_fence_chain(tmp_path):
    with use_registry(), use_timeline(), use_flight_recorder():
        for _ in range(3):
            record_decision("breaker", "decode:closed->open",
                            signals={"consecutive_failures": 1,
                                     "stage": "decode"},
                            replica="r1")
        record_decision("fault", "decode:numerics",
                        signals={"request_ids": ["a", "b", "c"]},
                        request_id="a", replica="r1")
        record_decision("fence", "breakers",
                        signals={"open_breakers": 1}, replica="r1")
        mgr = IncidentManager(str(tmp_path))
        path = mgr.trigger("fence", "replica r1 fenced: breakers",
                           scope="r1", replica="r1")
        report = T.render_incident_report(path)
        chain = next(ln for ln in report.splitlines()
                     if ln.strip().startswith("fence("))
        assert "fence(r1)" in chain
        assert "3x breaker:decode:closed->open" in chain
        assert "requests a, b, c" in chain
        # The table view names the fence decision too.
        assert "decision trail" in report and "fence" in report


# -- integration: scheduler fault/shed decision points -------------------------


@pytest.fixture(scope="module")
def engine():
    from fairness_llm_tpu.models.configs import get_model_config
    from fairness_llm_tpu.runtime.engine import DecodeEngine

    return DecodeEngine(get_model_config("tiny-test"), seed=0)


def _greedy(m):
    from fairness_llm_tpu.config import ModelSettings

    return ModelSettings(temperature=0.0, max_tokens=m)


def test_scheduler_fault_decision_and_breaker_bundle(engine, tmp_path):
    from fairness_llm_tpu.config import ResilienceConfig, ServingConfig
    from fairness_llm_tpu.serving import ContinuousScheduler, Request
    from fairness_llm_tpu.utils.failures import ScriptedFaultInjector

    with use_registry(), use_timeline(), use_flight_recorder() as rec, \
            use_incident_manager() as mgr:
        mgr.arm(str(tmp_path / "incidents"), cooldown_s=3600.0)
        inj = ScriptedFaultInjector(faults={("bad", "decode"): 1})
        sched = ContinuousScheduler(
            engine,
            ServingConfig(enabled=True, num_slots=2, max_new_tokens=8),
            settings=_greedy(8), fault_injector=inj,
            resilience=ResilienceConfig(enabled=True, breaker_threshold=1,
                                        breaker_cooldown_s=0.01),
        )
        results = sched.serve([Request(prompt="hello there", id="bad",
                                       settings=_greedy(8))])
        assert results[0].ok  # requeue-once healed it
        ds = [d for d in _decisions(rec) if d["decision"] == "fault"]
        assert ds and ds[0]["action"] == "decode:injected"
        assert ds[0]["signals"]["request_ids"] == ["bad"]
        _assert_schema(ds[0])
        bundles = T.list_bundles(str(tmp_path / "incidents"))
        assert [b["class"] for b in bundles] == ["breaker_open"]
        # The bundle's trail names the injected request — the "decision
        # trail names the cause" contract the chaos drill gates on.
        with open(os.path.join(bundles[0]["path"],
                               "decisions.jsonl")) as f:
            trail = [json.loads(line) for line in f if line.strip()]
        assert any(d.get("decision") == "fault"
                   and "bad" in d["signals"].get("request_ids", ())
                   for d in trail)
        # Lifecycle + chunk rings populated by the serve.
        assert rec.rings["lifecycle"] and rec.rings["chunks"]


def test_scheduler_shed_decision(engine):
    from fairness_llm_tpu.config import OverloadConfig, ServingConfig
    from fairness_llm_tpu.serving import ContinuousScheduler, Request

    with use_registry(), use_timeline(), use_flight_recorder() as rec, \
            use_incident_manager():
        sched = ContinuousScheduler(
            engine,
            ServingConfig(enabled=True, num_slots=2, max_new_tokens=8),
            settings=_greedy(8),
            overload=OverloadConfig(enabled=True),
        )
        sched.shed_controller.level = 3  # interactive_only brownout
        assert not sched.submit(Request(prompt="x", id="b1",
                                        settings=_greedy(8), qos="batch"))
        res = sched.take_result("b1")
        assert res is not None and res.finish_reason == "shed"
        ds = [d for d in _decisions(rec) if d["decision"] == "shed"]
        assert len(ds) == 1 and ds[0]["action"] == "overload"
        assert ds[0]["request_id"] == "b1"
        assert ds[0]["signals"]["level"] == 3
        _assert_schema(ds[0])


# -- JSONL sink rotation (satellite) -------------------------------------------


def test_jsonl_sink_rotation_and_merged_read(tmp_path):
    from fairness_llm_tpu.telemetry.export import JsonlSink, read_events

    path = str(tmp_path / "events.jsonl")
    sink = JsonlSink(path, max_bytes=300, keep=2)
    for i in range(40):
        sink.emit("tick", i=i)
    sink.close()
    assert sink.rotations > 2
    assert os.path.exists(path + ".1") and os.path.exists(path + ".2")
    assert not os.path.exists(path + ".3")  # beyond keep: deleted
    events = read_events(path)
    # Merged oldest-first across generations, newest event last.
    assert events[-1]["i"] == 39
    idx = [e["i"] for e in events]
    assert idx == sorted(idx)
    # Old generations were dropped (bounded), not silently kept.
    assert len(events) < 40


def test_read_events_tolerates_torn_tails_in_every_generation(tmp_path):
    from fairness_llm_tpu.telemetry.export import JsonlSink, read_events

    path = str(tmp_path / "events.jsonl")
    sink = JsonlSink(path, max_bytes=200, keep=3)
    for i in range(20):
        sink.emit("tick", i=i)
    sink.close()
    # A kill can tear the final line of ANY generation.
    for p in (path, path + ".1"):
        with open(p, "a", encoding="utf-8") as f:
            f.write('{"kind": "torn", "i":')
    events = read_events(path)
    assert events and all(e["kind"] == "tick" for e in events)


def test_sink_rejects_bad_rotation_args(tmp_path):
    from fairness_llm_tpu.telemetry.export import JsonlSink

    with pytest.raises(ValueError):
        JsonlSink(str(tmp_path / "e.jsonl"), max_bytes=0)
    with pytest.raises(ValueError):
        JsonlSink(str(tmp_path / "e.jsonl"), max_bytes=10, keep=0)


def test_forbid_catches_trigger_whose_dump_failed(tmp_path, monkeypatch):
    """A fired trigger whose dump died (contained exception, .partial
    cleaned) leaves nothing on disk — the snapshot counter must still
    fail --forbid-incidents."""
    vt = _tool("validate_telemetry")
    tel = str(tmp_path / "tel")
    with use_registry() as reg, use_timeline(), use_flight_recorder():
        mgr = IncidentManager(os.path.join(tel, "incidents"))
        monkeypatch.setattr(
            IncidentManager, "_write_json",
            staticmethod(lambda *a: (_ for _ in ()).throw(OSError("full"))))
        assert mgr.trigger("fence", "x", scope="r0") is None
        assert T.list_bundles(os.path.join(tel, "incidents")) == []
        T.write_snapshot(reg, tel)
        assert vt.check(tel, forbid_incidents=True) == 1


def test_read_events_survives_generation_gap(tmp_path):
    """A kill between _rotate's two renames leaves .2 without .1 — the
    reader must still find the orphaned generation."""
    from fairness_llm_tpu.telemetry.export import read_events

    path = str(tmp_path / "events.jsonl")
    with open(path + ".2", "w", encoding="utf-8") as f:
        f.write('{"kind": "old", "i": 0}\n')
    with open(path, "w", encoding="utf-8") as f:
        f.write('{"kind": "new", "i": 1}\n')
    events = read_events(path)
    assert [e["kind"] for e in events] == ["old", "new"]


def test_reports_read_rotated_telemetry_dir(tmp_path, capsys):
    """Regression (satellite): fairness-report and slo-report must keep
    working on a telemetry dir whose events.jsonl has rotated."""
    from fairness_llm_tpu.cli.main import fairness_report, slo_report
    from fairness_llm_tpu.telemetry.export import JsonlSink

    tel = str(tmp_path)
    with use_registry() as reg, use_timeline():
        reg.gauge("slo_burn_rate", component="serving", slo="error_rate",
                  window="run").set(0.5)
        reg.counter("fairness_requests_total", component="fairness").inc()
        sink = JsonlSink(os.path.join(tel, "events.jsonl"),
                         max_bytes=256, keep=2)
        for i in range(12):
            sink.emit("fairness_pair_divergent", pair_id=f"p{i}",
                      attribute="drill", cause="decode_error",
                      members={}, js_distance=0.0)
        sink.close()
        T.write_snapshot(reg, tel)
    assert os.path.exists(os.path.join(tel, "events.jsonl.1"))
    assert slo_report([tel]) == 0
    assert fairness_report([tel]) == 0
    out = capsys.readouterr().out
    assert "SLO BURN RATES" in out
    # The divergent-pair table joined events ACROSS generations: pairs
    # whose events now live only in the rotated file still render.
    assert "p0" in out or "pair" in out.lower()

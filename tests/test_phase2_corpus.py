"""Phase-2 non-toy surface: real ML-1M ranking corpus at scale, batched
multi-query listwise evaluation, and parse-failure reporting.

The reference's phase 2 is a single listwise prompt over 20 synthetic docs
(``phase2_cross_model_eval.py:27-43,70-109``); these tests pin the framework's
extensions beyond that — hundreds of real items, N queries in one decode
batch, and explicit failure rates instead of silent identity fallbacks.
"""

import numpy as np
import pytest

from fairness_llm_tpu.config import Config
from fairness_llm_tpu.data import movielens_ranking_corpus, synthetic_movielens
from fairness_llm_tpu.data.ranking import GROUP_A_LABEL, GROUP_B_LABEL, GENRE_CLASS_A, GENRE_CLASS_B
from fairness_llm_tpu.pipeline import SimulatedRecommender, run_phase2
from fairness_llm_tpu.pipeline.parsing import (
    parse_pairwise_answer_full,
    parse_ranking_indices_with_count,
)
from fairness_llm_tpu.pipeline.phase2 import (
    build_corpus,
    listwise_evaluation_batch,
    make_queries,
)


@pytest.fixture()
def ml_data():
    return synthetic_movielens(num_movies=300, num_users=120, ratings_per_user=50, seed=7)


def test_movielens_corpus_shape_and_determinism(ml_data):
    items = movielens_ranking_corpus(ml_data, num_items=100, seed=3, min_ratings=5)
    again = movielens_ranking_corpus(ml_data, num_items=100, seed=3, min_ratings=5)
    assert items == again
    assert len(items) == 100
    assert len({it.id for it in items}) == 100
    for it in items:
        assert 0.3 <= it.relevance <= 1.0
        assert it.protected_attribute in (GROUP_A_LABEL, GROUP_B_LABEL)
        assert it.genres  # real corpus items carry their genres


def test_movielens_corpus_popularity_order(ml_data):
    """Selection is most-rated-first: every chosen movie has >= as many
    ratings as any unchosen eligible movie."""
    items = movielens_ranking_corpus(ml_data, num_items=50, seed=3, min_ratings=5)
    counts = np.bincount(ml_data.rating_movie_ids, minlength=int(ml_data.movie_ids.max()) + 1)
    chosen = {it.id for it in items}
    min_chosen = min(int(counts[i]) for i in chosen)
    unchosen_eligible = [
        int(counts[mid]) for mid in ml_data.movie_ids
        if int(mid) not in chosen and counts[mid] >= 5
    ]
    assert all(c <= min_chosen for c in unchosen_eligible)


def test_genre_group_derivation(ml_data):
    """A movie whose genres are all in one class must land in that class."""
    items = movielens_ranking_corpus(ml_data, num_items=200, seed=3, min_ratings=1)
    a_only = [it for it in items if it.genres and all(g in GENRE_CLASS_A for g in it.genres)]
    b_only = [it for it in items if it.genres and all(g in GENRE_CLASS_B for g in it.genres)]
    assert a_only and b_only  # synthetic genre pool guarantees both occur
    assert all(it.protected_attribute == GROUP_A_LABEL for it in a_only)
    assert all(it.protected_attribute == GROUP_B_LABEL for it in b_only)


def test_parse_ranking_indices_with_count():
    order, parsed = parse_ranking_indices_with_count("3, 1, 2", 5)
    assert order[:3] == [2, 0, 1] and parsed == 3
    order, parsed = parse_ranking_indices_with_count("no numbers here", 4)
    assert parsed == 0 and order == [0, 1, 2, 3]  # identity fallback
    # out-of-range and duplicate indices don't count as parsed
    _, parsed = parse_ranking_indices_with_count("9, 9, 1, 1", 4)
    assert parsed == 1


def test_pairwise_answer_parsed_flag():
    assert parse_pairwise_answer_full("A") == ("A", True)
    assert parse_pairwise_answer_full("Answer: B") == ("B", True)
    assert parse_pairwise_answer_full("both A and B are fine") == ("tie", True)
    assert parse_pairwise_answer_full("I cannot decide") == ("tie", False)


def test_make_queries_genre_and_topic():
    data = synthetic_movielens(num_movies=100, seed=5)
    ml_items = movielens_ranking_corpus(data, num_items=40, seed=5, min_ratings=1)
    qs = make_queries(ml_items, 4)
    assert qs[0] is None and len(qs) == 4
    assert all("movies" in q for q in qs[1:])
    from fairness_llm_tpu.data import create_synthetic_ranking_data

    syn = create_synthetic_ranking_data(20, seed=1)
    qs = make_queries(syn, 3)
    assert qs[0] is None and len(qs) == 3
    assert all("topic" in q for q in qs[1:])


def test_make_queries_never_duplicates():
    """Identical query strings would double-count identical rankings in the
    averaged metrics — the pool must cap rather than repeat."""
    from fairness_llm_tpu.data import create_synthetic_ranking_data

    syn = create_synthetic_ranking_data(20, seed=1)  # 5 topics x 3 templates
    qs = make_queries(syn, 50)
    assert len(qs) == len(set(qs))
    assert len(qs) == 16  # None + 15 distinct, capped below 50


def test_listwise_batch_multi_query(ml_data):
    items = movielens_ranking_corpus(ml_data, num_items=30, seed=3, min_ratings=5)
    backend = SimulatedRecommender([it.text for it in items], seed=11)
    queries = make_queries(items, 3)
    rankings, parsed = listwise_evaluation_batch(backend, items, queries, seed=11)
    assert len(rankings) == 3 and len(parsed) == 3
    ids = {it.id for it in items}
    for r in rankings:
        assert set(r) == ids  # every query yields a full permutation
    # distinct queries draw distinct simulated rankings
    assert rankings[0] != rankings[1] or rankings[1] != rankings[2]


def test_run_phase2_movielens_at_scale(tmp_path):
    """Hundreds of real items, multiple queries, one simulated model — the
    scale the reference's 20-doc corpus never reaches."""
    data_dir = "/nonexistent"  # synthetic ML fallback inside load_movielens
    config = Config(results_dir=str(tmp_path / "r"), data_dir=data_dir)
    res = run_phase2(
        config, models=["simulated"], corpus="movielens",
        num_items=200, num_queries=4, num_comparisons=40,
    )
    meta = res["metadata"]
    assert meta["corpus"] == "movielens" and meta["num_queries"] == 4
    assert meta["num_items"] == 200
    mr = res["model_results"]["simulated"]
    assert mr["listwise"]["num_queries"] == 4
    assert len(mr["listwise"]["per_query"]) == 4
    assert 0.0 < mr["listwise"]["exposure_ratio"] <= 1.0
    pf = mr["parse_failures"]
    assert pf["listwise_failure_rate"] == 0.0  # simulator always ranks
    assert pf["listwise_mean_fraction_parsed"] == 1.0
    assert 0.0 <= pf["pairwise_unparsed_rate"] <= 1.0
    # groups present in exposure breakdown
    assert set(mr["listwise"]["group_exposure"]) <= {GROUP_A_LABEL, GROUP_B_LABEL}


def test_parse_failures_surface_real_failures(tmp_path):
    """A backend that answers garbage must be reported as failing, while the
    pipeline still completes with identity fallbacks."""

    class Garbage:
        name = "garbage"

        def generate(self, prompts, settings=None, seed=0, keys=None, prefix_ids=None):
            return ["no usable answer"] * len(prompts)

    config = Config(results_dir=str(tmp_path / "r"), data_dir="/nonexistent")
    res = run_phase2(
        config, models=["garbage"], backends={"garbage": Garbage()},
        num_items=10, num_queries=2, num_comparisons=5, save=False,
    )
    pf = res["model_results"]["garbage"]["parse_failures"]
    assert pf["listwise_failure_rate"] == 1.0
    assert pf["listwise_mean_fraction_parsed"] == 0.0
    assert pf["pairwise_unparsed_rate"] == 1.0


def test_cross_model_comparison_detects_bias(tmp_path):
    """The point of phase 2: models with different ranking-bias levels must
    be distinguishable. simulated-fair vs simulated-biased on the same corpus
    -> the biased variant scores a worse exposure ratio, and the preferred
    group's exposure share grows with bias."""
    config = Config(results_dir=str(tmp_path / "r"), data_dir="/nonexistent")
    res = run_phase2(
        config, models=["simulated-fair", "simulated-biased"], corpus="movielens",
        num_items=80, num_queries=2, num_comparisons=30, save=False,
    )
    mf = res["comparison"]["model_fairness"]
    fair_lw = mf["simulated-fair"]["listwise_fairness"]
    biased_lw = mf["simulated-biased"]["listwise_fairness"]
    assert biased_lw < fair_lw, (fair_lw, biased_lw)
    assert mf["simulated-biased"]["average_fairness"] < mf["simulated-fair"]["average_fairness"]
    # the biased ranker's pairwise preference ratio skews toward one group
    pr = res["model_results"]["simulated-biased"]["pairwise"]["preference_ratio"]
    assert max(pr.values()) - min(pr.values()) > 0.2


def test_simulated_group_bias_is_monotone(tmp_path):
    """Exposure ratio must degrade as the simulator's bias knob grows."""
    from fairness_llm_tpu.pipeline.phase2 import evaluate_model

    data = synthetic_movielens(num_movies=200, seed=7)
    items = movielens_ranking_corpus(data, num_items=60, seed=7, min_ratings=1)
    ers = []
    for bias in (0.0, 0.5, 1.5):
        backend = SimulatedRecommender(
            [it.text for it in items], seed=3, bias=bias,
            catalog_groups=[it.protected_attribute for it in items],
        )
        res = evaluate_model(backend, items, num_comparisons=10, seed=3)
        ers.append(res["listwise"]["exposure_ratio"])
    assert ers[0] > ers[1] > ers[2], ers


def test_build_corpus_rejects_unknown(tmp_path):
    config = Config(results_dir=str(tmp_path), data_dir="/nonexistent")
    with pytest.raises(ValueError):
        build_corpus(config, "nope", 10)

"""Fairness-observability tests (telemetry/fairness.py, ISSUE 9).

Covers the three instruments — streaming group accumulators (end-of-run
equality with the offline metrics), the counterfactual pair watch
(join rules, divergence verdicts, serving-event attribution), and the
serving-neutrality audit (disparity gauges + alert machinery) — plus the
edge cases the ISSUE names: empty-group NaN discipline, single-member
pairs that never join, window aging, and label isolation across
attributes. Serving-side tests run the real ContinuousScheduler on the
tiny CPU engine; journal tests pin the study-tag persistence contract.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from fairness_llm_tpu.config import ModelSettings, ServingConfig
from fairness_llm_tpu.metrics.fairness import (
    demographic_parity,
    individual_fairness,
)
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.runtime.engine import DecodeEngine
from fairness_llm_tpu.serving import ContinuousScheduler, Request
from fairness_llm_tpu.telemetry import use_registry, write_snapshot
from fairness_llm_tpu.telemetry.fairness import (
    FairnessMonitor,
    group_exposure,
    publish_offline_reference,
    render_fairness_report,
    use_fairness_monitor,
)
from fairness_llm_tpu.utils.failures import ScriptedFaultInjector

GREEDY = ModelSettings(temperature=0.0, max_tokens=8)
SCFG = ServingConfig(
    enabled=True, num_slots=2, queue_capacity=64,
    max_prompt_len=192, max_new_tokens=32, decode_chunk=4,
)


@pytest.fixture(scope="module")
def engine():
    return DecodeEngine(get_model_config("tiny-test"), seed=0)


RECS = {
    "a0": ["X", "Y", "Z"], "a1": ["X", "Q"],
    "b0": ["Y", "Z"], "b1": ["W", "X", "Y", "Q"],
}
GROUPS = {"a0": "g1", "a1": "g1", "b0": "g2", "b1": "g2"}
PAIRS = [("a0", "b0"), ("a1", "b1")]


def _feed_study(mon, recs=RECS, groups=GROUPS, pairs=PAIRS,
                errors=()):
    mon.begin_study()
    for k, g in groups.items():
        mon.register_request(k, {"gender": g})
    for i, (a, b) in enumerate(pairs):
        mon.register_pair(f"p{i}", a, b, "gender")
    for k, r in recs.items():
        mon.observe_output(k, r, error=(k in errors))
    mon.refresh()


# -- streaming accumulators vs offline metrics --------------------------------


def test_streaming_matches_offline():
    with use_registry() as reg, use_fairness_monitor() as mon:
        _feed_study(mon)
        by_group = {"g1": [RECS["a0"], RECS["a1"]],
                    "g2": [RECS["b0"], RECS["b1"]]}
        off_dp, _ = demographic_parity(by_group)
        off_if, sims = individual_fairness(PAIRS, RECS)
        off_ex, _ = group_exposure(by_group)
        live = lambda n, **lb: reg.read_value(n, component="fairness", **lb)
        assert live("fairness_dp", attribute="gender",
                    window="run") == pytest.approx(off_dp, abs=1e-6)
        assert live("fairness_if", attribute="all",
                    window="run") == pytest.approx(off_if, abs=1e-6)
        assert live("fairness_exposure_ratio", attribute="gender",
                    window="run") == pytest.approx(off_ex, abs=1e-6)
        assert len(sims) == mon.pairs_joined == 2


def test_observe_output_is_idempotent():
    """The resume-backfill contract: re-offering a streamed key no-ops, so
    the accumulators never double-count."""
    with use_registry() as reg, use_fairness_monitor() as mon:
        _feed_study(mon)
        before = reg.read_value("fairness_dp", component="fairness",
                                attribute="gender", window="run")
        for k, r in RECS.items():
            mon.observe_output(k, r)  # second offer
        mon.refresh()
        after = reg.read_value("fairness_dp", component="fairness",
                               attribute="gender", window="run")
        assert after == before
        assert mon.pairs_joined == 2  # pairs evaluate once


def test_empty_group_nan_discipline():
    """Empty demographic groups must never surface as NaN (the PR-5
    allow_nan=False contract): DP over one live group is vacuously 1.0,
    exposure excludes empty groups, IF with no joined pairs is 0.0."""
    with use_registry() as reg, use_fairness_monitor() as mon:
        mon.begin_study()
        mon.register_request("a0", {"gender": "g1"})
        mon.register_request("b0", {"gender": "g2"})
        mon.observe_output("a0", ["X", "Y"])
        mon.observe_output("b0", [])  # decoded to nothing
        mon.refresh()
        vals = [
            reg.read_value("fairness_dp", component="fairness",
                           attribute="gender", window="run"),
            reg.read_value("fairness_exposure_ratio", component="fairness",
                           attribute="gender", window="run"),
            reg.read_value("fairness_if", component="fairness",
                           attribute="all", window="run", default=0.0),
        ]
        assert all(np.isfinite(v) for v in vals), vals
        # One populated group: no comparable pair -> vacuous parity, and
        # the empty group is excluded from the exposure ratio.
        assert vals[0] == pytest.approx(1.0)
        assert vals[1] == pytest.approx(1.0)


def test_single_member_pair_never_joins():
    """A pair whose second member never reports (shed before any content,
    lost client, never submitted) must stay pending: not joined, excluded
    from the IF mean, never counted divergent."""
    with use_registry(), use_fairness_monitor() as mon:
        mon.begin_study()
        mon.register_request("a0", {"gender": "g1"})
        mon.register_request("b0", {"gender": "g2"})
        mon.register_pair("p0", "a0", "b0", "gender")
        mon.observe_output("a0", ["X"])
        mon.refresh()
        assert mon.pairs_joined == 0
        assert mon.pairs_divergent == 0
        assert mon._if.get("__all__") is None


def test_window_aging():
    """The recent-window gauges age out old observations; the run-window
    gauges keep them."""
    t = [0.0]
    with use_registry() as reg:
        mon = FairnessMonitor(window_s=10.0, clock=lambda: t[0])
        with use_fairness_monitor(mon):
            mon.begin_study()
            for k in ("a0", "a1"):
                mon.register_request(k, {"gender": "g1"})
            for k in ("b0", "b1"):
                mon.register_request(k, {"gender": "g2"})
            # Old epoch: groups differ maximally (disjoint rec sets).
            mon.observe_output("a0", ["X", "Y"])
            mon.observe_output("b0", ["P", "Q"])
            mon.refresh()
            run_0 = reg.read_value("fairness_dp", component="fairness",
                                   attribute="gender", window="run")
            t[0] = 100.0  # far past the window
            # New epoch: groups identical (DP -> 1.0 over recent data).
            mon.observe_output("a1", ["Z", "W"])
            mon.observe_output("b1", ["Z", "W"])
            mon.refresh()
            recent = reg.read_value("fairness_dp", component="fairness",
                                    attribute="gender", window="recent")
            run_1 = reg.read_value("fairness_dp", component="fairness",
                                   attribute="gender", window="run")
            assert recent == pytest.approx(1.0)  # only the identical epoch
            assert run_1 < 1.0  # the run window still sees the disjoint one
            assert run_1 != run_0


def test_label_isolation_across_attributes():
    """Observations fold into their own attribute's instruments only:
    construct data where gender distributions are identical (DP 1.0) but
    age distributions are disjoint (DP well below 1)."""
    with use_registry() as reg, use_fairness_monitor() as mon:
        mon.begin_study()
        tags = {
            "k0": {"gender": "m", "age": "young"},
            "k1": {"gender": "f", "age": "young"},
            "k2": {"gender": "m", "age": "old"},
            "k3": {"gender": "f", "age": "old"},
        }
        recs = {"k0": ["A"], "k1": ["A"], "k2": ["B"], "k3": ["B"]}
        for k, g in tags.items():
            mon.register_request(k, g)
        for k, r in recs.items():
            mon.observe_output(k, r)
        mon.refresh()
        dp_gender = reg.read_value("fairness_dp", component="fairness",
                                   attribute="gender", window="run")
        dp_age = reg.read_value("fairness_dp", component="fairness",
                                attribute="age", window="run")
        # gender groups both hold {A: 1, B: 1}; age groups are disjoint.
        assert dp_gender == pytest.approx(1.0, abs=1e-6)
        assert dp_age < 0.6


# -- serving-side: neutrality audit + pair watch ------------------------------


def _tagged_requests(prompts, tag=""):
    reqs = []
    for i, p in enumerate(prompts):
        for g in ("ga", "gb"):
            reqs.append(Request(prompt=p, id=f"{tag}{g}{i}",
                                settings=GREEDY, group=g, attribute="drill",
                                pair_id=f"{tag}pp{i}"))
    return reqs


def test_fault_free_serving_is_silent(engine):
    prompts = ["the quick brown fox", "hello there friend",
               "one two three", "name five good books"]
    with use_registry() as reg, use_fairness_monitor() as mon:
        mon.min_group_n = 3
        sched = ContinuousScheduler(engine, SCFG, settings=GREEDY)
        results = sched.serve(_tagged_requests(prompts))
        assert all(r.ok for r in results)
        assert mon.pairs_joined == len(prompts)
        assert mon.pairs_divergent == 0
        assert reg.read_value("fairness_alerts_total", component="fairness",
                              attribute="drill",
                              signal="impaired_rate") == 0
        # Neutrality audit populated: per-group outcome counters and
        # latency histograms exist for both groups.
        for g in ("ga", "gb"):
            assert reg.read_value("fairness_requests_total",
                                  component="fairness", attribute="drill",
                                  group=g, outcome="completed") == len(prompts)
            h = reg.peek("fairness_ttft_s", component="fairness",
                         attribute="drill", group=g)
            assert h is not None and h.count == len(prompts)


def test_biased_faults_alert_and_attribute(engine):
    prompts = ["the quick brown fox", "hello there friend",
               "one two three", "name five good books"]
    with use_registry() as reg, use_fairness_monitor() as mon:
        mon.min_group_n = 3
        inj = ScriptedFaultInjector(
            faults={("gb0", "decode"): 2, ("gb1", "decode"): 2},
        )
        sched = ContinuousScheduler(engine, SCFG, settings=GREEDY,
                                    fault_injector=inj)
        results = {r.id: r for r in sched.serve(_tagged_requests(prompts))}
        assert not results["gb0"].ok and not results["gb1"].ok
        assert mon.pairs_divergent >= 2
        assert reg.read_value("fairness_alerts_total", component="fairness",
                              attribute="drill",
                              signal="impaired_rate") >= 1
        assert reg.read_value("fairness_disparity", component="fairness",
                              attribute="drill",
                              signal="impaired_rate") >= 0.25
        # Attribution: the divergent pairs name the failed member's
        # requeue events.
        divergent = {d["pair_id"]: d for d in mon.divergent}
        for pid in ("pp0", "pp1"):
            members = divergent[pid]["members"]
            bad = members[f"gb{pid[-1]}"]
            assert bad["outcome"] == "failed"
            assert any("requeued" in e for e in bad["events"])


def test_identical_pair_content_divergence_counts(engine):
    """Byte-identical pair members that produce different bytes (the
    serving-corruption shape) count divergent with cause=content — while
    different-prompt counterfactual members never do."""
    with use_registry(), use_fairness_monitor() as mon:
        sched = ContinuousScheduler(engine, SCFG, settings=GREEDY)
        # Different prompts, same pair: legitimate counterfactual — the
        # outputs differ but that is measurement, not an incident.
        res = sched.serve([
            Request(prompt="the quick brown fox", id="c0", settings=GREEDY,
                    group="x", attribute="t", pair_id="cf"),
            Request(prompt="hello there friend", id="c1", settings=GREEDY,
                    group="y", attribute="t", pair_id="cf"),
        ])
        assert all(r.ok for r in res)
        assert mon.pairs_joined == 1 and mon.pairs_divergent == 0
        # Identical prompts with divergent row seeds under SAMPLED decode
        # would differ; emulate via direct observe_request with different
        # texts — the monitor sees identical prompts, different bytes.
        mon2 = FairnessMonitor()
        ra = Request(prompt="same", id="i0", group="x", attribute="t",
                     pair_id="ip")
        rb = Request(prompt="same", id="i1", group="y", attribute="t",
                     pair_id="ip")
        with use_registry():
            mon2.observe_request(ra, "completed", text="alpha beta")
            mon2.observe_request(rb, "completed", text="alpha GAMMA")
            assert mon2.pairs_joined == 1
            assert mon2.pairs_divergent == 1
            assert mon2.divergent[0]["cause"] == "content"


def test_latency_disparity_is_gauge_only():
    """Per-group latency ratios are exported but NEVER alert — queue
    position confounds them in a batch sweep."""
    with use_registry() as reg, use_fairness_monitor() as mon:
        mon.min_group_n = 2
        for i in range(4):
            g = "early" if i < 2 else "late"
            req = Request(prompt="p", id=f"l{i}", group=g, attribute="t")
            mon.observe_request(req, "completed", queue_wait_s=0.01 if
                                g == "early" else 10.0, ttft_s=0.02 if
                                g == "early" else 10.0)
        ratio = reg.read_value("fairness_disparity", component="fairness",
                               attribute="t", signal="queue_wait_mean_ratio")
        assert ratio > 100
        assert reg.read_value("fairness_alerts_total", component="fairness",
                              attribute="t",
                              signal="queue_wait_mean_ratio") == 0


def test_duplicate_terminal_keeps_pair_joinable():
    """A duplicate terminal observation for the FIRST member of a
    direct-tagged pair must not destroy the half-registered placeholder —
    the twin still joins the pair."""
    with use_registry():
        mon = FairnessMonitor()
        ra = Request(prompt="same", id="d0", group="x", attribute="t",
                     pair_id="dp")
        rb = Request(prompt="same", id="d1", group="y", attribute="t",
                     pair_id="dp")
        mon.observe_request(ra, "completed", text="w")
        mon.observe_request(ra, "completed", text="w")  # duplicate
        mon.observe_request(rb, "completed", text="w")
        assert mon.pairs_joined == 1
        assert mon.pairs_divergent == 0


# -- journal persistence of study tags ----------------------------------------


def test_journal_persists_study_tags(tmp_path):
    from fairness_llm_tpu.resilience.drain import ServingJournal

    j = ServingJournal(str(tmp_path))
    j.record_submitted(Request(prompt="p", id="r0", group="g1",
                               attribute="gender", pair_id="p0"))
    j.record_submitted(Request(prompt="q", id="r1"))
    j.close()
    reqs = {r.id: r for r in ServingJournal(str(tmp_path)).to_requests()}
    assert reqs["r0"].group == "g1"
    assert reqs["r0"].attribute == "gender"
    assert reqs["r0"].pair_id == "p0"
    assert reqs["r1"].group is None and reqs["r1"].pair_id is None


# -- validator + report surface ------------------------------------------------


def _study_snapshot_dir(tmp_path, perturb_offline=False):
    with use_registry() as reg, use_fairness_monitor() as mon:
        _feed_study(mon)
        by_group = {"g1": [RECS["a0"], RECS["a1"]],
                    "g2": [RECS["b0"], RECS["b1"]]}
        off_dp, _ = demographic_parity(by_group)
        off_if, _ = individual_fairness(PAIRS, RECS)
        off_ex, _ = group_exposure(by_group)
        if perturb_offline:
            off_dp += 0.05  # a real aggregation bug's signature
        publish_offline_reference({"gender": off_dp}, if_score=off_if,
                                  exposure={"gender": off_ex})
        # The gate also wants tagged serving traffic.
        mon.observe_request(
            Request(prompt="p", id="a0", group="g1", attribute="gender"),
            "completed", queue_wait_s=0.01, ttft_s=0.02,
        )
        write_snapshot(reg, str(tmp_path))
    return str(tmp_path)


def test_require_fairness_gate(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "validate_telemetry",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "validate_telemetry.py"),
    )
    vt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(vt)

    good = _study_snapshot_dir(tmp_path / "good")
    assert vt.check(good, require_fairness=True) == 0
    bad = _study_snapshot_dir(tmp_path / "bad", perturb_offline=True)
    assert vt.check(bad, require_fairness=True) == 1


def test_fairness_report_renders(tmp_path):
    path = _study_snapshot_dir(tmp_path)
    with open(os.path.join(path, "telemetry_snapshot.json")) as f:
        snap = json.load(f)
    text = render_fairness_report(snap, events=[{
        "kind": "fairness_pair_divergent", "pair_id": "p9",
        "attribute": "gender", "cause": "failed", "js_distance": 1.0,
        "members": {"x": {"outcome": "failed",
                          "events": ["requeued:device"]}},
    }])
    assert "FAIRNESS SIGNALS" in text
    assert "dp" in text and "gender" in text
    assert "p9" in text and "requeued:device" in text
    # Empty snapshot renders a hint, not a traceback.
    assert "no fairness instruments" in render_fairness_report(
        {"counters": [], "gauges": []})


def test_serving_backend_stamps_tags(engine):
    """ServingBackend.generate stamps registered study tags onto its sweep
    requests — verified through the journal ledger the scheduler writes."""
    from fairness_llm_tpu.serving.backend import ServingBackend

    with use_registry(), use_fairness_monitor() as mon:
        mon.begin_study()
        mon.register_request("user_0", {"gender": "m"})
        mon.register_pair("pr0", "user_0", "user_1", "gender")
        backend = ServingBackend(engine, SCFG)
        texts = backend.generate(["the quick brown fox"], GREEDY,
                                 keys=["user_0"])
        assert texts[0]
        # The terminal hook saw the tagged request: audit counters exist.
        reg_val = mon._reg().read_value(
            "fairness_requests_total", component="fairness",
            attribute="gender", group="m", outcome="completed")
        assert reg_val == 1

"""backend_for with a real on-disk checkpoint: the full weights_dir path
(save HF layout -> resolve -> load -> decode) plus the no-weights refusal,
and the reference-parity measure_* wrappers + RateLimiter."""

import time

import jax
import numpy as np
import pytest

from fairness_llm_tpu.config import Config, ModelSettings
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.models.transformer import init_params
from fairness_llm_tpu.pipeline.backends import EngineBackend, backend_for
from fairness_llm_tpu.runtime.engine import DecodeEngine
from fairness_llm_tpu.runtime.weights import save_checkpoint_hf
from fairness_llm_tpu.utils import RateLimiter


def test_backend_for_loads_weights_dir(tmp_path):
    cfg_model = get_model_config("tiny-test")
    params = init_params(cfg_model, jax.random.key(0))
    save_checkpoint_hf(cfg_model, params, str(tmp_path / "tiny-test"))

    config = Config(weights_dir=str(tmp_path))
    backend = backend_for("tiny-test", config)
    assert isinstance(backend, EngineBackend)
    texts = backend.generate(["hello"], ModelSettings(temperature=0.0, max_tokens=4))
    assert len(texts) == 1

    # loaded weights must reproduce the original params' greedy output
    direct = DecodeEngine(cfg_model, params=params)
    expect = direct.generate(["hello"], ModelSettings(temperature=0.0, max_tokens=4))
    got = backend.engine.generate(["hello"], ModelSettings(temperature=0.0, max_tokens=4))
    np.testing.assert_array_equal(expect.tokens, got.tokens)


def test_backend_for_refuses_without_weights(tmp_path):
    config = Config(weights_dir=str(tmp_path))  # empty dir
    with pytest.raises(FileNotFoundError):
        backend_for("tiny-test", config)
    # explicit opt-in for smoke runs
    backend = backend_for("tiny-test", config, allow_random=True)
    assert isinstance(backend, EngineBackend)


def test_measure_wrappers():
    from fairness_llm_tpu.data.profiles import Profile
    from fairness_llm_tpu.pipeline.phase1 import (
        measure_demographic_parity,
        measure_equal_opportunity,
        measure_individual_fairness,
    )

    groups = {"m": [["A", "B"]], "f": [["A", "C"]]}
    dp, _ = measure_demographic_parity(groups)
    assert 0 < dp < 1

    profiles = [
        Profile("p0", "m", "18-24", "x", [], []),
        Profile("p1", "f", "18-24", "x", [], []),
    ]
    if_score, sims = measure_individual_fairness(
        profiles, {"p0": ["A", "B"], "p1": ["A", "C"]}
    )
    assert if_score == pytest.approx(1 / 3)

    # canonicalization: year-suffixed outputs still match qualified titles
    eo, rates = measure_equal_opportunity(
        {"m": [["The Matrix (1999)"]], "f": [["Alien (1979)"]]},
        {"Matrix, The", "Alien"},
    )
    assert rates["m"] == 1.0 and rates["f"] == 1.0


def test_rate_limiter_blocks_third_call():
    rl = RateLimiter(calls_per_minute=2, window_seconds=0.2)
    assert rl.wait_if_needed() == 0.0
    assert rl.wait_if_needed() == 0.0
    t0 = time.monotonic()
    slept = rl.wait_if_needed()
    assert slept > 0.0 and time.monotonic() - t0 >= 0.1

"""Continuous-batching serving subsystem tests.

The correctness contract (ISSUE 2) is *token-for-token greedy parity with
``DecodeEngine.generate`` alone*: a request admitted into any slot — fresh
or recycled, alone or sharing the pool with unrelated rows — must decode the
same tokens the static engine decodes for that prompt by itself. On top of
that: allocator invariants under churn, queue backpressure + rate-limited
admission, scheduler eviction/backfill, fault requeue-then-fail containment,
and deadline expiry.
"""

import numpy as np
import pytest

from fairness_llm_tpu.config import ModelSettings, ServingConfig
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.runtime.engine import DecodeEngine
from fairness_llm_tpu.serving import (
    AdmissionQueue,
    ContinuousScheduler,
    Request,
    ServingBackend,
    SlotPool,
    SlotState,
)
from fairness_llm_tpu.utils.failures import DecodeFault, ScriptedFaultInjector
from fairness_llm_tpu.utils.profiling import ServingStats
from fairness_llm_tpu.utils.ratelimit import RateLimiter


def greedy(m: int) -> ModelSettings:
    return ModelSettings(temperature=0.0, max_tokens=m)


# max_prompt_len bounds the serving prompt budget; parity with the engine is
# guaranteed for prompts within it (tiny-test max_seq_len=256, cap=32 ->
# budget 192), so the mixed prompt set below stays under 192 tokens.
SCFG = ServingConfig(
    enabled=True, num_slots=2, queue_capacity=64,
    max_prompt_len=192, max_new_tokens=32, decode_chunk=4,
)


@pytest.fixture(scope="module")
def engine():
    return DecodeEngine(get_model_config("tiny-test"), seed=0)


def _req(prompt, m=8, **kw):
    return Request(prompt=prompt, settings=greedy(m), **kw)


# -- RateLimiter.try_acquire -------------------------------------------------


def test_try_acquire_non_blocking():
    rl = RateLimiter(calls_per_minute=2, window_seconds=60.0)
    assert rl.try_acquire()
    assert rl.try_acquire()
    assert not rl.try_acquire()  # quota spent, no sleep
    assert len(rl._times) == 2  # the rejected call was NOT recorded


def test_try_acquire_window_expiry():
    rl = RateLimiter(calls_per_minute=1, window_seconds=0.01)
    assert rl.try_acquire()
    assert not rl.try_acquire()
    import time

    time.sleep(0.02)
    assert rl.try_acquire()  # old call aged out of the window


def test_wait_if_needed_semantics_unchanged():
    rl = RateLimiter(calls_per_minute=3, window_seconds=60.0)
    # under quota: no sleep, call recorded
    assert rl.wait_if_needed() == 0.0
    assert len(rl._times) == 1
    # mixing styles shares the ledger
    assert rl.try_acquire() and rl.try_acquire()
    assert not rl.try_acquire()


# -- slot pool ---------------------------------------------------------------


def _state(i=0):
    return SlotState(request=Request(prompt=f"p{i}"), base=64, real_len=10)


def test_slot_pool_alloc_release_order():
    pool = SlotPool(3)
    slots = [pool.alloc(_state(i)) for i in range(3)]
    assert slots == [0, 1, 2]
    assert pool.alloc(_state()) is None  # exhausted
    pool.release(1)
    assert pool.occupancy == 2 and pool.free_count == 1
    assert pool.alloc(_state()) == 1  # lowest free slot first


def test_slot_pool_double_release_raises():
    pool = SlotPool(2)
    s = pool.alloc(_state())
    pool.release(s)
    with pytest.raises(KeyError):
        pool.release(s)


def test_slot_pool_invalidation_cancelled_on_reuse():
    """The recycled-slot regression: a slot released and REALLOCATED before
    the invalidation flush must drop its pending invalidation — a deferred
    flush would wipe the new tenant's freshly prefilled row."""
    pool = SlotPool(2)
    s = pool.alloc(_state())
    pool.release(s)
    assert pool.pending_invalidation == [s]
    assert pool.alloc(_state(1)) == s
    assert pool.pending_invalidation == []
    pool.release(s)
    assert pool.take_invalidations() == [s]
    assert pool.pending_invalidation == []


def test_slot_pool_churn_invariants():
    rng = np.random.default_rng(0)
    pool = SlotPool(4)
    live = set()
    for it in range(200):
        if live and (len(live) == 4 or rng.random() < 0.5):
            slot = rng.choice(sorted(live))
            pool.release(int(slot))
            live.discard(int(slot))
        else:
            slot = pool.alloc(_state(it))
            assert slot is not None and slot not in live
            live.add(slot)
        assert pool.occupancy == len(live)
        assert pool.free_count == 4 - len(live)
        assert sorted(pool.live_slots()) == sorted(live)
        # released-but-unreused slots are exactly the pending invalidations
        assert set(pool.pending_invalidation).isdisjoint(live)


# -- admission queue ---------------------------------------------------------


def test_queue_backpressure():
    q = AdmissionQueue(capacity=2)
    assert q.submit(Request(prompt="a"))
    assert q.submit(Request(prompt="b"))
    assert not q.submit(Request(prompt="c"))  # full -> rejected
    assert q.rejected == 1
    assert len(q.pop(1)) == 1
    assert q.submit(Request(prompt="c"))  # space freed


def test_queue_rate_limited_admission():
    q = AdmissionQueue(capacity=10, rate_limiter=RateLimiter(2, 60.0))
    assert q.submit(Request(prompt="a"))
    assert q.submit(Request(prompt="b"))
    assert not q.submit(Request(prompt="c"))  # quota, not capacity
    assert len(q) == 2 and q.rejected == 1


def test_queue_requeue_bypasses_limits_and_goes_first():
    q = AdmissionQueue(capacity=1, rate_limiter=RateLimiter(1, 60.0))
    assert q.submit(Request(prompt="a"))
    r = Request(prompt="retry")
    q.requeue(r)  # full AND over quota — still accepted, at the front
    assert q.pop(1)[0] is r


def test_queue_drain_expired():
    q = AdmissionQueue(capacity=4)
    fresh = Request(prompt="fresh")
    stale = Request(prompt="stale", deadline_s=0.0)
    q.submit(fresh)
    q.submit(stale)
    expired = q.drain_expired()
    assert [r.prompt for r in expired] == ["stale"]
    assert [r.prompt for r in q.pop(4)] == ["fresh"]


# -- scheduler: parity -------------------------------------------------------


MIXED_PROMPTS = [
    "the quick brown fox",
    "hi",
    "abc abc abc abc abc abc",
    # ~181 tokens: lands in a bigger prompt bucket than the others while
    # staying inside the 192-token serving budget (see SCFG note above)
    "a long prompt that shifts padding " * 5 + "and lands in a big bucket",
    "zz",
    "recommend ten films please",
    "one two three one two three",
]


def test_server_matches_engine_greedy_mixed_lengths(engine):
    """The headline contract: every request through the 2-slot server (so
    most rows ride recycled slots) decodes the engine's exact greedy tokens,
    including per-request decode budgets the static path can't express."""
    sched = ContinuousScheduler(engine, SCFG, settings=greedy(16))
    reqs = [
        _req(p, m=8 + 2 * (i % 5)) for i, p in enumerate(MIXED_PROMPTS)
    ]
    results = sched.serve(reqs)
    for req, res in zip(reqs, results):
        assert res.ok, res.error
        ref = engine.generate([req.prompt], req.settings)
        n = len(res.tokens)
        assert n > 0
        np.testing.assert_array_equal(res.tokens, ref.tokens[0][:n])
        # nothing real was dropped: the engine row past n is pad-only
        pad = engine.tokenizer.pad_id
        assert np.all(ref.tokens[0][n:] == pad)
        assert res.text == ref.texts[0]


def test_server_parity_with_early_eos(engine):
    """EOS mid-decode must evict the row exactly like the engine records it
    (EOS token kept, nothing after). Random weights rarely emit the real
    EOS, so re-tokenize with an eos id pulled from the greedy stream —
    the test_speculative idiom."""
    from fairness_llm_tpu.models.tokenizer import ByteTokenizer

    plain = engine.generate([MIXED_PROMPTS[0]], greedy(16))
    eos = int(plain.tokens[0][5])
    tok = ByteTokenizer(512)
    tok.eos_id = eos
    eng2 = DecodeEngine(
        get_model_config("tiny-test"), params=engine.params, tokenizer=tok
    )
    sched = ContinuousScheduler(eng2, SCFG, settings=greedy(16))
    res = sched.serve([_req(MIXED_PROMPTS[0], m=16)])[0]
    ref = eng2.generate([MIXED_PROMPTS[0]], greedy(16))
    assert res.finish_reason == "eos"
    assert res.tokens[-1] == eos
    np.testing.assert_array_equal(res.tokens, ref.tokens[0][: len(res.tokens)])
    assert np.all(ref.tokens[0][len(res.tokens):] == tok.pad_id)


def test_server_parity_independent_of_pool_composition(engine):
    """A request's tokens must not depend on what shares the pool: serve the
    same prompt alone and jammed between unrelated requests."""
    target = MIXED_PROMPTS[2]
    alone = ContinuousScheduler(engine, SCFG, settings=greedy(12)).serve(
        [_req(target, m=12)]
    )[0]
    crowd_reqs = [_req(p, m=6) for p in MIXED_PROMPTS[:2]] + [
        _req(target, m=12)
    ] + [_req(p, m=10) for p in MIXED_PROMPTS[3:]]
    crowded = ContinuousScheduler(engine, SCFG, settings=greedy(12)).serve(
        crowd_reqs
    )[2]
    np.testing.assert_array_equal(alone.tokens, crowded.tokens)


# -- scheduler: eviction + backfill ------------------------------------------


def test_scheduler_eviction_and_backfill(engine):
    """5 requests through 2 slots: every slot eviction must backfill from
    the queue (admitted == 5 with only 2 slots), and per-request budgets
    must bound each row individually."""
    sched = ContinuousScheduler(engine, SCFG, settings=greedy(16))
    caps = [4, 8, 12, 4, 8]
    reqs = [_req(p, m=c) for p, c in zip(MIXED_PROMPTS, caps)]
    results = sched.serve(reqs)
    stats = sched.last_stats
    assert all(r.ok for r in results)
    assert [len(r.tokens) for r in results] == caps  # random weights: no EOS
    assert stats.admitted == 5
    assert stats.completed == 5
    # depth is sampled at iteration start, before that iteration's
    # admissions — all 5 queued requests are visible on the first sample
    assert stats.queue_depth_max == 5
    # slot recycling really happened: far fewer steps than serial decode,
    # and the pool is empty at drain
    assert sched.pool.occupancy == 0
    assert stats.decoded_tokens == sum(caps)
    assert stats.decode_steps < sum(caps)  # overlap => fewer steps than serial
    assert stats.occupancy_sum > stats.decode_steps  # >1 live row on average


def test_submit_drain_take_result(engine):
    """The submit()-side API: requests queued directly (not via serve())
    decode on drain() and their Results are claimable exactly once."""
    sched = ContinuousScheduler(engine, SCFG, settings=greedy(8))
    assert sched.submit(_req(MIXED_PROMPTS[0], m=8, id="direct"))
    stats = sched.drain()
    assert stats.completed == 1
    res = sched.take_result("direct")
    assert res is not None and res.ok
    ref = engine.generate([MIXED_PROMPTS[0]], greedy(8))
    np.testing.assert_array_equal(res.tokens, ref.tokens[0][: len(res.tokens)])
    assert sched.take_result("direct") is None  # claimed once
    # a submit()-ed request riding along with a serve() batch is not lost
    assert sched.submit(_req(MIXED_PROMPTS[1], m=4, id="rider"))
    served = sched.serve([_req(MIXED_PROMPTS[2], m=4)])
    assert served[0].ok
    rider = sched.take_result("rider")
    assert rider is not None and rider.ok


def test_public_submit_rejections_reach_stats(engine):
    """Backpressure refusals from submit() made between drains must show in
    the next drain's stats.rejected (once each, not re-counted later)."""
    import dataclasses

    cfg = dataclasses.replace(SCFG, queue_capacity=1)
    sched = ContinuousScheduler(engine, cfg, settings=greedy(4))
    assert sched.submit(_req("a", m=4, id="a"))
    assert not sched.submit(_req("b", m=4, id="b"))  # queue full -> rejected
    stats = sched.drain()
    assert stats.rejected == 1 and stats.completed == 1
    assert sched.drain().rejected == 0  # delta, not cumulative


def test_serve_rejects_duplicate_request_ids(engine):
    sched = ContinuousScheduler(engine, SCFG, settings=greedy(4))
    with pytest.raises(ValueError, match="duplicate request ids"):
        sched.serve([_req("a", m=4, id="x"), _req("b", m=4, id="x")])


def test_scheduler_reusable_across_serves(engine):
    sched = ContinuousScheduler(engine, SCFG, settings=greedy(8))
    first = sched.serve([_req(MIXED_PROMPTS[0])])
    second = sched.serve([_req(MIXED_PROMPTS[0])])
    np.testing.assert_array_equal(first[0].tokens, second[0].tokens)
    assert sched.last_stats.admitted == 1  # per-serve stats, not cumulative


def test_scheduler_rejects_mismatched_sampler(engine):
    sched = ContinuousScheduler(engine, SCFG, settings=greedy(8))
    with pytest.raises(ValueError, match="sampler"):
        sched.submit(
            Request(prompt="x", settings=ModelSettings(temperature=0.9))
        )
    # serve() must apply the same guard (it feeds the queue directly) —
    # otherwise a mismatched request silently decodes at the compiled
    # temperature, the exact failure the guard exists for
    with pytest.raises(ValueError, match="sampler"):
        sched.serve([Request(prompt="x", settings=ModelSettings(temperature=0.9))])


def test_deadline_clock_starts_at_intake(engine):
    """A Request built long before serve() must not age toward its deadline
    while sitting on the host — the clock restarts at scheduler intake."""
    import time

    sched = ContinuousScheduler(engine, SCFG, settings=greedy(4))
    req = _req("hello", m=4, deadline_s=60.0)
    req.submitted_at -= 120.0  # simulate construction 2 minutes ago
    res = sched.serve([req])[0]
    assert res.ok and res.finish_reason == "length"
    assert 0.0 <= res.latency_s < 60.0


def test_rate_limited_serve_completes_without_phantom_rejections(engine):
    """Internal pending-queue retries under an admission rate limit are not
    'rejections': every request completes and stats.rejected stays 0."""
    import dataclasses

    cfg = dataclasses.replace(SCFG, admission_per_minute=2)
    # 50 ms quota window so the serve loop's retries actually clear
    sched = ContinuousScheduler(engine, cfg, settings=greedy(4))
    sched.queue.rate_limiter.window = 0.05
    res = sched.serve([_req(p, m=4) for p in MIXED_PROMPTS[:4]])
    assert all(r.ok for r in res)
    assert sched.last_stats.rejected == 0
    assert sched.last_stats.completed == 4


def test_scheduler_deadline_in_queue_and_mid_decode(engine):
    sched = ContinuousScheduler(engine, SCFG, settings=greedy(8))
    res = sched.serve([_req("hello", m=8, deadline_s=0.0)])[0]
    assert not res.ok and res.finish_reason == "deadline"
    assert sched.last_stats.expired == 1
    # a generous deadline completes normally
    res = sched.serve([_req("hello", m=8, deadline_s=300.0)])[0]
    assert res.ok and res.finish_reason == "length"


def test_deadline_expiry_races_requeue_window(engine):
    """A request whose deadline passes while it sits in the requeue-after-
    fault window must terminate ``expired`` — never spend a prefill on a
    second attempt. The racy window is a PREFILL fault: the requeue lands
    at the queue front while ``_admit`` is still looping, so the very next
    pop would re-admit it with no deadline check between (the queue's
    expiry sweep only runs at iteration start)."""
    reqs = {}

    class ExpireOnFault(ScriptedFaultInjector):
        def maybe_fail(self, request_id, stage):
            try:
                super().maybe_fail(request_id, stage)
            except DecodeFault:
                # Deterministic race: the deadline elapses during the fault
                # handling, before the requeue is popped again.
                reqs[request_id].deadline_s = 0.0
                raise

    inj = ExpireOnFault({("racy", "prefill"): 1})
    sched = ContinuousScheduler(
        engine, SCFG, settings=greedy(8), fault_injector=inj
    )
    r = _req("hello there", m=8, id="racy", deadline_s=300.0)
    reqs[r.id] = r
    res = sched.serve([r, _req("world", m=8, id="ok")])
    by_id = {x.id: x for x in res}
    assert by_id["ok"].ok
    racy = by_id["racy"]
    assert not racy.ok and racy.finish_reason == "deadline"
    assert len(racy.tokens) == 0  # no second decode attempt
    assert inj.fired == [("racy", "prefill")]  # one fault, no re-prefill
    assert sched.last_stats.expired == 1


# -- fault containment -------------------------------------------------------


def test_fault_requeued_once_then_ok(engine):
    inj = ScriptedFaultInjector({("A", "decode"): 1})
    sched = ContinuousScheduler(
        engine, SCFG, settings=greedy(8), fault_injector=inj
    )
    res = sched.serve([
        _req("hello", m=8, id="A"), _req("world", m=8, id="B"),
    ])
    assert all(r.ok for r in res)
    assert sched.last_stats.requeued == 1
    assert res[0].retries == 1
    # the retried request still decodes the engine's exact tokens
    ref = engine.generate(["hello"], greedy(8))
    np.testing.assert_array_equal(res[0].tokens, ref.tokens[0][: len(res[0].tokens)])


def test_fault_twice_fails_without_killing_loop(engine):
    inj = ScriptedFaultInjector({("B", "decode"): 2})
    sched = ContinuousScheduler(
        engine, SCFG, settings=greedy(8), fault_injector=inj
    )
    res = sched.serve([
        _req("hello", m=8, id="A"), _req("world", m=8, id="B"),
        _req("okay", m=8, id="C"),
    ])
    by_id = {r.id: r for r in res}
    assert by_id["A"].ok and by_id["C"].ok
    assert not by_id["B"].ok
    assert by_id["B"].finish_reason == "failed"
    assert "injected" in by_id["B"].error
    # exactly ONE requeue then terminal failure (not retried forever)
    assert sched.last_stats.failed == 1 and sched.last_stats.requeued == 1
    assert by_id["B"].retries == 1


def test_prefill_fault_contained(engine):
    inj = ScriptedFaultInjector({("A", "prefill"): 2})
    sched = ContinuousScheduler(
        engine, SCFG, settings=greedy(8), fault_injector=inj
    )
    res = sched.serve([_req("hello", m=8, id="A"), _req("world", m=8, id="B")])
    by_id = {r.id: r for r in res}
    assert not by_id["A"].ok and by_id["B"].ok


def test_injector_budget_semantics():
    inj = ScriptedFaultInjector({"X": 1})
    with pytest.raises(DecodeFault):
        inj.maybe_fail("X", "decode")
    inj.maybe_fail("X", "decode")  # budget spent: no raise
    inj.maybe_fail("Y", "decode")  # unlisted: no raise
    assert inj.fired == [("X", "decode")]


# -- ServingBackend / pipeline integration -----------------------------------


def test_serving_backend_matches_engine_backend_greedy(engine):
    from fairness_llm_tpu.pipeline.backends import EngineBackend

    prompts = MIXED_PROMPTS[:5]
    keys = [f"profile_{i}" for i in range(5)]
    eb = EngineBackend(engine)
    sb = ServingBackend(engine, SCFG)
    # share_prefix=False engine path == serving path for greedy
    ref = eb.generate(prompts, greedy(8), seed=7, keys=keys)
    got = sb.generate(prompts, greedy(8), seed=7, keys=keys)
    assert got == ref
    assert sb.serve_totals is not None and sb.serve_totals.admitted == 5
    assert sb.last_output.stats["serving"]["completed"] == 5


def test_serving_backend_accumulates_and_resets_totals(engine):
    sb = ServingBackend(engine, SCFG)
    sb.generate(MIXED_PROMPTS[:2], greedy(4), seed=0)
    sb.generate(MIXED_PROMPTS[2:4], greedy(4), seed=0)
    assert sb.serve_totals.admitted == 4  # merged across calls
    sb.serve_totals = None  # the phase-driver reset idiom
    sb.generate(MIXED_PROMPTS[:1], greedy(4), seed=0)
    assert sb.serve_totals.admitted == 1


def test_serving_backend_failed_rows_are_none(engine):
    inj = ScriptedFaultInjector({("k0", "decode"): 2})
    sb = ServingBackend(engine, SCFG, fault_injector=inj)
    out = sb.generate(
        MIXED_PROMPTS[:2], greedy(4), seed=0, keys=["k0", "k1"]
    )
    assert out[0] is None and isinstance(out[1], str)


def test_backend_for_returns_serving_backend(engine):
    import dataclasses

    from fairness_llm_tpu.config import Config
    from fairness_llm_tpu.pipeline import backends as B

    config = dataclasses.replace(
        Config(), serving=ServingConfig(enabled=True, num_slots=2)
    )
    be = B.backend_for("tiny-test", config, allow_random=True)
    assert isinstance(be, ServingBackend)
    config_off = Config()
    be2 = B.backend_for("tiny-test", config_off, allow_random=True)
    assert isinstance(be2, B.EngineBackend)


def test_decode_sweep_through_serving_backend(engine):
    """Phases consume the server through decode_sweep unchanged (protocol
    compatibility incl. failure containment + checkpoint shape)."""
    from fairness_llm_tpu.config import Config
    from fairness_llm_tpu.pipeline.phase1 import decode_sweep

    sb = ServingBackend(engine, SCFG)
    config = Config(decode_batch_size=4, checkpoint_every=0)
    prompts = MIXED_PROMPTS[:4]
    keys = [f"k{i}" for i in range(4)]
    recs = decode_sweep(
        sb, prompts, keys, config, "phase1",
        settings=greedy(4), save_checkpoints=False,
    )
    assert list(recs) == keys
    assert all("raw_response" in v for v in recs.values())


# -- stats -------------------------------------------------------------------


def test_serving_stats_roundtrip_and_merge():
    a = ServingStats(num_slots=8, admitted=3, decode_steps=10,
                     decoded_tokens=25, occupancy_sum=20, queue_depth_max=4,
                     loop_iterations=5, queue_depth_sum=10)
    b = ServingStats(num_slots=8, admitted=2, decode_steps=5,
                     decoded_tokens=10, occupancy_sum=10, queue_depth_max=7,
                     loop_iterations=2, queue_depth_sum=2)
    m = a.merge(b)
    assert m.admitted == 5 and m.decode_steps == 15
    assert m.queue_depth_max == 7  # max, not sum
    assert m.num_slots == 8
    d = m.as_dict()
    assert d["tokens_per_step"] == round(35 / 15, 3)
    assert d["avg_occupancy"] == 2.0
    rt = ServingStats.from_dict(d)  # derived keys dropped on the way in
    assert rt.decoded_tokens == 35 and rt.tokens_per_step == 35 / 15

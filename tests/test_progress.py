"""utils/progress.py: carriage-return bar rendering and TTY gating."""

import io

from fairness_llm_tpu.utils.progress import print_progress


class _Tty(io.StringIO):
    def isatty(self):
        return True


def test_renders_on_tty():
    out = _Tty()
    print_progress(5, 10, prefix="p1 ", width=10, stream=out)
    s = out.getvalue()
    assert s.startswith("\rp1 [")
    assert "#####-----" in s and "5/10" in s
    assert not s.endswith("\n")


def test_newline_on_completion():
    out = _Tty()
    print_progress(10, 10, width=10, stream=out)
    assert out.getvalue().endswith("\n")
    assert "##########" in out.getvalue()


def test_silent_when_not_a_tty():
    out = io.StringIO()
    print_progress(5, 10, stream=out)
    assert out.getvalue() == ""


def test_silent_on_zero_total_and_clamps():
    out = _Tty()
    print_progress(5, 0, stream=out)
    assert out.getvalue() == ""
    print_progress(15, 10, width=10, stream=out)  # clamps past-total
    assert "##########" in out.getvalue()

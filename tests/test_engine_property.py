"""Property-based decode-engine invariants (hypothesis over random prompt
sets; the deterministic versions of these live in tests/test_engine.py).

Pinned properties:
- batch composition independence: a prompt's greedy decode doesn't depend on
  which other prompts share its batch (left-pad masking + per-row positions)
- prefix-cache equivalence: share_prefix greedy-matches plain decode for any
  prompt set sharing a common prefix
- row-seed stability: with keys, sampled text per prompt is independent of
  batch order
"""

import zlib

import pytest

pytest.importorskip("hypothesis")  # property tests skip where hypothesis isn't baked in
from hypothesis import given, settings
from hypothesis import strategies as st

from fairness_llm_tpu.config import ModelSettings
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.runtime.engine import DecodeEngine

GREEDY = ModelSettings(temperature=0.0, max_tokens=8)
SAMPLED = ModelSettings(temperature=0.9, max_tokens=8)

# Printable-ish ASCII prompt pieces; engine is byte-level so content shape
# matters, not meaning. Sizes kept small: every distinct bucketed shape
# compiles once (~seconds on CPU).
piece = st.text(
    alphabet=st.characters(min_codepoint=32, max_codepoint=126),
    min_size=1, max_size=12,
)


@pytest.fixture(scope="module")
def engine():
    return DecodeEngine(get_model_config("tiny-test"), seed=0)


@settings(max_examples=10, deadline=None)
@given(st.lists(piece, min_size=2, max_size=4, unique=True))
def test_greedy_independent_of_batchmates(engine, prompts):
    together = engine.generate(prompts, GREEDY, seed=0).texts
    alone = [engine.generate([p], GREEDY, seed=0).texts[0] for p in prompts]
    assert together == alone


@settings(max_examples=10, deadline=None)
@given(st.lists(piece, min_size=2, max_size=4, unique=True), piece)
def test_shared_prefix_greedy_equivalence(engine, tails, common):
    # Build prompts sharing a >=64-token common prefix (byte tokenizer:
    # 1 token per byte), differing only in their tails.
    prefix = (common * 80)[:80]
    prompts = [prefix + t for t in tails]
    plain = engine.generate(prompts, GREEDY, seed=0, share_prefix=False).texts
    shared = engine.generate(prompts, GREEDY, seed=0, share_prefix=True).texts
    assert plain == shared


@settings(max_examples=8, deadline=None)
@given(st.lists(piece, min_size=3, max_size=4, unique=True))
def test_row_seeds_order_independent(engine, prompts):
    keys = [f"k{i}" for i in range(len(prompts))]
    # crc32, not hash(): PYTHONHASHSEED would make a recorded hypothesis
    # failure unreproducible across processes
    seed_of = lambda k: zlib.crc32(k.encode()) & 0xFFFF  # noqa: E731
    fwd = engine.generate(prompts, SAMPLED, seed=3,
                          row_seeds=[seed_of(k) for k in keys]).texts
    rev = engine.generate(prompts[::-1], SAMPLED, seed=3,
                          row_seeds=[seed_of(k) for k in keys[::-1]]).texts
    assert fwd == rev[::-1]

"""Load-replay + elastic-fleet tests (serving/replay.py,
serving/autoscaler.py, and the ISSUE-11 satellites).

Contracts under test:

- trace generation is seeded and deterministic (same config -> the same
  JSONL bytes), shaped (burst windows are denser, sessions heavy-tailed
  and capped, QoS mixed), and round-trips through write/read;
- the replay clock compresses an injectable base clock into trace time;
- the injectable clocks threaded through ``RateLimiter``,
  ``ClassedAdmissionQueue`` aging/expiry, and ``DeadlineEstimator`` age
  deterministically at simulated-hours scale, with wall-clock defaults
  unchanged (regression-tested);
- ``ScriptedFaultInjector``'s time-indexed ``*_at`` schedules fire once
  at their scheduled second on the armed clock, count-based budgets
  unchanged;
- soak: classed-queue aging under a sustained simulated-hours flood keeps
  its bounded-starvation promise with no drift, and the fairness
  monitor's sliding-window subtract-on-evict matches fresh accumulators
  after hours of replay;
- the autoscaler's hysteresis (sustained windows, cooldown, min/max
  bounds, lukewarm resets) on a stub fleet with a fake clock;
- fleet elasticity end to end on the tiny engine: canary-gated
  ``add_replica`` serves traffic, ``retire_replica`` migrates in-flight
  work with token parity, and a small replay drives the streaming
  submit/tick/take_result surface with zero accepted-then-lost.
"""

import dataclasses
import time

import pytest

from fairness_llm_tpu.config import (
    AutoscaleConfig,
    FleetConfig,
    ModelSettings,
    OverloadConfig,
    ResilienceConfig,
    ServingConfig,
)
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.runtime.engine import DecodeEngine
from fairness_llm_tpu.serving import (
    ClassedAdmissionQueue,
    DeadlineEstimator,
    ReplayClock,
    ReplayDriver,
    ReplicaSet,
    Request,
    TraceConfig,
    generate_trace,
    read_trace,
    write_trace,
)
from fairness_llm_tpu.serving.autoscaler import Autoscaler
from fairness_llm_tpu.telemetry import use_registry
from fairness_llm_tpu.telemetry.fairness import FairnessMonitor
from fairness_llm_tpu.telemetry.registry import MetricsRegistry, get_registry
from fairness_llm_tpu.telemetry.slo import SLOTargets, set_slo_targets
from fairness_llm_tpu.utils.failures import DecodeFault, ScriptedFaultInjector
from fairness_llm_tpu.utils.ratelimit import RateLimiter

GREEDY_SAFE = SLOTargets(ttft_p95_s=300.0, e2e_p99_s=600.0)


def greedy(m: int) -> ModelSettings:
    return ModelSettings(temperature=0.0, max_tokens=m)


SCFG = ServingConfig(
    enabled=True, num_slots=2, queue_capacity=32,
    max_prompt_len=96, max_new_tokens=16, decode_chunk=4,
)


class FakeClock:
    """Manually-advanced monotonic clock (optionally auto-stepping per
    read, which walks a replay through its schedule without sleeping)."""

    def __init__(self, t: float = 0.0, step: float = 0.0):
        self.t = float(t)
        self.step = float(step)

    def __call__(self) -> float:
        self.t += self.step
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(scope="module")
def engine():
    return DecodeEngine(get_model_config("tiny-test"), seed=0)


@pytest.fixture()
def safe_slo():
    prev = set_slo_targets(GREEDY_SAFE)
    yield
    set_slo_targets(prev)


# -- trace generation ---------------------------------------------------------


TCFG = TraceConfig(seed=3, duration_s=120.0, base_sessions_per_s=0.5,
                   think_time_s=5.0, session_max_turns=6,
                   bursts=((40.0, 20.0, 8.0),),
                   interactive_deadline_s=2.0, batch_deadline_s=None,
                   max_tokens_choices=(4, 8))


def test_trace_same_seed_identical_bytes():
    a = [e.to_json() for e in generate_trace(TCFG)]
    b = [e.to_json() for e in generate_trace(TCFG)]
    assert a == b and len(a) > 10


def test_trace_different_seed_differs():
    a = [e.to_json() for e in generate_trace(TCFG)]
    b = [e.to_json() for e in
         generate_trace(dataclasses.replace(TCFG, seed=4))]
    assert a != b


def test_trace_sorted_shaped_and_mixed():
    evs = generate_trace(TCFG)
    ts = [e.t for e in evs]
    assert ts == sorted(ts)
    assert all(0.0 <= e.t < TCFG.duration_s for e in evs)
    assert all(1 <= e.max_tokens for e in evs)
    assert all(e.turn < TCFG.session_max_turns for e in evs)
    qos = {e.qos for e in evs}
    assert qos <= {"interactive", "batch"} and len(qos) == 2
    # Per-class deadlines landed on the right class.
    for e in evs:
        if e.qos == "interactive":
            assert e.deadline_s == 2.0
        else:
            assert e.deadline_s is None
    # User ids draw from the configured million-user space.
    assert all(0 <= e.user < TCFG.users for e in evs)


def test_trace_burst_density():
    """The burst window must be denser per second than the off-burst rest
    — the overlay actually multiplies the rate."""
    evs = generate_trace(TCFG)
    start, dur, _ = TCFG.bursts[0]
    in_burst = sum(1 for e in evs if start <= e.t < start + dur)
    outside = len(evs) - in_burst
    burst_rate = in_burst / dur
    out_rate = outside / (TCFG.duration_s - dur)
    assert burst_rate > 2.0 * out_rate


def test_trace_overlapping_bursts_respect_thinning_majorant():
    """Overlapping burst windows MULTIPLY the instantaneous rate, so the
    Lewis–Shedler majorant must bound the max simultaneous PRODUCT — a
    majorant built from the largest single multiplier silently clamps
    rate(t)/peak past 1 and under-generates the overlap (regression)."""
    from fairness_llm_tpu.serving.replay import _peak_rate, _rate

    cfg = dataclasses.replace(
        TCFG, bursts=((30.0, 40.0, 3.0), (50.0, 40.0, 4.0)))
    peak = _peak_rate(cfg)
    for i in range(1200):
        t = cfg.duration_s * i / 1200.0
        assert _rate(cfg, t) <= peak + 1e-12
    # The overlap really is denser than either lone window: ~12x base
    # beats ~3x/~4x base per second.
    evs = generate_trace(cfg)

    def rate(a, b):
        return sum(1 for e in evs if a <= e.t < b) / (b - a)

    assert rate(50.0, 70.0) > rate(30.0, 50.0)
    assert rate(50.0, 70.0) > rate(70.0, 90.0)
    # A sub-unity multiplier (a scripted lull) can't inflate the majorant
    # floor: the quiet window is sparser than the untouched remainder.
    lull = dataclasses.replace(TCFG, bursts=((40.0, 40.0, 0.1),))
    evs = generate_trace(lull)
    assert rate(40.0, 80.0) < 0.7 * rate(0.0, 40.0)


def test_trace_write_read_roundtrip(tmp_path):
    evs = generate_trace(TCFG)
    path = write_trace(str(tmp_path / "trace.jsonl"), evs, TCFG)
    back = read_trace(path)
    assert [e.to_json() for e in back] == [e.to_json() for e in evs]


def test_trace_max_events_cap():
    evs = generate_trace(dataclasses.replace(TCFG, max_events=7))
    assert len(evs) == 7


def test_trace_empty_catalog_rejected():
    with pytest.raises(ValueError, match="prompt catalog"):
        generate_trace(TCFG, prompts=())


# -- ReplayClock --------------------------------------------------------------


def test_replay_clock_compression():
    base = FakeClock(t=100.0)
    clk = ReplayClock(compression=60.0, clock=base)
    assert clk.now() == 0.0
    base.advance(2.0)
    assert clk.now() == pytest.approx(120.0)


def test_replay_clock_rejects_nonpositive():
    with pytest.raises(ValueError):
        ReplayClock(compression=0.0)


# -- satellite: injectable clocks --------------------------------------------


def test_rate_limiter_default_clock_unchanged():
    rl = RateLimiter(calls_per_minute=2, window_seconds=0.05)
    assert rl.try_acquire() and rl.try_acquire()
    assert not rl.try_acquire()
    time.sleep(0.06)
    assert rl.try_acquire()  # wall-clock aging, as before


def test_rate_limiter_fake_clock_simulated_hours():
    clk = FakeClock()
    rl = RateLimiter(calls_per_minute=3, window_seconds=60.0, clock=clk)
    for _ in range(3):
        assert rl.try_acquire()
    assert not rl.try_acquire() and not rl.can_acquire()
    clk.advance(3600.0)  # one simulated hour, no sleeping
    assert rl.can_acquire() and rl.try_acquire()


def test_classed_queue_aging_on_injected_clock():
    clk = FakeClock()
    q = ClassedAdmissionQueue(
        capacity=16, overload=OverloadConfig(enabled=True, aging_s=5.0),
        clock=clk,
    )
    batch = Request(prompt="b", qos="batch", submitted_at=clk.t)
    assert q.submit(batch)
    clk.advance(2.0)
    for i in range(3):
        assert q.submit(Request(prompt=f"i{i}", qos="interactive",
                                submitted_at=clk.t))
    # Strict priority while the batch head is fresh.
    assert q.pop(1)[0].qos == "interactive"
    clk.advance(4.0)  # batch head (age 6) aged past 5 s on the fake
    assert q.pop(1)[0].qos == "batch"  # clock; interactive (age 4) is not


def test_classed_queue_drain_expired_uses_injected_clock():
    clk = FakeClock(t=1000.0)
    q = ClassedAdmissionQueue(capacity=8,
                              overload=OverloadConfig(enabled=True),
                              clock=clk)
    r = Request(prompt="x", deadline_s=2.0, submitted_at=clk.t)
    assert q.submit(r)
    assert q.drain_expired() == []  # fresh on the fake clock
    clk.advance(3.0)
    assert [e.id for e in q.drain_expired()] == [r.id]
    assert len(q) == 0


def test_deadline_estimator_injected_clock():
    with use_registry(MetricsRegistry()) as reg:
        reg.histogram("prefill_wall_s", component="serving").observe(1.0)
        reg.histogram("per_output_token_s", component="serving").observe(0.5)
        clk = FakeClock(t=50.0)
        est = DeadlineEstimator(safety=1.0, clock=clk)
        req = Request(prompt="x", deadline_s=10.0, submitted_at=50.0)
        assert est.infeasible(req, 0, 2, 4) is None
        clk.advance(9.0)  # 1 s of budget left < est (~1.5 s), on fake time
        assert est.infeasible(req, 0, 2, 4) is not None


# -- satellite: time-indexed fault schedule -----------------------------------


def test_replica_crash_at_seconds_fires_once():
    with use_registry(MetricsRegistry()):
        clk = FakeClock()
        inj = ScriptedFaultInjector(replica_crashes_at={"r1": 30.0})
        inj.arm(clock=clk)
        assert inj.maybe_replica_fault("r1") is None
        clk.advance(29.0)
        assert inj.maybe_replica_fault("r1") is None
        clk.advance(2.0)  # t=31 >= 30
        assert inj.maybe_replica_fault("r1") == "replica_crash"
        assert inj.maybe_replica_fault("r1") is None  # consumed
        assert inj.replica_faults_fired == [("r1", "replica_crash")]


def test_request_faults_at_seconds():
    with use_registry(MetricsRegistry()):
        clk = FakeClock()
        inj = ScriptedFaultInjector(
            faults_at={("req_a", "decode"): 10.0},
            hangs_at={"req_b": 20.0},
            corruptions_at={"req_c": 5.0},
        )
        inj.arm(clock=clk)
        inj.maybe_fail("req_a", "decode")  # not due yet
        assert inj.maybe_hang("req_b", "decode") == 0.0
        clk.advance(6.0)
        assert inj.maybe_corrupt("req_c", "decode") == "nan"
        assert inj.maybe_corrupt("req_c", "decode") is None  # consumed
        clk.advance(5.0)  # t=11
        with pytest.raises(DecodeFault):
            inj.maybe_fail("req_a", "decode")
        inj.maybe_fail("req_a", "decode")  # consumed: no second raise
        clk.advance(10.0)  # t=21
        assert inj.maybe_hang("req_b", "prefill") == inj.hang_seconds


def test_count_budgets_unchanged_alongside_schedule():
    with use_registry(MetricsRegistry()):
        inj = ScriptedFaultInjector(faults={"r": 1})
        with pytest.raises(DecodeFault):
            inj.maybe_fail("r", "decode")
        inj.maybe_fail("r", "decode")  # budget spent


def test_double_scripted_replica_rejected():
    with pytest.raises(ValueError, match="more than one fault"):
        ScriptedFaultInjector(replica_crashes_at={"r1": 1.0},
                              replica_hangs_at={"r1": 2.0})
    # A count-based and a time-indexed schedule for the SAME replica is
    # the same double-fault script, whichever kind lands second
    # (regression: only the hang side used to be cross-checked).
    with pytest.raises(ValueError, match="more than one fault"):
        ScriptedFaultInjector(replica_crashes={"r1": 2},
                              replica_crashes_at={"r1": 30.0})
    with pytest.raises(ValueError, match="more than one fault"):
        ScriptedFaultInjector(replica_hangs={"r1": 2},
                              replica_crashes_at={"r1": 30.0})


# -- satellite: soak tests ----------------------------------------------------


def test_classed_queue_aging_soak_simulated_hours():
    """A sustained ~91%-utilization interactive flood over three
    simulated hours, with a batch trickle that is only ever served
    through aging promotion. Bounded starvation must hold at hour-scale
    timestamps exactly as in the first minute — any drift in the
    promotion arithmetic (or a leak in the per-class bookkeeping) shows
    up as a batch wait growing with the clock."""
    clk = FakeClock()
    aging = 5.0
    q = ClassedAdmissionQueue(
        capacity=64, overload=OverloadConfig(enabled=True, aging_s=aging),
        clock=clk,
    )
    worst_batch_wait, served_batch, served_inter = 0.0, 0, 0
    accepted = 0
    for step in range(3000):  # 3000 x 4 s = ~3.3 simulated hours
        clk.advance(4.0)
        # Interactive pressure on 9 of 10 pop slots: strict priority
        # starves the batch trickle until its head ages past aging_s.
        if step % 10:
            accepted += q.submit(Request(prompt="i", qos="interactive",
                                         submitted_at=clk.t))
        if step % 100 == 50:
            accepted += q.submit(Request(prompt=f"b{step}", qos="batch",
                                         submitted_at=clk.t))
        for r in q.pop(1):
            wait = clk.t - r.submitted_at
            if r.qos == "batch":
                served_batch += 1
                worst_batch_wait = max(worst_batch_wait, wait)
            else:
                served_inter += 1
    assert served_batch == 30 and served_inter > 2600
    # Bounded starvation: a batch head is promoted once it ages past
    # aging_s, then waits out at most the small steady-state backlog —
    # a handful of pop cycles (4 s each), NOT a bound that grows with the
    # simulated hours.
    assert worst_batch_wait <= aging + 4 * 4.0 + 1e-9
    # Conservation at hour scale: every accepted request was served or is
    # still queued.
    assert served_batch + served_inter + len(q) == accepted


def test_fairness_window_no_drift_under_long_replay():
    """Sliding-window subtract-on-evict vs fresh accumulators after hours
    of simulated replay: the incremental window state must equal a
    from-scratch recomputation over exactly the in-window events — any
    leak or double-subtract shows up as drift."""
    clk = FakeClock(t=0.0)
    window_s = 300.0
    reg = MetricsRegistry()
    mon = FairnessMonitor(window_s=window_s, clock=clk, registry=reg)
    titles = [f"movie {i}" for i in range(12)]
    fed = []  # (t, key, group, recs)
    for step in range(2000):  # ~5.5 simulated hours at 10 s cadence
        clk.advance(10.0)
        key = f"k{step:05d}"
        group = ("male", "female", "non-binary")[step % 3]
        recs = [titles[(step + j) % len(titles)] for j in range(5)]
        mon.register_request(key, {"gender": group})
        mon.observe_output(key, recs)
        fed.append((clk.t, group, list(recs)))
        if step % 500 == 499:
            mon.refresh()  # ages the window incrementally
    mon.refresh()
    cutoff = clk.t - window_s
    # Fresh accumulators over exactly the in-window feed.
    from collections import Counter
    import math
    want_counts = {}
    want_expo = {}
    for t, group, recs in fed:
        if t < cutoff:
            continue
        want_counts.setdefault(group, Counter()).update(recs)
        e = sum(1.0 / math.log2(p + 2.0) for p in range(len(recs)))
        s, n = want_expo.get(group, (0.0, 0))
        want_expo[group] = (s + e, n + len(recs))
    got_counts = {g: {t: c for t, c in cnt.items() if c}
                  for g, cnt in mon._win_counts["gender"].items()}
    got_counts = {g: c for g, c in got_counts.items() if c}
    assert got_counts == {g: dict(c) for g, c in want_counts.items()}
    for g, (s, n) in want_expo.items():
        gs, gn = mon._win_expo["gender"][g]
        assert gn == n
        assert gs == pytest.approx(s, abs=1e-6)


# -- autoscaler hysteresis (stub fleet, fake clock) ---------------------------


class _StubSched:
    def __init__(self):
        self.pool = type("P", (), {"occupancy": 0})()
        self.queue = []
        self._pending = []
        self.num_slots = 2


class _StubReplica:
    def __init__(self, name):
        self.name = name
        self.fenced = False
        self.sched = _StubSched()


class _StubFleet:
    def __init__(self, n=1):
        self.replicas = [_StubReplica(f"r{i}") for i in range(n)]
        self.queue = []
        self._pending = []
        self.serving = ServingConfig(enabled=True, queue_capacity=10)
        self.shed_controller = None
        self._fleet_labels = {}
        self.burn = 0.0
        self.router = type(
            "R", (), {"load": staticmethod(lambda rep: 0.0)})()
        self.added, self.retired = 0, []
        self.deny_next_add = False
        self._seq = 1

    def _max_replica_burn(self):
        return self.burn

    def add_replica(self):
        self.added += 1
        if self.deny_next_add:
            self.deny_next_add = False
            return None
        rep = _StubReplica(f"r{self._seq}")
        self._seq += 1
        self.replicas.append(rep)
        return rep

    def retire_replica(self, rep):
        self.replicas.remove(rep)
        self.retired.append(rep.name)
        return 0


def _auto(fleet, clk, **kw):
    kwargs = dict(
        enabled=True, min_replicas=1, max_replicas=3,
        up_burn_threshold=2.0, up_queue_frac=0.8, up_window_s=1.0,
        down_burn_threshold=0.5, down_queue_frac=0.1, down_load_frac=0.5,
        down_window_s=5.0, cooldown_s=2.0, eval_interval_s=0.0,
    )
    kwargs.update(kw)
    cfg = AutoscaleConfig(**kwargs)
    with use_registry(MetricsRegistry()):
        a = Autoscaler(fleet, cfg, clock=clk)
    return a


def test_autoscaler_requires_sustained_hot_window():
    clk = FakeClock()
    fleet = _StubFleet(1)
    a = _auto(fleet, clk)
    fleet.burn = 10.0
    assert a.tick() is None  # hot, but not yet sustained
    clk.advance(0.5)
    assert a.tick() is None
    clk.advance(0.6)  # 1.1 s of sustained hot
    assert a.tick() == "up"
    assert len(fleet.replicas) == 2


def test_autoscaler_cooldown_and_max_bound():
    clk = FakeClock()
    fleet = _StubFleet(1)
    a = _auto(fleet, clk)
    fleet.burn = 10.0
    a.tick()  # starts the hot window
    clk.advance(1.1)
    assert a.tick() == "up"
    a.tick()  # restarts the hot window (reset by the scale-up)
    clk.advance(1.1)
    assert a.tick() is None  # sustained hot again, but inside cooldown
    clk.advance(1.0)  # past cooldown (2 s since the action)
    assert a.tick() == "up"  # 3 replicas = max
    a.tick()
    clk.advance(5.0)
    assert a.tick() is None  # hot + sustained + cooled, but at max
    assert len(fleet.replicas) == 3


def test_autoscaler_scale_down_needs_cold_window_and_min_bound():
    clk = FakeClock()
    fleet = _StubFleet(3)
    a = _auto(fleet, clk)
    fleet.burn = 0.0
    assert a.tick() is None
    clk.advance(4.9)
    assert a.tick() is None  # cold, not yet sustained
    clk.advance(0.2)
    assert a.tick() == "down"
    assert len(fleet.replicas) == 2
    clk.advance(2.1)  # past cooldown
    a.tick()  # restarts the cold window (reset by the scale-down)
    clk.advance(5.1)  # a fresh sustained-cold run
    assert a.tick() == "down"
    assert len(fleet.replicas) == 1
    a.tick()
    clk.advance(10.0)
    assert a.tick() is None  # bounded at min_replicas


def test_autoscaler_lukewarm_resets_windows():
    clk = FakeClock()
    fleet = _StubFleet(1)
    a = _auto(fleet, clk)
    fleet.burn = 10.0
    a.tick()
    clk.advance(0.8)
    fleet.burn = 1.0  # lukewarm: above down threshold, below up
    a.tick()
    fleet.burn = 10.0
    clk.advance(0.8)
    assert a.tick() is None  # the hot window restarted
    clk.advance(1.1)
    assert a.tick() == "up"


def test_autoscaler_denied_standby_counts_and_retries():
    clk = FakeClock()
    fleet = _StubFleet(1)
    a = _auto(fleet, clk)
    fleet.burn = 10.0
    fleet.deny_next_add = True
    a.tick()  # starts the hot window
    clk.advance(1.1)
    assert a.tick() is None  # standby canary refused
    assert a.denied == 1 and len(fleet.replicas) == 1
    # The target gauge carries the DENIED want while the pressure holds:
    # an operator sees "wants 2, has 1", not a content fleet.
    assert a._target_gauge().value == 2
    clk.advance(2.1)  # past the cooldown the denial started
    a.tick()  # a fresh hot window
    clk.advance(1.1)
    assert a.tick() == "up"
    assert a._target_gauge().value == 2  # satisfied: target == actual


def test_autoscaler_denied_want_clears_when_pressure_passes():
    clk = FakeClock()
    fleet = _StubFleet(1)
    a = _auto(fleet, clk)
    fleet.burn = 10.0
    fleet.deny_next_add = True
    a.tick()
    clk.advance(1.1)
    a.tick()  # denied: target sticks at 2
    assert a._target_gauge().value == 2
    fleet.burn = 1.0  # lukewarm: the want that was denied has passed
    a.tick()
    assert a._target_gauge().value == 1


def test_autoscaler_enforces_bounds_absolutely():
    """A fleet started (or reconfigured) outside [min, max] converges
    regardless of signal temperature — the bounds are absolute, not just
    caps on signal-driven moves (regression: min_replicas used to be only
    a scale-down floor, so ``--autoscale --min-replicas 3`` over a
    1-replica start idled below min forever)."""
    clk = FakeClock()
    fleet = _StubFleet(1)
    a = _auto(fleet, clk, min_replicas=2, max_replicas=3)
    fleet.burn = 1.0  # lukewarm: no signal would ever scale this up
    assert a.tick() == "up"  # below min: immediate, no hot window needed
    assert len(fleet.replicas) == 2
    clk.advance(10.0)
    assert a.tick() is None  # inside bounds, lukewarm: content
    # Above max (e.g. --replicas 5 handed to --max-replicas 3): retire one
    # per cooldown even though the fleet never goes cold.
    fleet = _StubFleet(5)
    a = _auto(fleet, clk, min_replicas=1, max_replicas=3)
    fleet.burn = 1.0
    assert a.tick() == "down"
    assert a.tick() is None  # cooldown between convergence steps
    clk.advance(2.1)
    assert a.tick() == "down"
    assert len(fleet.replicas) == 3
    clk.advance(10.0)
    assert a.tick() is None  # at max: converged, holds


def test_autoscaler_bounds_validated():
    with pytest.raises(ValueError):
        Autoscaler(_StubFleet(1), AutoscaleConfig(enabled=True,
                                                  min_replicas=0))
    with pytest.raises(ValueError):
        Autoscaler(_StubFleet(1), AutoscaleConfig(enabled=True,
                                                  min_replicas=3,
                                                  max_replicas=2))


class _WedgedFleet:
    """Streaming-surface stub that accepts work and never finishes it —
    the shape ReplayDriver's wall/drain guards exist for."""

    def __init__(self, refuse_first: int = 0):
        self.settings = greedy(4)
        self.refusals_counted = []  # count_rejection flag per refusal
        self._refuse = refuse_first
        self.accepted = []
        self.drained = False

    def submit(self, request, restamp=True, count_rejection=True):
        if self._refuse > 0:
            self._refuse -= 1
            self.refusals_counted.append(count_rejection)
            return False
        self.accepted.append(request.id)
        return True

    def tick(self):
        return False

    def take_result(self, request_id):
        return None

    @property
    def has_work(self):
        return bool(self.accepted)

    def drain(self):
        self.drained = True  # unbounded on a real wedged fleet


def test_replay_wall_guard_skips_unbounded_drain_on_abandon():
    """A replay that abandons outstanding work at the drain guard must NOT
    re-enter the fleet's unbounded drain() — that loop would hang on
    exactly the wedge the guard escaped (regression). The loss stays
    visible in the report."""
    fleet = _WedgedFleet()
    evs = generate_trace(dataclasses.replace(TCFG, max_events=3))
    with use_registry(MetricsRegistry()):
        report = ReplayDriver(fleet, evs, compression=1e6,
                              max_wall_s=0.05, poll_s=0.0).run()
    assert report.timed_out and not fleet.drained
    assert report.accepted == 3 and report.lost == 3


def test_replay_retries_do_not_recount_rejections():
    """Only an arrival's FIRST refusal counts a rejection; the driver's
    poll-loop re-offers pass count_rejection=False (regression: every ~1 ms
    retry used to count, inflating the stats orders of magnitude)."""
    fleet = _WedgedFleet(refuse_first=4)
    evs = generate_trace(dataclasses.replace(TCFG, max_events=2))
    with use_registry(MetricsRegistry()):
        report = ReplayDriver(fleet, evs, compression=1e6,
                              max_wall_s=0.05, poll_s=0.0).run()
    assert report.accepted == 2
    assert fleet.refusals_counted[0] is True  # first offer of event 1
    # Every subsequent refusal this poll-cycle is a re-offer of an
    # already-counted arrival OR the first offer of the next event.
    assert sum(fleet.refusals_counted) == 2
    assert report.backpressured == 4


def test_cli_min_replicas_over_default_max_rejected_upfront():
    """``--min-replicas`` above the default max without an explicit
    ``--max-replicas`` must fail at flag validation, not as a raw
    ValueError after model load (regression)."""
    from fairness_llm_tpu.cli.main import main

    with pytest.raises(SystemExit, match="exceeds the default"):
        main(["--phase", "1", "--quick", "--model", "simulated",
              "--no-save", "--continuous", "--autoscale",
              "--min-replicas", "5"])
    # An explicit, coherent pair still parses past this gate.
    with pytest.raises(SystemExit, match="must be >= --min-replicas"):
        main(["--phase", "1", "--quick", "--model", "simulated",
              "--no-save", "--continuous", "--autoscale",
              "--min-replicas", "5", "--max-replicas", "4"])


# -- fleet elasticity (real engine) ------------------------------------------


RES = ResilienceConfig(enabled=True, breaker_threshold=2,
                       breaker_cooldown_s=0.02)


def _fleet(engine, **kw):
    from fairness_llm_tpu.config import IntegrityConfig

    defaults = dict(
        serving=SCFG, settings=greedy(8),
        fleet=FleetConfig(replicas=1, fence_cooldown_s=0.05),
        resilience=RES, integrity=IntegrityConfig(canary_max_tokens=8),
    )
    defaults.update(kw)
    return ReplicaSet(engine, defaults.pop("serving"), **defaults)


def test_add_replica_canary_gated_and_serves(engine, safe_slo):
    fleet = _fleet(engine)
    rep = fleet.add_replica()
    assert rep is not None and rep.name == "r1"
    assert len(fleet.replicas) == 2 and fleet.healthy_count == 2
    assert get_registry().read_value("fleet_replicas",
                                     component="fleet") == 2
    prompts = ["the quick brown fox", "hello there friend",
               "one two three four", "a very different prompt"]
    reqs = [Request(prompt=p, id=f"el_{i}", settings=greedy(8))
            for i, p in enumerate(prompts)]
    results = fleet.serve(reqs)
    assert all(r.ok for r in results)
    # Both replicas took traffic (4 requests, 2 slots each, one queue).
    reg = get_registry()
    served = {
        rep.name: sum(
            getattr(m, "value", 0) for m in reg.instruments()
            if getattr(m, "name", "") == "requests_finished_total"
            and getattr(m, "labels", {}).get("replica") == rep.name
        )
        for rep in fleet.replicas
    }
    assert all(v > 0 for v in served.values()), served
    # Parity with the static engine.
    for req, res in zip(reqs, results):
        out = engine.generate([req.prompt], greedy(8), share_prefix=False)
        ref = [int(t) for t in out.tokens[0]
               if t != engine.tokenizer.pad_id]
        got = [int(t) for t in res.tokens]
        assert got == ref[: len(got)]


def test_monotone_replica_names_after_retire(engine, safe_slo):
    fleet = _fleet(engine)
    r1 = fleet.add_replica()
    fleet.retire_replica(r1)
    r2 = fleet.add_replica()
    assert r2.name == "r2"  # r1's name is never reused


def test_retire_replica_migrates_in_flight_with_parity(engine, safe_slo):
    reg = get_registry()
    # Process-global registry: earlier tests may have retired a replica
    # with the same name — assert deltas, not absolutes.
    retired_before = reg.read_value("fleet_retired_total",
                                    component="fleet", replica="r1")
    fenced_before = reg.read_value("fleet_fenced_total", component="fleet",
                                   replica="r1", reason="retired")
    fleet = _fleet(engine)
    assert fleet.add_replica() is not None
    reqs = [Request(prompt=p, id=f"ret_{i}", settings=greedy(8))
            for i, p in enumerate([
                "the quick brown fox", "hello there friend",
                "one two three four", "pack my box with jugs",
                "five quacking zephyrs", "how vexingly quick",
            ])]
    for r in reqs:
        assert fleet.submit(r)
    # Tick until the soon-to-retire replica actually holds work.
    victim = fleet.replicas[1]
    for _ in range(200):
        fleet.tick()
        if victim.assigned:
            break
    assert victim.assigned, "victim never took traffic"
    migrated = fleet.retire_replica(victim)
    assert migrated >= 1
    assert len(fleet.replicas) == 1
    fleet.drain()
    results = {r.id: fleet.take_result(r.id) for r in reqs}
    assert all(res is not None and res.ok for res in results.values())
    # Token parity incl. the migrated survivors.
    for req in reqs:
        out = engine.generate([req.prompt], greedy(8), share_prefix=False)
        ref = [int(t) for t in out.tokens[0]
               if t != engine.tokenizer.pad_id]
        got = [int(t) for t in results[req.id].tokens]
        assert got == ref[: len(got)]
    # The retired replica's work survived in the fleet stats record.
    assert fleet.last_stats is not None
    assert fleet.last_stats.completed == len(reqs)
    # Planned exit: retired counter, no fence counter.
    assert reg.read_value("fleet_retired_total", component="fleet",
                          replica=victim.name) == retired_before + 1
    assert reg.read_value("fleet_fenced_total", component="fleet",
                          replica=victim.name,
                          reason="retired") == fenced_before


def test_retire_last_replica_refused(engine, safe_slo):
    fleet = _fleet(engine)
    with pytest.raises(ValueError, match="last replica"):
        fleet.retire_replica(fleet.replicas[0])


def test_replay_driver_streaming_zero_lost(engine, safe_slo):
    cfg = TraceConfig(seed=5, duration_s=6.0, base_sessions_per_s=1.0,
                      think_time_s=1.0, session_max_turns=3,
                      max_tokens_choices=(4, 6), interactive_frac=0.5)
    evs = generate_trace(cfg, prompts=("the quick brown fox",
                                       "hello there friend"))
    assert evs
    fleet = _fleet(engine)
    report = ReplayDriver(fleet, evs, compression=4.0,
                          max_wall_s=120.0).run()
    assert report.lost == 0
    assert report.accepted == len(evs)
    assert report.outcomes.get("completed", 0) == len(evs)
    # Re-run: identical admitted-token set (the determinism contract).
    fleet2 = _fleet(engine)
    report2 = ReplayDriver(fleet2, evs, compression=4.0,
                           max_wall_s=120.0).run()
    assert report2.tokens == report.tokens

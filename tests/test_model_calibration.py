"""Value-level semantics of phase-3 ``calibration="model"``.

VERDICT/round-1 flagged that the model-derived conformal path was tested only
for shapes. These tests pin WHAT the filter keeps for known logprob patterns:

- ``facter.model_confidences`` mappings (percentile / probability) on known
  inputs,
- the full ``apply_facter(calibration="model")`` path with a stubbed scorer:
  the kept set must be exactly the titles whose mapped confidence clears the
  per-gender conformal threshold (floor 3), i.e. low-likelihood titles are
  the ones dropped.
"""

import numpy as np
import pytest

from fairness_llm_tpu.config import Config
from fairness_llm_tpu.data.profiles import Profile
from fairness_llm_tpu.pipeline.facter import model_confidences
from fairness_llm_tpu.pipeline.phase3 import apply_facter


# ---------------------------------------------------------------------------
# mapping unit semantics
# ---------------------------------------------------------------------------


def test_percentile_mapping_known_pattern():
    # ranks of [-1, -5, -3] are [2, 0, 1] -> /2 -> [1.0, 0.0, 0.5]
    conf = model_confidences(np.array([-1.0, -5.0, -3.0]))
    np.testing.assert_allclose(conf, [1.0, 0.0, 0.5])


def test_percentile_mapping_is_scale_free():
    lp = np.array([-2.0, -9.0, -4.5, -0.1])
    np.testing.assert_allclose(
        model_confidences(lp), model_confidences(lp * 100.0)
    )


def test_probability_mapping_preserves_gaps():
    # logprobs -0.1 and -0.2 are near each other; -8 is an outlier.
    # percentile spaces them evenly; probability keeps the near pair close.
    lp = np.array([-0.1, -0.2, -8.0])
    pct = model_confidences(lp, "percentile")
    prob = model_confidences(lp, "probability")
    assert pct[0] - pct[1] == pytest.approx(0.5)  # even rank spacing
    assert prob[0] - prob[1] < 0.15  # near pair stays near
    assert prob[2] == 0.0 and prob[0] == 1.0  # min-max endpoints
    # both mappings preserve ordering
    assert list(np.argsort(pct)) == list(np.argsort(prob)) == [2, 1, 0]


def test_probability_mapping_temperature():
    lp = np.array([-0.1, -0.2, -8.0])
    hot = model_confidences(lp, "probability", temperature=10.0)
    cold = model_confidences(lp, "probability", temperature=0.5)
    # low temperature sharpens the distribution: after min-max normalization
    # the near pair sits FURTHER apart than at high temperature (where all
    # probabilities converge and the normalized gap shrinks)
    assert (cold[0] - cold[1]) > (hot[0] - hot[1])
    # ordering invariant under temperature
    assert list(np.argsort(hot)) == list(np.argsort(cold)) == [2, 1, 0]


def test_mapping_edge_cases():
    assert model_confidences(np.zeros(0)).shape == (0,)
    np.testing.assert_allclose(model_confidences(np.array([-3.0, -3.0]), "probability"), [0.5, 0.5])
    with pytest.raises(ValueError):
        model_confidences(np.array([-1.0]), "nope")
    with pytest.raises(ValueError):
        model_confidences(np.array([-1.0]), "probability", temperature=0.0)


# ---------------------------------------------------------------------------
# end-to-end kept-set semantics through apply_facter
# ---------------------------------------------------------------------------

TITLES = {
    "m0": [f"M{i}" for i in range(6)],
    "f0": [f"F{i}" for i in range(6)],
}
# Known logprob pattern: within each list, title i gets logprob -(i+1) for M,
# offset by -0.5 for F — so the global likelihood order interleaves
# M0 > F0 > M1 > F1 > ... > M5 > F5 and low-rank titles are the UNLIKELY ones.
LOGPROBS = {f"M{i}": -(i + 1.0) for i in range(6)}
LOGPROBS.update({f"F{i}": -(i + 1.5) for i in range(6)})


class _ByteTokenizer:
    def encode(self, text):
        return list(text.encode("utf-8"))


class _StubEngine:
    tokenizer = _ByteTokenizer()


class StubBackend:
    """Returns each profile's fixed numbered list; exposes a truthy .engine
    (with the tokenizer the shared-prefix probe needs) so apply_facter takes
    the model-calibration path."""

    name = "stub"
    engine = _StubEngine()

    def generate(self, prompts, settings=None, seed=0, keys=None, prefix_ids=None):
        return ["\n".join(f"{j + 1}. {t}" for j, t in enumerate(TITLES[k])) for k in keys]


@pytest.fixture()
def profiles():
    return [
        Profile(id="m0", gender="male", age="25-34", occupation="pro",
                watched_movies=["watched-m"], favorite_genres=["Drama"], avg_rating=4.5),
        Profile(id="f0", gender="female", age="25-34", occupation="pro",
                watched_movies=["watched-f"], favorite_genres=["Drama"], avg_rating=4.5),
    ]


def _patch_scorer(monkeypatch):
    import fairness_llm_tpu.runtime.scoring as scoring

    class FakeScores:
        def __init__(self, titles):
            self.mean_logprobs = [LOGPROBS[t] for t in titles]

    monkeypatch.setattr(scoring, "score_texts", lambda engine, texts: FakeScores(texts))


def _expected_keep(pids, genders_of, mapping, config):
    """Independently recompute the kept sets from the pinned semantics:
    flatten confidences in profile order, per-gender conformal threshold on
    seeded nonconformity, keep conf >= threshold with floor 3 (top-by-conf)."""
    import jax.numpy as jnp

    from fairness_llm_tpu.pipeline.facter import (
        conformal_thresholds_kernel,
        nonconformity_from_confidence,
    )

    all_titles = [t for pid in pids for t in TITLES[pid]]
    conf = model_confidences(np.array([LOGPROBS[t] for t in all_titles]), mapping)
    nonconf = nonconformity_from_confidence(conf, config.random_seed)
    genders = sorted({genders_of[p] for p in pids})
    gidx = {g: i for i, g in enumerate(genders)}
    groups = np.concatenate([np.full(6, gidx[genders_of[p]], np.int32) for p in pids])
    thresholds = np.asarray(
        conformal_thresholds_kernel(jnp.asarray(nonconf), jnp.asarray(groups),
                                    len(genders), alpha=config.conformal_alpha)
    )
    out = {}
    off = 0
    for pid in pids:
        row_conf = conf[off: off + 6]
        t = thresholds[gidx[genders_of[pid]]]
        kept = [TITLES[pid][j] for j in range(6) if row_conf[j] >= t]
        if len(kept) < 3:  # floor: top-3 by confidence
            top = np.argsort(-row_conf, kind="stable")[:3]
            kept = [TITLES[pid][j] for j in sorted(top)]
        out[pid] = kept
        off += 6
    return out


@pytest.mark.parametrize("mapping", ["percentile", "probability"])
def test_model_calibration_keeps_high_likelihood_titles(profiles, monkeypatch, tmp_path, mapping):
    _patch_scorer(monkeypatch)
    config = Config(results_dir=str(tmp_path), data_dir="/nonexistent")
    kept = apply_facter(
        profiles, StubBackend(), config, variant="conformal",
        save_checkpoints=False, calibration="model", confidence_mapping=mapping,
    )
    expected = _expected_keep(
        ["m0", "f0"], {"m0": "male", "f0": "female"}, mapping, config
    )
    assert kept == expected
    # semantic floor: every kept list has >= 3 titles, order preserved
    for pid, lst in kept.items():
        assert len(lst) >= 3
        idx = [TITLES[pid].index(t) for t in lst]
        assert idx == sorted(idx)
    # dropped titles are always lower-likelihood than every kept title of the
    # same profile (both mappings are monotone in logprob)
    for pid, lst in kept.items():
        dropped = [t for t in TITLES[pid] if t not in lst]
        if dropped:
            assert max(LOGPROBS[t] for t in dropped) < min(LOGPROBS[t] for t in lst)


def test_model_calibration_golden_kept_set(profiles, monkeypatch, tmp_path):
    """Hard-pinned kept titles for the canonical pattern (percentile mapping,
    seed 42, alpha 0.1): any change to the mapping, threshold kernel, filter
    semantics, or seeding shows up as a diff here."""
    _patch_scorer(monkeypatch)
    config = Config(results_dir=str(tmp_path), data_dir="/nonexistent")
    kept = apply_facter(
        profiles, StubBackend(), config, variant="conformal",
        save_checkpoints=False, calibration="model",
    )
    assert kept == GOLDEN_KEPT


def test_conditional_calibration_uses_profile_context(profiles, monkeypatch, tmp_path):
    """calibration='model-conditional' must score each (profile, title) pair
    with THAT profile's watch-history context — not a shared unconditional
    score — and the context must carry no demographics."""
    import fairness_llm_tpu.runtime.scoring as scoring

    seen = {}

    class FakeScores:
        def __init__(self, titles):
            self.mean_logprobs = np.array([LOGPROBS[t] for t in titles])

    def fake_spc(engine, prompts, conts):
        seen["prompts"], seen["conts"] = list(prompts), list(conts)
        return FakeScores(conts)

    monkeypatch.setattr(scoring, "score_prompted_continuations", fake_spc)
    config = Config(results_dir=str(tmp_path), data_dir="/nonexistent")
    kept = apply_facter(
        profiles, StubBackend(), config, variant="conformal",
        save_checkpoints=False, calibration="model-conditional",
    )
    # one context row per (profile, title), profile-specific, no demographics
    assert len(seen["prompts"]) == 12 and seen["conts"] == TITLES["m0"] + TITLES["f0"]
    assert len(set(seen["prompts"])) == 2  # two distinct profile contexts
    for p in seen["prompts"]:
        assert "male" not in p and "female" not in p and "25-34" not in p
        assert "enjoyed watched-" in p  # the watch history IS the context
    # same logprob pattern as the unconditional golden -> same kept sets
    assert kept == GOLDEN_KEPT


def test_unknown_calibration_refused(profiles, tmp_path):
    """A typo'd calibration name must fail loudly, not silently run the
    simulated curve while the metadata records the requested name."""
    config = Config(results_dir=str(tmp_path), data_dir="/nonexistent")
    with pytest.raises(ValueError, match="unknown calibration"):
        apply_facter(
            profiles, StubBackend(), config, variant="conformal",
            save_checkpoints=False, calibration="model_conditional",  # underscore typo
        )


def test_conditional_calibration_requires_engine(profiles, tmp_path):
    class NoEngine:
        name = "sim"

        def generate(self, prompts, settings=None, seed=0, keys=None, prefix_ids=None):
            return ["\n".join(f"{j + 1}. {t}" for j, t in enumerate(TITLES[k])) for k in keys]

    config = Config(results_dir=str(tmp_path), data_dir="/nonexistent")
    with pytest.raises(ValueError, match="EngineBackend"):
        apply_facter(
            profiles, NoEngine(), config, variant="conformal",
            save_checkpoints=False, calibration="model-conditional",
        )


def test_confidence_temperature_reaches_mapping(profiles, monkeypatch, tmp_path):
    """run_phase3's confidence_temperature must reach model_confidences (it
    was once accepted-but-dropped)."""
    _patch_scorer(monkeypatch)
    seen = {}
    import fairness_llm_tpu.pipeline.phase3 as p3

    real = model_confidences

    def spy(lp, mapping="percentile", temperature=1.0):
        seen["mapping"], seen["temperature"] = mapping, temperature
        return real(lp, mapping, temperature)

    monkeypatch.setattr(p3, "model_confidences", spy)
    config = Config(results_dir=str(tmp_path), data_dir="/nonexistent")
    apply_facter(
        profiles, StubBackend(), config, variant="conformal",
        save_checkpoints=False, calibration="model",
        confidence_mapping="probability", confidence_temperature=2.5,
    )
    assert seen == {"mapping": "probability", "temperature": 2.5}


# Populated from a verified run of the pinned semantics (see
# test_model_calibration_keeps_high_likelihood_titles, which derives the same
# sets independently); hard-coded so regressions are visible as literal diffs.
GOLDEN_KEPT = {
    "m0": ["M0", "M1", "M2", "M3", "M4"],
    "f0": ["F0", "F1", "F2", "F3", "F4"],
}

"""Performance-attribution layer tests (ISSUE 7): the device-step timeline
(span recording, step gaps, Chrome-trace export + schema), compile
observability, live roofline gauges, the SLO burn-rate evaluator against
hand-computed fixtures, heartbeat gap detection under a fake clock, and the
scheduler/fleet integration invariants — spans land on the correct replica
track through eviction+requeue and fleet migration.
"""

from __future__ import annotations

import json

import pytest

from fairness_llm_tpu.config import ModelSettings, ResilienceConfig, ServingConfig
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.telemetry import (
    Heartbeat,
    SLOEvaluator,
    SLOTargets,
    Timeline,
    set_attribution,
    snapshot,
    summarize_chrome_trace,
    use_registry,
    use_timeline,
    validate_chrome_trace,
)
from fairness_llm_tpu.telemetry.compilestats import note_lookup, record_compile
from fairness_llm_tpu.telemetry.roofline import (
    decode_step_bytes,
    observe_decode,
    set_achievable_gbps,
)
from fairness_llm_tpu.telemetry.slo import render_slo_report


# -- timeline core ------------------------------------------------------------


def test_timeline_spans_export_and_schema():
    tl = Timeline()
    tl.record_span("prefill[8x64]", "prefill", "serving", 10.0, 0.5, rows=3)
    tl.record_instant("fence", "r1", t=10.2, reason="crash")
    tl.record_request("req-1", "serving", 9.8, 11.0, "completed", tokens=4)
    trace = tl.to_chrome_trace()
    assert validate_chrome_trace(trace) == []
    evs = trace["traceEvents"]
    # The request span backdates before the prefill span: ts must still be
    # relative to the EARLIEST event (no negative timestamps).
    assert all(e.get("ts", 0) >= 0 for e in evs if e["ph"] != "M")
    x = [e for e in evs if e["ph"] == "X"]
    assert len(x) == 1 and x[0]["name"] == "prefill[8x64]"
    assert x[0]["dur"] == pytest.approx(0.5e6)
    # Request lanes: one async b/e pair with the request id.
    b = [e for e in evs if e["ph"] == "b"]
    e_ = [e for e in evs if e["ph"] == "e"]
    assert len(b) == len(e_) == 1 and b[0]["id"] == "req-1"
    assert b[0]["args"]["outcome"] == "completed"
    # Thread metadata names every lane (requests lane + device lane).
    names = {m["args"]["name"] for m in evs
             if m["ph"] == "M" and m["name"] == "thread_name"}
    assert "serving · device steps" in names
    assert "serving · requests" in names
    assert "r1 · device steps" in names


def test_timeline_step_gap_accounting():
    with use_registry() as reg:
        tl = Timeline()
        tl.decode_chunk("serving", 1.0, 0.3, steps=8)  # first: no gap yet
        tl.decode_chunk("serving", 1.5, 0.3, steps=8)  # gap = 1.5 - 1.3
        tl.decode_chunk("other", 5.0, 0.1, steps=4)    # separate track
        h = reg.histogram("step_gap_s", component="serving")
        assert h.count == 1
        assert h.max == pytest.approx(0.2, abs=1e-9)
        assert tl.top_gaps[0][0] == pytest.approx(0.2, abs=1e-9)
        # Cursor cleared -> the idle stretch to the next chunk is NOT a gap.
        tl.clear_track_cursor("serving")
        tl.decode_chunk("serving", 100.0, 0.3, steps=8)
        assert h.count == 1
        # The gap rides on the span args for the trace summary.
        spans = [e for e in tl.to_chrome_trace()["traceEvents"]
                 if e["ph"] == "X" and "gap_s" in e.get("args", {})]
        assert len(spans) == 1


def test_timeline_ring_bound_counts_drops():
    tl = Timeline(capacity=4)
    for i in range(7):
        tl.record_instant(f"e{i}", "t")
    assert len(tl.events()) == 4
    assert tl.dropped == 3
    assert tl.to_chrome_trace()["otherData"]["dropped_events"] == 3


def test_validate_chrome_trace_catches_corruption():
    assert validate_chrome_trace([]) == ["trace is not an object"]
    assert validate_chrome_trace({}) == ["traceEvents missing or not a list"]
    bad = {"traceEvents": [
        {"ph": "X", "name": "a", "pid": 1, "ts": 1.0},          # no dur
        {"ph": "e", "name": "r", "pid": 1, "ts": 2.0, "id": "r",
         "cat": "request"},                                       # e before b
        {"ph": "??", "name": "x", "pid": 1, "ts": 0.0},           # unknown ph
    ]}
    problems = validate_chrome_trace(bad)
    assert any("bad dur" in p for p in problems)
    assert any("e before its b" in p for p in problems)
    assert any("unknown ph" in p for p in problems)


def test_summarize_chrome_trace_groups_programs_and_gaps():
    tl = Timeline()
    tl.record_span("prefill[8x64]", "prefill", "serving", 0.0, 0.5)
    tl.decode_chunk("serving", 1.0, 0.2, steps=8)
    tl.decode_chunk("serving", 1.3, 0.2, steps=8)
    tl.record_request("r1", "serving", 0.0, 1.5, "completed")
    with use_registry():
        pass
    text = summarize_chrome_trace(tl.to_chrome_trace())
    assert "prefill[8x64]" in text
    assert "decode_chunk[8]" in text
    assert "largest step gaps" in text
    assert "completed=1" in text


def test_attribution_switch_gates_everything():
    with use_registry() as reg, use_timeline() as tl:
        prev = set_attribution(False)
        try:
            tl.record_span("x", "decode", "serving", 0.0, 1.0)
            tl.decode_chunk("serving", 0.0, 1.0, steps=4)
            tl.decode_chunk("serving", 2.0, 1.0, steps=4)
            note_lookup("serve_step", hit=True)
            record_compile("serve_step", "shape", 1.0)
            observe_decode(get_model_config("tiny-test"),
                           {"batch": 2, "cache_slots": 8, "prefix_len": 0},
                           4, 1.0, program="serve_step")
            ev = SLOEvaluator()
            assert ev.observe("completed", ttft_s=0.1, e2e_s=0.2) is None
        finally:
            set_attribution(prev)
        assert tl.events() == []
        assert reg.instruments() == []


# -- compile stats ------------------------------------------------------------


def test_compilestats_counters_and_span():
    with use_registry() as reg, use_timeline() as tl:
        note_lookup("serve_step", hit=False)
        note_lookup("serve_step", hit=True)
        note_lookup("serve_step", hit=True)
        record_compile("serve_step", "shape", 1.25, track="serving",
                       key=("serve_step", 8, False))
        record_compile("serve_step", "decode_chunk", 0.5, track="serving")
        assert reg.counter("compile_cache_misses_total", component="compile",
                           program="serve_step").value == 1
        assert reg.counter("compile_cache_hits_total", component="compile",
                           program="serve_step").value == 2
        assert reg.counter("compiles_total", component="compile",
                           program="serve_step", reason="shape").value == 1
        assert reg.counter("compiles_total", component="compile",
                           program="serve_step",
                           reason="decode_chunk").value == 1
        h = reg.histogram("compile_seconds", component="compile",
                          program="serve_step")
        assert h.count == 2 and h.max == 1.25
        spans = [e for e in tl.events() if e["type"] == "span"]
        assert [s["name"] for s in spans] == ["compile:serve_step"] * 2
        assert all(s["cat"] == "compile" for s in spans)


# -- roofline -----------------------------------------------------------------


def test_decode_step_bytes_model():
    cfg = get_model_config("tiny-test")
    stats = {"batch": 4, "cache_slots": 96, "prefix_len": 0}
    model_item = 2 if cfg.dtype == "bfloat16" else 4
    per_slot = cfg.num_kv_heads * cfg.head_dim * model_item * 2 * cfg.num_layers
    expected = cfg.approx_param_count * model_item + 4 * 96 * per_slot
    assert decode_step_bytes(cfg, stats) == expected
    # The shared prefix adds one batch-wide read per step.
    with_prefix = decode_step_bytes(cfg, {**stats, "prefix_len": 64})
    assert with_prefix == expected + 64 * per_slot


def test_roofline_gauges_math():
    cfg = get_model_config("tiny-test")
    stats = {"batch": 4, "cache_slots": 96, "prefix_len": 0}
    with use_registry() as reg:
        set_achievable_gbps(100.0)
        try:
            out = observe_decode(cfg, stats, steps=10, wall_s=0.5,
                                 program="serve_step")
        finally:
            set_achievable_gbps(None)
        sb = decode_step_bytes(cfg, stats)
        assert out["step_bytes"] == sb
        assert out["gbps"] == pytest.approx(sb * 10 / 0.5 / 1e9)
        assert out["fraction"] == pytest.approx(out["gbps"] / 100.0)
        assert reg.read_value("achieved_over_achievable",
                              component="roofline",
                              program="serve_step") == pytest.approx(
            out["fraction"])
        assert reg.read_value("decode_step_bytes", component="roofline",
                              program="serve_step") == sb
        # No steps / no wall -> nothing observed (never a div-by-zero).
        assert observe_decode(cfg, stats, 0, 0.5, program="p") is None
        assert observe_decode(cfg, stats, 5, 0.0, program="p") is None


# -- SLO burn rates -----------------------------------------------------------


def test_slo_burn_rates_hand_computed():
    t = SLOTargets(ttft_p95_s=1.0, e2e_p99_s=10.0, error_rate=0.1,
                   fast_window_s=60.0, slow_window_s=600.0)
    clock = [1000.0]
    with use_registry() as reg:
        ev = SLOEvaluator(targets=t, clock=lambda: clock[0])
        # 8 good, 1 failed (ttft also over target), 1 expired (no ttft).
        for i in range(8):
            ev.observe("completed", ttft_s=0.5, e2e_s=1.0, t=1000.0 + i)
        ev.observe("failed", ttft_s=2.0, e2e_s=1.0, t=1009.0)
        ev.observe("expired", t=1010.0)
        out = ev.evaluate(now=1010.0)
        # errors: 2/10 observed vs 0.1 budget -> burn 2.0
        assert out["run"]["error_rate"] == pytest.approx(2.0)
        # ttft: 1 over of 9 with a ttft, vs 5% budget -> (1/9)/0.05
        assert out["run"]["ttft_p95"] == pytest.approx((1 / 9) / 0.05)
        assert out["run"]["e2e_p99"] == 0.0
        assert reg.read_value("slo_burn_rate", component="serving",
                              slo="error_rate",
                              window="run") == pytest.approx(2.0)
        # Crossing 1.0 counted exactly once per (slo, window).
        assert reg.counter("slo_alerts_total", component="serving",
                           slo="error_rate", window="run").value == 1
        # preempted is excluded entirely (infra scheduling, not failure).
        n = reg.read_value("slo_window_requests", component="serving",
                           window="run")
        ev.observe("preempted", t=1011.0)
        assert reg.read_value("slo_window_requests", component="serving",
                              window="run") == n


def test_slo_windows_age_out_and_alerts_resolve():
    t = SLOTargets(error_rate=0.5, fast_window_s=10.0, slow_window_s=1000.0)
    with use_registry() as reg:
        ev = SLOEvaluator(targets=t, clock=lambda: 0.0)
        ev.observe("failed", t=100.0)  # burn fast = (1/1)/0.5 = 2.0
        assert reg.read_value("slo_burn_rate", component="serving",
                              slo="error_rate",
                              window="fast") == pytest.approx(2.0)
        assert reg.counter("slo_alerts_total", component="serving",
                           slo="error_rate", window="fast").value == 1
        # 50s later the failure left the 10s fast window; two successes keep
        # the window populated -> burn 0, alert resolves, no double count.
        ev.observe("completed", t=150.0)
        ev.observe("completed", t=151.0)
        assert reg.read_value("slo_burn_rate", component="serving",
                              slo="error_rate", window="fast") == 0.0
        # slow window still sees 1 error of 3 -> (1/3)/0.5 < 1: no new alert
        assert reg.counter("slo_alerts_total", component="serving",
                           slo="error_rate", window="fast").value == 1
        # A second burst re-alerts (crossing again): three failures put the
        # fast window at 3 bad of 5 -> (3/5)/0.5 = 1.2 > 1.
        ev.observe("failed", t=152.0)
        ev.observe("failed", t=153.0)
        ev.observe("failed", t=154.0)
        assert reg.read_value("slo_burn_rate", component="serving",
                              slo="error_rate",
                              window="fast") == pytest.approx(1.2)
        assert reg.counter("slo_alerts_total", component="serving",
                           slo="error_rate", window="fast").value == 2


def test_slo_run_window_exact_past_deque_capacity():
    # An early error burst must NOT age out of the run window when the
    # bounded deque wraps — the --fail-on-burn gate reads run-window burns.
    t = SLOTargets(error_rate=0.1, fast_window_s=1.0, slow_window_s=2.0)
    with use_registry():
        ev = SLOEvaluator(targets=t, capacity=8, clock=lambda: 0.0)
        ev.observe("failed", t=0.0)
        for i in range(20):  # pushes the failure out of the deque
            ev.observe("completed", t=100.0 + i)
        out = ev.evaluate(now=200.0)
        assert out["run"]["error_rate"] == pytest.approx((1 / 21) / 0.1)
        assert out["fast"]["error_rate"] == 0.0


def test_slo_maybe_evaluate_decays_idle_windows():
    clock = [0.0]
    t = SLOTargets(error_rate=0.5, fast_window_s=10.0, slow_window_s=1000.0)
    with use_registry() as reg:
        ev = SLOEvaluator(targets=t, clock=lambda: clock[0])
        ev.observe("failed", t=5.0)
        assert reg.read_value("slo_burn_rate", component="serving",
                              slo="error_rate",
                              window="fast") == pytest.approx(2.0)
        # No further traffic: a loop calling maybe_evaluate decays the
        # fast window (and resolves the alert) once the failure ages out.
        clock[0] = 100.0
        ev.maybe_evaluate()
        assert reg.read_value("slo_burn_rate", component="serving",
                              slo="error_rate", window="fast") == 0.0
        # Run window keeps the whole-run truth.
        assert reg.read_value("slo_burn_rate", component="serving",
                              slo="error_rate",
                              window="run") == pytest.approx(2.0)


def test_slo_report_renders_from_snapshot():
    with use_registry() as reg:
        ev = SLOEvaluator(targets=SLOTargets(error_rate=0.1),
                          clock=lambda: 0.0)
        ev.observe("failed", t=1.0)
        text = render_slo_report(snapshot(reg))
        assert "error_rate" in text and "BURNING" in text
        assert "ttft_p95" in text  # gauges exist even with no ttft samples
    empty = render_slo_report({"gauges": [], "counters": []})
    assert "no slo_burn_rate gauges" in empty


# -- heartbeat gaps -----------------------------------------------------------


def test_heartbeat_gap_fake_clock():
    clock = [0.0]
    with use_registry() as reg:
        hb = Heartbeat(interval_s=10.0, name="sweep", clock=lambda: clock[0])
        assert hb.poke()            # first beat, no gap
        clock[0] = 11.0
        assert hb.poke()            # normal cadence: 11s < 1.5x interval
        assert reg.peek("heartbeat_gap_s", component="sweep") is None
        clock[0] = 14.0
        assert not hb.poke()        # within interval: no beat
        clock[0] = 61.0             # the loop went dark for 50s
        assert hb.poke()
        h = reg.histogram("heartbeat_gap_s", component="sweep")
        assert h.count == 1 and h.max == pytest.approx(50.0)
        assert reg.read_value("heartbeat_gap_max_s",
                              component="sweep") == pytest.approx(50.0)
        assert hb.max_gap_s == pytest.approx(50.0)


# -- scheduler / fleet integration --------------------------------------------


@pytest.fixture(scope="module")
def engine():
    from fairness_llm_tpu.runtime.engine import DecodeEngine

    return DecodeEngine(get_model_config("tiny-test"), seed=0)


def _greedy(m):
    return ModelSettings(temperature=0.0, top_k=0, top_p=1.0, max_tokens=m)


def _serve(engine, reqs, fault_injector=None):
    from fairness_llm_tpu.serving import ContinuousScheduler

    sched = ContinuousScheduler(
        engine,
        ServingConfig(enabled=True, num_slots=2, max_prompt_len=128,
                      max_new_tokens=8, decode_chunk=2),
        settings=_greedy(8),
        fault_injector=fault_injector,
    )
    return sched, sched.serve(reqs)


def test_scheduler_emits_spans_compiles_and_roofline(engine):
    from fairness_llm_tpu.serving import Request

    reqs = [Request(prompt=p, id=f"tl{i}", settings=_greedy(6))
            for i, p in enumerate(["one two three", "four five six",
                                   "seven eight nine"])]
    with use_registry() as reg, use_timeline() as tl:
        sched, results = _serve(engine, reqs)
        assert all(r.ok for r in results)
        spans = [e for e in tl.events() if e["type"] == "span"]
        cats = {s["cat"] for s in spans}
        assert {"prefill", "decode", "compile"} <= cats
        # Single-engine path: every span on the one "serving" track.
        assert {s["track"] for s in spans} == {"serving"}
        reqspans = [e for e in tl.events() if e["type"] == "request"]
        assert {e["name"] for e in reqspans} == {"tl0", "tl1", "tl2"}
        assert all(e["args"]["outcome"] == "completed" for e in reqspans)
        # Compile observability: this scheduler's first prefill bucket and
        # step program each compiled once; later chunks were cache hits.
        assert reg.counter("compiles_total", component="compile",
                           program="serve_step", reason="shape").value == 1
        assert reg.counter("compile_cache_misses_total",
                           component="compile",
                           program="serve_step").value == 1
        assert reg.counter("compile_cache_hits_total", component="compile",
                           program="serve_step").value >= 1
        # Live roofline gauges populated from real chunk walls.
        assert reg.read_value("achieved_over_achievable",
                              component="roofline",
                              program="serve_step") > 0
        assert reg.read_value("decode_step_bytes", component="roofline",
                              program="serve_step") == decode_step_bytes(
            engine.config,
            {"batch": 2, "cache_slots": sched.cache_len, "prefix_len": 0})
        # Step gaps: >= 2 chunks ran, so at least one gap was observed.
        assert reg.histogram("step_gap_s", component="serving").count >= 1
        # The export is schema-valid and carries the acceptance span kinds.
        trace = tl.to_chrome_trace()
        assert validate_chrome_trace(trace) == []


def test_eviction_requeue_events_on_track_and_ordered(engine):
    from fairness_llm_tpu.serving import Request
    from fairness_llm_tpu.telemetry import assert_span_order
    from fairness_llm_tpu.utils.failures import ScriptedFaultInjector

    reqs = [Request(prompt="the quick brown fox", id="flaky",
                    settings=_greedy(6)),
            Request(prompt="jumped over", id="calm", settings=_greedy(6))]
    inj = ScriptedFaultInjector({("flaky", "decode"): 1})
    with use_registry(), use_timeline() as tl:
        sched, results = _serve(engine, reqs, fault_injector=inj)
        assert all(r.ok for r in results)
        # The requeue instant landed on the scheduler's track, and the
        # request's lifecycle stayed ordered through eviction+readmission.
        instants = [e for e in tl.events() if e["type"] == "instant"]
        req_evs = [e for e in instants if e["args"].get("request_id")
                   == "flaky"]
        assert any(e["name"] == "requeued" for e in req_evs)
        assert {e["track"] for e in req_evs} == {"serving"}
        assert [e["name"] for e in req_evs].count("admitted") == 2
        for rid in ("flaky", "calm"):
            _, evs = next(f for f in sched.tracer.finished
                          if f[0].request_id == rid)
            assert_span_order(evs)
        # One balanced request span per request, despite the requeue.
        reqspans = [e for e in tl.events() if e["type"] == "request"]
        assert sorted(e["name"] for e in reqspans) == ["calm", "flaky"]
        assert validate_chrome_trace(tl.to_chrome_trace()) == []


def test_fleet_events_land_on_replica_tracks(engine):
    from fairness_llm_tpu.config import FleetConfig, IntegrityConfig
    from fairness_llm_tpu.serving import ReplicaSet, Request
    from fairness_llm_tpu.utils.failures import ScriptedFaultInjector

    reqs = [Request(prompt=f"prompt number {i} with words", id=f"fl{i}",
                    settings=_greedy(6)) for i in range(6)]
    inj = ScriptedFaultInjector(replica_crashes={"r1": 3})
    with use_registry(), use_timeline() as tl:
        fleet = ReplicaSet(
            engine,
            ServingConfig(enabled=True, num_slots=2, max_prompt_len=128,
                          max_new_tokens=8, decode_chunk=2),
            settings=_greedy(8),
            fleet=FleetConfig(replicas=2, fence_cooldown_s=0.01),
            resilience=ResilienceConfig(enabled=True, breaker_threshold=2,
                                        breaker_cooldown_s=0.01),
            fault_injector=inj,
            integrity=IntegrityConfig(canary_max_tokens=4),
        )
        results = fleet.serve(reqs)
        assert all(r.ok for r in results)
        assert inj.replica_faults_fired == [("r1", "replica_crash")]
        # The fence instant is pinned to the SICK replica's track.
        fences = [e for e in tl.events()
                  if e["type"] == "instant" and e["name"] == "fence"]
        assert fences and {e["track"] for e in fences} == {"r1"}
        assert fences[0]["args"]["reason"] == "replica_crash"
        # Both replicas decoded on their own tracks before/after the fence.
        decode_tracks = {e["track"] for e in tl.events()
                         if e["type"] == "span" and e["cat"] == "decode"}
        assert {"r0", "r1"} <= decode_tracks
        # Every request's terminal span sits on the replica that finished
        # it — never a mixed/unknown lane.
        reqspans = [e for e in tl.events() if e["type"] == "request"
                    and not e["name"].startswith("__")]
        assert {e["name"] for e in reqspans} == {f"fl{i}" for i in range(6)}
        assert {e["track"] for e in reqspans} <= {"r0", "r1"}
        assert validate_chrome_trace(tl.to_chrome_trace()) == []


def test_router_discounts_slo_burn(engine):
    from fairness_llm_tpu.serving.router import HealthRouter

    class _Q:
        closed = False
        full = False

        def __len__(self):
            return 0

    class _Pool:
        occupancy = 0

    class _Sched:
        breakers = None
        watchdog = None
        num_slots = 2
        queue = _Q()
        pool = _Pool()
        _pending = ()

    class _Rep:
        def __init__(self, name):
            self.name = name
            self.fenced = False
            self.sched = _Sched()

    with use_registry() as reg:
        router = HealthRouter()
        healthy, burning = _Rep("a"), _Rep("b")
        reg.gauge("slo_burn_rate", component="serving", replica="b",
                  slo="error_rate", window="fast").set(4.0)
        assert router.health_score(healthy) == 1.0
        assert router.health_score(burning) == pytest.approx(0.25)
        assert router.pick([healthy, burning]) is healthy
        # Burn below 1.0 is budget consumption WITHIN the SLO: no discount.
        reg.gauge("slo_burn_rate", component="serving", replica="b",
                  slo="error_rate", window="fast").set(0.9)
        assert router.health_score(burning) == 1.0


# -- CLI / validator surface --------------------------------------------------


def test_validate_telemetry_require_profile(engine, tmp_path):
    import sys

    sys.path.insert(0, "/root/repo/tools")
    try:
        from validate_telemetry import check
    finally:
        sys.path.pop(0)
    from fairness_llm_tpu.serving import Request
    from fairness_llm_tpu.telemetry import get_timeline, write_snapshot

    reqs = [Request(prompt=f"words here {i}", id=f"vp{i}",
                    settings=_greedy(6)) for i in range(3)]
    with use_registry() as reg, use_timeline():
        _serve(engine, reqs)
        write_snapshot(reg, str(tmp_path))
        # trace.json missing -> --require-profile fails naming it.
        assert check(str(tmp_path), require_profile=True) == 1
        get_timeline().export(str(tmp_path / "trace.json"))
        assert check(str(tmp_path), require_profile=True) == 0


def test_cli_slo_report_and_timeline_section(engine, tmp_path, capsys):
    from fairness_llm_tpu.cli.main import main as cli_main
    from fairness_llm_tpu.serving import Request
    from fairness_llm_tpu.telemetry import get_timeline, write_snapshot

    reqs = [Request(prompt=f"more words {i}", id=f"cli{i}",
                    settings=_greedy(6)) for i in range(3)]
    with use_registry() as reg, use_timeline():
        _serve(engine, reqs)
        write_snapshot(reg, str(tmp_path))
        get_timeline().export(str(tmp_path / "trace.json"))
    assert cli_main(["slo-report", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "SLO BURN RATES" in out and "error_rate" in out
    assert cli_main(["telemetry-report", str(tmp_path), "--validate",
                     "--timeline"]) == 0
    out = capsys.readouterr().out
    assert "TIMELINE SUMMARY" in out and "decode_chunk" in out
    assert "snapshot schema: OK" in out


def test_engine_generate_records_attribution(engine):
    with use_registry() as reg, use_timeline() as tl:
        out = engine.generate(["alpha beta gamma"], _greedy(5), seed=0)
        assert out.texts
        spans = [e for e in tl.events() if e["type"] == "span"]
        gen = [s for s in spans if s["name"].startswith("generate[")]
        assert gen and gen[0]["track"] == "engine"
        # A fresh (batch, prompt, max_new) key compiled under this registry.
        assert reg.counter("compiles_total", component="compile",
                           program="decode", reason="shape").value >= 1
        assert reg.read_value("achieved_over_achievable",
                              component="roofline", program="decode") > 0


def test_chrome_trace_json_roundtrip(tmp_path):
    tl = Timeline()
    tl.record_span("s", "decode", "serving", 0.0, 1.0)
    path = tl.export(str(tmp_path / "sub" / "trace.json"))
    with open(path) as f:
        loaded = json.load(f)
    assert validate_chrome_trace(loaded) == []
    assert loaded["displayTimeUnit"] == "ms"

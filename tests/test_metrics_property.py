"""Property-based tests for the jit metric kernels: the interned-ID/one-hot
formulations must agree with straightforward set/float math on arbitrary
inputs, not just the golden cases (tests/test_metrics_golden.py pins the
reference's committed values; this pins the MATH for everything else).
"""

import math

import numpy as np
import pytest

pytest.importorskip("hypothesis")  # property tests skip where hypothesis isn't baked in
from hypothesis import given, settings
from hypothesis import strategies as st

from fairness_llm_tpu import metrics as M

TITLES = [f"t{i}" for i in range(12)]

rec_list = st.lists(st.sampled_from(TITLES), min_size=0, max_size=8, unique=True)


def naive_jaccard(a, b):
    # Empty-vs-empty scores 1.0 (reference utils.py:232-233 convention).
    sa, sb = set(a), set(b)
    u = len(sa | sb)
    return len(sa & sb) / u if u else 1.0


@settings(max_examples=60, deadline=None)
@given(st.lists(rec_list, min_size=2, max_size=6))
def test_individual_fairness_matches_naive_pairwise_jaccard(lists):
    recs = {f"p{i}": lst for i, lst in enumerate(lists)}
    pairs = [
        (f"p{i}", f"p{j}") for i in range(len(lists)) for j in range(i + 1, len(lists))
    ]
    score, details = M.individual_fairness(pairs, recs)
    expected = [naive_jaccard(recs[a], recs[b]) for a, b in pairs]
    assert math.isclose(score, float(np.mean(expected)), abs_tol=1e-5)
    np.testing.assert_allclose(details, expected, atol=1e-5)


@settings(max_examples=60, deadline=None)
@given(st.lists(rec_list.filter(len), min_size=2, max_size=4),
       st.lists(rec_list.filter(len), min_size=2, max_size=4))
def test_demographic_parity_bounds_and_symmetry(g1, g2):
    score_ab, _ = M.demographic_parity({"a": g1, "b": g2})
    score_ba, _ = M.demographic_parity({"b": g2, "a": g1})
    assert 0.0 - 1e-6 <= score_ab <= 1.0 + 1e-6
    assert math.isclose(score_ab, score_ba, abs_tol=1e-5)
    # identical groups -> zero divergence -> perfect parity
    same, _ = M.demographic_parity({"a": g1, "b": [list(r) for r in g1]})
    assert math.isclose(same, 1.0, abs_tol=1e-5)


@settings(max_examples=60, deadline=None)
@given(st.lists(st.sampled_from(["x", "y"]), min_size=1, max_size=20))
def test_exposure_ratio_matches_naive(groups):
    ratio, per_group = M.exposure_ratio(groups)
    exp = {}
    for pos, g in enumerate(groups):
        exp.setdefault(g, []).append(1.0 / math.log2(pos + 2))
    means = {g: float(np.mean(v)) for g, v in exp.items()}
    expected = min(means.values()) / max(means.values()) if max(means.values()) > 0 else 0.0
    assert math.isclose(ratio, expected, abs_tol=1e-5)
    for g, m in means.items():
        assert math.isclose(per_group[g], m, abs_tol=1e-5)


@settings(max_examples=60, deadline=None)
@given(st.lists(rec_list, min_size=1, max_size=4),
       st.lists(rec_list, min_size=1, max_size=4),
       st.sets(st.sampled_from(TITLES), min_size=1, max_size=6))
def test_equal_opportunity_matches_naive(lists_a, lists_b, qualified):
    """Kernel semantics: per group, hit rate = |unique recommended ∩ qualified|
    / total recommended (duplicates count in the denominator only — the
    reference's set-vs-len math); score = 1 / (1 + var(rates))."""
    by_group = {"a": lists_a, "b": lists_b}
    score, details = M.equal_opportunity(by_group, qualified)

    def hit_rate(lists):
        flat = [t for l in lists for t in l]
        if not flat:
            return 0.0
        return len(set(flat) & qualified) / len(flat)

    rates = [hit_rate(v) for v in by_group.values()]
    expected = 1.0 / (1.0 + float(np.var(rates)))
    assert math.isclose(score, expected, abs_tol=1e-5)


@settings(max_examples=60, deadline=None)
@given(rec_list, st.lists(rec_list, min_size=1, max_size=5))
def test_snsr_snsv_matches_definition(neutral, group_lists):
    """SNSR = max - min of group-vs-neutral Jaccard; SNSV = their std."""
    by_group = {f"g{i}": lst for i, lst in enumerate(group_lists)}
    snsr, snsv, sims = M.snsr_snsv(neutral, by_group)
    expected = {g: naive_jaccard(lst, neutral) for g, lst in by_group.items()}
    for g in by_group:
        assert math.isclose(sims[g], expected[g], abs_tol=1e-5)
    vals = list(expected.values())
    assert math.isclose(snsr, max(vals) - min(vals), abs_tol=1e-5)
    assert math.isclose(snsv, float(np.std(vals)), abs_tol=1e-4)


@settings(max_examples=40, deadline=None)
@given(st.lists(st.sampled_from(TITLES), min_size=1, max_size=10, unique=True),
       st.dictionaries(st.sampled_from(TITLES), st.floats(0.1, 1.0), min_size=1, max_size=10))
def test_ndcg_bounded_and_perfect_on_ideal(ranking, truth):
    score = M.ndcg(ranking, truth, k=10)
    assert -1e-6 <= score <= 1.0 + 1e-6
    ideal = sorted(truth, key=lambda t: -truth[t])
    assert math.isclose(M.ndcg(ideal, truth, k=10), 1.0, abs_tol=1e-5)


@settings(max_examples=40, deadline=None)
@given(st.lists(st.floats(-20.0, 0.0), min_size=1, max_size=30))
def test_model_confidences_monotone_in_logprob(lps):
    """Both calibration mappings must preserve likelihood ordering."""
    from fairness_llm_tpu.pipeline.facter import model_confidences

    arr = np.array(lps)
    for mapping in ("percentile", "probability"):
        conf = model_confidences(arr, mapping)
        order = np.argsort(arr, kind="stable")
        assert (np.diff(conf[order]) >= -1e-7).all(), mapping

"""Speculative decoding: parity with plain greedy decode + n-gram lookup unit
tests.

The correctness contract (ISSUE 1) is *token-for-token identity with greedy
decode* — speculation may only change speed. The parity tests pin that across
batch sizes, shared-prefix on/off, early-EOS rows, and the dp×tp mesh; the
lookup tests pin the drafting math on synthetic repetitive prompts.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from fairness_llm_tpu.config import ModelSettings, SpeculationConfig
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.models.tokenizer import ByteTokenizer
from fairness_llm_tpu.runtime.engine import DecodeEngine
from fairness_llm_tpu.runtime.sampling import (
    SamplerSettings,
    greedy_accept_length,
    speculation_applicable,
)
from fairness_llm_tpu.runtime.speculative import ngram_draft


@pytest.fixture(scope="module")
def engine():
    return DecodeEngine(get_model_config("tiny-test"), seed=0)


GREEDY = ModelSettings(temperature=0.0, max_tokens=24)
SPEC = SpeculationConfig(enabled=True, ngram_max=3, draft_len=4)


# -- parity with plain greedy decode ----------------------------------------


@pytest.mark.parametrize("nprompts", [1, 3, 9])
def test_spec_matches_greedy_across_batch_sizes(engine, nprompts):
    prompts = [
        "the quick brown fox", "hi", "abc abc abc abc abc abc",
        "a much longer prompt that shifts padding around quite a bit",
        "movies", "fairness", "one two three one two three",
        "zz", "recommend ten films please",
    ][:nprompts]
    plain = engine.generate(prompts, GREEDY)
    spec = engine.generate(prompts, GREEDY, speculation=SPEC)
    np.testing.assert_array_equal(plain.tokens, spec.tokens)
    assert "speculation" in spec.stats and "speculation" not in plain.stats


@pytest.mark.parametrize("share", [False, True])
def test_spec_matches_greedy_with_shared_prefix(engine, share):
    common = "shared instruction block " * 8
    prompts = [common + f"user {i} tail" for i in range(5)]
    plain = engine.generate(prompts, GREEDY, share_prefix=share)
    spec = engine.generate(prompts, GREEDY, share_prefix=share, speculation=SPEC)
    np.testing.assert_array_equal(plain.tokens, spec.tokens)
    if share:
        assert spec.stats["prefix_len"] > 0  # the prefix path actually ran


def test_spec_matches_greedy_with_early_eos(engine):
    """Rows must stop at EOS mid-window exactly like the plain loop (EOS
    recorded, pads after). A random model rarely samples the real EOS, so
    re-tokenize with an eos_id chosen FROM the plain greedy stream — same
    params, same argmaxes, but now one row provably hits EOS mid-decode."""
    prompts = ["the quick brown fox", "hi there", "abc"]
    plain0 = engine.generate(prompts, GREEDY)
    eos = int(plain0.tokens[0][5])  # appears mid-stream in row 0

    tok = ByteTokenizer(512)
    tok.eos_id = eos
    eng2 = DecodeEngine(
        get_model_config("tiny-test"), params=engine.params, tokenizer=tok
    )
    plain = eng2.generate(prompts, GREEDY)
    spec = eng2.generate(prompts, GREEDY, speculation=SPEC)
    np.testing.assert_array_equal(plain.tokens, spec.tokens)
    # the early-EOS case genuinely occurred: row 0 stops, pads after EOS
    row = list(plain.tokens[0])
    assert eos in row
    after = row[row.index(eos) + 1 :]
    assert all(t == tok.pad_id for t in after)
    assert len(after) > 0


def test_spec_sharded_matches_unsharded(engine, eight_device_mesh):
    cfg = get_model_config("tiny-test")
    sharded = DecodeEngine(cfg, params=engine.params, mesh=eight_device_mesh)
    prompts = ["the quick brown fox", "hi there", "fairness", "movies"]
    a = engine.generate(prompts, GREEDY, speculation=SPEC)
    b = sharded.generate(prompts, GREEDY, speculation=SPEC)
    np.testing.assert_array_equal(a.tokens, b.tokens)


def test_spec_repetitive_prompt_accepts_drafts(engine):
    """A decode that settles into repetition (what a prompt full of repeated
    structure induces) must actually ACCEPT lookup drafts — acceptance is
    what makes speculation a perf feature rather than dead weight."""
    g = ModelSettings(temperature=0.0, max_tokens=48)
    common = "list list list list " * 6
    prompts = [common + "a", common + "b"]
    plain = engine.generate(prompts, g)
    spec = engine.generate(prompts, g, speculation=SPEC)
    np.testing.assert_array_equal(plain.tokens, spec.tokens)
    st = spec.stats["speculation"]
    assert st["accepted"] > 0
    assert st["verify_steps"] < 48  # strictly fewer loop trips than plain
    assert 0.0 < st["acceptance_rate"] <= 1.0
    assert st["emitted"] == int(np.sum(spec.tokens != engine.tokenizer.pad_id))


def test_spec_temperature_falls_back_to_plain_sampling(engine):
    """Sampled settings take the plain path byte-for-byte (same programs,
    same row-seed streams) and report no speculation stats."""
    s = ModelSettings(temperature=0.9, max_tokens=10)
    a = engine.generate(["hello there"], s, row_seeds=[123])
    b = engine.generate(["hello there"], s, row_seeds=[123], speculation=SPEC)
    np.testing.assert_array_equal(a.tokens, b.tokens)
    assert "speculation" not in b.stats
    assert not speculation_applicable(SamplerSettings(temperature=0.9))
    assert speculation_applicable(SamplerSettings(temperature=0.0))


def test_spec_compile_keys_disjoint(engine):
    """The satellite fix: the compile key's leading tag is the speculation
    slot — speculative and plain programs live under disjoint keys, so
    toggling speculation can never reuse a stale compiled step."""
    engine.generate(["hi"], GREEDY)
    engine.generate(["hi"], GREEDY, speculation=SPEC)
    kinds = {k[0] for k in engine._compiled if isinstance(k[0], str)}
    assert "decode" in kinds and "spec_decode" in kinds
    spec_keys = [k for k in engine._compiled if k[0] == "spec_decode"]
    assert all((SPEC.ngram_max, SPEC.draft_len) == k[-2:] for k in spec_keys)


def test_engine_backend_accumulates_spec_totals(engine):
    """The sweep-level observability chain: EngineBackend merges per-call
    counters into spec_totals (what phase 1/2 record in result metadata)."""
    from fairness_llm_tpu.pipeline.backends import EngineBackend

    be = EngineBackend(engine, name="tiny-test", speculation=SPEC)
    be.generate(["abc abc abc abc"], GREEDY, keys=["a"])
    steps1 = be.spec_totals.verify_steps
    be.generate(["def def def def"], GREEDY, keys=["b"])
    assert be.spec_totals.verify_steps > steps1
    assert set(be.spec_totals.as_dict()) >= {
        "drafted", "accepted", "acceptance_rate", "verify_steps", "emitted",
    }
    # sampled settings must not touch the totals (plain path, no stats)
    before = be.spec_totals.as_dict()
    be.generate(["xyz"], ModelSettings(temperature=0.8, max_tokens=6), keys=["c"])
    assert be.spec_totals.as_dict() == before


# -- n-gram lookup unit tests ------------------------------------------------


def _draft(ctx, valid, hist_end, k=4, n=3, pad=0):
    return np.asarray(ngram_draft(
        jnp.asarray(ctx, jnp.int32), jnp.asarray(valid, bool),
        jnp.asarray(hist_end, jnp.int32), k, n, pad,
    ))


def test_ngram_draft_repetitive_history():
    # history: 5 6 7 5 6 7 5 6 — suffix [7 5 6] matches ending at position 4,
    # drafts continue from position 5: [7 5 6]; the 4th draft position (8)
    # lies beyond hist_end, so it pads (drafts only source from history).
    ctx = np.array([[5, 6, 7, 5, 6, 7, 5, 6, 0, 0, 0, 0]])
    valid = ctx != 0
    out = _draft(ctx, valid, [8])
    np.testing.assert_array_equal(out[0], [7, 5, 6, 0])


def test_ngram_draft_prefers_longest_ngram():
    # suffix ...9 2 3 matches once (after 1), but the 1-gram 3 also occurs
    # later followed by 8 — the 3-gram match must win.
    ctx = np.array([[9, 2, 3, 4, 3, 8, 9, 2, 3, 0, 0, 0]])
    valid = ctx != 0
    out = _draft(ctx, valid, [9])
    np.testing.assert_array_equal(out[0], [4, 3, 8, 9])


def test_ngram_draft_no_match_gives_pads():
    ctx = np.array([[1, 2, 3, 4, 5, 6, 7, 8]])
    out = _draft(ctx, np.ones_like(ctx, bool), [8], pad=0)
    np.testing.assert_array_equal(out[0], [0, 0, 0, 0])


def test_ngram_draft_window_must_be_valid_and_pads_at_gaps():
    # A matching window containing an invalid position must not match; and a
    # draft that reads across a pad gap yields pad at the invalid slots
    # (verification then simply rejects from there on).
    ctx = np.array([[5, 6, 7, 9, 9, 5, 6, 7, 8, 5, 6, 7]])
    valid = np.ones_like(ctx, bool)
    valid[0, 0] = False  # the window [5 6 7] ending at 2 straddles the gap
    out = _draft(ctx, valid, [12])
    # earliest VALID match of suffix [5 6 7] ends at position 7 -> draft 8 5 6 7
    np.testing.assert_array_equal(out[0], [8, 5, 6, 7])


def test_ngram_draft_truncates_at_history_end():
    # match near the end of history: drafts past hist_end are pads
    ctx = np.array([[1, 2, 3, 1, 2, 3, 0, 0, 0, 0]])
    valid = ctx != 0
    out = _draft(ctx, valid, [6], pad=0)
    # suffix [3 1 2]? hist is 1 2 3 1 2 3: suffix (n=3) = [1 2 3] wait —
    # last three = [1, 2, 3] at positions 3..5; match ends at position 2,
    # drafts = positions 3..6 = [1, 2, 3, pad]
    np.testing.assert_array_equal(out[0], [1, 2, 3, 0])


def test_ngram_draft_per_row_independent():
    ctx = np.array([
        [5, 6, 7, 5, 6, 7, 5, 6, 0, 0, 0, 0],
        [1, 2, 3, 4, 5, 6, 7, 8, 0, 0, 0, 0],
    ])
    valid = ctx != 0
    out = _draft(ctx, valid, [8, 8])
    np.testing.assert_array_equal(out[0], [7, 5, 6, 0])
    np.testing.assert_array_equal(out[1], [0, 0, 0, 0])


def test_greedy_accept_length():
    drafts = jnp.asarray([[4, 5, 6], [4, 9, 6], [9, 5, 6], [4, 5, 6]])
    greedy = jnp.asarray([[4, 5, 6], [4, 5, 6], [4, 5, 6], [4, 5, 9]])
    np.testing.assert_array_equal(
        np.asarray(greedy_accept_length(drafts, greedy)), [3, 1, 0, 2]
    )

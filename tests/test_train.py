"""Training-step tests: loss decreases, sharded step runs on the 8-device mesh,
remat matches non-remat numerics."""


import jax
import jax.numpy as jnp
import numpy as np

from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.train import make_train_step


def _toy_batch(rng, batch=8, seq=16, vocab=512):
    tokens = rng.integers(3, vocab, size=(batch, seq)).astype(np.int32)
    valid = np.ones((batch, seq), dtype=bool)
    return jnp.asarray(tokens), jnp.asarray(valid)


def test_loss_decreases_single_device():
    cfg = get_model_config("tiny-test")
    init_state, step = make_train_step(cfg)
    state = init_state(jax.random.key(0))
    rng = np.random.default_rng(0)
    tokens, valid = _toy_batch(rng)  # overfit one batch
    losses = []
    for _ in range(8):
        state, loss = step(state, tokens, valid)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_sharded_train_step(eight_device_mesh):
    cfg = get_model_config("tiny-test")
    init_state, step = make_train_step(cfg, mesh=eight_device_mesh)
    state = init_state(jax.random.key(0))
    rng = np.random.default_rng(1)
    tokens, valid = _toy_batch(rng, batch=8)
    state, loss = step(state, tokens, valid)
    assert np.isfinite(float(loss))
    assert int(state.step) == 1


def test_remat_matches_no_remat():
    cfg = get_model_config("tiny-test")
    init_a, step_a = make_train_step(cfg, remat=False)
    init_b, step_b = make_train_step(cfg, remat=True)
    sa = init_a(jax.random.key(2))
    sb = init_b(jax.random.key(2))
    rng = np.random.default_rng(2)
    tokens, valid = _toy_batch(rng, batch=4, seq=12)
    _, la = step_a(sa, tokens, valid)
    _, lb = step_b(sb, tokens, valid)
    np.testing.assert_allclose(float(la), float(lb), rtol=1e-5)

"""Oracle tests for the fused decode-attention kernel (interpret mode).

The kernel (``ops/decode_attention.py``) is OFF by default — measured ~8%
slower than XLA's fusions on the sweep (docs/PERFORMANCE.md round 3) — but
stays in the tree as oracle-verified groundwork for a head-major cache
layout. These tests pin its semantics against a dense reference: GQA head
mapping, partial validity masks, the shared-prefix joint softmax (including
the 128-padding mask), and the engine-facing gate.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fairness_llm_tpu.ops.decode_attention import (
    decode_attention,
    decode_attn_supported,
)


def _oracle(q, k, v, valid, sk=None, sv=None):
    B, H, D = q.shape
    rep = H // k.shape[2]
    kk = jnp.repeat(k, rep, axis=2)
    vv = jnp.repeat(v, rep, axis=2)
    s_own = jnp.einsum("bhd,blhd->bhl", q, kk) * D ** -0.5
    s_own = jnp.where(valid[:, None, :], s_own, -1e30)
    if sk is not None:
        P = sk.shape[0]
        sk2 = jnp.repeat(sk, rep, axis=1)
        sv2 = jnp.repeat(sv, rep, axis=1)
        s_sh = jnp.einsum("bhd,phd->bhp", q, sk2) * D ** -0.5
        s = jnp.concatenate([s_sh, s_own], axis=-1)
        vj = jnp.concatenate(
            [jnp.broadcast_to(sv2[None], (B, P, H, D)), vv], axis=1
        )
    else:
        s, vj = s_own, vv
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhl,blhd->bhd", p, vj)


@pytest.mark.parametrize("shared_p", [None, 96, 128])
@pytest.mark.parametrize("hkv", [2, 4])
def test_kernel_matches_dense_oracle(shared_p, hkv):
    rng = np.random.default_rng(0)
    B, H, D, L = 8, 4, 64, 256
    q = jnp.asarray(rng.normal(size=(B, H, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, L, hkv, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, L, hkv, D)).astype(np.float32))
    valid = jnp.asarray(rng.random((B, L)) < 0.5).at[:, 0].set(True)
    shared = None
    if shared_p:
        sk = jnp.asarray(rng.normal(size=(shared_p, hkv, D)).astype(np.float32))
        sv = jnp.asarray(rng.normal(size=(shared_p, hkv, D)).astype(np.float32))
        shared = (sk, sv)
    got = decode_attention(q, k, v, valid, shared, interpret=True)
    want = _oracle(q, k, v, valid, *(shared or (None, None)))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_supported_gate():
    assert decode_attn_supported(48, 256, 64)
    assert not decode_attn_supported(45, 256, 64)  # batch not 8-multiple
    assert not decode_attn_supported(48, 224, 64)  # cache not 128-multiple
    assert not decode_attn_supported(48, 256, 48)  # head_dim not 64-multiple
    # batch-blocking keeps big batches eligible (rows are independent);
    # the per-block VMEM budget still bounds cache length x head_dim
    assert decode_attn_supported(192, 384, 64)
    assert decode_attn_supported(360, 256, 64, kv_itemsize=1)
    assert not decode_attn_supported(48, 4096, 64)  # 8-row block over budget
    assert decode_attn_supported(48, 4096, 64, kv_itemsize=1)  # int8: half bytes
    assert decode_attn_supported(48, 256, 64, shared_len=704)  # the sweep shape
    # a multi-thousand-token shared prefix joins the VMEM accounting
    assert not decode_attn_supported(48, 256, 64, shared_len=30000)


def test_batch_block_choice():
    from fairness_llm_tpu.ops.decode_attention import _pick_batch_block

    # whole batch when it fits; largest dividing 8-multiple otherwise
    assert _pick_batch_block(48, 256, 64, 0, 4) == 48
    bb = _pick_batch_block(360, 256, 64, 0, 1)
    assert bb > 0 and 360 % bb == 0 and bb % 8 == 0 and bb < 360


@pytest.mark.parametrize("shared_p", [None, 96])
def test_kernel_int8_cache_matches_dequant_oracle(shared_p):
    """int8-cache mode: the kernel must equal dense attention over the
    DEQUANTIZED cache (scale-folding into scores/probs is exact math, so
    tolerance is float rounding, not quantization error)."""
    rng = np.random.default_rng(2)
    B, H, hkv, D, L = 8, 4, 2, 64, 256
    q = jnp.asarray(rng.normal(size=(B, H, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, L, hkv, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, L, hkv, D)).astype(np.float32))
    valid = jnp.asarray(rng.random((B, L)) < 0.5).at[:, 0].set(True)

    from fairness_llm_tpu.models.transformer import _dequantize_kv, _quantize_kv

    qk, ks = _quantize_kv(k)
    qv, vs = _quantize_kv(v)
    shared = None
    if shared_p:
        sk = jnp.asarray(rng.normal(size=(shared_p, hkv, D)).astype(np.float32))
        sv = jnp.asarray(rng.normal(size=(shared_p, hkv, D)).astype(np.float32))
        shared = (sk, sv)
    got = decode_attention(
        q, qk, qv, valid, shared, k_scale=ks, v_scale=vs, interpret=True
    )
    want = _oracle(
        q, _dequantize_kv(qk, ks, jnp.float32), _dequantize_kv(qv, vs, jnp.float32),
        valid, *(shared or (None, None)),
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_kernel_int8_requires_both_scales():
    q = jnp.zeros((8, 4, 64), jnp.float32)
    k = jnp.zeros((8, 128, 2, 64), jnp.int8)
    valid = jnp.ones((8, 128), bool)
    with pytest.raises(ValueError, match="k_scale and v_scale"):
        decode_attention(q, k, k, valid, k_scale=jnp.ones((8, 128, 2)), interpret=True)


def test_zero_length_prefix_is_no_prefix():
    rng = np.random.default_rng(1)
    B, H, Hkv, D, L = 8, 4, 2, 64, 128
    q = jnp.asarray(rng.normal(size=(B, H, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, L, Hkv, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, L, Hkv, D)).astype(np.float32))
    valid = jnp.asarray(np.ones((B, L), bool))
    empty = (jnp.zeros((0, Hkv, D)), jnp.zeros((0, Hkv, D)))
    got = decode_attention(q, k, v, valid, empty, interpret=True)
    want = decode_attention(q, k, v, valid, None, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def _oracle_multi(q, k, v, valid, offs, sk=None, sv=None):
    """Dense reference for the multi-query (speculative verify) kernel mode:
    query i of row b sees own-cache slot j iff valid AND j <= offs[b] + i;
    shared-prefix slots are always visible."""
    B, Q, H, D = q.shape
    L = k.shape[1]
    rep = H // k.shape[2]
    kk = jnp.repeat(k, rep, axis=2)
    vv = jnp.repeat(v, rep, axis=2)
    s_own = jnp.einsum("bqhd,blhd->bhql", q, kk) * D ** -0.5
    j = jnp.arange(L)
    causal = j[None, None, :] <= offs[:, None, None] + jnp.arange(Q)[None, :, None]
    allowed = valid[:, None, :] & causal  # [B, Q, L]
    s_own = jnp.where(allowed[:, None, :, :], s_own, -1e30)
    if sk is not None:
        P = sk.shape[0]
        sk2 = jnp.repeat(sk, rep, axis=1)
        sv2 = jnp.repeat(sv, rep, axis=1)
        s_sh = jnp.einsum("bqhd,phd->bhqp", q, sk2) * D ** -0.5
        s = jnp.concatenate([s_sh, s_own], axis=-1)
        vj = jnp.concatenate(
            [jnp.broadcast_to(sv2[None], (B, P, H, D)), vv], axis=1
        )
    else:
        s, vj = s_own, vv
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhql,blhd->bqhd", p, vj)


@pytest.mark.parametrize("shared_p", [None, 96])
@pytest.mark.parametrize("hkv", [2, 4])
def test_multi_query_kernel_matches_dense_oracle(shared_p, hkv):
    """q_len > 1 (speculative verify window) with per-row causal offsets."""
    rng = np.random.default_rng(3)
    B, Q, H, D, L = 8, 4, 4, 64, 256
    q = jnp.asarray(rng.normal(size=(B, Q, H, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, L, hkv, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, L, hkv, D)).astype(np.float32))
    offs = jnp.asarray(rng.integers(1, L - Q, size=B).astype(np.int32))
    # valid: everything at/below the verify window (the engine invariant),
    # with a few earlier holes to exercise the mask AND.
    j = np.arange(L)[None, :]
    valid_np = j <= (np.asarray(offs)[:, None] + Q - 1)
    valid_np &= rng.random((B, L)) < 0.9
    valid_np[:, 0] = True
    valid = jnp.asarray(valid_np)
    shared = None
    if shared_p:
        sk = jnp.asarray(rng.normal(size=(shared_p, hkv, D)).astype(np.float32))
        sv = jnp.asarray(rng.normal(size=(shared_p, hkv, D)).astype(np.float32))
        shared = (sk, sv)
    got = decode_attention(q, k, v, valid, shared, q_offsets=offs, interpret=True)
    want = _oracle_multi(q, k, v, valid, offs, *(shared or (None, None)))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_multi_query_kernel_int8_cache_matches_dequant_oracle():
    rng = np.random.default_rng(4)
    B, Q, H, hkv, D, L = 8, 3, 4, 2, 64, 256
    q = jnp.asarray(rng.normal(size=(B, Q, H, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, L, hkv, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, L, hkv, D)).astype(np.float32))
    offs = jnp.asarray(rng.integers(0, L - Q, size=B).astype(np.int32))
    valid = jnp.asarray(rng.random((B, L)) < 0.8).at[:, 0].set(True)

    from fairness_llm_tpu.models.transformer import _dequantize_kv, _quantize_kv

    qk, ks = _quantize_kv(k)
    qv, vs = _quantize_kv(v)
    got = decode_attention(
        q, qk, qv, valid, None, k_scale=ks, v_scale=vs, q_offsets=offs,
        interpret=True,
    )
    want = _oracle_multi(
        q, _dequantize_kv(qk, ks, jnp.float32),
        _dequantize_kv(qv, vs, jnp.float32), valid, offs,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_multi_query_requires_offsets_and_gate_accounts_q():
    q4 = jnp.zeros((8, 4, 4, 64), jnp.float32)
    k = jnp.zeros((8, 128, 2, 64), jnp.float32)
    valid = jnp.ones((8, 128), bool)
    with pytest.raises(ValueError, match="q_offsets"):
        decode_attention(q4, k, k, valid, interpret=True)
    # the VMEM model must charge q_len (a huge window fails where q=1 passes)
    assert decode_attn_supported(48, 4096, 64, kv_itemsize=1, q_len=1)
    assert decode_attn_supported(48, 256, 64, q_len=9)


def test_model_gate_off_by_default_and_off_paths():
    """The model only takes the kernel on TPU + flag + compatible config;
    in this CPU suite the gate must always be False so decode behavior (and
    every parity/golden test) is byte-stable."""
    import dataclasses

    from fairness_llm_tpu.models.configs import get_model_config
    from fairness_llm_tpu.models.transformer import Attention

    cfg = get_model_config("gpt2-small")
    assert not cfg.use_decode_attention_kernel  # measured slower: default off
    on = dataclasses.replace(cfg, use_decode_attention_kernel=True)
    attn = Attention(on)
    # CPU backend -> gated off even when the flag is set
    assert not attn._decode_kernel_ok(1, object(), 48, 256)


def test_engine_vmem_compile_fallback(monkeypatch):
    """A Mosaic scoped-VMEM compile failure (the gate's calibrated byte
    model under-predicting) must degrade the engine to the XLA attention
    path, not fail generate(): the engine catches the error, disables the
    kernel flag, recompiles once, and serves."""
    import dataclasses

    from fairness_llm_tpu.config import ModelSettings
    from fairness_llm_tpu.models.configs import get_model_config
    from fairness_llm_tpu.runtime.engine import DecodeEngine

    cfg = dataclasses.replace(
        get_model_config("tiny-test"), use_decode_attention_kernel=True
    )
    eng = DecodeEngine(cfg, seed=0)
    real = DecodeEngine._decode_fn
    state = {"raised": False}

    def fake_decode_fn(self, *args, **kwargs):
        if not state["raised"]:
            state["raised"] = True

            def boom(*a, **k):
                # The realistic shape: Mosaic rejections surface through
                # the XLA runtime layer (jax.errors.JaxRuntimeError is the
                # XlaRuntimeError alias), which is what the engine's
                # narrowed compile-error check matches on.
                import jax

                raise jax.errors.JaxRuntimeError(
                    "Ran out of scoped vmem while compiling the kernel"
                )

            return boom
        return real(self, *args, **kwargs)

    monkeypatch.setattr(DecodeEngine, "_decode_fn", fake_decode_fn)
    out = eng.generate(
        ["hello there", "general kenobi"],
        ModelSettings(temperature=0.0, max_tokens=4),
        seed=0,
    )
    assert state["raised"]
    assert not eng.config.use_decode_attention_kernel  # fell back
    # The downgrade is RECORDED: stats carry the effective attention path,
    # so a record produced past a gate miss can't claim kernel provenance.
    assert out.stats["decode_kernel"] is False
    assert len(out.texts) == 2

    # A non-VMEM error (or one with the kernel already off) still raises.
    state["raised"] = False

    def fake_other(self, *args, **kwargs):
        def boom(*a, **k):
            raise RuntimeError("unrelated failure")

        return boom

    monkeypatch.setattr(DecodeEngine, "_decode_fn", fake_other)
    eng2 = DecodeEngine(cfg, seed=0)
    with pytest.raises(RuntimeError, match="unrelated"):
        eng2.generate(["x"], ModelSettings(temperature=0.0, max_tokens=2))

    # Narrowed catch: an arbitrary PYTHON exception that merely mentions
    # 'vmem' is NOT a kernel compile failure and must propagate instead of
    # silently downgrading the engine (the old substring-only match
    # absorbed it).
    def fake_lookalike(self, *args, **kwargs):
        def boom(*a, **k):
            raise RuntimeError("user callback touched vmem stats")

        return boom

    monkeypatch.setattr(DecodeEngine, "_decode_fn", fake_lookalike)
    eng3 = DecodeEngine(cfg, seed=0)
    with pytest.raises(RuntimeError, match="vmem stats"):
        eng3.generate(["x"], ModelSettings(temperature=0.0, max_tokens=2))
    assert eng3.config.use_decode_attention_kernel  # NOT downgraded

"""Logit parity against the ``transformers`` reference implementations.

The study's fidelity rests on ``runtime/weights.py`` + ``models/transformer.py``
reproducing each family's forward exactly: a transpose, RoPE-convention, or
QKV-split error would round-trip cleanly through our own save/load tests and
still decode garbage on real checkpoints. Here the checkpoints are *produced by
transformers itself* (tiny configs, real architectures, saved to safetensors)
and our float32 forward must match the torch forward to float32 noise.

Replaces the trust the reference places in the OpenAI API being the model
(``phase1_bias_detection.py:180-188``): when inference is in-framework the
framework must prove it computes the same function the published weights mean.

Covers, per family:
- llama: RoPE rotate-half convention, GQA head grouping, [out,in] transpose
- llama-tied: tied-embedding lm_head (llama-3.2 style)
- gemma: sqrt(d_model) embed scale, ``1 + weight`` RMSNorm, tied embeds
- gpt2: fused-QKV Conv1D split (no transpose), learned positions, gelu_tanh
- mistral: sliding-window masking at S > window
- qwen2: biases on q/k/v projections only (qkv_bias), tied embeds
plus the cached decode path (greedy parity vs ``generate``), the left-padded
batch layout, and the ``HFTokenizer`` adapter over a real tokenizer dir.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from fairness_llm_tpu.models.configs import ModelConfig
from fairness_llm_tpu.models.transformer import Transformer, init_cache
from fairness_llm_tpu.runtime.weights import load_checkpoint

ATOL = 1e-4  # observed max diff ~2e-7 at f32; wide margin for BLAS variation

_TINY = dict(d=64, ff=128, layers=2, heads=4, vocab=256, seq=256)


def _build(family: str):
    """Tiny real-architecture HF model + the matching framework config."""
    torch.manual_seed(0)
    t = _TINY
    common = dict(
        name=f"tiny-{family}-parity", vocab_size=t["vocab"], num_layers=t["layers"],
        num_heads=t["heads"], d_model=t["d"], d_ff=t["ff"], head_dim=16,
        max_seq_len=t["seq"], rope_theta=10000.0, dtype="float32",
        use_flash_attention=False,
    )
    if family in ("llama", "llama-tied"):
        tied = family == "llama-tied"
        hf = transformers.LlamaForCausalLM(transformers.LlamaConfig(
            vocab_size=t["vocab"], hidden_size=t["d"], intermediate_size=t["ff"],
            num_hidden_layers=t["layers"], num_attention_heads=t["heads"],
            num_key_value_heads=2, head_dim=16, max_position_embeddings=t["seq"],
            rms_norm_eps=1e-5, rope_theta=10000.0, tie_word_embeddings=tied,
            attention_bias=False, mlp_bias=False,
        ))
        name = "tiny-llama-parity" if not tied else "tiny-llama-parity-tied"
        cfg = ModelConfig(**{**common, "name": name}, num_kv_heads=2,
                          norm_eps=1e-5, tie_embeddings=tied)
    elif family == "gemma":
        hf = transformers.GemmaForCausalLM(transformers.GemmaConfig(
            vocab_size=t["vocab"], hidden_size=t["d"], intermediate_size=t["ff"],
            num_hidden_layers=t["layers"], num_attention_heads=t["heads"],
            num_key_value_heads=t["heads"], head_dim=16,
            max_position_embeddings=t["seq"], rms_norm_eps=1e-6,
            rope_theta=10000.0, hidden_activation="gelu_pytorch_tanh",
            attention_bias=False,
        ))
        cfg = ModelConfig(**common, num_kv_heads=t["heads"], norm_eps=1e-6,
                          activation="gelu_tanh", embed_scale=True,
                          tie_embeddings=True)
    elif family == "gpt2":
        hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(
            vocab_size=t["vocab"], n_positions=t["seq"], n_embd=t["d"],
            n_layer=t["layers"], n_head=t["heads"],
            activation_function="gelu_new", layer_norm_epsilon=1e-5,
        ))
        cfg = ModelConfig(**{**common, "d_ff": 4 * t["d"]}, num_kv_heads=t["heads"],
                          pos_emb="learned", norm="layernorm", mlp="mlp",
                          use_bias=True, activation="gelu_tanh",
                          tie_embeddings=True, norm_eps=1e-5)
    elif family == "mistral":
        hf = transformers.MistralForCausalLM(transformers.MistralConfig(
            vocab_size=t["vocab"], hidden_size=t["d"], intermediate_size=t["ff"],
            num_hidden_layers=t["layers"], num_attention_heads=t["heads"],
            num_key_value_heads=2, head_dim=16, max_position_embeddings=t["seq"],
            rms_norm_eps=1e-5, rope_theta=10000.0, sliding_window=8,
            attn_implementation="eager",
        ))
        cfg = ModelConfig(**common, num_kv_heads=2, norm_eps=1e-5,
                          sliding_window=8)
    elif family == "qwen2":
        hf = transformers.Qwen2ForCausalLM(transformers.Qwen2Config(
            vocab_size=t["vocab"], hidden_size=t["d"], intermediate_size=t["ff"],
            num_hidden_layers=t["layers"], num_attention_heads=t["heads"],
            num_key_value_heads=2, head_dim=16, max_position_embeddings=t["seq"],
            rms_norm_eps=1e-6, rope_theta=10000.0, tie_word_embeddings=True,
            attn_implementation="eager",
        ))
        cfg = ModelConfig(**common, num_kv_heads=2, norm_eps=1e-6,
                          qkv_bias=True, tie_embeddings=True)
    else:
        raise KeyError(family)
    return hf.eval(), cfg


def _load(hf, cfg, path):
    hf.save_pretrained(str(path), safe_serialization=True)
    return load_checkpoint(cfg, str(path), dtype=np.float32)


FAMILIES = ["llama", "llama-tied", "gemma", "gpt2", "mistral", "qwen2"]


@pytest.mark.parametrize("family", FAMILIES)
def test_logit_parity(family, tmp_path):
    hf, cfg = _build(family)
    params = _load(hf, cfg, tmp_path)
    # S=16 exceeds mistral's window of 8, so sliding-window masking is live.
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 16))
    with torch.no_grad():
        theirs = hf(torch.tensor(tokens)).logits.numpy()
    positions = np.tile(np.arange(16, dtype=np.int32)[None, :], (2, 1))
    ours, _ = Transformer(cfg).apply(
        {"params": params}, tokens.astype(np.int32), positions
    )
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=ATOL)


@pytest.mark.parametrize("family", FAMILIES)
def test_greedy_decode_parity(family, tmp_path):
    """Prefill + cached single-token decode must follow the same greedy path
    transformers' ``generate`` takes — exercises the KV-cache write/read,
    position bookkeeping, and last-position logits end to end. All six
    families: gemma's embed-scale + (1+w) norm in the cached path, mistral's
    sliding window live during decode (prompt 7 + 8 new > window 8), qwen2's
    qkv biases, and the tied-head variants."""
    hf, cfg = _build(family)
    params = _load(hf, cfg, tmp_path)
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, cfg.vocab_size, size=(1, 7))
    new = 8

    with torch.no_grad():
        theirs = hf.generate(
            torch.tensor(prompt), max_new_tokens=new, do_sample=False,
            pad_token_id=0,
        ).numpy()[0, prompt.shape[1]:]

    model = Transformer(cfg)
    cache = init_cache(cfg, 1, prompt.shape[1] + new)
    positions = np.arange(prompt.shape[1], dtype=np.int32)[None, :]
    logits, cache = model.apply(
        {"params": params}, prompt.astype(np.int32), positions,
        np.ones(prompt.shape, bool), cache, last_only=True,
    )
    got = []
    for _ in range(new):
        tok = int(np.argmax(np.asarray(logits)[0, -1]))
        got.append(tok)
        pos = np.asarray(cache.lengths, np.int32)[None, :]
        logits, cache = model.apply(
            {"params": params}, np.asarray([[tok]], np.int32), pos,
            np.ones((1, 1), bool), cache,
        )
    np.testing.assert_array_equal(np.asarray(got), theirs)


class _IntTokenizer:
    """Token-level passthrough tokenizer: text is space-separated ids. Lets a
    parity test drive the engine's PUBLIC generate() path (prefix sharing,
    bucketing, while_loop decode) with exact token control."""

    def __init__(self, vocab_size: int, eos_id: int):
        self.vocab_size = vocab_size
        self.pad_id = 0
        self.eos_id = eos_id
        self.bos_id = None

    def encode(self, text, add_bos=True):
        return [int(x) for x in text.split()]

    def decode(self, ids):
        return " ".join(str(int(i)) for i in ids)

    def encode_batch(self, texts, max_len=None):
        from fairness_llm_tpu.models.tokenizer import _left_pad

        return _left_pad([self.encode(t) for t in texts], self.pad_id, max_len)


def test_shared_prefix_decode_parity(tmp_path):
    """The shared-prefix decode path — prefix KV computed once [Pc, Hkv, D],
    every row attending to it plus its own left-padded remainder — must decode
    the SAME greedy tokens ``hf.generate`` produces for each full prompt. This
    is the headline perf feature tested against transformers, not just
    self-consistently (VERDICT r2 weak #2)."""
    from fairness_llm_tpu.config import ModelSettings
    from fairness_llm_tpu.runtime.engine import DecodeEngine

    hf, cfg = _build("llama")
    params = _load(hf, cfg, tmp_path)
    eos = 3
    rng = np.random.default_rng(7)
    prefix = rng.integers(4, cfg.vocab_size, size=(72,)).tolist()
    suffixes = [
        rng.integers(4, cfg.vocab_size, size=(n,)).tolist() for n in (5, 9, 1)
    ]
    rows = [prefix + s for s in suffixes]
    new = 8

    theirs = []
    for row in rows:
        with torch.no_grad():
            out = hf.generate(
                torch.tensor([row]), max_new_tokens=new, do_sample=False,
                pad_token_id=0, eos_token_id=eos,
            ).numpy()[0, len(row):]
        keep = []
        for t in out:
            if t == eos:
                break
            keep.append(int(t))
        theirs.append(keep)

    engine = DecodeEngine(
        cfg, params=params, tokenizer=_IntTokenizer(cfg.vocab_size, eos_id=eos)
    )
    out = engine.generate(
        [" ".join(map(str, r)) for r in rows],
        ModelSettings(temperature=0.0, max_tokens=new),
        prefix_ids=prefix,
        share_prefix=True,  # keep the exact caller prefix length (72)
    )
    assert out.stats["prefix_len"] == len(prefix)
    ours = [[int(x) for x in t.split()] for t in out.texts]
    assert ours == theirs


def test_left_padded_batch_parity(tmp_path):
    """Rows of different lengths, left-padded into one batch, must produce the
    same last-position logits as per-row unpadded HF forwards — validates the
    pad masking + position clamping the decode engine relies on."""
    hf, cfg = _build("llama")
    params = _load(hf, cfg, tmp_path)
    rng = np.random.default_rng(2)
    rows = [rng.integers(0, cfg.vocab_size, size=(n,)) for n in (5, 9)]

    theirs = []
    for row in rows:
        with torch.no_grad():
            theirs.append(hf(torch.tensor(row[None, :])).logits.numpy()[0, -1])

    S = 9
    tokens = np.zeros((2, S), np.int32)
    valid = np.zeros((2, S), bool)
    for i, row in enumerate(rows):
        tokens[i, S - len(row):] = row
        valid[i, S - len(row):] = True
    positions = np.maximum(np.cumsum(valid, axis=1) - 1, 0).astype(np.int32)
    ours, _ = Transformer(cfg).apply(
        {"params": params}, tokens, positions, valid, last_only=True
    )
    ours = np.asarray(ours)[:, -1, :]
    np.testing.assert_allclose(ours[0], theirs[0], atol=ATOL)
    np.testing.assert_allclose(ours[1], theirs[1], atol=ATOL)


def test_hf_tokenizer_adapter(tmp_path):
    """HFTokenizer over a real on-disk tokenizer dir (built with the
    ``tokenizers`` library — no network) must agree with the transformers
    tokenizer it wraps and satisfy the engine's pad/eos contract."""
    tokenizers = pytest.importorskip("tokenizers")
    from tokenizers import decoders
    from tokenizers import models as tok_models
    from tokenizers import pre_tokenizers, trainers

    corpus = [
        "Recommend 10 movies for a 25-34 year old user.",
        "The user has watched: The Matrix (1999), Toy Story (1995).",
        "Please respond with a numbered list of movie titles.",
    ] * 8
    tok = tokenizers.Tokenizer(tok_models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    tok.train_from_iterator(
        corpus,
        trainers.BpeTrainer(vocab_size=400, special_tokens=["<|endoftext|>"]),
    )
    fast = transformers.PreTrainedTokenizerFast(
        tokenizer_object=tok, eos_token="<|endoftext|>"
    )
    fast.save_pretrained(str(tmp_path))

    from fairness_llm_tpu.models.tokenizer import HFTokenizer

    ours = HFTokenizer(str(tmp_path))
    text = "Recommend 10 movies for a user."
    assert ours.encode(text) == fast.encode(text)
    assert ours.decode(ours.encode(text)) == text
    # no pad token declared -> engine's pad falls back to eos
    assert ours.pad_id == fast.eos_token_id
    assert ours.eos_id == fast.eos_token_id

    batch = ours.encode_batch(["short", "a much longer prompt here"])
    assert batch.tokens.shape[0] == 2
    assert bool(batch.valid[0, 0]) is False  # left-padded
    assert bool(batch.valid[0, -1]) is True

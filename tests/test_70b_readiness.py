"""70B readiness: compile-time proof of the BASELINE tp=8 config.

No environment this suite runs in holds 70B of weights, so readiness is
proven the way XLA allows it to be: AOT-lower and backend-compile the REAL
llama3-70b prefill+decode program at tp=8 over the virtual 8-device mesh with
abstract (``ShapeDtypeStruct``) parameters — every sharding rule, layout, and
collective is decided at compile time, so a rule change that would break the
70B path on hardware fails here first. Memory is asserted from the compiled
program's own analysis plus the analytic estimator, including the honest
negative result: bf16 70B params at tp=8 are ~17.6 GB/chip — OVER a v5e's
16 GB HBM — so the framework must flag it (fit paths: tp=16 or int8 weights).

Replaces nothing in the reference (it has no local models, SURVEY.md §0);
this guards the `BASELINE.json` llama3-70b TP=8 target config.
"""

import types

import flax.linen as nn
import jax
import jax.numpy as jnp
import pytest

from fairness_llm_tpu.config import MeshConfig
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.models.transformer import Transformer, init_cache
from fairness_llm_tpu.parallel import sharding as shd

V5E_HBM_BYTES = 16 * 1024**3

# The v5e-topology AOT proofs need the jax generation they were recorded on:
# under jax 0.4.x jaxlib the TPU-topology compile SIGABRTs the whole test
# process (observed on 0.4.37 — a fatal Mosaic/PJRT crash, not a Python
# error, so it cannot be caught in-test). CPU-mesh AOT compiles are fine.
_JAX_VERSION = tuple(int(x) for x in jax.__version__.split(".")[:2])
needs_tpu_aot = pytest.mark.skipif(
    _JAX_VERSION < (0, 6),
    reason="jax 0.4 jaxlib hard-crashes on TPU-topology AOT compiles",
)


def _rules_for_shape(cfg, shape):
    """make_axis_rules only reads mesh.shape — a shim lets us probe mesh
    geometries (tp=16) larger than the 8 virtual devices can realize."""
    return shd.make_axis_rules(cfg, types.SimpleNamespace(shape=shape))


def test_70b_rules_tp8_shard_everything():
    cfg = get_model_config("llama3-70b")
    rules = dict(_rules_for_shape(cfg, {"dp": 1, "tp": 8, "sp": 1}))
    # 64 q heads -> 8/chip; 8 kv heads -> exactly 1/chip; ff + vocab divide.
    assert rules["q_heads"] == "tp"
    assert rules["kv_heads"] == "tp"
    assert rules["ff"] == "tp"
    assert rules["vocab"] == "tp"


def test_70b_rules_tp16_gqa_fallback():
    """kv_heads=8 cannot split across tp=16: KV falls back to replicated
    (the production GQA fallback) while q/ff/vocab still shard."""
    cfg = get_model_config("llama3-70b")
    rules = dict(_rules_for_shape(cfg, {"dp": 1, "tp": 16, "sp": 1}))
    assert rules["kv_heads"] is None
    assert rules["q_heads"] == "tp"
    assert rules["ff"] == "tp"
    assert rules["vocab"] == "tp"


@pytest.fixture(scope="module")
def compiled_70b():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg = get_model_config("llama3-70b")
    mesh = shd.make_mesh(MeshConfig(dp=1, tp=8, sp=1))
    rules = shd.make_axis_rules(cfg, mesh)
    shardings = shd.param_shardings(cfg, mesh, rules)

    model = Transformer(cfg)
    abstract = jax.eval_shape(
        model.init, jax.random.key(0),
        jnp.zeros((1, 8), jnp.int32), jnp.zeros((1, 8), jnp.int32),
    )
    abstract = nn.meta.unbox(abstract["params"])
    aparams = jax.tree.map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, jnp.bfloat16, sharding=s),
        abstract, shardings,
    )

    B, S, NEW = 8, 128, 4

    def prefill_and_decode(params, tokens, positions, valid):
        # The engine's program shape: batch prefill writes the cache, then
        # cached single-token steps extend it — all inside ONE program so the
        # cache sharding is decided entirely by GSPMD propagation.
        cache = init_cache(cfg, B, S + NEW)
        logits, cache = model.apply(
            {"params": params}, tokens, positions, valid, cache,
            left_padded=True, last_only=True,
        )

        def step(_, carry):
            logits, cache = carry
            tok = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            pos = cache.lengths[:, None]
            logits, cache = model.apply(
                {"params": params}, tok[:, None], pos,
                jnp.ones((B, 1), jnp.bool_), cache,
            )
            return logits, cache

        logits, cache = jax.lax.fori_loop(0, NEW, step, (logits, cache))
        return logits

    bs = shd.batch_sharding(mesh)
    atoks = jax.ShapeDtypeStruct((B, S), jnp.int32, sharding=bs)
    apos = jax.ShapeDtypeStruct((B, S), jnp.int32, sharding=bs)
    avalid = jax.ShapeDtypeStruct((B, S), jnp.bool_, sharding=bs)
    with mesh, nn.logical_axis_rules(rules):
        compiled = jax.jit(prefill_and_decode).lower(
            aparams, atoks, apos, avalid
        ).compile()
    return cfg, mesh, rules, compiled


@pytest.mark.slow  # ~80 s: the 80-layer AOT backend-compile dominates tier-1
def test_70b_aot_compiles_tp8(compiled_70b):
    # Existence of `compiled` IS the proof — GSPMD accepted every rule and
    # laid out all 80 layers' collectives at tp=8.
    cfg, mesh, rules, compiled = compiled_70b
    assert compiled.memory_analysis() is not None


@pytest.mark.slow  # shares compiled_70b — must move with the test above
def test_70b_param_bytes_match_compiled_analysis(compiled_70b):
    cfg, mesh, rules, compiled = compiled_70b
    analytic = shd.per_device_param_bytes(cfg, mesh, rules)
    measured = compiled.memory_analysis().argument_size_in_bytes
    # measured includes the token/position inputs (KB-scale vs 17.6 GB params)
    assert abs(measured - analytic) / analytic < 0.02


def test_70b_bf16_tp8_exceeds_v5e_hbm_and_is_flagged():
    """The honest capacity statement the CLI warning is built on: bf16 70B
    params at tp=8 do NOT fit one v5e chip; tp=16 (two v5e-8 slices) does."""
    cfg = get_model_config("llama3-70b")
    mesh8 = types.SimpleNamespace(shape={"dp": 1, "tp": 8, "sp": 1})
    rules8 = _rules_for_shape(cfg, mesh8.shape)
    per8 = shd.per_device_param_bytes(cfg, mesh8, rules8)
    assert per8 > V5E_HBM_BYTES  # ~17.6 GB

    mesh16 = types.SimpleNamespace(shape={"dp": 1, "tp": 16, "sp": 1})
    rules16 = _rules_for_shape(cfg, mesh16.shape)
    per16 = shd.per_device_param_bytes(cfg, mesh16, rules16)
    assert per16 < V5E_HBM_BYTES  # ~8.9 GB (kv replicated but tiny vs ff/vocab)

    # 8B at tp=8 fits comfortably — the primary BASELINE serving config.
    cfg8b = get_model_config("llama3-8b")
    per_8b = shd.per_device_param_bytes(cfg8b, mesh8, _rules_for_shape(cfg8b, mesh8.shape))
    assert per_8b < 4e9


def test_70b_decode_kv_cache_estimate():
    cfg = get_model_config("llama3-70b")
    mesh = types.SimpleNamespace(shape={"dp": 1, "tp": 8, "sp": 1})
    rules = _rules_for_shape(cfg, mesh.shape)
    # sweep shape: batch 48, 1k cache slots; kv sharded 1 head/chip ->
    # 2 * 80 layers * 48 * 1024 * 1 head * 128 dim * 2 B = ~2.0 GB/chip
    got = shd.per_device_kv_cache_bytes(cfg, mesh, batch=48, max_len=1024, rules=rules)
    assert got == 2 * 80 * 48 * 1024 * 1 * 128 * 2


@needs_tpu_aot
def test_8b_flash_prefill_compiles_sharded_on_v5e_topology():
    """tp=8 serving prefill with the FLASH kernel engaged, through the real
    v5e compiler: the round-4 shard_map dispatch is what makes a Pallas
    flash call legal inside a multi-chip program at all (Mosaic refuses
    GSPMD-partitioned contexts — previously multi-chip prefill silently
    required dense attention). 2 layers of llama3-8b's exact dims at
    S=1024 (flash-eligible length, batch 8 over dp=2 x tp=4 so BOTH batch
    and head sharding run through the wrap)."""
    import dataclasses

    import numpy as np

    try:
        from jax.experimental import topologies

        td = topologies.get_topology_desc(platform="tpu", topology_name="v5e:2x4")
    except Exception as e:  # noqa: BLE001 — no TPU compiler in this env
        pytest.skip(f"TPU topology unavailable: {type(e).__name__}")
    from fairness_llm_tpu.ops.quant_matmul import force_pallas

    cfg = dataclasses.replace(
        get_model_config("llama3-8b"), name="llama3-8b-2l", num_layers=2,
    )
    assert cfg.use_flash_attention
    mesh = jax.sharding.Mesh(
        np.array(td.devices).reshape(2, 4, 1), ("dp", "tp", "sp")
    )
    rules = shd.make_axis_rules(cfg, mesh)
    shardings = shd.param_shardings(cfg, mesh, rules)
    model = Transformer(cfg)
    abstract = nn.meta.unbox(
        jax.eval_shape(
            model.init, jax.random.key(0),
            jnp.zeros((1, 8), jnp.int32), jnp.zeros((1, 8), jnp.int32),
        )["params"]
    )
    aparams = jax.tree.map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, jnp.bfloat16, sharding=s),
        abstract, shardings,
    )

    B, S = 8, 1024

    def prefill(params, tokens, positions, valid):
        cache = init_cache(cfg, B, S + 1)
        logits, _ = model.apply(
            {"params": params}, tokens, positions, valid, cache,
            left_padded=True, last_only=True,
        )
        return logits

    bs = shd.batch_sharding(mesh)
    atoks = jax.ShapeDtypeStruct((B, S), jnp.int32, sharding=bs)
    avalid = jax.ShapeDtypeStruct((B, S), jnp.bool_, sharding=bs)
    with mesh, nn.logical_axis_rules(rules), force_pallas():
        # force_pallas makes _flash_ok treat the lowering target as TPU in
        # this CPU-pinned test process; the wrap then must produce a program
        # the actual TPU compiler accepts.
        lowered = jax.jit(prefill).lower(aparams, atoks, atoks, avalid)
        # the kernel must actually be IN the program (a silent dense
        # fallback would also compile, proving nothing)
        assert "tpu_custom_call" in lowered.as_text()
        compiled = lowered.compile()
    assert compiled.memory_analysis() is not None


@needs_tpu_aot
def test_70b_int8_layer_compiles_on_v5e_topology():
    """The int8 fit proof's LOWERING, at suite speed: a 2-layer model with
    llama3-70b's exact per-layer dimensions, int8 weights, tp=8, compiled by
    the REAL v5e TPU compiler against a topology descriptor — every Pallas
    quant matmul, shard_map wrap, and collective the 80-layer program uses,
    in ~1/40th the compile time. The full-model compile (memory analysis:
    9.29 GB/chip vs 15.75 — fits) is tools/prove_70b_int8_fit.py, recorded
    in the bench as ``int8_70b_fit``. Temps must stay activation-scale: the
    round-3 negative was 35 GB of hoisted bf16 dequants, which two layers
    would already betray (~0.9 GB of kernels -> bf16 temps would dwarf the
    0.1 GB activation budget this asserts)."""
    import dataclasses

    import jax.tree_util as jtu

    try:
        from jax.experimental import topologies

        td = topologies.get_topology_desc(platform="tpu", topology_name="v5e:2x4")
    except Exception as e:  # noqa: BLE001 — no TPU compiler in this env
        pytest.skip(f"TPU topology unavailable: {type(e).__name__}")
    from fairness_llm_tpu.models.transformer import init_cache
    from fairness_llm_tpu.ops.quant_matmul import force_pallas

    cfg = dataclasses.replace(
        get_model_config("llama3-70b-int8"), name="llama3-70b-int8-2l", num_layers=2
    )
    import numpy as np

    mesh = jax.sharding.Mesh(
        np.array(td.devices).reshape(1, 8, 1), ("dp", "tp", "sp")
    )
    rules = shd.make_axis_rules(cfg, mesh)
    shardings = shd.param_shardings(cfg, mesh, rules)
    model = Transformer(cfg)
    abstract = nn.meta.unbox(
        jax.eval_shape(
            model.init, jax.random.key(0),
            jnp.zeros((1, 8), jnp.int32), jnp.zeros((1, 8), jnp.int32),
        )["params"]
    )
    flat, treedef = jtu.tree_flatten_with_path(abstract)
    aleaves = []
    for (path, leaf), s in zip(flat, jtu.tree_leaves(shardings)):
        name = getattr(path[-1], "key", "")
        if not jnp.issubdtype(leaf.dtype, jnp.floating):
            dt = leaf.dtype
        else:
            dt = jnp.float32 if name == "kernel_scale" else jnp.bfloat16
        aleaves.append(jax.ShapeDtypeStruct(leaf.shape, dt, sharding=s))
    aparams = jtu.tree_unflatten(treedef, aleaves)

    B, S = 8, 128

    def prefill_and_step(params, tokens, positions, valid):
        cache = init_cache(cfg, B, S + 1)
        logits, cache = model.apply(
            {"params": params}, tokens, positions, valid, cache,
            left_padded=True, last_only=True,
        )
        tok = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        logits, _ = model.apply(
            {"params": params}, tok[:, None], cache.lengths[:, None],
            jnp.ones((B, 1), jnp.bool_), cache,
        )
        return logits

    bs = shd.batch_sharding(mesh)
    atoks = jax.ShapeDtypeStruct((B, S), jnp.int32, sharding=bs)
    avalid = jax.ShapeDtypeStruct((B, S), jnp.bool_, sharding=bs)
    with mesh, nn.logical_axis_rules(rules), force_pallas():
        compiled = (
            jax.jit(prefill_and_step).lower(aparams, atoks, atoks, avalid).compile()
        )
    ma = compiled.memory_analysis()
    # int8 kernels dominate args; temps stay activation-scale (no hoisted
    # bf16 copy of the weights — the property the kernel exists to provide).
    assert ma.argument_size_in_bytes < 1.5e9
    assert ma.temp_size_in_bytes < 0.5e9

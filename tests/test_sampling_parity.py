"""Sampling-semantics parity vs transformers' logits processors.

The reference delegates temperature to its API (``phase1_bias_detection.py:
186-187``); SURVEY.md §7 hard part (b) names sampling parity as load-bearing
for comparable fairness numbers. These tests compare our sampler's *filtered,
renormalized distributions* — the deterministic object sampling draws from —
exactly against transformers' ``TemperatureLogitsWarper`` / ``TopKLogitsWarper``
/ ``TopPLogitsWarper`` pipeline (the order ``generate`` applies them in), so a
future real-weights study's sampled outputs are defensibly the same model
behavior an HF-served baseline would produce.

Conventions pinned here (see ``runtime/sampling.py:filtered_logits``):
- top-k ties at the k-th logit: both keep ALL tying tokens (may exceed k);
- top-p boundary: the token crossing the threshold stays in — identical
  exclusive-cumsum semantics;
- top-p VALUE-TIED boundary: we keep every tied token (sort-order invariant);
  HF drops a sort-position-dependent subset. Ours is always a superset,
  differing only in tokens value-tied with the boundary.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from transformers.generation.logits_process import (
    TemperatureLogitsWarper,
    TopKLogitsWarper,
    TopPLogitsWarper,
)

from fairness_llm_tpu.runtime.sampling import SamplerSettings, filtered_logits


def _hf_filtered(logits: np.ndarray, t: float, k: int, p: float) -> np.ndarray:
    scores = torch.tensor(logits, dtype=torch.float32)
    ids = torch.zeros((scores.shape[0], 1), dtype=torch.long)
    scores = TemperatureLogitsWarper(t)(ids, scores)
    if k > 0:
        scores = TopKLogitsWarper(k)(ids, scores)
    if p < 1.0:
        scores = TopPLogitsWarper(p)(ids, scores)
    return scores.numpy()


def _ours_filtered(logits: np.ndarray, t: float, k: int, p: float) -> np.ndarray:
    return np.asarray(
        filtered_logits(SamplerSettings(temperature=t, top_k=k, top_p=p), logits)
    )


def _dist(filtered: np.ndarray) -> np.ndarray:
    """Renormalized distribution over the kept set (-inf -> prob 0)."""
    x = np.asarray(filtered, np.float64)
    x = x - np.max(x, axis=-1, keepdims=True)
    prob = np.exp(x)
    return prob / prob.sum(axis=-1, keepdims=True)


# temperature-only, k-only (incl. k=1 and k>=V), p-only (incl. aggressive
# p=0.3), combined k+p, and near-1 p exercising the cumsum tail.
GRID = [
    (0.7, 0, 1.0),
    (1.3, 10, 1.0),
    (1.0, 1, 1.0),
    (1.0, 500, 1.0),
    (0.7, 0, 0.9),
    (0.9, 0, 0.3),
    (0.8, 17, 0.85),
    (0.25, 5, 0.999),
]


@pytest.mark.parametrize("t,k,p", GRID)
def test_filtered_distribution_parity(t, k, p):
    rng = np.random.default_rng(0)
    logits = (rng.normal(size=(4, 101)) * 3).astype(np.float32)
    ours = _ours_filtered(logits, t, k, p)
    theirs = _hf_filtered(logits, t, k, p)
    np.testing.assert_array_equal(np.isfinite(ours), np.isfinite(theirs))
    np.testing.assert_allclose(_dist(ours), _dist(theirs), atol=1e-6)


def test_topk_tie_at_kth_logit():
    """k=2 with an exact tie at the 2nd value: both samplers keep all three
    tying-or-above tokens (the '< k-th value' convention)."""
    logits = np.array([[3.0, 2.0, 2.0, 1.0, 0.5]], np.float32)
    ours = _ours_filtered(logits, 1.0, 2, 1.0)
    theirs = _hf_filtered(logits, 1.0, 2, 1.0)
    assert np.isfinite(ours[0]).tolist() == [True, True, True, False, False]
    np.testing.assert_array_equal(np.isfinite(ours), np.isfinite(theirs))
    np.testing.assert_allclose(_dist(ours), _dist(theirs), atol=1e-6)


def test_topp_boundary_token_kept():
    """probs ~ [0.5, 0.3, 0.2], p = 0.6: the 0.3 token CROSSES the threshold
    and must stay (exclusive-cumsum convention); the 0.2 token is dropped.
    Both implementations agree."""
    logits = np.log(np.array([[0.5, 0.3, 0.2]], np.float32))
    ours = _ours_filtered(logits, 1.0, 0, 0.6)
    theirs = _hf_filtered(logits, 1.0, 0, 0.6)
    assert np.isfinite(ours[0]).tolist() == [True, True, False]
    np.testing.assert_array_equal(np.isfinite(ours), np.isfinite(theirs))
    np.testing.assert_allclose(_dist(ours), _dist(theirs), atol=1e-6)


def test_topp_value_tied_boundary_is_superset():
    """probs [0.5, 0.25, 0.25], p = 0.75: the boundary token is value-tied
    with the next. We keep BOTH tied tokens (permutation-invariant); HF's
    positional scatter may drop one (rounding decides which side of the
    threshold the tie's cumsum lands on). Pinned property: our kept set is a
    superset of HF's, and any extra tokens are exact value-ties of our
    smallest kept logit."""
    logits = np.log(np.array([[0.5, 0.25, 0.25]], np.float32))
    ours = _ours_filtered(logits, 1.0, 0, 0.75)
    theirs = _hf_filtered(logits, 1.0, 0, 0.75)
    ours_kept = set(np.flatnonzero(np.isfinite(ours[0])))
    hf_kept = set(np.flatnonzero(np.isfinite(theirs[0])))
    assert ours_kept == {0, 1, 2}  # both tied tokens survive
    assert hf_kept <= ours_kept
    boundary = min(ours[0][i] for i in ours_kept)
    for extra in ours_kept - hf_kept:
        assert ours[0][extra] == boundary


def test_sampled_tokens_follow_filtered_distribution():
    """End to end: tokens drawn by make_sampler land only on the kept set and
    match its renormalized distribution (chi-square-loose bound), tying the
    parity proof above to what the decode loop actually samples."""
    import jax

    from fairness_llm_tpu.runtime.sampling import make_sampler

    settings = SamplerSettings(temperature=0.8, top_k=4, top_p=0.9)
    rng = np.random.default_rng(3)
    logits = (rng.normal(size=(1, 16)) * 2).astype(np.float32)
    kept = np.isfinite(_ours_filtered(logits, 0.8, 4, 0.9)[0])
    expect = _dist(_ours_filtered(logits, 0.8, 4, 0.9))[0]

    sample = make_sampler(settings)
    draws = 4000
    keys = jax.vmap(jax.random.key)(np.arange(draws, dtype=np.uint32))
    toks = np.asarray(
        jax.vmap(lambda k: sample(logits, k[None]))(keys)
    ).ravel()
    assert set(toks) <= set(np.flatnonzero(kept))
    freq = np.bincount(toks, minlength=16) / draws
    np.testing.assert_allclose(freq, expect, atol=0.03)

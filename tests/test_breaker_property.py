"""Property-based BreakerBoard invariants (hypothesis over random
fault/success/clock sequences).

The fleet (serving/fleet.py) multiplies the breaker machinery by N — every
replica carries its own board, and the router's fence policy reads board
state directly — so the state machine's invariants are now load-bearing N
times over:

1. **Transition order**: a breaker only ever moves along the legal edges
   closed->open, open->half_open, half_open->closed, half_open->open.
   There is no closed->half_open shortcut and no open->closed shortcut —
   an open stage must always pass through a half-open probe to recover.
2. **Ladder accounting**: the degradation level always equals the number
   of stages currently NOT closed (each tripped stage holds exactly one
   rung), and in particular all-breakers-healthy <=> level 0 — degradation
   is a function of current health, never of trip history.
"""

import pytest

pytest.importorskip("hypothesis")  # property tests skip where hypothesis isn't baked in
from hypothesis import given, settings
from hypothesis import strategies as st

from fairness_llm_tpu.resilience.breaker import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    STAGES,
    BreakerBoard,
)
from fairness_llm_tpu.telemetry import use_registry

LEGAL_EDGES = {
    (CLOSED, OPEN),
    (OPEN, HALF_OPEN),
    (HALF_OPEN, CLOSED),
    (HALF_OPEN, OPEN),
}

# One operation: (stage index, action). "tick" advances the fake clock past
# the cooldown so the next allow() can half-open; "allow" is the consult
# the serving loop makes before every stage attempt (and the only legal way
# to reach half_open).
OPS = st.lists(
    st.tuples(
        st.integers(min_value=0, max_value=len(STAGES) - 1),
        st.sampled_from(["fail", "success", "allow", "tick"]),
    ),
    max_size=200,
)


@settings(max_examples=100, deadline=None)
@given(ops=OPS, threshold=st.integers(min_value=1, max_value=4))
def test_breaker_transition_order_and_ladder_invariant(ops, threshold):
    clock = {"t": 0.0}
    transitions = []
    with use_registry():
        board = BreakerBoard(
            failure_threshold=threshold, cooldown_s=10.0,
            clock=lambda: clock["t"],
        )
        for stage, breaker in board.breakers.items():
            orig = breaker.on_transition

            def spy(s, old, new, _orig=orig):
                transitions.append((s, old, new))
                _orig(s, old, new)

            breaker.on_transition = spy
        for idx, action in ops:
            stage = STAGES[idx]
            if action == "fail":
                board.record_failure(stage)
            elif action == "success":
                board.record_success(stage)
            elif action == "allow":
                board.allow(stage)
            else:  # tick: the cooldown elapses
                clock["t"] += 11.0
            # Ladder accounting after EVERY op: level == tripped stages.
            tripped = sum(
                1 for b in board.breakers.values() if b.state != CLOSED
            )
            assert board.ladder.level == tripped, (
                f"level {board.ladder.level} != {tripped} tripped after "
                f"{(stage, action)}"
            )
            assert (board.ladder.level == 0) == all(
                b.state == CLOSED for b in board.breakers.values()
            )
        for s, old, new in transitions:
            assert (old, new) in LEGAL_EDGES, (
                f"illegal transition {old} -> {new} on stage {s}"
            )


@settings(max_examples=50, deadline=None)
@given(ops=OPS)
def test_open_breaker_refuses_until_cooldown(ops):
    """allow() semantics under random driving: an OPEN breaker refuses
    before its cooldown and half-opens (allowing) after — never the other
    way around."""
    clock = {"t": 0.0}
    with use_registry():
        board = BreakerBoard(failure_threshold=1, cooldown_s=10.0,
                             clock=lambda: clock["t"])
        for idx, action in ops:
            stage = STAGES[idx]
            breaker = board.breakers[stage]
            if action == "fail":
                board.record_failure(stage)
            elif action == "success":
                board.record_success(stage)
            elif action == "tick":
                clock["t"] += 11.0
            else:
                before = breaker.state
                remaining = breaker.seconds_until_probe
                allowed = board.allow(stage)
                if before == OPEN and remaining is not None and remaining > 0:
                    assert not allowed
                    assert breaker.state == OPEN
                elif before == OPEN:
                    assert allowed and breaker.state == HALF_OPEN
                else:
                    assert allowed

"""Sequence-parallel (ring-attention) training vs the plain XLA train step:
same batch, same init -> same loss and same updated params."""

import jax
import numpy as np
import optax
import pytest

from fairness_llm_tpu.config import MeshConfig
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.parallel import make_mesh
from fairness_llm_tpu.train import make_train_step
from fairness_llm_tpu.train.step import make_sequence_parallel_train_step


@pytest.fixture(scope="module")
def sp_mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(MeshConfig(dp=2, tp=1, sp=4))


def _batch(rng, b=4, s=17, vocab=512):
    tokens = rng.integers(3, vocab, size=(b, s)).astype(np.int32)
    valid = np.ones((b, s), dtype=bool)
    valid[0, :4] = False  # a left-padded row
    return tokens, valid


def test_ring_step_matches_plain(sp_mesh):
    cfg = get_model_config("tiny-test")
    opt = optax.sgd(0.1)  # deterministic, no moments to compare
    init_plain, step_plain = make_train_step(cfg, optimizer=opt)
    init_ring, step_ring = make_sequence_parallel_train_step(cfg, sp_mesh, optimizer=opt)

    sa = init_plain(jax.random.key(0))
    sb = init_ring(jax.random.key(0))
    rng = np.random.default_rng(0)
    tokens, valid = _batch(rng)

    sa2, loss_a = step_plain(sa, tokens, valid)
    sb2, loss_b = step_ring(sb, tokens, valid)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=2e-5)

    la = jax.tree.leaves(sa2.params)
    lb = jax.tree.leaves(sb2.params)
    for a, b in zip(la, lb):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_ring_step_loss_decreases(sp_mesh):
    cfg = get_model_config("tiny-test")
    init_ring, step_ring = make_sequence_parallel_train_step(cfg, sp_mesh)
    state = init_ring(jax.random.key(1))
    rng = np.random.default_rng(1)
    tokens, valid = _batch(rng, b=4, s=33)
    losses = []
    for _ in range(5):
        state, loss = step_ring(state, tokens, valid)
        losses.append(float(loss))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]

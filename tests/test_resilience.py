"""Resilience subsystem tests: watchdog, circuit breakers + degradation
ladder, serving journal, graceful drain, and the chaos soak.

The acceptance contract (ISSUE 4): under a scripted mix of prefill/decode
faults, an injected hang, and a mid-run drain + resume, every submitted
request reaches a terminal Result (none lost), survivors are token-for-token
greedy-parity with an uninterrupted run, and the breaker's
closed -> open -> half-open -> closed cycle is visible in telemetry.
"""

import json
import os

import numpy as np
import pytest

from fairness_llm_tpu.config import (
    ModelSettings,
    ResilienceConfig,
    ServingConfig,
)
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.resilience import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    BreakerBoard,
    CircuitBreaker,
    DegradationLadder,
    GracefulDrain,
    ServingJournal,
    StepWatchdog,
    resume_serving,
)
from fairness_llm_tpu.runtime.engine import DecodeEngine
from fairness_llm_tpu.serving import ContinuousScheduler, Request
from fairness_llm_tpu.telemetry import use_registry
from fairness_llm_tpu.utils.failures import (
    DecodeFault,
    HangFault,
    ScriptedFaultInjector,
)


def greedy(m: int) -> ModelSettings:
    return ModelSettings(temperature=0.0, max_tokens=m)


SCFG = ServingConfig(
    enabled=True, num_slots=2, queue_capacity=64,
    max_prompt_len=192, max_new_tokens=32, decode_chunk=4,
)


@pytest.fixture(scope="module")
def engine():
    return DecodeEngine(get_model_config("tiny-test"), seed=0)


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# -- watchdog -----------------------------------------------------------------


def test_watchdog_under_budget_observes_quietly():
    clock = FakeClock()
    with use_registry() as reg:
        wd = StepWatchdog(5.0, component="t", clock=clock)
        wd.arm("decode")
        clock.advance(1.0)
        assert wd.observe("decode") == pytest.approx(1.0)
        h = reg.histogram("step_wall_s", component="t", stage="decode")
        assert h.count == 1 and h.max == pytest.approx(1.0)
        assert reg.peek("watchdog_hangs_total", component="t",
                        stage="decode") is None


def test_watchdog_classifies_hang():
    clock = FakeClock()
    with use_registry() as reg:
        wd = StepWatchdog(2.0, component="t", clock=clock)
        wd.arm("decode")
        clock.advance(3.0)
        with pytest.raises(HangFault):
            wd.observe("decode")
        assert reg.counter("watchdog_hangs_total", component="t",
                           stage="decode").value == 1


def test_watchdog_injected_extra_seconds():
    """The ScriptedFaultInjector hang mode: simulated stall seconds classify
    a hang without any real time passing."""
    with use_registry():
        wd = StepWatchdog(1.0, component="t", clock=FakeClock())
        wd.arm("decode")
        with pytest.raises(HangFault):
            wd.observe("decode", extra_s=3600.0)


def test_watchdog_compile_exemption_and_injected_override():
    """classify=False (first-use compile) records but never faults; an
    INJECTED stall classifies even on an exempt step, so scripted chaos is
    not masked by a compile."""
    with use_registry() as reg:
        wd = StepWatchdog(1.0, component="t", clock=FakeClock())
        wd.observe("decode", elapsed=1e9, classify=False)  # no raise
        assert reg.histogram("step_wall_s", component="t",
                             stage="decode").count == 1
        with pytest.raises(HangFault):
            wd.observe("decode", elapsed=0.0, extra_s=3600.0, classify=False)


def test_watchdog_disabled_threshold_still_records():
    with use_registry() as reg:
        wd = StepWatchdog(0.0, component="t", clock=FakeClock())
        wd.arm("decode")
        wd.observe("decode", extra_s=1e9)  # no classification when disabled
        assert reg.histogram("step_wall_s", component="t",
                             stage="decode").count == 1


def test_watchdog_stalled_reads_liveness_gauge():
    clock = FakeClock()
    with use_registry() as reg:
        wd = StepWatchdog(2.0, component="t", clock=clock)
        # Observer-only path must not create the gauge just by looking.
        assert wd.stalled() is None
        assert reg.peek("step_last_completed_ts", component="t") is None
        wd.arm("decode")
        clock.advance(0.5)
        wd.observe("decode")
        assert wd.stalled() is None  # fresh
        clock.advance(5.0)
        assert wd.stalled() == pytest.approx(3.0)  # 5s idle - 2s budget


# -- circuit breaker ----------------------------------------------------------


def test_breaker_full_cycle():
    clock = FakeClock()
    with use_registry() as reg:
        b = CircuitBreaker("decode", failure_threshold=2, cooldown_s=10.0,
                           component="t", clock=clock)
        assert b.allow() and b.state == CLOSED
        b.record_failure()
        assert b.state == CLOSED  # one short of the threshold
        b.record_failure()
        assert b.state == OPEN
        assert not b.allow()  # cooldown not elapsed
        assert b.seconds_until_probe == pytest.approx(10.0)
        clock.advance(10.0)
        assert b.allow()  # this call IS the half-open transition
        assert b.state == HALF_OPEN
        b.record_success()
        assert b.state == CLOSED and b.consecutive_failures == 0
        tr = lambda to: reg.counter(  # noqa: E731
            "breaker_transitions_total", component="t", stage="decode", to=to
        ).value
        assert tr("open") == 1 and tr("half_open") == 1 and tr("closed") == 1


def test_breaker_probe_failure_reopens():
    clock = FakeClock()
    with use_registry():
        b = CircuitBreaker("prefill", failure_threshold=1, cooldown_s=5.0,
                           component="t", clock=clock)
        b.record_failure()
        assert b.state == OPEN
        clock.advance(5.0)
        assert b.allow() and b.state == HALF_OPEN
        b.record_failure()
        assert b.state == OPEN  # probe failed: cooldown restarts
        assert not b.allow()
        clock.advance(4.9)
        assert not b.allow()  # restarted, not resumed
        clock.advance(0.2)
        assert b.allow()


def test_breaker_success_resets_consecutive_count():
    with use_registry():
        b = CircuitBreaker("decode", failure_threshold=3, component="t",
                           clock=FakeClock())
        b.record_failure()
        b.record_failure()
        b.record_success()
        b.record_failure()
        b.record_failure()
        assert b.state == CLOSED  # never 3 CONSECUTIVE


def test_board_trips_drive_ladder():
    clock = FakeClock()
    with use_registry() as reg:
        board = BreakerBoard(failure_threshold=1, cooldown_s=1.0,
                             component="t", clock=clock)
        assert board.ladder.level == 0
        board.record_failure("decode")
        assert board.state("decode") == OPEN and board.ladder.level == 1
        board.record_failure("prefill")
        assert board.ladder.level == 2
        clock.advance(1.0)
        assert board.allow("decode")  # half-open probe
        board.record_success("decode")
        assert board.state("decode") == CLOSED and board.ladder.level == 1
        assert board.allow("prefill")
        board.record_success("prefill")
        assert board.ladder.level == 0
        assert reg.gauge("degradation_level", component="t").value == 0


def test_ladder_clamps_and_names_rungs():
    with use_registry():
        lad = DegradationLadder(component="t")
        lad.retreat()
        assert lad.level == 0  # clamped at the floor
        for _ in range(10):
            lad.advance()
        assert lad.level == 3 and lad.rung == "static_fallback"


# -- fault injector hang mode -------------------------------------------------


def test_injector_hang_budget():
    with use_registry():
        inj = ScriptedFaultInjector(hangs={("r0", "decode"): 1},
                                    hang_seconds=42.0)
        assert inj.maybe_hang("r0", "prefill") == 0.0  # wrong stage
        assert inj.maybe_hang("r0", "decode") == 42.0
        assert inj.maybe_hang("r0", "decode") == 0.0  # budget spent
        assert inj.hangs_fired == [("r0", "decode")]
        inj.maybe_fail("r0", "decode")  # no fault budget: no raise


# -- serving journal ----------------------------------------------------------


def _spec_req(i, deadline=None):
    return Request(prompt=f"prompt {i}", id=f"j{i}", settings=greedy(8),
                   row_seed=1000 + i, deadline_s=deadline)


def test_journal_roundtrip_and_unfinished(tmp_path):
    j = ServingJournal(str(tmp_path))
    for i in range(3):
        j.record_submitted(_spec_req(i))
    j.record_terminal("j1", "completed")
    assert [r["id"] for r in j.unfinished()] == ["j0", "j2"]
    reqs = j.to_requests()
    assert [r.id for r in reqs] == ["j0", "j2"]
    assert reqs[0].prompt == "prompt 0"
    assert reqs[0].row_seed == 1000
    assert reqs[0].settings == greedy(8)


def test_journal_remaining_deadline_shrinks(tmp_path):
    j = ServingJournal(str(tmp_path))
    j.record_submitted(_spec_req(0, deadline=60.0))
    # Backdate the ledger entry: 50 wall seconds already burned.
    recs = j.records()
    recs[0]["ts_unix"] -= 50.0
    with open(j.path, "w") as f:
        f.write(json.dumps(recs[0]) + "\n")
    (req,) = j.to_requests()
    assert req.deadline_s == pytest.approx(10.0, abs=1.0)
    # A blown deadline resumes with 0 remaining (expired, not re-decoded).
    recs[0]["ts_unix"] -= 100.0
    with open(j.path, "w") as f:
        f.write(json.dumps(recs[0]) + "\n")
    (req,) = j.to_requests()
    assert req.deadline_s == 0.0


def test_journal_rotation_compacts_atomically(tmp_path):
    j = ServingJournal(str(tmp_path), rotate_every=2)
    for i in range(4):
        j.record_submitted(_spec_req(i))
    j.record_terminal("j0", "completed")
    assert len(j.records()) == 5  # not rotated yet
    j.record_terminal("j3", "failed")  # second terminal triggers compaction
    recs = j.records()
    assert [r["id"] for r in recs] == ["j1", "j2"]  # finished pairs dropped
    assert all(r["kind"] == "submitted" for r in recs)
    # The compacted journal stays appendable.
    j.record_submitted(_spec_req(9))
    assert [r["id"] for r in j.unfinished()] == ["j1", "j2", "j9"]
    assert not [p for p in os.listdir(tmp_path) if p.endswith(".tmp")]


def test_journal_tolerates_torn_tail(tmp_path):
    j = ServingJournal(str(tmp_path))
    j.record_submitted(_spec_req(0))
    j.close()
    with open(j.path, "a") as f:
        f.write('{"kind": "termi')  # killed mid-append
    j2 = ServingJournal(str(tmp_path))
    assert [r["id"] for r in j2.unfinished()] == ["j0"]


def test_checkpoint_resume_falls_back_past_digest_mismatch(tmp_path):
    """The integrity sibling of the torn-write regressions
    (tests/test_pipeline.py::test_resume_falls_back_past_torn_checkpoint):
    a checkpoint whose bytes were silently corrupted AFTER an atomic write
    still parses as valid JSON — only its manifest digest can tell — and
    resume must fall back to the next-older VALID checkpoint, exactly like
    it does for a torn one."""
    from fairness_llm_tpu.pipeline import results as R

    good = {"p1": {"recommendations": ["A"], "raw_response": "1. A"}}
    R.save_checkpoint(good, str(tmp_path), "phase1", 7)
    evil = {"p1": {"recommendations": ["WRONG"], "raw_response": "1. WRONG"},
            "p2": {"recommendations": ["ALSO WRONG"], "raw_response": "x"}}
    R.save_checkpoint(evil, str(tmp_path), "phase1", 14)
    # Bit-rot AFTER the write: swap the newest checkpoint's bytes for
    # different-but-valid JSON without touching the manifest. Every
    # pre-integrity fallback reason (unreadable, wrong shape, all-errors)
    # would accept this file; only the digest refuses it.
    path = R.checkpoint_path(str(tmp_path), "phase1", 14)
    with open(path, "w") as f:
        json.dump({"completed": 14, "recommendations": evil}, f)
    with use_registry() as reg:
        assert R.load_latest_checkpoint(str(tmp_path), "phase1") == good
        c = reg.peek("manifest_failures_total", kind="results")
        assert c is not None and c.value == 1


# -- graceful drain -----------------------------------------------------------


def test_graceful_drain_signal_sets_flag():
    import signal

    from fairness_llm_tpu.resilience import drain_requested

    with use_registry():
        assert not drain_requested()
        with GracefulDrain(signals=(signal.SIGUSR1,)) as d:
            assert not d.requested
            signal.raise_signal(signal.SIGUSR1)
            assert d.requested and drain_requested()
        assert not drain_requested()  # uninstalled


# -- scheduler integration ----------------------------------------------------


# A GENEROUS watchdog budget: a real chunk on a loaded CPU harness can take
# seconds (the first one includes XLA compilation), and these tests must
# only ever classify the injector's SIMULATED stalls (hang_seconds=3600)
# as hangs — never a legitimately slow step.
RES = ResilienceConfig(enabled=True, max_step_seconds=120.0,
                       breaker_threshold=1, breaker_cooldown_s=0.02,
                       drain_grace_s=30.0)


def test_scheduler_contains_injected_hang(engine):
    """A watchdog-classified hang releases the whole chunk, requeues its
    riders once, and the retry decodes to full greedy parity."""
    inj = ScriptedFaultInjector(hangs={("hangme", "decode"): 1})
    with use_registry() as reg:
        sched = ContinuousScheduler(
            engine, SCFG, settings=greedy(8), fault_injector=inj,
            resilience=RES,
        )
        req = Request(prompt="the quick brown fox", id="hangme",
                      settings=greedy(8))
        (res,) = sched.serve([req])
        assert res.ok and res.retries == 1
        ref = engine.generate([req.prompt], req.settings)
        np.testing.assert_array_equal(
            res.tokens, ref.tokens[0][: len(res.tokens)]
        )
        assert reg.counter("watchdog_hangs_total", component="serving",
                           stage="decode").value == 1
        assert reg.counter("faults_total", component="serving", kind="hang",
                           stage="decode").value == 1
        assert reg.counter("serving_requeues_by_cause_total",
                           component="serving", cause="hang").value == 1


def test_scheduler_breaker_opens_and_recovers(engine):
    """Threshold-1 breaker: one scripted decode fault opens it (stopping
    decode until the cooldown), the half-open probe succeeds, and the run
    completes — full cycle in the transition counters."""
    inj = ScriptedFaultInjector({("flaky", "decode"): 1})
    with use_registry() as reg:
        sched = ContinuousScheduler(
            engine, SCFG, settings=greedy(8), fault_injector=inj,
            resilience=RES,
        )
        reqs = [Request(prompt=p, id=f"b{i}", settings=greedy(8))
                for i, p in enumerate(["hello there", "one two three"])]
        reqs.append(Request(prompt="fail fast", id="flaky",
                            settings=greedy(8)))
        results = sched.serve(reqs)
        assert all(r.ok for r in results)
        tr = lambda to: reg.counter(  # noqa: E731
            "breaker_transitions_total", component="serving", stage="decode",
            to=to,
        ).value
        assert tr("open") >= 1 and tr("half_open") >= 1 and tr("closed") >= 1
        # the board is back to healthy by drain end
        assert sched.breakers.state("decode") == CLOSED
        assert sched.breakers.ladder.level == 0


def test_degradation_rungs_apply_and_restore(engine):
    """Rung effects on the real scheduler: 1 sheds speculation, 2 halves the
    decode chunk + soft-caps the pool; retreat restores both."""
    from fairness_llm_tpu.config import SpeculationConfig

    with use_registry():
        sched = ContinuousScheduler(engine, SCFG, settings=greedy(8),
                                    resilience=RES)
        engine.speculation = SpeculationConfig(enabled=True)
        try:
            board = sched.breakers
            board.ladder.advance()
            sched._apply_degradation()
            assert engine.speculation is None  # rung 1: shed
            assert sched.decode_chunk == SCFG.decode_chunk
            board.ladder.advance()
            sched._apply_degradation()
            assert sched.decode_chunk == SCFG.decode_chunk // 2
            assert sched.live_cap == SCFG.num_slots // 2
            board.ladder.retreat()
            board.ladder.retreat()
            sched._apply_degradation()
            assert engine.speculation == SpeculationConfig(enabled=True)
            assert sched.decode_chunk == SCFG.decode_chunk
            assert sched.live_cap == SCFG.num_slots
        finally:
            engine.speculation = None


def test_shared_engine_spec_shed_restore_idempotent(engine):
    """Two schedulers sharing one engine + one board: the second shed must
    not capture the already-shed None, and whichever scheduler applies the
    retreat restores the ORIGINAL config (finding: a per-scheduler saved
    copy restored None forever)."""
    from fairness_llm_tpu.config import SpeculationConfig

    with use_registry():
        board = BreakerBoard(failure_threshold=1, cooldown_s=0.02)
        a = ContinuousScheduler(engine, SCFG, settings=greedy(8),
                                resilience=RES, breakers=board)
        b = ContinuousScheduler(engine, SCFG, settings=greedy(8),
                                resilience=RES, breakers=board)
        original = SpeculationConfig(enabled=True)
        engine.speculation = original
        try:
            board.ladder.advance()
            a._apply_degradation()
            assert engine.speculation is None
            b._apply_degradation()  # must not re-save the shed None
            board.ladder.retreat()
            b._apply_degradation()  # B restores what A shed
            assert engine.speculation == original
            a._apply_degradation()  # and A's pass changes nothing
            assert engine.speculation == original
        finally:
            engine.speculation = None
            engine._spec_shed = False


def test_static_fallback_probes_and_recovers(engine):
    """Degradation level 3 must be RECOVERABLE: while breakers cool the
    backend serves statically; once cooldowns elapse the next generate
    falls through to the scheduler as the probe and the ladder retreats."""
    from fairness_llm_tpu.serving import ServingBackend

    import dataclasses

    with use_registry():
        # A LONG cooldown makes "still cooling" deterministic however slow
        # the harness is; the elapse is then simulated by rewinding
        # opened_at rather than sleeping.
        backend = ServingBackend(
            engine, SCFG,
            resilience=dataclasses.replace(RES, breaker_cooldown_s=600.0),
        )
        board = backend.board
        for stage in ("prefill", "decode", "speculate"):
            board.record_failure(stage)
        assert board.ladder.level == 3
        prompts = ["hello there", "one two three"]
        # Cooldowns not elapsed: static path (ladder stays pinned at 3).
        texts1 = backend.generate(prompts, greedy(8), seed=0)
        assert board.ladder.level == 3
        for b in board.breakers.values():
            b.opened_at -= 601.0  # cooldown "elapses"
        # This call IS the probe — scheduler path, successes close
        # prefill+decode, ladder walks down.
        texts2 = backend.generate(prompts, greedy(8), seed=0)
        assert board.state("prefill") == CLOSED
        assert board.state("decode") == CLOSED
        assert board.ladder.level == 1  # speculate still holds its rung
        assert texts1 == texts2  # greedy parity across the two paths


def test_fault_during_drain_grace_still_yields_results(engine, tmp_path):
    """A fault DURING the drain-grace decode window requeues its victim
    into the closed queue; the drain must sweep it into a preempted Result
    (finding: it stranded with no Result and serve() raised KeyError)."""

    class DrainThenFault(ScriptedFaultInjector):
        """Requests a drain at the first decode consult, then faults 'g1'
        on the SECOND consult — i.e. inside the grace loop."""

        def __init__(self, sched_ref):
            super().__init__()
            self.sched_ref = sched_ref
            self.consults = 0

        def maybe_fail(self, request_id, stage):
            if stage != "decode":
                return
            if self.consults == 0:
                self.sched_ref[0].request_drain()
            self.consults += 1
            if request_id == "g1" and self.consults > 2:
                self.fired.append((request_id, stage))
                raise DecodeFault("injected grace-window fault for 'g1'")

    with use_registry():
        journal = ServingJournal(str(tmp_path))
        sched_ref = []
        inj = DrainThenFault(sched_ref)
        sched = ContinuousScheduler(
            engine, SCFG, settings=greedy(8), fault_injector=inj,
            resilience=RES, journal=journal,
        )
        sched_ref.append(sched)
        reqs = [Request(prompt="the quick brown fox", id="g0",
                        settings=greedy(8)),
                Request(prompt="hello there", id="g1", settings=greedy(8))]
        results = {r.id: r for r in sched.serve(reqs)}  # must not KeyError
        assert set(results) == {"g0", "g1"}
        assert inj.fired, "the grace-window fault must have fired"
        assert results["g1"].finish_reason == "preempted"
        # The victim is journaled unfinished and resumable with parity.
        assert [r["id"] for r in journal.unfinished()] == ["g1"]
        resumed = resume_serving(engine, journal, serving=SCFG,
                                 resilience=RES)
        res = resumed["g1"]
        assert res.ok
        ref = engine.generate(["hello there"], greedy(8))
        np.testing.assert_array_equal(
            res.tokens, ref.tokens[0][: len(res.tokens)]
        )


def test_soft_cap_still_serves_everything(engine):
    """With the pool soft-capped at 1 of 2 slots, the full workload still
    completes (serially) with greedy parity."""
    with use_registry():
        sched = ContinuousScheduler(engine, SCFG, settings=greedy(8),
                                    resilience=RES)
        sched.live_cap = 1
        reqs = [Request(prompt=p, id=f"c{i}", settings=greedy(8))
                for i, p in enumerate(["hi", "abc abc abc", "zz"])]
        results = sched.serve(reqs)
        for req, res in zip(reqs, results):
            assert res.ok
            ref = engine.generate([req.prompt], req.settings)
            np.testing.assert_array_equal(
                res.tokens, ref.tokens[0][: len(res.tokens)]
            )


def test_drain_preempts_and_resume_finishes(engine, tmp_path):
    """Mid-run drain: requests still queued preempt to the journal; a fresh
    resume_serving finishes them with greedy parity and empties the
    journal."""

    class DrainOnSight(ScriptedFaultInjector):
        def __init__(self, sched_ref, trigger_id):
            super().__init__()
            self.sched_ref = sched_ref
            self.trigger_id = trigger_id

        def maybe_fail(self, request_id, stage):
            # First decode consult of the trigger request: ask for a drain —
            # deterministic "SIGTERM arrived mid-run".
            if request_id == self.trigger_id and stage == "decode":
                self.sched_ref[0].request_drain()
            super().maybe_fail(request_id, stage)

    with use_registry():
        journal = ServingJournal(str(tmp_path))
        sched_ref = []
        inj = DrainOnSight(sched_ref, "d0")
        sched = ContinuousScheduler(
            engine, SCFG, settings=greedy(8), fault_injector=inj,
            resilience=RES, journal=journal,
        )
        sched_ref.append(sched)
        prompts = ["the quick brown fox", "hi", "abc abc abc abc",
                   "one two three", "recommend ten films please"]
        reqs = [Request(prompt=p, id=f"d{i}", settings=greedy(8))
                for i, p in enumerate(prompts)]
        results = sched.serve(reqs)
        by_reason = {}
        for r in results:
            by_reason.setdefault(r.finish_reason, []).append(r.id)
        assert by_reason.get("preempted"), "drain must preempt something"
        assert sched.last_stats.preempted == len(by_reason["preempted"])
        # Journal holds exactly the preempted set, unfinished.
        assert sorted(r["id"] for r in journal.unfinished()) == \
            sorted(by_reason["preempted"])
        # Resume in a "successor process" (fresh scheduler, same journal).
        resumed = resume_serving(engine, journal, serving=SCFG,
                                 resilience=RES)
        assert sorted(resumed) == sorted(by_reason["preempted"])
        for req in reqs:
            res = resumed.get(req.id) or next(
                r for r in results if r.id == req.id
            )
            assert res.ok, (req.id, res.error)
            ref = engine.generate([req.prompt], req.settings)
            np.testing.assert_array_equal(
                res.tokens, ref.tokens[0][: len(res.tokens)]
            )
        assert journal.unfinished() == []


# -- the chaos soak -----------------------------------------------------------


def test_chaos_soak_faults_hang_drain_resume(engine, tmp_path):
    """The ISSUE-4 acceptance run: scripted prefill+decode faults (one
    transient, one permanent), one injected hang, a mid-run drain, then
    resume — every request terminal, survivors greedy-parity, breaker
    closed -> open -> half-open -> closed visible in the snapshot."""
    from fairness_llm_tpu.telemetry import snapshot

    prompts = {
        "ok0": "the quick brown fox",
        "flaky": "hello there friend",
        "doomed": "abc abc abc abc abc",
        "pfault": "one two three one two",
        "hangme": "recommend ten films please",
        "late0": "zz zz zz",
        "late1": "a long prompt that shifts padding and lands in a bucket",
    }

    class DrainAfter(ScriptedFaultInjector):
        """Requests a drain the first time a LATE request reaches decode —
        by then the early cohort has churned through fault/hang/recovery."""

        def __init__(self, faults, hangs, sched_ref):
            super().__init__(faults, hangs=hangs)
            self.sched_ref = sched_ref

        def maybe_fail(self, request_id, stage):
            if request_id == "late0" and stage == "decode":
                self.sched_ref[0].request_drain()
            super().maybe_fail(request_id, stage)

    with use_registry() as reg:
        journal = ServingJournal(str(tmp_path))
        sched_ref = []
        inj = DrainAfter(
            faults={("flaky", "decode"): 1,   # transient: requeue + succeed
                    ("doomed", "decode"): 2,  # permanent: requeue + fail
                    ("pfault", "prefill"): 1},
            hangs={("hangme", "decode"): 1},  # one injected hang
            sched_ref=sched_ref,
        )
        sched = ContinuousScheduler(
            engine, SCFG, settings=greedy(8), fault_injector=inj,
            resilience=RES, journal=journal,
        )
        sched_ref.append(sched)
        reqs = [Request(prompt=p, id=rid, settings=greedy(8))
                for rid, p in prompts.items()]
        results = {r.id: r for r in sched.serve(reqs)}

        # Phase 1 invariants: everything terminal, the permanent fault
        # failed, nothing silently lost.
        assert set(results) == set(prompts)
        assert results["doomed"].finish_reason == "failed"
        assert results["doomed"].retries == 1
        preempted = [rid for rid, r in results.items()
                     if r.finish_reason == "preempted"]
        assert preempted, "the drain must have caught the late cohort"
        assert "doomed" not in preempted
        # "pfault" may legitimately fail: its one requeue went to the
        # scripted prefill fault, and if it then shares the hung decode
        # chunk with "hangme" the hang's whole-chunk blast radius is its
        # SECOND contained fault — requeue-once semantics say that
        # terminates failed, which is a terminal outcome, not a loss.
        must_succeed = set(prompts) - {"doomed", "pfault"}

        # Resume the journal in a fresh scheduler ("successor process").
        resumed = resume_serving(engine, journal, serving=SCFG,
                                 resilience=RES)
        assert sorted(resumed) == sorted(preempted)
        assert journal.unfinished() == []

        # Zero lost: every request has exactly one terminal outcome across
        # the two runs, and every survivor is token-for-token greedy parity
        # with the uninterrupted engine.
        final = {**results, **resumed}
        for rid, prompt in prompts.items():
            res = final[rid]
            if rid == "doomed":
                assert not res.ok
                continue
            if rid not in must_succeed and not res.ok:
                assert res.finish_reason == "failed"  # terminal, not lost
                continue
            assert res.ok, (rid, res.finish_reason, res.error)
            ref = engine.generate([prompt], greedy(8))
            np.testing.assert_array_equal(
                res.tokens, ref.tokens[0][: len(res.tokens)]
            )
            pad = engine.tokenizer.pad_id
            assert np.all(ref.tokens[0][len(res.tokens):] == pad)

        # The breaker walked its full cycle and telemetry can prove it.
        snap = snapshot(reg)
        tr = {
            (c["labels"]["stage"], c["labels"]["to"]): c["value"]
            for c in snap["counters"]
            if c["name"] == "breaker_transitions_total"
        }
        assert tr.get(("decode", "open"), 0) >= 1
        assert tr.get(("decode", "half_open"), 0) >= 1
        assert tr.get(("decode", "closed"), 0) >= 1
        counters = {
            (c["name"],) + tuple(sorted(c["labels"].items())): c["value"]
            for c in snap["counters"]
        }
        assert reg.counter("watchdog_hangs_total", component="serving",
                           stage="decode").value == 1
        assert reg.counter("serving_preempted_total",
                           component="serving").value == len(preempted)
        # Healthy again: breakers closed, ladder fully retreated.
        assert sched.breakers.state("decode") == CLOSED
        assert sched.breakers.state("prefill") == CLOSED
        assert sched.breakers.ladder.level == 0
        assert counters  # snapshot non-degenerate

"""Regression guard for the framework's own committed golden run
(``results/`` at the repo root — see ``results/README.md``).

Re-runs the deterministic simulated study with the same defaults and asserts
the headline metrics match the committed record. Any change to prompts,
simulator entropy, parsing, metric kernels, sweep chunking, or seeding that
shifts the numbers fails here — the same role the reference's committed
``results/*.json`` played for its README claims.
"""

import json
import pathlib

import pytest

from fairness_llm_tpu.config import Config
from fairness_llm_tpu.pipeline import run_phase1, run_phase2, run_phase3

GOLDEN_DIR = pathlib.Path(__file__).resolve().parent.parent / "results"
DATA_DIR = pathlib.Path(__file__).resolve().parent.parent / "data" / "ml-1m"

ATOL = 1e-4  # float32 metric kernels


@pytest.fixture(scope="module")
def golden_phase1():
    path = GOLDEN_DIR / "phase1" / "phase1_results.json"
    if not path.exists():
        pytest.skip("no committed golden run")
    with open(path) as f:
        return json.load(f)


def _require_matching_provenance(golden_meta):
    """Records pin their corpus identity; compare only when the CURRENT
    loader reproduces it (round-3 verdict: provenance pinning replaces the
    old requires-data-ABSENT fragility). A mismatch means the data under
    data/ml-1m changed (e.g. a real ratings.dat was added) — regenerate the
    records per results/README.md instead of chasing numeric drift."""
    from fairness_llm_tpu.data import load_movielens

    want = golden_meta.get("corpus")
    if want is None:
        pytest.skip("committed record predates corpus provenance — regenerate")
    have = load_movielens(str(DATA_DIR), seed=42).provenance()
    if have != want:
        pytest.skip(
            f"corpus provenance changed (record {want} vs current {have}) — "
            "regenerate results/ (see results/README.md)"
        )


@pytest.fixture(scope="module")
def fresh_phase1(tmp_path_factory, golden_phase1):
    _require_matching_provenance(golden_phase1["metadata"])
    config = Config(
        results_dir=str(tmp_path_factory.mktemp("golden")), data_dir=str(DATA_DIR)
    )
    return config, run_phase1(config, model_name="simulated", save=False)


def test_phase1_metrics_match_committed_record(golden_phase1, fresh_phase1):
    _, fresh = fresh_phase1
    g, f = golden_phase1["metrics"], fresh["metrics"]
    assert f["demographic_parity_gender"]["score"] == pytest.approx(
        g["demographic_parity_gender"]["score"], abs=ATOL
    )
    assert f["demographic_parity_age"]["score"] == pytest.approx(
        g["demographic_parity_age"]["score"], abs=ATOL
    )
    assert f["individual_fairness"]["score"] == pytest.approx(
        g["individual_fairness"]["score"], abs=ATOL
    )
    assert f["equal_opportunity"]["score"] == pytest.approx(
        g["equal_opportunity"]["score"], abs=ATOL
    )
    assert f["snsr_snsv"]["snsr"] == pytest.approx(g["snsr_snsv"]["snsr"], abs=ATOL)
    assert f["snsr_snsv"]["snsv"] == pytest.approx(g["snsr_snsv"]["snsv"], abs=ATOL)


def test_phase1_recommendations_match_committed_record(golden_phase1, fresh_phase1):
    """Decoded text, not just aggregates: the sweep is end-to-end deterministic."""
    _, fresh = fresh_phase1
    g_recs = golden_phase1["recommendations"]
    f_recs = fresh["recommendations"]
    assert set(g_recs) == set(f_recs)
    for pid in g_recs:
        assert g_recs[pid]["recommendations"] == f_recs[pid]["recommendations"], pid


def test_phase2_movielens_at_scale_matches_committed_record(tmp_path):
    """The at-scale phase-2 surface (200 ML-1M items, 4 queries, three
    bias-variant models) has its own committed record; re-running must
    reproduce every model's fairness numbers AND show the bias gradient
    (fair > default > biased on listwise exposure)."""
    path = GOLDEN_DIR / "phase2" / "phase2_movielens_results.json"
    if not path.exists():
        pytest.skip("no committed at-scale record")
    with open(path) as f:
        golden = json.load(f)

    _require_matching_provenance(
        {"corpus": golden["metadata"].get("corpus_provenance")}
    )
    config = Config(results_dir=str(tmp_path), data_dir=str(DATA_DIR))
    fresh = run_phase2(
        config, models=["simulated-fair", "simulated", "simulated-biased"],
        corpus="movielens", num_items=200, num_queries=4, num_comparisons=60,
        save=False,
    )
    g, f = golden["comparison"]["model_fairness"], fresh["comparison"]["model_fairness"]
    for model in g:
        for key in ("listwise_fairness", "pairwise_fairness", "average_fairness"):
            assert f[model][key] == pytest.approx(g[model][key], abs=ATOL), (model, key)
    lw = {m: f[m]["listwise_fairness"] for m in f}
    assert lw["simulated-fair"] > lw["simulated"] > lw["simulated-biased"]


def test_phase3_conformal_matches_committed_record(fresh_phase1):
    path = GOLDEN_DIR / "phase3" / "phase3_results.json"
    if not path.exists():
        pytest.skip("no committed golden run")
    with open(path) as f:
        golden = json.load(f)
    config, p1 = fresh_phase1
    fresh = run_phase3(config, phase1_results=p1, model_name="simulated", save=False)
    gb, fb = golden["bias_reduction"], fresh["bias_reduction"]
    assert fb["original_fairness"] == pytest.approx(gb["original_fairness"], abs=ATOL)
    assert fb["mitigated_fairness"] == pytest.approx(gb["mitigated_fairness"], abs=ATOL)
    assert fb["bias_reduction_rate"] == pytest.approx(gb["bias_reduction_rate"], abs=1e-2)

"""Study-level sharding proof: the ENTIRE phase-1 pipeline (tokenize ->
dp-sharded batched decode -> parse -> metrics) must produce byte-identical
recommendations and identical fairness numbers whether the engine runs on one
device or dp-sharded over the virtual mesh. Engine-level equivalence lives in
tests/test_engine.py; this covers the full study path the reference's API
loop corresponds to (SURVEY.md §3.2)."""

import pytest

from fairness_llm_tpu.config import Config, MeshConfig
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.parallel import sharding as shd
from fairness_llm_tpu.pipeline.backends import EngineBackend
from fairness_llm_tpu.pipeline.phase1 import run_phase1
from fairness_llm_tpu.runtime.engine import DecodeEngine

ATOL = 1e-5


@pytest.fixture(scope="module")
def engines():
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs multiple virtual devices")
    cfg = get_model_config("tiny-test")
    plain = DecodeEngine(cfg, seed=0)
    mesh = shd.make_mesh(MeshConfig(dp=2))
    sharded = DecodeEngine(cfg, params=plain.params, mesh=mesh)
    return plain, sharded


def _study(backend, tmp_path, sub):
    config = Config(
        results_dir=str(tmp_path / sub), data_dir="/nonexistent",
        profiles_per_combo=1, max_new_tokens=24,
    )
    return run_phase1(config, model_name="tiny-test", backend=backend, save=False)


def test_phase1_study_identical_sharded_vs_unsharded(engines, tmp_path):
    plain, sharded = engines
    r1 = _study(EngineBackend(plain, name="tiny-test"), tmp_path, "plain")
    r2 = _study(EngineBackend(sharded, name="tiny-test"), tmp_path, "sharded")

    # decoded text byte-identical per profile
    assert set(r1["recommendations"]) == set(r2["recommendations"])
    for pid, rec in r1["recommendations"].items():
        assert r2["recommendations"][pid]["raw_response"] == rec["raw_response"], pid

    # the sharded study must have taken the ON-DEVICE reduction path (psum
    # over dp — VERDICT r2 weak #1: a property of the study, not a library)
    # while the plain study reduced host-side...
    assert r1["metadata"]["metric_reduction"] == "host"
    assert r2["metadata"]["metric_reduction"] == "dp-psum"

    # ...and both reductions produce identical fairness numbers.
    m1, m2 = r1["metrics"], r2["metrics"]
    for key in ("demographic_parity_gender", "demographic_parity_age",
                "individual_fairness", "equal_opportunity",
                "equal_opportunity_age"):
        assert abs(m1[key]["score"] - m2[key]["score"]) < ATOL, key
    assert abs(m1["snsr_snsv"]["snsr"] - m2["snsr_snsv"]["snsr"]) < ATOL
    # EO per-group rates and DP divergence details agree too
    assert m1["equal_opportunity"]["group_scores"] == pytest.approx(
        m2["equal_opportunity"]["group_scores"]
    )
    assert m1["demographic_parity_gender"]["avg_divergence"] == pytest.approx(
        m2["demographic_parity_gender"]["avg_divergence"], abs=ATOL
    )

"""Phase 2 through the real decode engine at long-prompt scale: the listwise
ranking batch is the framework's prefill-heavy headline path (bench.py
``measure_phase2_listwise``); this covers it in the suite with the tiny model
so engine/bucketing/flash-gating regressions surface off-TPU too."""

import dataclasses

import pytest

from fairness_llm_tpu.config import ModelSettings
from fairness_llm_tpu.data import movielens_ranking_corpus, synthetic_movielens
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.pipeline.backends import EngineBackend
from fairness_llm_tpu.pipeline.phase2 import (
    evaluate_model,
    listwise_evaluation_batch,
    make_queries,
)
from fairness_llm_tpu.runtime.engine import DecodeEngine


@pytest.fixture(scope="module")
def long_engine():
    # tiny-test widened so a ~40-item byte-tokenized listwise prompt fits
    config = dataclasses.replace(get_model_config("tiny-test"), max_seq_len=4096)
    return DecodeEngine(config, seed=0)


@pytest.fixture(scope="module")
def corpus():
    data = synthetic_movielens(num_movies=120, seed=9)
    return movielens_ranking_corpus(data, num_items=40, seed=9, min_ratings=1)


def test_listwise_long_prompt_batch_through_engine(long_engine, corpus):
    backend = EngineBackend(long_engine, name="tiny-test")
    settings = ModelSettings(temperature=0.7, max_tokens=16)
    queries = make_queries(corpus, 3)
    rankings, parsed = listwise_evaluation_batch(backend, corpus, queries, settings, seed=0)
    assert len(rankings) == 3
    ids = {it.id for it in corpus}
    for r in rankings:
        assert set(r) == ids  # identity fallback still yields full permutations
    # the same prompts through the engine directly: decode shape confirms this
    # really is the long-prompt path (bucketed prompt length > 1k tokens)
    from fairness_llm_tpu.pipeline.prompts import listwise_prompt

    out = long_engine.generate([listwise_prompt(corpus)], settings, seed=0)
    assert out.stats["prompt_len"] > 1024


def test_evaluate_model_through_engine_reports_failures(long_engine, corpus):
    """Random-weight decode yields unparseable text; the failure report must
    say so rather than silently producing identity metrics."""
    backend = EngineBackend(long_engine, name="tiny-test")
    settings = ModelSettings(temperature=0.7, max_tokens=16)
    res = evaluate_model(backend, corpus, num_comparisons=4, settings=settings,
                         seed=0, num_queries=2)
    pf = res["parse_failures"]
    assert 0.0 <= pf["listwise_failure_rate"] <= 1.0
    assert "corpus_perplexity" in res  # engine-only extra
    assert res["listwise"]["num_queries"] == 2

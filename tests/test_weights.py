"""Checkpoint IO tests: HF-layout safetensors round-trip for both weight layouts
(llama-style [out,in] matrices; gpt2-style fused-QKV Conv1D), plus sharded load."""

import jax
import numpy as np
import pytest

from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.models.transformer import init_params
from fairness_llm_tpu.runtime.weights import (
    family_of,
    load_checkpoint,
    save_checkpoint_hf,
)


def _tree_equal(a, b, path=""):
    assert set(a.keys()) == set(b.keys()), f"{path}: {set(a)} != {set(b)}"
    for k in a:
        if isinstance(a[k], dict):
            _tree_equal(a[k], b[k], f"{path}/{k}")
        else:
            np.testing.assert_allclose(
                np.asarray(a[k], np.float32), np.asarray(b[k], np.float32),
                atol=1e-6, err_msg=f"{path}/{k}",
            )


@pytest.mark.parametrize("name", ["tiny-test", "tiny-gpt2"])
def test_hf_roundtrip(name, tmp_path):
    cfg = get_model_config(name)
    params = init_params(cfg, jax.random.key(0))
    save_checkpoint_hf(cfg, params, str(tmp_path))
    loaded = load_checkpoint(cfg, str(tmp_path), dtype=np.float32)
    _tree_equal(params, loaded)


def test_family_detection():
    assert family_of(get_model_config("llama3-8b")) == "llama"
    assert family_of(get_model_config("mistral-7b")) == "mistral"
    assert family_of(get_model_config("gemma-7b")) == "gemma"
    assert family_of(get_model_config("gpt2-small")) == "gpt2"
    assert family_of(get_model_config("tiny-test")) == "llama"
    assert family_of(get_model_config("tiny-gpt2")) == "gpt2"


def test_sharded_load_places_on_mesh(tmp_path, eight_device_mesh):
    cfg = get_model_config("tiny-test")
    params = init_params(cfg, jax.random.key(0))
    save_checkpoint_hf(cfg, params, str(tmp_path))
    loaded = load_checkpoint(cfg, str(tmp_path), mesh=eight_device_mesh, dtype=np.float32)
    q = loaded["layer_0"]["attn"]["q_proj"]["kernel"]
    assert "tp" in str(q.sharding.spec)
    _tree_equal(params, loaded)

"""Multi-host helpers: single-process degradation + mesh layout invariants."""

import numpy as np
import pytest

from fairness_llm_tpu.config import MeshConfig
from fairness_llm_tpu.parallel.multihost import initialize_distributed, make_multihost_mesh


def test_initialize_distributed_noop_single_process(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert initialize_distributed() is False


def test_multihost_mesh_single_process(eight_device_mesh):
    mesh = make_multihost_mesh(MeshConfig(dp=2, tp=2, sp=2))
    assert mesh.shape == {"dp": 2, "tp": 2, "sp": 2}
    # dp outermost: the first tp*sp block of the device order forms dp row 0
    devs = np.asarray(mesh.devices)
    assert devs.shape == (2, 2, 2)
    flat = [d.id for d in devs.reshape(-1)]
    assert flat == sorted(flat)  # contiguous device order => tp/sp groups stay local


def test_multihost_mesh_too_many_devices():
    with pytest.raises(ValueError):
        make_multihost_mesh(MeshConfig(dp=64, tp=8, sp=1))

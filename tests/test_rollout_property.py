"""Property-based RolloutController invariants (hypothesis over random
fault/gate sequences), sibling of test_breaker_property.py.

The rollout wave machine owns fleet membership during an upgrade, so its
state machine is load-bearing for every serving guarantee at once:

1. **Transition order**: the controller only ever moves along
   ``LEGAL_TRANSITIONS`` — whatever faults, gate signals, and clock jumps
   land in whatever order. Terminal states are absorbing: once
   ``rolled_back`` or ``complete``, further ticks are no-ops.
2. **Rollback reachability**: from EVERY non-terminal started state there
   is a fault/gate sequence that lands in ``rolled_back`` — no wave
   position exists where the operator has lost the abort lever.
3. **Version affinity**: the router's hard version filter
   (``pick(require_version=...)``) never returns a replica of another
   version — under any mix of versions, fences, and load, a pinned
   request either stays on its version or waits (the fleet restamps only
   when the pinned version has no live replica at all).
"""

import pytest

try:  # the fuzzed tests gate on hypothesis; deterministic ones always run
    from hypothesis import given, settings
    from hypothesis import strategies as st
except ImportError:  # pragma: no cover
    given = settings = st = None

from fairness_llm_tpu.config import FleetConfig, RolloutConfig
from fairness_llm_tpu.integrity.manifest import IntegrityError
from fairness_llm_tpu.serving import HealthRouter
from fairness_llm_tpu.serving.rollout import (
    LEGAL_TRANSITIONS,
    ROLLOUT_STATES,
    TERMINAL_STATES,
    RolloutController,
)
from fairness_llm_tpu.telemetry import use_registry

WINDOW_S = 1.0


# -- the duck-typed fleet the controller drives -------------------------------


class FakeReplica:
    def __init__(self, name, version):
        self.name = name
        self.version = version
        self.fenced = False
        self.fence_reason = None
        self.sched = type("S", (), {"breakers": None})()


class FakeRouter:
    def __init__(self):
        self.traffic = None

    def set_version_traffic(self, version, fraction=0.0):
        self.traffic = None if version is None or fraction <= 0.0 \
            else (version, fraction)

    def load(self, rep):
        return 0.0


class FakeFleet:
    """The exact surface RolloutController touches on ReplicaSet."""

    def __init__(self, n=2, version="v0"):
        self.version = version
        self.replicas = [FakeReplica(f"r{i}", version) for i in range(n)]
        self.router = FakeRouter()
        self.name = None
        self.autoscaler = None
        self.rollout = None
        self.refuse_add = False
        self._seq = n
        self._engine_pool = [object()]
        self._rep_serving = None

    def add_replica(self, engine=None, version=None, serving=None):
        if self.refuse_add:
            return None  # the standby's canary gate said no
        rep = FakeReplica(f"r{self._seq}", version or self.version)
        self._seq += 1
        self.replicas.append(rep)
        return rep

    def retire_replica(self, rep):
        assert len(self.replicas) > 1, "retire would empty the fleet"
        self.replicas.remove(rep)
        return 0

    def _fence(self, rep, reason):
        rep.fenced = True
        rep.fence_reason = reason


def build(n=2, **cfg):
    fleet = FakeFleet(n=n)
    clock = {"t": 0.0}
    ro = RolloutController(
        fleet, "v1", engine=object(),
        config=RolloutConfig(enabled=True, canary_window_s=WINDOW_S,
                             traffic_steps=2, **cfg),
        clock=lambda: clock["t"],
    )
    return fleet, clock, ro


def spy_transitions(ro):
    seen = []
    orig = ro._transition

    def spy(to, now, cause=None):
        seen.append((ro.state, to))
        orig(to, now=now, cause=cause)

    ro._transition = spy
    return seen


# -- 1 + 2: transition order and rollback reachability, fuzzed ---------------

OP_NAMES = [
    "tick",         # one controller step, clock unchanged
    "window",       # the gate window elapses, then a step
    "fence_new",    # watchdog/breaker verdict on a new-version replica
    "canary_fail",  # canary mismatch published for a new replica
    "refuse_add",   # the NEXT standby fails its join canary
    "allow_add",
]


def _run_fault_sequence(ops, n):
    with use_registry():
        from fairness_llm_tpu.telemetry import get_registry

        fleet, clock, ro = build(n=n)
        seen = spy_transitions(ro)
        ro.start()
        for op in ops:
            if ro.state in TERMINAL_STATES:
                break
            if op == "tick":
                ro.tick()
            elif op == "window":
                clock["t"] += WINDOW_S + 0.01
                ro.tick()
            elif op == "fence_new":
                for rep in ro.new_replicas:
                    fleet._fence(rep, "replica_crash")
            elif op == "canary_fail":
                for rep in ro.new_replicas:
                    get_registry().gauge(
                        "canary_last_ok", component="serving",
                        replica=rep.name,
                    ).set(0.0)
            elif op == "refuse_add":
                fleet.refuse_add = True
            else:
                fleet.refuse_add = False
            clock["t"] += 0.01

        assert all(edge in LEGAL_TRANSITIONS for edge in seen), seen
        assert ro.state in ROLLOUT_STATES

        # Terminal states are absorbing.
        if ro.state in TERMINAL_STATES:
            before = ro.state
            assert ro.tick() is False
            assert ro.state == before

        # Rollback (or legitimate completion) is reachable from ANY
        # random prefix: fencing every new replica and ticking must land
        # terminal — the abort lever never goes dead mid-wave.
        forced = False
        for _ in range(8 * n + 16):
            if ro.state in TERMINAL_STATES:
                break
            if ro.new_replicas:
                forced = True
                for rep in ro.new_replicas:
                    fleet._fence(rep, "replica_crash")
            clock["t"] += WINDOW_S + 0.01
            ro.tick()
        assert ro.state in TERMINAL_STATES, ro.state
        if forced and ro.state == "rolled_back":
            assert ro.cause is not None
        assert all(edge in LEGAL_TRANSITIONS for edge in seen), seen
        # However it ended, the fleet is never left version-mixed or
        # fenced: survivors are whole.
        live = [r for r in fleet.replicas if not r.fenced]
        assert live and len({r.version for r in live}) == 1


if st is not None:

    @settings(max_examples=100, deadline=None)
    @given(
        ops=st.lists(st.sampled_from(OP_NAMES), max_size=60),
        n=st.integers(min_value=2, max_value=4),
    )
    def test_rollout_legal_transitions_under_random_faults(ops, n):
        _run_fault_sequence(ops, n)


def test_rollout_legal_transitions_fixed_sequences():
    # Deterministic corpus so the invariants hold even where hypothesis
    # isn't installed: clean completion, every gate, and absorbing ends.
    corpus = [
        ["window"] * 12,                               # clean v0 -> v1
        ["tick", "tick", "fence_new", "window"] * 4,   # watchdog gate
        ["tick", "tick", "canary_fail", "tick"] * 4,   # canary mismatch
        ["refuse_add", "tick", "tick", "tick"],        # standby refused
        ["tick", "window", "allow_add", "fence_new", "window"] * 3,
        [],                                            # started, untouched
    ]
    for ops in corpus:
        for n in (2, 3):
            _run_fault_sequence(ops, n)


def test_rollback_reachable_from_every_nonterminal_state():
    # preparing: the manifest gate (engine_fn refused).
    with use_registry():
        fleet = FakeFleet()
        clock = {"t": 0.0}

        def refused():
            raise IntegrityError("digest mismatch: model.safetensors")

        ro = RolloutController(fleet, "v1", engine_fn=refused,
                               config=RolloutConfig(enabled=True),
                               clock=lambda: clock["t"])
        ro.start()
        assert ro.state == "preparing"
        ro.tick()
        assert ro.state == "rolled_back" and "manifest" in ro.cause

    # canary: the standby's join canary refuses.
    with use_registry():
        fleet, clock, ro = build()
        fleet.refuse_add = True
        ro.start()
        ro.tick()  # preparing -> canary
        assert ro.state == "canary"
        ro.tick()  # add refused -> rollback
        assert ro.state == "rolled_back" and "canary" in ro.cause

    # shifting: a watchdog fence on the new replica mid-window.
    with use_registry():
        fleet, clock, ro = build()
        ro.start()
        ro.tick()
        ro.tick()
        assert ro.state == "shifting"
        for rep in ro.new_replicas:
            fleet._fence(rep, "replica_crash")
        ro.tick()
        assert ro.state == "rolled_back" and "watchdog" in ro.cause

    # retiring: gates stay armed through the wave tail.
    with use_registry():
        fleet, clock, ro = build()
        ro.start()
        ro.tick()
        ro.tick()
        while ro.state == "shifting":
            clock["t"] += WINDOW_S + 0.01
            ro.tick()
        assert ro.state == "retiring"
        for rep in ro.new_replicas:
            fleet._fence(rep, "rollout_probe")
        ro.tick()
        assert ro.state == "rolled_back" and "breaker" in ro.cause

    # crash resolution: terminal from any mid-wave state, no membership.
    with use_registry():
        fleet, clock, ro = build()
        ro.start()
        ro.tick()
        ro.tick()
        assert ro.state == "shifting"
        ro.resolve_crashed("test crash")
        assert ro.state == "rolled_back" and "crash" in ro.cause


# -- 3: version affinity under the router's hard filter ----------------------


class _StubQueue:
    def __init__(self, depth=0, full=False):
        self.depth, self.full, self.closed = depth, full, False

    def __len__(self):
        return self.depth


class _StubSched:
    def __init__(self, occupancy=0, depth=0, full=False):
        self.pool = type("P", (), {"occupancy": occupancy})()
        self.queue = _StubQueue(depth, full=full)
        self._pending = []
        self.breakers = None
        self.watchdog = None
        self.num_slots = 4


class _StubReplica:
    def __init__(self, name, version, fenced=False, occupancy=0, depth=0,
                 full=False):
        self.name = name
        self.version = version
        self.fenced = fenced
        self.sched = _StubSched(occupancy=occupancy, depth=depth, full=full)


def _check_affinity(rows, pinned, frac):
    # rows: (version, fenced, occupancy, queue depth, queue full) tuples.
    with use_registry():
        router = HealthRouter(FleetConfig(replicas=max(2, len(rows))))
        router.set_version_traffic("v1", frac)
        replicas = [
            _StubReplica(f"r{i}", v, fenced=f, occupancy=o, depth=d,
                         full=fl)
            for i, (v, f, o, d, fl) in enumerate(rows)
        ]
        placeable = [r for r in replicas
                     if r.version == pinned and not r.fenced
                     and not r.sched.queue.full]
        for _ in range(4):  # the steering accumulator cycles; hold always
            chosen = router.pick(replicas, require_version=pinned)
            if chosen is not None:
                # The hard filter: NEVER a cross-version placement.
                assert chosen.version == pinned
            else:
                # Refusal is only legal when no placeable same-version
                # replica exists — otherwise affinity would starve.
                assert not placeable


if st is not None:

    @settings(max_examples=200, deadline=None)
    @given(
        rows=st.lists(
            st.tuples(
                st.sampled_from(["v0", "v1"]),
                st.booleans(),
                st.integers(min_value=0, max_value=4),
                st.integers(min_value=0, max_value=8),
                st.booleans(),
            ),
            min_size=1, max_size=6,
        ),
        pinned=st.sampled_from(["v0", "v1"]),
        frac=st.floats(min_value=0.0, max_value=1.0),
    )
    def test_version_affinity_never_violated(rows, pinned, frac):
        _check_affinity(rows, pinned, frac)


def test_version_affinity_fixed_cases():
    cases = [
        # mixed versions, all placeable
        ([("v0", False, 0, 0, False), ("v1", False, 0, 0, False)], "v0", 0.5),
        ([("v0", False, 0, 0, False), ("v1", False, 0, 0, False)], "v1", 0.5),
        # pinned version fenced out entirely -> pick must refuse
        ([("v0", True, 0, 0, False), ("v1", False, 2, 1, False)], "v0", 1.0),
        # pinned version only behind a full queue -> refuse, never cross
        ([("v1", False, 4, 8, True), ("v0", False, 0, 0, False)], "v1", 0.0),
        # single-version fleet, heavy load spread
        ([("v0", False, i, i, False) for i in range(5)], "v0", 0.0),
        # everything fenced
        ([("v0", True, 0, 0, False), ("v1", True, 0, 0, False)], "v1", 1.0),
    ]
    for rows, pinned, frac in cases:
        _check_affinity(rows, pinned, frac)

"""Aux subsystems: orbax train-state checkpointing, failure containment,
profiling context managers, native-parser strictness."""

import logging

import jax
import numpy as np
import pytest

from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.train import make_train_step
from fairness_llm_tpu.train.checkpoint import restore_train_state, save_train_state
from fairness_llm_tpu.utils import maybe_trace, phase_timer, with_failure_containment


def test_train_state_checkpoint_roundtrip(tmp_path):
    cfg = get_model_config("tiny-test")
    init_state, step = make_train_step(cfg)
    state = init_state(jax.random.key(0))
    tokens = np.random.default_rng(0).integers(3, 512, (4, 8)).astype(np.int32)
    valid = np.ones((4, 8), bool)
    state, _ = step(state, tokens, valid)
    save_train_state(str(tmp_path), state)

    template = init_state(jax.random.key(1))  # different values, same structure
    restored = restore_train_state(str(tmp_path), template)
    assert restored is not None
    assert int(restored.step) == 1
    a = jax.tree.leaves(state.params)[0]
    b = jax.tree.leaves(restored.params)[0]
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_restore_empty_dir_returns_none(tmp_path):
    cfg = get_model_config("tiny-test")
    init_state, _ = make_train_step(cfg)
    template = init_state(jax.random.key(0))
    assert restore_train_state(str(tmp_path / "nothing"), template) is None


def test_failure_containment_retries_then_sentinels(caplog):
    calls = []

    def flaky(prompts, settings=None, seed=0, keys=None, prefix_ids=None):
        calls.append(1)
        raise RuntimeError("device exploded")

    wrapped = with_failure_containment(flaky, retries=1)
    with caplog.at_level(logging.WARNING):
        out = wrapped(["a", "b"], seed=3)
    assert out == [None, None]
    assert len(calls) == 2  # initial + one retry


def test_failure_containment_passthrough():
    def ok(prompts, settings=None, seed=0, keys=None, prefix_ids=None):
        return [p.upper() for p in prompts]

    assert with_failure_containment(ok)(["hi"]) == ["HI"]


def test_profiling_contexts_noop(tmp_path):
    sink = {}
    with phase_timer("x", sink):
        pass
    assert "x" in sink
    with maybe_trace(None):  # no-op path
        pass
    with maybe_trace(str(tmp_path), "lbl"):  # real trace path
        import jax.numpy as jnp

        jnp.ones(4).sum().block_until_ready()


def test_native_parser_rejects_malformed(tmp_path):
    from fairness_llm_tpu import native

    if not native.available():
        pytest.skip("no C compiler")
    bad = tmp_path / "bad.dat"
    bad.write_text("1::2::3\ngarbage line here\n")
    with pytest.raises(ValueError):
        native.parse_ratings(str(bad))


def test_failed_decodes_not_resumed(tmp_path):
    """A contained decode failure must not be treated as completed work by
    --resume: checkpoints exclude error entries and the loader drops them."""
    from fairness_llm_tpu.pipeline import results as R

    R.save_checkpoint(
        {"ok": {"recommendations": ["x"], "raw_response": "1. x"},
         "bad": {"recommendations": [], "raw_response": "", "error": "decode_failed"}},
        str(tmp_path), "phase1", 2,
    )
    loaded = R.load_latest_checkpoint(str(tmp_path), "phase1")
    assert "ok" in loaded and "bad" not in loaded


def test_results_write_is_atomic(tmp_path, monkeypatch):
    """An interrupt mid-write must leave the previous file intact — resume
    depends on checkpoints never being truncated JSON."""
    import json

    from fairness_llm_tpu.pipeline import results as R

    path = tmp_path / "phase1" / "phase1_checkpoint_2.json"
    R.save_checkpoint({"a": {"recommendations": ["x"], "raw_response": "r"}},
                      str(tmp_path), "phase1", 2)
    before = path.read_text()

    def exploding_dump(*a, **k):
        raise KeyboardInterrupt  # simulated interrupt mid-serialization

    monkeypatch.setattr(json, "dump", exploding_dump)
    try:
        R.save_checkpoint({"b": {}}, str(tmp_path), "phase1", 2)
    except KeyboardInterrupt:
        pass
    assert path.read_text() == before  # old checkpoint untouched
    assert json.loads(before)  # and still valid JSON
    assert not list(path.parent.glob("*.tmp"))  # no tmp litter either


def test_resume_falls_back_past_corrupt_checkpoint(tmp_path):
    """A truncated newest checkpoint (older framework versions wrote
    non-atomically) must not make resume worse than starting over: fall back
    to the newest readable one."""
    from fairness_llm_tpu.pipeline import results as R

    R.save_checkpoint({"a": {"recommendations": ["x"], "raw_response": "r"}},
                      str(tmp_path), "phase1", 16)
    # newest checkpoint is truncated garbage
    bad = tmp_path / "phase1" / "phase1_checkpoint_32.json"
    bad.write_text('{"completed": 32, "recommendations": {"a": {')
    loaded = R.load_latest_checkpoint(str(tmp_path), "phase1")
    assert loaded == {"a": {"recommendations": ["x"], "raw_response": "r"}}
    # valid-JSON-but-wrong-shape corruption must also fall through
    for payload in ("[1, 2]", '{"recommendations": null}', '"just a string"'):
        bad.write_text(payload)
        loaded = R.load_latest_checkpoint(str(tmp_path), "phase1")
        assert loaded == {"a": {"recommendations": ["x"], "raw_response": "r"}}, payload
    # a newest checkpoint that parses but holds ONLY failed entries must also
    # fall back to older completed work, not return {}
    bad.write_text(
        '{"completed": 32, "recommendations": '
        '{"f": {"recommendations": [], "raw_response": "", "error": "decode_failed"}}}'
    )
    loaded = R.load_latest_checkpoint(str(tmp_path), "phase1")
    assert loaded == {"a": {"recommendations": ["x"], "raw_response": "r"}}


def test_trace_capture_and_summary(tmp_path):
    """maybe_trace writes an xplane capture and summarize_trace aggregates it
    without TensorBoard (SURVEY §5.1 — tracing with terminal analysis)."""
    import jax
    import jax.numpy as jnp

    from fairness_llm_tpu.utils.profiling import maybe_trace, summarize_trace

    with maybe_trace(str(tmp_path), "test-region"):
        x = jnp.ones((256, 256))
        jax.block_until_ready(jax.jit(lambda a: (a @ a).sum())(x))

    try:
        summaries = summarize_trace(str(tmp_path), top_k=5, device_filter="")
    except ImportError as e:
        pytest.skip(f"xplane protos unavailable: {e}")
    assert summaries, "no planes parsed from the capture"
    total_events = sum(s.num_events for s in summaries)
    assert total_events > 0
    text = summaries[0].format()
    assert "ms" in text and summaries[0].device in text

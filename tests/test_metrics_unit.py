"""Unit tests for metric kernels against scipy/numpy ground truth."""

import numpy as np
import pytest
import scipy.spatial.distance
import scipy.stats

from fairness_llm_tpu.metrics import (
    catalog_coverage,
    demographic_parity,
    equal_opportunity,
    exposure_ratio,
    f1_score,
    individual_fairness,
    js_distance,
    kl_divergence,
    ndcg,
    precision_at_k,
    recall_at_k,
    snsr_snsv,
)


def test_kl_matches_scipy():
    p = np.array([0.5, 0.3, 0.2])
    q = np.array([0.4, 0.4, 0.2])
    ours = float(kl_divergence(p, q))
    assert ours == pytest.approx(float(scipy.stats.entropy(p, q)), abs=1e-4)


def test_js_distance_matches_scipy_with_eps_semantics():
    # Two count vectors with disjoint-ish support, reference-style eps fill.
    p_counts = np.array([3.0, 1.0, 0.0, 2.0])
    q_counts = np.array([0.0, 2.0, 4.0, 0.0])
    eps = 1e-10
    p_probs = p_counts / p_counts.sum()
    q_probs = q_counts / q_counts.sum()
    p_ref = np.where(p_counts > 0, p_probs, eps)
    q_ref = np.where(q_counts > 0, q_probs, eps)
    expected = scipy.spatial.distance.jensenshannon(p_ref, q_ref)
    assert float(js_distance(p_counts, q_counts)) == pytest.approx(float(expected), abs=1e-5)


def test_js_distance_properties():
    """Kernel invariants the streaming fairness layer leans on
    (telemetry/fairness.py measures pair divergence with this kernel):
    identity -> 0, symmetry, bounded by sqrt(ln 2), scale invariance."""
    rng = np.random.default_rng(0)
    for _ in range(5):
        p = rng.integers(0, 6, size=8).astype(np.float64)
        q = rng.integers(0, 6, size=8).astype(np.float64)
        if p.sum() == 0 or q.sum() == 0:
            continue
        d_pq = float(js_distance(p, q))
        d_qp = float(js_distance(q, p))
        assert d_pq == pytest.approx(d_qp, abs=1e-6)  # symmetric
        assert -1e-7 <= d_pq <= np.sqrt(np.log(2)) + 1e-6  # bounded
        # Scale invariance: counts are normalized to distributions.
        assert float(js_distance(3 * p, q)) == pytest.approx(d_pq, abs=1e-5)
    identical = np.array([2.0, 0.0, 5.0, 1.0])
    assert float(js_distance(identical, identical)) == pytest.approx(
        0.0, abs=1e-6)
    disjoint_a = np.array([1.0, 1.0, 0.0, 0.0])
    disjoint_b = np.array([0.0, 0.0, 1.0, 1.0])
    # Fully disjoint support -> the JS distance maximum sqrt(ln 2)
    # (natural-log convention, the scipy default the reference uses).
    assert float(js_distance(disjoint_a, disjoint_b)) == pytest.approx(
        np.sqrt(np.log(2)), abs=1e-3)


def test_kl_divergence_properties():
    p = np.array([0.25, 0.25, 0.25, 0.25])
    assert float(kl_divergence(p, p)) == pytest.approx(0.0, abs=1e-7)
    q = np.array([0.7, 0.1, 0.1, 0.1])
    # KL is asymmetric and non-negative.
    kl_pq, kl_qp = float(kl_divergence(p, q)), float(kl_divergence(q, p))
    assert kl_pq >= 0 and kl_qp >= 0
    assert kl_pq != pytest.approx(kl_qp, abs=1e-4)
    assert kl_pq == pytest.approx(float(scipy.stats.entropy(p, q)), abs=1e-5)


def test_pairwise_js_matrix_matches_pairwise_calls():
    from fairness_llm_tpu.metrics.divergence import pairwise_js_matrix

    counts = np.array([
        [3.0, 1.0, 0.0, 2.0],
        [0.0, 2.0, 4.0, 0.0],
        [1.0, 1.0, 1.0, 1.0],
    ])
    mat = np.asarray(pairwise_js_matrix(counts))
    assert mat.shape == (3, 3)
    for i in range(3):
        assert mat[i, i] == pytest.approx(0.0, abs=1e-6)
        for j in range(3):
            assert mat[i, j] == pytest.approx(mat[j, i], abs=1e-6)
            assert mat[i, j] == pytest.approx(
                float(js_distance(counts[i], counts[j])), abs=1e-6)


def test_demographic_parity_identical_groups_is_one():
    recs = {"a": [["X", "Y"], ["Z"]], "b": [["X", "Y"], ["Z"]]}
    score, details = demographic_parity(recs)
    assert score == pytest.approx(1.0, abs=1e-6)
    assert details["avg_divergence"] == pytest.approx(0.0, abs=1e-6)


def test_demographic_parity_disjoint_groups_is_low():
    recs = {"a": [["X", "Y"]], "b": [["Z", "W"]]}
    score, _ = demographic_parity(recs)
    # Fully disjoint distributions -> JS distance ~ sqrt(ln 2) ~ 0.8326
    assert score == pytest.approx(1 - np.sqrt(np.log(2)), abs=1e-3)


def test_individual_fairness_jaccard():
    pairs = [("p1", "p2"), ("p1", "p3")]
    recs = {"p1": ["A", "B", "C"], "p2": ["A", "B", "C"], "p3": ["D"]}
    score, sims = individual_fairness(pairs, recs)
    assert sims[0] == pytest.approx(1.0)
    assert sims[1] == pytest.approx(0.0)
    assert score == pytest.approx(0.5)


def test_individual_fairness_empty_pair_is_one():
    score, sims = individual_fairness([("p1", "p2")], {"p1": [], "p2": []})
    assert sims == [1.0]


def test_equal_opportunity_variance_semantics():
    recs = {"g1": [["A", "B"]], "g2": [["C", "D"]]}
    score, by_group = equal_opportunity(recs, {"A", "C"})
    # both groups: 1 unique hit / 2 recommended = 0.5 -> var 0 -> EO 1
    assert by_group == {"g1": 0.5, "g2": 0.5}
    assert score == pytest.approx(1.0)
    score2, by_group2 = equal_opportunity(recs, {"A", "B"})
    rates = np.array([1.0, 0.0])
    assert score2 == pytest.approx(1 / (1 + rates.var()))


def test_exposure_ratio_matches_manual():
    ranked = ["m", "f", "m", "f"]
    ratio, means = exposure_ratio(ranked)
    exp = 1.0 / np.log2(np.arange(4) + 2)
    m_mean = np.mean([exp[0], exp[2]])
    f_mean = np.mean([exp[1], exp[3]])
    assert means["m"] == pytest.approx(m_mean, abs=1e-4)
    assert means["f"] == pytest.approx(f_mean, abs=1e-4)
    assert ratio == pytest.approx(f_mean / m_mean, abs=1e-4)


def test_exposure_single_group():
    ratio, means = exposure_ratio(["m", "m"])
    assert ratio == pytest.approx(1.0)


def test_ndcg_matches_manual():
    gt = {"item1": 5.0, "item2": 3.0, "item3": 1.0}
    val = ndcg(["item1", "item2", "item3"], gt)
    assert val == pytest.approx(1.0)
    val2 = ndcg(["item3", "item2", "item1"], gt)
    dcg = 1 / np.log2(2) + 3 / np.log2(3) + 5 / np.log2(4)
    idcg = 5 / np.log2(2) + 3 / np.log2(3) + 1 / np.log2(4)
    assert val2 == pytest.approx(dcg / idcg, abs=1e-5)


def test_precision_recall_f1_coverage():
    assert precision_at_k(["a", "b", "c"], {"a", "z"}, k=3) == pytest.approx(1 / 3)
    assert recall_at_k(["a", "b", "c"], {"a", "z"}, k=3) == pytest.approx(0.5)
    assert f1_score(0.5, 0.5) == pytest.approx(0.5)
    assert f1_score(0.0, 0.0) == 0.0
    assert catalog_coverage([["a"], ["b"], ["a"]], 4) == pytest.approx(50.0)


def test_snsr_snsv():
    neutral = ["A", "B", "C", "D"]
    groups = {"male": ["A", "B", "C", "D"], "female": ["A", "B", "X", "Y"]}
    snsr, snsv, sims = snsr_snsv(neutral, groups)
    assert sims["male"] == pytest.approx(1.0)
    assert sims["female"] == pytest.approx(2 / 6)
    assert snsr == pytest.approx(1.0 - 2 / 6)
    vals = np.array([1.0, 2 / 6])
    assert snsv == pytest.approx(vals.std(), abs=1e-6)

"""Decode-engine tests: determinism, left-pad batch invariance, sharded decode.

Replaces the verification the reference never had for its inference layer
(SURVEY.md §4: API calls are never mocked upstream). Runs on the virtual
8-device CPU mesh from conftest.py.
"""

import jax
import numpy as np
import pytest

from fairness_llm_tpu.config import MeshConfig, ModelSettings
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.models.tokenizer import ByteTokenizer
from fairness_llm_tpu.parallel import sharding as shd
from fairness_llm_tpu.runtime.engine import DecodeEngine


@pytest.fixture(scope="module")
def engine():
    cfg = get_model_config("tiny-test")
    return DecodeEngine(cfg, seed=0)


GREEDY = ModelSettings(temperature=0.0, max_tokens=12)


def test_greedy_is_deterministic(engine):
    out1 = engine.generate(["hello world"], GREEDY, seed=1)
    out2 = engine.generate(["hello world"], GREEDY, seed=2)  # seed irrelevant for greedy
    np.testing.assert_array_equal(out1.tokens, out2.tokens)


def test_left_pad_batch_invariance(engine):
    """A prompt decoded alone must equal the same prompt decoded in a mixed-length
    batch — the core correctness property of left-padded uniform-index caching."""
    solo = engine.generate(["the quick brown fox"], GREEDY, seed=0)
    batch = engine.generate(
        ["the quick brown fox", "hi", "a much longer prompt that shifts padding"],
        GREEDY,
        seed=0,
    )
    np.testing.assert_array_equal(solo.tokens[0], batch.tokens[0])


def test_eos_stops_row(engine):
    """Once EOS is sampled, the row emits pads forever after."""
    out = engine.generate(["abc", "xyz"], GREEDY, seed=0)
    for row in out.tokens:
        seen_eos = False
        for t in row:
            if seen_eos:
                assert t == engine.tokenizer.pad_id
            if t == engine.tokenizer.eos_id:
                seen_eos = True


def test_sampled_decode_seed_reproducible(engine):
    settings = ModelSettings(temperature=0.8, max_tokens=12, top_k=16, top_p=0.9)
    out1 = engine.generate(["hello"], settings, seed=7)
    out2 = engine.generate(["hello"], settings, seed=7)
    out3 = engine.generate(["hello"], settings, seed=8)
    np.testing.assert_array_equal(out1.tokens, out2.tokens)
    # different seed should (overwhelmingly) differ for an untrained model
    assert not np.array_equal(out1.tokens, out3.tokens)


def test_row_seeds_make_sampling_composition_independent(engine):
    """With row_seeds, a prompt's sampled tokens must not depend on which other
    prompts share the batch — the invariant resume/re-chunking relies on."""
    settings = ModelSettings(temperature=0.9, max_tokens=10)
    solo = engine.generate(["the quick brown fox"], settings, row_seeds=[123])
    mixed = engine.generate(
        ["padding prompt one", "the quick brown fox", "another row here"],
        settings,
        row_seeds=[7, 123, 9],
    )
    np.testing.assert_array_equal(solo.tokens[0], mixed.tokens[1])


def test_shared_prefix_decode_matches_plain(engine):
    """Prefix-cached decode must be EXACTLY the same computation as plain
    decode (same keys/values, same masks) — greedy tokens identical."""
    g = ModelSettings(temperature=0.0, max_tokens=16)
    common = "shared instruction block " * 8
    prompts = [common + f"user {i} tail" for i in range(5)]
    plain = engine.generate(prompts, g, share_prefix=False)
    shared = engine.generate(prompts, g, share_prefix=True)
    np.testing.assert_array_equal(plain.tokens, shared.tokens)


def test_shared_prefix_auto_threshold(engine):
    """Auto mode only engages for long common prefixes; short ones decode
    identically through the plain path."""
    g = ModelSettings(temperature=0.0, max_tokens=8)
    prompts = ["ab one", "ab two", "ab three"]  # tiny common prefix
    auto = engine.generate(prompts, g)  # share_prefix=None -> auto
    plain = engine.generate(prompts, g, share_prefix=False)
    np.testing.assert_array_equal(auto.tokens, plain.tokens)


def test_engine_sweep_resume_reproducible_with_prefix(engine, tmp_path):
    """decode_sweep on a REAL engine backend with prefix caching: a resumed
    run must reproduce the uninterrupted run exactly — the sweep-wide
    prefix_ids keep the attention split identical across chunk compositions."""
    from fairness_llm_tpu.config import Config
    from fairness_llm_tpu.pipeline import results as R
    from fairness_llm_tpu.pipeline.backends import EngineBackend
    from fairness_llm_tpu.pipeline.phase1 import decode_sweep

    backend = EngineBackend(engine, name="tiny-test")
    common = "identical instruction preamble repeated for every row " * 4
    prompts = [common + f"row {i}" for i in range(10)]
    keys = [f"k{i}" for i in range(10)]
    settings = ModelSettings(temperature=0.9, max_tokens=10)  # sampled, not greedy
    cfg_a = Config(results_dir=str(tmp_path / "a"), decode_batch_size=4,
                   checkpoint_every=4)
    full = decode_sweep(backend, prompts, keys, cfg_a, "phase1", settings=settings)

    cfg_b = Config(results_dir=str(tmp_path / "b"), decode_batch_size=4,
                   checkpoint_every=4)
    partial = {k: full[k] for k in keys[:3]}  # interrupt mid-first-chunk
    R.save_checkpoint(partial, cfg_b.results_dir, "phase1", 3)
    done = R.load_latest_checkpoint(cfg_b.results_dir, "phase1")
    resumed = decode_sweep(backend, prompts, keys, cfg_b, "phase1",
                           done=done, settings=settings)
    for k in keys:
        assert resumed[k]["raw_response"] == full[k]["raw_response"], k


def test_prefix_kv_cache_bounded(engine):
    """The per-sweep prefix-KV cache must not grow without bound."""
    g = ModelSettings(temperature=0.0, max_tokens=4)
    for i in range(6):
        common = f"sweep {i} preamble " * 12
        engine.generate([common + "a", common + "b"], g, share_prefix=True)
    kv_entries = [k for k in engine._compiled if k[0] == "prefix_kv"]
    assert 1 <= len(kv_entries) <= 4


def test_sharded_decode_matches_unsharded(engine, eight_device_mesh):
    """dp=2 x tp=4 sharded decode reproduces single-device greedy output."""
    cfg = get_model_config("tiny-test")
    sharded = DecodeEngine(cfg, params=engine.params, mesh=eight_device_mesh)
    prompts = ["the quick brown fox", "hi there", "fairness", "movies"]
    a = engine.generate(prompts, GREEDY, seed=0)
    b = sharded.generate(prompts, GREEDY, seed=0)
    np.testing.assert_array_equal(a.tokens, b.tokens)


def test_param_shardings_cover_tree(eight_device_mesh):
    cfg = get_model_config("tiny-test")
    shardings = shd.param_shardings(cfg, eight_device_mesh)
    leaves = jax.tree.leaves(shardings)
    assert leaves and all(hasattr(s, "spec") for s in leaves)
    # q_proj kernel must actually be tp-sharded
    q = shardings["layer_0"]["attn"]["q_proj"]["kernel"].spec
    assert "tp" in str(q)


def test_tokenizer_roundtrip():
    tok = ByteTokenizer(512)
    text = "Recommend 10 movies, please — numbered!"
    assert tok.decode(tok.encode(text)) == text
    tb = tok.encode_batch(["short", "a longer prompt here"])
    assert tb.tokens.shape[0] == 2
    # left padding: first row starts with pads, real tokens at the right edge
    assert tb.tokens[0, 0] == tok.pad_id and tb.valid[0, -1]
    assert tb.lengths[1] > tb.lengths[0]

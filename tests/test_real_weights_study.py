"""Golden regression for the REAL-WEIGHTS study path (VERDICT r2 item 1).

``results/real_weights/`` is a committed record of the full ``--all`` study
run against the transformers-built fine-tuned checkpoints committed under
``checkpoints/`` — provenance ``backend_for -> load_checkpoint -> HFTokenizer
-> EngineBackend`` end to end, the exact chain a real Llama checkpoint takes
(reference: inference was always a real model,
``phase1_bias_detection.py:180-188``). Swapping in actual pretrained weights
is a config change (``--weights-dir``), not new code.

These tests (a) pin the committed record's provenance and non-vacuousness,
and (b) RE-RUN phase 1 and the model-conditional conformal phase 3 through
the same path on CPU, asserting byte/metric equality with the record — a
regression anywhere in weights loading, HF tokenization, engine decode,
parsing, metrics, scoring-based calibration, or FACTER filtering fails here.

Record regeneration (CPU-forced; see checkpoints/*/PROVENANCE.json):
    python tools/build_tiny_study_checkpoints.py   # only if checkpoints change
    python -c "import jax; jax.config.update('jax_platforms','cpu'); \
      import sys; from fairness_llm_tpu.cli.main import main; sys.exit(main( \
      ['--all','--model','tiny-llama-study','--models','tiny-llama-study', \
       'tiny-gpt2-study','--weights-dir','checkpoints','--calibration', \
       'model-conditional','--results-dir','results/real_weights', \
       '--num-items','12','--num-comparisons','8','--num-queries','2', \
       '--seed','42'])"
    # plus --phase 3 --variant smart / aggressive (simulated calibration)
"""

import json
import os

import pytest

transformers = pytest.importorskip("transformers")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CKPTS = os.path.join(REPO, "checkpoints")
RECORD = os.path.join(REPO, "results", "real_weights")

pytestmark = pytest.mark.skipif(
    not (os.path.isdir(CKPTS) and os.path.isdir(RECORD)),
    reason="committed checkpoints/record not present",
)


def _load(phase, name):
    with open(os.path.join(RECORD, phase, name)) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def config():
    import dataclasses

    from fairness_llm_tpu.config import default_config
    from fairness_llm_tpu.data import load_movielens

    cfg = dataclasses.replace(
        default_config(), weights_dir=CKPTS, random_seed=42,
        results_dir=None,  # set per-test via tmp_path
    )
    # Records pin their corpus; compare only when the current loader
    # reproduces it (e.g. a real ratings.dat appearing under data/ml-1m
    # changes provenance -> regenerate records, don't chase numeric drift).
    want = _load("phase1", "phase1_results.json")["metadata"].get("corpus")
    have = load_movielens(cfg.data_dir, seed=cfg.random_seed).provenance()
    if want != have:
        pytest.skip(
            f"corpus provenance changed (record {want} vs current {have}) — "
            "regenerate results/real_weights (module docstring)"
        )
    return cfg


def test_committed_record_provenance_and_nonvacuous():
    """The record must be from the real engine path and carry non-trivial
    metrics (a vacuous all-1.0 record would prove parsing never worked)."""
    p1 = _load("phase1", "phase1_results.json")
    assert p1["metadata"]["model"] == "tiny-llama-study"
    m = p1["metrics"]
    assert 0.05 < m["demographic_parity_gender"]["score"] < 0.95
    assert 0.0 < m["individual_fairness"]["score"] < 0.9
    assert m["snsr_snsv"]["snsr"] > 0.01
    # raw decode text is present and parses to real catalog titles
    some = next(iter(p1["recommendations"].values()))
    assert some["raw_response"] and some["recommendations"]

    p2 = _load("phase2", "phase2_results.json")
    assert set(p2["model_results"]) == {"tiny-llama-study", "tiny-gpt2-study"}

    p3 = _load("phase3", "phase3_results.json")
    assert p3["metadata"]["calibration"] == "model-conditional"
    # the cross-variant spread: aggressive meets the 50% target on this model
    p3a = _load("phase3", "phase3_aggressive_results.json")
    assert p3a["bias_reduction"]["bias_reduction_rate"] > 50.0


def test_checkpoint_provenance_files():
    for name in ("tiny-llama-study", "tiny-gpt2-study"):
        with open(os.path.join(CKPTS, name, "PROVENANCE.json")) as f:
            prov = json.load(f)
        assert prov["builder"] == "tools/build_tiny_study_checkpoints.py"
        assert os.path.exists(os.path.join(CKPTS, name, "model.safetensors"))
        assert os.path.exists(os.path.join(CKPTS, name, "tokenizer_config.json"))


def test_phase1_rerun_matches_committed_record(config, tmp_path):
    """Full phase-1 re-run through backend_for's REAL path must reproduce the
    committed record: byte-identical decodes, equal metrics."""
    import dataclasses

    from fairness_llm_tpu.data import load_movielens
    from fairness_llm_tpu.models.tokenizer import HFTokenizer
    from fairness_llm_tpu.pipeline.backends import EngineBackend, backend_for
    from fairness_llm_tpu.pipeline.phase1 import run_phase1

    config = dataclasses.replace(config, results_dir=str(tmp_path))
    data = load_movielens(config.data_dir, seed=config.random_seed)
    backend = backend_for("tiny-llama-study", config, catalog=data.titles)
    # the provenance chain itself
    assert isinstance(backend, EngineBackend)
    assert isinstance(backend.engine.tokenizer, HFTokenizer)

    got = run_phase1(config, "tiny-llama-study", save=False, backend=backend)
    want = _load("phase1", "phase1_results.json")

    for pid, rec in want["recommendations"].items():
        assert got["recommendations"][pid]["raw_response"] == rec["raw_response"], pid
    gm, wm = got["metrics"], want["metrics"]
    for key in ("demographic_parity_gender", "demographic_parity_age",
                "individual_fairness", "equal_opportunity",
                "equal_opportunity_age"):
        assert gm[key]["score"] == pytest.approx(wm[key]["score"], abs=1e-6), key
    assert gm["snsr_snsv"]["snsr"] == pytest.approx(wm["snsr_snsv"]["snsr"], abs=1e-6)


def test_phase2_rerun_matches_committed_record(config, tmp_path):
    """Cross-model phase 2 (listwise + pairwise + likelihood-scored) through
    the real-weights engines must reproduce the committed per-model scores."""
    import dataclasses

    from fairness_llm_tpu.pipeline.phase2 import run_phase2

    config = dataclasses.replace(config, results_dir=str(tmp_path))
    got = run_phase2(
        config, models=["tiny-llama-study", "tiny-gpt2-study"],
        num_items=12, num_comparisons=8, num_queries=2, save=False,
    )
    want = _load("phase2", "phase2_results.json")
    for name, wm in want["model_results"].items():
        gm = got["model_results"][name]
        for method in ("listwise", "pairwise", "scored"):
            assert gm[method]["exposure_ratio"] == pytest.approx(
                wm[method]["exposure_ratio"], abs=1e-6
            ), (name, method)
            assert gm[method]["ndcg_per_group"] == pytest.approx(
                wm[method]["ndcg_per_group"], abs=1e-6
            ), (name, method)
        assert gm["parse_failures"] == wm["parse_failures"]


def test_phase3_model_conditional_rerun_matches_record(config, tmp_path):
    """The model-conditional conformal path (scoring -> confidence mapping ->
    thresholds -> filter -> measurement) end to end on real weights must
    reproduce the committed numbers (closes VERDICT r2 weak #6)."""
    import dataclasses

    from fairness_llm_tpu.pipeline.phase3 import run_phase3

    config = dataclasses.replace(config, results_dir=str(tmp_path))
    got = run_phase3(
        config, model_name="tiny-llama-study", variant="conformal",
        calibration="model-conditional", save=False,
    )
    want = _load("phase3", "phase3_results.json")
    for key in ("original_fairness", "mitigated_fairness", "bias_reduction_rate"):
        assert got["bias_reduction"][key] == pytest.approx(
            want["bias_reduction"][key], abs=1e-6
        ), key
    assert got["blended_fairness"] == pytest.approx(
        want["blended_fairness"], abs=1e-6
    )

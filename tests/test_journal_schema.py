"""Journal schema versioning regression tests (PR 20, satellite 2).

The serving journal is the one artifact that crosses process — and now
software-version — boundaries: a v+1 writer's journal may be read by a
v reader after a rollback. Every record written today carries
``schema_version``; the reader's contract is a three-way split:

* **no field** — legacy v1 record, parsed with v1 defaulting (every
  pre-versioning journal keeps resuming forever);
* **known version** (<= ``JOURNAL_SCHEMA_VERSION``) — parsed via the
  migration table;
* **future version** — refused with :class:`JournalSchemaError`, a
  *named* error, instead of a silent misparse that would resume requests
  with wrong deadlines/pins. Rollback keeps the newer journal intact; the
  operator upgrades before resuming.
"""

import json

import pytest

from fairness_llm_tpu.config import ModelSettings
from fairness_llm_tpu.resilience import ServingJournal, resume_serving
from fairness_llm_tpu.resilience.drain import (
    JOURNAL_SCHEMA_VERSION,
    JournalSchemaError,
)
from fairness_llm_tpu.serving import Request
from fairness_llm_tpu.telemetry import use_registry

GREEDY = ModelSettings(temperature=0.0, max_tokens=8)


def _req(i):
    return Request(prompt=f"prompt {i}", id=f"s{i}", settings=GREEDY,
                   row_seed=100 + i)


def _strip_schema_fields(path):
    """Rewrite a journal as a legacy (pre-versioning) writer would have."""
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            rec = json.loads(line)
            rec.pop("schema_version", None)
            rec.pop("version", None)
            out.append(rec)
    with open(path, "w", encoding="utf-8") as f:
        for rec in out:
            f.write(json.dumps(rec, sort_keys=True) + "\n")


def _bump_schema(path, to):
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("kind") == "submitted":
                rec["schema_version"] = to
            out.append(rec)
    with open(path, "w", encoding="utf-8") as f:
        for rec in out:
            f.write(json.dumps(rec, sort_keys=True) + "\n")


def test_current_writer_stamps_schema_version(tmp_path):
    j = ServingJournal(str(tmp_path))
    j.record_submitted(_req(0))
    j.record_submitted(_req(1), version="v3")
    (r0, r1) = j.unfinished()
    assert r0["schema_version"] == JOURNAL_SCHEMA_VERSION
    assert "version" not in r0  # intake record: no pin yet
    assert r1["schema_version"] == JOURNAL_SCHEMA_VERSION
    assert r1["version"] == "v3"  # the rollout pin survives the ledger


def test_versionless_journal_parses_as_v1(tmp_path):
    # A journal written before schema versioning existed must keep
    # resuming: records without the field default to version 1.
    j = ServingJournal(str(tmp_path))
    for i in range(3):
        j.record_submitted(_req(i))
    j.record_terminal("s1", "completed")
    j.close()
    _strip_schema_fields(j.path)

    j2 = ServingJournal(str(tmp_path))
    specs = j2.unfinished()
    assert [r["id"] for r in specs] == ["s0", "s2"]
    assert all("schema_version" not in r for r in specs)
    reqs = j2.to_requests(specs)
    assert [r.id for r in reqs] == ["s0", "s2"]
    assert reqs[0].settings == GREEDY


def test_future_schema_version_refused_by_name(tmp_path):
    j = ServingJournal(str(tmp_path))
    j.record_submitted(_req(0))
    j.close()
    _bump_schema(j.path, JOURNAL_SCHEMA_VERSION + 1)

    j2 = ServingJournal(str(tmp_path))
    with pytest.raises(JournalSchemaError) as exc:
        j2.unfinished()
    msg = str(exc.value)
    assert str(JOURNAL_SCHEMA_VERSION + 1) in msg  # names the version seen
    assert str(JOURNAL_SCHEMA_VERSION) in msg      # and what we understand
    assert "s0" in msg                             # and the offending record


def test_garbled_schema_version_refused_not_misparsed(tmp_path):
    # A non-int schema_version is a corrupt or hostile record, not a
    # legacy one — refuse, don't default.
    j = ServingJournal(str(tmp_path))
    j.record_submitted(_req(0))
    j.close()
    _bump_schema(j.path, "two")

    with pytest.raises(JournalSchemaError):
        ServingJournal(str(tmp_path)).unfinished()


def test_resume_serving_refuses_future_journal(tmp_path):
    # The refusal must surface through the real resume entry point — the
    # process-boundary API a post-rollback operator actually calls.
    with use_registry():
        j = ServingJournal(str(tmp_path))
        j.record_submitted(_req(0))
        j.close()
        _bump_schema(j.path, JOURNAL_SCHEMA_VERSION + 5)
        with pytest.raises(JournalSchemaError):
            resume_serving(None, ServingJournal(str(tmp_path)))


def test_rotation_preserves_schema_version(tmp_path):
    # Compaction rewrites records verbatim: the stamped version (and the
    # rollout pin) must ride through a rotate, or an old journal would be
    # silently "upgraded" by housekeeping.
    with use_registry():
        j = ServingJournal(str(tmp_path), rotate_every=1)
        j.record_submitted(_req(0), version="v2")
        j.record_submitted(_req(1))
        j.record_terminal("s1", "completed")  # triggers compaction
        (rec,) = j.records()
        assert rec["id"] == "s0"
        assert rec["schema_version"] == JOURNAL_SCHEMA_VERSION
        assert rec["version"] == "v2"

"""Mistral-7B / Gemma-7B readiness: compile-time proof of the BASELINE tp=8
configs (VERDICT r2 item 4 — after this, every ``BASELINE.json`` config has a
compile-time guard: llama3-8b/70b in ``test_70b_readiness``, qwen2 in
``test_qwen2_readiness``, mistral + gemma here).

Same method as the 70B proof: AOT-lower and backend-compile the REAL
prefill+decode program at tp=8 over the virtual 8-device mesh with abstract
(``ShapeDtypeStruct``) parameters. Gemma is the interesting one — tied
embeddings mean the vocab-sharded [V, D] embedding table is ALSO the lm_head
operand (``models/transformer.py`` tie path), a layout nothing else compiles
at tp=8. Mistral adds the sliding-window mask inside the compiled cache path.

Reference has no local models (SURVEY.md §0); these guard BASELINE.json's
mistral-7b / gemma-7b tp=8 target configs.
"""

import types

import flax.linen as nn
import jax
import jax.numpy as jnp
import pytest

from fairness_llm_tpu.config import MeshConfig
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.models.transformer import Transformer, init_cache
from fairness_llm_tpu.parallel import sharding as shd

V5E_HBM_BYTES = 16 * 1024**3

FAMILIES = ["mistral-7b", "gemma-7b"]


def _rules_for_shape(cfg, shape):
    return shd.make_axis_rules(cfg, types.SimpleNamespace(shape=shape))


def test_mistral_rules_tp8_shard_everything():
    cfg = get_model_config("mistral-7b")
    rules = dict(_rules_for_shape(cfg, {"dp": 1, "tp": 8, "sp": 1}))
    # 32 q heads -> 4/chip; 8 kv heads -> 1/chip; ff 14336 and vocab 32000 divide.
    assert rules["q_heads"] == "tp"
    assert rules["kv_heads"] == "tp"
    assert rules["ff"] == "tp"
    assert rules["vocab"] == "tp"


def test_gemma_rules_tp8_shard_everything():
    cfg = get_model_config("gemma-7b")
    rules = dict(_rules_for_shape(cfg, {"dp": 1, "tp": 8, "sp": 1}))
    # 16 q = 16 kv heads (MHA) -> 2/chip; ff 24576 and vocab 256000 divide.
    assert rules["q_heads"] == "tp"
    assert rules["kv_heads"] == "tp"
    assert rules["ff"] == "tp"
    assert rules["vocab"] == "tp"


def test_gemma_embedding_is_the_lm_head():
    """Tied embeddings: the abstract param tree must hold ONE [V, D] table
    (no separate lm_head kernel) whose vocab axis maps to tp — the layout the
    compile proof below exercises end to end."""
    cfg = get_model_config("gemma-7b")
    assert cfg.tie_embeddings
    specs, shapes = shd._abstract_params(cfg)
    flat = {"/".join(p): s for p, s in _flatten(shapes)}
    embed_keys = [k for k in flat if "embed" in k.lower()]
    head_keys = [k for k in flat if "head" in k.lower() and "kernel" in k.lower()]
    assert embed_keys and not head_keys
    (ek,) = embed_keys
    assert flat[ek].shape == (cfg.vocab_size, cfg.d_model)
    spec_flat = {"/".join(p): s for p, s in _flatten(
        specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))}
    rules = _rules_for_shape(cfg, {"dp": 1, "tp": 8, "sp": 1})
    resolved = shd._resolve_spec(spec_flat[ek], rules)
    assert "tp" in tuple(resolved)  # vocab axis sharded over tp


def _flatten(tree, is_leaf=None):
    return [
        (tuple(str(getattr(k, "key", k)) for k in path), leaf)
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree, is_leaf=is_leaf)[0]
    ]


@pytest.fixture(scope="module", params=FAMILIES)
def compiled_7b(request):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg = get_model_config(request.param)
    mesh = shd.make_mesh(MeshConfig(dp=1, tp=8, sp=1))
    rules = shd.make_axis_rules(cfg, mesh)
    shardings = shd.param_shardings(cfg, mesh, rules)

    model = Transformer(cfg)
    abstract = jax.eval_shape(
        model.init, jax.random.key(0),
        jnp.zeros((1, 8), jnp.int32), jnp.zeros((1, 8), jnp.int32),
    )
    abstract = nn.meta.unbox(abstract["params"])
    aparams = jax.tree.map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, jnp.bfloat16, sharding=s),
        abstract, shardings,
    )

    B, S, NEW = 8, 128, 2

    def prefill_and_decode(params, tokens, positions, valid):
        cache = init_cache(cfg, B, S + NEW)
        logits, cache = model.apply(
            {"params": params}, tokens, positions, valid, cache,
            left_padded=True, last_only=True,
        )

        def step(_, carry):
            logits, cache = carry
            tok = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            pos = cache.lengths[:, None]
            logits, cache = model.apply(
                {"params": params}, tok[:, None], pos,
                jnp.ones((B, 1), jnp.bool_), cache,
            )
            return logits, cache

        logits, cache = jax.lax.fori_loop(0, NEW, step, (logits, cache))
        return logits

    bs = shd.batch_sharding(mesh)
    atoks = jax.ShapeDtypeStruct((B, S), jnp.int32, sharding=bs)
    apos = jax.ShapeDtypeStruct((B, S), jnp.int32, sharding=bs)
    avalid = jax.ShapeDtypeStruct((B, S), jnp.bool_, sharding=bs)
    with mesh, nn.logical_axis_rules(rules):
        compiled = jax.jit(prefill_and_decode).lower(
            aparams, atoks, apos, avalid
        ).compile()
    return cfg, mesh, rules, compiled


@pytest.mark.slow  # ~30 s per family: AOT backend-compiles dominate tier-1
def test_7b_aot_compiles_tp8(compiled_7b):
    # Existence of `compiled` IS the proof — GSPMD accepted every rule
    # (including gemma's tied vocab-sharded embedding-as-lm_head and
    # mistral's sliding-window mask in the cached path) at tp=8.
    cfg, mesh, rules, compiled = compiled_7b
    assert compiled.memory_analysis() is not None


@pytest.mark.slow  # shares compiled_7b — must move with the test above
def test_7b_param_bytes_match_compiled_analysis(compiled_7b):
    cfg, mesh, rules, compiled = compiled_7b
    analytic = shd.per_device_param_bytes(cfg, mesh, rules)
    measured = compiled.memory_analysis().argument_size_in_bytes
    assert abs(measured - analytic) / analytic < 0.02


@pytest.mark.parametrize("name", FAMILIES)
def test_7b_bf16_tp8_fits_v5e_hbm(name):
    """Both 7B-class BASELINE configs fit a v5e chip at tp=8 in bf16 with
    headroom for cache + activations (unlike 70B, which test_70b_readiness
    proves does NOT fit)."""
    cfg = get_model_config(name)
    mesh = types.SimpleNamespace(shape={"dp": 1, "tp": 8, "sp": 1})
    per = shd.per_device_param_bytes(cfg, mesh, _rules_for_shape(cfg, mesh.shape))
    assert per < 0.25 * V5E_HBM_BYTES

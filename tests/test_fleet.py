"""Replica-fleet tests (serving/fleet.py + serving/router.py).

The correctness contract (ISSUE 6) is ZERO-LOSS FAILOVER with parity: kill
one replica of a fleet mid-sweep and every request still reaches a terminal
Result, migrated survivors decode token-for-token what the single static
engine would, the healthy replica keeps serving throughout, and the killed
replica rejoins only through a canary warm-up probe. Around that: router
health scoring, fence policy, per-replica telemetry labels, and the
fleet-level gauges the --require-fleet CI gate reads.
"""

import numpy as np
import pytest

from fairness_llm_tpu.config import (
    FleetConfig,
    IntegrityConfig,
    ModelSettings,
    ResilienceConfig,
    ServingConfig,
)
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.runtime.engine import DecodeEngine
from fairness_llm_tpu.serving import HealthRouter, ReplicaSet, Request
from fairness_llm_tpu.serving.backend import ServingBackend
from fairness_llm_tpu.telemetry import use_registry
from fairness_llm_tpu.utils.failures import ScriptedFaultInjector


def greedy(m: int) -> ModelSettings:
    return ModelSettings(temperature=0.0, max_tokens=m)


SCFG = ServingConfig(
    enabled=True, num_slots=2, queue_capacity=64,
    max_prompt_len=192, max_new_tokens=32, decode_chunk=4,
)
# Tight knobs so fence->rejoin cycles complete in test time: one fault trips
# a breaker, cooldowns are milliseconds, and the rejoin canary decodes 8
# tokens through a 2-slot pool.
RES = ResilienceConfig(enabled=True, breaker_threshold=1,
                       breaker_cooldown_s=0.01)
FLEET2 = FleetConfig(replicas=2, fence_cooldown_s=0.02)
INTEG = IntegrityConfig(canary_max_tokens=8)

PROMPTS = [
    "the quick brown fox",
    "hello there friend",
    "abc abc abc abc",
    "one two three one two",
    "recommend ten films please",
    "name five good books",
    "zz zz zz",
    "a longer prompt that shifts padding and lands in a bucket",
]


@pytest.fixture(scope="module")
def engine():
    return DecodeEngine(get_model_config("tiny-test"), seed=0)


@pytest.fixture(scope="module")
def baseline(engine):
    """Single-engine greedy reference rows — what every fleet survivor
    must reproduce token-for-token."""
    out = {}
    for i, p in enumerate(PROMPTS):
        out[f"q{i}"] = np.asarray(engine.generate([p], greedy(8)).tokens[0])
    return out


def _fleet(engine, fault_injector=None, fleet=FLEET2, resilience=RES,
           settings=None, journal=None):
    return ReplicaSet(
        engine, SCFG, settings=settings or greedy(8), fleet=fleet,
        resilience=resilience, journal=journal,
        fault_injector=fault_injector, integrity=INTEG,
    )


def _reqs(settings=None, n=None):
    s = settings or greedy(8)
    return [Request(prompt=p, id=f"q{i}", settings=s)
            for i, p in enumerate(PROMPTS[:n] if n else PROMPTS)]


def _assert_parity(results, baseline, engine):
    for r in results:
        assert r.ok, (r.id, r.finish_reason, r.error)
        got, ref = np.asarray(r.tokens), baseline[r.id]
        n = len(got)
        assert n > 0 and np.array_equal(got, ref[:n]) \
            and np.all(ref[n:] == engine.tokenizer.pad_id), \
            (r.id, list(got), list(ref))


def _counter(reg, name, **labels):
    m = reg.peek(name, **labels)
    return 0 if m is None else m.value


# -- router unit tests --------------------------------------------------------


class _StubQueue:
    def __init__(self, depth=0, full=False, closed=False):
        self.depth, self.full, self.closed = depth, full, closed

    def __len__(self):
        return self.depth


class _StubPool:
    def __init__(self, occupancy=0):
        self.occupancy = occupancy


class _StubSched:
    def __init__(self, occupancy=0, depth=0, full=False, breakers=None):
        self.pool = _StubPool(occupancy)
        self.queue = _StubQueue(depth, full=full)
        self._pending = []
        self.breakers = breakers
        self.watchdog = None
        self.num_slots = 4


class _StubReplica:
    def __init__(self, name, fenced=False, **kw):
        self.name = name
        self.fenced = fenced
        self.sched = _StubSched(**kw)


def test_router_prefers_idle_over_loaded():
    with use_registry():
        router = HealthRouter(FleetConfig(replicas=2))
        idle = _StubReplica("r0")
        busy = _StubReplica("r1", occupancy=4, depth=8)
        assert router.pick([busy, idle]) is idle


def test_router_skips_fenced_and_full():
    with use_registry():
        router = HealthRouter(FleetConfig(replicas=3))
        fenced = _StubReplica("r0", fenced=True)
        full = _StubReplica("r1", full=True)
        ok = _StubReplica("r2", occupancy=3, depth=5)
        assert router.pick([fenced, full, ok]) is ok
        assert router.pick([fenced, full]) is None


def test_router_discounts_open_breakers():
    from fairness_llm_tpu.resilience import BreakerBoard

    with use_registry():
        router = HealthRouter(FleetConfig(replicas=2))
        sick = _StubReplica("r0")
        sick.sched.breakers = BreakerBoard(failure_threshold=1,
                                           cooldown_s=60.0)
        sick.sched.breakers.trip("decode")
        healthy = _StubReplica("r1")
        assert router.health_score(sick) < router.health_score(healthy)
        assert router.pick([sick, healthy]) is healthy
        # An open breaker discounts but does not zero: alone, the sick
        # replica still takes traffic rather than stranding the queue.
        assert router.pick([sick]) is sick


def test_router_fence_policy_thresholds():
    from fairness_llm_tpu.resilience import BreakerBoard

    with use_registry():
        router = HealthRouter(FleetConfig(replicas=2, fence_ladder_level=2,
                                          fence_open_breakers=2))
        rep = _StubReplica("r0")
        assert router.should_fence(rep) is None
        rep.sched.breakers = BreakerBoard(failure_threshold=1,
                                          cooldown_s=60.0)
        rep.sched.breakers.trip("decode")
        assert router.should_fence(rep) is None  # one rung, one breaker
        rep.sched.breakers.trip("prefill")
        # Two open breakers AND ladder level 2 — either threshold fences.
        assert router.should_fence(rep) in ("degraded", "breakers")
        rep.fenced = True
        assert router.should_fence(rep) is None  # already fenced


# -- fault-free fleet ---------------------------------------------------------


def test_fleet_greedy_parity_and_stats(engine, baseline):
    with use_registry() as reg:
        fleet = _fleet(engine)
        results = fleet.serve(_reqs())
        assert [r.id for r in results] == [f"q{i}" for i in range(len(PROMPTS))]
        _assert_parity(results, baseline, engine)
        stats = fleet.last_stats
        assert stats.completed == len(PROMPTS)
        assert stats.num_slots == 2 * SCFG.num_slots
        # Both replicas took a share (the router spreads by load).
        for rep in fleet.replicas:
            assert rep.stats.completed == 0  # reset after the drain
        per_replica = [
            _counter(reg, "serving_completed_total", component="serving",
                     replica=rep.name)
            for rep in fleet.replicas
        ]
        assert sum(per_replica) == len(PROMPTS)
        assert all(v > 0 for v in per_replica)
        assert _counter(reg, "fleet_fenced_total", component="fleet",
                        replica="r0", reason="degraded") == 0
        assert reg.read_value("fleet_healthy_replicas",
                              component="fleet") == 2
        # The admission-queue high-water-mark gauge exists per replica.
        for rep in fleet.replicas:
            assert reg.peek("queue_depth_hwm", component="serving",
                            replica=rep.name) is not None


def test_fleet_single_replica_degenerate(engine, baseline):
    """replicas=1 is a working (if pointless) fleet — the router has one
    choice and every single-engine behavior carries over."""
    with use_registry():
        fleet = _fleet(engine, fleet=FleetConfig(replicas=1))
        results = fleet.serve(_reqs(n=4))
        _assert_parity(results, baseline, engine)


def test_fleet_serve_reusable_and_duplicate_ids_rejected(engine, baseline):
    with use_registry():
        fleet = _fleet(engine)
        _assert_parity(fleet.serve(_reqs(n=3)), baseline, engine)
        _assert_parity(fleet.serve(_reqs(n=3)), baseline, engine)
        with pytest.raises(ValueError, match="duplicate"):
            fleet.serve([Request(prompt="a", id="dup", settings=greedy(8)),
                         Request(prompt="b", id="dup", settings=greedy(8))])


# -- failover -----------------------------------------------------------------


def test_replica_crash_zero_loss_parity_and_rejoin(engine, baseline):
    """The acceptance drill in miniature: kill r1 after a few health polls
    — zero lost requests, migrated survivors token-identical, r0 never
    fenced, r1 rejoins through the canary, gauges back to whole."""
    with use_registry() as reg:
        inj = ScriptedFaultInjector(replica_crashes={"r1": 3})
        fleet = _fleet(engine, fault_injector=inj)
        results = fleet.serve(_reqs())
        assert inj.replica_faults_fired == [("r1", "replica_crash")]
        _assert_parity(results, baseline, engine)  # zero loss, zero corrupt
        r0, r1 = fleet.replicas
        assert r0.fences == 0 and r1.fences == 1
        assert r1.fence_reason in (None, "replica_crash")  # None once rejoined
        # r0 kept serving: it completed work, and with r1 fenced for part
        # of the sweep it carried more than half.
        assert _counter(reg, "serving_completed_total", component="serving",
                        replica="r0") > len(PROMPTS) / 2
        migrated = _counter(reg, "fleet_migrated_requests_total",
                            component="fleet")
        assert migrated > 0
        assert _counter(reg, "fleet_migrated_recovered_total",
                        component="fleet") == migrated
        assert _counter(reg, "fleet_fenced_total", component="fleet",
                        replica="r1", reason="replica_crash") == 1
        # Crash-class fence forces the breakers open — rejoin must pass
        # the half-open machinery (observable as a full cycle on r1).
        assert _counter(reg, "breaker_transitions_total",
                        component="serving", stage="decode", to="open",
                        replica="r1") >= 1
        assert fleet.await_recovery(timeout_s=30.0)
        assert reg.read_value("fleet_healthy_replicas",
                              component="fleet") == 2
        assert _counter(reg, "fleet_rejoins_total", component="fleet",
                        replica="r1") == 1
        assert _counter(reg, "canary_runs_total", component="serving",
                        replica="r1") >= 1
        assert fleet.last_failover_s is not None \
            and fleet.last_failover_s >= 0.0
        # The injected fault carries its own kind label.
        assert _counter(reg, "faults_total", component="fleet",
                        kind="injected_replica_crash", stage="replica",
                        replica="r1") == 1


def test_replica_hang_fences_and_migrates(engine, baseline):
    with use_registry() as reg:
        inj = ScriptedFaultInjector(replica_hangs={"r0": 2})
        fleet = _fleet(engine, fault_injector=inj)
        results = fleet.serve(_reqs())
        assert inj.replica_faults_fired == [("r0", "replica_hang")]
        _assert_parity(results, baseline, engine)
        assert _counter(reg, "fleet_fenced_total", component="fleet",
                        replica="r0", reason="replica_hang") == 1
        assert _counter(reg, "faults_total", component="fleet",
                        kind="injected_replica_hang", stage="replica",
                        replica="r0") == 1
        assert fleet.await_recovery(timeout_s=30.0)
        assert fleet.healthy_count == 2


def test_all_replicas_fenced_still_completes(engine, baseline):
    """Both replicas crash mid-sweep: the fleet holds the work, probes
    both back in after cooldown, and finishes everything — loss is never
    the answer to a whole-fleet outage, waiting is."""
    with use_registry():
        inj = ScriptedFaultInjector(replica_crashes={"r0": 2, "r1": 4})
        fleet = _fleet(engine, fault_injector=inj)
        results = fleet.serve(_reqs())
        assert sorted(inj.replica_faults_fired) == [
            ("r0", "replica_crash"), ("r1", "replica_crash")]
        _assert_parity(results, baseline, engine)
        assert all(rep.fences == 1 for rep in fleet.replicas)
        assert all(rep.rejoins >= 1 for rep in fleet.replicas) or \
            fleet.await_recovery(timeout_s=30.0)


def test_ladder_fence_from_request_faults(engine, baseline):
    """The INFERRED fence path: a request's repeated faults trip the
    hosting replica's breaker, its ladder climbs, and the router fences at
    the configured level — then the victim migrates with a fresh retry
    budget and completes cleanly elsewhere."""
    with use_registry() as reg:
        # Eager fence: one rung is enough. q2 faults once at decode on
        # whichever replica hosts it — that replica's breaker trips, its
        # ladder climbs, the router fences it, and q2 migrates (fresh
        # retry budget) to the healthy replica where the exhausted fault
        # budget lets it decode cleanly.
        inj = ScriptedFaultInjector(faults={("q2", "decode"): 1})
        fleet = _fleet(engine, fault_injector=inj,
                       fleet=FleetConfig(replicas=2, fence_ladder_level=1,
                                         fence_cooldown_s=0.02))
        results = fleet.serve(_reqs())
        _assert_parity(results, baseline, engine)
        fenced = [rep for rep in fleet.replicas if rep.fences]
        assert len(fenced) == 1
        assert _counter(reg, "fleet_fenced_total", component="fleet",
                        replica=fenced[0].name, reason="degraded") == 1
        assert fleet.await_recovery(timeout_s=30.0)


def test_fleet_zero_grace_fence_vs_graceful_drain(engine, tmp_path):
    """A fence drains with grace 0 (sick replicas don't finish work); a
    process-wide drain keeps the configured grace and journals the tail —
    the journal then resumes everything, fleet or no fleet."""
    from fairness_llm_tpu.resilience import ServingJournal, resume_serving

    with use_registry():
        journal = ServingJournal(str(tmp_path))
        fleet = _fleet(engine, journal=journal)
        reqs = _reqs(n=4)
        # Drain requested before serve: every request preempts to the
        # journal (the fleet checks the process-wide flag each tick).
        from fairness_llm_tpu.resilience import GracefulDrain

        with GracefulDrain() as gd:
            gd.requested = True
            results = fleet.serve(reqs)
        assert all(r.finish_reason == "preempted" for r in results)
        unfinished = sorted(r["id"] for r in journal.unfinished())
        assert unfinished == sorted(r.id for r in reqs)
        resumed = resume_serving(engine, journal, serving=SCFG,
                                 resilience=RES)
        assert sorted(resumed) == unfinished
        assert all(res.ok for res in resumed.values())
        assert journal.unfinished() == []


def test_sampled_fleet_rejoin_uses_smoke_probe(engine):
    """Sampled settings have no deterministic canary reference — the
    rejoin gate degrades to a smoke decode, and sampled traffic still
    survives a crash (stream-for-stream: same row_seed => same text)."""
    sampled = ModelSettings(temperature=0.7, top_k=0, top_p=1.0,
                            max_tokens=8)
    with use_registry():
        ref = {}
        for i, p in enumerate(PROMPTS[:4]):
            out = engine.generate([p], sampled, row_seeds=[1000 + i])
            ref[f"s{i}"] = out.texts[0]
        inj = ScriptedFaultInjector(replica_crashes={"r0": 2})
        fleet = _fleet(engine, fault_injector=inj, settings=sampled)
        reqs = [Request(prompt=p, id=f"s{i}", settings=sampled,
                        row_seed=1000 + i)
                for i, p in enumerate(PROMPTS[:4])]
        results = fleet.serve(reqs)
        for r in results:
            assert r.ok and r.text == ref[r.id], (r.id, r.text)
        assert fleet.await_recovery(timeout_s=30.0)


# -- backend integration ------------------------------------------------------


def test_serving_backend_builds_fleet(engine):
    with use_registry() as reg:
        backend = ServingBackend(
            engine, SCFG, resilience=RES, integrity=INTEG,
            fleet=FleetConfig(replicas=2),
        )
        texts = backend.generate(PROMPTS[:4], greedy(8), seed=0,
                                 keys=[f"k{i}" for i in range(4)])
        assert len(texts) == 4 and all(t is not None for t in texts)
        sched = backend.scheduler_for(greedy(8))
        assert isinstance(sched, ReplicaSet)
        assert backend.board is None  # resilience state is per-replica
        assert reg.read_value("fleet_replicas", component="fleet") == 2
        # Parity with the static engine through the whole backend stack.
        static = engine.generate(PROMPTS[:4], greedy(8), seed=0,
                                 share_prefix=False)
        assert texts == list(static.texts)
        assert backend.serve_totals is not None \
            and backend.serve_totals.completed == 4


def test_backend_second_fleet_gets_namespaced_labels(engine):
    """Two sampler tuples -> two ReplicaSets in one backend: the second
    fleet's replicas are namespaced ("s1.r0") and its fleet gauges carry a
    {"fleet": "s1"} label, so neither fleet's liveness/health instruments
    alias the other's."""
    sampled = ModelSettings(temperature=0.7, top_k=0, top_p=1.0,
                            max_tokens=8)
    with use_registry() as reg:
        backend = ServingBackend(engine, SCFG, resilience=RES,
                                 fleet=FleetConfig(replicas=2))
        first = backend.scheduler_for(greedy(8))
        second = backend.scheduler_for(sampled)
        assert first.name is None
        assert [r.name for r in first.replicas] == ["r0", "r1"]
        assert second.name == "s1"
        assert [r.name for r in second.replicas] == ["s1.r0", "s1.r1"]
        assert reg.read_value("fleet_replicas", component="fleet") == 2
        assert reg.read_value("fleet_replicas", component="fleet",
                              fleet="s1") == 2
        # Distinct per-replica breaker instruments, no aliasing.
        assert reg.peek("breaker_state", component="serving",
                        stage="decode", replica="r0") is not None
        assert reg.peek("breaker_state", component="serving",
                        stage="decode", replica="s1.r0") is not None


def test_backend_fleet_of_one_stays_scheduler(engine):
    from fairness_llm_tpu.serving import ContinuousScheduler

    with use_registry():
        backend = ServingBackend(engine, SCFG, fleet=FleetConfig(replicas=1))
        assert backend.fleet is None
        assert isinstance(backend.scheduler_for(greedy(8)),
                          ContinuousScheduler)


def test_replica_serving_config_rejects_bad_engine_count(engine):
    with pytest.raises(ValueError, match="engines"):
        ReplicaSet([engine], SCFG, settings=greedy(8),
                   fleet=FleetConfig(replicas=2))


def test_injector_rejects_conflicting_replica_scripts():
    with pytest.raises(ValueError, match="both crash and hang"):
        ScriptedFaultInjector(replica_crashes={"r0": 1},
                              replica_hangs={"r0": 1})


def test_submit_restamp_false_preserves_intake_clock(engine):
    """The fleet routes with restamp=False so a request's deadline/latency
    clock keeps running from FLEET intake — re-stamping at routing (or
    migration) would silently extend every deadline by its fleet-queue
    wait (the resume-serving deadline-from-first-submission contract)."""
    import time

    from fairness_llm_tpu.serving import ContinuousScheduler

    with use_registry():
        sched = ContinuousScheduler(engine, SCFG, settings=greedy(8))
        old = time.monotonic() - 5.0
        req = Request(prompt="hello there", id="clock", settings=greedy(8))
        req.submitted_at = old
        assert sched.submit(req, restamp=False)
        assert req.submitted_at == old  # intake clock preserved
        req2 = Request(prompt="hello there", id="clock2", settings=greedy(8))
        req2.submitted_at = old
        assert sched.submit(req2)
        assert req2.submitted_at > old  # default public submit re-stamps
        sched.drain()
        res = sched.take_result("clock")
        # The preserved clock shows up in the reported latency: the 5 s of
        # simulated pre-routing wait counts.
        assert res.ok and res.latency_s >= 5.0


def test_fleet_backend_periodic_canary_contains_mismatch(engine):
    """--canary-every in fleet mode: the probe is per-replica (round-robin)
    and a mismatch trips THAT replica's decode breaker — without this, a
    fleet-level mismatch would be detected but contained by nothing
    (there is no backend board in fleet mode)."""
    with use_registry() as reg:
        backend = ServingBackend(
            engine, SCFG, resilience=RES,
            integrity=IntegrityConfig(canary_every_n=1, canary_max_tokens=8),
            fleet=FleetConfig(replicas=2),
        )
        backend.generate(PROMPTS[:2], greedy(8), seed=0)  # probes r0: clean
        fleet = backend.scheduler_for(greedy(8))
        assert isinstance(fleet, ReplicaSet)
        assert _counter(reg, "canary_runs_total", component="serving",
                        replica="r0") == 1
        assert _counter(reg, "canary_mismatch_total", component="serving",
                        replica="r0") == 0
        # Silent corruption, as the comparator sees it: the fleet
        # version's shared reference is tampered (copy — the recorded
        # array is read-only), so the NEXT probe (round-robin: r1, whose
        # per-replica canary is built from the shared ref on first use)
        # mismatches and must trip r1's own decode breaker.
        ref = fleet._canary_refs[fleet.version]
        tampered = ref.reference.copy()
        tampered[0] += 1
        ref.reference = tampered
        texts = backend.generate(PROMPTS[2:4], greedy(8), seed=0)
        assert all(t is not None for t in texts)  # traffic kept flowing
        assert _counter(reg, "canary_mismatch_total", component="serving",
                        replica="r1") == 1
        assert _counter(reg, "breaker_transitions_total",
                        component="serving", stage="decode", to="open",
                        replica="r1") >= 1
        # r0's board is untouched — fault domains stay separate.
        assert _counter(reg, "breaker_transitions_total",
                        component="serving", stage="decode", to="open",
                        replica="r0") == 0


def test_fleet_deadline_expires_while_all_fenced(engine):
    """Requests stranded while the WHOLE fleet is fenced must terminate
    ``deadline`` instead of waiting forever — zero-loss means terminal,
    not necessarily served."""
    with use_registry():
        inj = ScriptedFaultInjector(replica_crashes={"r0": 0,
                                                     "r1": 0})
        # Long cooldown: the fleet stays fenced past every deadline.
        fleet = _fleet(engine, fault_injector=inj,
                       fleet=FleetConfig(replicas=2, fence_cooldown_s=60.0))
        reqs = [Request(prompt=p, id=f"d{i}", settings=greedy(8),
                        deadline_s=0.2)
                for i, p in enumerate(PROMPTS[:3])]
        results = fleet.serve(reqs)
        assert all(r.finish_reason == "deadline" for r in results)

"""Model forward tests: cached vs uncached equivalence, padding invariance,
family-flag paths (GPT-2-style, sliding window, GQA)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fairness_llm_tpu.models import Transformer, get_model_config, init_params
from fairness_llm_tpu.models.configs import MODEL_CONFIGS
from fairness_llm_tpu.models.transformer import init_cache


def _forward_uncached(config, params, tokens, token_valid=None, positions=None):
    model = Transformer(config)
    B, S = tokens.shape
    if positions is None:
        positions = jnp.tile(jnp.arange(S, dtype=jnp.int32)[None], (B, 1))
    apply = jax.jit(lambda p, t, po, tv: model.apply({"params": p}, t, po, token_valid=tv))
    logits, _ = apply(params, tokens, positions, token_valid)
    return logits


@pytest.mark.parametrize("name", ["tiny-test", "tiny-gpt2"])
def test_prefill_decode_matches_uncached(name):
    config = get_model_config(name)
    params = init_params(config, jax.random.key(0))
    model = Transformer(config)
    B, S = 2, 10
    tokens = jax.random.randint(jax.random.key(1), (B, S), 0, config.vocab_size)

    full_logits = _forward_uncached(config, params, tokens)

    # prefill S-1 tokens, then decode one step
    apply_cached = jax.jit(lambda p, t, po, c: model.apply({"params": p}, t, po, cache=c))
    cache = init_cache(config, B, max_len=S + 4)
    positions = jnp.tile(jnp.arange(S - 1, dtype=jnp.int32)[None], (B, 1))
    prefill_logits, cache = apply_cached(params, tokens[:, : S - 1], positions, cache)
    np.testing.assert_allclose(
        np.asarray(prefill_logits), np.asarray(full_logits[:, : S - 1]), atol=2e-4
    )
    step_pos = jnp.full((B, 1), S - 1, jnp.int32)
    step_logits, cache = apply_cached(params, tokens[:, S - 1 :], step_pos, cache)
    # S=1 vs S=10 take different XLA kernels; the ~7e-5 f32 reassociation noise is
    # amplified ~50x/layer by RMSNorm over tiny-init (0.02-scale) activations.
    # Verified: cache contents and any same-shape compare match exactly.
    np.testing.assert_allclose(
        np.asarray(step_logits[:, 0]), np.asarray(full_logits[:, -1]), atol=5e-3
    )
    assert int(cache.index) == S
    assert np.all(np.asarray(cache.lengths) == S)


def test_left_padding_invariance():
    """A left-padded row must produce the same last-token logits as unpadded."""
    config = get_model_config("tiny-test")
    params = init_params(config, jax.random.key(0))
    model = Transformer(config)
    S, pad = 6, 3
    tokens = jax.random.randint(jax.random.key(2), (1, S), 0, config.vocab_size)

    plain = _forward_uncached(config, params, tokens)

    padded = jnp.concatenate([jnp.zeros((1, pad), jnp.int32), tokens], axis=1)
    valid = jnp.concatenate(
        [jnp.zeros((1, pad), bool), jnp.ones((1, S), bool)], axis=1
    )
    positions = jnp.clip(jnp.cumsum(valid, axis=1) - 1, 0).astype(jnp.int32)
    logits = _forward_uncached(config, params, padded, token_valid=valid, positions=positions)
    np.testing.assert_allclose(
        np.asarray(logits[:, -1]), np.asarray(plain[:, -1]), atol=2e-4
    )


def test_sliding_window_changes_attention():
    base = get_model_config("tiny-test")
    windowed = dataclasses.replace(base, name="tiny-swa", sliding_window=4)
    params = init_params(base, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(3), (1, 12), 0, base.vocab_size)
    full = _forward_uncached(base, params, tokens)
    swa = _forward_uncached(windowed, params, tokens)
    # Early positions (inside window) agree; late positions differ.
    np.testing.assert_allclose(np.asarray(full[:, 2]), np.asarray(swa[:, 2]), atol=2e-4)
    assert not np.allclose(np.asarray(full[:, -1]), np.asarray(swa[:, -1]), atol=1e-3)


def test_gqa_head_counts():
    config = get_model_config("tiny-test")
    assert config.num_heads % config.num_kv_heads == 0
    params = init_params(config, jax.random.key(0))
    k_kernel = params["layer_0"]["attn"]["k_proj"]["kernel"]
    assert k_kernel.shape == (config.d_model, config.num_kv_heads * config.head_dim)
    q_kernel = params["layer_0"]["attn"]["q_proj"]["kernel"]
    assert q_kernel.shape == (config.d_model, config.num_heads * config.head_dim)


def test_all_registered_configs_are_consistent():
    for name, cfg in MODEL_CONFIGS.items():
        assert cfg.num_heads % cfg.num_kv_heads == 0, name
        assert cfg.q_dim == cfg.num_heads * cfg.head_dim
        assert cfg.pos_emb in ("rope", "learned")
        assert cfg.norm in ("rmsnorm", "layernorm")

"""Likelihood-based ("scored") phase-2 ranking: the TPU-native third method.

Core contract: ``score_continuations`` must satisfy the chain rule exactly
for the byte tokenizer — log p(prompt + c) = log p(prompt) + log p(c | prompt)
— so the ranking reflects true conditional likelihood, not an approximation.
"""

import numpy as np
import pytest

from fairness_llm_tpu.config import ModelSettings
from fairness_llm_tpu.data import movielens_ranking_corpus, synthetic_movielens
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.pipeline.backends import EngineBackend
from fairness_llm_tpu.pipeline.phase2 import (
    evaluate_model,
    make_queries,
    scored_evaluation,
)
from fairness_llm_tpu.runtime.engine import DecodeEngine
from fairness_llm_tpu.runtime.scoring import score_continuations, score_texts


@pytest.fixture(scope="module")
def engine():
    return DecodeEngine(get_model_config("tiny-test"), seed=0)


@pytest.fixture(scope="module")
def corpus():
    data = synthetic_movielens(num_movies=80, seed=4)
    return movielens_ranking_corpus(data, num_items=12, seed=4, min_ratings=1)


def test_chain_rule_decomposition(engine):
    """Conditional + prefix likelihood == full-text likelihood, per row."""
    prompt = "Query: best films\nA highly relevant result: "
    conts = ["Alpha Movie (1990)", "A Much Longer Movie Title (2001)", "Z"]
    full = score_texts(engine, [prompt + c for c in conts])
    prefix = score_texts(engine, [prompt])
    cond = score_continuations(engine, prompt, conts)
    for i in range(len(conts)):
        np.testing.assert_allclose(
            cond.log_likelihoods[i] + prefix.log_likelihoods[0],
            full.log_likelihoods[i],
            atol=5e-3,  # f32 log-softmax re-accumulation across two forwards
        )
    # token accounting: continuation tokens only
    assert (cond.token_counts == full.token_counts - prefix.token_counts[0]).all()


def test_truncated_row_boundary_accounting(engine):
    """A row longer than max_seq_len left-truncates the PREFIX first: the
    scored-token count must be kept_len - remaining_prefix, and untruncated
    rows in the same batch stay fully scored (the boundary filter previously
    dropped the first prefix_len continuation tokens of truncated rows)."""
    max_len = engine.config.max_seq_len  # tiny-test: 256 (byte tokenizer)
    prompt = "Q" * 40 + ": "
    prefix_len = len(engine.tokenizer.encode(prompt))
    short, long = "ok", "x" * (max_len + 50)
    out = score_continuations(engine, prompt, [short, long])

    short_total = len(engine.tokenizer.encode(prompt + short))
    assert out.token_counts[0] == short_total - prefix_len  # untruncated: exact

    long_total = len(engine.tokenizer.encode(prompt + long))
    kept = min(long_total, max_len)
    dropped = long_total - kept
    remaining_prefix = max(prefix_len - dropped, 0)  # 0 here: prefix fully cut
    assert remaining_prefix == 0
    assert out.token_counts[1] == kept - remaining_prefix - 1  # -1: first kept
    # token has no predecessor to be predicted from (target-shift)


def test_chunked_scoring_matches_unchunked(engine, monkeypatch):
    """The memory chunker must not change values — including when rows are
    ALSO truncated (the prefix adjustment once double-applied per recursion
    level, scoring surviving prompt tokens as continuation)."""
    import fairness_llm_tpu.runtime.scoring as scoring

    max_len = engine.config.max_seq_len
    prompt = "P" * 30 + ": "
    conts = [f"doc {i} " + "y" * (20 * i) for i in range(12)]
    conts.append("z" * (max_len + 40))  # forces left-truncation of its row
    baseline = score_continuations(engine, prompt, conts)

    monkeypatch.setattr(scoring, "LOGITS_BUDGET_BYTES", 1.0)  # chunk maximally
    chunked = score_continuations(engine, prompt, conts)
    np.testing.assert_allclose(
        chunked.log_likelihoods, baseline.log_likelihoods, atol=5e-3
    )
    assert (chunked.token_counts == baseline.token_counts).all()


def test_scored_evaluation_full_permutation_and_determinism(engine, corpus):
    backend = EngineBackend(engine, name="tiny-test")
    queries = make_queries(corpus, 2)
    r1 = scored_evaluation(backend, corpus, queries)
    r2 = scored_evaluation(backend, corpus, queries)
    assert r1 == r2  # deterministic: no sampling anywhere
    ids = {it.id for it in corpus}
    for r in r1:
        assert set(r) == ids


def test_evaluate_model_includes_scored_method(engine, corpus):
    backend = EngineBackend(engine, name="tiny-test")
    settings = ModelSettings(temperature=0.7, max_tokens=16)
    res = evaluate_model(backend, corpus, num_comparisons=4, settings=settings,
                         seed=0, num_queries=2)
    sc = res["scored"]
    assert sc["num_queries"] == 2 and len(sc["per_query"]) == 2
    assert 0.0 < sc["exposure_ratio"] <= 1.0
    assert set(sc["ranking"]) == {it.id for it in corpus}


def test_comparison_includes_scored_fairness(engine, corpus, tmp_path):
    from fairness_llm_tpu.pipeline.phase2 import compare_models_and_methods

    backend = EngineBackend(engine, name="tiny-test")
    settings = ModelSettings(temperature=0.7, max_tokens=16)
    res = evaluate_model(backend, corpus, num_comparisons=4, settings=settings, seed=0)
    comp = compare_models_and_methods({"tiny-test": res})
    mf = comp["model_fairness"]["tiny-test"]
    assert "scored_fairness" in mf
    # reference-compat average remains (listwise + pairwise) / 2
    assert mf["average_fairness"] == pytest.approx(
        (mf["listwise_fairness"] + mf["pairwise_fairness"]) / 2
    )
    assert "scored_avg" in comp["method_comparison"]

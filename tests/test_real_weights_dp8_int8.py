"""Golden regression for the int8-serving composed record (VERDICT r4 item 7).

``results/real_weights_dp8_int8/`` composes everything the dp8 record does
PLUS the int8 weight-only serving path end to end THROUGH a phase driver:

- REAL-WEIGHTS path: ``backend_for -> load_checkpoint`` (the float
  checkpoint quantized at load into QuantDense int8 kernels + scales)
- dp=8 mesh (8 virtual devices), sweep decodes batch-sharded
- ON-DEVICE metric reduction (``metadata.metric_reduction == "dp-psum"``)
- ``metadata.weight_quant == "int8"`` — the engine's own config, recorded
  by phase 1, witnesses the quantized serving mode

Regeneration (the suite's 8-virtual-CPU-device env, from the repo root):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 python -c "
    import jax; jax.config.update('jax_platforms','cpu'); \
    import sys; from fairness_llm_tpu.cli.main import main; sys.exit(main( \
    ['--all','--model','tiny-llama-study','--models','tiny-llama-study', \
     'tiny-gpt2-study','--weights-dir','checkpoints','--mesh','dp=8', \
     '--weight-quant','int8','--calibration','model-conditional', \
     '--results-dir','results/real_weights_dp8_int8','--num-items','12', \
     '--num-comparisons','8','--num-queries','2','--seed','42']))"
"""

import json
import os

import jax
import pytest

transformers = pytest.importorskip("transformers")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CKPTS = os.path.join(REPO, "checkpoints")
RECORD = os.path.join(REPO, "results", "real_weights_dp8_int8")

pytestmark = pytest.mark.skipif(
    not (os.path.isdir(CKPTS) and os.path.isdir(RECORD)),
    reason="committed checkpoints/record not present",
)


def _load(phase, name):
    with open(os.path.join(RECORD, phase, name)) as f:
        return json.load(f)


def test_record_metadata_witnesses_int8_composition():
    p1 = _load("phase1", "phase1_results.json")
    md = p1["metadata"]
    assert md["model"] == "tiny-llama-study"
    assert md["metric_reduction"] == "dp-psum"
    assert md["weight_quant"] == "int8"
    assert md["corpus"]["source"] == "real-catalog+synthetic-ratings"
    # non-vacuous: the teacher's bias survives int8 quantization
    assert 0.05 < p1["metrics"]["demographic_parity_gender"]["score"] < 0.95


def test_int8_dp8_rerun_matches_committed_record(tmp_path):
    """Re-run phase 1 with dp=8 + weight_quant=int8 through the real-weights
    load path: decodes byte-identical to the record, metrics equal."""
    import dataclasses

    from fairness_llm_tpu.config import MeshConfig, default_config
    from fairness_llm_tpu.data import load_movielens
    from fairness_llm_tpu.pipeline.backends import EngineBackend, backend_for
    from fairness_llm_tpu.pipeline.phase1 import run_phase1

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    config = dataclasses.replace(
        default_config(), weights_dir=CKPTS, random_seed=42,
        mesh=MeshConfig(dp=8), results_dir=str(tmp_path), weight_quant="int8",
    )
    data = load_movielens(config.data_dir, seed=config.random_seed)
    want = _load("phase1", "phase1_results.json")
    if want["metadata"].get("corpus") != data.provenance():
        pytest.skip("corpus provenance changed — regenerate the record")

    backend = backend_for("tiny-llama-study", config, catalog=data.titles)
    assert isinstance(backend, EngineBackend)
    assert backend.engine.config.weight_quant == "int8"
    assert dict(backend.engine.mesh.shape)["dp"] == 8

    got = run_phase1(config, "tiny-llama-study", save=False, backend=backend)
    assert got["metadata"]["metric_reduction"] == "dp-psum"
    assert got["metadata"]["weight_quant"] == "int8"
    for pid, rec in want["recommendations"].items():
        assert got["recommendations"][pid]["raw_response"] == rec["raw_response"], pid
    for key in ("demographic_parity_gender", "demographic_parity_age",
                "equal_opportunity", "individual_fairness"):
        assert got["metrics"][key]["score"] == pytest.approx(
            want["metrics"][key]["score"], abs=1e-4
        ), key


def test_int8_record_close_to_float_record():
    """int8 is a SERVING approximation of the same model: its study metrics
    must track the float dp8 record closely (per-channel int8 on a tiny
    distilled model shifts some near-tie decodes, so raw text may differ;
    the aggregate fairness picture must not)."""
    float_rec = os.path.join(REPO, "results", "real_weights_dp8")
    if not os.path.isdir(float_rec):
        pytest.skip("float dp8 record absent")
    with open(os.path.join(float_rec, "phase1", "phase1_results.json")) as f:
        want = json.load(f)
    got = _load("phase1", "phase1_results.json")
    if want["metadata"].get("corpus") != got["metadata"].get("corpus"):
        pytest.skip("records from different corpora — regenerate both")
    assert got["metrics"]["demographic_parity_gender"]["score"] == pytest.approx(
        want["metrics"]["demographic_parity_gender"]["score"], abs=0.15
    )
    assert got["metrics"]["equal_opportunity"]["score"] == pytest.approx(
        want["metrics"]["equal_opportunity"]["score"], abs=0.15
    )

"""Test harness: run everything on a virtual 8-device CPU mesh.

Env vars must be set before the first ``import jax`` anywhere in the test
process (SURVEY.md §4: XLA CPU exposes multiple devices via
``--xla_force_host_platform_device_count``, which is how sharding logic is
tested without TPU hardware).
"""

import os

# Hard override: the session env pins JAX_PLATFORMS=axon (the live TPU tunnel)
# and sitecustomize pre-imports jax, freezing that choice into jax.config — so
# the env-var route alone is too late. Set XLA_FLAGS (read at CPU-client
# creation, which hasn't happened yet) and flip the already-imported config.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import pathlib

import pytest

REFERENCE_ROOT = pathlib.Path("/root/reference")


@pytest.fixture(scope="session")
def reference_phase1_results():
    """The reference's committed phase-1 results JSON — the golden record for
    metric-parity tests. Skips when the reference tree isn't mounted."""
    path = REFERENCE_ROOT / "results" / "phase1" / "phase1_results.json"
    if not path.exists():
        pytest.skip("reference results not available")
    import json

    with open(path) as f:
        return json.load(f)


@pytest.fixture(scope="session")
def eight_device_mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from fairness_llm_tpu.config import MeshConfig
    from fairness_llm_tpu.parallel import make_mesh

    return make_mesh(MeshConfig(dp=2, tp=4, sp=1))

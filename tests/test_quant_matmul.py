"""int8 weight-only quantization: kernel oracle, model parity, sharding, load.

The capability under test is the round-4 headline: serving weights stored
int8 in HBM with dequantization inside the Pallas matmul tile (the naive
dequant-at-use gets hoisted out of decode loops by XLA and materializes the
float tree — docs/PERFORMANCE.md round 3). The reference has no local
weights at all (its models are remote APIs, SURVEY.md §0); parity here is
against our own float path, which is golden/HF-parity tested elsewhere.
"""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fairness_llm_tpu.config import MeshConfig, ModelSettings
from fairness_llm_tpu.models.configs import get_model_config
from fairness_llm_tpu.models.transformer import Transformer, init_params, init_params_lowmem
from fairness_llm_tpu.ops.quant_matmul import (
    dequantize_weight,
    quant_matmul,
    quant_tileable,
    quantize_weight,
)
from fairness_llm_tpu.parallel import sharding as shd
from fairness_llm_tpu.runtime.engine import DecodeEngine
from fairness_llm_tpu.runtime.weights import dequantize_params, quantize_params


def _ref_matmul(x, wq, scale):
    w = wq.astype(jnp.float32) * scale[None, :].astype(jnp.float32)
    return (x.astype(jnp.float32) @ w).astype(x.dtype)


# ---------------------------------------------------------------------------
# Quantize / dequantize
# ---------------------------------------------------------------------------


def test_quantize_round_trip_error_bound():
    w = jax.random.normal(jax.random.key(0), (256, 384), jnp.float32) * 0.05
    q, s = quantize_weight(w)
    assert q.dtype == jnp.int8 and s.dtype == jnp.float32
    back = dequantize_weight(q, s, jnp.float32)
    # symmetric 127-level quant: per-channel error <= scale/2 = amax/254
    bound = np.abs(np.asarray(w)).max(axis=0) / 254.0 + 1e-9
    assert (np.abs(np.asarray(back - w)) <= bound[None, :] * 1.001).all()


def test_quantize_zero_column_safe():
    w = jnp.zeros((128, 128), jnp.float32)
    q, s = quantize_weight(w)
    assert (np.asarray(q) == 0).all() and np.isfinite(np.asarray(s)).all()
    assert (np.asarray(dequantize_weight(q, s)) == 0).all()


# ---------------------------------------------------------------------------
# Kernel (interpret mode — the Mosaic pipeline itself is exercised on TPU by
# bench.py and the topology-AOT test below)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "m,k,n",
    [
        (48, 768, 3072),  # sweep decode shape
        (16, 256, 128),  # minimum tiles
        (1, 128, 256),  # single row -> sublane padding
        (45, 384, 640),  # M not a multiple of 8
    ],
)
def test_kernel_oracle_interpret(m, k, n):
    kx, kw = jax.random.split(jax.random.key(m * k + n))
    x = jax.random.normal(kx, (m, k), jnp.float32)
    w = jax.random.normal(kw, (k, n), jnp.float32) * 0.02
    wq, scale = quantize_weight(w)
    got = quant_matmul(x, wq, scale, interpret=True)
    want = _ref_matmul(x, wq, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_kernel_oracle_bf16_interpret():
    x = jax.random.normal(jax.random.key(1), (16, 256), jnp.bfloat16)
    w = jax.random.normal(jax.random.key(2), (256, 256), jnp.float32) * 0.02
    wq, scale = quantize_weight(w)
    got = quant_matmul(x, wq, scale, interpret=True)
    assert got.dtype == jnp.bfloat16
    want = _ref_matmul(x.astype(jnp.float32), wq, scale)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want), rtol=2e-2, atol=2e-2
    )


def test_tileability_gate():
    assert quant_tileable(768, 3072)
    assert not quant_tileable(768, 16032)  # llama vocab / 8: not a lane multiple
    assert not quant_tileable(100, 256)
    # the non-tileable XLA fallback still computes correctly
    x = jax.random.normal(jax.random.key(3), (8, 100), jnp.float32)
    w = jax.random.normal(jax.random.key(4), (100, 96), jnp.float32) * 0.02
    wq, scale = quantize_weight(w)
    np.testing.assert_allclose(
        np.asarray(quant_matmul(x, wq, scale)),
        np.asarray(_ref_matmul(x, wq, scale)),
        rtol=2e-5, atol=2e-5,
    )


# ---------------------------------------------------------------------------
# Model forward parity
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_quant():
    cfg = get_model_config("tiny-test")
    qcfg = dataclasses.replace(cfg, weight_quant="int8")
    params = init_params(cfg, jax.random.key(0))
    return cfg, qcfg, params, quantize_params(params)


def test_forward_matches_dequantized_float_model(tiny_quant):
    cfg, qcfg, params, qparams = tiny_quant
    dq = dequantize_params(qparams)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    pos = jnp.tile(jnp.arange(16, dtype=jnp.int32)[None, :], (2, 1))
    lf, _ = Transformer(cfg).apply({"params": dq}, tokens, pos)
    lq, _ = Transformer(qcfg).apply({"params": qparams}, tokens, pos)
    np.testing.assert_allclose(np.asarray(lq), np.asarray(lf), rtol=1e-4, atol=1e-5)


def test_quant_close_to_original_float_model(tiny_quant):
    """Quantization error on the LOGITS stays small for a normal-scale tree
    (the guarantee callers actually care about)."""
    cfg, qcfg, params, qparams = tiny_quant
    tokens = jax.random.randint(jax.random.key(2), (2, 16), 0, cfg.vocab_size)
    pos = jnp.tile(jnp.arange(16, dtype=jnp.int32)[None, :], (2, 1))
    lf, _ = Transformer(cfg).apply({"params": params}, tokens, pos)
    lq, _ = Transformer(qcfg).apply({"params": qparams}, tokens, pos)
    scale = float(jnp.max(jnp.abs(lf)))
    assert float(jnp.max(jnp.abs(lq - lf))) < 0.02 * scale + 0.02


def test_untied_lm_head_quantized():
    """tiny-test ties nothing: lm_head must appear as kernel_q + kernel_scale
    in the quant tree and the float leaf must be gone."""
    cfg = get_model_config("tiny-test")
    qcfg = dataclasses.replace(cfg, weight_quant="int8")
    qp = init_params(qcfg, jax.random.key(0))
    assert qp["lm_head"]["kernel_q"].dtype == jnp.int8
    assert qp["lm_head"]["kernel_scale"].dtype == jnp.float32
    assert qp["layer_0"]["attn"]["q_proj"]["kernel_q"].dtype == jnp.int8


def test_lowmem_init_matches_tree_structure():
    qcfg = dataclasses.replace(get_model_config("tiny-test"), weight_quant="int8")
    a = init_params(qcfg, jax.random.key(0))
    b = init_params_lowmem(qcfg, jax.random.key(0))
    sa = jax.tree.map(lambda x: (x.shape, str(x.dtype)), a)
    sb = jax.tree.map(lambda x: (x.shape, str(x.dtype)), b)
    assert sa == sb
    logits, _ = Transformer(qcfg).apply(
        {"params": b},
        jnp.zeros((1, 8), jnp.int32),
        jnp.tile(jnp.arange(8, dtype=jnp.int32)[None, :], (1, 1)),
    )
    assert bool(jnp.all(jnp.isfinite(logits)))


# ---------------------------------------------------------------------------
# Sharded parity (8-device CPU mesh)
# ---------------------------------------------------------------------------


def test_sharded_forward_matches_unsharded(tiny_quant):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg, qcfg, params, qparams = tiny_quant
    tokens = jax.random.randint(jax.random.key(3), (4, 16), 0, cfg.vocab_size)
    pos = jnp.tile(jnp.arange(16, dtype=jnp.int32)[None, :], (4, 1))
    l0, _ = Transformer(qcfg).apply({"params": qparams}, tokens, pos)

    mesh = shd.make_mesh(MeshConfig(dp=2, tp=2, sp=1))
    rules = shd.make_axis_rules(qcfg, mesh)
    qp_sharded = shd.shard_params(qparams, shd.param_shardings(qcfg, mesh, rules))
    model = Transformer(qcfg)
    with mesh, nn.logical_axis_rules(rules):
        ls = jax.jit(lambda p, t, po: model.apply({"params": p}, t, po)[0])(
            qp_sharded, tokens, pos
        )
    np.testing.assert_allclose(np.asarray(ls), np.asarray(l0), rtol=1e-5, atol=1e-5)


def test_engine_greedy_parity_and_mesh(tiny_quant):
    """Greedy decode: quant engine == engine over the dequantized float tree,
    single-device AND on a dp×tp mesh."""
    cfg, qcfg, params, qparams = tiny_quant
    settings = ModelSettings(temperature=0.0, top_k=0, top_p=1.0, max_tokens=8)
    prompts = ["hello world this is", "a quantization test of", "the tiny model decode"]
    e_f = DecodeEngine(cfg, params=dequantize_params(qparams), seed=0)
    e_q = DecodeEngine(qcfg, params=qparams, seed=0)
    of = e_f.generate(prompts, settings, seed=0)
    oq = e_q.generate(prompts, settings, seed=0)
    assert (of.tokens == oq.tokens).all()

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = shd.make_mesh(MeshConfig(dp=2, tp=2, sp=1))
    e_m = DecodeEngine(qcfg, params=qparams, mesh=mesh)
    om = e_m.generate(prompts, settings, seed=0)
    assert (om.tokens == oq.tokens).all()


# ---------------------------------------------------------------------------
# Checkpoint loading
# ---------------------------------------------------------------------------


def test_load_checkpoint_int8(tmp_path, tiny_quant):
    """HF-layout checkpoint -> int8 tree: quantize-at-load equals
    quantize(load) and the engine serves it."""
    from fairness_llm_tpu.runtime.weights import load_checkpoint, save_checkpoint_hf

    cfg, qcfg, params, qparams = tiny_quant
    save_checkpoint_hf(cfg, params, str(tmp_path))
    loaded = load_checkpoint(qcfg, str(tmp_path), dtype=jnp.float32)
    want = quantize_params(
        load_checkpoint(cfg, str(tmp_path), dtype=jnp.float32)
    )
    flat_a = jax.tree_util.tree_flatten_with_path(loaded)[0]
    flat_b = jax.tree_util.tree_flatten_with_path(want)[0]
    assert [p for p, _ in flat_a] == [p for p, _ in flat_b]
    for (pa, a), (_, b) in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=str(pa))


def test_save_checkpoint_dequantizes(tmp_path, tiny_quant):
    from fairness_llm_tpu.runtime.weights import load_checkpoint, save_checkpoint_hf

    cfg, qcfg, params, qparams = tiny_quant
    save_checkpoint_hf(qcfg, qparams, str(tmp_path))
    back = load_checkpoint(cfg, str(tmp_path), dtype=jnp.float32)
    want = dequantize_params(qparams)
    for pa, a in jax.tree_util.tree_flatten_with_path(back)[0]:
        b = want
        for part in pa:
            b = b[part.key]
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)


def test_quant_round_trip_preserves_biases():
    """tiny-gpt2 carries biases on every projection: quantize->dequantize
    must keep them (regression: dequantize_params once dropped sibling
    leaves while rebuilding the module dict)."""
    cfg = get_model_config("tiny-gpt2")
    params = init_params(cfg, jax.random.key(0))
    back = dequantize_params(quantize_params(params))
    flat_a = jax.tree_util.tree_flatten_with_path(params)[0]
    flat_b = jax.tree_util.tree_flatten_with_path(back)[0]
    assert [p for p, _ in flat_a] == [p for p, _ in flat_b]
    bias = back["layer_0"]["attn"]["q_proj"]["bias"]
    np.testing.assert_array_equal(
        np.asarray(bias), np.asarray(params["layer_0"]["attn"]["q_proj"]["bias"])
    )


def test_shared_prefix_on_mesh_batch1_forward(tiny_quant):
    """The engine's shared-prefix prefill runs batch=1 with an arbitrary
    prefix length; on a dp>1 mesh the QuantDense row sharding must fall back
    to replication when rows don't divide dp (regression: shard_map
    divisibility crash)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg, qcfg, params, qparams = tiny_quant
    settings = ModelSettings(temperature=0.0, top_k=0, top_p=1.0, max_tokens=6)
    mesh = shd.make_mesh(MeshConfig(dp=2, tp=2, sp=1))
    e_q = DecodeEngine(qcfg, params=qparams, seed=0)
    e_m = DecodeEngine(qcfg, params=qparams, mesh=mesh)
    # identical long-ish prompts -> auto prefix detection; explicit True
    # keeps the exact (odd) length, exercising the indivisible-rows path
    base = "the quick brown fox jumps over the lazy dog " * 4
    prompts = [base + tail for tail in ("alpha", "beta", "gamma")]
    o1 = e_q.generate(prompts, settings, seed=0, share_prefix=True)
    om = e_m.generate(prompts, settings, seed=0, share_prefix=True)
    assert o1.stats["prefix_len"] > 0
    assert (o1.tokens == om.tokens).all()


def test_train_step_rejects_quant_config():
    from fairness_llm_tpu.train import make_train_step

    qcfg = dataclasses.replace(get_model_config("tiny-test"), weight_quant="int8")
    with pytest.raises(ValueError, match="serving-only"):
        make_train_step(qcfg)


def test_backend_for_weight_quant_override(tmp_path, tiny_quant):
    """config.weight_quant='int8' (CLI --weight-quant) must route a real
    checkpoint through quantize-at-load and serve greedy-identically to an
    explicitly quantized engine."""
    import dataclasses as dc

    from fairness_llm_tpu.config import default_config
    from fairness_llm_tpu.pipeline.backends import EngineBackend, backend_for
    from fairness_llm_tpu.runtime.weights import save_checkpoint_hf

    cfg, qcfg, params, qparams = tiny_quant
    ckpt = tmp_path / "tiny-test"
    ckpt.mkdir()
    save_checkpoint_hf(cfg, params, str(ckpt))
    # tokenizer files: backend_for needs none for tiny-test (byte tokenizer)
    conf = dc.replace(
        default_config(), weights_dir=str(tmp_path), weight_quant="int8"
    )
    backend = backend_for("tiny-test", conf)
    assert isinstance(backend, EngineBackend)
    assert backend.engine.config.weight_quant == "int8"
    assert backend.engine.params["layer_0"]["attn"]["q_proj"]["kernel_q"].dtype == jnp.int8


@pytest.mark.parametrize("seed", [0, 1])
def test_fuzz_int8_load_path(tmp_path, seed):
    """Fuzz the quantize-at-load path: random weight trees (including
    adversarial values — zeros, huge magnitudes, denormals, +-inf-free
    extremes) must round-trip load->quantize->serve without NaN/Inf logits
    or crashes, and scales must stay finite/positive."""
    import dataclasses as dc

    from fairness_llm_tpu.runtime.weights import load_checkpoint, save_checkpoint_hf

    rng = np.random.default_rng(seed)
    cfg = get_model_config("tiny-test")
    qcfg = dc.replace(cfg, weight_quant="int8")
    params = init_params(cfg, jax.random.key(seed))

    def mutate(x):
        x = np.asarray(x, np.float32).copy()
        mode = rng.integers(0, 4)
        if mode == 0:
            x[:] = 0.0  # all-zero kernel -> zero scale guard
        elif mode == 1:
            x *= 1e30  # huge magnitudes -> scale overflow guard
        elif mode == 2:
            x *= 1e-38  # denormal-range -> scale underflow guard
        return jnp.asarray(x)

    params = jax.tree.map(mutate, params)
    d = tmp_path / "fuzz"
    d.mkdir()
    save_checkpoint_hf(cfg, params, str(d))
    loaded = load_checkpoint(qcfg, str(d), dtype=jnp.float32)
    for path, leaf in jax.tree_util.tree_flatten_with_path(loaded)[0]:
        name = getattr(path[-1], "key", "")
        arr = np.asarray(leaf)
        if name == "kernel_scale":
            assert np.isfinite(arr).all() and (arr > 0).all(), path
        if name == "kernel_q":
            assert arr.dtype == np.int8
            assert (np.abs(arr.astype(np.int32)) <= 127).all()


# ---------------------------------------------------------------------------
# 70B capacity accounting (cheap, analytic — the compiled-program proof runs
# on the TPU topology in tools/prove_70b_int8_fit.py / bench.py)
# ---------------------------------------------------------------------------


def test_70b_int8_analytic_bytes_fit_v5e():
    import types

    cfg = get_model_config("llama3-70b-int8")
    mesh = types.SimpleNamespace(shape={"dp": 1, "tp": 8, "sp": 1})
    rules = shd.make_axis_rules(cfg, mesh)

    class _M:
        shape = {"dp": 1, "tp": 8, "sp": 1}

    per = shd.per_device_param_bytes(cfg, _M, rules)
    # int8 kernels + f32 scales + bf16 embeddings/norms: ~9.1 GB/chip —
    # under 15.75 with ~6 GB left for KV cache + activations. The bf16
    # config at the same tp=8 is ~17.6 GB (test_70b_readiness.py).
    assert per < 10.0e9
    bf16 = shd.per_device_param_bytes(get_model_config("llama3-70b"), _M, rules)
    assert bf16 > 15.75e9 > per

"""Data-layer tests: loaders, profile grid, synthetic corpora."""

import numpy as np
import pytest

from fairness_llm_tpu.config import Config
from fairness_llm_tpu.data import (
    create_base_preferences,
    create_profile_grid,
    create_synthetic_ranking_data,
    load_movielens,
    synthetic_movielens,
)
from fairness_llm_tpu.data.profiles import profile_pairs


def test_synthetic_movielens_deterministic():
    a = synthetic_movielens(seed=7)
    b = synthetic_movielens(seed=7)
    assert a.titles == b.titles
    assert np.array_equal(a.rating_values, b.rating_values)
    assert a.num_movies == 200


def test_load_movielens_falls_back_to_synthetic(tmp_path):
    data = load_movielens(str(tmp_path), allow_synthetic=True)
    assert data.synthetic
    with pytest.raises(FileNotFoundError):
        load_movielens(str(tmp_path), allow_synthetic=False)


def test_load_movielens_parses_dat_files(tmp_path):
    (tmp_path / "movies.dat").write_text(
        "1::Toy Story (1995)::Animation|Children's|Comedy\n"
        "2::Heat (1995)::Action|Crime|Thriller\n",
        encoding="latin-1",
    )
    (tmp_path / "ratings.dat").write_text(
        "1::1::5::978300760\n1::2::4::978302109\n2::1::4::978301968\n"
    )
    data = load_movielens(str(tmp_path))
    assert not data.synthetic
    assert data.titles == ["Toy Story (1995)", "Heat (1995)"]
    assert data.genres[0] == ["Animation", "Children's", "Comedy"]
    assert data.num_ratings == 3
    assert data.rating_values[0] == 5.0


def test_load_movielens_mixed_mode(tmp_path):
    """Real catalog + missing ratings.dat -> seeded synthetic ratings over
    the REAL movie ids, with pinned provenance (the committed-snapshot mode
    the golden records run on)."""
    (tmp_path / "movies.dat").write_text(
        "1::Toy Story (1995)::Animation|Children's|Comedy\n"
        "7::Sabrina (1995)::Comedy|Romance\n",
        encoding="latin-1",
    )
    a = load_movielens(str(tmp_path), seed=5)
    b = load_movielens(str(tmp_path), seed=5)
    assert a.source == "real-catalog+synthetic-ratings"
    assert not a.synthetic  # catalog is real
    assert a.titles == ["Toy Story (1995)", "Sabrina (1995)"]
    assert set(np.unique(a.rating_movie_ids)) <= {1, 7}  # real ids only
    assert np.array_equal(a.rating_values, b.rating_values)  # seeded
    assert a.provenance() == {
        "source": "real-catalog+synthetic-ratings",
        "num_movies": 2,
        "num_ratings": a.num_ratings,
    }


def test_committed_catalog_is_real_ml1m():
    """The repo ships the true ML-1M movies/users tables; the loader must
    see all 3,883 movies (this is what every committed record pins)."""
    import pathlib

    data_dir = pathlib.Path(__file__).resolve().parent.parent / "data" / "ml-1m"
    if not (data_dir / "movies.dat").exists():
        pytest.skip("committed catalog absent")
    data = load_movielens(str(data_dir), seed=42)
    assert data.num_movies == 3883
    assert data.titles[0] == "Toy Story (1995)"
    # a developer may drop the true ratings.dat in (provenance "real") —
    # that's an upgrade, not a failure; only a fully-synthetic fallback
    # would mean the committed tables were silently ignored
    assert data.source in ("real-catalog+synthetic-ratings", "real")


def test_base_preferences_seeded_and_filtered():
    data = synthetic_movielens(seed=3)
    prefs1 = create_base_preferences(data, seed=11)
    prefs2 = create_base_preferences(data, seed=11)
    assert prefs1["watched_movies"] == prefs2["watched_movies"]
    assert len(prefs1["watched_movies"]) == 10
    assert 1 <= len(prefs1["favorite_genres"]) <= 3
    assert prefs1["avg_rating"] == 4.5


def test_profile_grid_shape_and_ids():
    config = Config()
    prefs = {"watched_movies": ["A", "B"], "favorite_genres": ["Drama"], "avg_rating": 4.5}
    profiles = create_profile_grid(prefs, config)
    # 3 genders x 5 ages x 3 = 45 (reference default)
    assert len(profiles) == 45
    assert profiles[0].id == "user_0000"
    assert profiles[-1].id == "user_0044"
    assert {p.gender for p in profiles} == set(config.genders)
    assert {p.age for p in profiles} == set(config.age_groups)
    assert all(p.occupation == "professional" for p in profiles)
    d = profiles[0].to_dict()
    assert d["preferences"]["watched_movies"] == ["A", "B"]


def test_profile_pairs_differ_in_exactly_one_attribute():
    config = Config()
    prefs = {"watched_movies": [], "favorite_genres": [], "avg_rating": 4.5}
    profiles = create_profile_grid(prefs, config, num_profiles_per_combination=1)
    pairs = profile_pairs(profiles)
    by_id = {p.id: p for p in profiles}
    for a, b in pairs:
        pa, pb = by_id[a], by_id[b]
        diffs = sum(getattr(pa, attr) != getattr(pb, attr) for attr in ("gender", "age", "occupation"))
        assert diffs == 1
    # 15 profiles: same-age cross-gender pairs 5*C(3,2)=15, same-gender cross-age 3*C(5,2)=30
    assert len(pairs) == 45


def test_ranking_data_seeded():
    a = create_synthetic_ranking_data(20, seed=5)
    b = create_synthetic_ranking_data(20, seed=5)
    assert [i.relevance for i in a] == [i.relevance for i in b]
    assert all(i.protected_attribute in ("male", "female") for i in a)
    assert all(0.3 <= i.relevance <= 1.0 for i in a)

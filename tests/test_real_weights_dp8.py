"""Golden regression for the COMPOSED flagship record (VERDICT r3 item 5).

``results/real_weights_dp8/`` is the committed record of the full ``--all``
study with every north-star piece composed at once:

- REAL-WEIGHTS path: ``backend_for -> load_checkpoint -> HFTokenizer ->
  EngineBackend`` over the committed ``checkpoints/tiny-*-study``
- dp=8 mesh (8 virtual devices): the sweep decodes batch-sharded
- ON-DEVICE metric reduction: phase 1's DP/EO group counts psum over dp
  (``metadata.metric_reduction == "dp-psum"``), not the host path
- the REAL ML-1M catalog (provenance-pinned)

Regeneration (the suite's 8-virtual-CPU-device env, from the repo root):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 python -c "
    import jax; jax.config.update('jax_platforms','cpu'); \
    import sys; from fairness_llm_tpu.cli.main import main; sys.exit(main( \
    ['--all','--model','tiny-llama-study','--models','tiny-llama-study', \
     'tiny-gpt2-study','--weights-dir','checkpoints','--mesh','dp=8', \
     '--calibration','model-conditional','--results-dir', \
     'results/real_weights_dp8','--num-items','12','--num-comparisons','8', \
     '--num-queries','2','--seed','42'])"
"""

import json
import os

import jax
import pytest

transformers = pytest.importorskip("transformers")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CKPTS = os.path.join(REPO, "checkpoints")
RECORD = os.path.join(REPO, "results", "real_weights_dp8")

pytestmark = pytest.mark.skipif(
    not (os.path.isdir(CKPTS) and os.path.isdir(RECORD)),
    reason="committed checkpoints/record not present",
)


def _load(phase, name):
    with open(os.path.join(RECORD, phase, name)) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def config():
    import dataclasses

    from fairness_llm_tpu.config import MeshConfig, default_config
    from fairness_llm_tpu.data import load_movielens

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg = dataclasses.replace(
        default_config(), weights_dir=CKPTS, random_seed=42,
        mesh=MeshConfig(dp=8), results_dir=None,
    )
    want = _load("phase1", "phase1_results.json")["metadata"].get("corpus")
    have = load_movielens(cfg.data_dir, seed=cfg.random_seed).provenance()
    if want != have:
        pytest.skip(
            f"corpus provenance changed (record {want} vs current {have}) — "
            "regenerate results/real_weights_dp8 (module docstring)"
        )
    return cfg


def test_record_is_the_composed_flagship():
    """The record's own metadata must prove the composition: real-weights
    model, dp-psum reduction, pinned real catalog."""
    p1 = _load("phase1", "phase1_results.json")
    md = p1["metadata"]
    assert md["model"] == "tiny-llama-study"
    assert md["metric_reduction"] == "dp-psum"
    assert md["corpus"]["source"] == "real-catalog+synthetic-ratings"
    # non-vacuous: the teacher's bias came through the dp-sharded sweep
    assert 0.05 < p1["metrics"]["demographic_parity_gender"]["score"] < 0.95
    assert p1["metrics"]["snsr_snsv"]["snsr"] > 0.005


def test_dp8_rerun_matches_committed_record(config, tmp_path):
    """Re-run phase 1 on the dp=8 mesh through the real-weights path: decodes
    byte-identical to the record, metrics equal, reduction on-device."""
    import dataclasses

    from fairness_llm_tpu.data import load_movielens
    from fairness_llm_tpu.pipeline.backends import EngineBackend, backend_for
    from fairness_llm_tpu.pipeline.phase1 import run_phase1

    config = dataclasses.replace(config, results_dir=str(tmp_path))
    data = load_movielens(config.data_dir, seed=config.random_seed)
    backend = backend_for("tiny-llama-study", config, catalog=data.titles)
    assert isinstance(backend, EngineBackend)
    assert backend.engine.mesh is not None
    assert dict(backend.engine.mesh.shape)["dp"] == 8

    got = run_phase1(config, "tiny-llama-study", save=False, backend=backend)
    want = _load("phase1", "phase1_results.json")
    assert got["metadata"]["metric_reduction"] == "dp-psum"
    for pid, rec in want["recommendations"].items():
        assert got["recommendations"][pid]["raw_response"] == rec["raw_response"], pid
    for key in ("demographic_parity_gender", "demographic_parity_age",
                "equal_opportunity", "individual_fairness"):
        assert got["metrics"][key]["score"] == pytest.approx(
            want["metrics"][key]["score"], abs=1e-4
        ), key


def test_dp8_record_agrees_with_single_device_record():
    """The composed record and the single-device real-weights record decode
    the SAME study (same checkpoints, same corpus, same seeds): raw decodes
    must be identical — the mesh changes WHERE work runs, not what it says.
    Metrics then agree to float tolerance (psum order vs host numpy)."""
    single = os.path.join(REPO, "results", "real_weights")
    if not os.path.isdir(single):
        pytest.skip("single-device record absent")
    with open(os.path.join(single, "phase1", "phase1_results.json")) as f:
        want = json.load(f)
    got = _load("phase1", "phase1_results.json")
    if want["metadata"].get("corpus") != got["metadata"].get("corpus"):
        pytest.skip("records from different corpora — regenerate both")
    for pid, rec in want["recommendations"].items():
        assert got["recommendations"][pid]["raw_response"] == rec["raw_response"], pid
    assert got["metrics"]["demographic_parity_gender"]["score"] == pytest.approx(
        want["metrics"]["demographic_parity_gender"]["score"], abs=1e-4
    )

"""Benchmark: phase-1 recommendation-sweep decode throughput per chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

What it measures: the end-to-end hot path of the study — the 45-profile
counterfactual prompt sweep (SURVEY.md §3.2 hot loop) — as batched
autoregressive decode on the local accelerator: tokenize -> left-pad ->
prefill -> 128 scan decode steps -> detokenize. Model is gpt2-small
(BASELINE.json configs[0]) with randomly initialized bf16 weights — weight
values don't change FLOPs or memory traffic, so throughput is representative
while requiring no checkpoint download.

``vs_baseline`` is the HONEST headline: achieved decode bandwidth as a
fraction of this chip's MEASURED achievable streaming bandwidth (1.0 =
decode at the hardware wall; falls back to the fraction of the 819 GB/s v5e
spec roofline if the in-run probe fails — ``baseline`` says which). The
reference-API comparison (its README estimates ~15 min for the 45-profile
sweep of sequential OpenAI calls, SURVEY.md §6 — a strawman next to
hardware-limit accounting) lives in ``detail.vs_reference_api_sweep``.

Run: python bench.py          (uses the default backend — TPU when present)
     BENCH_MODEL=tiny-test python bench.py   (smoke on CPU)
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax


REFERENCE_PROFILES_PER_SEC = 45 / (15 * 60)  # README estimate: 45 profiles / ~15 min
MAX_NEW_TOKENS = 128
V5E_HBM_GBPS = 819.0  # v5e spec HBM bandwidth — the decode roofline reference

# -- entry selection (ISSUE 12) ------------------------------------------------
# ``--entries a,b,c`` (or BENCH_ENTRIES) runs a subset of the auxiliary
# measurements — the perf-sentinel CI step runs only the CHEAP entries
# against the committed bench_baseline.json instead of the whole ~hour-long
# record. The headline sweep always runs (it IS the metric).

_ALL_ENTRIES = (
    "speculative", "continuous", "resilience", "integrity", "profiling",
    "fused_decode", "serve_tp", "incidents", "memory", "rollout", "fleet",
    "overload",
    "fairness", "prefix_cache", "capacity", "large_sweep", "phase2_listwise",
    "flash_proof", "int8_70b", "shard70b", "live8b",
)

_entries: "set | None" = None  # None = everything


class _SkippedEntry(Exception):
    """Raised inside an entry's try block when --entries excludes it."""


def _enabled(name: str) -> bool:
    return _entries is None or name in _entries


def _require_entry(name: str) -> None:
    if not _enabled(name):
        raise _SkippedEntry(name)


def set_entries(names) -> None:
    global _entries
    if names is None:
        _entries = None
        return
    bad = set(names) - set(_ALL_ENTRIES)
    if bad:
        raise SystemExit(f"unknown bench entries: {sorted(bad)} "
                         f"(choose from {', '.join(_ALL_ENTRIES)})")
    _entries = set(names)


# -- harness fingerprint + machine-readable baseline (ISSUE 12) ----------------


def _cpu_model() -> str:
    """Best-effort host CPU identity: ISA family plus the model name when
    readable. XLA-CPU codegen is host-target dependent (AVX2 vs AVX-512
    changes reduction order, which can flip near-tie argmax tokens), so
    exact-compared token checksums are only meaningful on one CPU model —
    the fingerprint must refuse across them."""
    import platform

    model = ""
    try:
        with open("/proc/cpuinfo", encoding="utf-8") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        model = platform.processor() or ""
    return f"{platform.machine()} {model}".strip()


def harness_fingerprint(model_name: str) -> dict:
    """What makes two bench runs comparable: same jax, same backend, same
    chip kind, same host CPU, same host parallelism, same model.
    tools/perf_sentinel.py REFUSES to compare runs whose fingerprints
    differ — a number recorded on a v5e means nothing next to one from a
    4-core CI runner."""
    devices = jax.devices()
    return {
        "jax": jax.__version__,
        "platform": jax.default_backend(),
        "device_kind": devices[0].device_kind if devices else "none",
        "cpu": _cpu_model(),
        "cpu_count": os.cpu_count(),
        "model": model_name,
    }


def baseline_entries(result: dict) -> dict:
    """Flatten a bench result into sentinel-comparable entries, each tagged
    ``kind``: ``wall`` metrics carry harness jitter (compared within a
    noise-aware ratio band) while ``exact`` counters (hit ratios, token
    counts/checksums, shed counts) are deterministic on one fingerprint and
    compared exactly — drift there is a correctness regression, not noise."""
    d = result.get("detail", {})
    entries: dict = {}

    def wall(name, value, better="higher"):
        # ``better`` is the improvement direction ("higher" for rates and
        # speedups, "lower" for on/off overhead ratios) — the sentinel's
        # best-of-N merge keeps the best rep PER THIS DIRECTION.
        if value is not None:
            entries[name] = {"kind": "wall", "value": float(value),
                             "better": better}

    def exact(name, value):
        if value is not None:
            entries[name] = {"kind": "exact", "value": value}

    wall("headline.profiles_per_sec", result.get("value"))
    wall("headline.decode_tokens_per_sec", d.get("decode_tokens_per_sec"))
    exact("headline.token_checksum", d.get("token_checksum"))
    c = d.get("continuous")
    if c:
        wall("continuous.tokens_per_sec",
             c.get("continuous", {}).get("tokens_per_sec"))
        wall("continuous.speedup", c.get("speedup_tokens_per_sec"))
        exact("continuous.useful_tokens",
              c.get("continuous", {}).get("useful_tokens"))
    s = d.get("speculative")
    if s:
        wall("speculative.speedup", s.get("speedup"))
        exact("speculative.acceptance_rate", s.get("acceptance_rate"))
        exact("speculative.verify_steps", s.get("verify_steps"))
    p = d.get("prefix_cache")
    if p:
        exact("prefix_cache.hit_ratio", p.get("on", {}).get("hit_ratio"))
        exact("prefix_cache.prefill_tokens_on",
              p.get("on", {}).get("prefill_tokens"))
        exact("prefix_cache.prefill_token_reduction",
              p.get("prefill_token_reduction"))
        wall("prefix_cache.speedup_ratio", p.get("speedup_ratio"))
    ov = d.get("overload_overhead")
    if ov:
        wall("overload.overhead_ratio", ov.get("overhead_ratio"),
             better="lower")
    pr = d.get("profiling_overhead")
    if pr:
        wall("profiling.overhead_ratio", pr.get("overhead_ratio"),
             better="lower")
    ic = d.get("incident_overhead")
    if ic:
        wall("incidents.overhead_ratio", ic.get("overhead_ratio"),
             better="lower")
    mo = d.get("memory_overhead")
    if mo:
        wall("memory.overhead_ratio", mo.get("overhead_ratio"),
             better="lower")
    ro = d.get("rollout_overhead")
    if ro:
        wall("rollout.overhead_ratio", ro.get("overhead_ratio"),
             better="lower")
    fd = d.get("fused_decode")
    if fd:
        # gap_per_token_reduction_k4 stays OUT of the sentinel baseline on
        # purpose: its run-to-run spread (measured 2.5-6.8x on this
        # harness — tiny absolute gaps divided by tiny absolute gaps)
        # exceeds the two-sided wall band. tokens/sec and the exact token
        # count are the stable regression proxies.
        wall("fused_decode.tokens_per_sec_k4",
             fd.get("k4", {}).get("tokens_per_sec"))
        exact("fused_decode.useful_tokens", fd.get("useful_tokens"))
    stp = d.get("serve_tp")
    if stp:
        # Real-mesh tp serving: walls per variant compare within the noise
        # band; the token checksum and the all-reduce count in the
        # compiled step HLO are exact — a zero all-reduce count means the
        # mesh silently degenerated to replication.
        wall("serve_tp.tokens_per_sec_contig_k4",
             stp.get("contig_k4", {}).get("tokens_per_sec"))
        wall("serve_tp.tokens_per_sec_paged_k4",
             stp.get("paged_k4", {}).get("tokens_per_sec"))
        exact("serve_tp.token_checksum", stp.get("token_checksum"))
        exact("serve_tp.useful_tokens", stp.get("useful_tokens"))
    cap = d.get("capacity")
    if cap:
        for n, row in (cap.get("capacity") or {}).items():
            wall(f"capacity.{n}.profiles_per_sec_per_chip",
                 row.get("profiles_per_sec_per_chip"))
            exact(f"capacity.{n}.shed_rate", row.get("shed_rate"))
    return entries


def write_bench_baseline(result: dict, path: str, model_name: str) -> str:
    """Write the machine-readable baseline tools/perf_sentinel.py compares
    against: per-entry metric + kind + the harness fingerprint."""
    baseline = {
        "schema_version": 1,
        "created_at_unix": time.time(),
        "fingerprint": harness_fingerprint(model_name),
        "entries": baseline_entries(result),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(baseline, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


# The bytes-per-step roofline model moved into the telemetry layer (ISSUE 7)
# so serving evaluates it LIVE per decode chunk; bench (and the tools that
# import it from here) share the single definition.
from fairness_llm_tpu.telemetry.roofline import decode_step_bytes  # noqa: E402


def measure_speculative(engine, prompts, settings_cls) -> dict | None:
    """Phase-1 sweep decoded GREEDILY with prompt-lookup speculation off vs on.

    Speculation is exact only for greedy decode, so this entry runs the same
    45-profile sweep at temperature 0 (the sweep's own 0.7-sampled headline
    can't use it). The sweep decodes in the STUDY's own chunking
    (``config.decode_batch_size``, the shape ``pipeline.phase1.decode_sweep``
    actually runs) — which on the CPU harness is also the decode-bound
    operating point where a verify step costs about a plain step (at
    whole-sweep batch a CPU is compute-bound and the k+1-wide forward
    multiplies FLOPs; on TPU decode is HBM-bound at every batch). Reports
    tokens/sec both ways plus measured acceptance and verify-step
    compression — the numbers the ISSUE-1 target (>= 1.2x) is judged on.
    Measured on the repo's CPU harness: 2.0x (28.4 -> 58.0 tok/s) at 46%
    acceptance, 28 verify steps for 128-token rows. Reuses the headline
    engine (same params; greedy programs compile alongside the sampled ones).
    """
    import numpy as np

    from fairness_llm_tpu.config import SpeculationConfig, default_config
    from fairness_llm_tpu.utils.profiling import SpeculationStats

    settings = settings_cls(temperature=0.0, top_k=0, top_p=1.0,
                            max_tokens=MAX_NEW_TOKENS)
    spec = SpeculationConfig(enabled=True)
    pad_id = engine.tokenizer.pad_id
    chunk = max(default_config().decode_batch_size, 1)
    chunks = [prompts[i : i + chunk] for i in range(0, len(prompts), chunk)]
    out: dict = {
        "profiles": len(prompts),
        "decode_batch_size": chunk,
        "max_new_tokens": MAX_NEW_TOKENS,
        "draft_len": spec.draft_len,
        "ngram_max": spec.ngram_max,
    }
    for label, sp in (("off", None), ("on", spec)):
        # Compile outside the timed window: one warmup per DISTINCT chunk
        # size (same-size chunks pad to the same bucket and share a program).
        warmed = set()
        for c in chunks:
            if len(c) not in warmed:
                warmed.add(len(c))
                engine.generate(c, settings, seed=0, speculation=sp)
        totals = SpeculationStats()
        ntok = 0
        t0 = time.perf_counter()
        for c in chunks:
            o = engine.generate(c, settings, seed=1, speculation=sp)
            jax.block_until_ready(o.tokens)
            # Greedy real models can stop at EOS early; count tokens actually
            # decoded rather than assuming the cap.
            ntok += int(np.sum(o.tokens != pad_id))
            st = (o.stats or {}).get("speculation")
            if st:
                totals = totals.merge(SpeculationStats.from_dict(st))
        wall = time.perf_counter() - t0
        out[label] = {
            "wall_s": round(wall, 3),
            "decoded_tokens": ntok,
            "tokens_per_sec": round(ntok / wall, 1),
            "speculation": totals.as_dict() if totals.verify_steps else None,
        }
    out["speedup"] = round(out["off"]["wall_s"] / out["on"]["wall_s"], 3)
    on_spec = out["on"]["speculation"] or {}
    out["acceptance_rate"] = on_spec.get("acceptance_rate")
    out["verify_steps"] = on_spec.get("verify_steps")
    return out


def _mixed_workload(engine, prompts, n_requests, targets, budgets):
    """Interleaved mixed-length serving workload shared by the continuous
    and resilience-overhead entries: request i's prompt repeats its source
    up to ``targets[i % ...]`` tokens and decodes ``budgets[i % ...]``
    tokens — every static chunk then contains one near-max row, which is
    precisely the waste continuous batching removes."""
    tok = engine.tokenizer
    out = []
    for i in range(n_requests):
        ids = tok.encode(prompts[i % len(prompts)])
        tl = targets[i % len(targets)]
        ids = (ids * (tl // max(len(ids), 1) + 1))[:tl]
        out.append((tok.decode(ids), budgets[i % len(budgets)]))
    return out


def _greedy(settings_cls, m):
    return settings_cls(temperature=0.0, top_k=0, top_p=1.0, max_tokens=m)


def measure_continuous(engine, prompts, settings_cls) -> dict | None:
    """Continuous batching vs static chunking on a mixed-length workload.

    The workload is what the static engine is worst at: prompts spanning
    32-448 tokens and per-request decode budgets spanning 16-128 tokens,
    interleaved so every static chunk pads to its longest prompt and decodes
    to its largest budget (finished rows burn steps until the chunk drains).
    The continuous server (serving/) evicts each row the step its own budget
    completes and backfills the freed KV slot from the queue, so total decode
    steps track sum(budgets)/num_slots instead of sum of per-chunk maxima.

    Greedy both ways (the serving parity contract), same number of rows in
    flight both ways (num_slots == the static chunk size), compile excluded
    by an identical warmup pass. Reports tokens/sec and p50/p95 request
    latency for both modes — the ISSUE-2 target is >= 1.3x tokens/sec.
    """
    import numpy as np

    from fairness_llm_tpu.config import ServingConfig, default_config
    from fairness_llm_tpu.serving import ContinuousScheduler, Request

    num_slots = max(default_config().decode_batch_size, 1)
    # 4x the pool: enough churn that the warm middle of the run (where
    # eviction+backfill keep the pool near-full) dominates the drain tail
    # (the tail is a fixed ~cap-length cost, so it amortizes with workload).
    n_requests = 4 * num_slots
    targets = [32, 64, 128, 256, 448]  # prompt token lengths, interleaved
    # Per-request max_tokens: a 10x spread (short lookups to long
    # generations) — see _mixed_workload.
    budgets = [16, 32, 48, 64, 96, 160]
    tok = engine.tokenizer
    workload = _mixed_workload(engine, prompts, n_requests, targets, budgets)

    def greedy(m):
        return _greedy(settings_cls, m)

    pad_id = tok.pad_id

    def run_static():
        lat, useful, t0 = [], 0, time.perf_counter()
        for s in range(0, n_requests, num_slots):
            chunk = workload[s : s + num_slots]
            cap = max(b for _, b in chunk)
            out = engine.generate([p for p, _ in chunk], greedy(cap), seed=1)
            jax.block_until_ready(out.tokens)
            done_at = time.perf_counter() - t0
            for row, (_, b) in zip(np.asarray(out.tokens), chunk):
                useful += int(np.sum(row[:b] != pad_id))
                lat.append(done_at)
        return time.perf_counter() - t0, useful, lat

    sched = ContinuousScheduler(
        engine,
        ServingConfig(
            enabled=True, num_slots=num_slots, max_prompt_len=512,
            max_new_tokens=max(budgets), decode_chunk=8,
        ),
        settings=greedy(max(budgets)),
    )

    def run_continuous():
        # Fresh Request objects each run (retry counters are per-object);
        # the SCHEDULER persists, so the warmup run leaves every prefill
        # bucket + the step program compiled.
        reqs = [
            Request(prompt=p, id=f"bench_{i:04d}", settings=greedy(b))
            for i, (p, b) in enumerate(workload)
        ]
        t0 = time.perf_counter()
        results = sched.serve(reqs)
        wall = time.perf_counter() - t0
        # Same counting rule as the static side (non-pad tokens): the
        # result array holds emitted tokens incl. any stopping EOS, which
        # for a pad==eos tokenizer the static count excludes — apply the
        # identical filter so neither side gets a free token per request.
        useful = sum(
            int(np.sum(np.asarray(r.tokens) != pad_id))
            for r in results if r.ok
        )
        # TTFT per request from the scheduler's lifecycle spans
        # (telemetry/tracing.py): first-token materialization relative to
        # submission, chunk-granular — the client-visible number.
        ttfts = [r.ttft_s for r in results if r.ttft_s is not None]
        return wall, useful, [r.latency_s for r in results], ttfts, \
            sched.last_stats

    run_static()  # warmup: compile every static chunk shape
    run_continuous()  # warmup: compile prefill buckets + the step program
    # Best-of-2 per mode (the headline's min-of-reps idiom): single-run
    # walls on a co-tenanted CPU harness swing enough to flip the ratio.
    st_wall, st_tok, st_lat = min(
        (run_static() for _ in range(2)), key=lambda r: r[0]
    )
    ct_wall, ct_tok, ct_lat, ct_ttft, ct_stats = min(
        (run_continuous() for _ in range(2)), key=lambda r: r[0]
    )

    def pcts(lat, prefix=""):
        if not lat:
            return {}
        return {
            f"{prefix}p50_s": round(float(np.percentile(lat, 50)), 3),
            f"{prefix}p95_s": round(float(np.percentile(lat, 95)), 3),
        }

    st_rate, ct_rate = st_tok / st_wall, ct_tok / ct_wall
    return {
        "num_requests": n_requests,
        "num_slots": num_slots,
        "prompt_token_lengths": targets,
        "budgets_max_tokens": budgets,
        "static": {
            "wall_s": round(st_wall, 3), "useful_tokens": st_tok,
            "tokens_per_sec": round(st_rate, 1), **pcts(st_lat),
        },
        "continuous": {
            "wall_s": round(ct_wall, 3), "useful_tokens": ct_tok,
            "tokens_per_sec": round(ct_rate, 1), **pcts(ct_lat),
            # per-request TTFT (lifecycle spans) next to the e2e latency the
            # static side can't decompose — chunk-granular, see
            # telemetry/tracing.py
            **pcts(ct_ttft, prefix="ttft_"),
            "serving_stats": ct_stats.as_dict() if ct_stats else None,
        },
        "speedup_tokens_per_sec": round(ct_rate / st_rate, 3),
    }


def measure_resilience_overhead(engine, prompts, settings_cls) -> dict | None:
    """Fault-free continuous serving with the resilience layer off vs on.

    The watchdog arms/observes around every compiled prefill/decode chunk
    and the breakers record a success per chunk — pure host-side integer
    arithmetic plus a couple of ``time.monotonic`` calls, so the ISSUE-4
    target is overhead WITHIN the CPU harness's run-to-run noise (±30-60%
    single-run wall jitter; best-of-N per mode in one process is the
    comparison that holds still, per docs/PERFORMANCE.md methodology).

    Same mixed-length workload shape as ``measure_continuous`` (the
    realistic regime: constant admission churn = maximum watchdog/breaker
    call frequency per decoded token)."""
    from fairness_llm_tpu.config import (
        ResilienceConfig,
        ServingConfig,
        default_config,
    )
    from fairness_llm_tpu.serving import ContinuousScheduler, Request

    num_slots = max(default_config().decode_batch_size, 1)
    n_requests = 2 * num_slots
    budgets = [16, 32, 48, 64]
    workload = _mixed_workload(engine, prompts, n_requests,
                               targets=[32, 64, 128, 256], budgets=budgets)

    def greedy(m):
        return _greedy(settings_cls, m)

    scfg = ServingConfig(
        enabled=True, num_slots=num_slots, max_prompt_len=512,
        max_new_tokens=max(budgets), decode_chunk=8,
    )
    # Generous watchdog budget: the guard measures the fault-free
    # bookkeeping cost, not hang classification (a CPU-harness chunk can
    # legitimately take seconds under co-tenancy).
    res = ResilienceConfig(enabled=True, max_step_seconds=300.0,
                           breaker_threshold=3)

    def run(sched, tag):
        reqs = [
            Request(prompt=p, id=f"res_{tag}_{i:04d}", settings=greedy(b))
            for i, (p, b) in enumerate(workload)
        ]
        t0 = time.perf_counter()
        results = sched.serve(reqs)
        wall = time.perf_counter() - t0
        assert all(r.ok for r in results)
        toks = sum(len(r.tokens) for r in results)
        return wall, toks

    out = {}
    for tag, resilience in (("off", None), ("on", res)):
        sched = ContinuousScheduler(
            engine, scfg, settings=greedy(max(budgets)), resilience=resilience
        )
        run(sched, tag)  # warmup: compile prefill buckets + step program
        wall, toks = min((run(sched, tag) for _ in range(3)),
                         key=lambda r: r[0])
        out[tag] = {
            "wall_s": round(wall, 3),
            "tokens_per_sec": round(toks / wall, 1),
        }
    out["overhead_ratio"] = round(
        out["on"]["wall_s"] / out["off"]["wall_s"], 3
    )
    return out


def measure_integrity_overhead(engine, prompts, settings_cls) -> dict | None:
    """Fault-free continuous serving with the numerics guards off vs on.

    The guard is one ``isfinite`` + AND-reduction over the step's logits
    folded INTO the compiled program (integrity/numerics.py) — device-side
    work this time, unlike the resilience guard's host-side bookkeeping, so
    the A/B compiles two distinct step programs and measures whether the
    reduction is visible over the decode loop's weight/KV streaming. The
    ISSUE-5 target is the same as ISSUE-4's: within the CPU harness's
    run-to-run noise (best-of-N per mode in one process).

    Same mixed-length workload shape as ``measure_continuous`` (constant
    admission churn = maximum prefill+decode program launches per token,
    i.e. maximum guard evaluations per token)."""
    from fairness_llm_tpu.config import ServingConfig, default_config
    from fairness_llm_tpu.serving import ContinuousScheduler, Request

    num_slots = max(default_config().decode_batch_size, 1)
    n_requests = 2 * num_slots
    budgets = [16, 32, 48, 64]
    workload = _mixed_workload(engine, prompts, n_requests,
                               targets=[32, 64, 128, 256], budgets=budgets)

    def greedy(m):
        return _greedy(settings_cls, m)

    scfg = ServingConfig(
        enabled=True, num_slots=num_slots, max_prompt_len=512,
        max_new_tokens=max(budgets), decode_chunk=8,
    )

    def run(sched, tag):
        reqs = [
            Request(prompt=p, id=f"integ_{tag}_{i:04d}", settings=greedy(b))
            for i, (p, b) in enumerate(workload)
        ]
        t0 = time.perf_counter()
        results = sched.serve(reqs)
        wall = time.perf_counter() - t0
        assert all(r.ok for r in results)
        return wall, sum(len(r.tokens) for r in results), results

    prev_guard = engine.numerics_guards
    out = {}
    tokens = {}
    try:
        for tag, guard in (("off", False), ("on", True)):
            engine.numerics_guards = guard
            sched = ContinuousScheduler(engine, scfg,
                                        settings=greedy(max(budgets)))
            run(sched, tag)  # warmup: compile prefill buckets + step program
            (wall, toks, results) = min(
                (run(sched, tag) for _ in range(3)), key=lambda r: r[0]
            )
            tokens[tag] = [tuple(int(t) for t in r.tokens) for r in results]
            out[tag] = {
                "wall_s": round(wall, 3),
                "tokens_per_sec": round(toks / wall, 1),
            }
    finally:
        engine.numerics_guards = prev_guard
    # The guard must never change the tokens — parity is part of the guard's
    # contract, so the bench asserts it on the workload it just decoded.
    assert tokens["on"] == tokens["off"], "numerics guard changed output"
    out["overhead_ratio"] = round(
        out["on"]["wall_s"] / out["off"]["wall_s"], 3
    )
    return out


def measure_profiling_overhead(engine, prompts, settings_cls) -> dict | None:
    """Fault-free continuous serving with the performance-attribution layer
    off vs on (ISSUE 7).

    The attribution layer is host-side bookkeeping per compiled call: one
    timeline span + step-gap histogram observe per decode chunk, a compile
    cache-lookup counter per program fetch, three roofline gauge writes, and
    one SLO window evaluation per terminal request. ``set_attribution``
    flips ALL of it, so the A/B isolates exactly the layer's cost. Target:
    overhead within the CPU harness's run-to-run noise (±30-60% single-run
    jitter; best-of-N per mode in one process, per docs/PERFORMANCE.md
    methodology), with token parity asserted.

    The "on" mode also reports what the layer measured: ``step_gap_s``
    p50/p95 (the per-chunk host sync ROADMAP item 3 attacks) next to
    tokens/sec, and the live ``achieved_over_achievable`` fraction.
    """
    from fairness_llm_tpu.config import ServingConfig, default_config
    from fairness_llm_tpu.serving import ContinuousScheduler, Request
    from fairness_llm_tpu.telemetry import (
        set_attribution,
        use_registry,
        use_timeline,
    )

    num_slots = max(default_config().decode_batch_size, 1)
    n_requests = 2 * num_slots
    budgets = [16, 32, 48, 64]
    workload = _mixed_workload(engine, prompts, n_requests,
                               targets=[32, 64, 128, 256], budgets=budgets)

    def greedy(m):
        return _greedy(settings_cls, m)

    scfg = ServingConfig(
        enabled=True, num_slots=num_slots, max_prompt_len=512,
        max_new_tokens=max(budgets), decode_chunk=8,
    )

    def run(sched, tag):
        reqs = [
            Request(prompt=p, id=f"prof_{tag}_{i:04d}", settings=greedy(b))
            for i, (p, b) in enumerate(workload)
        ]
        t0 = time.perf_counter()
        results = sched.serve(reqs)
        wall = time.perf_counter() - t0
        assert all(r.ok for r in results)
        toks = [tuple(int(t) for t in r.tokens) for r in results]
        return wall, toks

    out = {}
    tokens = {}
    prev = set_attribution(True)
    try:
        for tag, on in (("off", False), ("on", True)):
            # Fresh registry + timeline per mode: the "on" step-gap/roofline
            # numbers come from exactly this workload, and the "off" mode
            # proves the layer records nothing.
            with use_registry() as reg, use_timeline() as tl:
                set_attribution(on)
                sched = ContinuousScheduler(engine, scfg,
                                            settings=greedy(max(budgets)))
                run(sched, tag)  # warmup: compile prefill buckets + step
                wall, toks = min((run(sched, tag) for _ in range(3)),
                                 key=lambda r: r[0])
                tokens[tag] = toks
                total = sum(len(t) for t in toks)
                out[tag] = {
                    "wall_s": round(wall, 3),
                    "tokens_per_sec": round(total / wall, 1),
                }
                if on:
                    gap = reg.histogram("step_gap_s", component="serving")
                    out[tag].update({
                        "step_gap_p50_s": gap.percentile(50),
                        "step_gap_p95_s": gap.percentile(95),
                        "step_gap_count": gap.count,
                        "achieved_over_achievable": round(reg.read_value(
                            "achieved_over_achievable",
                            component="roofline", program="serve_step",
                        ), 4),
                        "timeline_events": len(tl.events()),
                    })
                else:
                    # The off mode must have recorded NOTHING.
                    assert not tl.events(), "attribution off still recorded"
                    assert reg.peek("step_gap_s", component="serving") is None
    finally:
        set_attribution(prev)
    assert tokens["on"] == tokens["off"], "attribution layer changed output"
    out["overhead_ratio"] = round(
        out["on"]["wall_s"] / out["off"]["wall_s"], 3
    )
    return out


def measure_fused_decode(engine, prompts, settings_cls) -> dict | None:
    """Fused multi-step decode dispatch sweep (ISSUE 14): ``fuse_steps``
    k in {1, 2, 4, 8} over the same mixed workload, one process.

    The fused dispatch folds k decode chunks into ONE compiled call
    (runtime/stepbuilder.py), so the host work between dispatches — the
    eviction sweep, queue polls, telemetry, and the blocking device_get —
    amortizes ~1/k per generated token. ``step_gap_s`` (ISSUE 7) measures
    exactly that gap, so this entry reports, per k: tokens/sec (best-of-3,
    the ±30-60% jitter discipline), the step-gap p50/p95, the HOST GAP PER
    TOKEN (step-gap seconds summed over the timed reps / tokens they
    generated — the acceptance metric: k=4 must cut it >= 2x vs k=1), and
    the live ``achieved_over_achievable`` fraction. Token parity across
    every k is asserted — fusion moves dispatch boundaries, never tokens.
    """
    from fairness_llm_tpu.config import ServingConfig, default_config
    from fairness_llm_tpu.serving import ContinuousScheduler, Request
    from fairness_llm_tpu.telemetry import (
        set_attribution,
        use_registry,
        use_timeline,
    )

    num_slots = max(default_config().decode_batch_size, 1)
    # ONE admission wave (n_requests == num_slots): a backfill prefill
    # between two chunks lands inside step_gap_s (PR 7 semantics: ALL host
    # time between dispatches), and that prefill work is the same absolute
    # seconds at every k — it would dilute the 1/k dispatch-sync signal
    # this entry exists to measure toward 1x. The churn/backfill surface
    # is covered by the parity tests and the continuous entry; PR 12's
    # decomposition attributes prefill to its own program either way.
    n_requests = num_slots
    budgets = [16, 32, 48, 64]
    workload = _mixed_workload(engine, prompts, n_requests,
                               targets=[32, 64, 128, 256], budgets=budgets)

    def greedy(m):
        return _greedy(settings_cls, m)

    def run(sched, tag):
        reqs = [
            Request(prompt=p, id=f"fused_{tag}_{i:04d}", settings=greedy(b))
            for i, (p, b) in enumerate(workload)
        ]
        t0 = time.perf_counter()
        results = sched.serve(reqs)
        wall = time.perf_counter() - t0
        assert all(r.ok for r in results)
        toks = [tuple(int(t) for t in r.tokens) for r in results]
        return wall, toks

    out: dict = {}
    tokens = {}
    prev = set_attribution(True)
    try:
        for k in (1, 2, 4, 8):
            with use_timeline():
                scfg = ServingConfig(
                    enabled=True, num_slots=num_slots,
                    max_prompt_len=512, max_new_tokens=max(budgets),
                    decode_chunk=8, fuse_steps=k,
                )
                sched = ContinuousScheduler(
                    engine, scfg, settings=greedy(max(budgets)))
                with use_registry():
                    # Warmup in a THROWAWAY registry: the compile-era step
                    # gaps (step_gap_s keeps PR-7 all-host-time semantics,
                    # so first-call XLA walls land as gap samples) must not
                    # pollute the percentiles/counts reported below. Every
                    # instrument writer resolves get_registry() at write
                    # time, so the swap is safe mid-scheduler-lifetime.
                    run(sched, f"w{k}")
                with use_registry() as reg:
                    gap = reg.histogram("step_gap_s", component="serving")
                    rep_tokens = 0
                    best = None
                    for rep in range(3):
                        wall, toks = run(sched, f"r{k}_{rep}")
                        rep_tokens += sum(len(t) for t in toks)
                        if best is None or wall < best[0]:
                            best = (wall, toks)
                    wall, toks = best
                    tokens[k] = toks
                    total = sum(len(t) for t in toks)
                    prog = "serve_step" if k == 1 else "serve_step_fused"
                    out[f"k{k}"] = {
                        "wall_s": round(wall, 3),
                        "tokens_per_sec": round(total / wall, 1),
                        # Accumulated over the 3 timed reps (dividing sums
                        # beats best-of-1 for a per-token average).
                        "host_gap_per_token_s": round(
                            gap.sum / max(rep_tokens, 1), 8),
                        "step_gap_p50_s": gap.percentile(50),
                        "step_gap_p95_s": gap.percentile(95),
                        "dispatch_gaps": gap.count,
                        "achieved_over_achievable": round(reg.read_value(
                            "achieved_over_achievable",
                            component="roofline", program=prog,
                        ), 4),
                    }
    finally:
        set_attribution(prev)
    for k in (2, 4, 8):
        assert tokens[k] == tokens[1], \
            f"fused decode k={k} changed the token stream"
    out["useful_tokens"] = sum(len(t) for t in tokens[1])
    out["gap_per_token_reduction_k4"] = round(
        out["k1"]["host_gap_per_token_s"]
        / max(out["k4"]["host_gap_per_token_s"], 1e-12), 2
    )
    out["speedup_k4_tokens_per_sec"] = round(
        out["k4"]["tokens_per_sec"] / out["k1"]["tokens_per_sec"], 3
    )
    return out


def measure_serve_tp() -> dict | None:
    """Real-mesh tensor-parallel serving (the stepbuilder's mesh axis):
    tp=2 continuous serving — contiguous AND paged, fuse 1 AND 4 — with
    the collectives EXECUTED, not modeled, on a real 2-device mesh
    (``--xla_force_host_platform_device_count`` on the CPU harness; the
    same code path is the TPU tp mesh). The worker
    (tools/serve_tp_bench.py) asserts token-for-token parity against the
    single-device engine and that the compiled step HLO contains
    all-reduce before reporting any number — a silent fall-back to
    replication fails the entry rather than flattering it.

    Subprocess by necessity: the forced host device count binds at jax
    init, which already happened in this process."""
    import subprocess

    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2 " + \
        env.get("XLA_FLAGS", "").replace(
            "--xla_force_host_platform_device_count", "--ignored")
    env.setdefault("JAX_PLATFORMS", "cpu")
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "serve_tp_bench.py")
    proc = subprocess.run(
        [sys.executable, worker, "--tp", "2"],
        env=env, capture_output=True, text=True, timeout=900,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"serve_tp worker failed (rc={proc.returncode}): "
            f"{proc.stderr.strip()[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def measure_incident_overhead(engine, prompts, settings_cls) -> dict | None:
    """Fault-free continuous serving with the incident layer — flight
    recorder + decision audit trail — off vs on (ISSUE 13).

    The layer is host-side bookkeeping per decision point: one bounded
    deque append + one counter per decision, a ring append per lifecycle
    edge / decode chunk / roofline sample, and a value-deduped transition
    ring entry per gauge change. ``set_recording`` flips ALL of it (the
    attribution layer stays ON in both modes), so the A/B isolates
    exactly this layer's cost. No incident manager is armed — a fault-free
    workload must never dump a bundle, and triggers are free no-ops while
    disarmed. Target: overhead within the CPU harness's run-to-run noise
    (±30-60% single-run jitter; best-of-3 per mode in one process, per
    docs/PERFORMANCE.md methodology), with token parity asserted.
    """
    from fairness_llm_tpu.config import ServingConfig, default_config
    from fairness_llm_tpu.serving import ContinuousScheduler, Request
    from fairness_llm_tpu.telemetry import (
        set_recording,
        use_flight_recorder,
        use_incident_manager,
        use_registry,
        use_timeline,
    )

    num_slots = max(default_config().decode_batch_size, 1)
    n_requests = 2 * num_slots
    budgets = [16, 32, 48, 64]
    workload = _mixed_workload(engine, prompts, n_requests,
                               targets=[32, 64, 128, 256], budgets=budgets)

    def greedy(m):
        return _greedy(settings_cls, m)

    scfg = ServingConfig(
        enabled=True, num_slots=num_slots, max_prompt_len=512,
        max_new_tokens=max(budgets), decode_chunk=8,
    )

    def run(sched, tag):
        reqs = [
            Request(prompt=p, id=f"inc_{tag}_{i:04d}", settings=greedy(b))
            for i, (p, b) in enumerate(workload)
        ]
        t0 = time.perf_counter()
        results = sched.serve(reqs)
        wall = time.perf_counter() - t0
        assert all(r.ok for r in results)
        toks = [tuple(int(t) for t in r.tokens) for r in results]
        return wall, toks

    out = {}
    tokens = {}
    prev = set_recording(True)
    try:
        for tag, on in (("off", False), ("on", True)):
            # Fresh registry/timeline/recorder/manager per mode: the "on"
            # ring depths come from exactly this workload, and the "off"
            # mode proves the layer records nothing.
            with use_registry() as reg, use_timeline(), \
                    use_flight_recorder() as rec, use_incident_manager():
                set_recording(on)
                sched = ContinuousScheduler(engine, scfg,
                                            settings=greedy(max(budgets)))
                run(sched, tag)  # warmup: compile prefill buckets + step
                wall, toks = min((run(sched, tag) for _ in range(3)),
                                 key=lambda r: r[0])
                tokens[tag] = toks
                total = sum(len(t) for t in toks)
                out[tag] = {
                    "wall_s": round(wall, 3),
                    "tokens_per_sec": round(total / wall, 1),
                }
                if on:
                    out[tag].update({
                        "ring_depths": {k: len(v)
                                        for k, v in rec.rings.items()},
                        "decisions_total": int(sum(
                            m.value for m in reg.instruments()
                            if getattr(m, "name", "") == "decisions_total"
                        )),
                    })
                else:
                    # The off mode must have recorded NOTHING. Counter
                    # absence is checked over instruments() (peek needs
                    # the exact label set incl. decision=..., so a
                    # component-only peek would pass vacuously).
                    assert all(not v for v in rec.rings.values()), \
                        "recording off still filled a ring"
                    assert not any(
                        getattr(m, "name", "") == "decisions_total"
                        for m in reg.instruments()
                    ), "recording off still counted decisions"
    finally:
        set_recording(prev)
    assert tokens["on"] == tokens["off"], "incident layer changed output"
    out["overhead_ratio"] = round(
        out["on"]["wall_s"] / out["off"]["wall_s"], 3
    )
    return out


def measure_memory_overhead(engine, prompts, settings_cls) -> dict | None:
    """Fault-free continuous serving with the HBM memory ledger — per-pool
    accounting + the AOT program-memory capture — off vs on (ISSUE 18).

    The ledger's steady-state cost is host-side: a pytree-nbytes walk per
    allocation/rebuild site (a handful per scheduler LIFETIME, not per
    step) and a gauge write per register/release; the AOT capture pays its
    second XLA compile during warmup only (once per program, flagged
    done). ``set_memory_obs`` flips both, so the A/B isolates exactly this
    layer. Target: overhead within the CPU harness's run-to-run noise
    (best-of-3 per mode, per docs/PERFORMANCE.md methodology), token
    parity asserted, ZERO reconciliation alerts in the on mode — a clean
    workload whose ledger disagrees with the device is an accounting bug,
    not noise.
    """
    from fairness_llm_tpu.config import ServingConfig, default_config
    from fairness_llm_tpu.serving import ContinuousScheduler, Request
    from fairness_llm_tpu.telemetry import (
        set_aot_memory_capture,
        set_memory_obs,
        use_memory_ledger,
        use_registry,
        use_timeline,
    )

    num_slots = max(default_config().decode_batch_size, 1)
    n_requests = 2 * num_slots
    budgets = [16, 32, 48, 64]
    workload = _mixed_workload(engine, prompts, n_requests,
                               targets=[32, 64, 128, 256], budgets=budgets)

    def greedy(m):
        return _greedy(settings_cls, m)

    scfg = ServingConfig(
        enabled=True, num_slots=num_slots, max_prompt_len=512,
        max_new_tokens=max(budgets), decode_chunk=8,
    )

    def run(sched, tag):
        reqs = [
            Request(prompt=p, id=f"mem_{tag}_{i:04d}", settings=greedy(b))
            for i, (p, b) in enumerate(workload)
        ]
        t0 = time.perf_counter()
        results = sched.serve(reqs)
        wall = time.perf_counter() - t0
        assert all(r.ok for r in results)
        toks = [tuple(int(t) for t in r.tokens) for r in results]
        return wall, toks

    out = {}
    tokens = {}
    prev_aot = set_aot_memory_capture(False)
    try:
        for tag, on in (("off", False), ("on", True)):
            # Fresh registry/timeline/ledger per mode: the "on" pool bytes
            # come from exactly this scheduler, and the "off" mode proves
            # the layer publishes nothing.
            with use_registry() as reg, use_timeline(), \
                    use_memory_ledger() as mem:
                set_memory_obs(on)
                sched = ContinuousScheduler(engine, scfg,
                                            settings=greedy(max(budgets)))
                run(sched, tag)  # warmup: compiles + the AOT capture
                wall, toks = min((run(sched, tag) for _ in range(3)),
                                 key=lambda r: r[0])
                tokens[tag] = toks
                total = sum(len(t) for t in toks)
                out[tag] = {
                    "wall_s": round(wall, 3),
                    "tokens_per_sec": round(total / wall, 1),
                }
                if on:
                    alerts = sum(
                        m.value for m in reg.instruments()
                        if getattr(m, "name", "")
                        == "hbm_reconciliation_alerts_total"
                    )
                    assert alerts == 0, \
                        "memory ledger reconciliation alerted on a clean A/B"
                    assert any(
                        getattr(m, "name", "") == "program_memory_bytes"
                        for m in reg.instruments()
                    ), "AOT memory capture published nothing"
                    out[tag].update({
                        "ledger_bytes": int(mem.total_bytes()),
                        "kv_bytes": int(mem.pool_bytes("kv_contiguous")
                                        + mem.pool_bytes("kv_paged")),
                        "reconciliation_alerts": int(alerts),
                    })
                else:
                    assert not any(
                        getattr(m, "name", "") in ("hbm_bytes",
                                                   "program_memory_bytes")
                        for m in reg.instruments()
                    ), "memory obs off still published gauges"
                    assert mem.total_bytes() == 0, \
                        "memory obs off still accounted bytes"
    finally:
        set_aot_memory_capture(prev_aot)
    assert tokens["on"] == tokens["off"], "memory ledger changed output"
    out["overhead_ratio"] = round(
        out["on"]["wall_s"] / out["off"]["wall_s"], 3
    )
    return out


def measure_rollout_overhead(engine, prompts, settings_cls) -> dict | None:
    """Armed-idle rollout controller vs none attached (PR 20).

    The version axis's steady-state cost when NO wave is in flight is
    pure hot-path bookkeeping: per-submit version stamping (the
    pinned-affinity map), the router's version filter short-circuit, and
    the fleet tick's ``rollout.active`` probe. A/B: the same mixed
    workload through identical 2-replica fleets, one bare, one with a
    :class:`RolloutController` constructed but never started. Target:
    within the CPU harness's run-to-run noise (best-of-3 per mode, per
    docs/PERFORMANCE.md methodology), token parity asserted, and the
    armed mode must record ZERO rollout transitions — armed means armed,
    not creeping.
    """
    from fairness_llm_tpu.config import (
        FleetConfig,
        ResilienceConfig,
        RolloutConfig,
        ServingConfig,
        default_config,
    )
    from fairness_llm_tpu.serving import ReplicaSet, Request, RolloutController
    from fairness_llm_tpu.telemetry import use_registry, use_timeline

    num_slots = max(default_config().decode_batch_size, 2)
    per_replica = max(num_slots // 2, 1)
    n_requests = 2 * num_slots
    budgets = [16, 32, 48, 64]
    workload = _mixed_workload(engine, prompts, n_requests,
                               targets=[32, 64, 128, 256], budgets=budgets)

    def greedy(m):
        return _greedy(settings_cls, m)

    scfg = ServingConfig(
        enabled=True, num_slots=per_replica, max_prompt_len=512,
        max_new_tokens=max(budgets), decode_chunk=8,
    )
    res = ResilienceConfig(enabled=True, breaker_threshold=3,
                           breaker_cooldown_s=0.05)

    def run(fleet, tag):
        reqs = [
            Request(prompt=p, id=f"ro_{tag}_{i:04d}", settings=greedy(b))
            for i, (p, b) in enumerate(workload)
        ]
        t0 = time.perf_counter()
        results = fleet.serve(reqs)
        wall = time.perf_counter() - t0
        assert all(r.ok for r in results)
        toks = [tuple(int(t) for t in r.tokens) for r in results]
        return wall, toks

    out = {"num_requests": n_requests, "replicas": 2,
           "slots_per_replica": per_replica}
    tokens = {}
    for tag, armed in (("off", False), ("on", True)):
        # Fresh registry/timeline per mode so the zero-transition check
        # reads exactly this fleet's instruments.
        with use_registry() as reg, use_timeline():
            fleet = ReplicaSet(engine, scfg, settings=greedy(max(budgets)),
                               fleet=FleetConfig(replicas=2),
                               resilience=res)
            ro = None
            if armed:
                ro = RolloutController(
                    fleet, "v1", engine=engine,
                    config=RolloutConfig(enabled=True),
                )  # constructed, never started: armed-idle
            run(fleet, tag)  # warmup: compile prefill buckets + steps
            wall, toks = min((run(fleet, tag) for _ in range(3)),
                             key=lambda r: r[0])
            tokens[tag] = toks
            total = sum(len(t) for t in toks)
            out[tag] = {
                "wall_s": round(wall, 3),
                "tokens_per_sec": round(total / wall, 1),
            }
            if armed:
                assert ro.state == "idle", "armed-idle controller moved"
                transitions = sum(
                    m.value for m in reg.instruments()
                    if getattr(m, "name", "") == "rollout_transitions_total"
                )
                assert transitions == 0, \
                    "armed-idle rollout recorded transitions"
    assert tokens["on"] == tokens["off"], "armed rollout changed output"
    out["overhead_ratio"] = round(
        out["on"]["wall_s"] / out["off"]["wall_s"], 3
    )
    return out


def measure_fleet(engine, prompts, settings_cls) -> dict | None:
    """2-replica fleet router vs a single scheduler, plus failover timing.

    Two measurements (ISSUE 6):

    - **Fault-free overhead**: the same mixed-length workload through one
      ``ContinuousScheduler`` with N slots vs a 2-replica ``ReplicaSet``
      with N/2 slots each — same TOTAL concurrency, so the delta is the
      router itself (health scoring, per-replica bookkeeping, the
      interleaved step loop). Target: within the CPU harness's run-to-run
      noise (±30-60% single-run jitter; best-of-3 per mode in one
      process, per docs/PERFORMANCE.md methodology). Token parity between
      the two modes is asserted on the workload just decoded.
    - **Failover recovery**: re-run with a scripted ``replica_crash`` on
      r1 mid-sweep and report fence -> first migrated token
      (``ReplicaSet.last_failover_s``), migrated count, and that zero
      requests were lost.
    """
    import numpy as np

    from fairness_llm_tpu.config import (
        FleetConfig,
        IntegrityConfig,
        ResilienceConfig,
        ServingConfig,
        default_config,
    )
    from fairness_llm_tpu.serving import ContinuousScheduler, ReplicaSet, Request
    from fairness_llm_tpu.utils.failures import ScriptedFaultInjector

    num_slots = max(default_config().decode_batch_size, 2)
    per_replica = num_slots // 2
    n_requests = 2 * num_slots
    budgets = [16, 32, 48, 64]
    workload = _mixed_workload(engine, prompts, n_requests,
                               targets=[32, 64, 128, 256], budgets=budgets)

    def greedy(m):
        return _greedy(settings_cls, m)

    def scfg(slots):
        return ServingConfig(
            enabled=True, num_slots=slots, max_prompt_len=512,
            max_new_tokens=max(budgets), decode_chunk=8,
        )

    res = ResilienceConfig(enabled=True, breaker_threshold=3,
                           breaker_cooldown_s=0.05)

    def run(server, tag):
        reqs = [
            Request(prompt=p, id=f"fleet_{tag}_{i:04d}", settings=greedy(b))
            for i, (p, b) in enumerate(workload)
        ]
        t0 = time.perf_counter()
        results = server.serve(reqs)
        wall = time.perf_counter() - t0
        assert all(r.ok for r in results)
        toks = [tuple(int(t) for t in r.tokens) for r in results]
        return wall, toks

    out = {"num_requests": n_requests, "total_slots": num_slots,
           "replicas": 2, "slots_per_replica": per_replica}
    tokens = {}
    single = ContinuousScheduler(engine, scfg(num_slots),
                                 settings=greedy(max(budgets)))
    fleet = ReplicaSet(engine, scfg(per_replica), settings=greedy(max(budgets)),
                       fleet=FleetConfig(replicas=2), resilience=res)
    for tag, server in (("single", single), ("fleet", fleet)):
        run(server, tag)  # warmup: compile prefill buckets + step programs
        wall, toks = min((run(server, tag) for _ in range(3)),
                         key=lambda r: r[0])
        tokens[tag] = toks
        total = sum(len(t) for t in toks)
        out[tag] = {
            "wall_s": round(wall, 3),
            "tokens_per_sec": round(total / wall, 1),
        }
    # The router must never change the tokens — fleet greedy parity is the
    # zero-loss contract's other half, asserted on what was just decoded.
    assert tokens["fleet"] == tokens["single"], "fleet routing changed output"
    out["router_overhead_ratio"] = round(
        out["fleet"]["wall_s"] / out["single"]["wall_s"], 3
    )

    # Failover: crash r1 a few health polls in, measure recovery.
    inj = ScriptedFaultInjector(replica_crashes={"r1": 4})
    crash_fleet = ReplicaSet(
        engine, scfg(per_replica), settings=greedy(max(budgets)),
        fleet=FleetConfig(replicas=2, fence_cooldown_s=0.1),
        resilience=res, fault_injector=inj,
        integrity=IntegrityConfig(canary_max_tokens=8),
    )
    reqs = [Request(prompt=p, id=f"failover_{i:04d}", settings=greedy(b))
            for i, (p, b) in enumerate(workload)]
    t0 = time.perf_counter()
    results = crash_fleet.serve(reqs)
    wall = time.perf_counter() - t0
    rejoined = crash_fleet.await_recovery(timeout_s=60.0)
    from fairness_llm_tpu.telemetry import get_registry

    out["failover"] = {
        "wall_s": round(wall, 3),
        "crash_fired": inj.replica_faults_fired == [("r1", "replica_crash")],
        "zero_lost": all(r.ok for r in results),
        "migrated_requests": int(get_registry().read_value(
            "fleet_migrated_requests_total", component="fleet")),
        "recovery_s_fence_to_first_migrated_token": (
            round(crash_fleet.last_failover_s, 4)
            if crash_fleet.last_failover_s is not None else None
        ),
        "crashed_replica_rejoined": rejoined,
    }
    assert out["failover"]["zero_lost"], "failover lost requests"
    return out


def measure_overload_overhead(engine, prompts, settings_cls) -> dict | None:
    """Fault-free, under-capacity continuous serving with overload control
    off vs on (ISSUE 8).

    With the controller armed but nothing overloaded, the added cost is
    host-side only: a per-class dequeue decision per admission, one depth
    sample + a throttled ladder evaluation per loop iteration, and a
    feasibility estimate per deadline-carrying request (none here) — the
    target is overhead within the CPU harness's run-to-run noise
    (best-of-N per mode in one process, docs/PERFORMANCE.md methodology),
    with token parity asserted across MIXED QoS classes: under capacity,
    class scheduling must not reorder anything observably.

    SLO targets are set harness-appropriate for the entry (compile-time
    TTFT outliers on the first chunk would otherwise legitimately burn the
    fast window and trigger a brownout mid-measurement — the controller
    doing its job, but not what an overhead A/B should measure)."""
    from fairness_llm_tpu.config import (
        OverloadConfig,
        ServingConfig,
        default_config,
    )
    from fairness_llm_tpu.serving import ContinuousScheduler, Request
    from fairness_llm_tpu.telemetry.slo import SLOTargets, set_slo_targets

    num_slots = max(default_config().decode_batch_size, 1)
    n_requests = 2 * num_slots  # under capacity: no queue pressure signal
    budgets = [16, 32, 48, 64]
    workload = _mixed_workload(engine, prompts, n_requests,
                               targets=[32, 64, 128, 256], budgets=budgets)

    def greedy(m):
        return _greedy(settings_cls, m)

    scfg = ServingConfig(
        enabled=True, num_slots=num_slots, max_prompt_len=512,
        max_new_tokens=max(budgets), decode_chunk=8,
    )
    ov = OverloadConfig(enabled=True)

    def run(sched, tag):
        reqs = [
            Request(prompt=p, id=f"ov_{tag}_{i:04d}", settings=greedy(b),
                    qos="interactive" if i % 2 == 0 else "batch")
            for i, (p, b) in enumerate(workload)
        ]
        t0 = time.perf_counter()
        results = sched.serve(reqs)
        wall = time.perf_counter() - t0
        assert all(r.ok for r in results), [
            (r.id, r.finish_reason) for r in results if not r.ok
        ]
        toks = [tuple(int(t) for t in r.tokens) for r in results]
        return wall, toks

    out = {}
    tokens = {}
    prev = set_slo_targets(SLOTargets(ttft_p95_s=300.0, e2e_p99_s=600.0))
    try:
        for tag, overload in (("off", None), ("on", ov)):
            sched = ContinuousScheduler(
                engine, scfg, settings=greedy(max(budgets)),
                overload=overload,
            )
            run(sched, tag)  # warmup: compile prefill buckets + step
            wall, toks = min((run(sched, tag) for _ in range(3)),
                             key=lambda r: r[0])
            tokens[tag] = toks
            total = sum(len(t) for t in toks)
            out[tag] = {
                "wall_s": round(wall, 3),
                "tokens_per_sec": round(total / wall, 1),
            }
            if overload is not None:
                assert sched.shed_controller.level == 0, (
                    "controller escalated on fault-free under-capacity "
                    "traffic"
                )
                assert sched.last_stats.shed == 0, "shed under capacity"
    finally:
        set_slo_targets(prev)
    # Class scheduling must be output-invariant under capacity: every
    # request decodes the same tokens whichever sub-queue it rode.
    assert tokens["on"] == tokens["off"], "overload control changed output"
    out["overhead_ratio"] = round(
        out["on"]["wall_s"] / out["off"]["wall_s"], 3
    )
    return out


def measure_fairness_overhead(engine, prompts, settings_cls) -> dict | None:
    """Fault-free continuous serving with fairness observability off vs on
    (ISSUE 9).

    The on mode is the full armed-and-fed path: every request tagged
    (group/attribute/pair_id), the profile grid + pair set registered with
    the monitor, the content feed folding each result into the streaming
    group accumulators, the pair watch joining every pair, and the derived
    DP/IF/exposure gauges refreshed — all inside the timed window, exactly
    the per-chunk cost a tagged study pays. The added work is host-side
    (dict folds per result, one small jit DP kernel per refresh), so the
    target is overhead within the CPU harness's run-to-run noise
    (best-of-N per mode in one process, docs/PERFORMANCE.md methodology),
    token parity asserted: observation must not change what is served."""
    from fairness_llm_tpu.config import ServingConfig, default_config
    from fairness_llm_tpu.serving import ContinuousScheduler, Request
    from fairness_llm_tpu.telemetry.fairness import (
        FairnessMonitor,
        set_fairness_monitor,
    )

    num_slots = max(default_config().decode_batch_size, 1)
    n_requests = 2 * num_slots
    budgets = [16, 32, 48, 64]
    workload = _mixed_workload(engine, prompts, n_requests,
                               targets=[32, 64, 128, 256], budgets=budgets)

    def greedy(m):
        return _greedy(settings_cls, m)

    scfg = ServingConfig(
        enabled=True, num_slots=num_slots, max_prompt_len=512,
        max_new_tokens=max(budgets), decode_chunk=8,
    )
    groups = ("g0", "g1")

    def run(sched, tag, mon):
        tagged = mon is not None
        reqs = []
        for i, (p, b) in enumerate(workload):
            rid = f"fair_{tag}_{i:04d}"
            reqs.append(Request(
                prompt=p, id=rid, settings=greedy(b),
                group=groups[i % 2] if tagged else None,
                attribute="bench" if tagged else None,
                pair_id=f"fair_{tag}_pp{i // 2:04d}" if tagged else None,
            ))
        if tagged:
            mon.begin_study()
            for r in reqs:
                mon.register_request(r.id, {"bench": r.group})
            for i in range(0, len(reqs) - 1, 2):
                mon.register_pair(f"fair_{tag}_pp{i // 2:04d}",
                                  reqs[i].id, reqs[i + 1].id, "bench")
        t0 = time.perf_counter()
        results = sched.serve(reqs)
        if tagged:
            # The content feed + gauge refresh belong inside the window:
            # a tagged study pays them per chunk.
            for r in results:
                mon.observe_output(r.id, r.text.split())
            mon.refresh()
        wall = time.perf_counter() - t0
        assert all(r.ok for r in results), [
            (r.id, r.finish_reason) for r in results if not r.ok
        ]
        toks = [tuple(int(t) for t in r.tokens) for r in results]
        return wall, toks

    out = {}
    tokens = {}
    for tag, mon in (("off", None), ("on", FairnessMonitor())):
        prev = set_fairness_monitor(mon) if mon is not None else None
        try:
            sched = ContinuousScheduler(engine, scfg,
                                        settings=greedy(max(budgets)))
            run(sched, tag, mon)  # warmup: compile + first DP kernel
            wall, toks = min((run(sched, tag, mon) for _ in range(3)),
                             key=lambda r: r[0])
            tokens[tag] = toks
            total = sum(len(t) for t in toks)
            out[tag] = {
                "wall_s": round(wall, 3),
                "tokens_per_sec": round(total / wall, 1),
            }
            if mon is not None:
                assert mon.pairs_joined == len(workload) // 2, (
                    mon.pairs_joined, len(workload))
                assert mon.pairs_divergent == 0, "divergence on fault-free"
                out[tag]["pairs_joined"] = mon.pairs_joined
        finally:
            if prev is not None:
                set_fairness_monitor(prev)
    # Observation must be output-invariant: every request decodes the same
    # tokens whether or not the fairness layer watched it.
    assert tokens["on"] == tokens["off"], "fairness observation changed output"
    out["overhead_ratio"] = round(
        out["on"]["wall_s"] / out["off"]["wall_s"], 3
    )
    return out


def measure_achievable_gbps() -> float | None:
    """This chip's ACHIEVABLE streaming bandwidth, measured in-run.

    The spec roofline (819 GB/s for v5e) is not what a tunneled chip actually
    serves; docs/PERFORMANCE.md round-2 probes measured ~260-300 GB/s on any
    access pattern. This puts that probe IN the bench (VERDICT r2 item 5) so
    every BENCH_r*.json can say whether decode is at the wall without
    re-deriving the experiment: a fori_loop whose carry feeds each iteration's
    element-wise read (acc-dependent ``minimum`` — loop-invariant code motion
    cannot hoist the re-read), timed with a value-forcing sync.
    """
    if jax.default_backend() != "tpu":
        # ~174 GB of host-memory traffic for a number that means nothing off
        # the chip; the headline then falls back to the spec-roofline fraction.
        return None
    import jax.numpy as jnp
    from jax import lax

    n = 85_000_000  # f32 -> 340 MB, far over any cache tier
    reps = 512  # ~174 GB of traffic (~0.2 s at spec): the tunnel's ~50 ms
    # dispatch+sync latency becomes a <20% CONSERVATIVE bias. No latency
    # subtraction — an over-corrected subtraction once reported above-spec
    # bandwidth, and an under-estimate can't overstate how close decode is
    # to the wall.
    x = jax.device_put(jnp.ones((n,), jnp.float32))

    @jax.jit
    def probe(x, start):
        def body(_, acc):
            return acc + jnp.sum(jnp.minimum(x, acc))

        return lax.fori_loop(0, reps, body, start)

    try:
        float(probe(x, jnp.float32(1e30)))  # compile + warm
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            float(probe(x, jnp.float32(1e30)))  # value-forced sync
            wall = time.perf_counter() - t0
            best = wall if best is None else min(best, wall)
        return reps * x.nbytes / best / 1e9
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"bandwidth probe skipped: {type(e).__name__}: {e}", file=sys.stderr)
        return None


def flash_memory_proof() -> dict | None:
    """Compile-time proof of flash attention's decisive claim: at ~150 ranked
    items (S≈7k) the DENSE prefill's [B, H, S, S] score tensors (~9.2 GB
    each) overflow one v5e chip's HBM — the TPU compiler itself REJECTS the
    program at compile time ("Ran out of memory in memory space hbm", ~18.4 G
    needed of 15.75 G) — while flash streams k/v blocks through VMEM and
    compiles comfortably (docs/PERFORMANCE.md round-2; VERDICT r2 item 6).
    Nothing is executed, so the dense side can't actually OOM the bench.
    TPU-only (flash is a Pallas kernel)."""
    if jax.default_backend() != "tpu":
        return None
    import dataclasses

    import flax.linen as nn
    import jax.numpy as jnp

    from fairness_llm_tpu.models.configs import get_model_config
    from fairness_llm_tpu.models.transformer import Transformer

    import re

    B, S = 4, 7168  # ~150 byte-tokenized ML-1M items per listwise prompt
    cfg = get_model_config("gpt2-small")
    out = {"batch": B, "seq": S}
    try:
        for label, flash in (("dense", False), ("flash", True)):
            c = dataclasses.replace(cfg, max_seq_len=8192, use_flash_attention=flash)
            model = Transformer(c)
            abstract = jax.eval_shape(
                model.init, jax.random.key(0),
                jnp.zeros((1, 8), jnp.int32), jnp.zeros((1, 8), jnp.int32),
            )
            aparams = jax.tree.map(
                lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype),
                nn.meta.unbox(abstract["params"]),
            )

            def fwd(params, tokens, positions, valid):
                # left_padded=True: the engine's layout promise, and the
                # static gate for the Pallas flash path (models/transformer.py
                # _flash_ok) — without it both sides compile dense.
                logits, _ = model.apply(
                    {"params": params}, tokens, positions, valid,
                    last_only=True, left_padded=True,
                )
                return logits

            arg = lambda dt: jax.ShapeDtypeStruct((B, S), dt)  # noqa: E731
            try:
                compiled = (
                    jax.jit(fwd)
                    .lower(aparams, arg(jnp.int32), arg(jnp.int32), arg(jnp.bool_))
                    .compile()
                )
            except Exception as e:  # noqa: BLE001 — compile-OOM is the signal
                msg = str(e)
                if "Ran out of memory" not in msg or "hbm" not in msg:
                    raise
                m = re.search(r"Used ([0-9.]+)G of ([0-9.]+)G hbm", msg)
                out[label] = {
                    "compiles": False,
                    "compile_oom": True,
                    "hbm_needed_gb": float(m.group(1)) if m else None,
                    "hbm_capacity_gb": float(m.group(2)) if m else None,
                }
                continue
            ma = compiled.memory_analysis()
            out[label] = {
                "compiles": True,
                "temp_gb": round(ma.temp_size_in_bytes / 1e9, 2),
                "total_gb": round(
                    (ma.temp_size_in_bytes + ma.argument_size_in_bytes
                     + ma.output_size_in_bytes) / 1e9, 2),
            }
            del compiled
        # The claim holds when dense is compiler-rejected (or needs more than
        # the chip) while flash compiles and fits.
        dense, flash_r = out.get("dense", {}), out.get("flash", {})
        out["proven"] = bool(
            (not dense.get("compiles", True)
             or dense.get("total_gb", 0) > 15.75)
            and flash_r.get("compiles")
            and flash_r.get("total_gb", 1e9) < 15.75
        )
        return out
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"flash memory proof skipped: {type(e).__name__}: {e}", file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
        return out


def int8_70b_fit() -> dict | None:
    """The round-4 capability record: llama3-70b int8 (dequant-in-tile
    weights, ops/quant_matmul.py) fits tp=8 on one v5e-8 slice.

    Two parts: (a) the committed full-model AOT memory analysis — 9.29
    GB/chip vs 15.75 (compiling all 80 layers takes ~4.5 min, so it is not
    re-run per bench; regenerate via ``python tools/prove_70b_int8_fit.py``);
    (b) an IN-RUN lowering check: a 2-layer same-dimensions variant compiled
    by the real v5e TPU compiler against a ``v5e:2x4`` topology descriptor,
    proving every kernel/shard_map/collective the artifact relies on still
    lowers today. TPU-compiler environments only.
    """
    root = os.path.dirname(os.path.abspath(__file__))
    out: dict = {}
    try:
        with open(os.path.join(root, "results", "proofs", "int8_70b_fit.json")) as f:
            out["full_model_committed"] = json.load(f)
    except Exception:  # noqa: BLE001 — artifact optional
        out["full_model_committed"] = None
    try:
        live = _load_tool("prove_70b_int8_fit").prove(num_layers=2)
        out["live_2layer_check"] = {
            "lowering_ok": True,
            "compile_s": live["compile_s"],
            "args_gb_per_chip": live["args_gb_per_chip"],
            "temps_gb_per_chip": live["temps_gb_per_chip"],
        }
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"70B 2-layer lowering check skipped: {type(e).__name__}", file=sys.stderr)
        out["live_2layer_check"] = {
            "lowering_ok": False, "error": f"{type(e).__name__}: {e}"
        }
    return out


def _load_tool(name: str):
    """Import a measurement tool module from tools/ by file path."""
    import importlib.util

    root = os.path.dirname(os.path.abspath(__file__))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(root, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def llama70b_shard_live() -> dict | None:
    """Recurring per-round 70B decode rate (VERDICT r4 item 4): the tp=8
    per-chip shard of llama3-70b-int8 decoded LIVE on this chip —
    tools/measure_70b_shard.py's measurement folded into the bench so a
    regression in the dequant-in-tile path's in-model rate (the round-4
    number: 569 GB/s, at the chip's own bandwidth wall) surfaces in the
    BENCH_r* record automatically instead of going stale in a one-off
    proof. ~2-3 min: 8.9 GB engine init + two decode-length compiles.
    TPU-only.

    This is the collectives-OMITTED emulation: one chip decodes its tp=8
    shard with no neighbors, so the number is an upper bound on the
    per-chip rate. The ``serve_tp`` entry is its cross-check — the same
    serving path over a REAL tp mesh with the all-reduces executed
    (asserted in the compiled HLO) and token parity pinned; when a real
    TPU pod is available, extend serve_tp rather than widening this
    emulation."""
    if jax.default_backend() != "tpu":
        return None
    return _load_tool("measure_70b_shard").run(batch=8, new_tokens=32)


def llama3_8b_live(achievable_gbps) -> dict | None:
    """BASELINE configs[1] — Llama-3-8B — served WHOLE on this chip
    (VERDICT r4 item 1, the first end-to-end >=7B full-model number):
    tools/serve_8b_live.py's phase-1 sweep + phase-2 listwise, int8
    dequant-in-tile weights (~8.6 GB of 15.75). The tool's own probe is
    skipped; the ratio uses THIS run's achievable-bandwidth probe so every
    operating point in the record is measured against the same wall."""
    if jax.default_backend() != "tpu":
        return None
    res = _load_tool("serve_8b_live").run(include_probe=False)
    ph1 = res.get("phase1_sweep")
    if ph1 and achievable_gbps:
        ph1["achievable_hbm_gbps_probe"] = round(achievable_gbps, 1)
        ph1["achieved_over_achievable"] = round(
            ph1["achieved_hbm_gbps"] / achievable_gbps, 3
        )
    return res


def phase2_7b_committed() -> dict | None:
    """Per-model summary of the committed 7B cross-model phase-2 record
    (tools/run_7b_cross_model.py -> results/phase2/phase2_7b_results.json):
    the BASELINE configs[2] set — mistral/qwen2/gemma at 7B, int8 weights —
    evaluated live on the chip. Embedded here (the int8_70b_fit pattern) so
    every BENCH_r* record carries the per-model numbers; the full eval
    (~25 min of engine inits + compiles) is a tool run, not a per-bench
    cost — regenerate with the tool when the serving path changes."""
    root = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(root, "results", "phase2", "phase2_7b_results.json")
    try:
        with open(path) as f:
            rec = json.load(f)
        return {
            "committed": True,
            "metadata": {
                k: rec["metadata"].get(k)
                for k in ("models", "num_items", "num_queries",
                          "num_comparisons", "elapsed_seconds", "device")
            },
            "per_model_perf": rec.get("per_model_perf"),
            "model_fairness": rec.get("comparison", {}).get("model_fairness"),
        }
    except Exception as e:  # noqa: BLE001 — artifact optional, but say so
        print(
            f"phase2_7b committed record unavailable ({type(e).__name__}: {e}); "
            f"regenerate with tools/run_7b_cross_model.py -> {path}",
            file=sys.stderr,
        )
        return None


def measure_prefix_cache(engine, prompts, settings_cls) -> dict | None:
    """Paged KV + radix prefix reuse A/B on the phase-1-shaped sweep
    (ISSUE 10 / ROADMAP item 1).

    The counterfactual prompts are byte-identical except for the trailing
    demographics block, so with ``--paged-kv`` admission should match most
    of every prompt and prefill only the short suffix. Same engine/params,
    same slots, greedy for parity; best-of-3 per mode in one process
    (docs/PERFORMANCE.md methodology — the CPU harness has ±30-60%
    single-run jitter). Reported: profiles/sec off vs on, prefill tokens
    off vs on (the measured reduction), and the radix hit rate — with
    token parity asserted and the ROADMAP >80% hit-rate target asserted
    on the warm-cache timed runs.
    """
    import dataclasses

    from fairness_llm_tpu.config import ServingConfig, default_config
    from fairness_llm_tpu.serving import ContinuousScheduler, Request

    num_slots = max(default_config().decode_batch_size, 1)
    budget = 32  # modest decode: keep prefill visible in the wall

    def greedy(m):
        return _greedy(settings_cls, m)

    scfg = ServingConfig(
        enabled=True, num_slots=num_slots, max_prompt_len=512,
        max_new_tokens=budget, decode_chunk=8,
    )
    pcfg = dataclasses.replace(scfg, paged_kv=True, kv_block_size=32)

    def run(sched, tag, rep):
        reqs = [
            Request(prompt=p, id=f"px_{tag}_{rep}_{i:04d}",
                    settings=greedy(budget))
            for i, p in enumerate(prompts)
        ]
        t0 = time.perf_counter()
        results = sched.serve(reqs)
        wall = time.perf_counter() - t0
        assert all(r.ok for r in results)
        toks = [tuple(int(t) for t in r.tokens) for r in results]
        return wall, toks, sched.last_stats

    out = {"profiles": len(prompts), "num_slots": num_slots,
           "kv_block_size": 32}
    tokens = {}
    for tag, cfg in (("off", scfg), ("on", pcfg)):
        sched = ContinuousScheduler(engine, cfg, settings=greedy(budget))
        run(sched, tag, 0)  # warmup: compiles AND (on) populates the radix
        wall, toks, stats = min((run(sched, tag, rep) for rep in (1, 2, 3)),
                                key=lambda r: r[0])
        tokens[tag] = toks
        out[tag] = {
            "wall_s": round(wall, 3),
            "profiles_per_sec": round(len(prompts) / wall, 2),
            "prefill_tokens": stats.prefill_tokens,
        }
        if tag == "on":
            paged = sched.pool.paged
            out[tag]["hit_ratio"] = round(paged.hit_ratio, 4)
            # The ROADMAP item-1 target, on the workload just decoded.
            assert paged.hit_ratio > 0.8, (
                f"warm-cache hit ratio {paged.hit_ratio:.3f} <= 0.8"
            )
    # Prefix reuse must never change the tokens — the parity contract.
    assert tokens["on"] == tokens["off"], "paged KV changed output"
    out["prefill_token_reduction"] = round(
        1.0 - out["on"]["prefill_tokens"] / max(out["off"]["prefill_tokens"],
                                                1), 4
    )
    out["speedup_ratio"] = round(
        out["off"]["wall_s"] / out["on"]["wall_s"], 3
    )
    return out


def measure_capacity(engine, prompts, settings_cls) -> dict | None:
    """Capacity planning: the SAME seeded trace replayed against fixed
    fleets of 1 -> 3 replicas (ISSUE 11).

    One deterministic synthetic trace (diurnal curve + one burst +
    heavy-tailed sessions + mixed QoS, ``serving/replay.py``) is replayed
    time-compressed at each fleet size, best-of-3 per size in one process
    (CPU-harness ±30-60% single-run jitter, docs/PERFORMANCE.md
    methodology). Reported per size: profiles/sec and profiles/sec/CHIP
    (each replica models one chip's slot pool), interactive TTFT
    attainment against a fixed target, and the shed rate — the table an
    operator reads to pick a fleet size for an offered load. Token parity
    across fleet sizes is asserted on the completed intersection (routing
    and fleet size must never change the tokens)."""
    import dataclasses

    from fairness_llm_tpu.config import (
        FleetConfig,
        IntegrityConfig,
        OverloadConfig,
        ResilienceConfig,
        ServingConfig,
    )
    from fairness_llm_tpu.serving import (
        ReplayDriver,
        ReplicaSet,
        TraceConfig,
        generate_trace,
    )
    from fairness_llm_tpu.telemetry.slo import SLOTargets, set_slo_targets

    compression = 4.0
    ttft_target_s = 2.0
    tcfg = TraceConfig(
        seed=17, duration_s=24.0, base_sessions_per_s=0.8,
        diurnal_amplitude=0.5, diurnal_period_s=24.0,
        bursts=((8.0, 6.0, 5.0),), session_tail_alpha=1.3,
        session_max_turns=3, think_time_s=2.0, interactive_frac=0.75,
        max_tokens_choices=(8, 12, 16),
    )
    catalog = tuple(prompts[:8])
    events = generate_trace(tcfg, catalog)
    budget = max(tcfg.max_tokens_choices)

    def greedy(m):
        return _greedy(settings_cls, m)

    scfg = ServingConfig(
        enabled=True, num_slots=4, queue_capacity=32, max_prompt_len=512,
        max_new_tokens=budget, decode_chunk=8,
    )
    prev_targets = set_slo_targets(SLOTargets(
        ttft_p95_s=ttft_target_s, e2e_p99_s=60.0, fast_window_s=2.0,
    ))
    out = {
        "trace_events": len(events),
        "interactive_events": sum(e.qos == "interactive" for e in events),
        "trace_span_s": round(events[-1].t, 2) if events else 0.0,
        "compression": compression,
        "ttft_target_s": ttft_target_s,
        "capacity": {},
    }
    tokens_by_n = {}
    try:
        for n in (1, 2, 3):
            fleet = ReplicaSet(
                engine, scfg, settings=greedy(budget),
                fleet=FleetConfig(replicas=n, fence_cooldown_s=0.1),
                resilience=ResilienceConfig(enabled=True,
                                            breaker_cooldown_s=0.05),
                integrity=IntegrityConfig(canary_max_tokens=8),
                overload=OverloadConfig(
                    enabled=True, deadline_admission=False,
                    aging_s=5.0 / compression, healthy_window_s=0.5,
                    queue_window_s=1.0, eval_interval_s=0.1,
                    burn_threshold=8.0, retry_after_s=0.2,
                ),
            )
            # Warmup compiles the per-replica programs, then best-of-3.
            # The zero-loss invariant must hold on EVERY run — a discarded
            # slower run (or the warmup) losing requests is still a bug.
            runs = [ReplayDriver(fleet, events, compression=compression,
                                 max_wall_s=300.0).run()
                    for _ in range(4)]
            for k, r in enumerate(runs):
                assert r.lost == 0, \
                    f"replay lost requests at n={n} (run {k})"
            report = min(runs[1:], key=lambda r: r.wall_s)
            completed = report.outcomes.get("completed", 0)
            attain = report.slo_attainment(ttft_target_s)
            out["capacity"][str(n)] = {
                "replicas": n,
                "wall_s": round(report.wall_s, 3),
                "profiles_per_sec": round(completed / report.wall_s, 2),
                "profiles_per_sec_per_chip": round(
                    completed / report.wall_s / n, 2),
                "completed": completed,
                "shed_rate": round(report.shed_rate(), 4),
                "slo_attainment_ttft": (round(attain, 4)
                                        if attain is not None else None),
            }
            tokens_by_n[n] = dict(report.tokens)
    finally:
        set_slo_targets(prev_targets)
    # Fleet size must never change a completed request's tokens.
    common = set(tokens_by_n[1]) & set(tokens_by_n[2]) & set(tokens_by_n[3])
    assert common, "no common completed requests across fleet sizes"
    for rid in common:
        assert tokens_by_n[1][rid] == tokens_by_n[2][rid] == \
            tokens_by_n[3][rid], f"fleet size changed tokens for {rid}"
    out["parity_checked_requests"] = len(common)
    return out


def build_sweep_prompts():
    from fairness_llm_tpu.config import default_config
    from fairness_llm_tpu.data import (
        create_base_preferences,
        create_profile_grid,
        load_movielens,
    )
    from fairness_llm_tpu.pipeline.prompts import recommendation_prompt

    config = default_config()
    data = load_movielens(config.data_dir, seed=config.random_seed)
    prefs = create_base_preferences(data, seed=config.random_seed)
    profiles = create_profile_grid(prefs, config)
    return [recommendation_prompt(p) for p in profiles]


def build_listwise_prompts(num_items: int = 60, num_queries: int = 4):
    """Phase-2 at scale: long listwise ranking prompts (hundreds of items),
    several queries decoded as one batch — the prefill-heavy counterpart to
    the decode-heavy phase-1 sweep. Returns (prompts, items, queries) so the
    scored measurement reuses the SAME corpus and query set (the
    vs_listwise_decode ratio depends on that identity)."""
    from fairness_llm_tpu.config import default_config
    from fairness_llm_tpu.data import load_movielens, movielens_ranking_corpus
    from fairness_llm_tpu.pipeline.phase2 import make_queries
    from fairness_llm_tpu.pipeline.prompts import listwise_prompt

    config = default_config()
    data = load_movielens(config.data_dir, seed=config.random_seed)
    items = movielens_ranking_corpus(data, num_items, seed=config.random_seed, min_ratings=1)
    queries = make_queries(items, num_queries)
    return [listwise_prompt(items, query=q) for q in queries], items, queries


def measure_phase2_listwise(config, settings_cls) -> dict | None:
    """Queries/sec for the long-prompt listwise batch, flash vs dense prefill.

    The phase-1 sweep is decode-bound (prefill is an amortized sliver), so the
    flash prefill kernel doesn't move that number; THIS workload is where it
    runs in a headline path. gpt2-small's 1024 learned positions can't hold a
    60-item byte-tokenized prompt (~2.5k tokens); the bench widens the table
    (random weights — FLOPs and memory traffic are representative either way).
    Corpus size is capped so the DENSE comparison's [B, H, S, S] score tensor
    stays well under chip HBM; flash itself scales much further.
    """
    import dataclasses

    from fairness_llm_tpu.runtime.engine import DecodeEngine

    prompts, items, queries = build_listwise_prompts()
    num_items = len(items)
    long_cfg = dataclasses.replace(config, max_seq_len=4096, kv_cache_quant=False)
    settings = settings_cls(temperature=0.7, top_k=0, top_p=1.0, max_tokens=32)

    out = {}
    # Dense first so the flash engine survives the loop: the scored
    # measurement below reuses it rather than compiling a third engine
    # (which pushed the whole bench past its time budget).
    eng = None
    for label, flash in (("dense", False), ("flash", True)):
        if eng is not None:
            del eng
        eng = DecodeEngine(
            dataclasses.replace(long_cfg, use_flash_attention=flash), seed=0
        )
        # share_prefix=False: the listwise prompts share an auto-detectable
        # ~64-token prefix, and the shared-prefix prefill takes the joint
        # dense path — WITH sharing enabled the "flash" engine never runs
        # the flash kernel at all (discovered round 4: both columns were
        # measuring the same dense program).
        res = eng.generate(prompts, settings, seed=0, share_prefix=False)  # warmup
        t0 = time.perf_counter()
        res = eng.generate(prompts, settings, seed=1, share_prefix=False)
        jax.block_until_ready(res.tokens)
        wall = time.perf_counter() - t0
        out[label] = {
            "wall_s": round(wall, 3),
            "queries_per_sec": round(len(prompts) / wall, 3),
            "decode_shape": res.stats,
        }
    out["num_items"] = num_items
    out["num_queries"] = len(prompts)
    out["flash_speedup"] = round(out["dense"]["wall_s"] / out["flash"]["wall_s"], 3)

    # Likelihood-scored ranking over the SAME corpus and queries: all
    # (query, item) pairs score as chunked teacher-forced forwards (no
    # autoregressive decode, no parsing).
    from fairness_llm_tpu.pipeline.backends import EngineBackend
    from fairness_llm_tpu.pipeline.phase2 import scored_evaluation

    backend = EngineBackend(eng, name="bench")
    scored_evaluation(backend, items, queries)  # warmup/compile
    t0 = time.perf_counter()
    scored_evaluation(backend, items, queries)
    wall = time.perf_counter() - t0
    out["scored"] = {
        "wall_s": round(wall, 3),
        "queries_per_sec": round(len(queries) / wall, 3),
        # same query count as the listwise measurement -> direct wall ratio
        "vs_listwise_decode": round(out["flash"]["wall_s"] / max(wall, 1e-9), 2),
    }

    # Pairwise at scale: 200 comparisons over the same ML-1M corpus decoded
    # as ONE batch — the reference's pairwise hot loop
    # (phase2_cross_model_eval.py:165-210, 30 sequential API calls) at 6.7x
    # its comparison budget, the last reference hot loop without an at-scale
    # live number (VERDICT r4 weak item 3). Short decode cap: the answer is
    # one letter; 16 tokens is the reference-compatible envelope.
    try:
        from fairness_llm_tpu.pipeline.phase2 import pairwise_evaluation

        pw_settings = settings_cls(
            temperature=0.7, top_k=0, top_p=1.0, max_tokens=16
        )
        # Warm with the SAME seed as the timed run: the seed picks the item
        # pairs, so a different seed could sample longer prompts that cross
        # a bucket boundary and put a fresh compile inside the timed window.
        pairwise_evaluation(backend, items, 200, pw_settings, seed=1)  # compile
        t0 = time.perf_counter()
        _, comps = pairwise_evaluation(backend, items, 200, pw_settings, seed=1)
        wall = time.perf_counter() - t0
        unparsed = sum(1 for c in comps if not c["parsed"])
        out["pairwise_200"] = {
            "num_comparisons": len(comps),
            "wall_s": round(wall, 3),
            "comparisons_per_sec": round(len(comps) / wall, 2),
            # random weights parse poorly; the rate is the honest field the
            # study reports either way (parse_failures in phase2 results)
            "parse_failure_rate": round(unparsed / max(len(comps), 1), 3),
        }
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"pairwise-200 skipped: {type(e).__name__}: {e}", file=sys.stderr)
    del eng

    # 150-item listwise (S≈7k): the corpus size DENSE attention provably
    # cannot serve at all on this chip (flash_memory_proof: compile-OOM at
    # 18.4 GB of score temps) — so this runs flash-ONLY, live, turning the
    # compile-time capability claim into a measured number. TPU-only: the
    # Pallas path is the enabler being measured.
    if jax.default_backend() == "tpu" and config.head_dim % 64 == 0:
        try:
            big_prompts, big_items, big_queries = build_listwise_prompts(150, 4)
            cfg7k = dataclasses.replace(
                config, max_seq_len=8192, use_flash_attention=True,
                kv_cache_quant=False,
            )
            eng7k = DecodeEngine(cfg7k, seed=0)
            try:
                # share_prefix=False: flash-only by necessity — the shared-
                # prefix joint path is dense, which compile-OOMs at this S
                res = eng7k.generate(
                    big_prompts, settings, seed=0, share_prefix=False
                )  # compile
                t0 = time.perf_counter()
                res = eng7k.generate(big_prompts, settings, seed=1, share_prefix=False)
                jax.block_until_ready(res.tokens)
                wall = time.perf_counter() - t0
                out["listwise_150_flash_only"] = {
                    "num_items": len(big_items),
                    "num_queries": len(big_prompts),
                    "wall_s": round(wall, 3),
                    "queries_per_sec": round(len(big_prompts) / wall, 3),
                    "decode_shape": res.stats,
                    "dense_alternative": "compile-OOM (see flash_memory_proof)",
                }
            finally:
                del eng7k
        except Exception as e:  # noqa: BLE001 — auxiliary measurement only
            print(
                f"150-item listwise skipped: {type(e).__name__}: {e}",
                file=sys.stderr,
            )
    return out


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--entries", default=os.environ.get("BENCH_ENTRIES"),
                    help="comma-separated subset of auxiliary entries to "
                         f"run (default: all). Choices: {', '.join(_ALL_ENTRIES)}")
    ap.add_argument("--baseline-out",
                    default=os.environ.get("BENCH_BASELINE_OUT"),
                    help="also write the machine-readable perf-sentinel "
                         "baseline (entries + harness fingerprint) here")
    args = ap.parse_args()
    if args.entries:
        set_entries([e.strip() for e in args.entries.split(",") if e.strip()])
    # The tunneled TPU occasionally drops one remote_compile mid-run
    # ("response body closed" / HTTP 500); one retry with fresh engines
    # recovers it. The driver runs this file ONCE per round — losing the
    # round's benchmark record to a transient is worse than the retry's cost.
    # Loop (not retry-inside-except): leaving the except block clears the
    # failed attempt's traceback, releasing the frames that pin the dead
    # engine's HBM buffers before attempt 2 allocates fresh ones.
    for attempt in (1, 2):
        try:
            _run(baseline_out=args.baseline_out)
            return
        except Exception as e:  # noqa: BLE001 — transient-tunnel retry
            if attempt == 2:
                raise
            print(f"bench attempt 1 failed ({type(e).__name__}: {e}); retrying once",
                  file=sys.stderr)


def _run(baseline_out: "str | None" = None) -> None:
    from fairness_llm_tpu.config import ModelSettings
    from fairness_llm_tpu.models.configs import get_model_config
    from fairness_llm_tpu.runtime.engine import DecodeEngine

    model_name = os.environ.get("BENCH_MODEL", "gpt2-small")
    config = get_model_config(model_name)
    if os.environ.get("BENCH_KV_QUANT") == "1":
        # int8 KV cache: the capacity lever that fits 3B-class models' caches
        # on one chip (see models/configs.py kv_cache_quant).
        import dataclasses

        config = dataclasses.replace(config, kv_cache_quant=True)
        model_name += "+int8kv"
    prompts = build_sweep_prompts()
    settings = ModelSettings(temperature=0.7, top_k=0, top_p=1.0, max_tokens=MAX_NEW_TOKENS)

    devices = jax.devices()
    engine = DecodeEngine(config, seed=0)

    # Warmup: compile prefill+decode for the sweep's bucketed shape.
    engine.generate(prompts, settings, seed=0)

    # Timed runs.
    times = []
    token_checksum = None
    for rep in range(3):
        t0 = time.perf_counter()
        out = engine.generate(prompts, settings, seed=rep + 1)
        jax.block_until_ready(out.tokens)
        times.append(time.perf_counter() - t0)
        if rep == 0:
            # Token-parity witness for the perf sentinel: the seed-1 sweep
            # is deterministic on one harness fingerprint, so a checksum
            # drift is a correctness regression (compared EXACTLY), unlike
            # the walls (compared within noise bands).
            import hashlib

            token_checksum = hashlib.sha256(
                out.tokens.tobytes()
            ).hexdigest()[:16]

    # Fused decode-attention kernel A/B on the same sweep (measured slower —
    # kept in the record so the regression/improvement trend is visible per
    # round; see docs/PERFORMANCE.md round 3 and ops/decode_attention.py).
    kernel_rate = None
    try:
        from fairness_llm_tpu.ops.decode_attention import decode_attn_supported

        # Only measure when the kernel would actually ENGAGE at this sweep's
        # shapes (same gate as the model) — otherwise the flag-on engine runs
        # the identical XLA path and the record would mislabel a baseline
        # rate as the kernel's.
        eligible = (
            not config.use_decode_attention_kernel
            and jax.default_backend() == "tpu"
            and jax.device_count() == 1
            and config.sliding_window is None
            and not config.kv_cache_quant
            and decode_attn_supported(
                out.stats["batch"], out.stats["cache_slots"],
                config.head_dim, out.stats["prefix_len"],
            )
        )
        if eligible:
            import dataclasses

            ek = DecodeEngine(
                dataclasses.replace(config, use_decode_attention_kernel=True),
                seed=0,
            )
            try:
                ek.generate(prompts, settings, seed=0)
                t0 = time.perf_counter()
                outk = ek.generate(prompts, settings, seed=1)
                jax.block_until_ready(outk.tokens)
                kernel_rate = len(prompts) / (time.perf_counter() - t0)
            finally:
                # release the duplicate weights even if generate() throws —
                # the large-sweep measurement below is already OOM-prone
                del ek
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"decode-kernel A/B skipped: {type(e).__name__}", file=sys.stderr)

    # Speculative decoding A/B on the same sweep, greedy (ISSUE 1): off vs on
    # tokens/sec plus measured acceptance. Runs while the headline engine is
    # alive (it reuses the params; only two more compiled programs).
    speculative = None
    try:
        if _enabled("speculative"):
            speculative = measure_speculative(engine, prompts, ModelSettings)
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"speculative A/B skipped: {type(e).__name__}: {e}", file=sys.stderr)

    # Continuous-batching serving A/B (ISSUE 2): static chunking vs the
    # serving/ scheduler on a mixed-length workload, same engine/params.
    continuous = None
    try:
        if _enabled("continuous"):
            continuous = measure_continuous(engine, prompts, ModelSettings)
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"continuous serving A/B skipped: {type(e).__name__}: {e}",
              file=sys.stderr)

    # Resilience overhead guard (ISSUE 4): fault-free continuous serving
    # with the watchdog+breakers off vs on — the on/off wall ratio must
    # stay within harness noise (docs/PERFORMANCE.md).
    resilience = None
    try:
        if _enabled("resilience"):
            resilience = measure_resilience_overhead(engine, prompts,
                                                     ModelSettings)
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"resilience overhead A/B skipped: {type(e).__name__}: {e}",
              file=sys.stderr)

    # Integrity overhead guard (ISSUE 5): fault-free continuous serving
    # with the on-device numerics guards off vs on — the in-program finite
    # reduction must stay within harness noise, and the tokens identical.
    integrity = None
    try:
        if _enabled("integrity"):
            integrity = measure_integrity_overhead(engine, prompts,
                                                   ModelSettings)
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"integrity overhead A/B skipped: {type(e).__name__}: {e}",
              file=sys.stderr)

    # Attribution-layer overhead guard (ISSUE 7): fault-free continuous
    # serving with the timeline/compile-stats/roofline/SLO layer off vs on
    # — the on/off wall ratio must stay within harness noise, tokens
    # identical; the on mode reports step_gap_s p50/p95 next to tokens/sec.
    profiling = None
    try:
        if _enabled("profiling"):
            profiling = measure_profiling_overhead(engine, prompts,
                                                   ModelSettings)
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"profiling overhead A/B skipped: {type(e).__name__}: {e}",
              file=sys.stderr)

    # Fused multi-step dispatch sweep (ISSUE 14): fuse_steps k in
    # {1,2,4,8} on the same mixed workload — host gap per token must fall
    # ~1/k at exact token parity; reports step_gap_s p50/p95 and
    # achieved_over_achievable per k.
    fused_decode = None
    try:
        if _enabled("fused_decode"):
            fused_decode = measure_fused_decode(engine, prompts,
                                                ModelSettings)
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"fused decode sweep skipped: {type(e).__name__}: {e}",
              file=sys.stderr)

    # Real-mesh tp=2 serving (subprocess; parity + executed collectives
    # asserted inside the worker). Cross-checks the llama70b_shard entry's
    # collectives-OMITTED per-chip emulation with a measurement where the
    # collectives are on the wire.
    serve_tp = None
    try:
        if _enabled("serve_tp"):
            serve_tp = measure_serve_tp()
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"serve_tp skipped: {type(e).__name__}: {e}", file=sys.stderr)

    # Incident-layer overhead guard (ISSUE 13): fault-free continuous
    # serving with the flight recorder + decision audit trail off vs on —
    # within harness noise, token parity asserted, zero bundles (no
    # manager armed).
    incidents = None
    try:
        if _enabled("incidents"):
            incidents = measure_incident_overhead(engine, prompts,
                                                  ModelSettings)
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"incident overhead A/B skipped: {type(e).__name__}: {e}",
              file=sys.stderr)

    # Memory-ledger overhead guard (ISSUE 18): fault-free continuous
    # serving with the HBM pool accounting + AOT program-memory capture
    # off vs on — within harness noise, token parity asserted, zero
    # reconciliation alerts.
    memory = None
    try:
        if _enabled("memory"):
            memory = measure_memory_overhead(engine, prompts,
                                             ModelSettings)
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"memory overhead A/B skipped: {type(e).__name__}: {e}",
              file=sys.stderr)

    # Armed-idle rollout overhead guard (PR 20): 2-replica fleet with a
    # constructed-but-idle RolloutController vs none — within harness
    # noise, token parity asserted, zero transitions recorded.
    rollout = None
    try:
        if _enabled("rollout"):
            rollout = measure_rollout_overhead(engine, prompts,
                                               ModelSettings)
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"rollout overhead A/B skipped: {type(e).__name__}: {e}",
              file=sys.stderr)

    # Replica-fleet A/B (ISSUE 6): 2-replica health-routed fleet vs a
    # single scheduler at the same total slot count (router overhead must
    # stay within harness noise), plus failover recovery time under an
    # injected replica crash (fence -> first migrated token).
    fleet = None
    try:
        if _enabled("fleet"):
            fleet = measure_fleet(engine, prompts, ModelSettings)
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"fleet A/B skipped: {type(e).__name__}: {e}",
              file=sys.stderr)

    # Overload-control overhead guard (ISSUE 8): fault-free, under-capacity
    # mixed-class serving with the QoS queue + shed controller off vs on —
    # within harness noise, token parity across classes, zero sheds, and
    # the controller pinned at level 0 throughout.
    overload = None
    try:
        if _enabled("overload"):
            overload = measure_overload_overhead(engine, prompts,
                                                 ModelSettings)
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"overload overhead A/B skipped: {type(e).__name__}: {e}",
              file=sys.stderr)

    # Fairness-observability overhead guard (ISSUE 9): fault-free
    # continuous serving with tagging + streaming accumulators + pair
    # watch off vs on — within harness noise, token parity asserted, every
    # pair joined with zero divergence.
    fairness = None
    try:
        if _enabled("fairness"):
            fairness = measure_fairness_overhead(engine, prompts,
                                                 ModelSettings)
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"fairness overhead A/B skipped: {type(e).__name__}: {e}",
              file=sys.stderr)

    # Paged-KV prefix-cache A/B (ISSUE 10): the phase-1-shaped sweep with
    # private-row slots vs the paged radix-indexed arena — profiles/sec,
    # measured prefill-token reduction, and the hit rate, parity asserted.
    prefix_cache = None
    try:
        if _enabled("prefix_cache"):
            prefix_cache = measure_prefix_cache(engine, prompts,
                                                ModelSettings)
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"prefix cache A/B skipped: {type(e).__name__}: {e}",
              file=sys.stderr)

    # Capacity planning (ISSUE 11): one seeded synthetic trace replayed
    # against 1/2/3-replica fleets — profiles/sec/chip vs interactive SLO
    # attainment vs shed rate, token parity across sizes asserted.
    capacity = None
    try:
        if _enabled("capacity"):
            capacity = measure_capacity(engine, prompts, ModelSettings)
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"capacity sweep skipped: {type(e).__name__}: {e}",
              file=sys.stderr)

    # Large-sweep throughput: decode is weight-streaming-bound at small batch,
    # so a thousands-of-profiles ML-1M sweep runs at the batch-192 rate
    # instead. Big models can OOM at this batch on one chip — report null
    # rather than failing the whole benchmark. Each operating point carries
    # its own roofline fields (bytes/step, achieved GB/s) so the efficiency
    # fraction at the BEST point is a measured number, not just the worst
    # (45-profile) one.
    big_rate = None
    big_stats = None
    big_rate_int8 = None
    big8_stats = None
    big_rate_int8_kernel = None
    grouped_rate_int8 = None
    grouped_shapes = None
    big_rate_int8w = None
    big8w_stats = None
    try:
        _require_entry("large_sweep")
        big = list(prompts) * 4
        engine.generate(big, settings, seed=0)
        t0 = time.perf_counter()
        out_big = engine.generate(big, settings, seed=99)
        jax.block_until_ready(out_big.tokens)
        big_rate = len(big) / (time.perf_counter() - t0)
        big_stats = out_big.stats

        # int8 KV at 2x that scale: at large batch the decode is KV-bound,
        # so the quantized cache both fits more rows AND runs faster — the
        # sweet spot measured on v5e is ~360 rows (328 profiles/s, +50% over
        # the f32 batch-180 rate; 720 rows adds only ~5% more).
        import dataclasses

        if not config.kv_cache_quant:
            big8 = list(prompts) * 8
            cfg8 = dataclasses.replace(config, kv_cache_quant=True)
            eng8 = DecodeEngine(cfg8, seed=0)
            eng8.generate(big8, settings, seed=0)
            t0 = time.perf_counter()
            out8 = eng8.generate(big8, settings, seed=99)
            jax.block_until_ready(out8.tokens)
            big_rate_int8 = len(big8) / (time.perf_counter() - t0)
            big8_stats = out8.stats

            # LEVER A (VERDICT r4 weak item 1): remainder-length grouping.
            # The single-bucket batch pads every row's remainder to the
            # longest profile's bucket; decoding short-remainder and
            # long-remainder halves as two programs tightens each group's
            # prompt_len (32-multiple buckets) at the cost of streaming the
            # weight tree twice. Both halves pass the SAME sweep-wide
            # explicit prefix so attention layout matches the baseline.
            try:
                from fairness_llm_tpu.pipeline.backends import (
                    EngineBackend,
                    shared_prefix_ids,
                )

                pref = shared_prefix_ids(EngineBackend(eng8), big8)
                if pref is not None:
                    rows = [eng8.tokenizer.encode(p) for p in big8]
                    order = sorted(range(len(big8)), key=lambda i: len(rows[i]))
                    half = (len(big8) // 2) // 8 * 8
                    gs = [
                        [big8[i] for i in order[:half]],
                        [big8[i] for i in order[half:]],
                    ]
                    for g in gs:  # compile both shapes
                        eng8.generate(g, settings, seed=0, prefix_ids=pref)
                    t0 = time.perf_counter()
                    shapes, outs = [], []
                    for g in gs:
                        og = eng8.generate(g, settings, seed=99, prefix_ids=pref)
                        shapes.append(og.stats)
                        outs.append(og.tokens)
                    # Block on EVERY group's tokens: on a mesh/multi-device
                    # run the first group's work may still be in flight
                    # when the last call returns.
                    jax.block_until_ready(outs)
                    grouped_rate_int8 = len(big8) / (time.perf_counter() - t0)
                    grouped_shapes = shapes
            except Exception as e:  # noqa: BLE001 — auxiliary measurement only
                print(f"grouped-sweep skipped: {type(e).__name__}", file=sys.stderr)
            del eng8

            # LEVER B (same verdict item): int8 WEIGHTS x int8 KV at the
            # 360-row sweet spot — best_sustained has always streamed
            # f32/bf16 weights; the dequant-in-tile tree cuts the per-step
            # param stream (gpt2's tied embedding stays float, so the win
            # is bounded by the non-embed fraction).
            if config.weight_quant == "none":
                # Local try: a failure here (batch-360 is OOM-prone on big
                # models) must not abort the int8-KV kernel A/B below, whose
                # per-round trend predates this lever.
                try:
                    cfg8w = dataclasses.replace(cfg8, weight_quant="int8")
                    eng8w = DecodeEngine(cfg8w, seed=0)
                    try:
                        eng8w.generate(big8, settings, seed=0)
                        t0 = time.perf_counter()
                        out8w = eng8w.generate(big8, settings, seed=99)
                        jax.block_until_ready(out8w.tokens)
                        big_rate_int8w = len(big8) / (time.perf_counter() - t0)
                        big8w_stats = out8w.stats
                    finally:
                        del eng8w
                except Exception as e:  # noqa: BLE001 — auxiliary measurement
                    print(
                        f"int8w-sweep skipped: {type(e).__name__}", file=sys.stderr
                    )

            # Fused int8-KV decode-attention kernel (dequant-in-tile,
            # ops/decode_attention.py round 4) A/B at the KV-bound operating
            # point — the one kernel target with a byte-reduction story.
            from fairness_llm_tpu.ops.decode_attention import decode_attn_supported

            if (
                jax.default_backend() == "tpu"
                and jax.device_count() == 1
                and config.sliding_window is None
                and decode_attn_supported(
                    big8_stats["batch"], big8_stats["cache_slots"],
                    config.head_dim, big8_stats["prefix_len"], kv_itemsize=1,
                )
            ):
                eng8k = DecodeEngine(
                    dataclasses.replace(cfg8, use_decode_attention_kernel=True),
                    seed=0,
                )
                try:
                    eng8k.generate(big8, settings, seed=0)
                    t0 = time.perf_counter()
                    out8k = eng8k.generate(big8, settings, seed=99)
                    jax.block_until_ready(out8k.tokens)
                    big_rate_int8_kernel = len(big8) / (time.perf_counter() - t0)
                finally:
                    del eng8k
    except _SkippedEntry:
        pass
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"large-sweep measurement skipped: {type(e).__name__}", file=sys.stderr)

    # Roofline accounting: achieved bandwidth over the analytic bytes/step,
    # reported against the chip's measured achievable bandwidth. Random
    # weights never sample EOS, so the early-exit while_loop runs the full
    # MAX_NEW_TOKENS steps and steps-executed == the cap (real models exit
    # early and the bytes model would overcount). Params count at the
    # COMPUTE width (see decode_step_bytes — the loop streams bf16 slices
    # even for f32-stored trees).
    best = min(times)
    profiles_per_sec = len(prompts) / best  # single chip: total == per-chip
    tokens_per_sec = len(prompts) * MAX_NEW_TOKENS / best
    sweep_stats = out.stats
    step_bytes = decode_step_bytes(config, sweep_stats)
    achieved_gbps = step_bytes * MAX_NEW_TOKENS / best / 1e9

    # Free the phase-1 engine (params + compiled big-batch caches) before the
    # long-context engines spin up — at 1B/3B scale keeping it alive OOMs the
    # auxiliary measurement.
    del engine, out
    achievable_gbps = measure_achievable_gbps()
    phase2_listwise = None
    if _enabled("phase2_listwise"):
        for attempt in (1, 2):  # transient tunnel drops cost one compile; retry once
            try:
                phase2_listwise = measure_phase2_listwise(config, ModelSettings)
                break
            except Exception as e:  # noqa: BLE001 — auxiliary measurement only
                print(
                    f"phase2-listwise attempt {attempt} failed: {type(e).__name__}: {e}",
                    file=sys.stderr,
                )
    flash_proof = flash_memory_proof() if _enabled("flash_proof") else None
    int8_70b = int8_70b_fit() if _enabled("int8_70b") else None

    # Big-model live sections (each owns most of HBM; they run only after
    # every other engine is freed, serially). Fail-soft: a tunnel drop loses
    # the section, not the round's record.
    shard70b = None
    try:
        if _enabled("shard70b"):
            shard70b = llama70b_shard_live()
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"70B shard live skipped: {type(e).__name__}: {e}", file=sys.stderr)
    live8b = None
    try:
        if _enabled("live8b"):
            live8b = llama3_8b_live(achievable_gbps)
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"8B live skipped: {type(e).__name__}: {e}", file=sys.stderr)

    # Roofline accounting per operating point: the headline (45 profiles,
    # the framework's WORST sustained number) plus each large-sweep point,
    # so "is decode efficient at scale" is answered where it's best.
    import dataclasses as _dc

    def roofline(cfg_for, stats_for, rate, n_profiles):
        if not (stats_for and rate):
            return None
        sb = decode_step_bytes(cfg_for, stats_for)
        gbps = sb * MAX_NEW_TOKENS * rate / n_profiles / 1e9
        return {
            "profiles_per_sec": round(rate, 2),
            "decode_shape": stats_for,
            "decode_bytes_per_step_mb": round(sb / 1e6, 1),
            "achieved_hbm_gbps": round(gbps, 1),
            "achieved_over_achievable": (
                round(gbps / achievable_gbps, 3) if achievable_gbps else None
            ),
        }

    large_sweep = roofline(config, big_stats, big_rate, len(prompts) * 4)
    cfg_int8 = _dc.replace(config, kv_cache_quant=True)
    large_sweep_int8 = roofline(cfg_int8, big8_stats, big_rate_int8, len(prompts) * 8)
    if large_sweep_int8 is not None:
        large_sweep_int8["kernel_profiles_per_sec"] = (
            round(big_rate_int8_kernel, 2) if big_rate_int8_kernel else None
        )
        if big_rate_int8_kernel:
            large_sweep_int8["kernel_speedup"] = round(
                big_rate_int8_kernel / big_rate_int8, 3
            )
        # Lever A record: remainder-length grouping A/B at the same rows.
        if grouped_rate_int8:
            large_sweep_int8["grouped_profiles_per_sec"] = round(
                grouped_rate_int8, 2
            )
            large_sweep_int8["grouped_speedup"] = round(
                grouped_rate_int8 / big_rate_int8, 3
            )
            large_sweep_int8["grouped_shapes"] = grouped_shapes
    # Lever B record: int8 weights UNDER the int8-KV operating point.
    cfg_int8w = _dc.replace(config, kv_cache_quant=True, weight_quant="int8")
    large_sweep_int8w = roofline(
        cfg_int8w, big8w_stats, big_rate_int8w, len(prompts) * 8
    )
    if large_sweep_int8w is not None and big_rate_int8:
        large_sweep_int8w["vs_float_weights"] = round(
            big_rate_int8w / big_rate_int8, 3
        )
    candidates = [
        ("base", roofline(config, sweep_stats, profiles_per_sec, len(prompts))),
        ("large_sweep", large_sweep),
        ("large_sweep_int8kv", large_sweep_int8),
        ("large_sweep_int8w_int8kv", large_sweep_int8w),
    ]
    if big_rate_int8_kernel and big8_stats:
        candidates.append(
            ("large_sweep_int8kv_kernel",
             roofline(cfg_int8, big8_stats, big_rate_int8_kernel, len(prompts) * 8))
        )
    if grouped_rate_int8 and grouped_shapes:
        # The grouped point streams DIFFERENT bytes than the single-program
        # batch (weight tree twice, tighter per-half KV), so its bandwidth
        # fields are computed from the halves' own shapes — best_sustained
        # must never carry roofline numbers for bytes it didn't stream.
        g_bytes = sum(
            decode_step_bytes(cfg_int8, s) * MAX_NEW_TOKENS for s in grouped_shapes
        )
        g_wall = len(prompts) * 8 / grouped_rate_int8
        g_gbps = g_bytes / g_wall / 1e9
        candidates.append(
            ("large_sweep_int8kv_grouped", {
                "profiles_per_sec": round(grouped_rate_int8, 2),
                "decode_shape": grouped_shapes,
                "decode_bytes_per_step_mb": [
                    round(decode_step_bytes(cfg_int8, s) / 1e6, 1)
                    for s in grouped_shapes
                ],
                "achieved_hbm_gbps": round(g_gbps, 1),
                "achieved_over_achievable": (
                    round(g_gbps / achievable_gbps, 3) if achievable_gbps else None
                ),
            })
        )
    best_label, best_point = max(
        (c for c in candidates if c[1]),
        key=lambda c: c[1]["profiles_per_sec"],
        default=(None, None),
    )

    # Headline comparison: achieved decode bandwidth over this chip's MEASURED
    # achievable bandwidth (the honest "are we at the wall" number — VERDICT
    # r2 item 8). The reference-API speedup multiple (a strawman: 45 profiles
    # / ~15 min of HTTPS round-trips) is kept as a detail field.
    achieved_over_achievable = (
        round(achieved_gbps / achievable_gbps, 3) if achievable_gbps else None
    )
    result = {
        "metric": f"phase1_sweep_decode_throughput[{model_name},{devices[0].platform}]",
        "value": round(profiles_per_sec, 3),
        "unit": "profiles/sec/chip",
        # vs_baseline changed meaning in round 3 (was: speedup multiple over
        # the reference API sweep; now: bandwidth-utilization fraction).
        # schema_version + the explicitly-named duplicate keys exist so
        # cross-round tooling can't silently compare incompatible numbers.
        "schema_version": 2,
        "vs_baseline_kind": "bandwidth_utilization_fraction",
        "vs_baseline": (
            achieved_over_achievable
            if achieved_over_achievable is not None
            else round(achieved_gbps / V5E_HBM_GBPS, 3)
        ),
        "baseline": (
            "fraction of this chip's measured achievable HBM streaming "
            "bandwidth (1.0 = decode at the wall); API-sweep multiple in "
            "detail.vs_reference_api_sweep"
            if achieved_over_achievable is not None
            else "fraction of the 819 GB/s v5e SPEC roofline (bandwidth probe "
                 "failed this run); API-sweep multiple in "
                 "detail.vs_reference_api_sweep"
        ),
        "detail": {
            "profiles": len(prompts),
            "max_new_tokens": MAX_NEW_TOKENS,
            "decode_tokens_per_sec": round(tokens_per_sec, 1),
            "token_checksum": token_checksum,
            "best_wall_s": round(best, 3),
            "all_wall_s": [round(t, 3) for t in times],
            "decode_shape": sweep_stats,
            "decode_bytes_per_step_mb": round(step_bytes / 1e6, 1),
            "achieved_hbm_gbps": round(achieved_gbps, 1),
            "achievable_hbm_gbps_probe": (
                round(achievable_gbps, 1) if achievable_gbps else None
            ),
            "achieved_over_achievable": achieved_over_achievable,
            "pct_v5e_hbm_roofline": round(100 * achieved_gbps / V5E_HBM_GBPS, 1),
            "vs_reference_api_sweep": round(
                profiles_per_sec / REFERENCE_PROFILES_PER_SEC, 1
            ),
            "decode_attention_kernel_profiles_per_sec": (
                round(kernel_rate, 3) if kernel_rate else None
            ),
            "large_sweep_profiles_per_sec": round(big_rate, 3) if big_rate else None,
            "large_sweep_int8kv_profiles_per_sec": (
                round(big_rate_int8, 3) if big_rate_int8 else None
            ),
            "speculative": speculative,
            "continuous": continuous,
            "resilience_overhead": resilience,
            "integrity_overhead": integrity,
            "profiling_overhead": profiling,
            "fused_decode": fused_decode,
            "serve_tp": serve_tp,
            "incident_overhead": incidents,
            "memory_overhead": memory,
            "rollout_overhead": rollout,
            "fleet": fleet,
            "overload_overhead": overload,
            "fairness_overhead": fairness,
            "prefix_cache": prefix_cache,
            "capacity": capacity,
            "large_sweep": large_sweep,
            "large_sweep_int8kv": large_sweep_int8,
            "large_sweep_int8w_int8kv": large_sweep_int8w,
            "best_sustained": (
                {"operating_point": best_label, **best_point} if best_point else None
            ),
            "phase2_listwise": phase2_listwise,
            "flash_memory_proof": flash_proof,
            "int8_70b_fit": int8_70b,
            "llama70b_shard": shard70b,
            "llama3_8b_live": live8b,
            "phase2_7b": phase2_7b_committed(),
            "reference_api_baseline": (
                "reference README: ~15 min for the 45-profile sweep via API "
                "(what vs_reference_api_sweep is measured against)"
            ),
        },
    }
    print(json.dumps(result))
    if baseline_out:
        path = write_bench_baseline(result, baseline_out, model_name)
        print(f"bench baseline: {path}", file=sys.stderr)


if __name__ == "__main__":
    main()

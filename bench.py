"""Benchmark: phase-1 recommendation-sweep decode throughput per chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

What it measures: the end-to-end hot path of the study — the 45-profile
counterfactual prompt sweep (SURVEY.md §3.2 hot loop) — as batched
autoregressive decode on the local accelerator: tokenize -> left-pad ->
prefill -> 128 scan decode steps -> detokenize. Model is gpt2-small
(BASELINE.json configs[0]) with randomly initialized bf16 weights — weight
values don't change FLOPs or memory traffic, so throughput is representative
while requiring no checkpoint download.

Baseline: the reference runs the same sweep as sequential OpenAI API calls —
~15 min for 45 profiles per its README runtime estimate (SURVEY.md §6), i.e.
0.05 profiles/sec. ``vs_baseline`` is the speedup over that.

Run: python bench.py          (uses the default backend — TPU when present)
     BENCH_MODEL=tiny-test python bench.py   (smoke on CPU)
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax


REFERENCE_PROFILES_PER_SEC = 45 / (15 * 60)  # README estimate: 45 profiles / ~15 min
MAX_NEW_TOKENS = 128


def build_sweep_prompts():
    from fairness_llm_tpu.config import default_config
    from fairness_llm_tpu.data import (
        create_base_preferences,
        create_profile_grid,
        load_movielens,
    )
    from fairness_llm_tpu.pipeline.prompts import recommendation_prompt

    config = default_config()
    data = load_movielens(config.data_dir, seed=config.random_seed)
    prefs = create_base_preferences(data, seed=config.random_seed)
    profiles = create_profile_grid(prefs, config)
    return [recommendation_prompt(p) for p in profiles]


def main() -> None:
    from fairness_llm_tpu.config import ModelSettings
    from fairness_llm_tpu.models.configs import get_model_config
    from fairness_llm_tpu.runtime.engine import DecodeEngine

    model_name = os.environ.get("BENCH_MODEL", "gpt2-small")
    config = get_model_config(model_name)
    if os.environ.get("BENCH_KV_QUANT") == "1":
        # int8 KV cache: the capacity lever that fits 3B-class models' caches
        # on one chip (see models/configs.py kv_cache_quant).
        import dataclasses

        config = dataclasses.replace(config, kv_cache_quant=True)
        model_name += "+int8kv"
    prompts = build_sweep_prompts()
    settings = ModelSettings(temperature=0.7, top_k=0, top_p=1.0, max_tokens=MAX_NEW_TOKENS)

    devices = jax.devices()
    engine = DecodeEngine(config, seed=0)

    # Warmup: compile prefill+decode for the sweep's bucketed shape.
    engine.generate(prompts, settings, seed=0)

    # Timed runs.
    times = []
    for rep in range(3):
        t0 = time.perf_counter()
        out = engine.generate(prompts, settings, seed=rep + 1)
        jax.block_until_ready(out.tokens)
        times.append(time.perf_counter() - t0)

    # Large-sweep throughput: decode is weight-streaming-bound at small batch,
    # so a thousands-of-profiles ML-1M sweep runs at the batch-192 rate
    # instead. Big models can OOM at this batch on one chip — report null
    # rather than failing the whole benchmark.
    big_rate = None
    try:
        big = list(prompts) * 4
        engine.generate(big, settings, seed=0)
        t0 = time.perf_counter()
        out_big = engine.generate(big, settings, seed=99)
        jax.block_until_ready(out_big.tokens)
        big_rate = len(big) / (time.perf_counter() - t0)
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"large-sweep measurement skipped: {type(e).__name__}", file=sys.stderr)

    best = min(times)
    # The decode program runs on a single chip (no mesh in this bench), so
    # total throughput == per-chip throughput.
    profiles_per_sec = len(prompts) / best
    tokens_per_sec = len(prompts) * MAX_NEW_TOKENS / best

    result = {
        "metric": f"phase1_sweep_decode_throughput[{model_name},{devices[0].platform}]",
        "value": round(profiles_per_sec, 3),
        "unit": "profiles/sec/chip",
        "vs_baseline": round(profiles_per_sec / REFERENCE_PROFILES_PER_SEC, 1),
        "detail": {
            "profiles": len(prompts),
            "max_new_tokens": MAX_NEW_TOKENS,
            "decode_tokens_per_sec": round(tokens_per_sec, 1),
            "best_wall_s": round(best, 3),
            "all_wall_s": [round(t, 3) for t in times],
            "large_sweep_profiles_per_sec": round(big_rate, 3) if big_rate else None,
            "baseline": "reference README: ~15 min for the 45-profile sweep via API",
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()

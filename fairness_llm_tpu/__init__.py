"""fairness_llm_tpu — a TPU-native (JAX/XLA/pjit/Pallas) framework replicating the
capabilities of ``saakshipatel/fairness-llm-replication``.

The reference (see ``SURVEY.md``) is a three-phase fairness study of LLM-based
recommenders over MovieLens-1M driven by remote OpenAI API calls. This framework
runs the same detect -> cross-model-eval -> mitigate pipeline entirely on device:

- ``data/``     — MovieLens-1M loading, counterfactual profile grids, synthetic corpora
- ``metrics/``  — jit-compiled fairness + ranking metric kernels (DP/IF/EO/exposure/
                  NDCG/SNSR/SNSV) with on-device ``psum`` reductions
- ``models/``   — Flax decoder-only transformer family (Llama-3, Mistral, Gemma, GPT-2)
- ``runtime/``  — KV-cache autoregressive decode engine (jit prefill + ``lax.scan`` decode)
- ``parallel/`` — device mesh, sharding rules, tensor-parallel decode, ring attention
- ``ops/``      — Pallas TPU kernels for the hot ops
- ``pipeline/`` — phase 1/2/3 drivers reproducing the reference's behavior
- ``training/`` — sharded LM training step (loss + optax update) for fine-tuning
- ``cli/``      — ``main.py``-equivalent front end (``--all/--phase/--quick``)
- ``reports/``  — summary printers and figures
- ``telemetry/`` — metrics registry, request-lifecycle tracing, exporters
                  (``--telemetry-dir``; see docs/OBSERVABILITY.md)
"""

__version__ = "0.1.0"

"""Tokenizers: a dependency-free byte-level tokenizer + an HF adapter.

The reference never tokenizes — text goes to the OpenAI API verbatim
(``phase1_bias_detection.py:180-188``). In-framework decode needs a tokenizer:

- ``ByteTokenizer``: deterministic UTF-8 byte tokenizer with reserved specials.
  Works for any model vocab >= 258, needs no downloaded files — this is what
  tests, the simulated backend, and randomly initialized models use.
- ``HFTokenizer``: thin adapter over a locally available ``transformers``
  tokenizer directory (for real Llama/Mistral/Gemma/GPT-2 checkpoints). Never
  touches the network (``local_files_only=True``).

Both expose the same surface: ``encode_batch`` producing **left-padded** fixed
shape ``[B, S]`` int32 arrays (left padding keeps the KV write index uniform
across the batch — see ``models/transformer.py`` design notes) and ``decode``.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class TokenBatch:
    """Left-padded prompt batch ready for prefill."""

    tokens: np.ndarray  # [B, S] int32
    valid: np.ndarray  # [B, S] bool (False on left pads)
    lengths: np.ndarray  # [B] int32 real token counts


def _left_pad(rows: Sequence[List[int]], pad_id: int, max_len: Optional[int] = None) -> TokenBatch:
    n = len(rows)
    s = max_len or max((len(r) for r in rows), default=1)
    s = max(s, 1)
    tokens = np.full((n, s), pad_id, dtype=np.int32)
    valid = np.zeros((n, s), dtype=bool)
    lengths = np.zeros((n,), dtype=np.int32)
    for i, row in enumerate(rows):
        row = row[-s:] if len(row) > s else row  # truncate from the left, keep recency
        if row:
            tokens[i, s - len(row):] = row
            valid[i, s - len(row):] = True
        lengths[i] = len(row)
    return TokenBatch(tokens=tokens, valid=valid, lengths=lengths)


class ByteTokenizer:
    """UTF-8 bytes -> ids with reserved specials.

    Layout: 0=pad, 1=eos, 2=bos, bytes b -> 3+b. Total 259 ids; any model with
    vocab_size >= 259 can host it (the tiny test configs use vocab 512).
    """

    PAD_ID = 0
    EOS_ID = 1
    BOS_ID = 2
    OFFSET = 3

    def __init__(self, vocab_size: int = 512):
        if vocab_size < self.OFFSET + 256:
            raise ValueError(f"vocab_size {vocab_size} < {self.OFFSET + 256}")
        self.vocab_size = vocab_size
        self.pad_id = self.PAD_ID
        self.eos_id = self.EOS_ID
        self.bos_id = self.BOS_ID

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = [self.OFFSET + b for b in text.encode("utf-8")]
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(
            i - self.OFFSET for i in ids if self.OFFSET <= i < self.OFFSET + 256
        )
        return data.decode("utf-8", errors="replace")

    def encode_batch(self, texts: Sequence[str], max_len: Optional[int] = None) -> TokenBatch:
        return _left_pad([self.encode(t) for t in texts], self.pad_id, max_len)


class HFTokenizer:
    """Adapter over a local HuggingFace tokenizer (no network)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.pad_id = self._tok.pad_token_id
        if self.pad_id is None:
            self.pad_id = self._tok.eos_token_id
        self.eos_id = self._tok.eos_token_id
        self.bos_id = self._tok.bos_token_id
        self.vocab_size = len(self._tok)

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        return self._tok.encode(text, add_special_tokens=add_bos)

    def decode(self, ids: Sequence[int]) -> str:
        ids = [i for i in ids if i not in (self.pad_id, self.eos_id)]
        return self._tok.decode(ids, skip_special_tokens=True)

    def encode_batch(self, texts: Sequence[str], max_len: Optional[int] = None) -> TokenBatch:
        return _left_pad([self.encode(t) for t in texts], self.pad_id, max_len)


def tokenizer_for(model_config, tokenizer_path: Optional[str] = None):
    """Pick the tokenizer: HF if a local path is given, else byte-level."""
    if tokenizer_path is not None:
        return HFTokenizer(tokenizer_path)
    return ByteTokenizer(model_config.vocab_size)

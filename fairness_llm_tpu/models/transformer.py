"""Configurable decoder-only transformer (Flax linen), TPU-first.

Design notes (vs the reference, which has no local model at all — its "model layer"
is remote OpenAI calls, ``phase1_bias_detection.py:180-188``):

- One forward path for every family; ``ModelConfig`` flags choose RoPE vs learned
  positions, RMSNorm vs LayerNorm, gated vs plain MLP, sliding window, GQA ratio.
- Everything is static-shape and jit-friendly. Batched decode uses **left-padded**
  prompts so the KV write index is uniform across the batch (one
  ``dynamic_update_slice`` per layer per step — no per-row scatters).
- Weights carry flax *logical* partitioning axes ("embed", "q_heads", "kv_heads",
  "ff", "vocab"); ``parallel/sharding.py`` maps them onto the ("dp", "tp", "sp")
  device mesh, so TP=8 sharding is a rule change, not a model change.
- Matmuls run in the config dtype (bfloat16 on TPU -> MXU); softmax and norms
  accumulate in float32.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import flax.struct
import jax
import jax.numpy as jnp

from fairness_llm_tpu.models.configs import ModelConfig


def _dtype_of(config: ModelConfig):
    return jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32


# ---------------------------------------------------------------------------
# KV cache (functional pytree, fixed max_len)
# ---------------------------------------------------------------------------


@flax.struct.dataclass
class LayerCache:
    """k/v are either the model dtype, or int8 with per-(slot, head) float32
    scales when ``config.kv_cache_quant`` — halving the HBM bytes each decode
    step must stream (decode is KV-read-bound; see runtime/engine.py)."""

    k: jnp.ndarray  # [B, max_len, n_kv, head_dim]
    v: jnp.ndarray  # [B, max_len, n_kv, head_dim]
    k_scale: Optional[jnp.ndarray] = None  # [B, max_len, n_kv] float32
    v_scale: Optional[jnp.ndarray] = None


def _quantize_kv(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[B, S, H, D] -> (int8 values, [B, S, H] scales), symmetric per-vector."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def _dequantize_kv(q: jnp.ndarray, scale: jnp.ndarray, dtype) -> jnp.ndarray:
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def _cache_write(buf: jnp.ndarray, upd: jnp.ndarray, index, write_offsets):
    """Write ``upd`` into ``buf`` along the slot axis (axis 1 of [B, L, ...]).

    ``write_offsets=None``: one uniform ``dynamic_update_slice`` at ``index``
    (the left-padded batch invariant — every row writes the same slots).
    ``write_offsets=[B]``: per-row slot offsets (speculative verify steps,
    where rows advance by their own accepted counts) — a vmapped DUS, which
    XLA lowers to a batched scatter over the small [S, ...] update window.
    """
    zero = jnp.zeros((), jnp.int32)
    if write_offsets is None:
        return jax.lax.dynamic_update_slice(
            buf, upd, (zero, index) + (zero,) * (buf.ndim - 2)
        )

    def one(b, u, off):
        return jax.lax.dynamic_update_slice(
            b, u, (off,) + (zero,) * (b.ndim - 1)
        )

    return jax.vmap(one)(buf, upd, write_offsets)


@flax.struct.dataclass
class KVCache:
    """Decode state shared across layers.

    ``index`` is the uniform next-write slot (left-padding makes it batch-uniform);
    ``key_valid`` marks real (non-pad) cached keys; ``key_positions`` holds RoPE
    positions of cached keys (needed for Mistral's sliding-window test);
    ``lengths`` counts real tokens per row (the next RoPE position).
    """

    layers: Tuple[LayerCache, ...]
    key_valid: jnp.ndarray  # [B, max_len] bool
    key_positions: jnp.ndarray  # [B, max_len] int32
    index: jnp.ndarray  # scalar int32
    lengths: jnp.ndarray  # [B] int32

    @property
    def max_len(self) -> int:
        return self.layers[0].k.shape[1]


def init_cache(config: ModelConfig, batch_size: int, max_len: int, dtype=None) -> KVCache:
    dtype = dtype or _dtype_of(config)
    shape = (batch_size, max_len, config.num_kv_heads, config.head_dim)
    if config.kv_cache_quant:
        layers = tuple(
            LayerCache(
                k=jnp.zeros(shape, jnp.int8),
                v=jnp.zeros(shape, jnp.int8),
                k_scale=jnp.zeros(shape[:3], jnp.float32),
                v_scale=jnp.zeros(shape[:3], jnp.float32),
            )
            for _ in range(config.num_layers)
        )
    else:
        layers = tuple(
            LayerCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))
            for _ in range(config.num_layers)
        )
    return KVCache(
        layers=layers,
        key_valid=jnp.zeros((batch_size, max_len), jnp.bool_),
        key_positions=jnp.zeros((batch_size, max_len), jnp.int32),
        index=jnp.zeros((), jnp.int32),
        lengths=jnp.zeros((batch_size,), jnp.int32),
    )


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        scale = self.param(
            "scale", nn.with_logical_partitioning(nn.initializers.ones, ("embed",)), (x.shape[-1],)
        )
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (normed * scale.astype(jnp.float32)).astype(x.dtype)


def _norm(config: ModelConfig, name: str):
    if config.norm == "rmsnorm":
        return RMSNorm(eps=config.norm_eps, name=name)
    return nn.LayerNorm(epsilon=config.norm_eps, name=name, dtype=jnp.float32)


def rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """Rotary position embedding. x: [B, S, H, D], positions: [B, S]."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _activation(name: str):
    if name == "silu":
        return nn.silu
    if name == "gelu":
        return nn.gelu
    if name == "gelu_tanh":
        return lambda x: nn.gelu(x, approximate=True)
    raise ValueError(f"unknown activation {name}")


# ---------------------------------------------------------------------------
# int8 weight-only quantized dense (serving; see ops/quant_matmul.py)
# ---------------------------------------------------------------------------


def _int8_kernel_init(key, shape):
    """Random int8 kernel whose dequantized values (under the nominal scale)
    follow ~N(0, 0.02) like the float init — exact distribution parity is
    irrelevant for random-weight use; real checkpoints overwrite both."""
    return jnp.clip(
        jnp.round(jax.random.normal(key, shape) * 42.0), -127, 127
    ).astype(jnp.int8)


_NOMINAL_SCALE = 0.02 / 42.0


def _scale_init(key, shape):
    del key
    return jnp.full(shape, _NOMINAL_SCALE, jnp.float32)


class QuantDense(nn.Module):
    """Dense layer storing its kernel as int8 + per-output-channel f32 scales.

    The matmul dequantizes inside the Pallas tile loop (``ops/quant_matmul``),
    so HBM never holds a float copy of the weight — the property that lets
    llama3-70b tp=8 fit a v5e-8 (the naive dequant-at-use expression gets
    hoisted out of the decode loop by XLA and materializes the full bf16
    tree; docs/PERFORMANCE.md round 3). When the enclosing ``with mesh:``
    context shards the kernel's logical axes, the matmul runs as a
    FULL-manual shard_map over every mesh axis (column-parallel local,
    row-parallel + f32 psum, batch sharding encoded in the specs — Mosaic
    kernels can't lower partially-auto; see quant_matmul_sharded).
    """

    features: int
    in_axis: str
    out_axis: str
    use_bias: bool = False
    dtype: Any = jnp.bfloat16
    out_dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x):
        from fairness_llm_tpu.ops.quant_matmul import (
            quant_matmul,
            quant_matmul_sharded,
        )
        from fairness_llm_tpu.parallel.sharding import current_mesh

        in_dim = x.shape[-1]
        wq = self.param(
            "kernel_q",
            nn.with_logical_partitioning(
                _int8_kernel_init, (self.in_axis, self.out_axis)
            ),
            (in_dim, self.features),
        )
        scale = self.param(
            "kernel_scale",
            nn.with_logical_partitioning(_scale_init, (self.out_axis,)),
            (self.features,),
        )
        lead = x.shape[:-1]
        x2 = x.reshape(-1, in_dim).astype(self.dtype)
        out_dtype = self.out_dtype or self.dtype

        mesh = current_mesh()
        if mesh is None or all(s == 1 for s in mesh.shape.values()):
            y = quant_matmul(x2, wq, scale, out_dtype=out_dtype)
        else:
            from fairness_llm_tpu.parallel.sharding import resolve_logical_axis

            k_axis, n_axis, b_axis, s_axis = (
                resolve_logical_axis(a, mesh)
                for a in (self.in_axis, self.out_axis, "batch", "seq")
            )
            if b_axis is not None and x2.shape[0] % mesh.shape[b_axis] != 0:
                # batch=1 shared-prefix forward (rows = sequence positions),
                # or any batch not divisible by dp: replicate rows instead.
                # (Matmul rows are independent, so ANY row layout is correct;
                # divisibility is shard_map's hard requirement.)
                b_axis = None
            if s_axis is not None and x.ndim >= 3 and x.shape[1] > 1:
                # Sequence-sharded activations: x2's rows interleave B and S
                # shards, which P(b_axis, ...) cannot express. The XLA dequant
                # matmul is fine here — sp meshes are the training/scoring
                # forward, which runs OUTSIDE any decode loop (nothing for
                # XLA to hoist a float tree across).
                y = jnp.dot(
                    x2, wq.astype(x2.dtype), preferred_element_type=jnp.float32
                )
                y = (y * scale[None, :].astype(jnp.float32)).astype(out_dtype)
            else:
                y = quant_matmul_sharded(
                    x2, wq, scale, mesh, k_axis, n_axis, b_axis,
                    out_dtype=out_dtype,
                )
        y = y.reshape(*lead, self.features)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (self.features,))
            y = y + bias.astype(y.dtype)
        return y


class Attention(nn.Module):
    config: ModelConfig

    def _flash_ok(self, seq_len: int, left_padded: bool) -> bool:
        """Static gate for the Pallas fast path.

        Requires TPU, tile-compatible shapes, AND the caller's explicit promise
        that batches are left-padded (the kernel reconstructs the padding mask
        from a per-row length, which is only correct when valid tokens occupy
        the trailing slots). The decode engine always left-pads; other callers
        must opt in via ``left_padded=True``.

        Under a sharded mesh the kernel runs per-shard via shard_map (see
        ``_flash_dispatch``); that wrap is only correct when q and kv heads
        shard the SAME way — the tp=16 GQA fallback (q sharded, kv
        replicated) would change the per-shard head ratio, so it stays on
        the XLA dense path.
        """
        if not (self.config.use_flash_attention and left_padded) or seq_len <= 1:
            return False
        from fairness_llm_tpu.ops.quant_matmul import _FORCE_PALLAS

        if jax.default_backend() != "tpu" and not _FORCE_PALLAS.get():
            return False
        _, qh_ax, kv_ax = self._mesh_axes()
        if qh_ax != kv_ax:
            return False
        from fairness_llm_tpu.ops import flash_supported

        return flash_supported(seq_len, self.config.head_dim)

    def _mesh_axes(self):
        """(batch, q_heads, kv_heads) mesh axes actually sharded (>1) under
        the enclosing mesh + logical-rules context, else Nones.

        Axes resolve one at a time (``resolve_logical_axis``): a joint
        PartitionSpec lookup may use each mesh axis only once, so q_heads
        would claim "tp" and kv_heads silently resolve to None (observed:
        the sharded flash gate quietly never engaged)."""
        from fairness_llm_tpu.parallel.sharding import current_mesh, resolve_logical_axis

        mesh = current_mesh()
        if mesh is None:
            return None, None, None
        return tuple(
            resolve_logical_axis(a, mesh) for a in ("batch", "q_heads", "kv_heads")
        )

    def _flash_dispatch(self, q, k, v, lengths):
        """Run flash attention; under a sharded mesh, per-shard.

        A bare Mosaic ``pallas_call`` cannot be partitioned by GSPMD — a
        multi-chip program must wrap it in ``shard_map``. Heads are
        per-kernel-instance, and each batch row is masked independently, so
        sharding batch over dp and heads over tp is exactly local; other
        mesh axes stay GSPMD-auto. (Single-chip callers skip the wrap.)
        """
        from fairness_llm_tpu.ops import flash_attention
        from fairness_llm_tpu.parallel.sharding import current_mesh

        window = self.config.sliding_window

        def call(q, k, v, lengths):
            return flash_attention(q, k, v, lengths, causal=True, window=window)

        mesh = current_mesh()
        if mesh is None or all(s == 1 for s in mesh.shape.values()):
            return call(q, k, v, lengths)
        # Full-manual: Mosaic kernels refuse partially-auto SPMD contexts
        # (see ops/quant_matmul.quant_matmul_sharded); unnamed spec entries
        # are replicated per shard.
        b_ax, qh_ax, kv_ax = self._mesh_axes()
        if b_ax is not None and q.shape[0] % mesh.shape[b_ax] != 0:
            # The engine's shared-prefix prefill runs batch=1 ([1, Pc]
            # tokens) on any mesh; an indivisible batch dim replicates
            # instead of sharding (shard_map requires exact divisibility).
            b_ax = None
        from jax.sharding import PartitionSpec as P

        from fairness_llm_tpu.parallel.sharding import compat_shard_map

        return compat_shard_map(
            call,
            mesh,
            in_specs=(
                P(b_ax, qh_ax, None, None),
                P(b_ax, kv_ax, None, None),
                P(b_ax, kv_ax, None, None),
                P(b_ax),
            ),
            out_specs=P(b_ax, qh_ax, None, None),
        )(q, k, v, lengths)

    def _decode_kernel_ok(
        self, seq_len: int, cache_layer, batch: int, cache_len: int,
        shared_len: int = 0, multi_q: bool = False,
    ) -> bool:
        """Static gate for the fused decode-attention kernel: TPU, a cached
        SINGLE-token step (key_valid alone encodes causality there) or a
        short multi-token speculative verify step (``multi_q`` — per-row
        write offsets supply the causal window), XLA-path semantics (no
        ring), no sliding window (mask not implemented in the kernel), and
        tile-compatible shapes. An int8 cache takes the dequant-in-tile
        kernel mode (the kernel streams int8 + scales, so its VMEM envelope
        is ~4x the f32 accounting)."""
        cfg = self.config
        q_ok = seq_len == 1 or (multi_q and seq_len <= 16)
        if not (cfg.use_decode_attention_kernel and q_ok and cache_layer is not None):
            return False
        if cfg.sliding_window is not None:
            return False
        if cfg.attention_impl != "xla" or jax.default_backend() != "tpu":
            return False
        if jax.device_count() > 1:
            # Multi-chip: a bare pallas_call inside a GSPMD-partitioned
            # program would need a shard_map wrapper; not validated on real
            # multi-chip hardware, so the sharded path keeps XLA attention.
            return False
        from fairness_llm_tpu.ops.decode_attention import decode_attn_supported

        if cfg.kv_cache_quant:
            itemsize = 1
        else:
            itemsize = 2 if cfg.dtype == "bfloat16" else 4
        return decode_attn_supported(
            batch, cache_len, cfg.head_dim, shared_len, kv_itemsize=itemsize,
            q_len=seq_len,
        )

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,  # [B, S, D]
        positions: jnp.ndarray,  # [B, S]
        cache_layer: Optional[LayerCache],
        cache_index: Optional[jnp.ndarray],
        key_valid: jnp.ndarray,  # [B, K] for the post-update key set
        key_positions: jnp.ndarray,  # [B, K]
        left_padded: bool = False,
        shared_kv: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
        write_offsets: Optional[jnp.ndarray] = None,
    ):
        # ``shared_kv``: (k, v) each [Pc, Hkv, D] — a prompt prefix COMMON to
        # every batch row, computed once and read once per step instead of
        # B times (prefix caching; decode is KV-read-bound). Shared keys sit
        # at global positions 0..Pc-1, strictly before every query, so they
        # are always causally visible; per-row positions are offset by Pc.
        #
        # ``write_offsets``: [B] int32 per-row cache-slot offsets for the new
        # tokens (speculative verify steps — rows advance at their own
        # accepted rates, so the uniform ``cache_index`` cannot serve).
        # When given, it replaces ``cache_index`` for BOTH the cache writes
        # and the causal rule: query i of row b may see own-cache slot j iff
        # j <= write_offsets[b] + i (the "small causal window" against the
        # already-valid cache).
        cfg = self.config
        dtype = _dtype_of(cfg)
        # qwen2 carries biases on q/k/v only (o_proj and MLP stay bias-free).
        if cfg.weight_quant == "int8":
            dense = lambda feats, axes, name: QuantDense(  # noqa: E731
                feats, in_axis="embed", out_axis=axes,
                use_bias=cfg.use_bias or cfg.qkv_bias, dtype=dtype, name=name,
            )
        else:
            dense = lambda feats, axes, name: nn.DenseGeneral(  # noqa: E731
                feats,
                use_bias=cfg.use_bias or cfg.qkv_bias,
                dtype=dtype,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.normal(0.02), ("embed", axes)
                ),
                name=name,
            )
        B, S, _ = x.shape
        q = dense(cfg.q_dim, "q_heads", "q_proj")(x).reshape(B, S, cfg.num_heads, cfg.head_dim)
        k = dense(cfg.kv_dim, "kv_heads", "k_proj")(x).reshape(B, S, cfg.num_kv_heads, cfg.head_dim)
        v = dense(cfg.kv_dim, "kv_heads", "v_proj")(x).reshape(B, S, cfg.num_kv_heads, cfg.head_dim)

        if cfg.pos_emb == "rope":
            q = rope(q, positions, cfg.rope_theta)
            k = rope(k, positions, cfg.rope_theta)

        if cfg.attention_impl == "ring" and cache_layer is not None:
            raise ValueError("attention_impl='ring' supports only the no-cache path")

        # Shared cache write (prefill records the prompt for later decode steps).
        if cache_layer is not None:
            if cfg.kv_cache_quant:
                qk, k_sc = _quantize_kv(k)
                qv, v_sc = _quantize_kv(v)
                ck = _cache_write(cache_layer.k, qk, cache_index, write_offsets)
                cv = _cache_write(cache_layer.v, qv, cache_index, write_offsets)
                cks = _cache_write(cache_layer.k_scale, k_sc, cache_index, write_offsets)
                cvs = _cache_write(cache_layer.v_scale, v_sc, cache_index, write_offsets)
                new_cache_layer = LayerCache(k=ck, v=cv, k_scale=cks, v_scale=cvs)
                keys = _dequantize_kv(ck, cks, dtype)
                values = _dequantize_kv(cv, cvs, dtype)
            else:
                keys = _cache_write(cache_layer.k, k.astype(dtype), cache_index, write_offsets)
                values = _cache_write(cache_layer.v, v.astype(dtype), cache_index, write_offsets)
                new_cache_layer = LayerCache(k=keys, v=values)
        else:
            keys, values = k, v
            new_cache_layer = None

        if cfg.attention_impl == "ring":
            # Ring attention over the sp axis (parallel/ring.py): exact
            # attention with each device holding a sequence shard; requires
            # tracing inside shard_map with axis "sp" bound (training /
            # scoring forward). GQA kv stay unexpanded on the ring.
            from fairness_llm_tpu.parallel.ring import ring_attention

            out = ring_attention(
                q, k, v, positions, positions, key_valid,
                axis_name="sp", window=cfg.sliding_window,
            ).astype(dtype)
        elif shared_kv is None and self._flash_ok(S, left_padded):
            # Training (no cache) or first prefill (cache present but empty —
            # S > 1 is the engine's static marker; a chunked-prefill caller
            # must set use_flash_attention=False). In both cases the NEW k/v
            # are the entire key set, so the kernel sees only [B, S].
            # With an int8 cache, later decode steps attend to the quantization
            # round-trip of these keys/values — attend to the same dequantized
            # tensors here so flash-eligible and fallback shapes agree.
            fk, fv = (keys[:, :S], values[:, :S]) if (
                cfg.kv_cache_quant and cache_layer is not None
            ) else (k, v)
            out = self._flash_dispatch(
                q.transpose(0, 2, 1, 3),
                fk.astype(dtype).transpose(0, 2, 1, 3),
                fv.astype(dtype).transpose(0, 2, 1, 3),
                jnp.sum(key_valid[:, :S], axis=1, dtype=jnp.int32),
            ).transpose(0, 2, 1, 3)
        elif self._decode_kernel_ok(
            S, cache_layer, keys.shape[0], keys.shape[1],
            0 if shared_kv is None else shared_kv[0].shape[0],
            multi_q=write_offsets is not None,
        ):
            # Cached decode: the Pallas fused kernel. For S == 1, key_valid
            # alone is the mask (slots past the write index are invalid, so
            # causality is already encoded). For a speculative verify step
            # (S == k+1, write_offsets given) the kernel additionally applies
            # the small causal window j <= offsets[b] + i over the newly
            # written slots.
            from fairness_llm_tpu.ops.decode_attention import decode_attention

            sh = None if shared_kv is None else (
                shared_kv[0].astype(dtype), shared_kv[1].astype(dtype)
            )
            kq = q[:, 0] if S == 1 else q  # [B, H, D] or [B, S, H, D]
            if cfg.kv_cache_quant:
                # Raw int8 cache + scales straight into the kernel; the
                # dequantized `keys`/`values` computed above are unused in
                # this branch and get dead-code-eliminated, so the step
                # streams HALF the cache bytes of the bf16 path.
                out = decode_attention(
                    kq, new_cache_layer.k, new_cache_layer.v, key_valid,
                    shared_kv=sh,
                    k_scale=new_cache_layer.k_scale,
                    v_scale=new_cache_layer.v_scale,
                    q_offsets=write_offsets,
                ).reshape(B, S, cfg.num_heads, cfg.head_dim)
            else:
                out = decode_attention(
                    kq, keys.astype(dtype), values.astype(dtype), key_valid,
                    shared_kv=sh, q_offsets=write_offsets,
                ).reshape(B, S, cfg.num_heads, cfg.head_dim)
        else:
            if cache_layer is not None:
                K = keys.shape[1]
                j_idx = jnp.arange(K)[None, :]
                if write_offsets is None:
                    # causal: new query i (global slot index+i) sees key slot
                    # j iff j <= index+i
                    q_idx = cache_index + jnp.arange(S)[:, None]
                    causal = (j_idx <= q_idx)[None, :, :]  # [1, S, K]
                else:
                    # per-row window: query i of row b wrote slot offsets[b]+i
                    q_idx = (
                        write_offsets[:, None, None]
                        + jnp.arange(S)[None, :, None]
                    )  # [B, S, 1]
                    causal = j_idx[None, :, :] <= q_idx  # [B, S, K]
            else:
                K = S
                causal = jnp.tril(jnp.ones((S, S), jnp.bool_))[None, :, :]

            allowed = causal & key_valid[:, None, :]  # [B, S, K]
            if cfg.sliding_window is not None:
                delta = positions[:, :, None] - key_positions[:, None, :]
                allowed = allowed & (delta < cfg.sliding_window)

            # GQA: repeat kv heads up to num_heads.
            rep = cfg.num_heads // cfg.num_kv_heads
            dense_keys, dense_values = keys, values
            if rep > 1:
                dense_keys = jnp.repeat(dense_keys, rep, axis=2)
                dense_values = jnp.repeat(dense_values, rep, axis=2)

            scale = cfg.head_dim ** -0.5
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, dense_keys).astype(jnp.float32) * scale
            scores = jnp.where(allowed[:, None, :, :], scores, -1e30)
            if shared_kv is not None:
                sk, sv = shared_kv  # [Pc, Hkv, D]
                if rep > 1:
                    sk = jnp.repeat(sk, rep, axis=1)
                    sv = jnp.repeat(sv, rep, axis=1)
                # [B,H,S,Pc] — note sk has no batch dim: read once, not B times
                s_sh = jnp.einsum("bqhd,khd->bhqk", q, sk.astype(q.dtype)).astype(jnp.float32) * scale
                if cfg.sliding_window is not None:
                    sh_pos = jnp.arange(sk.shape[0])
                    sh_allowed = (positions[:, :, None] - sh_pos[None, None, :]) < cfg.sliding_window
                    s_sh = jnp.where(sh_allowed[:, None, :, :], s_sh, -1e30)
                joint = jnp.concatenate([s_sh, scores], axis=-1)
                probs = jax.nn.softmax(joint, axis=-1).astype(dtype)
                p_sh, p_own = probs[..., : sk.shape[0]], probs[..., sk.shape[0]:]
                out = jnp.einsum("bhqk,khd->bqhd", p_sh, sv.astype(dtype))
                out = out + jnp.einsum("bhqk,bkhd->bqhd", p_own, dense_values)
            else:
                probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
                out = jnp.einsum("bhqk,bkhd->bqhd", probs, dense_values)

        out = out.reshape(B, S, cfg.q_dim)
        if cfg.weight_quant == "int8":
            out = QuantDense(
                cfg.d_model, in_axis="q_heads", out_axis="embed",
                use_bias=cfg.use_bias, dtype=dtype, name="o_proj",
            )(out)
        else:
            out = nn.DenseGeneral(
                cfg.d_model,
                use_bias=cfg.use_bias,
                dtype=dtype,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.normal(0.02), ("q_heads", "embed")
                ),
                name="o_proj",
            )(out)
        return out, new_cache_layer


class MLP(nn.Module):
    config: ModelConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dtype = _dtype_of(cfg)
        act = _activation(cfg.activation)
        use_bias = cfg.use_bias
        if cfg.weight_quant == "int8":
            up_d = lambda name: QuantDense(  # noqa: E731
                cfg.d_ff, in_axis="embed", out_axis="ff",
                use_bias=use_bias, dtype=dtype, name=name,
            )
            down_d = QuantDense(
                cfg.d_model, in_axis="ff", out_axis="embed",
                use_bias=use_bias, dtype=dtype, name="down_proj",
            )
        else:
            up_init = nn.with_logical_partitioning(nn.initializers.normal(0.02), ("embed", "ff"))
            down_init = nn.with_logical_partitioning(nn.initializers.normal(0.02), ("ff", "embed"))
            up_d = lambda name: nn.DenseGeneral(  # noqa: E731
                cfg.d_ff, use_bias=use_bias, dtype=dtype, kernel_init=up_init, name=name,
            )
            down_d = nn.DenseGeneral(
                cfg.d_model, use_bias=use_bias, dtype=dtype, kernel_init=down_init,
                name="down_proj",
            )
        if cfg.mlp == "glu":
            h = act(up_d("gate_proj")(x)) * up_d("up_proj")(x)
        else:
            h = act(up_d("up_proj")(x))
        return down_d(h)


class Block(nn.Module):
    config: ModelConfig

    @nn.compact
    def __call__(self, x, positions, cache_layer, cache_index, key_valid, key_positions,
                 left_padded=False, shared_kv=None, write_offsets=None):
        attn_out, new_cache = Attention(self.config, name="attn")(
            _norm(self.config, "attn_norm")(x),
            positions, cache_layer, cache_index, key_valid, key_positions,
            left_padded=left_padded, shared_kv=shared_kv,
            write_offsets=write_offsets,
        )
        x = x + attn_out
        x = x + MLP(self.config, name="mlp")(_norm(self.config, "mlp_norm")(x))
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        return x, new_cache


# ---------------------------------------------------------------------------
# Full model
# ---------------------------------------------------------------------------


class Transformer(nn.Module):
    """Decoder-only LM.

    Call patterns:
      - training / scoring: ``logits, None = apply(params, tokens, positions, token_valid)``
      - prefill/decode:     ``logits, cache = apply(..., cache=cache)`` where
        ``tokens`` occupy cache slots ``[cache.index, cache.index + S)``.
    """

    config: ModelConfig

    @nn.compact
    def __call__(
        self,
        tokens: jnp.ndarray,  # [B, S] int32
        positions: jnp.ndarray,  # [B, S] int32 (RoPE/learned positions, pad rows clamped)
        token_valid: Optional[jnp.ndarray] = None,  # [B, S] bool
        cache: Optional[KVCache] = None,
        left_padded: bool = False,  # promise: valid tokens occupy trailing slots
        last_only: bool = False,  # return logits for the final position only
        shared_layers: Optional[Tuple] = None,  # per-layer (k, v) [Pc, Hkv, D] prefix KV
        write_offsets: Optional[jnp.ndarray] = None,  # [B] per-row cache slots
    ) -> Tuple[jnp.ndarray, Optional[KVCache]]:
        cfg = self.config
        dtype = _dtype_of(cfg)
        B, S = tokens.shape
        if token_valid is None:
            token_valid = jnp.ones((B, S), jnp.bool_)

        embed = self.param(
            "embedding",
            nn.with_logical_partitioning(nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.d_model),
        )
        x = embed[tokens].astype(dtype)
        if cfg.embed_scale:  # gemma
            x = x * jnp.asarray(cfg.d_model ** 0.5, dtype)
        if cfg.pos_emb == "learned":
            wpe = self.param(
                "pos_embedding",
                nn.with_logical_partitioning(nn.initializers.normal(0.02), (None, "embed")),
                (cfg.max_seq_len, cfg.d_model),
            )
            x = x + wpe[positions].astype(dtype)
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))

        if cache is not None:
            # Static guard against silent dynamic_update_slice clamping: a single
            # call can never write more new tokens than the cache holds. The
            # engine guarantees max_len >= prompt_len + max_new_tokens.
            if S > cache.max_len:
                raise ValueError(
                    f"writing {S} tokens into a cache of max_len {cache.max_len}"
                )
            key_valid = _cache_write(cache.key_valid, token_valid, cache.index, write_offsets)
            key_positions = _cache_write(cache.key_positions, positions, cache.index, write_offsets)
        else:
            key_valid = token_valid
            key_positions = positions

        new_layers = []
        for i in range(cfg.num_layers):
            layer_cache = cache.layers[i] if cache is not None else None
            x, new_layer = Block(cfg, name=f"layer_{i}")(
                x, positions,
                layer_cache, cache.index if cache is not None else None,
                key_valid, key_positions, left_padded=left_padded,
                shared_kv=shared_layers[i] if shared_layers is not None else None,
                write_offsets=write_offsets,
            )
            new_layers.append(new_layer)

        x = _norm(cfg, "final_norm")(x)
        if last_only:
            # Prefill only needs the final position's distribution; skipping the
            # [B, S, V] projection saves B·(S-1)·D·V FLOPs (for a gpt2-small
            # 64x896 prefill that's ~2 TFLOP of pure waste).
            x = x[:, -1:, :]

        # Logits matmul: operands stay in the model dtype (bf16 -> full MXU
        # rate; the [D, V] projection dominates each decode step's FLOPs) with
        # float32 accumulation — the standard precision recipe. float32
        # configs are unaffected.
        if cfg.tie_embeddings:
            logits = jnp.einsum(
                "bsd,vd->bsv", x, embed.astype(x.dtype),
                preferred_element_type=jnp.float32,
            )
        elif cfg.weight_quant == "int8":
            logits = QuantDense(
                cfg.vocab_size, in_axis="embed", out_axis="vocab",
                use_bias=False, dtype=_dtype_of(cfg), out_dtype=jnp.float32,
                name="lm_head",
            )(x)
        else:
            lm_head = self.param(
                "lm_head",
                nn.with_logical_partitioning(nn.initializers.normal(0.02), ("embed", "vocab")),
                (cfg.d_model, cfg.vocab_size),
            )
            logits = jnp.einsum(
                "bsd,dv->bsv", x, lm_head.astype(x.dtype),
                preferred_element_type=jnp.float32,
            )
        logits = nn.with_logical_constraint(logits, ("batch", "seq", "vocab"))

        new_cache = None
        if cache is not None:
            new_cache = KVCache(
                layers=tuple(new_layers),
                key_valid=key_valid,
                key_positions=key_positions,
                index=cache.index + S,
                lengths=cache.lengths + jnp.sum(token_valid, axis=1, dtype=jnp.int32),
            )
        return logits, new_cache


def init_params_lowmem(config: ModelConfig, rng: jax.Array, dtype=None) -> Any:
    """Random params WITHOUT materializing the float32 init tree.

    ``flax`` init allocates every param in float32 (param_dtype default); for a
    multi-billion-param model that transient f32 tree alone can exceed one
    chip's HBM. This path gets shapes from ``jax.eval_shape`` (no memory) and
    fills each leaf directly in the target dtype: kernels/embeddings ~ N(0,
    0.02), biases zero, norm scales one — the same families as the real init
    (exact distribution parity is irrelevant for random-weight use).
    """
    dtype = dtype or (jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32)
    model = Transformer(config)
    tokens = jnp.zeros((1, 8), jnp.int32)
    positions = jnp.zeros((1, 8), jnp.int32)
    abstract = jax.eval_shape(model.init, jax.random.key(0), tokens, positions)
    abstract = nn.meta.unbox(abstract["params"])

    flat, treedef = jax.tree_util.tree_flatten_with_path(abstract)
    leaves = []
    for i, (path, leaf) in enumerate(flat):
        name = "/".join(str(p.key) for p in path if hasattr(p, "key"))
        key = jax.random.fold_in(rng, i)
        if name.endswith("kernel_q"):  # before the generic "scale"/bias rules
            leaves.append(_int8_kernel_init(key, leaf.shape))
        elif name.endswith("kernel_scale"):
            leaves.append(_scale_init(key, leaf.shape))
        elif name.endswith("scale"):
            leaves.append(jnp.ones(leaf.shape, dtype))
        elif name.endswith("bias"):
            leaves.append(jnp.zeros(leaf.shape, dtype))
        else:
            leaves.append((jax.random.normal(key, leaf.shape, dtype) * 0.02))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def init_params(config: ModelConfig, rng: jax.Array, seq_len: int = 8) -> Any:
    """Initialize parameters with a tiny dummy batch (shape doesn't matter for params).

    The init is run under ``jit``: unjitted flax init dispatches op-by-op, and
    per-op XLA mini-compiles are orders of magnitude slower than one fused
    compile (observed 45 s eager vs 3 s jitted for the tiny test model).
    """
    model = Transformer(config)
    tokens = jnp.zeros((1, seq_len), jnp.int32)
    positions = jnp.tile(jnp.arange(seq_len, dtype=jnp.int32)[None, :], (1, 1))
    variables = jax.jit(model.init)(rng, tokens, positions)
    # Strip the LogicallyPartitioned metadata boxes; sharding specs are recovered
    # separately via eval_shape + nn.get_partition_spec (parallel/sharding.py).
    return nn.meta.unbox(variables["params"])

"""Model-family configurations.

Architecture hyperparameters for the open-weight families named in
``BASELINE.json.configs`` (Llama-3-8B/70B, Mistral-7B, Gemma-7B, GPT-2-small)
plus tiny test configs. All sizes are public-knowledge architecture constants.

Flags rather than subclasses select family behavior:
- ``pos_emb``: "rope" (llama/mistral/gemma) or "learned" (gpt2)
- ``norm``: "rmsnorm" or "layernorm"
- ``mlp``: "glu" (SwiGLU/GeGLU) or "mlp" (GPT-2's fc->gelu->proj)
- ``embed_scale``: Gemma multiplies embeddings by sqrt(d_model)
- ``sliding_window``: Mistral's local attention span
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    d_model: int
    d_ff: int
    head_dim: int
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    pos_emb: str = "rope"  # "rope" | "learned"
    norm: str = "rmsnorm"  # "rmsnorm" | "layernorm"
    mlp: str = "glu"  # "glu" | "mlp"
    use_bias: bool = False  # biases on attention + MLP projections (gpt2 family)
    qkv_bias: bool = False  # biases ONLY on q/k/v projections (qwen2 family)
    activation: str = "silu"  # "silu" | "gelu" | "gelu_tanh"
    embed_scale: bool = False  # gemma: scale embeddings by sqrt(d_model)
    tie_embeddings: bool = False
    sliding_window: Optional[int] = None  # mistral
    eos_token_id: int = 2
    pad_token_id: int = 0
    dtype: str = "bfloat16"
    # Pallas flash-attention for prefill/training attention on TPU (falls back
    # to the XLA path off-TPU or when shapes don't meet the 128-lane tiling).
    use_flash_attention: bool = True
    # int8 KV cache with per-vector scales: halves cache MEMORY (the enabler
    # for long-context / big-batch decode that wouldn't otherwise fit HBM).
    # Measured on v5e gpt2-small it is ~8% slower than bf16 — the dequant adds
    # work — so it's a capacity lever, not a speed lever. Opt-in.
    kv_cache_quant: bool = False
    # Pallas fused decode-step attention (ops/decode_attention.py): keeps the
    # per-layer scores/softmax/PV in VMEM instead of XLA's separate fusions.
    # MEASURED SLOWER both ways and OFF by default: bf16 104 vs 112
    # profiles/s at batch 48 (round 3); int8-cache mode (dequant-in-tile,
    # round 4) 0.28x XLA at batch 192/360 — the per-step head-major cache
    # transposes dominate, and the native head-major layout was also
    # measured and rejected (docs/PERFORMANCE.md). Kept oracle-tested; the
    # bench A/Bs it every round. Applies only on TPU to single-token cached
    # steps with compatible shapes (no sliding window); all other paths use
    # XLA regardless.
    use_decode_attention_kernel: bool = False
    # Weight-only quantization for serving: "int8" stores every 2D matmul
    # kernel (q/k/v/o, gate/up/down, untied lm_head) as int8 with per-output-
    # channel float32 scales, dequantized INSIDE the Pallas matmul tile loop
    # (ops/quant_matmul.py) so no bf16 copy of the tree ever exists in HBM —
    # the capability that fits Llama-3-70B tp=8 on one v5e-8 slice (bf16 is
    # 17.6 GB/chip vs 16 GB HBM; int8 is ~9.1 GB). Embeddings, norms, and
    # biases stay in the float dtype. Serving-only: the train step rejects it.
    weight_quant: str = "none"  # "none" | "int8"
    # "xla" (default): dense/flash attention, GSPMD decides any resharding.
    # "ring": exact ring attention over the sp axis — the forward must run
    # inside shard_map with axis "sp" bound and activations sequence-sharded
    # (train/step.py sequence_parallel=True). No-cache path only.
    attention_impl: str = "xla"

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    @property
    def approx_param_count(self) -> int:
        """Parameter-count estimate from the architecture constants."""
        embed = self.vocab_size * self.d_model
        attn = self.d_model * (2 * self.q_dim + 2 * self.kv_dim)
        mlp_in = 2 if self.mlp == "glu" else 1
        mlp = self.d_model * self.d_ff * (mlp_in + 1)
        head = 0 if self.tie_embeddings else embed
        return embed + head + self.num_layers * (attn + mlp)


MODEL_CONFIGS = {
    # Tiny config for tests/CI: fast to init, exercises GQA + RoPE + GLU path.
    "tiny-test": ModelConfig(
        name="tiny-test", vocab_size=512, num_layers=2, num_heads=4, num_kv_heads=2,
        d_model=64, d_ff=128, head_dim=16, max_seq_len=256, eos_token_id=1,
        dtype="float32",
    ),
    # Tiny GPT-2-style config: learned positions + layernorm + gelu MLP path.
    "tiny-gpt2": ModelConfig(
        name="tiny-gpt2", vocab_size=512, num_layers=2, num_heads=4, num_kv_heads=4,
        d_model=64, d_ff=256, head_dim=16, max_seq_len=256, pos_emb="learned",
        norm="layernorm", mlp="mlp", use_bias=True, activation="gelu_tanh",
        tie_embeddings=True, eos_token_id=1, dtype="float32",
    ),
    # Tiny *study* configs: match the transformers-built checkpoints committed
    # under checkpoints/ (tools/build_tiny_study_checkpoints.py). These exist
    # so the full --all study can run through the REAL weights path
    # (backend_for -> load_checkpoint -> HFTokenizer -> EngineBackend) end to
    # end without pretrained weights in the environment — the reference's
    # inference layer was always a real model (phase1_bias_detection.py:180-188),
    # and results/real_weights/ holds the committed record. Swapping in actual
    # Llama weights is then a config change, not new code. vocab 512 matches
    # the committed BPE tokenizer; eos/pad 0 = its <|endoftext|>.
    "tiny-llama-study": ModelConfig(
        name="tiny-llama-study", vocab_size=512, num_layers=4, num_heads=4,
        num_kv_heads=2, d_model=128, d_ff=256, head_dim=32, max_seq_len=1024,
        eos_token_id=0, pad_token_id=0, dtype="float32",
        use_flash_attention=False,
    ),
    "tiny-gpt2-study": ModelConfig(
        name="tiny-gpt2-study", vocab_size=512, num_layers=4, num_heads=4,
        num_kv_heads=4, d_model=128, d_ff=512, head_dim=32, max_seq_len=1024,
        pos_emb="learned", norm="layernorm", mlp="mlp", use_bias=True,
        activation="gelu_tanh", tie_embeddings=True, eos_token_id=0,
        pad_token_id=0, dtype="float32", use_flash_attention=False,
    ),
    "gpt2-small": ModelConfig(
        name="gpt2-small", vocab_size=50257, num_layers=12, num_heads=12,
        num_kv_heads=12, d_model=768, d_ff=3072, head_dim=64, max_seq_len=1024,
        pos_emb="learned", norm="layernorm", mlp="mlp", use_bias=True,
        activation="gelu_tanh", tie_embeddings=True, eos_token_id=50256,
        pad_token_id=50256,
    ),
    # Llama-3.2 small models: the single-chip-friendly members of the family
    # (1B/3B fit a v5e chip in bf16 with room for KV cache and batch).
    "llama32-1b": ModelConfig(
        name="llama32-1b", vocab_size=128256, num_layers=16, num_heads=32,
        num_kv_heads=8, d_model=2048, d_ff=8192, head_dim=64, max_seq_len=8192,
        rope_theta=500000.0, tie_embeddings=True, eos_token_id=128001,
        pad_token_id=128001,
    ),
    "llama32-3b": ModelConfig(
        name="llama32-3b", vocab_size=128256, num_layers=28, num_heads=24,
        num_kv_heads=8, d_model=3072, d_ff=8192, head_dim=128, max_seq_len=8192,
        rope_theta=500000.0, tie_embeddings=True, eos_token_id=128001,
        pad_token_id=128001,
    ),
    "llama3-8b": ModelConfig(
        name="llama3-8b", vocab_size=128256, num_layers=32, num_heads=32,
        num_kv_heads=8, d_model=4096, d_ff=14336, head_dim=128, max_seq_len=8192,
        rope_theta=500000.0, eos_token_id=128001, pad_token_id=128001,
    ),
    "llama3-70b": ModelConfig(
        name="llama3-70b", vocab_size=128256, num_layers=80, num_heads=64,
        num_kv_heads=8, d_model=8192, d_ff=28672, head_dim=128, max_seq_len=8192,
        rope_theta=500000.0, eos_token_id=128001, pad_token_id=128001,
    ),
    "mistral-7b": ModelConfig(
        name="mistral-7b", vocab_size=32000, num_layers=32, num_heads=32,
        num_kv_heads=8, d_model=4096, d_ff=14336, head_dim=128, max_seq_len=8192,
        rope_theta=1000000.0, sliding_window=4096, eos_token_id=2, pad_token_id=0,
    ),
    "gemma-7b": ModelConfig(
        name="gemma-7b", vocab_size=256000, num_layers=28, num_heads=16,
        num_kv_heads=16, d_model=3072, d_ff=24576, head_dim=256, max_seq_len=8192,
        activation="gelu_tanh", embed_scale=True, tie_embeddings=True,
        eos_token_id=1, pad_token_id=0,
    ),
    # Qwen2/2.5: llama-like (RMSNorm + SwiGLU + RoPE + GQA) with biases on
    # the q/k/v projections only (qkv_bias).
    "qwen2-0.5b": ModelConfig(
        name="qwen2-0.5b", vocab_size=151936, num_layers=24, num_heads=14,
        num_kv_heads=2, d_model=896, d_ff=4864, head_dim=64, max_seq_len=8192,
        rope_theta=1000000.0, norm_eps=1e-6, qkv_bias=True, tie_embeddings=True,
        eos_token_id=151643, pad_token_id=151643,
    ),
    "qwen2-7b": ModelConfig(
        name="qwen2-7b", vocab_size=152064, num_layers=28, num_heads=28,
        num_kv_heads=4, d_model=3584, d_ff=18944, head_dim=128, max_seq_len=8192,
        rope_theta=1000000.0, norm_eps=1e-6, qkv_bias=True,
        eos_token_id=151643, pad_token_id=151643,
    ),
}

# int8 weight-only serving variants, DERIVED from their base entries (not
# hand-copied — a fix to a base architecture constant must not need applying
# twice). These are the configs that make the BASELINE.json targets actually
# fit v5e HBM with dequant-in-tile weights (ops/quant_matmul.py):
#   llama3-8b-int8   ~8.6 GB  — BASELINE configs[1] on ONE 15.75 GB chip
#                               (bf16 8B is ~16 GB of params alone)
#   llama3-70b-int8  ~9.0 GB/chip at tp=8 on a v5e-8 (bf16 is 17.6 GB/chip;
#                               proven in tests/test_70b_readiness.py)
#   mistral-7b-int8  ~7.4 GB, qwen2-7b-int8 ~8.2 GB, gemma-7b-int8 ~9.3 GB
#                    — the configs[2] cross-model set, single chip each
#                      (tied embeddings, e.g. gemma's 256k vocab, stay bf16)
for _base in ("llama3-8b", "llama3-70b", "mistral-7b", "gemma-7b", "qwen2-7b"):
    _cfg = MODEL_CONFIGS[_base]
    MODEL_CONFIGS[f"{_base}-int8"] = dataclasses.replace(
        _cfg, name=f"{_base}-int8", weight_quant="int8"
    )
del _base, _cfg


def get_model_config(name: str) -> ModelConfig:
    if name not in MODEL_CONFIGS:
        raise KeyError(f"unknown model '{name}'; available: {sorted(MODEL_CONFIGS)}")
    return MODEL_CONFIGS[name]

"""Model layer: Flax decoder-only transformer family.

One configurable implementation (``transformer.py``) covers every family in
``BASELINE.json.configs`` — Llama-3-8B/70B, Mistral-7B, Gemma-7B (RoPE + GQA +
RMSNorm + gated MLP, with Mistral's sliding window and Gemma's embedding scaling)
and GPT-2 (learned positions + LayerNorm + GELU MLP) — selected purely by
``ModelConfig`` flags so there is exactly one forward path to shard, test, and
optimize.
"""

from fairness_llm_tpu.models.configs import MODEL_CONFIGS, ModelConfig, get_model_config
from fairness_llm_tpu.models.transformer import Transformer, init_params

__all__ = [
    "ModelConfig",
    "MODEL_CONFIGS",
    "get_model_config",
    "Transformer",
    "init_params",
]

"""Fused decode-step attention (Pallas TPU): one kernel call per layer per step.

Why a kernel when decode attention is tiny: the round-3 device trace
(docs/PERFORMANCE.md) showed XLA lowering each layer's single-token attention
into several HBM-round-tripping fusions — scores written to HBM, read back
for softmax, probabilities written again, read for the PV reduce — costing
~140 us/layer where the data (a few MB of KV in VMEM) supports ~20 us. This
kernel computes one head per grid step entirely in VMEM: QK^T, joint
(shared-prefix + own-cache) online softmax, PV — nothing intermediate
touches HBM.

Layout contract (head-major, so each grid step's block is a legal TPU tile —
dynamic head indexing on the sublane dim is forbidden, so the wrapper
transposes to head-leading layouts; the transposes are step-local copies
XLA fuses into the cache-update neighborhood):
- q: [B, H, D] -> kernel sees [H, B, D], one [1, B, D] block per head
- k/v: [B, L, Hkv, D] -> [Hkv, B, L, D], GQA head h reads block h // rep
- valid: [B, L] bool — which cache slots hold real keys; for single-token
  decode this already encodes causality (slots after the write index are
  False), so it is the ONLY own-cache mask
- shared_k/v: [P, Hkv, D] -> [Hkv, P128, D] — optional prompt prefix common
  to every row, always causally visible; padded to a 128 multiple
  (loop-invariant: XLA hoists the pad+transpose out of the decode
  while_loop), masked by the true P inside the kernel

Supported when D % 64 == 0, L % 128 == 0, B % 8 == 0 (else callers fall back
to the XLA path). Sliding windows and the int8 cache use the XLA path.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
_BLOCK_L = 128  # own-cache block size (flash-style L iteration)


def decode_attn_supported(
    batch: int, cache_len: int, head_dim: int, shared_len: int = 0,
) -> bool:
    if not (batch % 8 == 0 and cache_len % _BLOCK_L == 0 and head_dim % 64 == 0):
        return False
    # VMEM bound: each grid step holds whole [1, B, L, D] k and v blocks
    # (double-buffered), the f32 shared-prefix operands (the shared matmul is
    # UNBLOCKED — sk/sv cast whole plus [B, P128] scores), and f32 scratch,
    # inside the 16 MB scoped budget; a tile-compatible but oversized shape
    # must fall back to XLA, not crash Mosaic. 4 bytes/elt is the
    # conservative (f32-input) width.
    p128 = -(-shared_len // 128) * 128
    kv_block_bytes = 2 * batch * cache_len * head_dim * 4
    shared_bytes = 2 * p128 * head_dim * 4 * 2 + batch * p128 * 4 * 3
    return kv_block_bytes + shared_bytes <= 8 * 1024 * 1024


def _kernel(
    q_ref,  # [1, B, D]
    k_ref,  # [1, B, L, D]
    v_ref,  # [1, B, L, D]
    valid_ref,  # [B, L] int32
    *rest,  # ([1, P128, D] sk, sv when shared) + o_ref [1, B, D]
    scale: float,
    shared_len: int,
):
    if shared_len:
        sk_ref, sv_ref, o_ref = rest
    else:
        o_ref = rest[0]

    B = q_ref.shape[1]
    D = q_ref.shape[2]
    L = k_ref.shape[2]
    q = q_ref[0, :, :].astype(jnp.float32) * scale  # [B, D]

    # Online-softmax accumulators, seeded from the shared-prefix part (one
    # [B, D] x [D, P128] MXU matmul — the prefix is read once per (head,
    # step), not once per row).
    if shared_len:
        sk = sk_ref[0, :, :].astype(jnp.float32)  # [P128, D]
        sv = sv_ref[0, :, :].astype(jnp.float32)
        s_sh = jnp.dot(q, sk.T, preferred_element_type=jnp.float32)
        sh_mask = (
            jax.lax.broadcasted_iota(jnp.int32, (1, sk.shape[0]), 1) < shared_len
        )
        s_sh = jnp.where(sh_mask, s_sh, NEG_INF)
        m0 = jnp.max(s_sh, axis=1)  # [B]
        p_sh = jnp.where(sh_mask, jnp.exp(s_sh - m0[:, None]), 0.0)
        l0 = jnp.sum(p_sh, axis=1)
        acc0 = jnp.dot(p_sh, sv, preferred_element_type=jnp.float32)  # [B, D]
    else:
        m0 = jnp.full((B,), NEG_INF, jnp.float32)
        l0 = jnp.zeros((B,), jnp.float32)
        acc0 = jnp.zeros((B, D), jnp.float32)

    # Own-cache attention in L-blocks of 128 (flash pattern): per-block f32
    # casts keep peak VMEM under the 16 MB scoped budget — a whole-cache f32
    # cast overflowed it at the sweep shape.
    def body(lb, carry):
        m_acc, l_acc, acc = carry
        kb = k_ref[0, :, pl.ds(lb * _BLOCK_L, _BLOCK_L), :].astype(jnp.float32)
        vb = v_ref[0, :, pl.ds(lb * _BLOCK_L, _BLOCK_L), :].astype(jnp.float32)
        mask = valid_ref[:, pl.ds(lb * _BLOCK_L, _BLOCK_L)] != 0  # [B, bl]
        # batched matvec as a VPU multiply-reduce, all in VMEM
        s = jnp.sum(q[:, None, :] * kb, axis=-1)  # [B, bl]
        s = jnp.where(mask, s, NEG_INF)
        m_blk = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_acc, m_blk)
        p = jnp.where(mask, jnp.exp(s - m_new[:, None]), 0.0)
        alpha = jnp.exp(m_acc - m_new)
        l_new = l_acc * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jnp.sum(p[:, :, None] * vb, axis=1)
        return m_new, l_new, acc

    m, l, acc = jax.lax.fori_loop(0, L // _BLOCK_L, body, (m0, l0, acc0))
    o_ref[0, :, :] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def decode_attention(
    q: jnp.ndarray,  # [B, H, D]
    k: jnp.ndarray,  # [B, L, Hkv, D]
    v: jnp.ndarray,
    valid: jnp.ndarray,  # [B, L] bool
    shared_kv: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,  # ([P, Hkv, D]) x2
    interpret: bool = False,
) -> jnp.ndarray:  # [B, H, D]
    B, H, D = q.shape
    L = k.shape[1]
    Hkv = k.shape[2]
    rep = H // Hkv
    if not decode_attn_supported(B, L, D):
        raise ValueError(f"unsupported decode-attention shape B={B} L={L} D={D}")
    scale = D ** -0.5

    qh = q.transpose(1, 0, 2)  # [H, B, D]
    kh = k.transpose(2, 0, 1, 3)  # [Hkv, B, L, D]
    vh = v.transpose(2, 0, 1, 3)
    args = [qh, kh, vh, valid.astype(jnp.int32)]
    in_specs = [
        pl.BlockSpec((1, B, D), lambda h: (h, 0, 0)),
        pl.BlockSpec((1, B, L, D), lambda h: (h // rep, 0, 0, 0)),
        pl.BlockSpec((1, B, L, D), lambda h: (h // rep, 0, 0, 0)),
        pl.BlockSpec((B, L), lambda h: (0, 0)),
    ]

    if shared_kv is not None and shared_kv[0].shape[0] == 0:
        # A zero-length prefix is the no-prefix case; passing empty refs
        # through would desync _kernel's ref unpacking.
        shared_kv = None
    shared_len = 0
    if shared_kv is not None:
        sk, sv = shared_kv
        shared_len = sk.shape[0]
        pad = (-shared_len) % 128
        if pad:
            sk = jnp.pad(sk, ((0, pad), (0, 0), (0, 0)))
            sv = jnp.pad(sv, ((0, pad), (0, 0), (0, 0)))
        p128 = sk.shape[0]
        args += [sk.transpose(1, 0, 2), sv.transpose(1, 0, 2)]  # [Hkv, P128, D]
        in_specs += [
            pl.BlockSpec((1, p128, D), lambda h: (h // rep, 0, 0)),
            pl.BlockSpec((1, p128, D), lambda h: (h // rep, 0, 0)),
        ]

    kernel = functools.partial(_kernel, scale=scale, shared_len=shared_len)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((H, B, D), q.dtype),
        grid=(H,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, B, D), lambda h: (h, 0, 0)),
        interpret=interpret,
    )(*args)
    return out.transpose(1, 0, 2)  # [B, H, D]

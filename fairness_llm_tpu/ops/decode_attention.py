"""Fused decode-step attention (Pallas TPU): one kernel call per layer per step.

Why a kernel when decode attention is tiny: the round-3 device trace
(docs/PERFORMANCE.md) showed XLA lowering each layer's single-token attention
into several HBM-round-tripping fusions — scores written to HBM, read back
for softmax, probabilities written again, read for the PV reduce — costing
~140 us/layer where the data (a few MB of KV in VMEM) supports ~20 us. This
kernel computes one head per grid step entirely in VMEM: QK^T, joint
(shared-prefix + own-cache) online softmax, PV — nothing intermediate
touches HBM.

int8 cache mode (round 4): with ``kv_cache_quant`` the cache stores int8
values + per-(slot, head) f32 scales; the kernel streams the int8 blocks
and dequantizes IN VMEM. The per-slot scale commutes with the head_dim
reduction, so dequant costs two [B, block] elementwise multiplies (fold
k_scale into the scores, v_scale into the probabilities), never a scaled
[B, block, D] temporary — and HBM sees half the bytes of the bf16 cache.
The XLA int8 path instead relies on fusing ``dequant -> attention``, which
the round-3 trace shows it does imperfectly (separate fusions per stage).

Layout contract (head-major, so each grid step's block is a legal TPU tile —
dynamic head indexing on the sublane dim is forbidden, so the wrapper
transposes to head-leading layouts; the transposes are step-local copies
XLA fuses into the cache-update neighborhood):
- q: [B, H, D] -> kernel sees [H, B, D], one [1, B, D] block per head
- k/v: [B, L, Hkv, D] -> [Hkv, B, L, D], GQA head h reads block h // rep
- k/v_scale (int8 mode): [B, L, Hkv] f32 -> [Hkv, B, L]
- valid: [B, L] bool — which cache slots hold real keys; for single-token
  decode this already encodes causality (slots after the write index are
  False), so it is the ONLY own-cache mask
- shared_k/v: [P, Hkv, D] -> [Hkv, P128, D] — optional prompt prefix common
  to every row, always causally visible; padded to a 128 multiple
  (loop-invariant: XLA hoists the pad+transpose out of the decode
  while_loop), masked by the true P inside the kernel. The prefix KV is
  bf16 even in int8-cache mode (it is read once per step, not per row —
  see runtime/engine._prefix_fn).

Supported when D % 64 == 0, L % 128 == 0, B % 8 == 0 (else callers fall back
to the XLA path). Sliding windows use the XLA path.

Multi-query mode (round 6 / ISSUE 1): a speculative verify step carries
q_len = k+1 queries per row, each owning cache slot ``q_offsets[b] + i``.
Pass q as [B, Q, H, D] with ``q_offsets`` [B]; the kernel applies the
causal window ``j <= q_offsets[b] + i`` on top of ``valid`` and runs QK^T /
PV as bb-batched MXU ``dot_general``s (see ``_kernel_multi``).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
_BLOCK_L = 128  # own-cache block size (flash-style L iteration)


def _block_bytes(bb: int, cache_len: int, head_dim: int, shared_len: int,
                 kv_itemsize: int, q_len: int = 1) -> int:
    """Scoped-VMEM bytes one (head, batch-block) grid step needs: the
    [1, bb, L, D] k and v block refs (plus their [bb, L] f32 scales in int8
    mode), the f32 shared-prefix operands (the shared matmul is UNBLOCKED —
    sk/sv cast whole plus [bb, P128] scores), and the kernel body's f32
    temporaries — ~six [bb, 128, D] tensors live across the fori body
    (kb/vb casts, the q*kb product, p, and the PV expansion). The temp term
    is calibrated against Mosaic's own OOM report (bb=120 int8 L=256 D=64:
    predicted 27.8 MB vs reported 27.73 MB).

    ``q_len > 1`` (speculative verify windows): the per-block score/prob
    temporaries and the shared-prefix scores gain a Q axis, and the
    accumulators/q tiles scale by Q; the kb/vb casts don't. Conservative
    additive model — a gate miss degrades to the XLA path via the engine's
    compile-failure fallback, never fails a study."""
    p128 = -(-shared_len // 128) * 128
    kv = 2 * bb * cache_len * head_dim * kv_itemsize
    if kv_itemsize == 1:
        kv += 2 * bb * cache_len * 4  # the f32 scales
    shared = 2 * p128 * head_dim * 4 * 2 + bb * q_len * p128 * 4 * 3
    temps = 6 * bb * _BLOCK_L * head_dim * 4
    if q_len > 1:
        temps += 4 * bb * q_len * _BLOCK_L * 4  # [bb, Q, bl] scores/probs/mask
        temps += 3 * bb * q_len * head_dim * 4  # q tile + acc + PV output
    return kv + shared + temps


def _pick_batch_block(batch: int, cache_len: int, head_dim: int,
                      shared_len: int, kv_itemsize: int, q_len: int = 1) -> int:
    """Largest batch block (multiple of 8, dividing batch) whose grid step
    fits the 16 MB scoped-VMEM window (minus 1 MB slack); 0 if even 8 rows
    don't fit. Rows are independent, so blocking the batch is free
    parallelism — it's what keeps the kernel eligible at batch 192/360
    where a whole-batch block would blow VMEM.

    NOTE the budget is intentionally NOT more conservative: the 48-row
    sweep shape sits exactly at the 15 MiB boundary and has run whole-batch
    on v5e since round 3 — extra slack would silently split a proven-live
    configuration. Because ``_block_bytes``'s temp term is a calibrated
    model (fitted to one Mosaic OOM report), a shape where it
    under-predicts can still pass the gate and fail in Mosaic; the engine
    catches that compile failure and retries with the kernel disabled
    (DecodeEngine's VMEM-fallback), so a gate miss degrades to the XLA
    path instead of failing the study."""
    budget = 15 * 1024 * 1024
    best = 0
    for bb in range(8, batch + 1, 8):
        if batch % bb:
            continue
        if _block_bytes(bb, cache_len, head_dim, shared_len, kv_itemsize,
                        q_len) <= budget:
            best = bb
    return best


def decode_attn_supported(
    batch: int, cache_len: int, head_dim: int, shared_len: int = 0,
    kv_itemsize: int = 4, q_len: int = 1,
) -> bool:
    """Static shape gate + VMEM budget for the fused decode kernel.

    ``kv_itemsize``: bytes/element the k and v BLOCKS occupy in VMEM — 4 for
    the conservative f32-input default (bf16 callers may pass 2; int8-cache
    callers pass 1, which roughly quadruples the eligible shape envelope).
    ``q_len``: queries per row — 1 for plain decode, k+1 for a speculative
    verify window (small: capped at 16 by the model gate).
    """
    if not (batch % 8 == 0 and cache_len % _BLOCK_L == 0 and head_dim % 64 == 0):
        return False
    return _pick_batch_block(
        batch, cache_len, head_dim, shared_len, kv_itemsize, q_len
    ) > 0


def _kernel(
    q_ref,  # [1, B, D]
    k_ref,  # [1, B, L, D] (model dtype, or int8 in quant mode)
    v_ref,  # [1, B, L, D]
    valid_ref,  # [B, L] int32
    *rest,  # ([1, B, L] ks, vs when quant) + ([1, P128, D] sk, sv when shared) + o_ref
    scale: float,
    shared_len: int,
    quant: bool,
):
    rest = list(rest)
    ks_ref = vs_ref = sk_ref = sv_ref = None
    if quant:
        ks_ref, vs_ref = rest[0], rest[1]
        rest = rest[2:]
    if shared_len:
        sk_ref, sv_ref = rest[0], rest[1]
        rest = rest[2:]
    o_ref = rest[0]

    B = q_ref.shape[1]
    D = q_ref.shape[2]
    L = k_ref.shape[2]
    q = q_ref[0, :, :].astype(jnp.float32) * scale  # [B, D]

    # Online-softmax accumulators, seeded from the shared-prefix part (one
    # [B, D] x [D, P128] MXU matmul — the prefix is read once per (head,
    # step), not once per row).
    if shared_len:
        sk = sk_ref[0, :, :].astype(jnp.float32)  # [P128, D]
        sv = sv_ref[0, :, :].astype(jnp.float32)
        s_sh = jnp.dot(q, sk.T, preferred_element_type=jnp.float32)
        sh_mask = (
            jax.lax.broadcasted_iota(jnp.int32, (1, sk.shape[0]), 1) < shared_len
        )
        s_sh = jnp.where(sh_mask, s_sh, NEG_INF)
        m0 = jnp.max(s_sh, axis=1)  # [B]
        p_sh = jnp.where(sh_mask, jnp.exp(s_sh - m0[:, None]), 0.0)
        l0 = jnp.sum(p_sh, axis=1)
        acc0 = jnp.dot(p_sh, sv, preferred_element_type=jnp.float32)  # [B, D]
    else:
        m0 = jnp.full((B,), NEG_INF, jnp.float32)
        l0 = jnp.zeros((B,), jnp.float32)
        acc0 = jnp.zeros((B, D), jnp.float32)

    # Own-cache attention in L-blocks of 128 (flash pattern): per-block f32
    # casts keep peak VMEM under the 16 MB scoped budget — a whole-cache f32
    # cast overflowed it at the sweep shape.
    def body(lb, carry):
        m_acc, l_acc, acc = carry
        kb = k_ref[0, :, pl.ds(lb * _BLOCK_L, _BLOCK_L), :].astype(jnp.float32)
        vb = v_ref[0, :, pl.ds(lb * _BLOCK_L, _BLOCK_L), :].astype(jnp.float32)
        mask = valid_ref[:, pl.ds(lb * _BLOCK_L, _BLOCK_L)] != 0  # [B, bl]
        # batched matvec as a VPU multiply-reduce, all in VMEM
        s = jnp.sum(q[:, None, :] * kb, axis=-1)  # [B, bl]
        if quant:
            # per-slot k scale commutes with the D-reduction: scale the
            # SCORES, not the [B, bl, D] key block
            s = s * ks_ref[0, :, pl.ds(lb * _BLOCK_L, _BLOCK_L)]
        s = jnp.where(mask, s, NEG_INF)
        m_blk = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_acc, m_blk)
        p = jnp.where(mask, jnp.exp(s - m_new[:, None]), 0.0)
        alpha = jnp.exp(m_acc - m_new)
        l_new = l_acc * alpha + jnp.sum(p, axis=1)  # normalizer: UNSCALED p
        if quant:
            # v scale likewise commutes with the slot reduction: fold it
            # into the probabilities used for PV (only), not into vb
            p = p * vs_ref[0, :, pl.ds(lb * _BLOCK_L, _BLOCK_L)]
        acc = acc * alpha[:, None] + jnp.sum(p[:, :, None] * vb, axis=1)
        return m_new, l_new, acc

    m, l, acc = jax.lax.fori_loop(0, L // _BLOCK_L, body, (m0, l0, acc0))
    o_ref[0, :, :] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _kernel_multi(
    q_ref,  # [1, B, Q, D]
    k_ref,  # [1, B, L, D] (model dtype, or int8 in quant mode)
    v_ref,  # [1, B, L, D]
    valid_ref,  # [B, L] int32
    offs_ref,  # [B, 1] int32 — per-row first-query slot index
    *rest,  # ([1, B, L] ks, vs when quant) + ([1, P128, D] sk, sv when shared) + o_ref
    scale: float,
    shared_len: int,
    quant: bool,
):
    """Speculative-verify variant of ``_kernel``: Q queries per row in one
    grid step. Query i of row b occupies cache slot ``offs[b] + i``; the
    causal rule is ``j <= offs[b] + i`` ANDed with ``valid`` (slots beyond
    the verify window are already invalid in ``valid``, slots inside it need
    the triangular window). QK^T and PV run as bb-batched MXU ``dot_general``
    ([bb, Q, D] x [bb, bl, D]) instead of the single-query VPU
    multiply-reduce; everything else (online softmax over L-blocks, the
    shared-prefix seed, int8 scale folding) matches ``_kernel``.
    """
    rest = list(rest)
    ks_ref = vs_ref = sk_ref = sv_ref = None
    if quant:
        ks_ref, vs_ref = rest[0], rest[1]
        rest = rest[2:]
    if shared_len:
        sk_ref, sv_ref = rest[0], rest[1]
        rest = rest[2:]
    o_ref = rest[0]

    B = q_ref.shape[1]
    Q = q_ref.shape[2]
    D = q_ref.shape[3]
    L = k_ref.shape[2]
    q = q_ref[0].astype(jnp.float32) * scale  # [B, Q, D]
    offs = offs_ref[:, 0]  # [B]
    qi = jax.lax.broadcasted_iota(jnp.int32, (1, Q, 1), 1)  # [1, Q, 1]

    if shared_len:
        # Shared-prefix slots precede every query: always causally visible.
        sk = sk_ref[0].astype(jnp.float32)  # [P128, D]
        sv = sv_ref[0].astype(jnp.float32)
        p128 = sk.shape[0]
        s_sh = jax.lax.dot_general(
            q.reshape(B * Q, D), sk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).reshape(B, Q, p128)
        sh_mask = (
            jax.lax.broadcasted_iota(jnp.int32, (1, 1, p128), 2) < shared_len
        )
        s_sh = jnp.where(sh_mask, s_sh, NEG_INF)
        m0 = jnp.max(s_sh, axis=-1)  # [B, Q]
        p_sh = jnp.where(sh_mask, jnp.exp(s_sh - m0[..., None]), 0.0)
        l0 = jnp.sum(p_sh, axis=-1)
        acc0 = jax.lax.dot_general(
            p_sh.reshape(B * Q, p128), sv, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).reshape(B, Q, D)
    else:
        m0 = jnp.full((B, Q), NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, Q), jnp.float32)
        acc0 = jnp.zeros((B, Q, D), jnp.float32)

    def body(lb, carry):
        m_acc, l_acc, acc = carry
        kb = k_ref[0, :, pl.ds(lb * _BLOCK_L, _BLOCK_L), :].astype(jnp.float32)
        vb = v_ref[0, :, pl.ds(lb * _BLOCK_L, _BLOCK_L), :].astype(jnp.float32)
        vmask = valid_ref[:, pl.ds(lb * _BLOCK_L, _BLOCK_L)] != 0  # [B, bl]
        j = lb * _BLOCK_L + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, _BLOCK_L), 2
        )  # [1, 1, bl]
        mask = vmask[:, None, :] & (j <= offs[:, None, None] + qi)  # [B, Q, bl]
        s = jax.lax.dot_general(
            q, kb, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [B, Q, bl]
        if quant:
            s = s * ks_ref[0, :, pl.ds(lb * _BLOCK_L, _BLOCK_L)][:, None, :]
        s = jnp.where(mask, s, NEG_INF)
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_acc, m_blk)
        p = jnp.where(mask, jnp.exp(s - m_new[..., None]), 0.0)
        alpha = jnp.exp(m_acc - m_new)
        l_new = l_acc * alpha + jnp.sum(p, axis=-1)
        if quant:
            p = p * vs_ref[0, :, pl.ds(lb * _BLOCK_L, _BLOCK_L)][:, None, :]
        acc = acc * alpha[..., None] + jax.lax.dot_general(
            p, vb, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc

    m, l, acc = jax.lax.fori_loop(0, L // _BLOCK_L, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[..., None]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def decode_attention(
    q: jnp.ndarray,  # [B, H, D], or [B, Q, H, D] with q_offsets (verify window)
    k: jnp.ndarray,  # [B, L, Hkv, D] (int8 when scales given)
    v: jnp.ndarray,
    valid: jnp.ndarray,  # [B, L] bool
    shared_kv: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,  # ([P, Hkv, D]) x2
    k_scale: Optional[jnp.ndarray] = None,  # [B, L, Hkv] f32 (int8 cache mode)
    v_scale: Optional[jnp.ndarray] = None,
    q_offsets: Optional[jnp.ndarray] = None,  # [B] int32 first-query slot (4D q)
    interpret: bool = False,
) -> jnp.ndarray:  # [B, H, D] (3D q) or [B, Q, H, D] (4D q)
    multi = q.ndim == 4
    if multi:
        if q_offsets is None:
            raise ValueError("multi-query decode attention needs q_offsets")
        B, Q, H, D = q.shape
    else:
        B, H, D = q.shape
        Q = 1
    L = k.shape[1]
    Hkv = k.shape[2]
    rep = H // Hkv
    quant = k_scale is not None
    if quant and v_scale is None:
        raise ValueError("int8 cache mode needs both k_scale and v_scale")
    shared_true_len = 0 if shared_kv is None else shared_kv[0].shape[0]
    # Account k/v VMEM at the width actually streamed (bf16 callers get the
    # 2-byte envelope, matching the model gate's accounting).
    itemsize = 1 if quant else jnp.dtype(k.dtype).itemsize
    if not decode_attn_supported(B, L, D, shared_true_len, kv_itemsize=itemsize,
                                 q_len=Q):
        raise ValueError(
            f"unsupported decode-attention shape B={B} L={L} D={D} Q={Q}"
        )
    bb = _pick_batch_block(B, L, D, shared_true_len, itemsize, Q)
    scale = D ** -0.5

    kh = k.transpose(2, 0, 1, 3)  # [Hkv, B, L, D]
    vh = v.transpose(2, 0, 1, 3)
    if multi:
        qh = q.transpose(2, 0, 1, 3)  # [H, B, Q, D]
        args = [qh, kh, vh, valid.astype(jnp.int32),
                q_offsets.astype(jnp.int32)[:, None]]
        in_specs = [
            pl.BlockSpec((1, bb, Q, D), lambda h, b: (h, b, 0, 0)),
            pl.BlockSpec((1, bb, L, D), lambda h, b: (h // rep, b, 0, 0)),
            pl.BlockSpec((1, bb, L, D), lambda h, b: (h // rep, b, 0, 0)),
            pl.BlockSpec((bb, L), lambda h, b: (b, 0)),
            pl.BlockSpec((bb, 1), lambda h, b: (b, 0)),
        ]
    else:
        qh = q.transpose(1, 0, 2)  # [H, B, D]
        args = [qh, kh, vh, valid.astype(jnp.int32)]
        in_specs = [
            pl.BlockSpec((1, bb, D), lambda h, b: (h, b, 0)),
            pl.BlockSpec((1, bb, L, D), lambda h, b: (h // rep, b, 0, 0)),
            pl.BlockSpec((1, bb, L, D), lambda h, b: (h // rep, b, 0, 0)),
            pl.BlockSpec((bb, L), lambda h, b: (b, 0)),
        ]
    if quant:
        args += [
            k_scale.transpose(2, 0, 1).astype(jnp.float32),  # [Hkv, B, L]
            v_scale.transpose(2, 0, 1).astype(jnp.float32),
        ]
        in_specs += [
            pl.BlockSpec((1, bb, L), lambda h, b: (h // rep, b, 0)),
            pl.BlockSpec((1, bb, L), lambda h, b: (h // rep, b, 0)),
        ]

    if shared_kv is not None and shared_kv[0].shape[0] == 0:
        # A zero-length prefix is the no-prefix case; passing empty refs
        # through would desync _kernel's ref unpacking.
        shared_kv = None
    shared_len = 0
    if shared_kv is not None:
        sk, sv = shared_kv
        shared_len = sk.shape[0]
        pad = (-shared_len) % 128
        if pad:
            sk = jnp.pad(sk, ((0, pad), (0, 0), (0, 0)))
            sv = jnp.pad(sv, ((0, pad), (0, 0), (0, 0)))
        p128 = sk.shape[0]
        args += [sk.transpose(1, 0, 2), sv.transpose(1, 0, 2)]  # [Hkv, P128, D]
        # b-invariant index: consecutive batch-block grid steps revisit the
        # same prefix block, so Pallas doesn't re-DMA it per step.
        in_specs += [
            pl.BlockSpec((1, p128, D), lambda h, b: (h // rep, 0, 0)),
            pl.BlockSpec((1, p128, D), lambda h, b: (h // rep, 0, 0)),
        ]

    kernel = functools.partial(
        _kernel_multi if multi else _kernel,
        scale=scale, shared_len=shared_len, quant=quant,
    )
    if multi:
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((H, B, Q, D), q.dtype),
            grid=(H, B // bb),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, bb, Q, D), lambda h, b: (h, b, 0, 0)),
            interpret=interpret,
        )(*args)
        return out.transpose(1, 2, 0, 3)  # [B, Q, H, D]
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((H, B, D), q.dtype),
        grid=(H, B // bb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bb, D), lambda h, b: (h, b, 0)),
        interpret=interpret,
    )(*args)
    return out.transpose(1, 0, 2)  # [B, H, D]

"""Flash attention (Pallas TPU): fused causal attention for the prefill phase.

Why a kernel: prefill attention materializes [B, H, S, S] scores in HBM under
stock XLA when S is long; the flash pattern keeps each [block_q, block_k]
score tile in VMEM, folding into an online-softmax accumulator, so memory
traffic is O(S·D) instead of O(S²). This is the one op in the pipeline where
hand-tiling beats the compiler (pallas_guide.md tiling rules: last dim 128,
fp32 accumulation on the MXU).

Layout contract:
- q: [B, H, S, D], k/v: [B, Hkv, S, D] (GQA handled by the index map — each q
  head reads its kv head directly, no jnp.repeat materialization)
- left-padded batches: row b's valid keys are exactly positions
  ``S - lengths[b] ..< S``, so the padding mask needs only a scalar per row
  (prefetched to SMEM) rather than a [B, S] mask array
- causal masking over slot indices (left-padding keeps causality aligned)
- optional sliding window (Mistral): key j visible iff q_idx - j < window

Supported when S is a multiple of the 128-lane tile and D is a multiple of
64 (a 64-lane D tail pads to the 128-lane tile at half occupancy — see
``flash_supported``); callers fall back to the XLA path otherwise.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _kernel(
    lengths_ref,  # SMEM [1] int32 — this batch row's real token count
    q_ref,  # [1, 1, block_q, D]
    k_ref,  # [1, 1, S, D]
    v_ref,  # [1, 1, S, D]
    o_ref,  # [1, 1, block_q, D]
    *,
    seq_len: int,
    block_q: int,
    block_k: int,
    causal: bool,
    window: Optional[int],
    scale: float,
):
    qi = pl.program_id(2)
    q = q_ref[0, 0, :, :].astype(jnp.float32) * scale  # [bq, D]
    length = lengths_ref[pl.program_id(0)]
    pad_start = seq_len - length  # first valid slot

    q_idx = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        # keys strictly after the last query row of this block are never visible
        num_k_blocks = jnp.minimum(num_k_blocks, pl.cdiv((qi + 1) * block_q, block_k))

    def body(kb, carry):
        m_acc, l_acc, acc = carry
        k_blk = k_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)  # [bq, bk]

        k_idx = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = k_idx >= pad_start
        if causal:
            mask &= k_idx <= q_idx
        if window is not None:
            mask &= (q_idx - k_idx) < window
        s = jnp.where(mask, s, NEG_INF)

        m_blk = jnp.max(s, axis=1)  # [bq]
        m_new = jnp.maximum(m_acc, m_blk)
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_acc - m_new)
        l_new = l_acc * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jnp.dot(
            p.astype(v_blk.dtype), v_blk, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_k_blocks, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-30)[:, None]
    o_ref[0, 0, :, :] = out.astype(o_ref.dtype)


def flash_supported(seq_len: int, head_dim: int, block_q: int = 128, block_k: int = 128) -> bool:
    """head_dim >= 64: a 64-lane tail pads to the 128-lane tile (half-lane
    occupancy on the D axis) but the kernel stays correct and still beats the
    XLA dense path — prefill is score-matmul-bound, and the [bq, bk] score
    tiles are full 128x128 regardless of D. head_dim < 64 wastes > half the
    VMEM tile; fall back to XLA there."""
    return (
        seq_len % block_k == 0
        and seq_len >= block_q
        and seq_len % block_q == 0
        and head_dim % 64 == 0
    )


@functools.partial(
    jax.jit, static_argnames=("causal", "window", "block_q", "block_k", "interpret")
)
def flash_attention(
    q: jnp.ndarray,  # [B, H, S, D]
    k: jnp.ndarray,  # [B, Hkv, S, D]
    v: jnp.ndarray,  # [B, Hkv, S, D]
    lengths: jnp.ndarray,  # [B] int32 real token counts (left-padded layout)
    causal: bool = True,
    window: Optional[int] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    B, H, S, D = q.shape
    Hkv = k.shape[1]
    rep = H // Hkv
    if not flash_supported(S, D, block_q, block_k):
        raise ValueError(f"unsupported flash shape S={S} D={D}")
    scale = D ** -0.5

    grid = (B, H, S // block_q)
    kernel = functools.partial(
        _kernel,
        seq_len=S, block_q=block_q, block_k=block_k,
        causal=causal, window=window, scale=scale,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, *_: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, S, D), lambda b, h, qi, *_: (b, h // rep, 0, 0)),
            pl.BlockSpec((1, 1, S, D), lambda b, h, qi, *_: (b, h // rep, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, *_: (b, h, qi, 0)),
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(lengths.astype(jnp.int32), q, k, v)

"""Hand-written TPU kernels (Pallas) for the hot ops.

XLA's fusions cover most of this framework; kernels live here only where
hand-tiling beats the compiler — currently flash attention for the prefill
phase (the O(S^2) op that dominates long-prompt sweeps).
"""

from fairness_llm_tpu.ops.flash_attention import flash_attention, flash_supported

__all__ = ["flash_attention", "flash_supported"]

"""int8 weight-only matmul with dequantization INSIDE the Pallas tile loop.

Why a kernel instead of ``x @ (wq * scale)``: XLA hoists loop-invariant
computation out of decode loops. For int8-stored weights that "obvious"
dequant-at-use expression materializes a full bf16 copy of the parameter
tree in HBM (measured round 3: llama3-70b tp=8 — int8 args 8.84 GB/chip
would fit a v5e, but 35.2 GB of hoisted bf16 temps; docs/PERFORMANCE.md).
Inside a Pallas kernel the int8->bf16 conversion happens per [bk, bn] tile
in VMEM, so HBM only ever holds the int8 tree: weight-only-quantized
serving streams half the bytes AND fits models that bf16 cannot.

Scheme: symmetric per-output-channel quantization. ``w ≈ wq * scale[None, :]``
with ``wq`` int8 and ``scale`` float32. Because the scale is constant along
the contraction axis it commutes with the matmul:

    x @ (wq * scale[None, :]) == (x @ wq) * scale[None, :]

so the kernel runs the MXU matmul on (bf16 x, int8->bf16 wq) tiles with a
float32 accumulator and applies the scale once, on the final K step. The
int8->bf16 cast is exact (|q| <= 127 << 2^8), making the kernel numerically
equivalent to a bf16 matmul against the dequantized weights.

Sharding: ``quant_matmul_sharded`` wraps the kernel in a FULL-manual
``jax.shard_map`` over every mesh axis (Mosaic kernels refuse to lower in a
partially-auto SPMD context) — column-parallel (N sharded) runs purely
locally; row-parallel (K sharded) psums f32 partial products, the same
collective GSPMD inserts for the dense equivalent; batch (dp) sharding is
encoded in the specs rather than left to GSPMD. This is the
trace-time-lowered integration (works under AOT topology compilation, where
``custom_partitioning``'s runtime callback is unavailable).

The reference has no quantization support at all (its models are remote
APIs, SURVEY.md §0); this is the capability that puts Llama-3-70B tp=8 — a
``BASELINE.json`` target config — on a single v5e-8 slice.
"""

from __future__ import annotations

import contextvars
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

__all__ = [
    "quantize_weight",
    "dequantize_weight",
    "quant_tileable",
    "quant_matmul",
    "quant_matmul_sharded",
    "force_pallas",
]

_LANE = 128  # TPU lane width: last-dim tiling granule for every dtype

# Dispatch override for AOT lowering: ``jax.default_backend()`` reports the
# process's live backend, not the topology being lowered FOR — a CPU-pinned
# test process AOT-compiling against a TPU topology descriptor must still
# take the Pallas path (that's the thing being proven). Context-managed, not
# an argument, because the call sites sit inside flax modules. A ContextVar
# (not a module-level flag) so a concurrent trace in another thread — e.g.
# a test runner compiling while the bench's AOT check runs — can't observe
# this thread's override.
_FORCE_PALLAS: contextvars.ContextVar[int] = contextvars.ContextVar(
    "quant_matmul_force_pallas", default=0
)


class force_pallas:
    """``with force_pallas():`` — treat the lowering target as TPU."""

    def __enter__(self):
        self._token = _FORCE_PALLAS.set(_FORCE_PALLAS.get() + 1)
        return self

    def __exit__(self, *exc):
        _FORCE_PALLAS.reset(self._token)
        return False


# ---------------------------------------------------------------------------
# Quantization (host/XLA side)
# ---------------------------------------------------------------------------


def quantize_weight(w: jnp.ndarray, axis: int = 0) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[K, N] float -> (int8 [K, N], float32 scale [N]); symmetric per-channel.

    ``axis`` is the contraction (reduced) axis; scales live on the other one.
    """
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis)
    scale = jnp.maximum(amax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / jnp.expand_dims(scale, axis)), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def dequantize_weight(wq: jnp.ndarray, scale: jnp.ndarray, dtype=jnp.bfloat16) -> jnp.ndarray:
    return (wq.astype(jnp.float32) * scale[None, :].astype(jnp.float32)).astype(dtype)


# ---------------------------------------------------------------------------
# Kernel
# ---------------------------------------------------------------------------


def _matmul_kernel(x_ref, wq_ref, scale_ref, o_ref, acc_ref, *, nk: int):
    """One (m, n) output tile; grid dim 2 walks K accumulating into VMEM f32."""

    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # int8 -> x.dtype happens HERE, on a [bk, bn] tile already in VMEM — the
    # whole point of the kernel: no dequantized copy of the weight ever
    # exists in HBM, and XLA cannot hoist what it cannot see.
    acc_ref[:] += jnp.dot(
        x_ref[:], wq_ref[:].astype(x_ref.dtype),
        preferred_element_type=jnp.float32,
    )

    @pl.when(pl.program_id(2) == nk - 1)
    def _done():
        o_ref[:] = (acc_ref[:] * scale_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _pick_block(dim: int, candidates=(512, 256, 128)) -> int:
    for c in candidates:
        if dim % c == 0:
            return c
    return 0


def quant_tileable(k: int, n: int) -> bool:
    """Static gate: can the Pallas kernel tile a [k, n] int8 weight?

    Both dims must hit a 128-multiple block (last-dim lane constraint; K
    blocks stay MXU-sized). Callers fall back to the XLA dequant matmul when
    this fails (e.g. llama's 128256 vocab sharded 8 ways -> 16032, not a
    lane multiple).
    """
    return k > 0 and n > 0 and k % _LANE == 0 and n % _LANE == 0


def _quant_matmul_pallas(x, wq, scale, interpret: bool, out_dtype):
    m, k = x.shape
    _, n = wq.shape
    bm = m if m % 8 == 0 else -(-m // 8) * 8
    if bm != m:
        x = jnp.pad(x, ((0, bm - m), (0, 0)))
    bm_t = min(bm, 256)
    while bm % bm_t:
        bm_t //= 2
    bk, bn = _pick_block(k), _pick_block(n)
    nk = k // bk
    out = pl.pallas_call(
        functools.partial(_matmul_kernel, nk=nk),
        grid=(bm // bm_t, n // bn, nk),
        in_specs=[
            pl.BlockSpec((bm_t, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm_t, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((bm, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm_t, bn), jnp.float32)],
        # CompilerParams was TPUCompilerParams before jax 0.7.
        compiler_params=getattr(
            pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
        )(dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(x, wq, scale[None, :])
    return out[:m]


def quant_matmul(
    x: jnp.ndarray,
    wq: jnp.ndarray,
    scale: jnp.ndarray,
    *,
    interpret: bool = False,
    out_dtype=None,
) -> jnp.ndarray:
    """``x [M, K] @ dequant(wq [K, N], scale [N]) -> [M, N]``.

    Pallas on TPU (or under ``interpret=True`` anywhere); otherwise the XLA
    expression with the SAME operation order as the kernel (cast-then-matmul-
    then-scale) so both paths agree to float rounding, not just mathematically.
    """
    out_dtype = out_dtype or x.dtype
    on_tpu = jax.default_backend() == "tpu" or bool(_FORCE_PALLAS.get())
    if (on_tpu or interpret) and quant_tileable(*wq.shape):
        return _quant_matmul_pallas(x, wq, scale, interpret, out_dtype)
    y = jnp.dot(x, wq.astype(x.dtype), preferred_element_type=jnp.float32)
    return (y * scale[None, :].astype(jnp.float32)).astype(out_dtype)


def quant_matmul_sharded(
    x: jnp.ndarray,
    wq: jnp.ndarray,
    scale: jnp.ndarray,
    mesh: jax.sharding.Mesh,
    k_axis: Optional[str],
    n_axis: Optional[str],
    b_axis: Optional[str] = None,
    *,
    interpret: bool = False,
    out_dtype=None,
) -> jnp.ndarray:
    """The kernel under ``shard_map``, manual over EVERY mesh axis.

    ``k_axis``/``n_axis``: mesh axis (or None) sharding the weight's
    contraction / output dim; ``b_axis``: the axis sharding x's rows (dp).
    Column-parallel (n_axis) is purely local; row-parallel (k_axis) psums
    partial products — exactly the collective GSPMD inserts for the dense
    row-parallel matmul. The row-parallel psum accumulates in float32 (each
    shard's kernel output stays f32 until after the all-reduce) to match
    the dense GSPMD path, which all-reduces the f32 dot output before the
    downcast — casting shards to bf16 pre-psum would add avoidable
    accumulation error at tp=8 (e.g. the 70B down_proj).

    Why full-manual: Mosaic kernels refuse to lower in a partially-auto
    SPMD context (``tpu_custom_call.py`` requires manual_axes == all mesh
    axes), so the wrap names every axis and encodes batch sharding in the
    specs instead of leaving it to GSPMD. Axes that shard nothing here are
    manual-but-unused (their spec entries are None == replicated).
    """
    out_dtype = out_dtype or x.dtype

    def local(xl, wql, scalel):
        if k_axis is not None:
            y = quant_matmul(
                xl, wql, scalel, interpret=interpret, out_dtype=jnp.float32
            )
            return jax.lax.psum(y, k_axis).astype(out_dtype)
        return quant_matmul(xl, wql, scalel, interpret=interpret, out_dtype=out_dtype)

    from fairness_llm_tpu.parallel.sharding import compat_shard_map

    return compat_shard_map(
        local,
        mesh,
        in_specs=(P(b_axis, k_axis), P(k_axis, n_axis), P(n_axis)),
        out_specs=P(b_axis, n_axis),
    )(x, wq, scale)

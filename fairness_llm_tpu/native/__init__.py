"""Native (C) data-path components, bound via ctypes.

The reference has zero native code (SURVEY.md §2) and parses the 1M-row
``ratings.dat`` with pandas' python engine; here the hot parse is ~50 lines of
C compiled on first use (``cc -O3 -shared``) and cached next to the source.
Everything degrades gracefully: if no compiler is available the callers fall
back to the pure-Python parser (``data/movielens.py``).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "parse_dat.c")
_SO = os.path.join(_DIR, "_parse_dat.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> Optional[ctypes.CDLL]:
    """Compile (once) and load the parser library; None if unavailable."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        try:
            if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
                cc = os.environ.get("CC", "cc")
                # Build to a per-pid temp path, then atomically rename: multiple
                # processes (multi-host ranks, pytest -n) may race the first
                # build, and a concurrently-truncated .so would poison CDLL.
                tmp = f"{_SO}.{os.getpid()}.tmp"
                try:
                    subprocess.run(
                        [cc, "-O3", "-shared", "-fPIC", _SRC, "-o", tmp],
                        check=True, capture_output=True, timeout=120,
                    )
                    os.replace(tmp, _SO)
                finally:
                    if os.path.exists(tmp):  # failed/timed-out compile leftovers
                        os.unlink(tmp)
                logger.info("built native parser %s", _SO)
            lib = ctypes.CDLL(_SO)
            lib.parse_ratings.restype = ctypes.c_long
            lib.parse_ratings.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_long,
            ]
            lib.count_lines.restype = ctypes.c_long
            lib.count_lines.argtypes = [ctypes.c_char_p]
            _lib = lib
            return lib
        except Exception as e:  # noqa: BLE001 — any failure means "no native path"
            logger.info("native parser unavailable (%s); using pure Python", e)
            _build_failed = True
            return None


def available() -> bool:
    return _build() is not None


def parse_ratings(path: str) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Parse ``user::movie::rating[::ts]`` rows -> (users, movies, values).

    Returns None when the native library can't be built — callers fall back.
    """
    lib = _build()
    if lib is None:
        return None
    encoded = path.encode()
    n_lines = lib.count_lines(encoded)
    if n_lines < 0:
        raise FileNotFoundError(path)
    users = np.empty(n_lines, dtype=np.int32)
    movies = np.empty(n_lines, dtype=np.int32)
    values = np.empty(n_lines, dtype=np.float32)
    n = lib.parse_ratings(
        encoded,
        users.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        movies.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n_lines,
    )
    if n == -3:
        raise ValueError(f"malformed line in {path}")
    if n < 0:
        raise IOError(f"native parse failed ({n}) for {path}")
    return users[:n], movies[:n], values[:n]

/* Fast single-pass parser for MovieLens "::"-separated numeric tables.
 *
 * The reference parses ratings.dat (1,000,209 rows) with pandas' *python*
 * engine because of the two-char separator (reference
 * phase1_bias_detection.py:40-46) — the slowest possible path. This parser
 * does one pass over the raw bytes, no allocation per row, writing straight
 * into caller-provided numpy buffers via ctypes.
 *
 * Contract: each line is `a::b::c[::d...]` with the first three fields
 * numeric (user_id::movie_id::rating). Extra fields (timestamp) are skipped.
 * Returns the number of rows parsed, or -1 on I/O error, -2 if out_cap was
 * too small.
 */

#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <stdint.h>

static const char *parse_long(const char *p, const char *end, long *out) {
    long v = 0;
    int neg = 0;
    if (p < end && *p == '-') { neg = 1; p++; }
    while (p < end && *p >= '0' && *p <= '9') {
        /* Clamp instead of overflowing: a hostile digit run must not trigger
         * signed-overflow UB. Real ids are < 2^31; clamped rows then fail the
         * int32 range downstream rather than corrupting memory semantics. */
        if (v < (1L << 56)) v = v * 10 + (*p - '0');
        p++;
    }
    *out = neg ? -v : v;
    return p;
}

static const char *parse_double(const char *p, const char *end, double *out) {
    long ip = 0;
    p = parse_long(p, end, &ip);
    double v = (double)ip;
    if (p < end && *p == '.') {
        p++;
        double scale = 0.1;
        while (p < end && *p >= '0' && *p <= '9') {
            v += (*p - '0') * scale;
            scale *= 0.1;
            p++;
        }
    }
    *out = v;
    return p;
}

/* Exactly "::" between numeric fields — a single colon or a ":::" run makes
 * the NEXT field start with ':' which python's int()/split("::") combination
 * rejects, so both are malformed here too. NULL signals the error. */
static const char *expect_sep(const char *p, const char *end) {
    if (p + 1 >= end || p[0] != ':' || p[1] != ':') return NULL;
    p += 2;
    if (p < end && *p == ':') return NULL; /* ":::" -> next field starts with ':' */
    return p;
}

long parse_ratings(const char *path, int32_t *users, int32_t *movies,
                   float *values, long out_cap) {
    FILE *f = fopen(path, "rb");
    if (!f) return -1;
    /* ftell on a non-seekable path (FIFO) returns -1; feeding that size to
     * malloc/fread would be a 0-byte buffer with an unbounded read. */
    if (fseek(f, 0, SEEK_END) != 0) { fclose(f); return -1; }
    long size = ftell(f);
    if (size < 0) { fclose(f); return -1; }
    if (fseek(f, 0, SEEK_SET) != 0) { fclose(f); return -1; }
    char *buf = (char *)malloc(size + 1);
    if (!buf) { fclose(f); return -1; }
    if ((long)fread(buf, 1, size, f) != size) { free(buf); fclose(f); return -1; }
    fclose(f);
    buf[size] = '\0';

    const char *p = buf;
    const char *end = buf + size;
    long n = 0;
    while (p < end) {
        while (p < end && (*p == '\n' || *p == '\r')) p++;
        if (p >= end) break;
        if (n >= out_cap) { free(buf); return -2; }
        long user, movie;
        double val;
        const char *q;
        /* Strict: every field must consume digits and be followed by the
         * separator (or EOL for the last). A malformed line returns -3 so the
         * caller raises — matching the pure-Python path's ValueError instead
         * of silently emitting phantom (0, 0, 0.0) rows. */
        q = parse_long(p, end, &user);
        if (q == p) { free(buf); return -3; }
        p = expect_sep(q, end);
        if (!p) { free(buf); return -3; }
        q = parse_long(p, end, &movie);
        if (q == p) { free(buf); return -3; }
        p = expect_sep(q, end);
        if (!p) { free(buf); return -3; }
        q = parse_double(p, end, &val);
        if (q == p) { free(buf); return -3; }
        if (q < end && *q != ':' && *q != '\n' && *q != '\r') { free(buf); return -3; }
        /* After the rating: "::" starts the (ignored) extra field, whose
         * CONTENT may be anything including more colons — python's
         * split("::") puts it all in field 4. A single ':' means the rating
         * field itself continued with garbage ("5:978"), which python's
         * float() rejects too. */
        if (q < end && *q == ':' && (q + 1 >= end || q[1] != ':')) { free(buf); return -3; }
        p = q;
        /* int32 range check: the pure-Python fallback raises OverflowError on
         * out-of-range ids; silent (int32_t) truncation would diverge. */
        if (user > 2147483647L || user < -2147483648L ||
            movie > 2147483647L || movie < -2147483648L) { free(buf); return -3; }
        users[n] = (int32_t)user;
        movies[n] = (int32_t)movie;
        values[n] = (float)val;
        n++;
        while (p < end && *p != '\n') p++;
    }
    free(buf);
    return n;
}

/* Count lines (for sizing output buffers without a Python pre-pass). */
long count_lines(const char *path) {
    FILE *f = fopen(path, "rb");
    if (!f) return -1;
    char chunk[1 << 16];
    size_t got;
    long lines = 0;
    int last = '\n';
    while ((got = fread(chunk, 1, sizeof(chunk), f)) > 0) {
        for (size_t i = 0; i < got; i++)
            if (chunk[i] == '\n') lines++;
        last = chunk[got - 1];
    }
    fclose(f);
    if (last != '\n') lines++;
    return lines;
}

"""Figures + text report over phase-1 results.

The reference ships these as a 16-cell notebook (``notebooks/analysis_phase1.ipynb``,
SURVEY.md §1 side artifacts) rendering three PNGs: a fairness-overview bar chart,
a gender JSD histogram + parity panel, and an IF Jaccard histogram. Here the same
three figures are a library call (and a CLI-reachable function), so they run
headless in CI; the text summary mirrors ``phase1_summary_report.txt``.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

_FAIR, _MODERATE = 0.8, 0.7  # notebook cell-5 thresholds


def _level(score: float) -> str:
    return "fair" if score >= _FAIR else ("moderate" if score >= _MODERATE else "biased")


def generate_phase1_figures(results: Dict, out_dir: str) -> List[str]:
    """Render the three notebook figures; returns written paths."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    os.makedirs(out_dir, exist_ok=True)
    m = results["metrics"]
    written = []

    # 1. fairness overview bars
    names = ["DP (gender)", "DP (age)", "Individual", "Equal opp."]
    scores = [
        m["demographic_parity_gender"]["score"],
        m["demographic_parity_age"]["score"],
        m["individual_fairness"]["score"],
        m["equal_opportunity"]["score"],
    ]
    fig, ax = plt.subplots(figsize=(8, 5))
    colors = ["#2a9d8f" if s >= _FAIR else "#e9c46a" if s >= _MODERATE else "#e76f51" for s in scores]
    ax.bar(names, scores, color=colors)
    ax.axhline(_FAIR, ls="--", c="gray", lw=1, label=f"fair ({_FAIR})")
    ax.axhline(_MODERATE, ls=":", c="gray", lw=1, label=f"moderate ({_MODERATE})")
    ax.set_ylim(0, 1.05)
    ax.set_ylabel("score")
    ax.set_title(f"Fairness overview — {results['metadata']['model']}")
    ax.legend()
    path = os.path.join(out_dir, "fairness_overview.png")
    fig.savefig(path, dpi=120, bbox_inches="tight")
    plt.close(fig)
    written.append(path)

    # 2. gender divergences histogram + parity bar
    divs = m["demographic_parity_gender"].get("divergences", [])
    fig, axes = plt.subplots(1, 2, figsize=(11, 4.5))
    if divs:
        axes[0].hist(divs, bins=min(10, max(3, len(divs))), color="#264653")
    axes[0].set_title("Pairwise JS distance between gender groups")
    axes[0].set_xlabel("JS distance")
    axes[1].bar(
        ["gender", "age"],
        [m["demographic_parity_gender"]["score"], m["demographic_parity_age"]["score"]],
        color="#2a9d8f",
    )
    axes[1].set_ylim(0, 1.05)
    axes[1].set_title("Demographic parity")
    path = os.path.join(out_dir, "gender_analysis.png")
    fig.savefig(path, dpi=120, bbox_inches="tight")
    plt.close(fig)
    written.append(path)

    # 3. SNSR/SNSV per-group similarity (extends the notebook's IF histogram
    # with the benchmark metric the reference lacks; guard fully — reference-
    # shaped result JSONs have no snsr_snsv block)
    sns = m.get("snsr_snsv", {})
    sims = sns.get("group_similarities", {})
    fig, ax = plt.subplots(figsize=(8, 4.5))
    if sims:
        ax.bar(list(sims.keys()), list(sims.values()), color="#457b9d")
    ax.set_ylim(0, 1.05)
    ax.set_title(
        f"Sensitive-to-neutral similarity (SNSR={sns.get('snsr', float('nan')):.3f}, "
        f"SNSV={sns.get('snsv', float('nan')):.3f})"
    )
    path = os.path.join(out_dir, "snsr_similarity.png")
    fig.savefig(path, dpi=120, bbox_inches="tight")
    plt.close(fig)
    written.append(path)

    logger.info("wrote %d figures to %s", len(written), out_dir)
    return written


def generate_phase2_figure(results: Dict, out_dir: str) -> str:
    """Per-model listwise/pairwise exposure-ratio bars + per-group exposure —
    a phase-2 figure the reference's notebook never had."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    os.makedirs(out_dir, exist_ok=True)
    mf = results["comparison"]["model_fairness"]
    models = list(mf.keys())
    fig, axes = plt.subplots(1, 2, figsize=(11, 4.5))
    x = range(len(models))
    w = 0.35
    axes[0].bar([i - w / 2 for i in x], [mf[m]["listwise_fairness"] for m in models],
                w, label="listwise", color="#2a9d8f")
    axes[0].bar([i + w / 2 for i in x], [mf[m]["pairwise_fairness"] for m in models],
                w, label="pairwise", color="#457b9d")
    axes[0].set_xticks(list(x))
    axes[0].set_xticklabels(models, rotation=15)
    axes[0].axhline(_FAIR, ls="--", c="gray", lw=1)
    axes[0].set_ylim(0, 1.05)
    axes[0].set_title("Exposure ratio by model and method")
    axes[0].legend()

    # per-group exposure for the first model (means over queries)
    first = results["model_results"][models[0]]["listwise"]["group_exposure"]
    axes[1].bar(list(first.keys()), list(first.values()), color="#264653")
    axes[1].set_title(f"Listwise group exposure — {models[0]}")
    path = os.path.join(out_dir, "phase2_ranking_fairness.png")
    fig.savefig(path, dpi=120, bbox_inches="tight")
    plt.close(fig)
    logger.info("wrote %s", path)
    return path


def generate_phase3_figure(results: Dict, out_dir: str) -> str:
    """Before/after mitigation bars (fairness, bias, quality) — a figure the
    reference's notebook never had for phase 3."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    os.makedirs(out_dir, exist_ok=True)
    b = results["bias_reduction"]
    q = results["quality_preservation"]
    fig, axes = plt.subplots(1, 2, figsize=(11, 4.5))
    axes[0].bar(
        ["before", "after"],
        [b["original_fairness"], b["mitigated_fairness"]],
        color=["#e76f51", "#2a9d8f"],
    )
    axes[0].set_ylim(0, 1.05)
    axes[0].set_title(
        f"Demographic parity — bias reduced {b['bias_reduction_rate']:.1f}%"
    )
    axes[1].bar(
        ["quality preserved"], [q["quality_preservation_pct"]], color="#457b9d"
    )
    axes[1].set_ylim(0, 105)
    axes[1].set_title(f"Quality preservation ({q['num_comparisons']} profiles)")
    variant = results["metadata"]["variant"]
    path = os.path.join(out_dir, f"phase3_{variant}_mitigation.png")
    fig.savefig(path, dpi=120, bbox_inches="tight")
    plt.close(fig)
    logger.info("wrote %s", path)
    return path


def generate_summary_report(results: Dict, path: Optional[str] = None) -> str:
    """Text mirror of the reference's ``phase1_summary_report.txt``."""
    m = results["metrics"]
    md = results["metadata"]
    lines = [
        "=" * 60,
        "PHASE 1 — BIAS DETECTION SUMMARY",
        "=" * 60,
        f"model: {md['model']}",
        f"profiles: {md['num_profiles']}",
        "",
        f"Demographic Parity (gender): {m['demographic_parity_gender']['score']:.4f} "
        f"[{_level(m['demographic_parity_gender']['score'])}]",
        f"Demographic Parity (age):    {m['demographic_parity_age']['score']:.4f} "
        f"[{_level(m['demographic_parity_age']['score'])}]",
        f"Individual Fairness:         {m['individual_fairness']['score']:.4f} "
        f"({m['individual_fairness']['num_pairs']} pairs)",
        f"Equal Opportunity:           {m['equal_opportunity']['score']:.4f}",
    ]
    sns = m.get("snsr_snsv")
    if sns:
        lines.append(f"SNSR: {sns['snsr']:.4f}   SNSV: {sns['snsv']:.4f}")
    lines.append("")
    text = "\n".join(lines)
    if path:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            f.write(text)
    return text

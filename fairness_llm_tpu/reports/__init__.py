"""Reports: figures + text summaries over saved phase results."""

from fairness_llm_tpu.reports.figures import (
    generate_phase1_figures,
    generate_phase2_figure,
    generate_phase3_figure,
    generate_summary_report,
)

__all__ = [
    "generate_phase1_figures",
    "generate_phase2_figure",
    "generate_phase3_figure",
    "generate_summary_report",
]

"""CLI orchestrator — ``python -m fairness_llm_tpu.cli.main``.

Mirrors the reference front-end surface (``main.py:184-214``): ``--all``,
``--phase {1,2,3}``, ``--quick``, model/profile-count flags, setup checks,
sequential phase execution with timing, and a cross-phase final summary —
plus the TPU-native knobs (mesh shape, weights dir, backend choice).

Run examples:
    python -m fairness_llm_tpu.cli.main --all --quick
    python -m fairness_llm_tpu.cli.main --phase 1 --model llama3-8b --mesh dp=8
    python -m fairness_llm_tpu.cli.main --phase 3 --variant smart
    python -m fairness_llm_tpu.cli.main --phase 1 --continuous --telemetry-dir tel/
    python -m fairness_llm_tpu.cli.main telemetry-report tel/
"""

from __future__ import annotations

import argparse
import dataclasses
import logging
import sys
from typing import Dict, Optional

from fairness_llm_tpu.config import Config, MeshConfig, create_directories, default_config
from fairness_llm_tpu.pipeline.phase1 import print_phase1_summary, run_phase1
from fairness_llm_tpu.pipeline.phase2 import print_phase2_summary, run_phase2
from fairness_llm_tpu.pipeline.phase3 import print_phase3_summary, run_phase3

logger = logging.getLogger(__name__)

BANNER = r"""
==========================================================
  fairness_llm_tpu — LLM recommendation fairness on TPU
  phase 1: bias detection   phase 2: cross-model ranking
  phase 3: FACTER mitigation
==========================================================
"""


def parse_mesh(spec: Optional[str]) -> MeshConfig:
    """'dp=2,tp=4' -> MeshConfig(dp=2, tp=4)."""
    if not spec:
        return MeshConfig()
    kwargs = {}
    for part in spec.split(","):
        k, _, v = part.partition("=")
        if k.strip() not in ("dp", "tp", "sp"):
            raise SystemExit(f"unknown mesh axis '{k}' (use dp/tp/sp)")
        try:
            kwargs[k.strip()] = int(v)
        except ValueError:
            raise SystemExit(f"bad mesh spec '{part}' (use e.g. dp=2,tp=4)") from None
    return MeshConfig(**kwargs)


def check_setup(config: Config) -> None:
    """Environment probes (reference ``check_setup``, ``main.py:42-76``):
    warn-and-continue on missing data, report the device fleet."""
    import os

    import jax

    create_directories(config)
    devices = jax.devices()
    print(f"devices: {len(devices)} x {devices[0].platform if devices else 'none'}")
    need = config.mesh.num_devices
    if need > len(devices):
        print(f"WARNING: mesh {config.mesh.shape} wants {need} devices, found {len(devices)}")
    if not os.path.exists(os.path.join(config.data_dir, "movies.dat")):
        print(f"WARNING: MovieLens not found at {config.data_dir}; synthetic fallback will be used")
    if config.weights_dir is None:
        print("NOTE: no --weights-dir; real model names will FAIL (no checkpoint to "
              "load) — use --model simulated for the no-weights deterministic backend")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="fairness_llm_tpu",
        description="Three-phase LLM recommendation-fairness study, TPU-native",
    )
    g = p.add_mutually_exclusive_group(required=True)
    g.add_argument("--all", action="store_true", help="run phases 1 -> 2 -> 3")
    g.add_argument("--phase", type=int, choices=(1, 2, 3), help="run one phase")
    p.add_argument("--quick", action="store_true",
                   help="demo mode: 1 profile/combo, fewer items/comparisons")
    p.add_argument("--model", default=None, help="model for phases 1/3 (or 'simulated')")
    p.add_argument("--models", nargs="+", default=None, help="models for phase 2")
    p.add_argument("--profiles", type=int, default=None, help="profiles per demographic combo")
    p.add_argument("--num-items", type=int, default=20, help="phase-2 ranking corpus size")
    p.add_argument("--corpus", default="synthetic", choices=("synthetic", "movielens"),
                   help="phase-2 corpus: reference-compat synthetic docs, or real "
                        "ML-1M titles with genre-derived groups")
    p.add_argument("--num-queries", type=int, default=1,
                   help="phase-2 listwise queries, decoded as one batch")
    p.add_argument("--num-comparisons", type=int, default=30, help="phase-2 pairwise budget")
    p.add_argument("--variant", default="conformal", choices=("conformal", "smart", "aggressive"),
                   help="phase-3 mitigation variant")
    p.add_argument("--strategy", default="demographic_parity",
                   choices=("demographic_parity", "equal_opportunity", "individual_fairness"))
    p.add_argument("--calibration", default="simulated", choices=("simulated", "model", "model-conditional"),
                   help="phase-3 conformal confidences: reference-style simulated "
                        "curve, the model's own unconditional title likelihoods, or "
                        "likelihoods conditioned on the profile's watch history "
                        "(model-conditional; demographics excluded from the context)")
    p.add_argument("--confidence-mapping", default="percentile",
                   choices=("percentile", "probability"),
                   help="with --calibration model or model-conditional: how "
                        "likelihoods map onto the conformal scale (rank-normalized, "
                        "or temperature-scaled probabilities — see "
                        "pipeline.facter.model_confidences)")
    p.add_argument("--confidence-temperature", type=float, default=1.0,
                   help="temperature for --confidence-mapping probability")
    p.add_argument("--max-new-tokens", type=int, default=None,
                   help="global decode-length cap: clamps every model's "
                        "max_tokens (bounds per-sweep decode cost)")
    p.add_argument("--speculate", action="store_true",
                   help="prompt-lookup speculative decoding for engine "
                        "backends (greedy decode only — sampled settings "
                        "silently use the plain path; output is identical "
                        "either way, see runtime/speculative.py)")
    p.add_argument("--draft-len", type=int, default=None,
                   help="with --speculate: drafted tokens verified per step")
    p.add_argument("--ngram-max", type=int, default=None,
                   help="with --speculate: longest lookup n-gram tried first")
    p.add_argument("--continuous", action="store_true",
                   help="serve engine backends through the continuous-"
                        "batching scheduler (serving/): fixed KV slot pool, "
                        "per-step eviction + backfill from a bounded "
                        "admission queue. Greedy output is token-for-token "
                        "identical to the static engine for prompts within "
                        "the serving budget (longer ones truncate, with a "
                        "warning); see docs/SERVING.md")
    p.add_argument("--slots", type=int, default=None,
                   help="with --continuous: concurrent KV slots "
                        "(= decode-step batch rows)")
    p.add_argument("--fuse-steps", type=int, default=None, metavar="K",
                   help="with --continuous: decode chunks fused into ONE "
                        "compiled dispatch (runtime/stepbuilder.py) — the "
                        "step program runs decode_chunk x K steps before "
                        "returning to the host, amortizing per-dispatch "
                        "host sync ~1/K per token at an identical token "
                        "stream (per-row budgets clamp in-program). Not "
                        "combinable with --speculate (its verify window is "
                        "already multi-token; composition lands with tree "
                        "speculation)")
    p.add_argument("--tp", type=int, default=None, metavar="N",
                   help="with --continuous: tensor-parallel serving over an "
                        "N-device tp mesh — every step program (prefill, "
                        "decode, fused, paged) lowers as ONE SPMD "
                        "computation: params sharded by the parallel/ "
                        "rules, the slot KV cache / block arena sharded on "
                        "kv heads, collectives inserted by XLA. N must "
                        "divide the model's attention heads and the device "
                        "count (checked at parse time). --tp 1 is "
                        "byte-identical to no flag. Mutually exclusive "
                        "with --mesh; CPU harness: "
                        "XLA_FLAGS=--xla_force_host_platform_device_count=N")
    p.add_argument("--paged-kv", action="store_true",
                   help="with --continuous: paged KV cache with radix-tree "
                        "prefix reuse (serving/paged.py) — slots hold block "
                        "tables into one shared arena, admission matches "
                        "the longest cached prompt prefix (refcounted, "
                        "copy-on-write at the divergence point) and "
                        "prefills only the unmatched suffix. The "
                        "counterfactual sweep's near-duplicate prompts "
                        "become lookups; greedy output is token-for-token "
                        "identical to the non-paged path")
    p.add_argument("--kv-block-size", type=int, default=None, metavar="B",
                   help="with --paged-kv: tokens per KV block — the "
                        "prefix-sharing granularity (default 16)")
    p.add_argument("--kv-blocks", type=int, default=None, metavar="N",
                   help="with --paged-kv: total arena blocks (default 2x "
                        "the all-slots-private worst case, so a full pool "
                        "still leaves an equal prefix-cache reserve)")
    p.add_argument("--overload", action="store_true",
                   help="with --continuous: arm overload control "
                        "(serving/overload.py) — QoS classes (interactive/"
                        "batch/probe) with per-class bounded sub-queues and "
                        "strict-priority-with-aging dequeue, deadline-"
                        "feasibility admission (provably-doomed requests "
                        "shed with finish_reason=shed + retry-after instead "
                        "of burning a prefill), and an SLO-burn-driven shed "
                        "controller walking a brownout ladder: shed batch "
                        "-> cap batch tokens -> interactive-only. See "
                        "docs/SERVING.md §QoS and overload control")
    p.add_argument("--shed-burn-threshold", type=float, default=None,
                   metavar="B",
                   help="with --overload: fast-window SLO burn rate at "
                        "which the shed controller escalates one brownout "
                        "rung (default 2.0)")
    p.add_argument("--shed-healthy-window", type=float, default=None,
                   metavar="S",
                   help="with --overload: seconds of sustained health "
                        "required per de-escalation rung (hysteresis; "
                        "default 5)")
    p.add_argument("--batch-token-cap", type=int, default=None, metavar="T",
                   help="with --overload: max_new_tokens clamp applied to "
                        "batch-class requests at brownout rung 2+ "
                        "(default 32)")
    p.add_argument("--replicas", type=int, default=None, metavar="N",
                   help="with --continuous: serve through N data-parallel "
                        "engine replicas behind a health-aware router "
                        "(serving/fleet.py) — each replica gets its own KV "
                        "slot pool, breakers, watchdog, and rejoin canary; "
                        "a sick replica is fenced and drained, its requests "
                        "migrate to healthy replicas with original "
                        "ids/settings/row-seeds (greedy parity preserved), "
                        "and it rejoins only after a canary warm-up probe. "
                        "See docs/SERVING.md §Replica fleet")
    p.add_argument("--fence-level", type=int, default=None,
                   help="with --replicas: degradation-ladder level at which "
                        "the router fences a replica (default 2 = "
                        "reduced_footprint; 0 disables ladder-driven "
                        "fencing — crash/hang/stall still fence)")
    p.add_argument("--fence-cooldown", type=float, default=None,
                   help="with --replicas: seconds a fenced replica waits "
                        "before its first canary rejoin probe (default 1; "
                        "probes additionally defer until the replica's "
                        "open breakers can half-open, so the effective "
                        "delay is max of this and --breaker-cooldown)")
    p.add_argument("--autoscale", action="store_true",
                   help="with --continuous: SLO-coupled elastic fleet "
                        "(serving/autoscaler.py) — replica membership "
                        "becomes a runtime control loop reading the "
                        "fast-window SLO burn gauges, the overload rung, "
                        "and queue depth; scale-up adds a canary-gated "
                        "standby replica, scale-down retires the lowest-"
                        "load replica through the drain/migration path "
                        "(in-flight requests survive with token parity). "
                        "Implies fleet mode even at --replicas 1. See "
                        "docs/SERVING.md §Elastic fleet & autoscaling")
    p.add_argument("--min-replicas", type=int, default=None, metavar="N",
                   help="with --autoscale: lower membership bound "
                        "(default 1)")
    p.add_argument("--max-replicas", type=int, default=None, metavar="N",
                   help="with --autoscale: upper membership bound "
                        "(default 4)")
    p.add_argument("--max-step-seconds", type=float, default=None,
                   help="resilience watchdog: a compiled prefill/decode step "
                        "slower than this is classified HUNG and contained "
                        "as a fault (requeue-once / chunk-retry); implies "
                        "the per-stage circuit breakers. See "
                        "docs/RESILIENCE.md")
    p.add_argument("--breaker-threshold", type=int, default=None,
                   help="resilience: consecutive faults per stage before "
                        "that stage's circuit breaker opens (default 3); "
                        "implies the breakers even without a watchdog")
    p.add_argument("--breaker-cooldown", type=float, default=None,
                   help="resilience: seconds an open breaker waits before "
                        "half-opening for a probe (default 5)")
    p.add_argument("--serving-journal", default=None, metavar="DIR",
                   help="with --continuous: crash-safe request journal under "
                        "DIR (journal.jsonl) + SIGTERM/SIGINT graceful "
                        "drain; a preempted run's unfinished requests are "
                        "re-served by `resume-serving DIR`")
    p.add_argument("--drain-grace", type=float, default=None,
                   help="with --serving-journal: seconds live slots may "
                        "keep decoding after a drain signal before being "
                        "journaled as unfinished (default 5)")
    p.add_argument("--numerics-guards", action="store_true",
                   help="integrity: fold an on-device finite check of the "
                        "logits into every compiled decode program (one "
                        "reduced flag per chunk); NaN/Inf chunks are "
                        "contained as NumericsFault instead of silently "
                        "decoding garbage. Output is token-for-token "
                        "identical either way. See docs/RESILIENCE.md")
    p.add_argument("--canary-every", type=int, default=None, metavar="N",
                   help="with --continuous: every N backend calls, decode a "
                        "golden prompt through the live scheduler and "
                        "compare token-for-token against a static-engine "
                        "reference; a mismatch trips the breaker "
                        "degradation ladder")
    p.add_argument("--no-verify-manifests", action="store_true",
                   help="skip sha256 manifest verification of weight "
                        "checkpoints at load (on by default where a "
                        "manifest.json exists)")
    p.add_argument("--mesh", default=None, help="device mesh, e.g. 'dp=2,tp=4'")
    p.add_argument("--weights-dir", default=None, help="directory of HF safetensors checkpoints")
    p.add_argument("--weight-quant", default=None, choices=("none", "int8"),
                   help="weight-only quantization for served models: int8 "
                        "stores matmul kernels as int8 with dequant inside "
                        "the Pallas tile (fits llama3-70b tp=8 on a v5e-8)")
    p.add_argument("--data-dir", default=None, help="MovieLens-1M directory")
    p.add_argument("--results-dir", default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--resume", action="store_true", help="resume phase-1 sweep from checkpoints")
    p.add_argument("--trace-dir", default=None,
                   help="write a jax.profiler device trace per phase to this directory")
    p.add_argument("--telemetry-dir", default=None,
                   help="export telemetry here: streamed events.jsonl plus an "
                        "end-of-run registry snapshot (telemetry_snapshot.json "
                        "+ metrics.prom) with TTFT/queue-wait/latency "
                        "histograms, and the device-step timeline as "
                        "trace.json; render with `telemetry-report <dir>` "
                        "(see docs/OBSERVABILITY.md)")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="write the device-step timeline (prefill/decode/"
                        "compile spans, request lanes, per-replica tracks) "
                        "as Chrome-trace JSON — open at "
                        "https://ui.perfetto.dev. With --telemetry-dir, "
                        "<telemetry-dir>/trace.json is written regardless "
                        "(the copy the validator and --timeline report "
                        "read); this flag adds an extra copy at PATH, or "
                        "enables the export without a telemetry dir")
    p.add_argument("--incidents", action="store_true",
                   help="arm the incident engine (telemetry/incidents.py): "
                        "trigger conditions (breaker open, fence, hang, "
                        "numerics fault, canary mismatch, fairness "
                        "divergence/alert, error-budget burn, heartbeat "
                        "gap) dump self-contained postmortem bundles under "
                        "<telemetry-dir>/incidents — flight-recorder rings, "
                        "decision trail, registry snapshot, trace slice, "
                        "journal tail. Render with `incident-report <dir>`; "
                        "gate with tools/validate_telemetry.py "
                        "--require-incidents / --forbid-incidents. "
                        "Requires --telemetry-dir")
    p.add_argument("--fairness-obs", action="store_true",
                   help="fairness observability (telemetry/fairness.py): "
                        "phases register their profile grid with the "
                        "fairness monitor, sweep requests carry "
                        "group/attribute/pair_id study tags, and the run "
                        "records streaming per-group DP/IF/exposure "
                        "gauges, a counterfactual pair watch with "
                        "serving-event attribution, and a serving-"
                        "neutrality audit (per-group TTFT/queue-wait/"
                        "shed/fault disparity, alerting via "
                        "fairness_alerts_total). Render with "
                        "`fairness-report <telemetry-dir>`; gate with "
                        "tools/validate_telemetry.py --require-fairness. "
                        "See docs/OBSERVABILITY.md §Fairness signals")
    p.add_argument("--slo-ttft-p95", type=float, default=None, metavar="S",
                   help="SLO target: p95 time-to-first-token in seconds "
                        "(default 2.0); burn rates exported as "
                        "slo_burn_rate gauges, rendered by `slo-report`")
    p.add_argument("--slo-e2e-p99", type=float, default=None, metavar="S",
                   help="SLO target: p99 end-to-end request latency in "
                        "seconds (default 30.0)")
    p.add_argument("--slo-error-rate", type=float, default=None, metavar="F",
                   help="SLO target: allowed failed/expired request "
                        "fraction (default 0.01)")
    p.add_argument("--achievable-gbps", type=float, default=None,
                   help="measured achievable HBM streaming bandwidth for "
                        "the live achieved_over_achievable roofline gauges "
                        "(default: 819 spec on TPU, a nominal DDR figure "
                        "on CPU — indicative only)")
    p.add_argument("--no-save", action="store_true")
    p.add_argument("-v", "--verbose", action="store_true")
    return p


def config_from_args(args: argparse.Namespace) -> Config:
    config = default_config()
    updates: Dict = {}
    if args.mesh:
        updates["mesh"] = parse_mesh(args.mesh)
    if args.weights_dir:
        updates["weights_dir"] = args.weights_dir
    if args.weight_quant is not None:
        updates["weight_quant"] = args.weight_quant
    if args.data_dir:
        updates["data_dir"] = args.data_dir
    if args.results_dir:
        updates["results_dir"] = args.results_dir
    if args.seed is not None:
        updates["random_seed"] = args.seed
    if args.trace_dir:
        updates["profile_trace_dir"] = args.trace_dir
    if args.telemetry_dir:
        updates["telemetry_dir"] = args.telemetry_dir
    attribution_flags = (args.trace_out, args.slo_ttft_p95, args.slo_e2e_p99,
                         args.slo_error_rate, args.achievable_gbps)
    if args.fairness_obs or any(v is not None for v in attribution_flags):
        from fairness_llm_tpu.config import TelemetryConfig

        tel_kwargs: Dict = {}
        if args.fairness_obs:
            tel_kwargs["fairness_obs"] = True
        if args.trace_out:
            tel_kwargs["trace_out"] = args.trace_out
        if args.achievable_gbps is not None:
            if args.achievable_gbps <= 0:
                raise SystemExit("--achievable-gbps must be > 0")
            tel_kwargs["achievable_gbps"] = args.achievable_gbps
        for val, field, flag in (
            (args.slo_ttft_p95, "slo_ttft_p95_s", "--slo-ttft-p95"),
            (args.slo_e2e_p99, "slo_e2e_p99_s", "--slo-e2e-p99"),
            (args.slo_error_rate, "slo_error_rate", "--slo-error-rate"),
        ):
            if val is not None:
                if val <= 0:
                    raise SystemExit(f"{flag} must be > 0")
                tel_kwargs[field] = val
        updates["telemetry"] = TelemetryConfig(**tel_kwargs)
    if args.max_new_tokens is not None:
        if args.max_new_tokens < 1:
            # A zero cap would reach the engine as a [B, 0] decode buffer and
            # die inside jit with an opaque dynamic_update_slice error.
            raise SystemExit("--max-new-tokens must be >= 1")
        updates["max_new_tokens"] = args.max_new_tokens
    if args.quick:
        updates["profiles_per_combo"] = 1
    if args.speculate or args.draft_len is not None or args.ngram_max is not None:
        from fairness_llm_tpu.config import SpeculationConfig

        if not args.speculate:
            raise SystemExit("--draft-len/--ngram-max require --speculate")
        spec_kwargs = {"enabled": True}
        if args.draft_len is not None:
            if args.draft_len < 1:
                raise SystemExit("--draft-len must be >= 1")
            spec_kwargs["draft_len"] = args.draft_len
        if args.ngram_max is not None:
            if args.ngram_max < 1:
                raise SystemExit("--ngram-max must be >= 1")
            spec_kwargs["ngram_max"] = args.ngram_max
        updates["speculation"] = SpeculationConfig(**spec_kwargs)
    if args.continuous or args.slots is not None or args.paged_kv \
            or args.kv_block_size is not None or args.kv_blocks is not None \
            or args.fuse_steps is not None or args.tp is not None:
        from fairness_llm_tpu.config import ServingConfig

        if not args.paged_kv and (args.kv_block_size is not None
                                  or args.kv_blocks is not None):
            raise SystemExit("--kv-block-size/--kv-blocks require --paged-kv")
        if not args.continuous:
            raise SystemExit(
                "--slots/--paged-kv/--fuse-steps/--tp require --continuous")
        serve_kwargs = {"enabled": True}
        if args.tp is not None:
            # Same parse-time discipline as the --fuse-steps gates: every
            # invalid combination dies HERE with the flag named, not
            # minutes later inside a weight load or a jit trace.
            if args.tp < 1:
                raise SystemExit("--tp must be >= 1")
            if args.mesh:
                raise SystemExit(
                    "--tp cannot combine with --mesh: --tp N builds the "
                    "tp-only serving mesh itself (use --mesh for the "
                    "static-engine dp/sp paths)")
            if args.tp > 1:
                if args.model and args.model not in (
                        "simulated", "simulated-fair", "simulated-biased"):
                    from fairness_llm_tpu.models.configs import (
                        get_model_config,
                    )

                    try:
                        mc = get_model_config(args.model)
                    except KeyError:
                        mc = None
                    if mc is not None and (mc.num_heads % args.tp != 0 or
                                           mc.num_kv_heads % args.tp != 0):
                        raise SystemExit(
                            f"--tp {args.tp} must divide {args.model}'s "
                            f"attention heads ({mc.num_heads} q / "
                            f"{mc.num_kv_heads} kv)")
                import jax as _jax

                if _jax.device_count() % args.tp != 0:
                    raise SystemExit(
                        f"--tp {args.tp} must divide the device count "
                        f"({_jax.device_count()}); on CPU set XLA_FLAGS="
                        f"--xla_force_host_platform_device_count={args.tp}")
                serve_kwargs["tp"] = args.tp
                from fairness_llm_tpu.config import MeshConfig

                updates["mesh"] = MeshConfig(tp=args.tp)
        if args.slots is not None:
            if args.slots < 1:
                raise SystemExit("--slots must be >= 1")
            serve_kwargs["num_slots"] = args.slots
        if args.fuse_steps is not None:
            if args.fuse_steps < 1:
                raise SystemExit("--fuse-steps must be >= 1")
            if args.fuse_steps > 1 and args.speculate:
                raise SystemExit(
                    "--fuse-steps cannot combine with --speculate: the "
                    "speculative verify window is already multi-token; "
                    "fused tree speculation is deferred to the "
                    "tree-speculation PR")
            serve_kwargs["fuse_steps"] = args.fuse_steps
        if args.paged_kv:
            serve_kwargs["paged_kv"] = True
            if args.kv_block_size is not None:
                if args.kv_block_size < 1:
                    raise SystemExit("--kv-block-size must be >= 1")
                serve_kwargs["kv_block_size"] = args.kv_block_size
            if args.kv_blocks is not None:
                if args.kv_blocks < 1:
                    raise SystemExit("--kv-blocks must be >= 1")
                serve_kwargs["kv_blocks"] = args.kv_blocks
        updates["serving"] = ServingConfig(**serve_kwargs)
    overload_flags = (args.shed_burn_threshold, args.shed_healthy_window,
                      args.batch_token_cap)
    if args.overload or any(v is not None for v in overload_flags):
        from fairness_llm_tpu.config import OverloadConfig

        if not args.overload:
            raise SystemExit("--shed-burn-threshold/--shed-healthy-window/"
                             "--batch-token-cap require --overload")
        if not args.continuous:
            raise SystemExit("--overload requires --continuous (overload "
                             "control gates the serving admission queue)")
        ov_kwargs: Dict = {"enabled": True}
        if args.shed_burn_threshold is not None:
            if args.shed_burn_threshold <= 0:
                raise SystemExit("--shed-burn-threshold must be > 0")
            ov_kwargs["burn_threshold"] = args.shed_burn_threshold
        if args.shed_healthy_window is not None:
            if args.shed_healthy_window < 0:
                raise SystemExit("--shed-healthy-window must be >= 0")
            ov_kwargs["healthy_window_s"] = args.shed_healthy_window
        if args.batch_token_cap is not None:
            if args.batch_token_cap < 1:
                raise SystemExit("--batch-token-cap must be >= 1")
            ov_kwargs["batch_token_cap"] = args.batch_token_cap
        updates["overload"] = OverloadConfig(**ov_kwargs)
    fleet_flags = (args.replicas, args.fence_level, args.fence_cooldown)
    if any(v is not None for v in fleet_flags):
        from fairness_llm_tpu.config import FleetConfig

        if not args.continuous:
            raise SystemExit("--replicas/--fence-level/--fence-cooldown "
                             "require --continuous (the fleet routes over "
                             "serving schedulers)")
        fleet_kwargs: Dict = {}
        if args.replicas is not None:
            if args.replicas < 1:
                raise SystemExit("--replicas must be >= 1")
            fleet_kwargs["replicas"] = args.replicas
        if args.fence_level is not None:
            if args.fence_level < 0:
                raise SystemExit("--fence-level must be >= 0")
            fleet_kwargs["fence_ladder_level"] = args.fence_level
        if args.fence_cooldown is not None:
            if args.fence_cooldown < 0:
                raise SystemExit("--fence-cooldown must be >= 0")
            fleet_kwargs["fence_cooldown_s"] = args.fence_cooldown
        updates["fleet"] = FleetConfig(**fleet_kwargs)
    autoscale_flags = (args.min_replicas, args.max_replicas)
    if args.autoscale or any(v is not None for v in autoscale_flags):
        from fairness_llm_tpu.config import AutoscaleConfig

        if not args.autoscale:
            raise SystemExit("--min-replicas/--max-replicas require "
                             "--autoscale")
        if not args.continuous:
            raise SystemExit("--autoscale requires --continuous (the "
                             "autoscaler drives fleet membership over "
                             "serving schedulers)")
        as_kwargs: Dict = {"enabled": True}
        if args.min_replicas is not None:
            if args.min_replicas < 1:
                raise SystemExit("--min-replicas must be >= 1")
            as_kwargs["min_replicas"] = args.min_replicas
        if args.max_replicas is not None:
            if args.max_replicas < (args.min_replicas or 1):
                raise SystemExit("--max-replicas must be >= --min-replicas")
            as_kwargs["max_replicas"] = args.max_replicas
        elif args.min_replicas is not None and \
                args.min_replicas > AutoscaleConfig.max_replicas:
            raise SystemExit(
                f"--min-replicas {args.min_replicas} exceeds the default "
                f"--max-replicas ({AutoscaleConfig.max_replicas}); pass "
                "--max-replicas explicitly")
        updates["autoscale"] = AutoscaleConfig(**as_kwargs)
    resilience_flags = (args.max_step_seconds, args.breaker_threshold,
                        args.breaker_cooldown, args.serving_journal,
                        args.drain_grace)
    if any(v is not None for v in resilience_flags):
        from fairness_llm_tpu.config import ResilienceConfig

        if (args.serving_journal or args.drain_grace is not None) \
                and not args.continuous:
            raise SystemExit("--serving-journal/--drain-grace require "
                             "--continuous (the journal ledgers serving "
                             "requests)")
        res_kwargs: Dict = {"enabled": True}
        if args.max_step_seconds is not None:
            if args.max_step_seconds <= 0:
                raise SystemExit("--max-step-seconds must be > 0")
            res_kwargs["max_step_seconds"] = args.max_step_seconds
        if args.breaker_threshold is not None:
            if args.breaker_threshold < 1:
                raise SystemExit("--breaker-threshold must be >= 1")
            res_kwargs["breaker_threshold"] = args.breaker_threshold
        if args.breaker_cooldown is not None:
            if args.breaker_cooldown < 0:
                raise SystemExit("--breaker-cooldown must be >= 0")
            res_kwargs["breaker_cooldown_s"] = args.breaker_cooldown
        if args.serving_journal:
            res_kwargs["journal_dir"] = args.serving_journal
        if args.drain_grace is not None:
            if args.drain_grace < 0:
                raise SystemExit("--drain-grace must be >= 0")
            res_kwargs["drain_grace_s"] = args.drain_grace
        updates["resilience"] = ResilienceConfig(**res_kwargs)
    if args.numerics_guards or args.canary_every is not None \
            or args.no_verify_manifests:
        from fairness_llm_tpu.config import IntegrityConfig

        integ_kwargs: Dict = {}
        if args.numerics_guards:
            integ_kwargs["numerics_guards"] = True
        if args.canary_every is not None:
            if not args.continuous:
                raise SystemExit("--canary-every requires --continuous (the "
                                 "canary probes the serving scheduler)")
            if args.canary_every < 1:
                raise SystemExit("--canary-every must be >= 1")
            integ_kwargs["canary_every_n"] = args.canary_every
        if args.no_verify_manifests:
            integ_kwargs["verify_manifests"] = False
        updates["integrity"] = IntegrityConfig(**integ_kwargs)
    if updates:
        config = dataclasses.replace(config, **updates)
    return config


def telemetry_report(argv) -> int:
    """``cli telemetry-report <dir|snapshot.json>`` — render a telemetry
    snapshot in the terminal (the ``summarize_trace`` of the metrics world:
    no dashboards required). ``--validate`` also runs the schema /
    percentile-consistency check and fails on problems (the CI smoke
    step's gate)."""
    ap = argparse.ArgumentParser(
        prog="fairness_llm_tpu telemetry-report",
        description="Render (and optionally validate) a telemetry snapshot",
    )
    ap.add_argument("path", help="telemetry dir (uses telemetry_snapshot.json "
                                 "inside) or a snapshot file")
    ap.add_argument("--validate", action="store_true",
                    help="schema-check the snapshot; non-zero exit on problems")
    ap.add_argument("--timeline", action="store_true",
                    help="also summarize the trace.json device-step timeline "
                         "beside the snapshot: top programs by wall, largest "
                         "step gaps, request outcomes")
    a = ap.parse_args(argv)
    import json
    import os

    from fairness_llm_tpu.telemetry import (
        TRACE_FILENAME,
        load_snapshot,
        render_report,
        summarize_chrome_trace,
        validate_snapshot,
    )

    snap = load_snapshot(a.path)
    if a.validate:
        # Validate BEFORE rendering: the renderer assumes a well-formed
        # snapshot, and the user asked for diagnostics, not a traceback.
        problems = validate_snapshot(snap)
        if problems:
            print("SNAPSHOT INVALID:")
            for p in problems:
                print(f"  - {p}")
            return 1
    print(render_report(snap))
    from fairness_llm_tpu.telemetry import has_cost_data, render_cost_report

    if has_cost_data(snap):
        # Cost-ledger section rides along whenever the run recorded the
        # jaxpr cost walk (telemetry/costmodel.py) — the standalone
        # `perf-report` subcommand renders the same decomposition alone.
        print("\n" + render_cost_report(snap))
    from fairness_llm_tpu.telemetry import has_memory_data, render_memory_report

    if has_memory_data(snap):
        # Memory-ledger section rides along whenever the run accounted
        # device memory (telemetry/memory.py); `memory-report` standalone.
        print("\n" + render_memory_report(snap))
    if any(row.get("labels", {}).get("component") == "fairness"
           for section in ("counters", "gauges")
           for row in snap.get(section, [])):
        # Fairness section rides along whenever the run recorded fairness
        # instruments (--fairness-obs / tagged requests); the standalone
        # `fairness-report` subcommand adds the divergent-pair table from
        # events.jsonl.
        from fairness_llm_tpu.telemetry import render_fairness_report

        print("\n" + render_fairness_report(snap))
    from fairness_llm_tpu.serving.rollout import render_rollout_report

    rollout_section = render_rollout_report(snap)
    if rollout_section:
        # Rollout section rides along whenever the run drove a version
        # rollout (cli rollout / tools/rollout_drill.py): wave position,
        # traffic split, transition and rollback-cause tallies.
        print("\n" + rollout_section)
    if a.timeline:
        trace_dir = a.path if os.path.isdir(a.path) else os.path.dirname(a.path)
        trace_path = os.path.join(trace_dir, TRACE_FILENAME)
        if os.path.exists(trace_path):
            with open(trace_path, encoding="utf-8") as f:
                print("\n" + summarize_chrome_trace(json.load(f)))
        else:
            print(f"\n(no {TRACE_FILENAME} beside the snapshot — run with "
                  "--trace-out or --telemetry-dir to produce one)")
    if a.validate:
        print("\nsnapshot schema: OK")
    return 0


def perf_report(argv) -> int:
    """``cli perf-report <dir|snapshot.json>`` — render the decode cost
    ledger and per-program gap attribution a run recorded
    (telemetry/costmodel.py): per compiled program, the jaxpr-walked
    bytes/FLOPs per component, the analytic floor, and the decomposition
    ``measured wall + host gap = floor + dispatch + unattributed + host
    gap`` with the top gap contributor named — the live replacement for
    the offline xplane accounting in tools/account_decode_step.py. See
    docs/PERFORMANCE.md §Round 12."""
    ap = argparse.ArgumentParser(
        prog="fairness_llm_tpu perf-report",
        description="Render the decode cost ledger / gap attribution from "
                    "a telemetry snapshot",
    )
    ap.add_argument("path", help="telemetry dir (uses telemetry_snapshot."
                                 "json inside) or a snapshot file")
    ap.add_argument("--require-ledger", action="store_true",
                    help="exit non-zero when the snapshot has no cost-"
                         "ledger data (a CI gate)")
    a = ap.parse_args(argv)
    from fairness_llm_tpu.telemetry import (
        has_cost_data,
        load_snapshot,
        render_cost_report,
    )

    snap = load_snapshot(a.path)
    print(render_cost_report(snap))
    if a.require_ledger and not has_cost_data(snap):
        return 1
    return 0


def memory_report(argv) -> int:
    """``cli memory-report <dir|snapshot.json>`` — render the HBM memory
    ledger a run recorded (telemetry/memory.py): per-pool residency
    (params / contiguous KV / paged arena / prefix cache / carried
    logits), the reconciliation verdict against the device's own
    ``memory_stats`` (measured on TPU, indicative on CPU), headroom
    against the limit, and the per-program AOT memory table XLA budgeted
    (``compiled.memory_analysis``). See docs/OBSERVABILITY.md §Memory
    signals."""
    ap = argparse.ArgumentParser(
        prog="fairness_llm_tpu memory-report",
        description="Render the HBM memory ledger from a telemetry "
                    "snapshot",
    )
    ap.add_argument("path", help="telemetry dir (uses telemetry_snapshot."
                                 "json inside) or a snapshot file")
    ap.add_argument("--require-ledger", action="store_true",
                    help="exit non-zero when the snapshot has no memory-"
                         "ledger data (a CI gate)")
    a = ap.parse_args(argv)
    from fairness_llm_tpu.telemetry import (
        has_memory_data,
        load_snapshot,
        render_memory_report,
    )

    snap = load_snapshot(a.path)
    print(render_memory_report(snap))
    if a.require_ledger and not has_memory_data(snap):
        return 1
    return 0


def slo_report(argv) -> int:
    """``cli slo-report <dir|snapshot.json>`` — render the SLO burn rates a
    run recorded: one table per label set (replica in fleet mode), burn per
    (objective, window), alert counts. Burn 1.0 = consuming the error
    budget exactly at the sustainable rate; >1 = an SLO on its way to
    violation. See docs/OBSERVABILITY.md §SLOs and burn rates."""
    ap = argparse.ArgumentParser(
        prog="fairness_llm_tpu slo-report",
        description="Render SLO burn rates from a telemetry snapshot",
    )
    ap.add_argument("path", help="telemetry dir (uses telemetry_snapshot.json "
                                 "inside) or a snapshot file")
    ap.add_argument("--fail-on-burn", action="store_true",
                    help="exit non-zero when any run-window burn rate "
                         "exceeds 1.0 (a CI gate)")
    a = ap.parse_args(argv)
    from fairness_llm_tpu.telemetry import load_snapshot, render_slo_report

    snap = load_snapshot(a.path)
    print(render_slo_report(snap))
    if a.fail_on_burn:
        burning = [
            g for g in snap.get("gauges", [])
            if g.get("name") == "slo_burn_rate"
            and g.get("labels", {}).get("window") == "run"
            and g.get("value", 0.0) > 1.0
        ]
        if burning:
            print(f"\n{len(burning)} SLO(s) burning over the whole run")
            return 1
    return 0


def fairness_report(argv) -> int:
    """``cli fairness-report <dir|snapshot.json>`` — render the fairness
    signals a run recorded (telemetry/fairness.py): streaming vs offline
    DP/IF/exposure, the per-group neutrality audit, disparity gauges with
    alert counts, and the divergent-pair attribution table (joined from
    ``events.jsonl`` when rendering a telemetry dir). See
    docs/OBSERVABILITY.md §Fairness signals."""
    ap = argparse.ArgumentParser(
        prog="fairness_llm_tpu fairness-report",
        description="Render fairness observability from a telemetry "
                    "snapshot",
    )
    ap.add_argument("path", help="telemetry dir (uses telemetry_snapshot."
                                 "json + events.jsonl inside) or a "
                                 "snapshot file")
    ap.add_argument("--fail-on-alert", action="store_true",
                    help="exit non-zero when any fairness_alerts_total is "
                         "nonzero or any pair diverged (a CI gate)")
    a = ap.parse_args(argv)
    import os

    from fairness_llm_tpu.telemetry import (
        load_snapshot,
        read_events,
        render_fairness_report,
    )

    snap = load_snapshot(a.path)
    events = None
    ev_dir = a.path if os.path.isdir(a.path) else os.path.dirname(a.path)
    ev_path = os.path.join(ev_dir, "events.jsonl")
    if os.path.exists(ev_path):
        events = read_events(ev_path)
    print(render_fairness_report(snap, events=events))
    if a.fail_on_alert:
        alerts = sum(c["value"] for c in snap.get("counters", [])
                     if c.get("name") == "fairness_alerts_total")
        diverged = sum(c["value"] for c in snap.get("counters", [])
                       if c.get("name") == "fairness_pair_divergence_total")
        if alerts or diverged:
            print(f"\n{int(alerts)} fairness alert(s), {int(diverged)} "
                  "divergent pair(s)")
            return 1
    return 0


def incident_report(argv) -> int:
    """``cli incident-report <bundle-dir | incidents-dir | telemetry-dir>``
    — render incident postmortem bundles: manifest, the causal chain
    derived from the decision trail ("fence(r1) <- 3x breaker trips <-
    numerics faults <- requests a, b"), flight-recorder ring depths, and
    the implicated decision tail. Given a telemetry dir (or an incidents
    dir), renders every bundle inside; given one bundle, renders it alone.
    See docs/OBSERVABILITY.md §Incidents."""
    ap = argparse.ArgumentParser(
        prog="fairness_llm_tpu incident-report",
        description="Render incident postmortem bundles",
    )
    ap.add_argument("path", help="one bundle dir, an incidents/ dir, or a "
                                 "telemetry dir containing incidents/")
    ap.add_argument("--chain-only", action="store_true",
                    help="print only the one-line causal chain per bundle")
    a = ap.parse_args(argv)
    import os

    from fairness_llm_tpu.telemetry import list_bundles, render_incident_report
    from fairness_llm_tpu.telemetry.incidents import (
        INCIDENTS_DIRNAME,
        MANIFEST_FILENAME,
        causal_chain,
        _read_jsonl,
    )

    path = a.path.rstrip("/")
    if os.path.isfile(os.path.join(path, MANIFEST_FILENAME)):
        bundles = [path]
    else:
        inc_dir = path
        if os.path.isdir(os.path.join(path, INCIDENTS_DIRNAME)):
            inc_dir = os.path.join(path, INCIDENTS_DIRNAME)
        bundles = [m["path"] for m in list_bundles(inc_dir)]
        if not bundles:
            print(f"no incident bundles under {inc_dir} — a clean run, or "
                  "the engine was never armed (--incidents)")
            return 0
    for i, b in enumerate(bundles):
        if a.chain_only:
            import json as _json

            with open(os.path.join(b, MANIFEST_FILENAME),
                      encoding="utf-8") as f:
                manifest = _json.load(f)
            trail = _read_jsonl(os.path.join(b, "decisions.jsonl"))
            implicated = _read_jsonl(
                os.path.join(b, "decisions_implicated.jsonl"))
            print(f"{os.path.basename(b)}: "
                  + causal_chain(manifest, trail, implicated))
        else:
            if i:
                print()
            print(render_incident_report(b))
    return 0


def resume_serving_cmd(argv) -> int:
    """``cli resume-serving <journal-dir>`` — finish the unfinished.

    Loads the serving journal a drained/preempted ``--continuous`` run left
    behind and re-serves every request without a terminal record, with its
    ORIGINAL id, sampler settings, and row seed (greedy survivors decode
    the exact tokens an uninterrupted run would) and its deadline reduced
    by the wall time already spent. See docs/RESILIENCE.md.
    """
    ap = argparse.ArgumentParser(
        prog="fairness_llm_tpu resume-serving",
        description="Re-serve a drained run's journaled unfinished requests",
    )
    ap.add_argument("journal_dir", help="directory holding journal.jsonl "
                                        "(the --serving-journal DIR)")
    ap.add_argument("--model", required=True,
                    help="engine model name (must match the drained run)")
    ap.add_argument("--weights-dir", default=None)
    ap.add_argument("--allow-random", action="store_true",
                    help="serve with randomly initialized weights (smoke "
                         "runs / chaos drills only)")
    ap.add_argument("--slots", type=int, default=None)
    ap.add_argument("--max-new-tokens", type=int, default=None,
                    help="serving decode cap (default: the serving default, "
                         "clamped to fit the model's position budget)")
    ap.add_argument("--max-step-seconds", type=float, default=None)
    ap.add_argument("--breaker-threshold", type=int, default=None)
    ap.add_argument("--telemetry-dir", default=None)
    ap.add_argument("-v", "--verbose", action="store_true")
    a = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if a.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    from fairness_llm_tpu.config import ResilienceConfig, ServingConfig
    from fairness_llm_tpu.pipeline.backends import backend_for
    from fairness_llm_tpu.resilience import (
        GracefulDrain,
        ServingJournal,
        resume_serving,
    )

    config = default_config()
    res_kwargs: Dict = {"enabled": True, "journal_dir": a.journal_dir}
    if a.max_step_seconds is not None:
        res_kwargs["max_step_seconds"] = a.max_step_seconds
    if a.breaker_threshold is not None:
        res_kwargs["breaker_threshold"] = a.breaker_threshold
    serve_kwargs: Dict = {"enabled": True}
    if a.slots is not None:
        serve_kwargs["num_slots"] = a.slots
    from fairness_llm_tpu.models.configs import get_model_config

    # The scheduler requires max_new_tokens < the model's max_seq_len (a
    # KV-slot row holds prompt bucket + decode cap). Clamp the DEFAULT so
    # small study models resume without ceremony; an explicit flag is taken
    # verbatim and fails loudly if it can't fit.
    model_seq = get_model_config(a.model).max_seq_len
    serve_kwargs["max_new_tokens"] = (
        a.max_new_tokens if a.max_new_tokens is not None
        else min(ServingConfig().max_new_tokens, model_seq // 2)
    )
    config = dataclasses.replace(
        config,
        weights_dir=a.weights_dir,
        serving=ServingConfig(**serve_kwargs),
        resilience=ResilienceConfig(**res_kwargs),
        telemetry_dir=a.telemetry_dir,
    )
    sink = None
    if a.telemetry_dir:
        from fairness_llm_tpu import telemetry as T

        sink = T.configure(a.telemetry_dir)
    # The backend owns the engine build (weights, quant, single-device
    # guard); its journal handle is the same ledger we resume from, so
    # completions append terminal records and a SECOND preemption during
    # the resume re-journals the still-unfinished tail.
    backend = backend_for(a.model, config, allow_random=a.allow_random)
    journal = backend.journal or ServingJournal(a.journal_dir)
    with GracefulDrain():
        results = resume_serving(
            backend.engine, journal, serving=backend.serving,
            resilience=config.resilience,
        )
    outcomes: Dict[str, int] = {}
    for res in results.values():
        outcomes[res.finish_reason] = outcomes.get(res.finish_reason, 0) + 1
    print(f"resumed {len(results)} request(s): "
          + (", ".join(f"{k}={v}" for k, v in sorted(outcomes.items()))
             or "nothing to do"))
    still = journal.unfinished()
    if still:
        print(f"{len(still)} request(s) remain unfinished (drained again?) — "
              f"re-run resume-serving {a.journal_dir}")
    if a.telemetry_dir:
        from fairness_llm_tpu import telemetry as T

        path = T.write_snapshot(T.get_registry(), a.telemetry_dir)
        print(f"telemetry snapshot: {path}")
        if sink is not None:
            T.install_event_sink(None)
            sink.close()
    return 1 if still else 0


def rollout_cmd(argv) -> int:
    """``cli rollout`` — zero-downtime rolling version upgrade.

    Builds a ``ReplicaSet`` on the current model/weights, then walks it
    to a new immutable version with a :class:`RolloutController` while a
    synthetic workload streams through the fleet: one canary-gated
    standby per wave, stepped traffic shift, planned retirement of each
    old replica, automatic rollback on any deployment gate (manifest
    refusal of the incoming checkpoint, canary mismatch, SLO error burn,
    fairness alert / counterfactual pair divergence attributed to the
    new version, watchdog or breaker trip). Requests keep pinned-version
    affinity throughout: a stream finishes on the version that served
    its first token. See docs/SERVING.md §Rollouts.

    Exit status: 0 = rollout complete; 2 = rolled back (the gate and
    cause are printed and, with ``--telemetry-dir``, bundled under
    incidents/); 1 = requests lost (never expected — file a bug).
    """
    ap = argparse.ArgumentParser(
        prog="fairness_llm_tpu rollout",
        description="Drive a canary-gated rolling upgrade across a "
                    "replica fleet under live traffic",
    )
    ap.add_argument("--model", required=True,
                    help="engine model name for the CURRENT version")
    ap.add_argument("--weights-dir", default=None,
                    help="HF safetensors dir for the current version")
    ap.add_argument("--to-checkpoint", default=None, metavar="DIR",
                    help="HF safetensors dir for the NEW version's weights "
                         "(manifest-verified during PREPARING; a refused "
                         "checkpoint rolls back before any replica joins)")
    ap.add_argument("--to-config", default=None, metavar="MODEL",
                    help="model config name for the new version "
                         "(default: --model)")
    ap.add_argument("--to-version", default=None, metavar="ID",
                    help="immutable version id for the new fleet "
                         "(default: bump the current one, v0 -> v1)")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--canary-window", type=float, default=None,
                    metavar="S",
                    help="gate-watch window per traffic step (seconds)")
    ap.add_argument("--traffic-steps", type=int, default=None, metavar="N",
                    help="traffic-shift steps per wave")
    ap.add_argument("--abort-on-fairness-alert",
                    action=argparse.BooleanOptionalAction, default=True,
                    help="treat fairness alerts / counterfactual pair "
                         "divergence attributed to the new version as a "
                         "rollback gate (default: on)")
    ap.add_argument("--requests", type=int, default=32,
                    help="synthetic requests streamed during the rollout")
    ap.add_argument("--max-new-tokens", type=int, default=16)
    ap.add_argument("--slo-ttft-p95", type=float, default=None, metavar="S",
                    help="TTFT p95 target feeding the rollout's SLO burn "
                         "gate (default: the stack default; set generously "
                         "on CPU smoke runs or the gate will fire)")
    ap.add_argument("--slo-e2e-p99", type=float, default=None, metavar="S")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--telemetry-dir", default=None)
    ap.add_argument("--allow-random", action="store_true",
                    help="run with randomly initialized weights (smoke "
                         "runs / drills only)")
    ap.add_argument("-v", "--verbose", action="store_true")
    a = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if a.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    if not a.weights_dir and not a.allow_random:
        raise SystemExit("rollout needs --weights-dir (or --allow-random "
                         "for smoke runs)")
    import time

    from fairness_llm_tpu.config import (
        FleetConfig,
        IntegrityConfig,
        ModelSettings,
        ResilienceConfig,
        RolloutConfig,
        ServingConfig,
    )
    from fairness_llm_tpu.models.configs import get_model_config
    from fairness_llm_tpu.runtime.engine import DecodeEngine
    from fairness_llm_tpu.runtime.weights import load_checkpoint
    from fairness_llm_tpu.serving import ReplicaSet, Request, RolloutController
    from fairness_llm_tpu.serving.replay import DEFAULT_PROMPTS

    sink = None
    if a.telemetry_dir:
        from fairness_llm_tpu import telemetry as T

        sink = T.configure(a.telemetry_dir)
        T.arm_incidents(a.telemetry_dir)

    if a.slo_ttft_p95 is not None or a.slo_e2e_p99 is not None:
        from fairness_llm_tpu.telemetry.slo import SLOTargets, set_slo_targets

        slo_kwargs = {}
        if a.slo_ttft_p95 is not None:
            slo_kwargs["ttft_p95_s"] = a.slo_ttft_p95
        if a.slo_e2e_p99 is not None:
            slo_kwargs["e2e_p99_s"] = a.slo_e2e_p99
        set_slo_targets(SLOTargets(**slo_kwargs))

    cfg = get_model_config(a.model)
    engine = DecodeEngine(cfg, seed=a.seed)
    if a.weights_dir:
        engine.params = load_checkpoint(cfg, a.weights_dir)

    to_cfg = get_model_config(a.to_config) if a.to_config else cfg

    def new_engine():
        # Built lazily inside the controller's PREPARING step so a
        # manifest refusal of --to-checkpoint lands as a rollback gate,
        # not a CLI traceback.
        eng = DecodeEngine(to_cfg, seed=a.seed)
        if a.to_checkpoint:
            eng.params = load_checkpoint(to_cfg, a.to_checkpoint)
        return eng

    serving = ServingConfig(
        enabled=True, num_slots=2, queue_capacity=max(16, a.requests),
        max_new_tokens=min(a.max_new_tokens, cfg.max_seq_len // 2,
                           to_cfg.max_seq_len // 2),
    )
    fleet = ReplicaSet(
        engine, serving,
        settings=ModelSettings(temperature=0.0,
                               max_tokens=serving.max_new_tokens),
        fleet=FleetConfig(replicas=a.replicas),
        resilience=ResilienceConfig(enabled=True),
        integrity=IntegrityConfig(),
    )
    ro_kwargs = {}
    if a.canary_window is not None:
        ro_kwargs["canary_window_s"] = a.canary_window
    if a.traffic_steps is not None:
        ro_kwargs["traffic_steps"] = a.traffic_steps
    to_version = a.to_version or f"v{int(fleet.version.lstrip('v') or 0) + 1}"
    ro = RolloutController(
        fleet, to_version, engine_fn=new_engine,
        config=RolloutConfig(
            enabled=True,
            abort_on_fairness_alert=a.abort_on_fairness_alert,
            **ro_kwargs,
        ),
    )
    from_version = fleet.version
    ro.start()
    pending = [
        Request(prompt=DEFAULT_PROMPTS[i % len(DEFAULT_PROMPTS)],
                id=f"ro_{i}", settings=fleet.settings)
        for i in range(a.requests)
    ]
    results: Dict[str, object] = {}
    outstanding: list = []
    t0 = time.monotonic()
    while (ro.active or pending or outstanding or fleet.has_work):
        if time.monotonic() - t0 > 600.0:
            print("rollout wall guard tripped (600 s) — aborting")
            break
        if pending and fleet.submit(pending[0]):
            outstanding.append(pending.pop(0).id)
        fleet.tick()
        for rid in list(outstanding):
            res = fleet.take_result(rid)
            if res is not None:
                results[rid] = res
                outstanding.remove(rid)
    pins: Dict[str, int] = {}
    for rid in results:
        ver = fleet.request_version(rid) or "?"
        pins[ver] = pins.get(ver, 0) + 1
    lost = a.requests - len(results)
    print(f"rollout {from_version} -> {to_version}: state={ro.state}"
          + (f" cause={ro.cause}" if ro.cause else ""))
    print(f"served {len(results)}/{a.requests} request(s), pinned "
          + (", ".join(f"{k}={v}" for k, v in sorted(pins.items()))
             or "none"))
    if a.telemetry_dir:
        from fairness_llm_tpu import telemetry as T

        path = T.write_snapshot(T.get_registry(), a.telemetry_dir)
        print(f"telemetry snapshot: {path}")
        if sink is not None:
            T.install_event_sink(None)
            sink.close()
    if lost:
        print(f"LOST {lost} request(s) — this is a bug")
        return 1
    return 0 if ro.state == "complete" else 2


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "telemetry-report":
        # Subcommand dispatch ahead of the study parser (whose --all/--phase
        # group is required and would reject it).
        return telemetry_report(argv[1:])
    if argv and argv[0] == "perf-report":
        return perf_report(argv[1:])
    if argv and argv[0] == "memory-report":
        return memory_report(argv[1:])
    if argv and argv[0] == "slo-report":
        return slo_report(argv[1:])
    if argv and argv[0] == "fairness-report":
        return fairness_report(argv[1:])
    if argv and argv[0] == "incident-report":
        return incident_report(argv[1:])
    if argv and argv[0] == "resume-serving":
        return resume_serving_cmd(argv[1:])
    if argv and argv[0] == "rollout":
        return rollout_cmd(argv[1:])
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    print(BANNER)
    config = config_from_args(args)
    check_setup(config)
    save = not args.no_save
    telemetry_sink = None
    if args.incidents and not config.telemetry_dir:
        raise SystemExit("--incidents requires --telemetry-dir (bundles "
                         "dump under <telemetry-dir>/incidents)")
    if config.telemetry_dir:
        from fairness_llm_tpu import telemetry as T

        telemetry_sink = T.configure(config.telemetry_dir)
        if args.incidents:
            import os as _os

            from fairness_llm_tpu.telemetry import arm_incidents
            from fairness_llm_tpu.telemetry.incidents import INCIDENTS_DIRNAME

            arm_incidents(_os.path.join(config.telemetry_dir,
                                        INCIDENTS_DIRNAME))
    # Performance attribution setup (telemetry/slo.py, telemetry/roofline.py):
    # install the SLO objectives and the roofline reference BEFORE any
    # backend/scheduler is built, so every evaluator judges against the
    # configured targets from its first request.
    from fairness_llm_tpu.telemetry import (
        SLOTargets,
        set_achievable_gbps,
        set_slo_targets,
    )

    tc = config.telemetry
    set_slo_targets(SLOTargets(
        ttft_p95_s=tc.slo_ttft_p95_s, e2e_p99_s=tc.slo_e2e_p99_s,
        error_rate=tc.slo_error_rate, fast_window_s=tc.slo_fast_window_s,
        slow_window_s=tc.slo_slow_window_s,
    ))
    if tc.achievable_gbps:
        set_achievable_gbps(tc.achievable_gbps)

    if args.quick:
        args.num_items = min(args.num_items, 10)
        args.num_comparisons = min(args.num_comparisons, 6)

    from fairness_llm_tpu.utils import maybe_trace, phase_timer

    drain_handler = None
    if config.resilience.enabled and config.serving.enabled:
        # SIGTERM/SIGINT drain: the serving scheduler polls the handler's
        # flag each loop iteration, stops admission, finishes what it can
        # within --drain-grace, and journals the rest (when --serving-journal
        # is set) for `resume-serving`. Second signal = normal kill.
        from fairness_llm_tpu.resilience import GracefulDrain

        drain_handler = GracefulDrain().install()

    phases = [1, 2, 3] if args.all else [args.phase]
    timings: Dict[str, float] = {}
    p1 = None
    for phase in phases:
        if drain_handler is not None and drain_handler.requested:
            # A drain mid-phase already preempted/journaled that phase's
            # serving work; running the REMAINING phases would just burn
            # the preemption window producing instantly-preempted results.
            # Stop at the boundary and get to the snapshot/journal note.
            print(f"\ndrain requested — skipping phase {phase} and beyond")
            break
        with phase_timer(f"phase {phase}", timings), maybe_trace(
            config.profile_trace_dir, f"phase{phase}"
        ):
            if phase == 1:
                p1 = run_phase1(config, args.model, args.profiles, save=save, resume=args.resume)
                print_phase1_summary(p1)
                if save:
                    from fairness_llm_tpu.reports import (
                        generate_phase1_figures,
                        generate_summary_report,
                    )

                    generate_phase1_figures(p1, f"{config.results_dir}/visualizations")
                    generate_summary_report(
                        p1, f"{config.results_dir}/phase1/phase1_summary_report.txt"
                    )
            elif phase == 2:
                p2 = run_phase2(config, args.models or ([args.model] if args.model else None),
                                args.num_items, args.num_comparisons, save=save,
                                corpus=args.corpus, num_queries=args.num_queries)
                print_phase2_summary(p2)
                if save:
                    from fairness_llm_tpu.reports import generate_phase2_figure

                    generate_phase2_figure(p2, f"{config.results_dir}/visualizations")
            else:
                p3 = run_phase3(config, phase1_results=p1, model_name=args.model,
                                num_profiles=args.profiles, variant=args.variant,
                                strategy=args.strategy, save=save,
                                calibration=args.calibration,
                                confidence_mapping=args.confidence_mapping,
                                confidence_temperature=args.confidence_temperature)
                print_phase3_summary(p3)
                if save:
                    from fairness_llm_tpu.reports import generate_phase3_figure

                    generate_phase3_figure(p3, f"{config.results_dir}/visualizations")

    if drain_handler is not None:
        drain_handler.uninstall()
        if drain_handler.requested:
            print("\nNOTE: run was drained by a signal; unfinished serving "
                  "requests (if a --serving-journal was set) can be "
                  "finished with: resume-serving <journal-dir>")

    if config.profile_trace_dir:
        # Terminal-friendly device-op breakdown of the captured trace — the
        # analysis TensorBoard would show, without leaving the shell.
        try:
            from fairness_llm_tpu.utils.profiling import summarize_trace

            # One parse of all planes; prefer the TPU device planes and fall
            # back to host planes on CPU-only runs.
            summaries = summarize_trace(
                config.profile_trace_dir, top_k=8, device_filter=""
            )
            tpu = [s for s in summaries if "TPU" in s.device]
            for summary in tpu or summaries:
                print("\n" + summary.format())
        except Exception as e:  # noqa: BLE001 — diagnostics must not fail the run
            logger.warning("trace summary unavailable: %s", e)

    if config.telemetry_dir:
        # End-of-run snapshot (JSON + Prometheus text) and the terminal
        # report — the telemetry sibling of the trace summary above.
        from fairness_llm_tpu import telemetry as T

        try:
            path = T.write_snapshot(T.get_registry(), config.telemetry_dir)
            print("\n" + T.render_report(T.snapshot(T.get_registry())))
            print(f"\ntelemetry snapshot: {path}")
        except Exception as e:  # noqa: BLE001 — diagnostics must not fail the run
            logger.warning("telemetry snapshot unavailable: %s", e)
        finally:
            if telemetry_sink is not None:
                T.install_event_sink(None)
                telemetry_sink.close()

    # Perfetto timeline export. The telemetry dir ALWAYS gets its bundle
    # copy (trace.json beside the snapshot — what `telemetry-report
    # --timeline` and `validate_telemetry --require-profile` read);
    # --trace-out adds/redirects an extra copy at an explicit path.
    trace_paths = []
    if config.telemetry_dir:
        trace_paths.append(f"{config.telemetry_dir}/trace.json")
    if config.telemetry.trace_out \
            and config.telemetry.trace_out not in trace_paths:
        trace_paths.append(config.telemetry.trace_out)
    if trace_paths:
        from fairness_llm_tpu.telemetry import get_timeline

        try:
            for tp in trace_paths:
                out = get_timeline().export(tp)
                print(f"device-step timeline: {out} "
                      "(open at https://ui.perfetto.dev)")
        except Exception as e:  # noqa: BLE001 — diagnostics must not fail the run
            logger.warning("timeline export unavailable: %s", e)

    print("\n" + "=" * 60)
    print("RUN COMPLETE")
    for name, dt in timings.items():
        print(f"  {name}: {dt:.1f}s")
    print(f"results under: {config.results_dir}/")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""CLI orchestrator — ``python -m fairness_llm_tpu.cli.main``.

Mirrors the reference front-end surface (``main.py:184-214``): ``--all``,
``--phase {1,2,3}``, ``--quick``, model/profile-count flags, setup checks,
sequential phase execution with timing, and a cross-phase final summary —
plus the TPU-native knobs (mesh shape, weights dir, backend choice).

Run examples:
    python -m fairness_llm_tpu.cli.main --all --quick
    python -m fairness_llm_tpu.cli.main --phase 1 --model llama3-8b --mesh dp=8
    python -m fairness_llm_tpu.cli.main --phase 3 --variant smart
    python -m fairness_llm_tpu.cli.main --phase 1 --continuous --telemetry-dir tel/
    python -m fairness_llm_tpu.cli.main telemetry-report tel/
"""

from __future__ import annotations

import argparse
import dataclasses
import logging
import sys
from typing import Dict, Optional

from fairness_llm_tpu.config import Config, MeshConfig, create_directories, default_config
from fairness_llm_tpu.pipeline.phase1 import print_phase1_summary, run_phase1
from fairness_llm_tpu.pipeline.phase2 import print_phase2_summary, run_phase2
from fairness_llm_tpu.pipeline.phase3 import print_phase3_summary, run_phase3

logger = logging.getLogger(__name__)

BANNER = r"""
==========================================================
  fairness_llm_tpu — LLM recommendation fairness on TPU
  phase 1: bias detection   phase 2: cross-model ranking
  phase 3: FACTER mitigation
==========================================================
"""


def parse_mesh(spec: Optional[str]) -> MeshConfig:
    """'dp=2,tp=4' -> MeshConfig(dp=2, tp=4)."""
    if not spec:
        return MeshConfig()
    kwargs = {}
    for part in spec.split(","):
        k, _, v = part.partition("=")
        if k.strip() not in ("dp", "tp", "sp"):
            raise SystemExit(f"unknown mesh axis '{k}' (use dp/tp/sp)")
        try:
            kwargs[k.strip()] = int(v)
        except ValueError:
            raise SystemExit(f"bad mesh spec '{part}' (use e.g. dp=2,tp=4)") from None
    return MeshConfig(**kwargs)


def check_setup(config: Config) -> None:
    """Environment probes (reference ``check_setup``, ``main.py:42-76``):
    warn-and-continue on missing data, report the device fleet."""
    import os

    import jax

    create_directories(config)
    devices = jax.devices()
    print(f"devices: {len(devices)} x {devices[0].platform if devices else 'none'}")
    need = config.mesh.num_devices
    if need > len(devices):
        print(f"WARNING: mesh {config.mesh.shape} wants {need} devices, found {len(devices)}")
    if not os.path.exists(os.path.join(config.data_dir, "movies.dat")):
        print(f"WARNING: MovieLens not found at {config.data_dir}; synthetic fallback will be used")
    if config.weights_dir is None:
        print("NOTE: no --weights-dir; real model names will FAIL (no checkpoint to "
              "load) — use --model simulated for the no-weights deterministic backend")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="fairness_llm_tpu",
        description="Three-phase LLM recommendation-fairness study, TPU-native",
    )
    g = p.add_mutually_exclusive_group(required=True)
    g.add_argument("--all", action="store_true", help="run phases 1 -> 2 -> 3")
    g.add_argument("--phase", type=int, choices=(1, 2, 3), help="run one phase")
    p.add_argument("--quick", action="store_true",
                   help="demo mode: 1 profile/combo, fewer items/comparisons")
    p.add_argument("--model", default=None, help="model for phases 1/3 (or 'simulated')")
    p.add_argument("--models", nargs="+", default=None, help="models for phase 2")
    p.add_argument("--profiles", type=int, default=None, help="profiles per demographic combo")
    p.add_argument("--num-items", type=int, default=20, help="phase-2 ranking corpus size")
    p.add_argument("--corpus", default="synthetic", choices=("synthetic", "movielens"),
                   help="phase-2 corpus: reference-compat synthetic docs, or real "
                        "ML-1M titles with genre-derived groups")
    p.add_argument("--num-queries", type=int, default=1,
                   help="phase-2 listwise queries, decoded as one batch")
    p.add_argument("--num-comparisons", type=int, default=30, help="phase-2 pairwise budget")
    p.add_argument("--variant", default="conformal", choices=("conformal", "smart", "aggressive"),
                   help="phase-3 mitigation variant")
    p.add_argument("--strategy", default="demographic_parity",
                   choices=("demographic_parity", "equal_opportunity", "individual_fairness"))
    p.add_argument("--calibration", default="simulated", choices=("simulated", "model", "model-conditional"),
                   help="phase-3 conformal confidences: reference-style simulated "
                        "curve, the model's own unconditional title likelihoods, or "
                        "likelihoods conditioned on the profile's watch history "
                        "(model-conditional; demographics excluded from the context)")
    p.add_argument("--confidence-mapping", default="percentile",
                   choices=("percentile", "probability"),
                   help="with --calibration model or model-conditional: how "
                        "likelihoods map onto the conformal scale (rank-normalized, "
                        "or temperature-scaled probabilities — see "
                        "pipeline.facter.model_confidences)")
    p.add_argument("--confidence-temperature", type=float, default=1.0,
                   help="temperature for --confidence-mapping probability")
    p.add_argument("--max-new-tokens", type=int, default=None,
                   help="global decode-length cap: clamps every model's "
                        "max_tokens (bounds per-sweep decode cost)")
    p.add_argument("--speculate", action="store_true",
                   help="prompt-lookup speculative decoding for engine "
                        "backends (greedy decode only — sampled settings "
                        "silently use the plain path; output is identical "
                        "either way, see runtime/speculative.py)")
    p.add_argument("--draft-len", type=int, default=None,
                   help="with --speculate: drafted tokens verified per step")
    p.add_argument("--ngram-max", type=int, default=None,
                   help="with --speculate: longest lookup n-gram tried first")
    p.add_argument("--continuous", action="store_true",
                   help="serve engine backends through the continuous-"
                        "batching scheduler (serving/): fixed KV slot pool, "
                        "per-step eviction + backfill from a bounded "
                        "admission queue. Greedy output is token-for-token "
                        "identical to the static engine for prompts within "
                        "the serving budget (longer ones truncate, with a "
                        "warning); see docs/SERVING.md")
    p.add_argument("--slots", type=int, default=None,
                   help="with --continuous: concurrent KV slots "
                        "(= decode-step batch rows)")
    p.add_argument("--mesh", default=None, help="device mesh, e.g. 'dp=2,tp=4'")
    p.add_argument("--weights-dir", default=None, help="directory of HF safetensors checkpoints")
    p.add_argument("--weight-quant", default=None, choices=("none", "int8"),
                   help="weight-only quantization for served models: int8 "
                        "stores matmul kernels as int8 with dequant inside "
                        "the Pallas tile (fits llama3-70b tp=8 on a v5e-8)")
    p.add_argument("--data-dir", default=None, help="MovieLens-1M directory")
    p.add_argument("--results-dir", default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--resume", action="store_true", help="resume phase-1 sweep from checkpoints")
    p.add_argument("--trace-dir", default=None,
                   help="write a jax.profiler device trace per phase to this directory")
    p.add_argument("--telemetry-dir", default=None,
                   help="export telemetry here: streamed events.jsonl plus an "
                        "end-of-run registry snapshot (telemetry_snapshot.json "
                        "+ metrics.prom) with TTFT/queue-wait/latency "
                        "histograms; render with `telemetry-report <dir>` "
                        "(see docs/OBSERVABILITY.md)")
    p.add_argument("--no-save", action="store_true")
    p.add_argument("-v", "--verbose", action="store_true")
    return p


def config_from_args(args: argparse.Namespace) -> Config:
    config = default_config()
    updates: Dict = {}
    if args.mesh:
        updates["mesh"] = parse_mesh(args.mesh)
    if args.weights_dir:
        updates["weights_dir"] = args.weights_dir
    if args.weight_quant is not None:
        updates["weight_quant"] = args.weight_quant
    if args.data_dir:
        updates["data_dir"] = args.data_dir
    if args.results_dir:
        updates["results_dir"] = args.results_dir
    if args.seed is not None:
        updates["random_seed"] = args.seed
    if args.trace_dir:
        updates["profile_trace_dir"] = args.trace_dir
    if args.telemetry_dir:
        updates["telemetry_dir"] = args.telemetry_dir
    if args.max_new_tokens is not None:
        if args.max_new_tokens < 1:
            # A zero cap would reach the engine as a [B, 0] decode buffer and
            # die inside jit with an opaque dynamic_update_slice error.
            raise SystemExit("--max-new-tokens must be >= 1")
        updates["max_new_tokens"] = args.max_new_tokens
    if args.quick:
        updates["profiles_per_combo"] = 1
    if args.speculate or args.draft_len is not None or args.ngram_max is not None:
        from fairness_llm_tpu.config import SpeculationConfig

        if not args.speculate:
            raise SystemExit("--draft-len/--ngram-max require --speculate")
        spec_kwargs = {"enabled": True}
        if args.draft_len is not None:
            if args.draft_len < 1:
                raise SystemExit("--draft-len must be >= 1")
            spec_kwargs["draft_len"] = args.draft_len
        if args.ngram_max is not None:
            if args.ngram_max < 1:
                raise SystemExit("--ngram-max must be >= 1")
            spec_kwargs["ngram_max"] = args.ngram_max
        updates["speculation"] = SpeculationConfig(**spec_kwargs)
    if args.continuous or args.slots is not None:
        from fairness_llm_tpu.config import ServingConfig

        if not args.continuous:
            raise SystemExit("--slots requires --continuous")
        serve_kwargs = {"enabled": True}
        if args.slots is not None:
            if args.slots < 1:
                raise SystemExit("--slots must be >= 1")
            serve_kwargs["num_slots"] = args.slots
        updates["serving"] = ServingConfig(**serve_kwargs)
    if updates:
        config = dataclasses.replace(config, **updates)
    return config


def telemetry_report(argv) -> int:
    """``cli telemetry-report <dir|snapshot.json>`` — render a telemetry
    snapshot in the terminal (the ``summarize_trace`` of the metrics world:
    no dashboards required). ``--validate`` also runs the schema /
    percentile-consistency check and fails on problems (the CI smoke
    step's gate)."""
    ap = argparse.ArgumentParser(
        prog="fairness_llm_tpu telemetry-report",
        description="Render (and optionally validate) a telemetry snapshot",
    )
    ap.add_argument("path", help="telemetry dir (uses telemetry_snapshot.json "
                                 "inside) or a snapshot file")
    ap.add_argument("--validate", action="store_true",
                    help="schema-check the snapshot; non-zero exit on problems")
    a = ap.parse_args(argv)
    from fairness_llm_tpu.telemetry import load_snapshot, render_report, validate_snapshot

    snap = load_snapshot(a.path)
    if a.validate:
        # Validate BEFORE rendering: the renderer assumes a well-formed
        # snapshot, and the user asked for diagnostics, not a traceback.
        problems = validate_snapshot(snap)
        if problems:
            print("SNAPSHOT INVALID:")
            for p in problems:
                print(f"  - {p}")
            return 1
    print(render_report(snap))
    if a.validate:
        print("\nsnapshot schema: OK")
    return 0


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "telemetry-report":
        # Subcommand dispatch ahead of the study parser (whose --all/--phase
        # group is required and would reject it).
        return telemetry_report(argv[1:])
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    print(BANNER)
    config = config_from_args(args)
    check_setup(config)
    save = not args.no_save
    telemetry_sink = None
    if config.telemetry_dir:
        from fairness_llm_tpu import telemetry as T

        telemetry_sink = T.configure(config.telemetry_dir)

    if args.quick:
        args.num_items = min(args.num_items, 10)
        args.num_comparisons = min(args.num_comparisons, 6)

    from fairness_llm_tpu.utils import maybe_trace, phase_timer

    phases = [1, 2, 3] if args.all else [args.phase]
    timings: Dict[str, float] = {}
    p1 = None
    for phase in phases:
        with phase_timer(f"phase {phase}", timings), maybe_trace(
            config.profile_trace_dir, f"phase{phase}"
        ):
            if phase == 1:
                p1 = run_phase1(config, args.model, args.profiles, save=save, resume=args.resume)
                print_phase1_summary(p1)
                if save:
                    from fairness_llm_tpu.reports import (
                        generate_phase1_figures,
                        generate_summary_report,
                    )

                    generate_phase1_figures(p1, f"{config.results_dir}/visualizations")
                    generate_summary_report(
                        p1, f"{config.results_dir}/phase1/phase1_summary_report.txt"
                    )
            elif phase == 2:
                p2 = run_phase2(config, args.models or ([args.model] if args.model else None),
                                args.num_items, args.num_comparisons, save=save,
                                corpus=args.corpus, num_queries=args.num_queries)
                print_phase2_summary(p2)
                if save:
                    from fairness_llm_tpu.reports import generate_phase2_figure

                    generate_phase2_figure(p2, f"{config.results_dir}/visualizations")
            else:
                p3 = run_phase3(config, phase1_results=p1, model_name=args.model,
                                num_profiles=args.profiles, variant=args.variant,
                                strategy=args.strategy, save=save,
                                calibration=args.calibration,
                                confidence_mapping=args.confidence_mapping,
                                confidence_temperature=args.confidence_temperature)
                print_phase3_summary(p3)
                if save:
                    from fairness_llm_tpu.reports import generate_phase3_figure

                    generate_phase3_figure(p3, f"{config.results_dir}/visualizations")

    if config.profile_trace_dir:
        # Terminal-friendly device-op breakdown of the captured trace — the
        # analysis TensorBoard would show, without leaving the shell.
        try:
            from fairness_llm_tpu.utils.profiling import summarize_trace

            # One parse of all planes; prefer the TPU device planes and fall
            # back to host planes on CPU-only runs.
            summaries = summarize_trace(
                config.profile_trace_dir, top_k=8, device_filter=""
            )
            tpu = [s for s in summaries if "TPU" in s.device]
            for summary in tpu or summaries:
                print("\n" + summary.format())
        except Exception as e:  # noqa: BLE001 — diagnostics must not fail the run
            logger.warning("trace summary unavailable: %s", e)

    if config.telemetry_dir:
        # End-of-run snapshot (JSON + Prometheus text) and the terminal
        # report — the telemetry sibling of the trace summary above.
        from fairness_llm_tpu import telemetry as T

        try:
            path = T.write_snapshot(T.get_registry(), config.telemetry_dir)
            print("\n" + T.render_report(T.snapshot(T.get_registry())))
            print(f"\ntelemetry snapshot: {path}")
        except Exception as e:  # noqa: BLE001 — diagnostics must not fail the run
            logger.warning("telemetry snapshot unavailable: %s", e)
        finally:
            if telemetry_sink is not None:
                T.install_event_sink(None)
                telemetry_sink.close()

    print("\n" + "=" * 60)
    print("RUN COMPLETE")
    for name, dt in timings.items():
        print(f"  {name}: {dt:.1f}s")
    print(f"results under: {config.results_dir}/")
    return 0


if __name__ == "__main__":
    sys.exit(main())

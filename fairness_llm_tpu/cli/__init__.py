"""Command-line front-end (reference ``src/main.py`` equivalent)."""

"""Trace-driven load replay: synthetic million-user traffic against the fleet.

Every control surface below this module — QoS classes and the shed ladder
(PR 8), fence/rejoin and journal migration (PR 6), SLO burn gauges (PR 7),
the autoscaler (``serving/autoscaler.py``) — has only ever been exercised
by the batch sweep plus hand-scripted chaos: one traffic shape. This
module generates the shapes production actually serves and replays them
deterministically:

**Trace generation** (``TraceConfig`` + ``generate_trace``): a seeded
non-homogeneous Poisson process over a million-user id space —

- a **diurnal rate curve** (sinusoid over ``diurnal_period_s``) scales the
  session-arrival rate through the day;
- a **burst overlay** (``bursts``: (start, duration, multiplier) tuples)
  multiplies it for flash crowds;
- **heavy-tailed sessions**: each arrival is a SESSION whose turn count is
  Pareto-distributed (most users ask once; a tail asks dozens of times),
  with exponential think time between turns — so load autocorrelates the
  way user populations do instead of arriving i.i.d.;
- a **QoS mix**: each session is interactive (latency-sensitive, optional
  deadline) or batch with seeded probability.

Session arrivals use Lewis–Shedler thinning (draw at the peak rate, keep
with probability rate(t)/peak), so the same seed produces the same
arrival set under any rate-curve parameters. Every event carries a stable
id, prompt, QoS class, decode budget, and row seed; ``write_trace`` emits
byte-deterministic JSONL (sorted keys, rounded stamps) — the same seed
produces the same file, byte for byte, which is the first half of the
replay determinism contract.

**Replay** (``ReplayDriver``): events are submitted against a
``ReplicaSet``'s streaming surface (``submit``/``tick``/``take_result``)
when their arrival time comes due on a ``ReplayClock`` — an injectable
monotonic clock reading ``(monotonic() - t0) * compression`` in TRACE
seconds. With ``compression=1440`` a 24-hour trace replays in one minute;
event ORDER and spacing come from the trace, not from how fast the
harness happens to decode, so a same-seed re-run offers the same load.
Request deadlines are divided by the compression factor at submission
(trace-time budgets hold in compressed wall time); time-dependent serving
knobs (aging, healthy windows, SLO windows) are the operator's to scale
the same way — ``tools/load_replay.py`` shows the mapping. The driver
arms a ``ScriptedFaultInjector`` with its trace clock, so ``at_seconds``
fault schedules fire at trace-time positions ("crash r1 mid-burst")
independent of compression.

Accounting is the zero-loss ledger the drills gate on: every event is
``accepted`` (fleet took it — it must reach a terminal Result), ``shed``
(explicit refusal Result with retry-after), or ``backpressured`` (queue
full — the driver retries while the arrival stays due, like a client with
a retry loop). ``replay_accepted_total`` / ``replay_terminal_total``
counters make "zero accepted-then-lost" machine-checkable
(``validate_telemetry --require-autoscale``).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from fairness_llm_tpu.serving.request import Request, Result
from fairness_llm_tpu.telemetry import get_registry

logger = logging.getLogger(__name__)

TRACE_VERSION = 1

# A tiny built-in prompt catalog so traces can generate without a study
# corpus; real drills pass the sweep's own prompts for realistic shapes.
DEFAULT_PROMPTS = (
    "recommend five movies for a quiet evening",
    "recommend five upbeat movies for a road trip",
    "recommend five classic films for a family night",
    "recommend five documentaries about nature",
    "recommend five comedies from the nineties",
    "recommend five thrillers with a twist ending",
    "recommend five animated films for all ages",
    "recommend five dramas with strong ensembles",
)


@dataclasses.dataclass(frozen=True)
class TraceConfig:
    """Knobs for one synthetic trace. Everything is TRACE time (seconds
    from trace start); the replay's compression factor maps it to wall
    time later. Frozen/hashable like every other config object."""

    seed: int = 0
    duration_s: float = 86400.0  # trace span (default: one day)
    users: int = 1_000_000  # user-id space sessions draw from
    # Session arrivals per second at the diurnal MIDLINE. The mean request
    # rate is roughly base_sessions_per_s x mean session turns.
    base_sessions_per_s: float = 0.5
    # Diurnal curve: rate x (1 + amplitude * sin(2pi (t+phase)/period)),
    # clamped at 0. amplitude 0 = flat.
    diurnal_amplitude: float = 0.6
    diurnal_period_s: float = 86400.0
    diurnal_phase_s: float = 0.0
    # Burst overlay: (start_s, duration_s, multiplier) windows that
    # multiply the instantaneous rate — flash crowds on the diurnal base.
    bursts: Tuple[Tuple[float, float, float], ...] = ()
    # Heavy-tailed session length: turns = 1 + floor(Pareto(alpha)),
    # capped. alpha 1.3 gives mean ~4 with a long tail.
    session_tail_alpha: float = 1.3
    session_max_turns: int = 32
    think_time_s: float = 120.0  # mean exponential gap between turns
    interactive_frac: float = 0.85  # sessions that are interactive QoS
    # Per-class deadlines in TRACE seconds (None = no deadline). The
    # replay driver scales them by 1/compression at submission.
    interactive_deadline_s: Optional[float] = None
    batch_deadline_s: Optional[float] = None
    max_tokens_choices: Tuple[int, ...] = (8, 12, 16, 24)
    max_events: Optional[int] = None  # hard cap (None = the curve decides)


@dataclasses.dataclass
class TraceEvent:
    """One request arrival in trace time."""

    t: float  # trace seconds from start
    id: str
    user: int
    session: int
    turn: int
    prompt: str
    qos: str
    max_tokens: int
    row_seed: int
    deadline_s: Optional[float] = None

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        if d["deadline_s"] is None:
            del d["deadline_s"]
        return json.dumps(d, sort_keys=True, separators=(",", ":"))

    @classmethod
    def from_json(cls, line: str) -> "TraceEvent":
        return cls(**json.loads(line))


def _rate(cfg: TraceConfig, t: float) -> float:
    lam = cfg.base_sessions_per_s * max(
        0.0,
        1.0 + cfg.diurnal_amplitude
        * math.sin(2.0 * math.pi * (t + cfg.diurnal_phase_s)
                   / cfg.diurnal_period_s),
    )
    for start, dur, mult in cfg.bursts:
        if start <= t < start + dur:
            lam *= mult
    return lam


def _peak_burst_mult(bursts: Tuple[Tuple[float, float, float], ...]) -> float:
    """Max PRODUCT of simultaneously-active burst multipliers. Overlapping
    windows multiply in ``_rate``, so the thinning majorant must bound the
    product, not the largest single multiplier — otherwise rate(t)/peak
    silently clamps past 1 in the overlap and the trace under-generates.
    The product is piecewise-constant between window boundaries; every
    maximal interval starts at t=0, a window start, or a window end."""
    best = 1.0
    points = {0.0}
    for start, dur, _ in bursts:
        points.add(start)
        points.add(start + dur)
    for t in points:
        prod = 1.0
        for start, dur, mult in bursts:
            if start <= t < start + dur:
                prod *= mult
        best = max(best, prod)
    return best


def _peak_rate(cfg: TraceConfig) -> float:
    peak = cfg.base_sessions_per_s * (1.0 + abs(cfg.diurnal_amplitude))
    return max(peak * _peak_burst_mult(cfg.bursts), 1e-9)


def generate_trace(cfg: TraceConfig,
                   prompts: Sequence[str] = DEFAULT_PROMPTS
                   ) -> List[TraceEvent]:
    """Deterministic synthetic trace: same (cfg, prompts) -> same events.
    Events come back sorted by (t, id) with stamps rounded to
    microseconds, so serialization is byte-stable."""
    if not prompts:
        raise ValueError("generate_trace needs a non-empty prompt catalog")
    rng = np.random.default_rng(cfg.seed)
    peak = _peak_rate(cfg)
    events: List[TraceEvent] = []
    t = 0.0
    session = 0
    while True:
        # Lewis–Shedler thinning: candidate arrivals at the PEAK rate,
        # kept with probability rate(t)/peak — one rng stream regardless
        # of curve parameters.
        t += float(rng.exponential(1.0 / peak))
        if t >= cfg.duration_s:
            break
        if float(rng.random()) > _rate(cfg, t) / peak:
            continue
        user = int(rng.integers(cfg.users))
        turns = 1 + int(rng.pareto(cfg.session_tail_alpha))
        turns = min(turns, cfg.session_max_turns)
        interactive = float(rng.random()) < cfg.interactive_frac
        qos = "interactive" if interactive else "batch"
        deadline = (cfg.interactive_deadline_s if interactive
                    else cfg.batch_deadline_s)
        tt = t
        for turn in range(turns):
            if turn:
                tt += float(rng.exponential(cfg.think_time_s))
            if tt >= cfg.duration_s:
                break
            prompt = prompts[int(rng.integers(len(prompts)))]
            max_tokens = int(
                cfg.max_tokens_choices[
                    int(rng.integers(len(cfg.max_tokens_choices)))
                ]
            )
            # Stable per-request identity: the row seed keys the sampling
            # stream, so a migrated/requeued/re-run request decodes the
            # same text (the engine's row_seeds contract).
            row_seed = (cfg.seed * 2_654_435_761
                        + user * 1_000_003 + session * 8191 + turn) \
                & 0xFFFFFFFF
            events.append(TraceEvent(
                t=round(tt, 6),
                id=f"u{user:07d}_s{session:06d}_t{turn:02d}",
                user=user, session=session, turn=turn,
                prompt=prompt, qos=qos, max_tokens=max_tokens,
                row_seed=row_seed, deadline_s=deadline,
            ))
        session += 1
        if cfg.max_events is not None and len(events) >= cfg.max_events:
            events = events[: cfg.max_events]
            break
    events.sort(key=lambda e: (e.t, e.id))
    return events


def write_trace(path: str, events: Sequence[TraceEvent],
                cfg: Optional[TraceConfig] = None) -> str:
    """Write one JSONL trace: a header record (version + the generating
    config, when given) then one event per line. Byte-deterministic for a
    given (cfg, events)."""
    with open(path, "w", encoding="utf-8") as f:
        header = {"trace_version": TRACE_VERSION}
        if cfg is not None:
            header["config"] = dataclasses.asdict(cfg)
        f.write(json.dumps(header, sort_keys=True,
                           separators=(",", ":")) + "\n")
        for ev in events:
            f.write(ev.to_json() + "\n")
    return path


def read_trace(path: str) -> List[TraceEvent]:
    events: List[TraceEvent] = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            if i == 0 and "trace_version" in line:
                continue  # header record
            events.append(TraceEvent.from_json(line))
    return events


class ReplayClock:
    """Monotonic TRACE-time clock: ``now()`` is trace seconds elapsed,
    i.e. ``(clock() - t0) * compression``. Injectable base clock for
    deterministic tests (a fake clock stepping a fixed dt per read walks
    the replay through its schedule with no sleeping)."""

    def __init__(self, compression: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        if compression <= 0:
            raise ValueError(f"compression must be > 0, got {compression}")
        self.compression = float(compression)
        self._clock = clock
        self._t0 = clock()

    def now(self) -> float:
        return (self._clock() - self._t0) * self.compression

    __call__ = now


@dataclasses.dataclass
class ReplayReport:
    """What one replay did — the drill's raw material."""

    events: int = 0
    accepted: int = 0
    gate_sheds: int = 0  # refused at the overload gate (terminal Results)
    backpressured: int = 0  # refusal INSTANCES (an event may retry many)
    dropped: int = 0  # events never accepted (still backpressured at end)
    outcomes: Dict[str, int] = dataclasses.field(default_factory=dict)
    tokens: Dict[str, Tuple[int, ...]] = dataclasses.field(
        default_factory=dict)
    # Full Result objects, populated only under ReplayDriver(
    # keep_results=True): at the advertised million-user scale, retaining
    # every Result would roughly double the driver's memory for data
    # ``tokens``/``outcomes``/``ttft_by_qos`` already carry.
    results: Dict[str, Result] = dataclasses.field(default_factory=dict)
    ttft_by_qos: Dict[str, List[float]] = dataclasses.field(
        default_factory=dict)
    wall_s: float = 0.0
    trace_span_s: float = 0.0
    compression: float = 1.0
    timed_out: bool = False

    @property
    def terminal(self) -> int:
        """Terminal Results observed, gate refusals included (``outcomes``
        counts both — an explicit shed is an answer, not a loss)."""
        return sum(self.outcomes.values())

    @property
    def lost(self) -> int:
        """Accepted-then-lost — the number the whole stack exists to keep
        at zero. Gate sheds were never accepted, so they subtract out."""
        return self.accepted - (self.terminal - self.gate_sheds)

    def shed_rate(self) -> float:
        """Explicit refusals (gate + post-admission sheds) over everything
        terminally answered."""
        return (self.outcomes.get("shed", 0) / self.terminal
                if self.terminal else 0.0)

    def slo_attainment(self, ttft_target_s: float,
                       qos: str = "interactive") -> Optional[float]:
        """Fraction of completed ``qos`` requests whose TTFT met the
        target (None when none completed with a TTFT)."""
        vals = self.ttft_by_qos.get(qos, [])
        if not vals:
            return None
        return sum(1 for v in vals if v <= ttft_target_s) / len(vals)

    def summary(self) -> Dict:
        out = {
            "events": self.events,
            "accepted": self.accepted,
            "terminal": self.terminal,
            "lost": self.lost,
            "gate_sheds": self.gate_sheds,
            "backpressured": self.backpressured,
            "dropped": self.dropped,
            "outcomes": dict(sorted(self.outcomes.items())),
            "shed_rate": round(self.shed_rate(), 4),
            "wall_s": round(self.wall_s, 3),
            "trace_span_s": round(self.trace_span_s, 3),
            "compression": self.compression,
            "timed_out": self.timed_out,
        }
        for qos, vals in sorted(self.ttft_by_qos.items()):
            if vals:
                v = sorted(vals)
                out[f"ttft_p50_{qos}_s"] = round(
                    v[len(v) // 2], 4)
                out[f"ttft_p95_{qos}_s"] = round(
                    v[min(len(v) - 1, int(0.95 * len(v)))], 4)
        return out


class ReplayDriver:
    """Replays one trace against a ``ReplicaSet`` (or anything exposing
    the same ``submit``/``tick``/``take_result``/``has_work``/``drain``
    streaming surface, e.g. a bare ``ContinuousScheduler`` via a thin
    adapter).

    ``settings`` is the fleet's compiled sampler settings; each event's
    ``max_tokens`` replaces the decode budget per request (sampler fields
    must match the fleet — one fleet, one compiled sampler). ``max_wall_s``
    is the CI hang-guard: a replay that exceeds it stops submitting,
    drains what it accepted, and reports ``timed_out`` instead of wedging
    the job."""

    def __init__(self, fleet, events: Sequence[TraceEvent],
                 compression: float = 1.0,
                 clock: Callable[[], float] = time.monotonic,
                 fault_injector=None,
                 scale_deadlines: bool = True,
                 max_wall_s: Optional[float] = None,
                 poll_s: float = 0.001,
                 tail_s: float = 0.0,
                 keep_results: bool = False):
        self.fleet = fleet
        self.events = sorted(events, key=lambda e: (e.t, e.id))
        self.compression = float(compression)
        self._base_clock = clock
        self.fault_injector = fault_injector
        self.scale_deadlines = scale_deadlines
        self.max_wall_s = max_wall_s
        self.poll_s = poll_s
        # Quiet-tail ticking, in TRACE seconds past the last event: the
        # replay keeps driving the fleet loop through the post-trace lull
        # so time-based controllers (autoscaler scale-DOWN hysteresis,
        # brownout de-escalation, SLO window decay) see the quiet period
        # instead of the run ending the instant the last Result lands.
        self.tail_s = float(tail_s)
        self.keep_results = keep_results

    def _request_for(self, ev: TraceEvent) -> Request:
        settings = dataclasses.replace(self.fleet.settings,
                                       max_tokens=ev.max_tokens)
        deadline = ev.deadline_s
        if deadline is not None and self.scale_deadlines:
            # Trace-time budgets hold under compression: a 2 s deadline in
            # a 60x-compressed day is ~33 ms of wall time — the workload's
            # urgency scales with its arrival cadence.
            deadline = deadline / self.compression
        return Request(
            prompt=ev.prompt, id=ev.id, settings=settings,
            row_seed=ev.row_seed, deadline_s=deadline, qos=ev.qos,
        )

    def run(self) -> ReplayReport:
        reg = get_registry()
        trace_clock = ReplayClock(self.compression, self._base_clock)
        if self.fault_injector is not None and \
                hasattr(self.fault_injector, "arm"):
            # Time-indexed fault schedules fire in TRACE seconds: "crash
            # r1 at t=30" means mid-burst whatever the compression.
            self.fault_injector.arm(clock=trace_clock)
        report = ReplayReport(
            events=len(self.events), compression=self.compression,
            trace_span_s=self.events[-1].t if self.events else 0.0,
        )
        outstanding: Dict[str, TraceEvent] = {}
        # Backpressured/shed arrivals awaiting re-offer, as (event,
        # not-before trace-time). Backpressure keeps the hot re-offer
        # (not_before = now); a SHED Result's retry_after_s is honored as
        # real wall seconds (x compression = trace seconds), so the replay
        # client backs off exactly as a well-behaved caller would instead
        # of hammering the gate every poll.
        retry: List[Tuple[TraceEvent, float]] = []
        shed_retried: Dict[str, int] = {}  # id -> honored-shed count
        i = 0
        t0_wall = time.monotonic()
        reg.counter("replay_events_total", component="replay") \
            .inc(len(self.events))
        submitting = True
        abandoned = False
        while True:
            now = trace_clock.now()
            progressed = False
            if submitting:
                due: List[Tuple[TraceEvent, bool]] = []
                if retry:
                    still_held = [(ev, nb) for ev, nb in retry if nb > now]
                    due.extend((ev, True) for ev, nb in retry if nb <= now)
                    retry = still_held
                while i < len(self.events) and self.events[i].t <= now:
                    due.append((self.events[i], False))
                    i += 1
                for ev, is_retry in due:
                    # A retry re-offers an arrival the fleet already
                    # counted one rejection for; re-counting every ~1 ms
                    # poll would inflate the rejection stats by orders of
                    # magnitude during saturation.
                    if self.fleet.submit(self._request_for(ev),
                                         count_rejection=not is_retry):
                        outstanding[ev.id] = ev
                        report.accepted += 1
                        reg.counter("replay_accepted_total",
                                    component="replay").inc()
                        progressed = True
                        continue
                    res = self.fleet.take_result(ev.id)
                    if res is not None:
                        if res.retry_after_s is not None and \
                                shed_retried.get(ev.id, 0) < 1:
                            # The gate shed WITH retry advice: honor it
                            # once. No outcome is recorded — the arrival
                            # comes back after the advised backoff and
                            # its retry decides terminal-vs-shed. Trace
                            # time runs compression x wall, so wall
                            # advice maps to advice x compression.
                            shed_retried[ev.id] = \
                                shed_retried.get(ev.id, 0) + 1
                            reg.counter("replay_retry_after_honored_total",
                                        component="replay").inc()
                            retry.append(
                                (ev, now + res.retry_after_s
                                 * self.compression))
                            progressed = True
                            continue
                        # Terminal shed at the gate — an explicit refusal
                        # Result, not backpressure (or retry advice was
                        # already honored once: record the re-shed).
                        report.gate_sheds += 1
                        self._record(report, ev, res, reg, accepted=False)
                        progressed = True
                    else:
                        report.backpressured += 1
                        reg.counter("replay_backpressure_total",
                                    component="replay").inc()
                        retry.append((ev, now))
            progressed |= self.fleet.tick()
            for rid in list(outstanding):
                res = self.fleet.take_result(rid)
                if res is not None:
                    self._record(report, outstanding.pop(rid), res, reg)
                    progressed = True
            if not (i < len(self.events) or retry or outstanding
                    or self.fleet.has_work):
                if now >= report.trace_span_s + self.tail_s:
                    break
            if self.max_wall_s is not None and \
                    time.monotonic() - t0_wall > self.max_wall_s:
                if submitting:
                    # Stop offering load, keep draining what was accepted
                    # — the zero-lost contract outranks trace completion.
                    logger.warning(
                        "replay wall guard hit at %.1fs: %d events unsent, "
                        "%d outstanding — draining", self.max_wall_s,
                        len(self.events) - i + len(retry), len(outstanding))
                    report.timed_out = True
                    report.dropped += len(self.events) - i + len(retry)
                    retry = []
                    i = len(self.events)
                    submitting = False
                elif time.monotonic() - t0_wall > 2 * self.max_wall_s:
                    logger.error("replay drain guard hit; abandoning %d "
                                 "outstanding", len(outstanding))
                    abandoned = True
                    break
            if not progressed:
                time.sleep(self.poll_s)
        if not abandoned:
            # Close the stats window (also publishes per-replica stats).
            # When the drain guard fired, the fleet still OWES the
            # abandoned requests — its unbounded drain() loop would hang
            # on exactly the wedge the guard exists to escape, so the
            # stats window stays open and the report carries the loss.
            self.fleet.drain()
        report.wall_s = time.monotonic() - t0_wall
        reg.gauge("replay_outstanding", component="replay") \
            .set(len(outstanding))
        return report

    def _record(self, report: ReplayReport, ev: TraceEvent, res: Result,
                reg, accepted: bool = True) -> None:
        outcome = res.finish_reason
        if res.ok:
            outcome = "completed"
        report.outcomes[outcome] = report.outcomes.get(outcome, 0) + 1
        if self.keep_results:
            report.results[ev.id] = res
        if res.ok:
            report.tokens[ev.id] = tuple(int(t) for t in res.tokens)
        if res.ttft_s is not None:
            report.ttft_by_qos.setdefault(ev.qos, []).append(res.ttft_s)
        # Accepted terminals only: replay_accepted_total ==
        # replay_terminal_total is the machine-checkable zero-accepted-
        # then-lost witness; gate refusals count separately.
        name = ("replay_terminal_total" if accepted
                else "replay_gate_shed_total")
        reg.counter(name, component="replay", outcome=outcome).inc()


__all__ = [
    "DEFAULT_PROMPTS",
    "ReplayClock",
    "ReplayDriver",
    "ReplayReport",
    "TraceConfig",
    "TraceEvent",
    "generate_trace",
    "read_trace",
    "write_trace",
]

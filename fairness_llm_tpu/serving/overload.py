"""Overload control: QoS classes, deadline-aware admission, SLO-driven
load shedding.

The stack below this module survives *faults* — watchdog, breakers,
fence/migrate/rejoin — but not *overload*: the admission queue was one FIFO
with one rate limiter, every request was equal, and a doomed request (a
deadline unmeetable at admission time) still burned a full prefill before
expiring. At production scale the paper's own workload makes that acute:
the phase-1/3 counterfactual sweeps are batch floods that would starve
interactive recommendation traffic, and PR 7's SLO burn rates could *see*
the starvation but nothing *acted* on it beyond a router discount. This
module is the acting half:

- **QoS classes** (``Request.qos``: ``interactive`` / ``batch`` /
  ``probe``): the admission queue becomes per-class bounded sub-queues
  (``ClassedAdmissionQueue``, serving/queue.py) with per-class rate
  quotas and strict-priority-with-aging dequeue — a batch flood can never
  delay an interactive admission by more than the chunk in flight, while
  aging bounds batch starvation under a steady interactive stream.
- **Deadline-feasibility admission** (``DeadlineEstimator``): from live
  telemetry (the ``prefill_wall_s`` and ``per_output_token_s`` histograms
  this scheduler already feeds), lower-bound the earliest possible first
  token — queue turnover waves + one prefill + one decode step — and
  REJECT with ``finish_reason="shed"`` + a retry-after hint any request
  whose remaining deadline is provably below it. The bound is
  deliberately optimistic (p50 estimates, a ``feasibility_safety``
  discount, cold start never rejects): only certainly-doomed work sheds;
  everything marginal is admitted and judged by the real clock.
- **SLO-driven shedding** (``ShedController``): a brownout ladder driven
  by the fast-window burn rates (``telemetry/slo.py``) and the admission
  queue depth, with hysteresis:

      0 healthy
      1 shed_batch          — reject new batch admissions (retry-after)
      2 cap_batch_tokens    — also clamp batch max_new_tokens
      3 interactive_only    — reject everything non-interactive

  Escalation moves at most ONE rung per evaluation while any signal is
  hot; de-escalation requires ``healthy_window_s`` of sustained health
  per rung (a flapping signal ratchets up but cannot oscillate). Every
  transition is exported: the ``overload_level`` gauge,
  ``overload_transitions_total{to}`` counters, ``overload_shed`` /
  ``overload_restore`` JSONL events, and shed/restore instants on the
  scheduler's timeline track. Sheds count ``shed_total{class,reason}``.

Placement: the gate lives at the serving FRONT DOOR — the
``ContinuousScheduler`` when it is the front door (single-engine mode),
the ``ReplicaSet`` intake in fleet mode (replica schedulers stay plain:
gating per-replica after fleet routing would double-shed). Shed requests
are excluded from SLO burn math (like ``preempted``): deliberate load
shedding is flow control the controller itself reports via ``shed_total``
— feeding it back into the error burn would lock the ladder at its top
rung. See docs/SERVING.md §QoS and overload control.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from fairness_llm_tpu.config import OverloadConfig
from fairness_llm_tpu.serving.request import QOS_CLASSES, QOS_PRIORITY, Request
from fairness_llm_tpu.telemetry import emit_event, get_registry
from fairness_llm_tpu.telemetry.flightrecorder import get_flight_recorder
from fairness_llm_tpu.telemetry.incidents import record_decision
from fairness_llm_tpu.telemetry.timeline import get_timeline

logger = logging.getLogger(__name__)

# Brownout rungs, in escalation order. Rung semantics live in admits() /
# batch_cap(); the names label events, logs, and the gauge description.
SHED_LADDER = ("healthy", "shed_batch", "cap_batch_tokens",
               "interactive_only")


def count_shed(qos: str, reason: str, component: str = "serving",
               labels: Optional[Dict[str, str]] = None) -> None:
    """One shed, attributed: ``shed_total{class, reason}``. Reasons:
    ``overload`` (class refused at the current brownout rung),
    ``deadline_infeasible`` (feasibility admission), ``queue_full`` /
    ``rate_limit`` (per-class bounds, when the caller terminates rather
    than backpressures)."""
    get_registry().counter(
        "shed_total", component=component,
        **{"class": qos, "reason": reason, **(labels or {})},
    ).inc()


class DeadlineEstimator:
    """Feasibility math for admission, from live telemetry.

    The earliest possible first token for a request with ``queued_ahead``
    same-or-higher-priority requests in front of it is lower-bounded by

        waves x chunk_wall + prefill + one_decode_step

    where ``waves = queued_ahead // num_slots`` (each turnover of the slot
    pool frees at most ``num_slots`` seats and takes at least one compiled
    decode chunk) and the walls come from this scheduler's own histograms
    — ``prefill_wall_s`` p50 and ``per_output_token_s`` p50 (the steady
    decode cadence). Every term is an under-estimate on purpose: admitted
    rows usually hold their slots far longer than one chunk, so a request
    failing even this bound is *provably* doomed. ``safety`` discounts the
    bound further before it can reject. With no telemetry yet (cold
    start), ``estimate_ttft_s`` returns None and nothing is ever shed.
    """

    def __init__(self, safety: float = 0.5, component: str = "serving",
                 labels: Optional[Dict[str, str]] = None,
                 clock=time.monotonic):
        self.safety = float(safety)
        self.component = component
        self.labels = dict(labels or {})
        self._clock = clock

    def _p50(self, name: str) -> Optional[float]:
        h = get_registry().peek(name, component=self.component,
                                **self.labels)
        if h is None or not getattr(h, "count", 0):
            return None
        return h.percentile(50)

    def estimate_ttft_s(self, queued_ahead: int, num_slots: int,
                        decode_chunk: int) -> Optional[float]:
        """Lower-bound seconds to the request's first token, or None when
        there is no telemetry to bound with."""
        prefill = self._p50("prefill_wall_s")
        per_tok = self._p50("per_output_token_s")
        if prefill is None and per_tok is None:
            return None
        waves = max(0, int(queued_ahead)) // max(int(num_slots), 1)
        chunk_s = (per_tok or 0.0) * max(int(decode_chunk), 1)
        return waves * chunk_s + (prefill or 0.0) + (per_tok or 0.0)

    def infeasible(self, request: Request, queued_ahead: int,
                   num_slots: int, decode_chunk: int,
                   now: Optional[float] = None) -> Optional[float]:
        """None when the request might make its deadline (or has none, or
        safety is 0, or telemetry is cold); otherwise the estimated
        earliest-TTFT in seconds — the retry-after hint's basis. A
        deadline already in the past is infeasible by definition."""
        if request.deadline_s is None or self.safety <= 0.0:
            return None
        t = self._clock() if now is None else now
        remaining = request.submitted_at + request.deadline_s - t
        est = self.estimate_ttft_s(queued_ahead, num_slots, decode_chunk)
        if remaining <= 0.0:
            return est if est is not None else 0.0
        if est is not None and remaining < self.safety * est:
            return est
        return None


class ShedController:
    """The brownout ladder: one level in [0, 3], walked up under sustained
    overload signals and back down only after a sustained-healthy window
    per rung. One controller per serving front door (scheduler or fleet),
    labeled like its other instruments."""

    def __init__(self, config: Optional[OverloadConfig] = None,
                 component: str = "serving",
                 labels: Optional[Dict[str, str]] = None,
                 clock=time.monotonic, burn_fn=None):
        self.config = config or OverloadConfig(enabled=True)
        self.component = component
        self.labels = dict(labels or {})
        self._clock = clock
        # Custom burn reader: the fleet's controller aggregates PER-REPLICA
        # burn gauges (its own label set has none); None = read this
        # controller's own labeled gauges.
        self._burn_fn = burn_fn
        # Burn-driven escalation is gated on recent INTERACTIVE presence
        # (note_interactive below): the burn signal exists to protect
        # latency-sensitive users, and in a single-tenant batch run — the
        # CPU-harness study sweep — a deep queue of the user's OWN batch
        # work legitimately burns the TTFT budget, where shedding/capping
        # batch would brown out the only tenant to protect nobody. The
        # depth signal guards the queue itself in both regimes.
        self._last_interactive: Optional[float] = None
        self.level = 0
        self._healthy_since: Optional[float] = None
        self._last_eval: Optional[float] = None
        # (t, depth) samples — a self-decaying high-water mark over
        # queue_window_s, fed by the scheduler loop. Unlike the
        # queue_depth_hwm gauge (which resets per drain), this window ages
        # out on its own, so de-escalation works mid-serve.
        self._depth: Deque[Tuple[float, float]] = deque()
        self._depth_capacity = 1.0
        # Gauge exists from construction: a healthy snapshot still shows
        # the controller was armed (level 0).
        self._gauge().set(0)

    # -- instruments --------------------------------------------------------

    def _gauge(self):
        return get_registry().gauge("overload_level",
                                    component=self.component, **self.labels)

    @property
    def rung(self) -> str:
        return SHED_LADDER[self.level]

    # -- gating -------------------------------------------------------------

    def admits(self, qos: str) -> bool:
        """Whether the current rung admits this class. Rungs 1-2 shed
        ``batch``; rung 3 admits only ``interactive``. Probes survive to
        rung 3 despite their bottom dequeue priority — blinding the canary
        while the stack is sick would cost more than a probe's decode."""
        if self.level <= 0:
            return True
        if self.level >= 3:
            return qos == "interactive"
        return qos != "batch"

    def batch_cap(self, cap: int, qos: str) -> int:
        """Rung >= 2: clamp a batch request's decode budget to
        ``batch_token_cap`` (brownout: shorter answers beat no answers).
        Interactive and probe budgets are never touched.

        With ``headroom_cap_frac`` opted in (> 0), the same clamp also
        engages BEFORE rung 2 whenever the memory ledger's measured HBM
        headroom falls below that fraction — every decode token is KV
        bytes, so shortening batch answers is the cheapest lever against
        an approaching memory wall (ISSUE 18)."""
        if qos != "batch":
            return cap
        if self.level >= 2:
            return max(1, min(cap, self.config.batch_token_cap))
        if self.config.headroom_cap_frac > 0:
            from fairness_llm_tpu.telemetry.memory import (  # lazy
                get_memory_ledger,
            )

            frac = get_memory_ledger().headroom_frac()
            if frac is not None and frac <= self.config.headroom_cap_frac:
                return max(1, min(cap, self.config.batch_token_cap))
        return cap

    def retry_after(self, est_ttft: Optional[float] = None) -> float:
        """The retry-after hint for a shed: the configured base, scaled by
        the current rung (a deeper brownout clears slower), or the
        feasibility estimate when that is what refused the request."""
        base = self.config.retry_after_s * max(1, self.level)
        if est_ttft is not None:
            base = max(base, est_ttft)
        return round(base, 3)

    # -- signals + evaluation -----------------------------------------------

    def observe_queue_depth(self, depth: int, capacity: int) -> None:
        """One depth sample from the serving loop (window-pruned here so
        the windowed max decays during quiet stretches)."""
        now = self._clock()
        self._depth.append((now, float(depth)))
        self._depth_capacity = float(max(capacity, 1))
        cutoff = now - self.config.queue_window_s
        while self._depth and self._depth[0][0] < cutoff:
            self._depth.popleft()

    def _depth_frac(self, now: float) -> float:
        cutoff = now - self.config.queue_window_s
        vals = [d for t, d in self._depth if t >= cutoff]
        return (max(vals) / self._depth_capacity) if vals else 0.0

    def _burn(self) -> float:
        """The hottest fast-window burn among the SLOs a brownout can
        relieve (error rate and TTFT — e2e recovers with them)."""
        if self._burn_fn is not None:
            return float(self._burn_fn())
        reg = get_registry()
        return max(
            reg.read_value("slo_burn_rate", default=0.0,
                           component=self.component, slo=slo, window="fast",
                           **self.labels)
            for slo in ("error_rate", "ttft_p95")
        )

    def note_interactive(self, now: Optional[float] = None) -> None:
        """One interactive-class submission seen at the front door — arms
        the burn signal for ``interactive_presence_s``."""
        self._last_interactive = self._clock() if now is None else now

    def interactive_present(self, now: float) -> bool:
        return self._last_interactive is not None and \
            now - self._last_interactive <= self.config.interactive_presence_s

    def overloaded(self, now: Optional[float] = None) -> Optional[str]:
        """The hot signal's name, or None when everything is healthy.
        Queue depth always counts; SLO burn counts only while interactive
        traffic is present (see __init__ on why)."""
        t = self._clock() if now is None else now
        frac = self._depth_frac(t)
        if frac >= self.config.queue_frac_threshold:
            return f"queue_depth {frac:.2f}x capacity"
        if self.interactive_present(t):
            burn = self._burn()
            if burn >= self.config.burn_threshold:
                return f"slo_burn {burn:.2f}"
        return None

    def maybe_evaluate(self) -> int:
        """Throttled ``evaluate`` for the serving loop (one controller step
        per ``eval_interval_s`` at most, so escalation takes at least
        3 x interval to reach the top rung — monotone, never a jump)."""
        now = self._clock()
        if self._last_eval is not None and \
                now - self._last_eval < self.config.eval_interval_s:
            return self.level
        return self.evaluate(now=now)

    def evaluate(self, now: Optional[float] = None) -> int:
        """One controller step: at most one rung up (a signal is hot) or
        one rung down (healthy for ``healthy_window_s``, hysteresis
        restarting per rung). Returns the level after the step."""
        t = self._clock() if now is None else now
        self._last_eval = t
        reason = self.overloaded(now=t)
        if reason is not None:
            self._healthy_since = None
            if self.level < len(SHED_LADDER) - 1:
                self._transition(self.level + 1, reason, t)
        else:
            if self._healthy_since is None:
                self._healthy_since = t
            elif self.level > 0 and \
                    t - self._healthy_since >= self.config.healthy_window_s:
                # Restart the healthy clock per rung: each step down needs
                # its own sustained-healthy window (the hysteresis that
                # stops a marginal signal from sawtoothing the ladder).
                self._healthy_since = t
                self._transition(self.level - 1, "sustained_healthy", t)
        self._gauge().set(self.level)
        return self.level

    def _transition(self, to: int, reason: str, now: float) -> None:
        frm, self.level = self.level, to
        escalating = to > frm
        self._gauge().set(to)
        # Decision audit trail (telemetry/incidents.py): the rung move with
        # the INPUT SIGNALS at decision time — the windowed queue fraction
        # and the burn the controller judged — plus a flight-recorder gauge
        # edge, so a postmortem shows why the ladder was where it was.
        scope = self.labels.get("replica") \
            or self.labels.get("fleet") or self.component
        record_decision(
            "overload", f"{frm}->{to}",
            signals={"rung": SHED_LADDER[to], "reason": reason,
                     "queue_frac": round(self._depth_frac(now), 3),
                     "burn": round(self._burn(), 3)},
            replica=self.labels.get("replica"),
        )
        get_flight_recorder().transition("overload_level", scope, to,
                                         reason=reason)
        get_registry().counter(
            "overload_transitions_total", component=self.component,
            to=str(to), **self.labels,
        ).inc()
        event = "overload_shed" if escalating else "overload_restore"
        emit_event(event, level=to, rung=SHED_LADDER[to], reason=reason,
                   component=self.component, **self.labels)
        get_timeline().record_instant(
            "shed" if escalating else "restore",
            self.labels.get("replica") or self.component,
            t=now, cat="overload", level=to, reason=reason,
        )
        log = logger.warning if escalating else logger.info
        log("overload level %d -> %d (%s): %s", frm, to, SHED_LADDER[to],
            reason)


__all__ = [
    "DeadlineEstimator",
    "QOS_CLASSES",
    "QOS_PRIORITY",
    "SHED_LADDER",
    "ShedController",
    "count_shed",
]
